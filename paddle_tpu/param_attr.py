"""ParamAttr — per-parameter configuration.

≙ reference python/paddle/fluid/param_attr.py (ParamAttr, WeightNormParamAttr).
"""

from __future__ import annotations

from typing import Optional


class ParamAttr:
    def __init__(self, name: Optional[str] = None, initializer=None,
                 learning_rate: float = 1.0, regularizer=None,
                 trainable: bool = True, gradient_clip=None,
                 sharding_spec=None):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip
        # PartitionSpec-style tuple of mesh axis names (or None) per dim —
        # consumed by ParallelExecutor to place this parameter sharded
        # (TP/EP; NEW capability, no reference analogue — SURVEY §2.3).
        self.sharding_spec = sharding_spec

    @staticmethod
    def _to_attr(arg) -> Optional["ParamAttr"]:
        """Normalize the many accepted spellings (None/False/str/Initializer/
        ParamAttr) like the reference's ParamAttr._to_attr."""
        if arg is None:
            return ParamAttr()
        if arg is False:
            return None
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        # assume initializer
        return ParamAttr(initializer=arg)
