"""Profiler: host-event timing + device trace capture.

Capability equivalent of the reference profiler stack (reference:
paddle/fluid/platform/profiler.h:73-121 RecordEvent/EnableProfiler,
platform/device_tracer.h:49 CUPTI tracer, tools/timeline.py Chrome-trace
export, python/paddle/fluid/profiler.py context managers).

TPU-first mapping: per-op host interpretation doesn't exist (whole programs
are XLA-compiled), so host events time the phases that exist here — trace,
compile, execute, feed/fetch — while *device*-side op-level detail comes from
jax.profiler's XPlane trace (viewable in TensorBoard / Perfetto), the XLA
analogue of the CUPTI device tracer. Host events still support user-scoped
`RecordEvent` annotation and export to Chrome trace format.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

from .core.enforce import InvalidArgumentError, enforce

_enabled = False
_events_lock = threading.Lock()
_completed: List["_Event"] = []
_trace_dir: Optional[str] = None


class _Event:
    __slots__ = ("name", "thread_id", "start", "end")

    def __init__(self, name, thread_id, start, end):
        self.name = name
        self.thread_id = thread_id
        self.start = start
        self.end = end

    @property
    def duration_ms(self):
        return (self.end - self.start) * 1e3


_device_tracing = False


class RecordEvent:
    """RAII scope annotation (≙ platform::RecordEvent, profiler.h:73).
    Nesting shows up in the Chrome trace via overlapping ts/dur spans.

    While a device (XPlane) trace is active, the same name is additionally
    entered as a jax.profiler.TraceAnnotation, so it appears ON the device
    timeline correlated with the XLA ops dispatched inside the scope — the
    RecordEvent→device correlation the reference gets from CUPTI
    correlation ids (device_tracer.h:49 + tools/timeline.py:45)."""

    def __init__(self, name: str):
        self.name = name
        self._start = None
        self._annotation = None

    def __enter__(self):
        if _enabled:
            self._start = time.perf_counter()
            if _device_tracing:
                import jax
                self._annotation = jax.profiler.TraceAnnotation(self.name)
                self._annotation.__enter__()
        return self

    def __exit__(self, *exc):
        if self._annotation is not None:
            self._annotation.__exit__(*exc)
            self._annotation = None
        if self._start is not None:
            ev = _Event(self.name, threading.get_ident(), self._start,
                        time.perf_counter())
            self._start = None
            with _events_lock:
                _completed.append(ev)
        return False


record_event = RecordEvent  # snake_case alias used by layers/executor


def reset_profiler():
    """≙ fluid.profiler.reset_profiler — drop all recorded events."""
    with _events_lock:
        _completed.clear()


def start_profiler(state: str = "All", tracer_option: Optional[str] = None):
    """Enable host-event recording; state 'All' additionally starts a
    jax.profiler device trace when a trace dir was configured via
    `profiler(..., output=dir)` or PTPU_TRACE_DIR env.

    ≙ EnableProfiler (reference profiler.h:116; states CPU/GPU/All map to
    host-only vs host+device here).
    """
    global _enabled, _trace_dir, _device_tracing
    enforce(state in ("CPU", "GPU", "All", "TPU"),
            f"invalid profiler state {state!r}", exc=InvalidArgumentError)
    _enabled = True
    if state in ("GPU", "All", "TPU"):
        trace_dir = _trace_dir or os.environ.get("PTPU_TRACE_DIR")
        if trace_dir:
            import jax
            try:
                jax.profiler.start_trace(trace_dir)
                _device_tracing = True
            except RuntimeError:
                pass  # already tracing


def stop_profiler(sorted_key: Optional[str] = None,
                  profile_path: Optional[str] = None):
    """Disable recording, print the per-event summary table, optionally dump
    a Chrome trace JSON to profile_path (≙ DisableProfiler profiler.h:119 +
    tools/timeline.py)."""
    global _enabled, _device_tracing
    if not _enabled:
        return
    _enabled = False
    was_device = _device_tracing
    _device_tracing = False
    import jax
    try:
        jax.profiler.stop_trace()
    except RuntimeError:
        pass
    if profile_path:
        export_chrome_tracing(
            profile_path,
            device_trace_dir=(_trace_dir or os.environ.get("PTPU_TRACE_DIR"))
            if was_device else None)
    print_profiler_summary(sorted_key or "default")


def print_profiler_summary(sorted_key: str = "default"):
    """Aggregate events by name: calls, total/min/max/avg ms (≙ the
    reference's sorted profiling report, profiler.cc PrintProfiler)."""
    enforce(sorted_key in ("default", "calls", "total", "max", "min", "ave"),
            f"invalid sorted_key {sorted_key!r}", exc=InvalidArgumentError)
    with _events_lock:
        events = list(_completed)
    if not events:
        print("[profiler] no events recorded")
        return
    agg: Dict[str, List[float]] = {}
    for ev in events:
        agg.setdefault(ev.name, []).append(ev.duration_ms)
    rows = []
    for name, durs in agg.items():
        rows.append((name, len(durs), sum(durs), max(durs), min(durs),
                     sum(durs) / len(durs)))
    key_idx = {"default": 2, "calls": 1, "total": 2, "max": 3, "min": 4,
               "ave": 5}[sorted_key]
    rows.sort(key=lambda r: -r[key_idx])
    hdr = f"{'Event':<44} {'Calls':>7} {'Total(ms)':>11} {'Max':>9} " \
          f"{'Min':>9} {'Ave':>9}"
    print("-" * len(hdr))
    print(hdr)
    print("-" * len(hdr))
    for name, calls, tot, mx, mn, ave in rows:
        print(f"{name[:44]:<44} {calls:>7} {tot:>11.3f} {mx:>9.3f} "
              f"{mn:>9.3f} {ave:>9.3f}")
    print("-" * len(hdr))


def _collect_device_trace_events(trace_dir: str):
    """Pull the device timeline out of a jax.profiler dump: the profiler
    writes a Chrome-format *.trace.json.gz under
    <dir>/plugins/profile/<run>/ — merge its events (annotated with the
    RecordEvent names via TraceAnnotation) rather than asking users to
    open TensorBoard separately. ≙ tools/timeline.py merging the CUPTI
    device records into one timeline."""
    import glob
    import gzip
    pats = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not pats:
        return []
    with gzip.open(pats[-1], "rt") as f:
        data = json.load(f)
    out = []
    for ev in data.get("traceEvents", []):
        if not isinstance(ev, dict):
            continue
        # keep metadata ('M': process/thread names) AND timed events; shift
        # every device pid up by 1 so lanes never collide with the host
        # (pid 0) while distinct planes stay distinct
        if "ts" not in ev and ev.get("ph") != "M":
            continue
        ev = dict(ev)
        ev["cat"] = ev.get("cat", "device")
        ev["pid"] = int(ev.get("pid", 0)) + 1
        out.append(ev)
    return out


def export_chrome_tracing(path: str, device_trace_dir: Optional[str] = None):
    """Write recorded host events — and, when a device trace dir is given,
    the jax.profiler device timeline — as ONE Chrome trace (catapult) JSON
    (≙ tools/timeline.py, which merges host + CUPTI device records)."""
    with _events_lock:
        events = list(_completed)
    trace = {"traceEvents": [], "displayTimeUnit": "ms"}
    for ev in events:
        trace["traceEvents"].append({
            "name": ev.name, "cat": "host", "ph": "X",
            "ts": ev.start * 1e6, "dur": (ev.end - ev.start) * 1e6,
            "pid": 0, "tid": ev.thread_id,
        })
    if device_trace_dir:
        trace["traceEvents"].extend(
            _collect_device_trace_events(device_trace_dir))
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


def merge_process_traces(trace_paths, path: str, labels=None):
    """Merge per-process Chrome traces — each produced by
    `export_chrome_tracing` inside one trainer process — into ONE timeline
    with per-process lanes (≙ the reference's tools/timeline.py:24-33,
    whose --profile_path takes a list of per-trainer profile files and
    emits a single catapult view).

    Each input trace's pids are shifted into a disjoint range and labeled
    `rank{r}/host` / `rank{r}/device{k}`, so an N-process world reads as N
    stacked lanes in chrome://tracing / Perfetto."""
    traces = []
    for p in trace_paths:
        with open(p) as f:
            traces.append(json.load(f))
    # pid stride: one disjoint block per rank, wide enough for the
    # largest pid any input trace carries (device-trace planes can be
    # numerous)
    max_pid = 0
    for t in traces:
        for ev in t.get("traceEvents", []):
            if isinstance(ev, dict):
                max_pid = max(max_pid, int(ev.get("pid", 0)))
    stride = max(100, max_pid + 1)
    merged = {"traceEvents": [], "displayTimeUnit": "ms"}
    for r, t in enumerate(traces):
        label = (labels[r] if labels and r < len(labels) else f"rank{r}")
        base = r * stride
        seen = set()
        for ev in t.get("traceEvents", []):
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            pid = int(ev.get("pid", 0))
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                # rewritten below with the rank prefix
                continue
            ev["pid"] = base + pid
            seen.add(pid)
            merged["traceEvents"].append(ev)
        for pid in sorted(seen):
            merged["traceEvents"].append({
                "ph": "M", "name": "process_name", "pid": base + pid,
                "args": {"name": label + ("/host" if pid == 0
                                          else f"/device{pid - 1}")}})
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(merged, f)
    return path


@contextmanager
def profiler(state: str = "All", sorted_key: str = "default",
             profile_path: Optional[str] = None,
             trace_dir: Optional[str] = None):
    """Context manager (≙ fluid.profiler.profiler, profiler.py:221):

        with profiler('All', sorted_key='total', profile_path='/tmp/t.json'):
            for batch in data:
                exe.run(...)
    """
    global _trace_dir
    _trace_dir = trace_dir
    reset_profiler()
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key=sorted_key, profile_path=profile_path)
        _trace_dir = None


@contextmanager
def device_tracer(log_dir: str):
    """Capture a device (XPlane) trace to log_dir for TensorBoard — the
    TPU analogue of the CUPTI DeviceTracer (device_tracer.h:49)."""
    global _device_tracing
    import jax
    jax.profiler.start_trace(log_dir)
    _device_tracing = True
    try:
        yield
    finally:
        _device_tracing = False
        jax.profiler.stop_trace()


def profiler_enabled() -> bool:
    return _enabled
