"""Profiler: fluid-compatible surface over the observability tracer.

Capability equivalent of the reference profiler stack (reference:
paddle/fluid/platform/profiler.h:73-121 RecordEvent/EnableProfiler,
platform/device_tracer.h:49 CUPTI tracer, tools/timeline.py Chrome-trace
export, python/paddle/fluid/profiler.py context managers).

Since r12 the actual recorder is `paddle_tpu.observability.tracing`: one
ring buffer of typed nested spans shared by the executors, the rewrite
passes, and the serving engine. This module keeps the fluid-shaped API
as a thin WINDOW over that ring — `start_profiler` marks a position,
`stop_profiler` aggregates/export everything recorded since — so the
pre-r12 contract (RecordEvent records while a profiler context is open,
even with PTPU_TRACE=0) still holds, and the global-state leakage the
old module suffered (events and the enabled bit bleeding across test
suites) is gone: `reset()` restores every module global, and the test
conftest calls it around each test.

Device-side (XPlane) tracing is unchanged: state 'All' starts a
jax.profiler trace when a trace dir is configured, RecordEvent names
ride onto the device timeline as TraceAnnotations, and export merges
host + device events into one Chrome trace.
"""

from __future__ import annotations

import json
import os
from contextlib import contextmanager
from typing import Optional

from .core import flags
from .core.enforce import InvalidArgumentError, enforce
from .observability import tracing as _tracing

_enabled = False
_forced = False           # we hold one tracing.force_enable(True) ref
_trace_dir: Optional[str] = None
_device_tracing = False
_window_mark = 0          # ring position where the current window began


class RecordEvent(_tracing.span):
    """RAII scope annotation (≙ platform::RecordEvent, profiler.h:73) —
    a thin alias over the observability span API (kind 'user'). Nesting
    shows up in the Chrome trace via overlapping ts/dur spans and in the
    span's parent/depth attribution.

    While a device (XPlane) trace is active, the same name is additionally
    entered as a jax.profiler.TraceAnnotation, so it appears ON the device
    timeline correlated with the XLA ops dispatched inside the scope — the
    RecordEvent→device correlation the reference gets from CUPTI
    correlation ids (device_tracer.h:49 + tools/timeline.py:45)."""

    def __init__(self, name: str):
        super().__init__("user", name)


record_event = RecordEvent  # snake_case alias used by layers/executor


def reset_profiler():
    """≙ fluid.profiler.reset_profiler — drop all recorded events (the
    summary/export window restarts here; the tracer ring itself keeps
    spans for observability consumers)."""
    global _window_mark
    _window_mark = _tracing.mark()


def reset():
    """Full state reset for test isolation: disable recording, release
    the force-enable ref, detach the device-annotation factory, and
    restart the window. Safe to call at any point, any number of times
    (tests/conftest.py runs it around every test so neither recorded
    events nor the enabled bit bleed between suites)."""
    global _enabled, _forced, _device_tracing, _trace_dir
    if _forced:
        _tracing.force_enable(False)
        _forced = False
    _enabled = False
    _device_tracing = False
    _trace_dir = None
    _tracing.annotation_factory = None
    reset_profiler()


def start_profiler(state: str = "All", tracer_option: Optional[str] = None):
    """Enable host-event recording; state 'All' additionally starts a
    jax.profiler device trace when a trace dir was configured via
    `profiler(..., output=dir)` or PTPU_TRACE_DIR env.

    ≙ EnableProfiler (reference profiler.h:116; states CPU/GPU/All map to
    host-only vs host+device here).
    """
    global _enabled, _forced, _trace_dir, _device_tracing, _window_mark
    enforce(state in ("CPU", "GPU", "All", "TPU"),
            f"invalid profiler state {state!r}", exc=InvalidArgumentError)
    if not _enabled:
        _window_mark = _tracing.mark()
    _enabled = True
    if not _forced:
        _tracing.force_enable(True)
        _forced = True
    if state in ("GPU", "All", "TPU"):
        trace_dir = _trace_dir or os.environ.get("PTPU_TRACE_DIR")
        if trace_dir:
            import jax
            try:
                jax.profiler.start_trace(trace_dir)
                _device_tracing = True
                _tracing.annotation_factory = jax.profiler.TraceAnnotation
            except RuntimeError:
                pass  # already tracing


def stop_profiler(sorted_key: Optional[str] = None,
                  profile_path: Optional[str] = None):
    """Disable recording, print the per-event summary table, optionally
    dump a Chrome trace JSON to profile_path (≙ DisableProfiler
    profiler.h:119 + tools/timeline.py)."""
    global _enabled, _forced, _device_tracing
    if not _enabled:
        return
    _enabled = False
    if _forced:
        _tracing.force_enable(False)
        _forced = False
    was_device = _device_tracing
    _device_tracing = False
    _tracing.annotation_factory = None
    import jax
    try:
        jax.profiler.stop_trace()
    except RuntimeError:
        pass
    if profile_path:
        export_chrome_tracing(
            profile_path,
            device_trace_dir=(_trace_dir or os.environ.get("PTPU_TRACE_DIR"))
            if was_device else None)
    print_profiler_summary(sorted_key or "default")


def _window_spans():
    spans = _tracing.spans_since(_window_mark)
    # the recorder is a bounded ring (PTPU_TRACE_RING, default 65536);
    # a window longer than that has lost its oldest events — say so
    # instead of printing a silently-truncated report (the pre-r12
    # profiler kept an unbounded list)
    if len(spans) >= int(flags.get_flag("trace_ring")):
        print("[profiler] span ring capacity reached: oldest events in "
              "this window were dropped — raise PTPU_TRACE_RING to keep "
              "longer windows")
    return spans


def print_profiler_summary(sorted_key: str = "default"):
    """Aggregate the window's spans by name: calls, total/min/max/avg ms
    (≙ the reference's sorted profiling report, profiler.cc
    PrintProfiler)."""
    enforce(sorted_key in ("default", "calls", "total", "max", "min", "ave"),
            f"invalid sorted_key {sorted_key!r}", exc=InvalidArgumentError)
    agg = _tracing.aggregate(_window_spans())
    if not agg:
        print("[profiler] no events recorded")
        return
    key = {"default": "total_ms", "calls": "calls", "total": "total_ms",
           "max": "max_ms", "min": "min_ms", "ave": "avg_ms"}[sorted_key]
    rows = sorted(agg.items(), key=lambda kv: -kv[1][key])
    hdr = f"{'Event':<44} {'Calls':>7} {'Total(ms)':>11} {'Max':>9} " \
          f"{'Min':>9} {'Ave':>9}"
    print("-" * len(hdr))
    print(hdr)
    print("-" * len(hdr))
    for name, r in rows:
        print(f"{name[:44]:<44} {r['calls']:>7} {r['total_ms']:>11.3f} "
              f"{r['max_ms']:>9.3f} {r['min_ms']:>9.3f} {r['avg_ms']:>9.3f}")
    print("-" * len(hdr))


def _collect_device_trace_events(trace_dir: str):
    """Pull the device timeline out of a jax.profiler dump: the profiler
    writes a Chrome-format *.trace.json.gz under
    <dir>/plugins/profile/<run>/ — merge its events (annotated with the
    RecordEvent names via TraceAnnotation) rather than asking users to
    open TensorBoard separately. ≙ tools/timeline.py merging the CUPTI
    device records into one timeline."""
    import glob
    import gzip
    pats = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not pats:
        return []
    with gzip.open(pats[-1], "rt") as f:
        data = json.load(f)
    out = []
    for ev in data.get("traceEvents", []):
        if not isinstance(ev, dict):
            continue
        # keep metadata ('M': process/thread names) AND timed events; shift
        # every device pid up by 1 so lanes never collide with the host
        # (pid 0) while distinct planes stay distinct
        if "ts" not in ev and ev.get("ph") != "M":
            continue
        ev = dict(ev)
        ev["cat"] = ev.get("cat", "device")
        ev["pid"] = int(ev.get("pid", 0)) + 1
        out.append(ev)
    return out


def export_chrome_tracing(path: str, device_trace_dir: Optional[str] = None):
    """Write the window's host spans — and, when a device trace dir is
    given, the jax.profiler device timeline — as ONE Chrome trace
    (catapult) JSON (≙ tools/timeline.py, which merges host + CUPTI
    device records)."""
    trace = {"traceEvents": _tracing.chrome_trace_events(_window_spans(),
                                                         pid=0),
             "displayTimeUnit": "ms"}
    if device_trace_dir:
        trace["traceEvents"].extend(
            _collect_device_trace_events(device_trace_dir))
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


def merge_process_traces(trace_paths, path: str, labels=None):
    """Merge per-process Chrome traces — each produced by
    `export_chrome_tracing` inside one trainer process — into ONE timeline
    with per-process lanes (≙ the reference's tools/timeline.py:24-33,
    whose --profile_path takes a list of per-trainer profile files and
    emits a single catapult view).

    Each input trace's pids are shifted into a disjoint range and labeled
    `rank{r}/host` / `rank{r}/device{k}`, so an N-process world reads as N
    stacked lanes in chrome://tracing / Perfetto."""
    traces = []
    for p in trace_paths:
        with open(p) as f:
            traces.append(json.load(f))
    # pid stride: one disjoint block per rank, wide enough for the
    # largest pid any input trace carries (device-trace planes can be
    # numerous)
    max_pid = 0
    for t in traces:
        for ev in t.get("traceEvents", []):
            if isinstance(ev, dict):
                max_pid = max(max_pid, int(ev.get("pid", 0)))
    stride = max(100, max_pid + 1)
    merged = {"traceEvents": [], "displayTimeUnit": "ms"}
    for r, t in enumerate(traces):
        label = (labels[r] if labels and r < len(labels) else f"rank{r}")
        base = r * stride
        seen = set()
        for ev in t.get("traceEvents", []):
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            pid = int(ev.get("pid", 0))
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                # rewritten below with the rank prefix
                continue
            ev["pid"] = base + pid
            seen.add(pid)
            merged["traceEvents"].append(ev)
        for pid in sorted(seen):
            merged["traceEvents"].append({
                "ph": "M", "name": "process_name", "pid": base + pid,
                "args": {"name": label + ("/host" if pid == 0
                                          else f"/device{pid - 1}")}})
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(merged, f)
    return path


@contextmanager
def profiler(state: str = "All", sorted_key: str = "default",
             profile_path: Optional[str] = None,
             trace_dir: Optional[str] = None):
    """Context manager (≙ fluid.profiler.profiler, profiler.py:221):

        with profiler('All', sorted_key='total', profile_path='/tmp/t.json'):
            for batch in data:
                exe.run(...)
    """
    global _trace_dir
    _trace_dir = trace_dir
    reset_profiler()
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key=sorted_key, profile_path=profile_path)
        _trace_dir = None


@contextmanager
def device_tracer(log_dir: str):
    """Capture a device (XPlane) trace to log_dir for TensorBoard — the
    TPU analogue of the CUPTI DeviceTracer (device_tracer.h:49)."""
    global _device_tracing
    import jax
    jax.profiler.start_trace(log_dir)
    _device_tracing = True
    _tracing.annotation_factory = jax.profiler.TraceAnnotation
    try:
        yield
    finally:
        _device_tracing = False
        _tracing.annotation_factory = None
        jax.profiler.stop_trace()


def profiler_enabled() -> bool:
    return _enabled
