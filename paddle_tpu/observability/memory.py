"""Memory + utilization observability: census, watermarks, MFU.

The r12 ledger closed the loop on WIRE bytes (predicted == census
exactly) and r09 on bubbles (2% band); the `memory` section of
`costs.predict` stayed a pure static estimate with no measured side.
This module is the measured side — the sensor layer ROADMAP items 1
(auto-parallel planner) and 2 (memory planner) both stand on:

- **executable census** (`executable_memory`): per-device
  argument/output/temp/alias bytes from the XLA executable's buffer
  assignment (`compiled.memory_analysis()` — per-DEVICE on sharded
  compiles, verified on the virtual mesh). Where the backend reports
  `temp_size_in_bytes == 0` (this container's jaxlib-0.4.x CPU backend
  does for some programs), the documented fallback is a liveness walk
  over the scheduled HLO text (`costs.hlo_liveness_temp_bytes`), tagged
  `temp_source: "hlo_liveness_walk"` so an artifact never passes off an
  estimate as a backend report.
- **live-state census** (`state_census` / `device_memory_census`): the
  executor's state walked from the scope — params, ZeRO accumulators,
  error-feedback residuals, KV-cache slots, everything else — measured
  from the ACTUAL device arrays (committed bytes over the arrays' own
  shard counts = per-device bytes), plus a `jax.live_arrays()` sweep
  that counts device bytes the scope does not track (the host-side
  truth a dossier wants after an OOM-shaped death).
- **watermarks** (`update_watermark`): live per-channel high-water
  marks — device state, executor temp, KV cache, checkpoint host
  staging — each update records a `memory`-channel counter sample
  (Chrome counter track via `tracing.record_counter`) and backs the
  `ptpu_memory_*` gauges in `metrics.default_registry()`, so one
  /metrics scrape and a flight-recorder dossier both carry the memory
  board.
- **MFU** (`note_mfu`): `costs.predict` flops over measured step time
  as the `ptpu_mfu` gauge — the utilization signal the planner search
  trusts its cost model against (TVM-style measured feedback,
  PAPERS.md).

The ledger's accounting identity over all of this lives in
`observability/ledger.py` (`check_memory_identity`); the committed
artifact is `BENCH_MEM_r17.json` (tools/bench_mem.py).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Sequence

import numpy as np

from ..core.enforce import InvalidArgumentError, enforce

#: the watermark channels (fixed set: a typo'd channel raises instead of
#: minting a gauge no scrape ever finds)
CHANNELS = ("device_state_bytes", "executor_temp_bytes",
            "kv_cache_bytes", "kv_cache_used_bytes",
            "host_staging_bytes", "host_kv_bytes",
            "host_optimizer_bytes")

_lock = threading.Lock()
_marks: Dict[str, Dict[str, float]] = {
    c: {"current": 0.0, "peak": 0.0} for c in CHANNELS}
_mfu = {"value": 0.0, "flops": 0.0, "step_s": 0.0}
_metrics = None


def memory_metrics():
    """The memory/utilization series, registered (idempotently) into
    `metrics.default_registry()` — `ptpu_memory_<channel>` (current
    level), `ptpu_memory_watermark_bytes{channel=...}` (high-water), and
    `ptpu_mfu`. One /metrics scrape sees them next to `ptpu_ckpt_*` and
    `ptpu_train_*` (the r16 unified-registry discipline)."""
    global _metrics
    if _metrics is None:
        from . import metrics as m
        r = m.default_registry()
        out: Dict[str, Any] = {}
        for c in CHANNELS:
            out[c] = m.get_or_create(
                r, "gauge", f"ptpu_memory_{c}",
                f"Current {c.replace('_', ' ')} (memory census channel).",
                fn=(lambda c=c: _marks[c]["current"]))
            out[f"{c}_peak"] = m.get_or_create(
                r, "gauge", "ptpu_memory_watermark_bytes",
                "Per-channel high-water mark of the memory census.",
                labels={"channel": c},
                fn=(lambda c=c: _marks[c]["peak"]))
        out["mfu"] = m.get_or_create(
            r, "gauge", "ptpu_mfu",
            "Model-flops utilization: predicted step flops over measured "
            "step time, fraction of the hardware peak.",
            fn=(lambda: _mfu["value"]))
        _metrics = out
    return _metrics


def update_watermark(channel: str, value: float):
    """Set a channel's current level; the high-water mark ratchets.
    When tracing is enabled the sample also lands on the ring as a
    `memory/<channel>` counter event (Chrome counter track,
    tools/trace_merge.py gives it a per-rank lane). This is the
    executor's per-step hot path — no eager f-strings, one dict probe
    for the channel check."""
    m = _marks.get(channel)
    if m is None:
        raise InvalidArgumentError(
            f"unknown memory channel {channel!r}; known: "
            f"{list(CHANNELS)}")
    if _metrics is None:
        memory_metrics()
    v = float(value)
    with _lock:
        m["current"] = v
        if v > m["peak"]:
            m["peak"] = v
    from . import tracing as _tracing
    if _tracing.enabled():
        _tracing.record_counter("memory/" + channel, v)


def note_mfu(flops: float, step_s: float):
    """One measured step: predicted flops over wall seconds -> the
    `ptpu_mfu` gauge (+ a `memory/mfu` counter sample when tracing).
    Callers measure step_s across a dispatch window; under donated-state
    backpressure successive dispatches track true step time."""
    from ..framework import costs as _costs
    memory_metrics()
    with _lock:
        _mfu["flops"] = float(flops)
        _mfu["step_s"] = float(step_s)
        _mfu["value"] = _costs.mfu(flops, step_s)
    from . import tracing as _tracing
    _tracing.record_counter("memory/mfu", _mfu["value"])


def watermark_board() -> Dict[str, Dict[str, float]]:
    """{channel: {current, peak}} + the last MFU reading — what
    /healthz and the flight-recorder dossier embed as the memory
    board."""
    with _lock:
        out: Dict[str, Any] = {c: dict(v) for c, v in _marks.items()}
        out["mfu"] = dict(_mfu)
    return out


def reset_watermarks():
    """Test isolation: zero every channel and the MFU reading."""
    with _lock:
        for v in _marks.values():
            v["current"] = v["peak"] = 0.0
        _mfu.update(value=0.0, flops=0.0, step_s=0.0)


# ---------------------------------------------------------------------------
# measured census
# ---------------------------------------------------------------------------


def per_device_bytes(val) -> float:
    """Per-device bytes of one array: committed bytes over the array's
    own shard count (replicated on N devices: N copies / N = one; dim-0
    sharded: total / N). Host/numpy values count their nbytes whole —
    they live on the one local device once placed."""
    shards = getattr(val, "addressable_shards", None)
    if shards:
        return sum(s.data.nbytes for s in shards) / len(shards)
    return float(getattr(val, "nbytes", 0) or 0)


def _var_category(v, name: str, kv_names) -> str:
    # kv_cache is a census-side refinement of other_state (the static
    # walk cannot know which persistables are slot caches); everything
    # else goes through the ONE classifier shared with the predicted
    # walk (costs.state_category), so the ledger's exact per-category
    # checks cannot fail from classifier drift
    if name in kv_names:
        return "kv_cache"
    from ..framework.costs import state_category
    return state_category(v, name)


def state_census(scope, program, names: Sequence[str],
                 kv_names: Sequence[str] = ()) -> Dict:
    """Measured per-device state bytes by category for the named scope
    vars (a compiled step's ro + rw lists): params / params_quantized /
    optimizer_state / ef_residual / kv_cache / other_state, each from the
    ACTUAL device arrays via `per_device_bytes`. `kv_names` marks the
    serving engine's slot-cache vars (they are plain persistables to the
    program)."""
    kv = set(kv_names)
    cats: Dict[str, float] = {"params": 0.0, "params_quantized": 0.0,
                              "params_draft": 0.0,
                              "optimizer_state": 0.0, "ef_residual": 0.0,
                              "kv_cache": 0.0, "other_state": 0.0}
    per_var: Dict[str, Dict] = {}
    for name in names:
        if not scope.has_var(name):
            continue
        val = scope.get(name)
        nb = per_device_bytes(val)
        v = None
        for b in program.blocks:
            if b.has_var(name):
                v = b.var(name)
                break
        cat = _var_category(v, name, kv) if v is not None else "other_state"
        cats[cat] += nb
        per_var[name] = {"category": cat, "per_device_bytes": nb}
    cats["state_total"] = sum(cats[c] for c in
                              ("params", "params_quantized",
                               "params_draft", "optimizer_state",
                               "ef_residual", "kv_cache", "other_state"))
    return {"categories": cats, "per_var": per_var}


def live_array_census(scope=None, tracked_names: Sequence[str] = ()) -> Dict:
    """The host-side truth: every live jax array in the process
    (`jax.live_arrays()`), split into scope-tracked vs untracked bytes.
    Untracked bytes are real device residency the program's state walk
    cannot see (donation ghosts, caller-held fetches, prefetch staging) —
    exactly what an OOM post-mortem needs named."""
    import jax
    tracked_ids = set()
    if scope is not None:
        for name in (tracked_names or scope.local_var_names()):
            if scope.has_var(name):
                tracked_ids.add(id(scope.get(name)))
    total = tracked = 0.0
    n = 0
    for a in jax.live_arrays():
        try:
            nb = sum(s.data.nbytes for s in a.addressable_shards)
        except Exception:
            nb = getattr(a, "nbytes", 0) or 0
        total += nb
        n += 1
        if id(a) in tracked_ids:
            tracked += nb
    return {"live_arrays": n, "committed_bytes": total,
            "tracked_bytes": tracked,
            "untracked_bytes": total - tracked}


def executable_memory(aot) -> Dict:
    """Per-device memory of one AOT-compiled executable from XLA's
    buffer assignment (`memory_analysis()`): argument / output / temp /
    alias / generated-code bytes. Falls back to the documented HLO
    liveness walk for the temp figure when the backend reports 0 on a
    program with intermediate values (`temp_source` names which side
    produced the number)."""
    from ..framework import costs as _costs
    ma = aot.memory_analysis()
    ma = ma[0] if isinstance(ma, (list, tuple)) else ma
    out = {
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
        "temp_source": "xla",
    }
    if out["temp_bytes"] == 0:
        walked = int(_costs.hlo_liveness_temp_bytes(aot.as_text()))
        if walked:
            out["temp_bytes"] = walked
            out["temp_source"] = "hlo_liveness_walk"
    return out


def device_memory_census(executor, feed: Dict[str, Any], scope, *,
                         program=None, compiled=None, dp: int = 1,
                         kv_names: Sequence[str] = ()) -> Dict:
    """The full measured memory census for one compiled step (the
    ledger's measured side; run the step once first so the compile
    cache is warm):

      state     per-device category bytes of the step's ro+rw scope vars
                (`state_census`, actual arrays)
      feeds     per-device bytes of the actual feed arrays — batch-led
                feeds split rows over dp, fixed-shape aux feeds
                replicated (the manual-mode placement rule)
      seed      the uint32 step seed (4)
      xla       `executable_memory` of the SAME executable (argument /
                output / temp / alias; `args balance` in the ledger
                cross-checks state+feeds+seed against argument_bytes)
      live      `live_array_census` process-wide sweep
      peak_bytes   argument + temp + non-aliased output bytes — the
                per-device live-step footprint the residual bound is
                measured against

    Updates the `device_state_bytes` and `executor_temp_bytes`
    watermarks with what it measured."""
    program = program or getattr(executor, "main_program", None)
    if program is None:
        from ..framework.program import default_main_program
        program = default_main_program()
    rewritten = executor._prepare_program(program, scope)
    if compiled is None:
        enforce(len(executor._cache) > 0,
                "device_memory_census: the executor has no compiled step "
                "yet — run the step once first (the census measures the "
                "executable the runs actually use)",
                exc=InvalidArgumentError)
        compiled = list(executor._cache.values())[-1]
    st = state_census(scope, rewritten,
                      sorted(set(compiled.ro_names)
                             | set(compiled.rw_names)),
                      kv_names=kv_names)
    import jax
    feed_bytes = 0.0
    per_feed = {}
    for name in compiled.feed_names:
        if name not in feed:
            # the bench convention Executor._aot_compiled supports:
            # feed names absent from the dict resolve to scope values —
            # real XLA arguments that memory_args_balance must see, so
            # count the placed array itself
            if scope is not None and scope.has_var(name):
                nb = per_device_bytes(scope.get(name))
                per_feed[name] = {"per_device_bytes": nb,
                                  "batch_led": False,
                                  "from_scope": True}
                feed_bytes += nb
            continue
        val = np.asarray(feed[name])
        # count CANONICAL dtypes: the device buffer is what jnp.asarray
        # makes of the host value (int64 -> int32 with x64 disabled), so
        # host nbytes would overcount exactly the narrowed feeds
        itemsize = np.dtype(
            jax.dtypes.canonicalize_dtype(val.dtype)).itemsize
        nb = float(val.size * itemsize)
        shape = None
        for b in rewritten.blocks:
            if b.has_var(name):
                shape = getattr(b.var(name), "shape", None)
                break
        batch_led = shape is None or (bool(shape) and shape[0] == -1)
        if batch_led and dp > 1:
            nb /= dp
        per_feed[name] = {"per_device_bytes": nb, "batch_led": batch_led}
        feed_bytes += nb
    aot = executor._aot_compiled(compiled, feed, scope)
    xla = executable_memory(aot)
    peak = (xla["argument_bytes"] + xla["temp_bytes"]
            + max(0, xla["output_bytes"] - xla["alias_bytes"]))
    update_watermark("device_state_bytes", st["categories"]["state_total"])
    update_watermark("executor_temp_bytes", xla["temp_bytes"])
    from ..framework import offload as _offload
    return {
        "state": st,
        "feeds": {"per_device_bytes": feed_bytes, "per_feed": per_feed,
                  "dp": dp},
        "seed_bytes": 4,
        "xla": xla,
        "live": live_array_census(scope),
        "peak_bytes": peak,
        # the second tier, from the ONE host-byte ledger (r23): the same
        # rows the host_*_bytes watermark channels publish, so a dossier
        # and /healthz cannot disagree about host residency
        "host_tier": _offload.shared_host_pool().rows(),
    }
