"""Distributed flight recorder: beacons, crash dossiers, post-mortems.

The r12 ring answers "what happened on THIS thread while the process was
alive". It cannot answer the questions a dying distributed run poses:
which rank died, in which barrier phase, who was still waiting on whom —
a SIGKILL leaves no chance to serialize anything at death. This module
closes that gap with three artifacts, all plain JSON under one
`dossier_dir` (configured explicitly or via `PTPU_DOSSIER_DIR`, so
supervised child processes inherit it through the environment):

- **beacons** (`flight-<pid>-rank<r>.jsonl`): an append-only
  write-ahead log of protocol phase transitions. `note_phase` is called
  at every barrier phase boundary (process_world.fault /
  parallel/elastic.py) BEFORE the phase's work — and, when a fault
  directive is about to fire, with `crashing`/`dropped` markers before
  the SIGKILL/RankDead. The OS page cache survives process death, so
  after a kill -9 the beacon's last line names the dead rank and the
  exact phase it reached. Timestamps per line give the straggler
  timeline.
- **dossiers** (`dossier-<ts>-pid<pid>[-rank<r>].json`): a full dump —
  last-N spans from the trace ring, a metrics snapshot, the live state
  board, the environment's world identity — written on the deaths the
  process CAN see coming: an enforce error escaping to the top
  (`install()` wires sys.excepthook), SIGTERM (preemption notice), and
  simulated rank death (RankDead in process_world.run).
- **post-mortems** (`post_mortem-<k>.json`): the Supervisor's synthesis
  after a gang incarnation dies — beacons + dossiers folded into
  {dead_rank, phase, serial, per-rank timeline} so the operator reads
  one file, not N logs. tests/test_process_world.py asserts the
  crash-anywhere SIGKILL sweep produces a correct one for every fault
  in the matrix.

Everything here is OFF until configured: `note_phase` with no dossier
dir updates the in-memory state board only (a dict merge — nanoseconds),
so the tracing overhead budget is unaffected.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from ..core import flags
from ..core.enforce import InvalidArgumentError, enforce

#: spans included in a dossier (newest last)
DOSSIER_SPANS = 256
BEACON_PREFIX = "flight-"
DOSSIER_PREFIX = "dossier-"
POST_MORTEM_PREFIX = "post_mortem-"

_lock = threading.Lock()
_dossier_dir: Optional[str] = None
#: True once configure() ran — even with None. Distinguishes
#: "explicitly disabled" (no PTPU_DOSSIER_DIR fallback) from
#: "never configured" (a fresh process inherits the env var).
_configured = False
_world_id: Optional[str] = None
#: component -> {field: value} — the live "what is in flight" board a
#: dossier snapshots (barrier serial/phase, engine tick state, ...)
_state_board: Dict[str, Dict[str, Any]] = {}
_beacon_files: Dict[int, Any] = {}          # rank -> open file handle
_extra_registries: List[Any] = []
_prev_excepthook = None
_prev_sigterm = None
_sigterm_installed = False
_dossier_seq = 0


def configure(dossier_dir: Optional[str], world_id: Optional[str] = None):
    """Point the recorder at a dossier directory. None DISABLES it —
    explicitly, i.e. a later call will NOT fall back to
    PTPU_DOSSIER_DIR; only a process that never configured inherits the
    env var (how supervised children pick up the Supervisor's dir)."""
    global _dossier_dir, _world_id, _configured
    with _lock:
        for f in _beacon_files.values():
            try:
                f.close()
            except OSError:
                pass
        _beacon_files.clear()
        _dossier_dir = dossier_dir
        _world_id = world_id
        _configured = True
        if dossier_dir:
            os.makedirs(dossier_dir, exist_ok=True)


def dossier_dir() -> Optional[str]:
    if _configured or _dossier_dir is not None:
        return _dossier_dir
    env = os.environ.get("PTPU_DOSSIER_DIR")
    if env:
        configure(env)
        return _dossier_dir
    return None


def enabled() -> bool:
    return dossier_dir() is not None


def set_state(component: str, **fields):
    """Merge fields into the component's state-board entry (the live
    snapshot a dossier captures: active barrier serial, engine draining
    flag, supervisor restart count...). None values delete keys."""
    with _lock:
        entry = _state_board.setdefault(component, {})
        for k, v in fields.items():
            if v is None:
                entry.pop(k, None)
            else:
                entry[k] = v


def clear_state(component: str):
    with _lock:
        _state_board.pop(component, None)


def state_board() -> Dict[str, Dict[str, Any]]:
    with _lock:
        return {k: dict(v) for k, v in _state_board.items()}


def register_metrics(registry):
    """Add a registry whose snapshot rides every dossier (the engine's
    per-instance registry; the default registry is always included)."""
    with _lock:
        if registry not in _extra_registries:
            _extra_registries.append(registry)


def _beacon_file(rank: int):
    d = dossier_dir()
    if d is None:
        return None
    with _lock:
        f = _beacon_files.get(rank)
        if f is None:
            path = os.path.join(
                d, f"{BEACON_PREFIX}{os.getpid()}-rank{rank}.jsonl")
            f = open(path, "a", buffering=1)   # line-buffered: each note
            _beacon_files[rank] = f            # hits the page cache whole
        return f


def note_phase(component: str, phase: str, rank: int = 0,
               serial: Optional[int] = None, **extra):
    """One phase-transition note: updates the state board always, and —
    when a dossier dir is configured — appends a beacon line that
    survives a SIGKILL landing ANY time after this call. `extra` carries
    the fault markers (`crashing=True` just before a SIGKILL directive
    fires, `dropped=True` before a RankDead) the post-mortem keys on."""
    set_state(component, phase=phase, rank=rank, serial=serial,
              ts=time.time(), **extra)
    f = _beacon_file(rank)
    if f is None:
        return
    row = {"component": component, "phase": phase, "rank": rank,
           "ts": time.time(), "pid": os.getpid()}
    if serial is not None:
        row["serial"] = serial
    if _world_id is not None:
        row["world"] = _world_id
    row.update(extra)
    try:
        f.write(json.dumps(row) + "\n")
    except (OSError, ValueError):
        pass   # a full disk must not take the protocol down with it


def _metrics_snapshot() -> Dict[str, str]:
    from . import metrics as _metrics
    out = {}
    regs = [("default", _metrics.default_registry())]
    with _lock:
        regs += [(f"extra{i}", r)
                 for i, r in enumerate(_extra_registries)]
    for name, r in regs:
        try:
            out[name] = r.expose()
        except Exception as e:   # a broken scrape callback must not
            out[name] = f"<scrape failed: {e}>"   # block the dossier
    return out


def dump_dossier(reason: str, rank: int = 0, exc: Optional[BaseException]
                 = None, extra: Optional[dict] = None) -> Optional[str]:
    """Write one dossier (returns its path; None when disabled): the
    last-N trace spans, a metrics snapshot, the state board, and the
    world identity — everything a post-mortem needs from a death the
    process could still serialize (enforce error / SIGTERM / RankDead).
    Never raises: a failing dossier must not mask the original error."""
    global _dossier_seq
    d = dossier_dir()
    if d is None:
        return None
    try:
        from . import tracing as _tracing
        spans = [s.to_dict() for s in _tracing.spans()[-DOSSIER_SPANS:]]
    except Exception:
        spans = []
    try:
        # the memory board: current + high-water bytes per channel and
        # the last MFU reading — an OOM-shaped death is attributable
        # from the dossier alone (was the KV cache or the checkpoint
        # staging holding the bytes?). SAME shape as /healthz's
        # "memory" field, so one post-mortem tool reads both.
        from . import memory as _memory
        mem_board = _memory.watermark_board()
    except Exception:
        mem_board = {}
    with _lock:
        _dossier_seq += 1
        seq = _dossier_seq
    doc = {
        "reason": reason,
        "ts": time.time(),
        "pid": os.getpid(),
        "rank": rank,
        "world": _world_id or os.environ.get("PTPU_WORLD_RANK", ""),
        "world_size": os.environ.get("PTPU_WORLD_SIZE", ""),
        "exception": (f"{type(exc).__name__}: {exc}"
                      if exc is not None else None),
        "state": state_board(),
        "memory": mem_board,
        "spans": spans,
        "metrics": _metrics_snapshot(),
        "extra": dict(extra or {}),
    }
    path = os.path.join(
        d, f"{DOSSIER_PREFIX}{int(time.time() * 1e3)}-"
           f"pid{os.getpid()}-rank{rank}-{seq}.json")
    try:
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, default=str)
    except (OSError, TypeError, ValueError):
        return None
    flags.vlog(1, "flight recorder: dossier %s (%s)", path, reason)
    return path


def install(dir: Optional[str] = None, excepthook: bool = True,
            sigterm: bool = True):
    """Arm the recorder for a process: configure the dossier dir (or
    inherit PTPU_DOSSIER_DIR) and wire the two deaths a process can
    observe — an uncaught exception (sys.excepthook chain) and SIGTERM
    (main thread only; the prior handler is chained, so the
    EngineServer drain installed first still runs)."""
    global _prev_excepthook, _prev_sigterm, _sigterm_installed
    if dir is not None:
        configure(dir)
    if not enabled():
        return
    if excepthook and _prev_excepthook is None:
        _prev_excepthook = sys.excepthook

        def _hook(etype, evalue, etb):
            dump_dossier("uncaught exception", exc=evalue)
            (_prev_excepthook or sys.__excepthook__)(etype, evalue, etb)

        sys.excepthook = _hook
    # install the SIGTERM wrapper at most ONCE: a second install() must
    # not stack wrappers (one SIGTERM would then dump N dossiers), and
    # reset() restores the captured original
    if sigterm and not _sigterm_installed \
            and threading.current_thread() is threading.main_thread():
        import signal as _signal
        prev = _prev_sigterm = _signal.getsignal(_signal.SIGTERM)

        def _on_term(signum, frame):
            dump_dossier("SIGTERM")
            if callable(prev):
                prev(signum, frame)
            elif prev == _signal.SIG_DFL:   # pragma: no cover
                _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
                os.kill(os.getpid(), _signal.SIGTERM)

        _signal.signal(_signal.SIGTERM, _on_term)
        _sigterm_installed = True


# ---------------------------------------------------------------------------
# post-mortem synthesis (the Supervisor's side)
# ---------------------------------------------------------------------------

def read_beacons(dir_path: str) -> Dict[int, List[dict]]:
    """{rank: [beacon rows, oldest first]} across every pid that wrote
    into `dir_path`. Torn last lines (the writer died mid-write) are
    dropped silently — that is exactly the crash the log exists for."""
    out: Dict[int, List[dict]] = {}
    if not os.path.isdir(dir_path):
        return out
    for name in sorted(os.listdir(dir_path)):
        if not (name.startswith(BEACON_PREFIX)
                and name.endswith(".jsonl")):
            continue
        with open(os.path.join(dir_path, name)) as f:
            for line in f:
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                out.setdefault(int(row.get("rank", 0)), []).append(row)
    for rows in out.values():
        rows.sort(key=lambda r: r.get("ts", 0.0))
    return out


def collect_dossiers(dir_path: str) -> List[dict]:
    out = []
    if not os.path.isdir(dir_path):
        return out
    for name in sorted(os.listdir(dir_path)):
        if not (name.startswith(DOSSIER_PREFIX)
                and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(dir_path, name)) as f:
                doc = json.load(f)
            doc["_path"] = os.path.join(dir_path, name)
            out.append(doc)
        except (OSError, json.JSONDecodeError):
            continue
    return out


def analyze(dir_path: str) -> Dict[str, Any]:
    """Fold beacons + dossiers into the post-mortem verdict:

    - `dead_rank`/`dead_phase`/`serial`: the rank whose beacon carries a
      `crashing`/`dropped` marker (a fault directive announced itself),
      else the LEAST-ADVANCED rank by last-note timestamp — in an
      unplanned whole-world death, the rank that stopped logging first
      is the best available culprit;
    - `timeline`: per-rank [(phase, ts)] — who waited on whom;
    - `straggler_order`: ranks by last-note time, laggard first."""
    beacons = read_beacons(dir_path)
    dossiers = collect_dossiers(dir_path)
    verdict: Dict[str, Any] = {
        "dead_rank": None, "dead_phase": None, "serial": None,
        "cause": None,
        "timeline": {str(r): [
            {"phase": row.get("phase"), "ts": row.get("ts"),
             "serial": row.get("serial"),
             "component": row.get("component")}
            for row in rows] for r, rows in beacons.items()},
        "n_dossiers": len(dossiers),
        "dossier_reasons": [d.get("reason") for d in dossiers],
    }
    marked = []
    for r, rows in beacons.items():
        for row in rows:
            if row.get("crashing") or row.get("dropped"):
                marked.append((row.get("ts", 0.0), r, row))
    if marked:
        # beacons ACCUMULATE across gang restarts into one dossier dir —
        # the verdict must describe the incarnation that just died, i.e.
        # the MOST RECENT marker, not the first crash ever recorded
        marked.sort(key=lambda x: x[0])
        _, r, row = marked[-1]
        verdict.update(dead_rank=r, dead_phase=row.get("phase"),
                       serial=row.get("serial"),
                       cause=("crash_rank SIGKILL" if row.get("crashing")
                              else "drop_rank simulated death"))
    elif beacons:
        last = {r: rows[-1].get("ts", 0.0)
                for r, rows in beacons.items()}
        r = min(last, key=last.get)
        verdict.update(dead_rank=r,
                       dead_phase=beacons[r][-1].get("phase"),
                       serial=beacons[r][-1].get("serial"),
                       cause="least-advanced rank (heuristic)")
    verdict["straggler_order"] = [
        r for r, _ in sorted(((r, rows[-1].get("ts", 0.0))
                              for r, rows in beacons.items()),
                             key=lambda x: x[1])]
    return verdict


def write_post_mortem(dir_path: str, incarnation: int = 0,
                      extra: Optional[dict] = None) -> str:
    """Analyze `dir_path` and commit the verdict as
    post_mortem-<incarnation>.json (what Supervisor writes after each
    gang death). Returns the path."""
    enforce(os.path.isdir(dir_path),
            f"post-mortem: dossier dir {dir_path!r} does not exist",
            exc=InvalidArgumentError)
    doc = analyze(dir_path)
    doc["incarnation"] = int(incarnation)
    doc["written_ts"] = time.time()
    doc.update(extra or {})
    path = os.path.join(dir_path,
                        f"{POST_MORTEM_PREFIX}{int(incarnation)}.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, default=str)
    return path


def reset():
    """Test isolation: drop configuration, state board, beacon handles,
    and the installed excepthook/SIGTERM chains."""
    global _prev_excepthook, _prev_sigterm, _sigterm_installed, \
        _dossier_seq
    configure(None)
    with _lock:
        _state_board.clear()
        _extra_registries.clear()
        _dossier_seq = 0
    if _prev_excepthook is not None:
        sys.excepthook = _prev_excepthook
        _prev_excepthook = None
    if _sigterm_installed:
        import signal as _signal
        try:
            _signal.signal(_signal.SIGTERM,
                           _prev_sigterm
                           if _prev_sigterm is not None
                           else _signal.SIG_DFL)
        except ValueError:   # not the main thread: leave it installed
            pass
        else:
            _sigterm_installed = False
            _prev_sigterm = None
