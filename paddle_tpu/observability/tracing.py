"""Structured step tracing: typed, nested spans in a lock-cheap ring.

The r03-r11 probes each re-invented span timing with ad-hoc
`time.perf_counter()` pairs; the profiler shim recorded flat host events
only while a profiler context was open. This module is the ONE recorder:

- `span(kind, name, **attrs)` — a context manager recording a typed,
  NESTED interval (parent/depth come from a per-thread stack) with
  provenance attributes (op_loc strings, pass names, schedule configs);
- recording appends into a preallocated ring buffer; the only shared
  mutation on the hot path is one `itertools.count()` draw (atomic under
  the GIL) plus a slot store, so concurrent threads never contend on a
  lock;
- kill switch `PTPU_TRACE=0` (core flag `trace`) makes `__enter__`/
  `__exit__` near-free — the overhead budget for BOTH states is asserted
  in tests/test_observability.py;
- `export_chrome_trace()` / `aggregate()` turn the ring into the Chrome
  (catapult) timeline and the per-span summary tables;
  `paddle_tpu/profiler.py` keeps its fluid-compatible surface as a thin
  window over this ring (`RecordEvent` == a "user" span).

Span kinds are CLOSED (SPAN_KINDS): a typo'd kind raises instead of
minting a new category that no aggregation ever finds.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..core import flags
from ..core.enforce import InvalidArgumentError, enforce

SPAN_KINDS = frozenset({
    "compile",     # executor trace+XLA-compile of a program
    "trace",       # program -> jaxpr tracing sub-phases (region runners)
    "step",        # one executor.run / run_steps dispatch
    "tick",        # one serving-engine decode tick
    "collective",  # host-side collective setup (placement, reconcile)
    "feed_fetch",  # feed placement / fetch realization & write-back
    "admission",   # serving-engine request admission
    "pp_tick",     # pipeline schedule construction / tick tables
    "dp_comm",     # explicit gradient-comm rewrite planning
    "pass",        # any registered Pass application (provenance = name)
    "checkpoint",  # elastic snapshot/restore phases (parallel/elastic.py)
    "request",     # one serving request's lifecycle phases (queue_wait/
                   # prefill/decode/transport, serving_engine.py)
    "memory",      # memory watermark sample (record_counter; rendered as
                   # a Chrome COUNTER track, observability/memory.py)
    "dispatch",    # host-side argument assembly + write-back around the
                   # compiled tick fn (serving engine zero-dispatch path)
    "speculate",   # one speculative round's draft-model propose phase
                   # (γ+1 bound draft ticks, serving/speculative.py)
    "verify",      # the round's single target verify forward over the
                   # γ+1-wide window (serving/speculative.py)
    "offload",     # one host-tier transfer job on the offload stream
                   # (d2h spill / h2d prefetch, framework/offload.py)
    "user",        # RecordEvent-style user annotation
})


class Span:
    """One completed interval. Slots only — the ring holds up to
    `trace_ring` of these."""

    __slots__ = ("kind", "name", "start", "end", "thread_id", "parent",
                 "depth", "attrs", "seq")

    def __init__(self, kind, name, start, end, thread_id, parent, depth,
                 attrs, seq):
        self.kind = kind
        self.name = name
        self.start = start
        self.end = end
        self.thread_id = thread_id
        self.parent = parent       # enclosing span's name ('' at top level)
        self.depth = depth
        self.attrs = attrs
        self.seq = seq

    @property
    def duration_ms(self) -> float:
        return (self.end - self.start) * 1e3

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "name": self.name,
                "duration_ms": round(self.duration_ms, 6),
                "parent": self.parent, "depth": self.depth,
                "thread_id": self.thread_id, "attrs": self.attrs}


# ring storage: preallocated slot list + monotone counter. next(_seq) is
# atomic under the GIL; each writer owns its slot exclusively, so no lock
# is taken on the record path.
_ring: List[Optional[Span]] = []
_ring_cap = 0
_seq = itertools.count()
_resize_lock = threading.Lock()

# per-thread nesting stack: (name, depth) — plus the thread's tag dict
# (scoped_tags), merged into every span the thread records
_tls = threading.local()


class scoped_tags:
    """Tag every span recorded by THIS thread while the scope is open:

        with tracing.scoped_tags(world="w1", rank=2, world_size=4):
            ...   # every span (and record_span) carries these attrs

    Scopes nest (inner tags shadow outer ones of the same key, the rest
    merge); a span's own attrs win over thread tags. This is how the
    process-world rank threads stamp {world_id, rank, world_size} onto
    every span they record without threading the identity through every
    instrumented callsite."""

    __slots__ = ("tags", "_prev")

    def __init__(self, **tags):
        self.tags = tags

    def __enter__(self):
        self._prev = getattr(_tls, "tags", None)
        merged = dict(self._prev) if self._prev else {}
        merged.update(self.tags)
        _tls.tags = merged
        return self

    def __exit__(self, *exc):
        _tls.tags = self._prev
        return False


def rank_scope(world: str, rank: int, world_size: int) -> scoped_tags:
    """The distributed-tracing tag triple: every span this thread records
    is attributed to (world, rank) — tools/trace_merge.py turns the rank
    into a Chrome-trace pid lane."""
    return scoped_tags(world=str(world), rank=int(rank),
                       world_size=int(world_size))


def current_tags() -> Dict[str, Any]:
    """This thread's active scoped_tags (empty dict outside any scope)."""
    tags = getattr(_tls, "tags", None)
    return dict(tags) if tags else {}

# profiler interop: incremented while the legacy profiler context is
# active (spans then record even with the trace flag down — the old
# RecordEvent contract), and an optional device-annotation factory set
# while a jax.profiler device trace runs.
_force_count = 0
annotation_factory: Optional[Callable[[str], Any]] = None


def _ensure_ring():
    global _ring, _ring_cap
    raw = flags.get_flag("trace_ring")
    try:
        cap = int(raw)
    except (TypeError, ValueError):
        raise InvalidArgumentError(
            f"PTPU_TRACE_RING (flag trace_ring) must be a positive "
            f"integer span-ring capacity, got {raw!r}") from None
    if cap < 1:   # no eager f-string on the record hot path
        raise InvalidArgumentError(
            f"PTPU_TRACE_RING (flag trace_ring) must be >= 1 (the span "
            f"ring needs at least one slot), got {cap}")
    if cap != _ring_cap:
        with _resize_lock:
            if cap != _ring_cap:
                _ring = [None] * cap
                _ring_cap = cap
    return _ring


# the flag SPEC object is stable across set_flag calls (set_flag mutates
# .value in place) — holding it dodges a registry lookup per span on the
# hot path
_TRACE_FLAG = flags._REGISTRY["trace"]


def enabled() -> bool:
    return bool(_TRACE_FLAG.value) or _force_count > 0


def force_enable(on: bool):
    """Used by paddle_tpu.profiler: while a profiler() context is open,
    spans record regardless of the PTPU_TRACE flag (the pre-r12
    RecordEvent contract)."""
    global _force_count
    _force_count += (1 if on else -1)
    if _force_count < 0:
        _force_count = 0


def mark() -> int:
    """Current ring position — pass to spans_since() to read only spans
    recorded after this point (the profiler window / bench breakdowns)."""
    _ensure_ring()
    # peek without consuming: count() has no peek, so mint-and-remember
    # would skip a slot. Track via a sacrificial draw is wrong; instead
    # the mark is the NEXT sequence number, derived from a draw we then
    # hand to no span — acceptable: one empty slot per mark.
    return next(_seq)


def _record(span: Span):
    # index with the CAPTURED ring's own length: a concurrent trace_ring
    # resize swaps _ring/_ring_cap as a pair, and mixing the old list
    # with the new cap would IndexError out of span.__exit__ on an
    # instrumented hot path
    ring = _ensure_ring()
    ring[span.seq % len(ring)] = span


class span:
    """RAII span scope. Usage:

        with span("pass", "tp_shard_pass", tp=2):
            ...

    Attributes must be JSON-serializable scalars/strings (op_loc output,
    config ints) — they land in the Chrome trace `args` and the ledger.
    When disabled, enter/exit touch one module global and return.
    """

    __slots__ = ("kind", "name", "attrs", "_start", "_parent", "_depth",
                 "_annotation", "_live")

    def __init__(self, kind: str, name: Optional[str] = None, **attrs):
        if kind not in SPAN_KINDS:   # no eager f-string on the hot path
            raise InvalidArgumentError(
                f"unknown span kind {kind!r}; known: "
                f"{sorted(SPAN_KINDS)}")
        self.kind = kind
        self.name = name or kind
        self.attrs = attrs
        self._start = None
        self._annotation = None
        self._live = False

    def __enter__(self):
        if not (_TRACE_FLAG.value or _force_count):
            return self
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        self._parent = stack[-1][0] if stack else ""
        self._depth = len(stack)
        stack.append((self.name, self._depth))
        self._live = True
        if annotation_factory is not None:
            try:
                self._annotation = annotation_factory(self.name)
                self._annotation.__enter__()
            except Exception:
                self._annotation = None
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if not self._live:
            return False
        end = time.perf_counter()
        if self._annotation is not None:
            self._annotation.__exit__(*exc)
            self._annotation = None
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1][0] == self.name:
            stack.pop()
        tags = getattr(_tls, "tags", None)
        attrs = {**tags, **self.attrs} if tags else self.attrs
        _record(Span(self.kind, self.name, self._start, end,
                     threading.get_ident(), self._parent, self._depth,
                     attrs, next(_seq)))
        self._live = False
        return False


def record_span(kind: str, name: str, start: float, end: float,
                **attrs) -> Optional[Span]:
    """Record a RETROACTIVE span from externally measured perf_counter
    timestamps — phases whose boundaries were observed as plain floats
    (a request's queue-wait between submit and slot assignment, a
    barrier phase reconstructed from beacon notes) become first-class
    spans on the same timeline the live `span` scopes draw on. Thread
    tags (scoped_tags) merge in exactly like live spans; returns None
    when tracing is disabled."""
    if kind not in SPAN_KINDS:
        raise InvalidArgumentError(
            f"unknown span kind {kind!r}; known: {sorted(SPAN_KINDS)}")
    if not (_TRACE_FLAG.value or _force_count):
        return None
    tags = getattr(_tls, "tags", None)
    if tags:
        attrs = {**tags, **attrs}
    s = Span(kind, name, float(start), float(end),
             threading.get_ident(), "", 0, attrs, next(_seq))
    _record(s)
    return s


def record_counter(name: str, value: float, **attrs) -> Optional[Span]:
    """Record one SAMPLE on the `memory` channel: a zero-duration span
    whose `value` attr is the sampled level (a watermark's current bytes,
    an MFU reading). Samples ride the same ring as interval spans — one
    counter draw, no lock — and `chrome_trace_events` renders them as
    Chrome COUNTER events (`ph: "C"`), i.e. a plotted track per sample
    name, so memory levels read as a line under the span lanes. Thread
    tags (scoped_tags / rank_scope) merge in exactly like live spans;
    returns None when tracing is disabled."""
    if not (_TRACE_FLAG.value or _force_count):
        return None
    now = time.perf_counter()
    tags = getattr(_tls, "tags", None)
    attrs = ({**tags, "value": float(value), **attrs} if tags
             else {"value": float(value), **attrs})
    s = Span("memory", name, now, now, threading.get_ident(), "", 0,
             attrs, next(_seq))
    _record(s)
    return s


def clear():
    """Drop every recorded span (test isolation; profiler.reset)."""
    global _ring, _seq
    with _resize_lock:
        _ring = [None] * max(_ring_cap, 1)
        _seq = itertools.count()


def spans(since: Optional[int] = None) -> List[Span]:
    """All live spans in record order; `since` (a mark()) filters to spans
    recorded after that point."""
    out = [s for s in _ring if s is not None]
    out.sort(key=lambda s: s.seq)
    if since is not None:
        out = [s for s in out if s.seq >= since]
    return out


def spans_since(mark_value: int) -> List[Span]:
    return spans(since=mark_value)


def aggregate(span_list: Optional[List[Span]] = None,
              by: str = "name") -> Dict[str, Dict]:
    """Per-span summary table: {key: {calls, total_ms, max_ms, min_ms,
    avg_ms, kind}} — the profiler report and the benchmark span_ms rows
    both read this. `by` is 'name' or 'kind'."""
    enforce(by in ("name", "kind"), f"aggregate by {by!r}?",
            exc=InvalidArgumentError)
    rows: Dict[str, Dict] = {}
    for s in (spans() if span_list is None else span_list):
        key = s.name if by == "name" else s.kind
        r = rows.get(key)
        d = s.duration_ms
        if r is None:
            rows[key] = {"kind": s.kind, "calls": 1, "total_ms": d,
                         "max_ms": d, "min_ms": d}
        else:
            r["calls"] += 1
            r["total_ms"] += d
            r["max_ms"] = max(r["max_ms"], d)
            r["min_ms"] = min(r["min_ms"], d)
    for r in rows.values():
        r["avg_ms"] = r["total_ms"] / r["calls"]
    return rows


def chrome_trace_events(span_list: Optional[List[Span]] = None,
                        pid: int = 0) -> List[Dict]:
    """Spans as Chrome (catapult) complete events; nesting renders from
    the overlapping ts/dur intervals per thread lane."""
    evs = []
    for s in (spans() if span_list is None else span_list):
        if s.kind == "memory":
            # counter sample -> Chrome COUNTER event: args values are
            # plotted as a track named after the sample. Non-numeric
            # attrs (rank tags) ride along for trace_merge's lane
            # assignment and are ignored by the counter renderer.
            evs.append({
                "name": s.name, "cat": s.kind, "ph": "C",
                "ts": s.start * 1e6, "pid": pid, "tid": s.thread_id,
                "args": dict(s.attrs),
            })
            continue
        evs.append({
            "name": s.name, "cat": s.kind, "ph": "X",
            "ts": s.start * 1e6, "dur": (s.end - s.start) * 1e6,
            "pid": pid, "tid": s.thread_id,
            "args": {**s.attrs, "parent": s.parent, "depth": s.depth},
        })
    return evs


def export_chrome_trace(path: str,
                        span_list: Optional[List[Span]] = None) -> str:
    """Write the ring (or a filtered list) as ONE Chrome trace JSON."""
    trace = {"traceEvents": chrome_trace_events(span_list),
             "displayTimeUnit": "ms"}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


def span_overhead_s(n: int = 2000) -> float:
    """Measured per-span enter/exit cost IN THE CURRENT enabled state —
    the number the overhead-budget assertions multiply by spans-per-step.
    Best of 3 windows so a scheduler blip doesn't fail the budget."""
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            with span("user", "overhead_probe"):
                pass
        dt = (time.perf_counter() - t0) / n
        best = dt if best is None else min(best, dt)
    return best
