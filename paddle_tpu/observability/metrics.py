"""Metrics registry: counters / gauges / histograms + Prometheus text.

Serving telemetry for the load harness (ROADMAP item 3): the
continuous-batching engine publishes tokens/s, queue depth, slot
occupancy, tick-latency quantiles, and KV-cache bytes here, and
`EngineServer` exposes the registry over HTTP `/metrics` in the
Prometheus text exposition format (version 0.0.4 — the `# HELP`/`# TYPE`
+ sample-line format every Prometheus-compatible scraper reads).

Distinct from `paddle_tpu.metrics` (model-quality accumulators mirroring
fluid's Accuracy/Auc/...): these are OPERATIONAL metrics about the
runtime itself.

Semantics follow the Prometheus client-library data model:
- Counter: monotone; `inc(v)` with v < 0 raises.
- Gauge: `set`/`inc`/`dec`.
- Histogram: cumulative `le` buckets + `_sum`/`_count` samples, plus a
  host-side `quantile(q)` estimate (linear interpolation inside the
  bucket) for the p50/p95/p99 gauges the engine exports.

Each metric takes one small lock per update — the hot paths here are
per-tick, not per-op, so contention is nil; correctness over cleverness.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.enforce import AlreadyExistsError, InvalidArgumentError, enforce

_NAME_OK = None


def _check_name(name: str):
    global _NAME_OK
    if _NAME_OK is None:
        import re
        _NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    enforce(bool(_NAME_OK.match(name)),
            f"invalid metric name {name!r} (Prometheus [a-zA-Z_:][a-zA-Z0-9_:]*)",
            exc=InvalidArgumentError)


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    parts = []
    for k, v in sorted(labels.items()):
        v = str(v).replace("\\", "\\\\").replace('"', '\\"')
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        _check_name(name)
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._lock = threading.Lock()

    def header_lines(self) -> List[str]:
        out = []
        if self.help:
            out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        return out

    def sample_lines(self) -> List[str]:
        raise NotImplementedError


class Counter(_Metric):
    """Monotonically-increasing count (requests, tokens, ticks)."""

    kind = "counter"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self._value = 0.0

    def inc(self, v: float = 1.0):
        enforce(v >= 0, f"counter {self.name} cannot decrease (inc {v})",
                exc=InvalidArgumentError)
        with self._lock:
            self._value += v

    @property
    def value(self) -> float:
        return self._value

    def sample_lines(self):
        return [f"{self.name}{_fmt_labels(self.labels)} "
                f"{_fmt_value(self._value)}"]


class Gauge(_Metric):
    """Instantaneous value (queue depth, occupancy, cache bytes). An
    optional callback makes the gauge computed at scrape time."""

    kind = "gauge"

    def __init__(self, name, help="", labels=None, fn=None):
        super().__init__(name, help, labels)
        self._value = 0.0
        self._fn = fn

    def set(self, v: float):
        with self._lock:
            self._value = float(v)

    def inc(self, v: float = 1.0):
        with self._lock:
            self._value += v

    def dec(self, v: float = 1.0):
        self.inc(-v)

    @property
    def value(self) -> float:
        if self._fn is not None:
            return float(self._fn())
        return self._value

    def sample_lines(self):
        return [f"{self.name}{_fmt_labels(self.labels)} "
                f"{_fmt_value(self.value)}"]


DEFAULT_BUCKETS = (1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2,
                   5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram(_Metric):
    """Cumulative-bucket histogram (tick/step latency). `observe(v)` is
    O(#buckets); `quantile(q)` estimates from the bucket counts with
    linear interpolation inside the winning bucket (the standard
    histogram_quantile() estimate, computed host-side)."""

    kind = "histogram"

    def __init__(self, name, help="", labels=None,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labels)
        bs = sorted(float(b) for b in buckets)
        enforce(len(bs) >= 1 and bs == sorted(set(bs)),
                f"histogram {name}: buckets must be distinct and sorted",
                exc=InvalidArgumentError)
        self.buckets = bs + [float("inf")]
        self._counts = [0] * len(self.buckets)
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float):
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    break

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> Optional[float]:
        enforce(0.0 <= q <= 1.0, f"quantile {q} outside [0, 1]",
                exc=InvalidArgumentError)
        with self._lock:
            total = self._count
            if total == 0:
                return None
            rank = q * total
            cum = 0
            lo = 0.0
            for i, b in enumerate(self.buckets):
                prev_cum = cum
                cum += self._counts[i]
                if cum >= rank:
                    if b == float("inf"):
                        return lo  # open-ended top bucket: lower bound
                    if self._counts[i] == 0:
                        return b
                    frac = (rank - prev_cum) / self._counts[i]
                    return lo + frac * (b - lo)
                lo = b
            return lo

    def sample_lines(self):
        # snapshot under the same lock observe() takes: a scrape racing
        # an observe must not render _count ahead of the +Inf bucket
        # (the Prometheus invariant histogram_quantile() relies on)
        with self._lock:
            counts = list(self._counts)
            total_sum, total_count = self._sum, self._count
        out = []
        cum = 0
        for i, b in enumerate(self.buckets):
            cum += counts[i]
            labels = dict(self.labels)
            labels["le"] = _fmt_value(b)
            out.append(f"{self.name}_bucket{_fmt_labels(labels)} {cum}")
        out.append(f"{self.name}_sum{_fmt_labels(self.labels)} "
                   f"{_fmt_value(total_sum)}")
        out.append(f"{self.name}_count{_fmt_labels(self.labels)} "
                   f"{total_count}")
        return out


class MetricsRegistry:
    """Named collection of metrics with one text exposition."""

    def __init__(self):
        self._metrics: Dict[Tuple[str, Tuple], _Metric] = {}
        self._lock = threading.Lock()

    def _register(self, metric: _Metric) -> _Metric:
        key = (metric.name, tuple(sorted(metric.labels.items())))
        with self._lock:
            if key in self._metrics:
                raise AlreadyExistsError(
                    f"metric {metric.name!r} with labels {metric.labels} "
                    f"already registered")
            self._metrics[key] = metric
        return metric

    def counter(self, name, help="", labels=None) -> Counter:
        return self._register(Counter(name, help, labels))

    def gauge(self, name, help="", labels=None, fn=None) -> Gauge:
        return self._register(Gauge(name, help, labels, fn=fn))

    def histogram(self, name, help="", labels=None,
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram(name, help, labels, buckets))

    def get(self, name, labels=None) -> Optional[_Metric]:
        return self._metrics.get((name,
                                  tuple(sorted((labels or {}).items()))))

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def expose(self) -> str:
        """Prometheus text exposition (0.0.4). Families sharing a name
        emit their HELP/TYPE header once, label variants consecutively."""
        return _expose_metrics(self.metrics())


def _expose_metrics(metrics: Sequence[_Metric]) -> str:
    """The ONE exposition renderer (MetricsRegistry and MultiRegistry
    both call it): sorted families, HELP/TYPE headers deduplicated,
    label variants consecutive."""
    lines: List[str] = []
    seen_headers = set()
    for m in sorted(metrics, key=lambda m: m.name):
        if m.name not in seen_headers:
            lines.extend(m.header_lines())
            seen_headers.add(m.name)
        lines.extend(m.sample_lines())
    return "\n".join(lines) + "\n"


class MultiRegistry:
    """Read-only union of several registries with ONE text exposition —
    what a single /metrics scrape serves when checkpoint/training series
    live in the process-wide `default_registry()` while each serving
    engine keeps its own registry (two engines in one process must not
    collide on `ptpu_engine_*` names). Families sort and deduplicate
    headers across the members exactly like one registry would."""

    def __init__(self, registries: Sequence[MetricsRegistry]):
        enforce(len(registries) >= 1,
                "MultiRegistry needs at least one member registry",
                exc=InvalidArgumentError)
        self._registries = list(registries)

    def metrics(self) -> List[_Metric]:
        out: List[_Metric] = []
        for r in self._registries:
            out.extend(r.metrics())
        return out

    def get(self, name, labels=None) -> Optional[_Metric]:
        for r in self._registries:
            m = r.get(name, labels)
            if m is not None:
                return m
        return None

    def expose(self) -> str:
        return _expose_metrics(self.metrics())


_default_registry = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The ONE process-wide registry: checkpoint (`ptpu_ckpt_*`,
    parallel/elastic.py), training (`ptpu_train_*`, trainer.py), and any
    other module-level series register here, so a single /metrics scrape
    sees them all next to the scraped engine's own registry
    (EngineServer exposes MultiRegistry([engine, default]))."""
    return _default_registry


def get_or_create(registry: MetricsRegistry, kind: str, name: str,
                  help: str = "", labels=None, **kw) -> _Metric:
    """Idempotent registration: the existing metric when (name, labels)
    is already registered, a fresh one otherwise — what module-level
    metric sets use so re-initialization (tests, reloads) cannot trip
    the duplicate-registration enforce."""
    m = registry.get(name, labels)
    if m is not None:
        return m
    return getattr(registry, kind)(name, help, labels=labels, **kw)
