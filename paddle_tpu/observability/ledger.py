"""Cost ledger: predicted-vs-measured reconciliation in one artifact.

Every evidence round so far published its analytic-vs-census comparison
through a bespoke script (bench_dp wire bytes, probe_bubble slot fits,
bench_tp ring sums). The ledger is the common form: one row per
(model, strategy) run joining

  predicted:  a `framework.costs.predict()` CostReport
  measured:   the HLO collective census (exact), span aggregates from the
              tracer (timing), and any run-reported numbers (losses,
              step_ms)
  checks:     named predicted-vs-measured comparisons, each with the
              tolerance it was held to and whether it passed.

`write()` emits the BENCH_OBS artifact; `check_*` helpers implement the
two standing reconciliation disciplines — EXACT byte balance for
collectives (r08/r11) and banded agreement for bubbles (r09).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from ..core.enforce import InvalidArgumentError, enforce
from ..framework.costs import census_wire_bytes, predicted_wire_bytes


class LedgerRow:
    """One run's predicted-vs-measured record."""

    def __init__(self, name: str, config: Optional[Dict] = None):
        self.name = name
        self.config = dict(config or {})
        self.predicted: Optional[Dict] = None
        self.measured: Dict = {}
        self.checks: List[Dict] = []

    # -- inputs -----------------------------------------------------------
    def set_prediction(self, report: Dict):
        """Attach a framework.costs.predict() CostReport."""
        self.predicted = report
        return self

    def set_census(self, census: Dict, n_devices: int,
                   min_bytes: int = 8):
        """Attach an HLO collective census (framework.costs
        .collective_census output); stores per-kind counts/bytes and the
        ring-model wire total. `min_bytes` excludes scalar loss/metric
        reductions, matching the r08 test discipline."""
        step_census = {k: v for k, v in census.items()
                       if k != "collective-permute"}
        self.measured["census"] = {
            "collectives": {k: len(v) for k, v in census.items()},
            "bytes_by_kind": {k: sum(b for b, _ in v)
                              for k, v in census.items()},
            # once-per-step collectives only: pipeline boundary permutes
            # run per TICK inside the scan (see check_pp_boundary)
            "wire_bytes": int(census_wire_bytes(step_census, n_devices,
                                                min_bytes=min_bytes)),
            "permute_bytes": [b for b, _ in
                              census.get("collective-permute", [])],
            "n_devices": n_devices,
            "min_bytes": min_bytes,
        }
        return self

    def set_spans(self, aggregate: Dict):
        """Attach a tracing.aggregate() table (per-name timing rows)."""
        self.measured["spans"] = {
            k: {f: round(v, 4) if isinstance(v, float) else v
                for f, v in row.items()}
            for k, row in aggregate.items()}
        return self

    def set_measured(self, **fields):
        self.measured.update(fields)
        return self

    # -- reconciliation ---------------------------------------------------
    def _check(self, what, predicted, measured, tolerance, ok):
        rec = {"what": what, "predicted": predicted, "measured": measured,
               "tolerance": tolerance, "ok": bool(ok)}
        self.checks.append(rec)
        return rec

    def check_wire_bytes_exact(self) -> Dict:
        """Predicted per-device wire bytes must equal the census ring
        total EXACTLY — the r08/r11 byte-balance discipline. Requires
        set_prediction and set_census first."""
        enforce(self.predicted is not None and "census" in self.measured,
                f"ledger row {self.name!r}: need both a prediction and a "
                f"census before check_wire_bytes_exact",
                exc=InvalidArgumentError)
        pred = int(predicted_wire_bytes(self.predicted))
        meas = int(self.measured["census"]["wire_bytes"])
        return self._check("wire_bytes", pred, meas, "exact", pred == meas)

    def check_pp_boundary(self) -> Dict:
        """Structural reconciliation of the pipeline boundary transfers
        (the r09 discipline): the compiled step must carry EXACTLY 2
        collective-permutes (one act shift + one grad shift), each moving
        the predicted cut buffer's bytes. Their per-step total is
        per-tick x ticks, which the static census cannot count — hence
        structural, not summed."""
        enforce(self.predicted is not None
                and self.predicted.get("pipeline") is not None
                and "census" in self.measured,
                f"ledger row {self.name!r}: need a pipeline prediction "
                f"and a census before check_pp_boundary",
                exc=InvalidArgumentError)
        boundary = self.predicted["pipeline"]["boundary"]
        pred_bytes = int(boundary["buffer_numel"]) * 4
        meas = sorted(self.measured["census"]["permute_bytes"])
        ok = meas == [pred_bytes, pred_bytes]
        return self._check("pp_boundary_permutes",
                           [pred_bytes, pred_bytes], meas,
                           "exactly 2, exact bytes", ok)

    def check_bubble_fraction(self, measured_fraction: float,
                              band: float = 0.02) -> Dict:
        """Predicted schedule-table bubble fraction vs a measured one,
        within `band` (the r09 2% wall-clock band)."""
        enforce(self.predicted is not None
                and self.predicted.get("pipeline") is not None,
                f"ledger row {self.name!r}: prediction has no pipeline "
                f"section", exc=InvalidArgumentError)
        pred = self.predicted["pipeline"]["bubble_fraction"]
        ok = abs(pred - measured_fraction) <= band
        return self._check("bubble_fraction", pred, measured_fraction,
                           f"abs<={band}", ok)

    def check(self, what: str, predicted, measured, rel: float) -> Dict:
        """Generic relative-tolerance comparison."""
        denom = max(abs(measured), 1e-12)
        ok = abs(predicted - measured) / denom <= rel
        return self._check(what, predicted, measured, f"rel<={rel}", ok)

    @property
    def ok(self) -> bool:
        return all(c["ok"] for c in self.checks)

    def to_dict(self) -> Dict:
        return {"name": self.name, "config": self.config,
                "predicted": self.predicted, "measured": self.measured,
                "checks": self.checks, "ok": self.ok}


class CostLedger:
    """A run's collection of rows + one artifact writer."""

    def __init__(self, run: str, meta: Optional[Dict] = None):
        self.run = run
        self.meta = dict(meta or {})
        self.rows: List[LedgerRow] = []

    def row(self, name: str, **config) -> LedgerRow:
        r = LedgerRow(name, config)
        self.rows.append(r)
        return r

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.rows)

    def to_dict(self) -> Dict:
        return {"run": self.run, "meta": self.meta, "ok": self.ok,
                "rows": [r.to_dict() for r in self.rows]}

    def write(self, path: str) -> str:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=1, default=_json_default)
            f.write("\n")
        return path


def _json_default(o):
    try:
        import numpy as np
        if isinstance(o, np.generic):
            return o.item()
    except ImportError:
        pass
    return str(o)
