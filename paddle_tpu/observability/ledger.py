"""Cost ledger: predicted-vs-measured reconciliation in one artifact.

Every evidence round so far published its analytic-vs-census comparison
through a bespoke script (bench_dp wire bytes, probe_bubble slot fits,
bench_tp ring sums). The ledger is the common form: one row per
(model, strategy) run joining

  predicted:  a `framework.costs.predict()` CostReport
  measured:   the HLO collective census (exact), span aggregates from the
              tracer (timing), and any run-reported numbers (losses,
              step_ms)
  checks:     named predicted-vs-measured comparisons, each with the
              tolerance it was held to and whether it passed.

`write()` emits the BENCH_OBS artifact; `check_*` helpers implement the
two standing reconciliation disciplines — EXACT byte balance for
collectives (r08/r11) and banded agreement for bubbles (r09).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from ..core.enforce import InvalidArgumentError, enforce
from ..framework.costs import census_wire_bytes, predicted_wire_bytes


class LedgerRow:
    """One run's predicted-vs-measured record."""

    def __init__(self, name: str, config: Optional[Dict] = None):
        self.name = name
        self.config = dict(config or {})
        self.predicted: Optional[Dict] = None
        self.measured: Dict = {}
        self.checks: List[Dict] = []

    # -- inputs -----------------------------------------------------------
    def set_prediction(self, report: Dict):
        """Attach a framework.costs.predict() CostReport."""
        self.predicted = report
        return self

    def set_census(self, census: Dict, n_devices: int,
                   min_bytes: int = 8):
        """Attach an HLO collective census (framework.costs
        .collective_census output); stores per-kind counts/bytes and the
        ring-model wire total. `min_bytes` excludes scalar loss/metric
        reductions, matching the r08 test discipline."""
        step_census = {k: v for k, v in census.items()
                       if k != "collective-permute"}
        self.measured["census"] = {
            "collectives": {k: len(v) for k, v in census.items()},
            "bytes_by_kind": {k: sum(b for b, _ in v)
                              for k, v in census.items()},
            # once-per-step collectives only: pipeline boundary permutes
            # run per TICK inside the scan (see check_pp_boundary)
            "wire_bytes": int(census_wire_bytes(step_census, n_devices,
                                                min_bytes=min_bytes)),
            "permute_bytes": [b for b, _ in
                              census.get("collective-permute", [])],
            "n_devices": n_devices,
            "min_bytes": min_bytes,
        }
        return self

    def set_spans(self, aggregate: Dict):
        """Attach a tracing.aggregate() table (per-name timing rows)."""
        self.measured["spans"] = {
            k: {f: round(v, 4) if isinstance(v, float) else v
                for f, v in row.items()}
            for k, row in aggregate.items()}
        return self

    def set_measured(self, **fields):
        self.measured.update(fields)
        return self

    def set_memory_census(self, census: Dict):
        """Attach a measured memory census
        (observability.memory.device_memory_census output — per-device
        state categories from the actual arrays, feed bytes, and the XLA
        executable's argument/output/temp/alias figures)."""
        self.measured["memory"] = census
        return self

    # -- reconciliation ---------------------------------------------------
    def _check(self, what, predicted, measured, tolerance, ok):
        rec = {"what": what, "predicted": predicted, "measured": measured,
               "tolerance": tolerance, "ok": bool(ok)}
        self.checks.append(rec)
        return rec

    def check_wire_bytes_exact(self) -> Dict:
        """Predicted per-device wire bytes must equal the census ring
        total EXACTLY — the r08/r11 byte-balance discipline. Requires
        set_prediction and set_census first."""
        enforce(self.predicted is not None and "census" in self.measured,
                f"ledger row {self.name!r}: need both a prediction and a "
                f"census before check_wire_bytes_exact",
                exc=InvalidArgumentError)
        pred = int(predicted_wire_bytes(self.predicted))
        meas = int(self.measured["census"]["wire_bytes"])
        return self._check("wire_bytes", pred, meas, "exact", pred == meas)

    def check_pp_boundary(self) -> Dict:
        """Structural reconciliation of the pipeline boundary transfers
        (the r09 discipline): the compiled step must carry EXACTLY 2
        collective-permutes (one act shift + one grad shift), each moving
        the predicted cut buffer's bytes. Their per-step total is
        per-tick x ticks, which the static census cannot count — hence
        structural, not summed."""
        enforce(self.predicted is not None
                and self.predicted.get("pipeline") is not None
                and "census" in self.measured,
                f"ledger row {self.name!r}: need a pipeline prediction "
                f"and a census before check_pp_boundary",
                exc=InvalidArgumentError)
        boundary = self.predicted["pipeline"]["boundary"]
        pred_bytes = int(boundary["buffer_numel"]) * 4
        meas = sorted(self.measured["census"]["permute_bytes"])
        ok = meas == [pred_bytes, pred_bytes]
        return self._check("pp_boundary_permutes",
                           [pred_bytes, pred_bytes], meas,
                           "exactly 2, exact bytes", ok)

    def check_bubble_fraction(self, measured_fraction: float,
                              band: float = 0.02) -> Dict:
        """Predicted schedule-table bubble fraction vs a measured one,
        within `band` (the r09 2% wall-clock band)."""
        enforce(self.predicted is not None
                and self.predicted.get("pipeline") is not None,
                f"ledger row {self.name!r}: prediction has no pipeline "
                f"section", exc=InvalidArgumentError)
        pred = self.predicted["pipeline"]["bubble_fraction"]
        ok = abs(pred - measured_fraction) <= band
        return self._check("bubble_fraction", pred, measured_fraction,
                           f"abs<={band}", ok)

    #: categories whose per-device bytes are EXACTLY predictable from
    #: declared shapes + placement markers (costs.memory_categories) —
    #: any drift is a placement/accounting bug, not noise
    MEMORY_EXACT_CATEGORIES = ("params", "params_quantized",
                               "params_draft", "optimizer_state",
                               "ef_residual", "other_state", "feeds")

    def check_memory_identity(self, residual_frac: float = 0.10) -> Dict:
        """The r17 memory accounting identity: every MEASURED per-device
        byte of the step's footprint is attributed to a predicted
        category or lands in an explicitly NAMED residual bucket, and
        the named residual stays bounded. Three disciplines in one
        check set (requires set_prediction — with the memory.per_device
        section — and set_memory_census first):

        1. `memory_<cat>` per category in MEMORY_EXACT_CATEGORIES:
           measured == predicted EXACTLY (declared shapes + placement
           markers fully determine these; `unrealized:<cat>` /
           `unattributed:<cat>` buckets name any drift).
        2. `memory_args_balance`: the category walk must re-derive the
           XLA executable's own argument figure —
           state_total + feeds + seed == argument_bytes within 64 bytes
           (scalar-seed/alignment slack). Catches a category the walk
           missed entirely.
        3. `memory_residual_bound`: unattributed measured bytes (the
           sum of every `unattributed:<cat>` bucket, dominated by
           measured temp exceeding the static transient estimate)
           <= residual_frac of the measured peak footprint.

        The identity itself — sum(attributed) + sum(unattributed) ==
        measured total — holds by construction and is recorded in the
        check's `buckets` field for the artifact."""
        enforce(self.predicted is not None
                and isinstance(self.predicted.get("memory"), dict)
                and "per_device" in self.predicted["memory"]
                and "memory" in self.measured,
                f"ledger row {self.name!r}: need a prediction carrying "
                f"memory.per_device (costs.predict) AND a memory census "
                f"(set_memory_census) before check_memory_identity",
                exc=InvalidArgumentError)
        pred = self.predicted["memory"]["per_device"]
        mem = self.measured["memory"]
        mcats = mem["state"]["categories"]
        measured = {
            "params": mcats["params"],
            "params_quantized": mcats["params_quantized"],
            "params_draft": mcats["params_draft"],
            "optimizer_state": mcats["optimizer_state"],
            "ef_residual": mcats["ef_residual"],
            # kv_cache is the census's refinement of other_state (slot
            # caches are plain persistables to the static walk, which
            # prices them under other_state) — attribute them together
            # so a serving census with kv_names set reconciles instead
            # of pushing every KV byte into unattributed
            "other_state": mcats["other_state"] + mcats["kv_cache"],
            "feeds": mem["feeds"]["per_device_bytes"],
            "seed": mem["seed_bytes"],
            "transient_peak": mem["xla"]["temp_bytes"],
        }
        predicted = {c: float(pred.get(c, 0)) for c in measured}
        attributed, buckets = {}, {}
        for c, mv in measured.items():
            pv = predicted[c]
            attributed[c] = min(mv, pv)
            if mv > pv + 0.5:
                buckets[f"unattributed:{c}"] = mv - pv
            elif pv > mv + 0.5:
                buckets[f"unrealized:{c}"] = pv - mv
        for c in self.MEMORY_EXACT_CATEGORIES:
            self._check(f"memory_{c}", predicted[c], measured[c],
                        "exact", abs(predicted[c] - measured[c]) < 0.5)
        args_lhs = (mcats["state_total"]
                    + mem["feeds"]["per_device_bytes"]
                    + mem["seed_bytes"])
        args_rhs = mem["xla"]["argument_bytes"]
        self._check("memory_args_balance", round(args_lhs), args_rhs,
                    "abs<=64", abs(args_lhs - args_rhs) <= 64)
        unattributed = sum(v for k, v in buckets.items()
                           if k.startswith("unattributed:"))
        peak = float(mem["peak_bytes"])
        rec = self._check(
            "memory_residual_bound", round(residual_frac * peak),
            round(unattributed), f"unattributed<={residual_frac}*peak",
            unattributed <= residual_frac * peak)
        rec["buckets"] = {k: round(v) for k, v in buckets.items()}
        rec["attributed_total"] = round(sum(attributed.values()))
        rec["measured_total"] = round(sum(measured.values()))
        rec["peak_bytes"] = round(peak)
        # the identity proper: attribution is a partition of measured
        assert abs((sum(attributed.values()) + unattributed)
                   - sum(measured.values())) < 1.0
        return rec

    def check_plan_reduction(self, unplanned, *, min_reduction: float = 0.0,
                             time_band: float = 0.02) -> Dict:
        """Reconcile a memory-PLANNED cell against its unplanned twin
        (the r18 acceptance shape — bench_mem --plan): `unplanned` is the
        twin's row-shaped dict {"memory": census, "step_ms": float}.

        1. `plan_state_feeds_invariant`: the plan may only move TRANSIENT
           bytes — state/feed/seed categories must match the unplanned
           census exactly (a plan that changed resident state re-placed
           something it had no business touching).
        2. `plan_reduction_named`: the measured peak reduction must be
           fully explained by the transient/temp category — the named
           side of the r17 identity — not by drift in the residual
           (|Δpeak − Δtemp| bounded by the output-alias slack).
        3. `plan_step_time_band`: planned step time within `time_band`
           of unplanned.
        4. `plan_reduction_floor`: peak reduction >= `min_reduction`
           (fraction; 0 records the measured value without gating).
        """
        enforce("memory" in self.measured
                and isinstance(unplanned, dict)
                and "memory" in unplanned,
                f"ledger row {self.name!r}: need a memory census on both "
                f"the planned row and the unplanned twin",
                exc=InvalidArgumentError)
        mem_p, mem_u = self.measured["memory"], unplanned["memory"]
        sp = dict(mem_p["state"]["categories"],
                  feeds=mem_p["feeds"]["per_device_bytes"])
        su = dict(mem_u["state"]["categories"],
                  feeds=mem_u["feeds"]["per_device_bytes"])
        cats = ("params", "params_quantized", "params_draft",
                "optimizer_state", "ef_residual", "kv_cache",
                "other_state", "feeds")
        same_state = all(abs(sp[c] - su[c]) < 0.5 for c in cats)
        # record every compared category so a failing artifact row shows
        # WHICH one the plan perturbed
        self._check("plan_state_feeds_invariant",
                    {c: round(su[c]) for c in cats},
                    {c: round(sp[c]) for c in cats},
                    "exact", same_state)
        d_peak = float(mem_u["peak_bytes"]) - float(mem_p["peak_bytes"])
        d_temp = float(mem_u["xla"]["temp_bytes"]) \
            - float(mem_p["xla"]["temp_bytes"])
        slack = 64 + abs(
            (mem_u["xla"]["output_bytes"] - mem_u["xla"]["alias_bytes"])
            - (mem_p["xla"]["output_bytes"] - mem_p["xla"]["alias_bytes"]))
        self._check("plan_reduction_named", round(d_temp), round(d_peak),
                    "Δpeak == Δtemp (named transient category)",
                    abs(d_peak - d_temp) <= slack)
        t_p = self.measured.get("step_ms")
        t_u = unplanned.get("step_ms")
        if t_p is not None and t_u is not None and t_u > 0:
            # one-sided: the plan must not SLOW the step past the band;
            # a faster planned step is a win, never a violation
            rel = t_p / t_u - 1.0
            self._check("plan_step_time_band", f"<= +{time_band:.0%}",
                        round(rel, 4), f"rel<={time_band}",
                        rel <= time_band)
        frac = d_peak / max(float(mem_u["peak_bytes"]), 1.0)
        rec = self._check("plan_reduction_floor", min_reduction,
                          round(frac, 4), f">={min_reduction}",
                          frac >= min_reduction)
        rec["planned_peak_bytes"] = round(float(mem_p["peak_bytes"]))
        rec["unplanned_peak_bytes"] = round(float(mem_u["peak_bytes"]))
        rec["reduction_bytes"] = round(d_peak)
        return rec

    def check(self, what: str, predicted, measured, rel: float) -> Dict:
        """Generic relative-tolerance comparison."""
        denom = max(abs(measured), 1e-12)
        ok = abs(predicted - measured) / denom <= rel
        return self._check(what, predicted, measured, f"rel<={rel}", ok)

    @property
    def ok(self) -> bool:
        return all(c["ok"] for c in self.checks)

    def to_dict(self) -> Dict:
        return {"name": self.name, "config": self.config,
                "predicted": self.predicted, "measured": self.measured,
                "checks": self.checks, "ok": self.ok}


class CostLedger:
    """A run's collection of rows + one artifact writer."""

    def __init__(self, run: str, meta: Optional[Dict] = None):
        self.run = run
        self.meta = dict(meta or {})
        self.rows: List[LedgerRow] = []

    def row(self, name: str, **config) -> LedgerRow:
        r = LedgerRow(name, config)
        self.rows.append(r)
        return r

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.rows)

    def to_dict(self) -> Dict:
        return {"run": self.run, "meta": self.meta, "ok": self.ok,
                "rows": [r.to_dict() for r in self.rows]}

    def write(self, path: str) -> str:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=1, default=_json_default)
            f.write("\n")
        return path


def _json_default(o):
    try:
        import numpy as np
        if isinstance(o, np.generic):
            return o.item()
    except ImportError:
        pass
    return str(o)
