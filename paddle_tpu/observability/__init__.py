"""Unified observability layer (r12).

Three pieces, one story — see docs/observability.md:

- `tracing`: typed nested spans recorded into a lock-cheap ring buffer
  by the executors, the rewrite passes, and the serving engine; exported
  as Chrome traces and per-span aggregate tables. Kill switch
  PTPU_TRACE=0.
- `metrics`: operational counters/gauges/histograms with Prometheus text
  exposition; the serving EngineServer serves them over HTTP `/metrics`.
- `ledger`: joins `framework.costs.predict()` analytic cost reports with
  measured spans and HLO collective censuses into one
  predicted-vs-measured artifact per run (BENCH_OBS_*.json).
- `flight_recorder` (r16): the distributed half — per-rank phase
  beacons that survive SIGKILL, crash dossiers (spans + metrics + state
  board) on enforce error/SIGTERM/rank death, and the Supervisor's
  post-mortem synthesis (which rank died, in which barrier phase).
- `memory` (r17): the measured memory + utilization half — the
  device-memory census (XLA buffer-assignment figures + live-state
  walk), per-channel watermarks behind the `ptpu_memory_*` gauges and
  the `memory` trace channel, and the `ptpu_mfu` utilization gauge; the
  ledger reconciles the census against `costs.predict()["memory"]`
  with a committed accounting identity (`check_memory_identity`).

The capability equivalent of the reference's platform/profiler +
device_tracer + timeline stack, grown into the always-on,
prediction-reconciling form the auto-parallel planner (ROADMAP item 2)
and the serving load harness (item 3) consume.
"""

from . import flight_recorder, ledger, memory, metrics, tracing  # noqa: F401
from .ledger import CostLedger, LedgerRow  # noqa: F401
from .metrics import (Counter, Gauge, Histogram,  # noqa: F401
                      MetricsRegistry, MultiRegistry, default_registry)
from .tracing import (SPAN_KINDS, Span, aggregate,  # noqa: F401
                      export_chrome_trace, rank_scope, record_counter,
                      record_span, scoped_tags, span, spans)
