"""Preemption-tolerant training: checkpoint-restart elasticity.

≙ the reference's fault-tolerance story translated to TPU reality. The
reference combines (a) Trainer checkpoints (trainer.py:641-1202), (b) pserver
barrier counts that tolerate trainer exit (SendComplete, executor.cc:48-54),
and (c) the Go master's task retry. XLA worlds are *static* — a compiled
collective program cannot lose a participant — so elasticity on TPU is
checkpoint-restart: detect preemption / peer failure, persist a consistent
step, and restart the job with the survivors (SURVEY.md §5 "failure
detection" row and §7 hard-part 3).
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Callable, Optional

from ..framework.program import default_main_program
from ..trainer import load_checkpoint, save_checkpoint


class PreemptionGuard:
    """Install SIGTERM/SIGINT handlers that request a clean checkpoint stop
    (the TPU-pod preemption notice pattern). Training loops poll
    `should_stop` once per step; on preemption the current step finishes,
    a checkpoint is written, and the process exits 0 so the scheduler
    restarts it."""

    def __init__(self, signals=(signal.SIGTERM,)):
        self._stop = threading.Event()
        self._prev = {}
        for sig in signals:
            try:
                self._prev[sig] = signal.signal(sig, self._handler)
            except ValueError:
                pass  # not main thread — polling still works via request()

    def _handler(self, signum, frame):
        self._stop.set()

    def request(self):
        """Programmatic preemption request (tests, health watchers)."""
        self._stop.set()

    @property
    def should_stop(self) -> bool:
        return self._stop.is_set()


class ElasticTrainer:
    """Checkpoint-restart training driver (≙ Trainer + CheckpointConfig +
    master retry composed; reference trainer.py:442-519 train loop shape).

    train_step(step) -> loss is user code; this driver owns resume,
    periodic checkpointing, preemption, and peer-failure restart.
    """

    def __init__(self, executor, checkpoint_dir: str,
                 save_interval_steps: int = 100,
                 max_checkpoints: int = 3,
                 guard: Optional[PreemptionGuard] = None,
                 main_program=None):
        self.exe = executor
        self.dir = checkpoint_dir
        self.program = main_program or default_main_program()
        self.interval = save_interval_steps
        self.max_checkpoints = max_checkpoints
        self.guard = guard or PreemptionGuard(signals=())
        os.makedirs(checkpoint_dir, exist_ok=True)

    def resume_step(self) -> int:
        """Latest durable step, -1 if fresh (≙ load_checkpoint on init,
        trainer.py:741)."""
        extra = load_checkpoint(self.exe, self.dir, self.program)
        if extra is None:
            return -1
        return int(extra.get("step", -1))

    def run(self, train_step: Callable[[int], float], num_steps: int,
            start_step: Optional[int] = None) -> dict:
        """Run to `num_steps`, checkpointing every `interval` steps and on
        preemption. Returns {last_step, losses, preempted}."""
        step = (self.resume_step() if start_step is None else start_step - 1)
        losses = []
        preempted = False
        last_saved = step
        while step + 1 < num_steps:
            step += 1
            losses.append(float(train_step(step)))
            # read the flag ONCE: a signal landing between two reads must
            # not skip the checkpoint the docstring promises
            stopping = self.guard.should_stop
            if stopping or (step + 1) % self.interval == 0:
                save_checkpoint(self.exe, self.dir, self.program,
                                trainer_args={"step": step},
                                max_num_checkpoints=self.max_checkpoints)
                last_saved = step
            if stopping:
                preempted = True
                break
        if not preempted and last_saved != step:
            save_checkpoint(self.exe, self.dir, self.program,
                            trainer_args={"step": step},
                            max_num_checkpoints=self.max_checkpoints)
        return {"last_step": step, "losses": losses, "preempted": preempted}


class FailureDetector:
    """Chief-side peer liveness watcher over master heartbeats
    (≙ etcd liveness + barrier counts). Calls `on_failure(dead_workers)`
    once when any expected worker misses the horizon."""

    def __init__(self, master, expected_workers, horizon_s: float = 30.0,
                 poll_s: float = 1.0, grace_s: Optional[float] = None):
        self.master = master
        self.expected = set(expected_workers)
        self.horizon_s = horizon_s
        self.poll_s = poll_s
        # startup grace: workers still booting have sent no heartbeat yet —
        # without this the detector fires spuriously on every cold start
        self.grace_s = horizon_s if grace_s is None else grace_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self, on_failure: Callable[[set], None]):
        started = time.time()

        def watch():
            seen: set = set()
            while not self._stop.is_set():
                live = set(self.master.live_workers(self.horizon_s))
                seen.update(live)
                in_grace = time.time() - started < self.grace_s
                # during grace, only workers that already joined can "die"
                watched = self.expected if not in_grace \
                    else self.expected & seen
                dead = watched - live
                if dead:
                    on_failure(dead)
                    return
                time.sleep(self.poll_s)
        self._thread = threading.Thread(target=watch, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
