"""Distributed role/environment configuration + coordinator bootstrap.

≙ reference env-var role config (PADDLE_TRAINING_ROLE / PADDLE_PSERVER_IPS /
PADDLE_TRAINER_ID read by trainer.py:324 and benchmark/fluid/fluid_benchmark.py)
and the gen_nccl_id bootstrap (operators/distributed/gen_nccl_id_op.cc:24,
which gRPC-broadcasts an ncclUniqueId so every process joins one NCCL world).

TPU translation: the "id broadcast" becomes jax.distributed.initialize
against a coordinator address — XLA then compiles collectives over the
ICI/DCN mesh; no per-op communicator plumbing exists or is needed.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

TRAINER = "TRAINER"
PSERVER = "PSERVER"


@dataclass
class DistributedEnv:
    """Parsed role config (≙ the PADDLE_* env protocol)."""
    training_role: str = TRAINER
    trainer_id: int = 0
    num_trainers: int = 1
    coordinator: Optional[str] = None      # host:port of process 0
    pserver_endpoints: tuple = ()
    current_endpoint: Optional[str] = None

    @property
    def is_chief(self) -> bool:
        return self.trainer_id == 0


def parse_env(environ=None) -> DistributedEnv:
    """Read the reference's env-var protocol (trainer.py:324 names kept,
    coordinator added for the jax.distributed bootstrap)."""
    e = environ if environ is not None else os.environ
    return DistributedEnv(
        training_role=e.get("PADDLE_TRAINING_ROLE", TRAINER).upper(),
        trainer_id=int(e.get("PADDLE_TRAINER_ID", "0")),
        num_trainers=int(e.get("PADDLE_TRAINERS_NUM",
                               e.get("PADDLE_TRAINERS", "1"))),
        coordinator=e.get("PADDLE_COORDINATOR_ENDPOINT") or None,
        pserver_endpoints=tuple(
            p for p in e.get("PADDLE_PSERVER_IPS", "").split(",") if p),
        current_endpoint=e.get("PADDLE_CURRENT_ENDPOINT") or None,
    )


_initialized = False


def init_parallel_env(env: Optional[DistributedEnv] = None,
                      timeout_s: int = 300) -> DistributedEnv:
    """Join the multi-host world (≙ gen_nccl_id bootstrap).

    On a single host (no coordinator configured) this is a no-op so the same
    training script runs everywhere. With PADDLE_COORDINATOR_ENDPOINT set,
    process `trainer_id` of `num_trainers` calls jax.distributed.initialize;
    afterwards jax.devices() spans every host and pjit/shard_map programs
    compile cross-host collectives over DCN+ICI.
    """
    global _initialized
    env = env or parse_env()
    if env.coordinator and env.num_trainers > 1 and not _initialized:
        import jax
        jax.distributed.initialize(
            coordinator_address=env.coordinator,
            num_processes=env.num_trainers,
            process_id=env.trainer_id,
            initialization_timeout=timeout_s)
        _initialized = True
    return env


def global_rank() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def world_size() -> int:
    try:
        import jax
        return jax.process_count()
    except Exception:
        return 1
