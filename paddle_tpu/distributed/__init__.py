"""Distributed job layer: bootstrap, fault-tolerant master, elasticity.

≙ reference go/ (etcd master + pserver, SURVEY.md §2.3 last row), the
gen_nccl_id bootstrap (gen_nccl_id_op.cc:24), and the PADDLE_* env role
protocol (trainer.py:324) — rebuilt TPU-native: jax.distributed bootstrap,
file-snapshot task master, checkpoint-restart elasticity.
"""

from .env import (DistributedEnv, PSERVER, TRAINER, global_rank,  # noqa: F401
                  init_parallel_env, parse_env, world_size)
from .master import Master, MasterClient, Task  # noqa: F401
from .elastic import (ElasticTrainer, FailureDetector,  # noqa: F401
                      PreemptionGuard)
