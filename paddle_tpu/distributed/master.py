"""Fault-tolerant data-dispatch master (the Go master, rebuilt).

≙ reference go/master/service.go — the etcd-backed job master that hands out
file-chunk *tasks* to workers with lease timeouts, retries failed/expired
tasks up to a max (processFailedTask service.go:313, max-retry discard :331),
snapshots its queues for crash recovery (:166-207), and starts a new pass
when all tasks finish. The reference pairs it with etcd for liveness and a Go
pserver; here the snapshot goes to a local/NFS path (the coordinator's
durable store), liveness is heartbeat-based, and the service speaks stdlib
XML-RPC so a localhost multi-process test needs no extra deps (the reference
tests fork local subprocesses the same way, test_dist_base.py:27).
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

DEFAULT_TIMEOUT_S = 60.0
DEFAULT_MAX_RETRY = 3     # ≙ MaxTaskFailures semantics (service.go:331)


@dataclass
class Task:
    """A unit of dispatch: a set of data chunks (≙ master.Task over recordio
    chunks, go/master/service.go:89)."""
    task_id: int
    chunks: List[str]
    num_failures: int = 0
    deadline: float = 0.0      # only meaningful while pending
    epoch: int = 0


@dataclass
class _Queues:
    todo: List[Task] = field(default_factory=list)
    pending: Dict[int, Task] = field(default_factory=dict)
    done: List[Task] = field(default_factory=list)
    failed_forever: List[Task] = field(default_factory=list)
    epoch: int = 0


class Master:
    """Task-queue master with timeout/retry/snapshot (≙ go/master Service).

    Thread-safe; serve with `serve_forever` (XML-RPC) or call in-process.
    """

    def __init__(self, snapshot_path: Optional[str] = None,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 max_retry: int = DEFAULT_MAX_RETRY,
                 chunks_per_task: int = 1,
                 num_passes: int = 1):
        self._lock = threading.RLock()
        self._q = _Queues()
        self._next_id = 0
        self.timeout_s = timeout_s
        self.max_retry = max_retry
        self.chunks_per_task = chunks_per_task
        # ≙ the v2 trainer's num_passes: epochs to dispatch before get_task
        # reports exhaustion (0 = endless recycling like the Go master)
        self.num_passes = num_passes
        self.snapshot_path = snapshot_path
        self._heartbeats: Dict[str, float] = {}
        if snapshot_path and os.path.exists(snapshot_path):
            self._recover()

    # -- dataset ----------------------------------------------------------

    def set_dataset(self, chunk_paths: Sequence[str]) -> int:
        """Partition chunks into tasks (≙ SetDataset/partition,
        service.go:140). Idempotent: only the first call seeds the queue
        (recovered state wins, matching the reference's recover-over-reseed
        behavior)."""
        with self._lock:
            if self._q.todo or self._q.pending or self._q.done:
                return 0
            chunks = list(chunk_paths)
            for i in range(0, len(chunks), self.chunks_per_task):
                self._q.todo.append(
                    Task(task_id=self._next_id,
                         chunks=chunks[i:i + self.chunks_per_task]))
                self._next_id += 1
            self._snapshot()
            return len(self._q.todo)

    # -- worker protocol --------------------------------------------------

    def get_task(self, worker_id: str = "") -> Optional[dict]:
        """Lease the next task (≙ GetTask, service.go:280). Returns None
        when nothing is available (caller backs off); implicitly rolls to
        the next pass when a pass completes."""
        with self._lock:
            self._check_timeouts()
            if worker_id:
                self._heartbeats[worker_id] = time.time()
            if not self._q.todo:
                more = (self.num_passes == 0 or
                        self._q.epoch + 1 < self.num_passes)
                if not self._q.pending and self._q.done and more:
                    self._new_pass()        # all done -> next epoch
                else:
                    return None
            if not self._q.todo:
                return None
            t = self._q.todo.pop(0)
            t.deadline = time.time() + self.timeout_s
            self._q.pending[t.task_id] = t
            from ..core.flags import vlog
            vlog(2, "master: leased task %d (%d chunks) to %s",
                 t.task_id, len(t.chunks), worker_id or "?")
            self._snapshot()
            return {"task_id": t.task_id, "chunks": list(t.chunks),
                    "epoch": self._q.epoch}

    def task_finished(self, task_id: int) -> bool:
        """≙ TaskFinished (service.go:313 area)."""
        with self._lock:
            t = self._q.pending.pop(int(task_id), None)
            if t is None:
                return False
            t.num_failures = 0
            self._q.done.append(t)
            self._snapshot()
            return True

    def task_failed(self, task_id: int) -> bool:
        """≙ TaskFailed -> processFailedTask (service.go:313): requeue, or
        discard after max_retry failures (:331)."""
        with self._lock:
            t = self._q.pending.pop(int(task_id), None)
            if t is None:
                return False
            self._fail(t)
            self._snapshot()
            return True

    def heartbeat(self, worker_id: str) -> float:
        """Record liveness; returns the master's clock (workers can detect
        skew). ≙ etcd keepalive in the reference."""
        with self._lock:
            now = time.time()
            self._heartbeats[worker_id] = now
            return now

    def live_workers(self, horizon_s: float = 30.0) -> List[str]:
        """Failure detection: workers with a heartbeat in the last
        `horizon_s` seconds."""
        with self._lock:
            now = time.time()
            return sorted(w for w, ts in self._heartbeats.items()
                          if now - ts <= horizon_s)

    def stats(self) -> dict:
        with self._lock:
            return {"todo": len(self._q.todo),
                    "pending": len(self._q.pending),
                    "done": len(self._q.done),
                    "discarded": len(self._q.failed_forever),
                    "epoch": self._q.epoch}

    # -- internals --------------------------------------------------------

    def _fail(self, t: Task):
        t.num_failures += 1
        if t.num_failures >= self.max_retry:
            self._q.failed_forever.append(t)   # discard (service.go:331)
        else:
            self._q.todo.append(t)

    def _check_timeouts(self):
        """≙ the checkTimeout goroutine: expired leases are failures."""
        now = time.time()
        expired = [tid for tid, t in self._q.pending.items()
                   if t.deadline < now]
        for tid in expired:
            self._fail(self._q.pending.pop(tid))

    def _new_pass(self):
        """All tasks done: recycle into the next pass (epoch)."""
        self._q.epoch += 1
        for t in self._q.done:
            t.num_failures = 0
            t.epoch = self._q.epoch
        self._q.todo = self._q.done
        self._q.done = []

    # -- snapshot/recover (≙ service.go:166-207, etcd -> file) -----------

    def _snapshot(self):
        if not self.snapshot_path:
            return
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump({"q": self._q, "next_id": self._next_id}, f)
        os.replace(tmp, self.snapshot_path)   # atomic like etcd txn

    def _recover(self):
        with open(self.snapshot_path, "rb") as f:
            state = pickle.load(f)
        self._q = state["q"]
        self._next_id = state["next_id"]
        # leases don't survive a master restart: pending -> todo, preserving
        # failure counts (≙ recover path re-queuing in the reference)
        for t in list(self._q.pending.values()):
            self._q.todo.append(t)
        self._q.pending.clear()

    # -- serving ----------------------------------------------------------

    def serve_forever(self, host: str = "127.0.0.1", port: int = 0):
        """Serve the worker protocol over XML-RPC. Returns (server, thread)
        with the bound port in server.server_address."""
        from xmlrpc.server import SimpleXMLRPCServer
        server = SimpleXMLRPCServer((host, port), allow_none=True,
                                    logRequests=False)
        for name in ("set_dataset", "get_task", "task_finished",
                     "task_failed", "heartbeat", "live_workers", "stats"):
            server.register_function(getattr(self, name), name)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        return server, thread


class MasterClient:
    """Worker-side client (≙ go/master client lib). `next_record`-style
    iteration: lease a task, read its chunks, report finish/failure."""

    def __init__(self, endpoint: str, worker_id: str = ""):
        from xmlrpc.client import ServerProxy
        self._proxy = ServerProxy(f"http://{endpoint}", allow_none=True)
        self.worker_id = worker_id or f"worker-{os.getpid()}"

    def set_dataset(self, chunks: Sequence[str]) -> int:
        return self._proxy.set_dataset(list(chunks))

    def get_task(self) -> Optional[dict]:
        return self._proxy.get_task(self.worker_id)

    def task_finished(self, task_id: int) -> bool:
        return self._proxy.task_finished(task_id)

    def task_failed(self, task_id: int) -> bool:
        return self._proxy.task_failed(task_id)

    def heartbeat(self) -> float:
        return self._proxy.heartbeat(self.worker_id)

    def live_workers(self, horizon_s: float = 30.0) -> List[str]:
        """Workers with a heartbeat inside the horizon — lets a chief-side
        FailureDetector watch peers through the master from any process."""
        return self._proxy.live_workers(horizon_s)

    def stats(self) -> dict:
        return self._proxy.stats()

    def tasks(self, poll_interval_s: float = 0.2, max_polls: int = 0):
        """Generator over leased tasks; yields (task_id, chunks). Stops
        after `max_polls` consecutive empty polls (0 = forever)."""
        empty = 0
        while True:
            t = self.get_task()
            if t is None:
                empty += 1
                if max_polls and empty >= max_polls:
                    return
                time.sleep(poll_interval_s)
                continue
            empty = 0
            yield t["task_id"], t["chunks"]
