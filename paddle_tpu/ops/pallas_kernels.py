"""Pallas TPU kernels for the hot ops.

SURVEY.md §7 stage 4: "Pallas kernels only where XLA underperforms". The
first such op is fused attention — XLA materializes the [T, T] score matrix
in HBM for a naive composite, while the flash kernel keeps per-tile scores
in VMEM with an online softmax (O(T) memory), which is the difference
between fitting long sequences on-chip or not (reference analogue: the
hand-written CUDA kernels under operators/math/, e.g. lstm/gru_compute —
the places the reference dropped below its framework abstractions for
speed).

Backend selection: on TPU the kernel compiles via Mosaic; elsewhere the
mathematically-identical jnp composite runs (tests additionally exercise
the kernel itself in pallas interpret mode to pin the tiling logic).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _auto_backend():
    from ..core import flags as _flags
    if _flags.get_flag("disable_pallas"):
        return "xla"
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def _normalize_segment_ids(segment_ids, q, k):
    """Accept a single [B, Tq] array (self-attention; Tq must equal Tk) or
    a (q_ids [B, Tq], kv_ids [B, Tk]) pair. Returns (q_ids, kv_ids) int32
    or (None, None). Same semantics as parallel.ring_attention: a query
    attends a key iff their ids are equal — the static-shape translation
    of the reference's LoD ragged batches (SURVEY §5 long-context row)."""
    if segment_ids is None:
        return None, None
    if isinstance(segment_ids, (tuple, list)):
        q_ids, kv_ids = segment_ids
    else:
        q_ids = kv_ids = segment_ids
    q_ids = jnp.asarray(q_ids, jnp.int32)
    kv_ids = jnp.asarray(kv_ids, jnp.int32)
    B, _, Tq, _ = q.shape
    Tk = k.shape[2]
    if q_ids.shape != (B, Tq) or kv_ids.shape != (B, Tk):
        raise ValueError(
            f"segment_ids shapes {q_ids.shape}/{kv_ids.shape} do not match "
            f"q [B={B}, Tq={Tq}] / k [B={B}, Tk={Tk}]")
    return q_ids, kv_ids


def _attention_reference(q, k, v, scale, causal, segment_ids=None):
    """Naive composite (the XLA fallback path). q/k/v: [B, H, T, D].
    Causal masking is bottom-right aligned (query i sees keys up to
    i + Tk - Tq — the incremental-decode convention). A query row with NO
    visible keys (causal T > Tk head rows, or a segment id matching no
    key) outputs zeros — the flash kernels' semantics — rather than
    softmax's uniform-weights artifact, so every backend computes
    identical values and gradients."""
    q_ids, kv_ids = _normalize_segment_ids(segment_ids, q, k)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    tq, tk = s.shape[-2], s.shape[-1]
    mask = jnp.ones((1, tq, tk), bool)
    if causal:
        mask &= jnp.tril(jnp.ones((tq, tk), bool), tk - tq)[None]
    if q_ids is not None:
        mask &= q_ids[:, :, None] == kv_ids[:, None, :]      # [B, tq, tk]
    if causal or q_ids is not None:
        s = jnp.where(mask[:, None], s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        any_key = jnp.any(mask, axis=-1)                     # [B?, tq]
        p = jnp.where(any_key[:, None, :, None], p, 0.0)
    else:
        p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _segment_mask(qseg_ref, kvseg_ref, block_k):
    """[bq, bk] equality mask from the staged segment-id blocks.

    Layout (mirrors jax's own TPU flash kernel): q ids ride broadcast over
    128 lanes as a [bq, 128] block, kv ids ride broadcast over 8 sublanes
    as an [8, bk] block — Mosaic-legal tilings for what are logically 1-D
    vectors."""
    if block_k <= 128:
        q_ids = qseg_ref[0][:, :block_k]           # [bq, bk] (lane slice)
    else:
        repeats, rem = divmod(block_k, 128)
        if rem:
            raise NotImplementedError("block_k must be a multiple of 128 "
                                      "when segment ids are used")
        q_ids = jnp.tile(qseg_ref[0], (1, repeats))  # [bq, bk]
    kv_ids = kvseg_ref[0][:1]                      # [1, bk]
    return q_ids == kv_ids


def _block_alive(q_blk_idx, k_blk_idx, block_q, block_k, causal,
                 causal_offset, qseg_ref, kvseg_ref):
    """Cheap scalar predicate: can ANY (query, key) pair in this
    (q-block, k-block) tile be unmasked? False → the whole tile's matmuls,
    exp and accumulator updates are skipped (pl.when), which at T=32768
    causal halves the issued FLOPs and on packed batches skips most
    cross-segment tiles. Two safe over-approximations compose:

    - causal: alive iff the LAST query row of the block can see the FIRST
      key column (bottom-right alignment).
    - segments: alive iff the blocks' id RANGES overlap — exact as a
      "no-pair-can-match" test for any id assignment (ranges disjoint ⇒ no
      equality), merely conservative when ranges overlap without an exact
      match; the per-element mask still zeroes those.
    Returns None when nothing can be skipped (no causal, no segments)."""
    alive = None
    if causal:
        alive = ((q_blk_idx + 1) * block_q - 1 + causal_offset
                 >= k_blk_idx * block_k)
    if qseg_ref is not None:
        q_ids = qseg_ref[0]
        kv_ids = kvseg_ref[0]
        seg_alive = ((jnp.max(q_ids) >= jnp.min(kv_ids))
                     & (jnp.min(q_ids) <= jnp.max(kv_ids)))
        alive = seg_alive if alive is None else alive & seg_alive
    return alive


def _flash_kernel(q_ref, k_ref, v_ref, qseg_ref, kvseg_ref, o_ref, lse_ref,
                  m_ref, l_ref, acc_ref, *, scale, causal, block_q, block_k,
                  num_k_blocks, causal_offset, true_tk):
    """One (batch·head, q-block, k-block) grid step of flash attention.

    Grid iterates the k dimension innermost; m/l/acc scratch persists
    across those sequential iterations (TPU grid semantics), implementing
    the online softmax. Fully-masked tiles are skipped (_block_alive).
    """
    from jax.experimental import pallas as pl

    j = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _compute():
        q = q_ref[0]                               # [bq, D]
        k = k_ref[0]                               # [bk, D]
        v = v_ref[0]                               # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]

        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        # padded key columns (from rounding Tk up to the block size) are
        # dead
        s = jnp.where(k_pos < true_tk, s, _NEG_INF)
        if qseg_ref is not None:
            s = jnp.where(_segment_mask(qseg_ref, kvseg_ref, block_k), s,
                          _NEG_INF)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            # bottom-right alignment: matches _attention_reference for
            # Tq != Tk
            s = jnp.where(q_pos + causal_offset >= k_pos, s, _NEG_INF)

        m_prev = m_ref[:]                          # [bq, 1]
        l_prev = l_ref[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                     # [bq, bk]
        # a fully-masked row has m == s == NEG_INF, making exp(s - m) == 1
        # for every DEAD entry — zero them so such rows output 0, not
        # mean(v)
        p = jnp.where(s > _NEG_INF / 2, p, 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new
        l_ref[:] = l_new

    alive = _block_alive(qi, j, block_q, block_k, causal, causal_offset,
                         qseg_ref, kvseg_ref)
    if alive is None:
        _compute()
    else:
        pl.when(alive)(_compute)

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] /
                    jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)
        if lse_ref is not None:
            # logsumexp per query row — the backward kernels' residual.
            # Stored broadcast over 128 lanes: Mosaic requires the last two
            # block dims to be (8k, 128m)-tileable, so a [bq] vector output
            # is illegal on real TPU (same layout as jax's own tpu
            # flash_attention lse).
            lse = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))  # [bq,1]
            lse_ref[0] = jnp.broadcast_to(lse, lse_ref[0].shape)



def _out_struct(shape, dtype, *refs):
    """ShapeDtypeStruct for a pallas_call output, carrying the union of the
    inputs' device-varying axes — required when the kernel runs inside
    shard_map (ring attention) where check_vma demands explicit vma."""
    vma = set()
    for r in refs:
        vma |= set(getattr(getattr(r, "aval", None), "vma", ()) or ())
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    return jax.ShapeDtypeStruct(shape, dtype)


def _clamp_block(block, t):
    """Block size actually used for length t: the requested block, clamped
    to t rounded UP to a 128 multiple. Keeps every block shape
    Mosaic-legal (128 | bq, bk) for ANY sequence length — the sequence is
    padded up to the block multiple and the padding masked/sliced — and
    guarantees the segment-id tiling precondition (128 | bk) by
    construction."""
    return min(block, -(-t // 128) * 128)


def _pad_to(x, axis, target):
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, target - x.shape[axis])
    return jnp.pad(x, pad) if target != x.shape[axis] else x


def _stage_segment_ids(q_ids, kv_ids, H, Tp, Tkp, bq, bk):
    """Broadcast + pad segment ids into their Mosaic-legal layouts and
    build (inputs, specs) for a grid whose leading dim is B*H. Padding
    rows/columns carry id 0, which is harmless: padded key columns are
    killed by the true_tk position guard and padded query rows are sliced
    off (fwd) / killed by the true_tq guard (bwd) regardless of id."""
    from jax.experimental import pallas as pl

    B = q_ids.shape[0]
    qseg = jnp.broadcast_to(
        _pad_to(q_ids, 1, Tp)[:, :, None], (B, Tp, 128))
    kvseg = jnp.broadcast_to(
        _pad_to(kv_ids, 1, Tkp)[:, None, :], (B, 8, Tkp))
    qseg_spec = pl.BlockSpec((1, bq, 128), lambda b, i, j, H=H: (b // H, i, 0))
    kvseg_spec = pl.BlockSpec((1, 8, bk), lambda b, i, j, H=H: (b // H, 0, j))
    return (qseg, kvseg), (qseg_spec, kvseg_spec)


def _flash_attention_pallas(q, k, v, scale, causal, block_q, block_k,
                            interpret, with_lse=False, segment_ids=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    q_ids, kv_ids = _normalize_segment_ids(segment_ids, q, k)
    B, H, T, D = q.shape
    Tk = k.shape[2]
    bq = _clamp_block(block_q, T)
    bk = _clamp_block(block_k, Tk)
    # round sequence lengths up to block multiples: padded queries are
    # sliced off, padded keys are masked dead inside the kernel
    Tp = -(-T // bq) * bq
    Tkp = -(-Tk // bk) * bk
    qf = _pad_to(q.reshape(B * H, T, D), 1, Tp)
    kf = _pad_to(k.reshape(B * H, Tk, D), 1, Tkp)
    vf = _pad_to(v.reshape(B * H, Tk, D), 1, Tkp)
    nq, nk = Tp // bq, Tkp // bk

    inputs = [qf, kf, vf]
    in_specs = [
        pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
    ]
    has_seg = q_ids is not None
    if has_seg:
        seg_inputs, seg_specs = _stage_segment_ids(
            q_ids, kv_ids, H, Tp, Tkp, bq, bk)
        inputs += list(seg_inputs)
        in_specs += list(seg_specs)

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        num_k_blocks=nk, causal_offset=Tk - T, true_tk=Tk)
    out_specs = [pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))]
    out_shape = [_out_struct((B * H, Tp, D), q.dtype, q, k, v)]
    if with_lse:
        out_specs.append(
            pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0)))
        out_shape.append(
            _out_struct((B * H, Tp, 128), jnp.float32, q, k, v))
    # adapt the kernel's (fixed) signature to the optional refs actually
    # staged: segment refs when packed, lse only on the training path.
    # pallas passes refs positionally (inputs, outputs, scratch), so one
    # generic splicer covers every combination.
    n_in, n_out = len(in_specs), len(out_specs)

    def body(*refs, _k=kernel):
        ins, outs = refs[:n_in], refs[n_in:n_in + n_out]
        scratch = refs[n_in + n_out:]
        qs_ref, ks_ref = (ins[3], ins[4]) if has_seg else (None, None)
        lse_ref = outs[1] if with_lse else None
        _k(ins[0], ins[1], ins[2], qs_ref, ks_ref, outs[0], lse_ref,
           *scratch)
    res = pl.pallas_call(
        body,
        grid=(B * H, nq, nk),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)
    out = res[0][:, :T].reshape(B, H, T, D)
    if with_lse:
        return out, res[1][:, :T, 0].reshape(B, H, T)
    return out


# ---------------------------------------------------------------------------
# flash backward (FlashAttention-2 style): recompute P tiles from (q, k,
# lse) in VMEM — no [T, T] materialization in HBM on the backward either
# ---------------------------------------------------------------------------

def _bwd_masks(qi, j, block_q, block_k, causal, causal_offset,
               true_tq, true_tk, qseg_ref=None, kvseg_ref=None):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    valid = (q_pos < true_tq) & (k_pos < true_tk)
    if causal:
        valid &= q_pos + causal_offset >= k_pos
    if qseg_ref is not None:
        valid &= _segment_mask(qseg_ref, kvseg_ref, block_k)
    return valid


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         qseg_ref, kvseg_ref, dq_ref, acc_ref, *, scale,
                         causal, block_q, block_k, num_k_blocks,
                         causal_offset, true_tq, true_tk):
    from jax.experimental import pallas as pl

    j = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]                    # [bq, 1] (128-lane bcast)
        delta = delta_ref[0][:, :1]                # [bq, 1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        valid = _bwd_masks(qi, j, block_q, block_k, causal, causal_offset,
                           true_tq, true_tk, qseg_ref, kvseg_ref)
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)  # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    alive = _block_alive(qi, j, block_q, block_k, causal, causal_offset,
                         qseg_ref, kvseg_ref)
    if alive is None:
        _compute()
    else:
        pl.when(alive)(_compute)

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          qseg_ref, kvseg_ref, dk_ref, dv_ref, dk_acc,
                          dv_acc, *, scale, causal, block_q, block_k,
                          num_q_blocks, causal_offset, true_tq, true_tk):
    from jax.experimental import pallas as pl

    i = pl.program_id(2)      # inner: q blocks
    ki = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]                    # [bq, 1] (128-lane bcast)
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        valid = _bwd_masks(i, ki, block_q, block_k, causal, causal_offset,
                           true_tq, true_tk, qseg_ref, kvseg_ref)
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)  # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [bk, D]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale              # [bq, bk]
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [bk, D]

    alive = _block_alive(i, ki, block_q, block_k, causal, causal_offset,
                         qseg_ref, kvseg_ref)
    if alive is None:
        _compute()
    else:
        pl.when(alive)(_compute)

    @pl.when(i == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_attention_bwd_pallas(q, k, v, o, lse, do, scale, causal,
                                block_q, block_k, interpret,
                                segment_ids=None, delta=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    q_ids, kv_ids = _normalize_segment_ids(segment_ids, q, k)
    B, H, T, D = q.shape
    Tk = k.shape[2]
    bq = _clamp_block(block_q, T)
    bk = _clamp_block(block_k, Tk)
    Tp = -(-T // bq) * bq
    Tkp = -(-Tk // bk) * bk
    nq, nk = Tp // bq, Tkp // bk

    if delta is None:
        # delta_i = sum_d do*o — recomputed here on the single-device path;
        # ring attention passes the global delta in (o may then be None)
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1)                   # [B, H, T]
    qf = _pad_to(q.reshape(B * H, T, D), 1, Tp)
    kf = _pad_to(k.reshape(B * H, Tk, D), 1, Tkp)
    vf = _pad_to(v.reshape(B * H, Tk, D), 1, Tkp)
    dof = _pad_to(do.reshape(B * H, T, D), 1, Tp)
    # per-row residuals ride broadcast over 128 lanes (Mosaic tiling; see
    # the forward lse layout note)
    lsef = jnp.broadcast_to(
        _pad_to(lse.reshape(B * H, T), 1, Tp)[..., None],
        (B * H, Tp, 128))
    deltaf = jnp.broadcast_to(
        _pad_to(delta.reshape(B * H, T), 1, Tp)[..., None],
        (B * H, Tp, 128))

    common = dict(scale=scale, causal=causal, block_q=bq, block_k=bk,
                  causal_offset=Tk - T, true_tq=T, true_tk=Tk)
    has_seg = q_ids is not None
    q_spec = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))
    r_spec = pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0))
    k_spec = pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0))

    def _splice_seg(kernel, n_in):
        """Generic adapter: insert (None, None) for the segment refs when
        no segment inputs are staged (pallas passes refs positionally:
        inputs, outputs, scratch)."""
        if has_seg:
            return kernel

        def body(*refs, _k=kernel):
            return _k(*refs[:n_in], None, None, *refs[n_in:])
        return body

    dq_inputs = [qf, kf, vf, dof, lsef, deltaf]
    dq_specs = [q_spec, k_spec, k_spec, q_spec, r_spec, r_spec]
    dq_kernel = functools.partial(_flash_bwd_dq_kernel, num_k_blocks=nk,
                                  **common)
    if has_seg:
        seg_inputs, seg_specs = _stage_segment_ids(
            q_ids, kv_ids, H, Tp, Tkp, bq, bk)
        dq_inputs += list(seg_inputs)
        dq_specs += list(seg_specs)
    dq = pl.pallas_call(
        _splice_seg(dq_kernel, 6),
        grid=(B * H, nq, nk),
        in_specs=dq_specs,
        out_specs=q_spec,
        out_shape=_out_struct((B * H, Tp, D), q.dtype, q, k, v, do),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(*dq_inputs)

    # dk/dv: k blocks are the outer (revisited) dim, q blocks stream inner
    qi_spec = pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0))
    ri_spec = pl.BlockSpec((1, bq, 128), lambda b, j, i: (b, i, 0))
    kj_spec = pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0))
    dkv_inputs = [qf, kf, vf, dof, lsef, deltaf]
    dkv_specs = [qi_spec, kj_spec, kj_spec, qi_spec, ri_spec, ri_spec]
    dkv_kernel = functools.partial(_flash_bwd_dkv_kernel, num_q_blocks=nq,
                                   **common)
    if has_seg:
        # grid order here is (b, k-block j, q-block i): swap the index-map
        # arguments accordingly
        qsegf, kvsegf = seg_inputs
        dkv_inputs += [qsegf, kvsegf]
        dkv_specs += [
            pl.BlockSpec((1, bq, 128), lambda b, j, i, H=H: (b // H, i, 0)),
            pl.BlockSpec((1, 8, bk), lambda b, j, i, H=H: (b // H, 0, j)),
        ]
    dk, dv = pl.pallas_call(
        _splice_seg(dkv_kernel, 6),
        grid=(B * H, nk, nq),
        in_specs=dkv_specs,
        out_specs=[kj_spec, kj_spec],
        out_shape=[_out_struct((B * H, Tkp, D), k.dtype, q, k, v, do),
                   _out_struct((B * H, Tkp, D), v.dtype, q, k, v, do)],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        interpret=interpret,
    )(*dkv_inputs)

    return (dq[:, :T].reshape(B, H, T, D),
            dk[:, :Tk].reshape(B, H, Tk, D),
            dv[:, :Tk].reshape(B, H, Tk, D))


def flash_attention(q, k, v, scale=None, causal=False, block_q=512,
                    block_k=1024, backend=None, segment_ids=None):
    """Fused multi-head attention. q/k/v: [B, H, T, D].

    backend: None = auto (pallas on TPU, XLA composite elsewhere);
    "pallas_interpret" forces the kernel through the pallas interpreter
    (CPU-testable); "xla" forces the composite.

    segment_ids: packed-batch masking (the LoD translation, SURVEY §5) —
    a [B, T] int array (self-attention) or a (q_ids, kv_ids) pair; a query
    attends a key iff their ids are equal, matching
    parallel.ring_attention's semantics. Composes with `causal`.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if backend is None:
        backend = _auto_backend()
    return _fused_attention(q, k, v, segment_ids, scale, causal, backend,
                            block_q, block_k)


# ---------------------------------------------------------------------------
# differentiable wrapper + op registration
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _fused_attention(q, k, v, segment_ids, scale, causal, backend,
                     block_q=512, block_k=1024):
    if backend == "xla":
        return _attention_reference(q, k, v, scale, causal, segment_ids)
    return _flash_attention_pallas(q, k, v, scale, causal, block_q, block_k,
                                   interpret=(backend == "pallas_interpret"),
                                   segment_ids=segment_ids)


def _fused_attention_fwd(q, k, v, segment_ids, scale, causal, backend,
                         block_q=512, block_k=1024):
    if backend == "xla":
        out = _attention_reference(q, k, v, scale, causal, segment_ids)
        return out, (q, k, v, segment_ids, None, None)
    out, lse = _flash_attention_pallas(
        q, k, v, scale, causal, block_q, block_k,
        interpret=(backend == "pallas_interpret"), with_lse=True,
        segment_ids=segment_ids)
    return out, (q, k, v, segment_ids, out, lse)


def _fused_attention_bwd(scale, causal, backend, block_q, block_k, res, g):
    q, k, v, segment_ids, o, lse = res
    if backend == "xla":
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _attention_reference(q_, k_, v_, scale,
                                                    causal, segment_ids),
            q, k, v)
        return vjp(g) + (None,)
    # flash backward: recompute P tiles from (q, k, lse) in VMEM — the
    # [T, T] score matrix never exists in HBM in either direction
    return _flash_attention_bwd_pallas(
        q, k, v, o, lse, g, scale, causal, block_q, block_k,
        interpret=(backend == "pallas_interpret"),
        segment_ids=segment_ids) + (None,)


_fused_attention.defvjp(_fused_attention_fwd, _fused_attention_bwd)


def _register():
    from ..framework.registry import register_op

    @register_op("fused_attention")
    def _fused_attention_op(ctx, ins, attrs):
        """Fused scaled-dot-product attention (≙ the composite
        nets.py:332 scaled_dot_product_attention upgraded to a flash
        kernel). Lowering picks the backend per device — the TPU-native
        translation of the reference's (place, dtype, ...) kernel
        dispatch (op_registry.h:214)."""
        q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
        scale = attrs.get("scale") or 1.0 / (q.shape[-1] ** 0.5)
        backend = attrs.get("backend") or _auto_backend()
        seg = None
        if ins.get("QSeg"):
            q_ids = ins["QSeg"][0]
            kv_ids = ins["KVSeg"][0] if ins.get("KVSeg") else q_ids
            seg = (q_ids, kv_ids)
        out = _fused_attention(q, k, v, seg, scale,
                               attrs.get("causal", False), backend)
        return {"Out": [out]}


_register()
