"""Sequence-family op lowerings (static-shape translation of LoD).

≙ reference sequence ops (SURVEY §2.2 "Sequence/LoD" family) and the recurrent
lstm/gru ops (operators/lstm_op.cc, gru_op.cc with the sequence2batch trick,
operators/math/sequence2batch.h).

TPU-native representation: a "sequence" variable is a dense padded array
[batch, max_len, ...] plus a companion int32 length vector [batch] (slot
"SeqLen"), replacing the reference's LoD ragged offsets (lod_tensor.h:58).
Masked/segmented lowerings keep XLA shapes static; recurrences use lax.scan
over the time dimension — the compiler-friendly control flow replacing the
reference's block-based RecurrentOp/WhileOp interpretation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op


def _mask(x, seqlen):
    """[B, T] validity mask broadcastable to x: [B, T, ...]."""
    b, t = x.shape[0], x.shape[1]
    m = jnp.arange(t)[None, :] < seqlen[:, None]
    return m.reshape((b, t) + (1,) * (x.ndim - 2))


@register_op("sequence_pool")
def _sequence_pool(ctx, ins, attrs):
    x = ins["X"][0]            # [B, T, D]
    seqlen = ins["SeqLen"][0]  # [B]
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    m = _mask(x, seqlen)
    mf = m.astype(x.dtype)
    if ptype == "SUM":
        out = jnp.sum(x * mf, axis=1)
    elif ptype == "AVERAGE":
        out = jnp.sum(x * mf, axis=1) / jnp.maximum(
            seqlen.astype(x.dtype), 1).reshape((-1,) + (1,) * (x.ndim - 2))
    elif ptype == "SQRT":
        out = jnp.sum(x * mf, axis=1) / jnp.sqrt(jnp.maximum(
            seqlen.astype(x.dtype), 1)).reshape((-1,) + (1,) * (x.ndim - 2))
    elif ptype == "MAX":
        neg = jnp.finfo(x.dtype).min
        out = jnp.max(jnp.where(m, x, neg), axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(seqlen - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1)
        out = jnp.squeeze(out, axis=1)
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError(f"unknown pooltype {ptype}")
    return {"Out": [out]}


@register_op("sequence_softmax")
def _sequence_softmax(ctx, ins, attrs):
    x = ins["X"][0]            # [B, T]
    seqlen = ins["SeqLen"][0]
    m = jnp.arange(x.shape[1])[None, :] < seqlen[:, None]
    neg = jnp.finfo(x.dtype).min
    out = jax.nn.softmax(jnp.where(m, x, neg), axis=1)
    return {"Out": [out * m.astype(x.dtype)]}


@register_op("sequence_first_step")
def _sequence_first_step(ctx, ins, attrs):
    return {"Out": [ins["X"][0][:, 0]]}


@register_op("sequence_last_step")
def _sequence_last_step(ctx, ins, attrs):
    x = ins["X"][0]
    seqlen = ins["SeqLen"][0]
    idx = jnp.maximum(seqlen - 1, 0)
    out = jnp.take_along_axis(
        x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1)
    return {"Out": [jnp.squeeze(out, axis=1)]}


@register_op("sequence_reverse")
def _sequence_reverse(ctx, ins, attrs):
    x = ins["X"][0]
    seqlen = ins["SeqLen"][0]
    t = x.shape[1]
    # reverse only the valid prefix: index i -> len-1-i for i < len else i
    ar = jnp.arange(t)[None, :]
    idx = jnp.where(ar < seqlen[:, None], seqlen[:, None] - 1 - ar, ar)
    return {"Y": [jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)]}


@register_op("sequence_expand")
def _sequence_expand(ctx, ins, attrs):
    # broadcast per-sequence vector over time (simplified ref semantics)
    x = ins["X"][0]      # [B, D]
    y = ins["Y"][0]      # [B, T, ...] provides target length
    t = y.shape[1]
    return {"Out": [jnp.repeat(x[:, None], t, axis=1)]}


@register_op("sequence_concat")
def _sequence_concat(ctx, ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=-1)]}


@register_op("sequence_slice")
def _sequence_slice(ctx, ins, attrs):
    x = ins["X"][0]
    offset = ins["Offset"][0].reshape(-1)
    length = attrs.get("length", None)
    # static-length slice per batch element
    t = int(length) if length is not None else x.shape[1]
    idx = offset[:, None] + jnp.arange(t)[None, :]
    return {"Out": [jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)]}


@register_op("sequence_mask", stop_gradient=True)
def _sequence_mask(ctx, ins, attrs):
    seqlen = ins["X"][0].reshape(-1)
    maxlen = attrs.get("maxlen", -1)
    if maxlen is None or maxlen < 0:
        raise ValueError("sequence_mask requires static maxlen on TPU")
    m = jnp.arange(maxlen)[None, :] < seqlen[:, None]
    return {"Y": [m.astype(jnp.float32)]}


@register_op("sequence_pad")
def _sequence_pad(ctx, ins, attrs):
    # already-padded representation: identity + emit lengths
    return {"Out": [ins["X"][0]], "Length": [ins["SeqLen"][0]]}


@register_op("sequence_erase", stop_gradient=True)
def _sequence_erase(ctx, ins, attrs):
    # mark erased tokens invalid via mask rather than compaction (static shape)
    x = ins["X"][0]
    tokens = jnp.asarray(attrs["tokens"])
    keep = jnp.all(x[..., None] != tokens.reshape((1,) * x.ndim + (-1,)),
                   axis=-1)
    return {"Out": [jnp.where(keep, x, 0)], "Mask": [keep.astype(jnp.int32)]}


# ---- recurrent cells over time via lax.scan (≙ lstm_op.cc / gru_op.cc) ----

def _lstm_scan(x_proj, h0, c0, w_h, seqlen, gate_act, cell_act, cand_act,
               reverse=False):
    """x_proj: [B, T, 4H] input projections (i, f, c, o gate order as the
    reference's lstm_compute), w_h: [H, 4H]."""
    b, t, h4 = x_proj.shape
    h = h4 // 4
    steps = jnp.arange(t)
    if reverse:
        x_proj = jnp.flip(x_proj, axis=1)

    def step(carry, inp):
        h_prev, c_prev = carry
        xt, it = inp  # xt: [B, 4H], it: scalar time index
        gates = xt + jnp.dot(h_prev, w_h)
        i, f, c_hat, o = jnp.split(gates, 4, axis=-1)
        i, f, o = gate_act(i), gate_act(f), gate_act(o)
        c_hat = cand_act(c_hat)
        c_new = f * c_prev + i * c_hat
        h_new = o * cell_act(c_new)
        # freeze state for finished sequences (≙ shrink_rnn_memory)
        tpos = it if not reverse else (t - 1 - it)
        valid = (tpos < seqlen)[:, None]
        h_new = jnp.where(valid, h_new, h_prev)
        c_new = jnp.where(valid, c_new, c_prev)
        return (h_new, c_new), (h_new, c_new)

    (_, _), (hs, cs) = jax.lax.scan(
        step, (h0, c0), (jnp.swapaxes(x_proj, 0, 1), steps))
    hs = jnp.swapaxes(hs, 0, 1)  # [B, T, H]
    cs = jnp.swapaxes(cs, 0, 1)
    if reverse:
        hs, cs = jnp.flip(hs, axis=1), jnp.flip(cs, axis=1)
    return hs, cs


_ACTS = {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh, "relu": jax.nn.relu,
         "identity": lambda x: x}


@register_op("dynamic_lstm")
def _dynamic_lstm(ctx, ins, attrs):
    """≙ lstm_op.cc: Input is the pre-projected [B, T, 4H] sequence (the fc
    is done by the layer, as in the reference where fc precedes dynamic_lstm).
    Weight: [H, 4H] hidden-to-hidden; Bias: [4H] (+[3H] peepholes if
    use_peepholes — peepholes folded into gates here)."""
    x = ins["Input"][0]
    w = ins["Weight"][0]
    seqlen = ins["SeqLen"][0]
    h = w.shape[0]
    b = x.shape[0]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    if bias is not None:
        x = x + bias.reshape(1, 1, -1)[:, :, :4 * h]
    gate_act = _ACTS[attrs.get("gate_activation", "sigmoid")]
    cell_act = _ACTS[attrs.get("cell_activation", "tanh")]
    cand_act = _ACTS[attrs.get("candidate_activation", "tanh")]
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((b, h), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((b, h), x.dtype)
    hs, cs = _lstm_scan(x, h0, c0, w, seqlen, gate_act, cell_act, cand_act,
                        reverse=attrs.get("is_reverse", False))
    return {"Hidden": [hs], "Cell": [cs]}


@register_op("dynamic_lstmp")
def _dynamic_lstmp(ctx, ins, attrs):
    """≙ lstmp_op.cc: LSTM with a recurrent projection layer. Input
    [B, T, 4H] pre-projected; Weight [P, 4H] recurrent (acts on the
    PROJECTED state); ProjWeight [H, P]. Emits Projection [B, T, P] and
    Cell [B, T, H]. With use_peepholes (default), Bias carries 7H values
    and the peephole weights w_ic/w_fc ⊙ c_{t-1} and w_oc ⊙ c_t enter the
    gates as in the reference."""
    x = ins["Input"][0]
    w = ins["Weight"][0]          # [P, 4H]
    w_proj = ins["ProjWeight"][0]  # [H, P]
    seqlen = ins["SeqLen"][0]
    h = w_proj.shape[0]
    p_dim = w_proj.shape[1]
    b, t, _ = x.shape
    bias = ins["Bias"][0] if ins.get("Bias") else None
    # use_peepholes: bias is [7H] = 4H gate bias + w_ic/w_fc/w_oc peephole
    # weights, which enter the i/f gates via c_{t-1} and the o gate via c_t
    # (≙ reference lstmp_op.h ComputeGate peephole connections)
    w_ic = w_fc = w_oc = None
    if bias is not None:
        flat = bias.reshape(-1)
        x = x + flat[:4 * h].reshape(1, 1, -1)
        if attrs.get("use_peepholes", True) and flat.shape[0] == 7 * h:
            w_ic = flat[4 * h:5 * h]
            w_fc = flat[5 * h:6 * h]
            w_oc = flat[6 * h:7 * h]
    gate_act = _ACTS[attrs.get("gate_activation", "sigmoid")]
    cell_act = _ACTS[attrs.get("cell_activation", "tanh")]
    cand_act = _ACTS[attrs.get("candidate_activation", "tanh")]
    proj_act = _ACTS[attrs.get("proj_activation", "identity")]
    reverse = attrs.get("is_reverse", False)
    if reverse:
        x = jnp.flip(x, axis=1)
    steps = jnp.arange(t)
    # H0 is the HIDDEN state [B, H] as in lstmp_op.cc — it enters the
    # recurrence through the projection, like every other step's hidden
    if ins.get("H0"):
        proj_act0 = _ACTS[attrs.get("proj_activation", "identity")]
        r0 = proj_act0(jnp.dot(ins["H0"][0], w_proj))
    else:
        r0 = jnp.zeros((b, p_dim), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((b, h), x.dtype)

    def step(carry, inp):
        r_prev, c_prev = carry
        xt, it = inp
        gates = xt + jnp.dot(r_prev, w)
        i, f, c_hat, o = jnp.split(gates, 4, axis=-1)
        if w_ic is not None:
            i = i + w_ic * c_prev
            f = f + w_fc * c_prev
        i, f = gate_act(i), gate_act(f)
        c_new = f * c_prev + i * cand_act(c_hat)
        if w_oc is not None:
            o = o + w_oc * c_new
        o = gate_act(o)
        h_new = o * cell_act(c_new)
        r_new = proj_act(jnp.dot(h_new, w_proj))
        tpos = it if not reverse else (t - 1 - it)
        valid = (tpos < seqlen)[:, None]
        r_new = jnp.where(valid, r_new, r_prev)
        c_new = jnp.where(valid, c_new, c_prev)
        return (r_new, c_new), (r_new, c_new)

    (_, _), (rs, cs) = jax.lax.scan(
        step, (r0, c0), (jnp.swapaxes(x, 0, 1), steps))
    rs = jnp.swapaxes(rs, 0, 1)
    cs = jnp.swapaxes(cs, 0, 1)
    if reverse:
        rs = jnp.flip(rs, axis=1)
        cs = jnp.flip(cs, axis=1)
    return {"Projection": [rs], "Cell": [cs]}


@register_op("sequence_reshape")
def _sequence_reshape(ctx, ins, attrs):
    """≙ sequence_reshape_op.cc: change the feature width of a sequence,
    scaling each sequence length by old_dim/new_dim. [B, T, D] + lengths
    -> [B, T*D/new_dim, new_dim] + scaled lengths.

    The reference additionally checks every sequence's numel is divisible
    by new_dim at runtime; that is a data-dependent error the compiled
    graph cannot raise, so divisibility of each seqlen*D is the caller's
    contract (it holds automatically whenever new_dim divides D)."""
    from ..core.enforce import InvalidArgumentError, enforce
    x = ins["X"][0]
    seqlen = ins["SeqLen"][0]
    new_dim = attrs["new_dim"]
    b, t, d = x.shape
    enforce((t * d) % new_dim == 0,
            f"sequence_reshape: T*D={t*d} not divisible by "
            f"new_dim={new_dim}", exc=InvalidArgumentError)
    out = jnp.reshape(x, (b, (t * d) // new_dim, new_dim))
    new_len = (seqlen * d) // new_dim
    return {"Out": [out], "SeqLenOut": [new_len]}


@register_op("dynamic_gru")
def _dynamic_gru(ctx, ins, attrs):
    """≙ gru_op.cc: Input [B, T, 3H] pre-projected; Weight packs
    [H, 2H] update/reset and [H, H] candidate."""
    x = ins["Input"][0]
    w = ins["Weight"][0]  # [H, 3H]
    seqlen = ins["SeqLen"][0]
    h = w.shape[0]
    b = x.shape[0]
    if ins.get("Bias"):
        x = x + ins["Bias"][0].reshape(1, 1, -1)
    w_rz = w[:, :2 * h]
    w_c = w[:, 2 * h:]
    gate_act = _ACTS[attrs.get("gate_activation", "sigmoid")]
    cand_act = _ACTS[attrs.get("activation", "tanh")]
    reverse = attrs.get("is_reverse", False)
    if reverse:
        x = jnp.flip(x, axis=1)
    t = x.shape[1]
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((b, h), x.dtype)

    def step(h_prev, inp):
        xt, it = inp
        x_rz, x_c = xt[:, :2 * h], xt[:, 2 * h:]
        rz = gate_act(x_rz + jnp.dot(h_prev, w_rz))
        r, z = jnp.split(rz, 2, axis=-1)
        c = cand_act(x_c + jnp.dot(r * h_prev, w_c))
        h_new = z * h_prev + (1 - z) * c
        tpos = it if not reverse else (t - 1 - it)
        valid = (tpos < seqlen)[:, None]
        h_new = jnp.where(valid, h_new, h_prev)
        return h_new, h_new

    _, hs = jax.lax.scan(step, h0, (jnp.swapaxes(x, 0, 1), jnp.arange(t)))
    hs = jnp.swapaxes(hs, 0, 1)
    if reverse:
        hs = jnp.flip(hs, axis=1)
    return {"Hidden": [hs]}


@register_op("edit_distance", stop_gradient=True)
def _edit_distance(ctx, ins, attrs):
    """Levenshtein distance per batch pair via dynamic programming with
    lax.scan over one string (≙ edit_distance_op.cc)."""
    hyp = ins["Hyps"][0]       # [B, Th]
    ref = ins["Refs"][0]       # [B, Tr]
    hyp_len = ins["HypsLen"][0]
    ref_len = ins["RefsLen"][0]
    b, th = hyp.shape
    tr = ref.shape[1]

    def per_pair(h, r, hl, rl):
        row0 = jnp.arange(tr + 1, dtype=jnp.float32)

        def step(prev_row, i):
            ch = h[i]
            sub_cost = (r != ch).astype(jnp.float32)

            def inner(carry, j):
                left = carry
                dele = prev_row[j + 1] + 1
                ins_ = left + 1
                sub = prev_row[j] + sub_cost[j]
                val = jnp.minimum(jnp.minimum(dele, ins_), sub)
                return val, val

            first = prev_row[0] + 1
            _, rest = jax.lax.scan(inner, first, jnp.arange(tr))
            new_row = jnp.concatenate([first[None], rest])
            # only advance while i < hl
            new_row = jnp.where(i < hl, new_row, prev_row)
            return new_row, None

        final, _ = jax.lax.scan(step, row0, jnp.arange(th))
        return final[rl]

    dist = jax.vmap(per_pair)(hyp, ref, hyp_len, ref_len)
    if attrs.get("normalized", False):
        dist = dist / jnp.maximum(ref_len.astype(jnp.float32), 1)
    return {"Out": [dist[:, None]],
            "SequenceNum": [jnp.asarray(b, dtype=jnp.int64)]}


@register_op("sequence_conv")
def _sequence_conv(ctx, ins, attrs):
    """Context-window convolution over time (≙ sequence_conv_op.cc +
    operators/math/context_project.h): for each timestep gather a
    [context_length] window of features, flatten, matmul with the filter
    [context_length * D, num_filters]. Out-of-sequence context rows are zero.
    """
    x = ins["X"][0]              # [B, T, D]
    w = ins["Filter"][0]         # [ctx_len * D, M]
    seqlen = ins["SeqLen"][0]
    b, t, d = x.shape
    ctx_len = int(attrs.get("contextLength", 3))
    ctx_start = int(attrs.get("contextStart", -(ctx_len // 2)))
    m = _mask(x, seqlen).astype(x.dtype)
    xm = x * m
    cols = []
    for k in range(ctx_len):
        off = ctx_start + k
        shifted = jnp.roll(xm, -off, axis=1)
        ar = jnp.arange(t)
        valid = ((ar + off >= 0) & (ar + off < t))[None, :, None]
        cols.append(jnp.where(valid, shifted, 0))
    ctx_mat = jnp.concatenate(cols, axis=-1)       # [B, T, ctx_len*D]
    out = jnp.matmul(ctx_mat, w, preferred_element_type=jnp.float32)
    out = out.astype(x.dtype) * m
    return {"Out": [out]}


@register_op("row_conv")
def _row_conv(ctx, ins, attrs):
    """≙ row_conv_op.cc (lookahead row convolution from DeepSpeech2):
    out[t] = sum_{i=0..k-1} w[i] * x[t+i], zero past the sequence end.
    X [B, T, D], Filter [k, D]."""
    x = ins["X"][0]
    w = ins["Filter"][0]
    k = w.shape[0]
    T = x.shape[1]
    out = jnp.zeros_like(x)
    for i in range(k):  # k is small (lookahead window); unrolled is fine
        shifted = jnp.pad(x, ((0, 0), (0, i), (0, 0)))[:, i:i + T, :]
        out = out + shifted * w[i][None, None, :]
    return {"Out": [out]}


@register_op("lstm_unit")
def _lstm_unit(ctx, ins, attrs):
    """≙ lstm_unit_op.h:63-66: one LSTM cell step from pre-projected gates.
    X [B, 4H] sliced (i, f, o, g) in the REFERENCE order:
    i = sig(X[:, :H]), f = sig(X[:, H:2H] + forget_bias),
    o = sig(X[:, 2H:3H]), g = tanh(X[:, 3H:])."""
    x = ins["X"][0]
    c_prev = ins["C_prev"][0]
    h = c_prev.shape[-1]
    forget_bias = attrs.get("forget_bias", 0.0)
    i = jax.nn.sigmoid(x[:, :h])
    f = jax.nn.sigmoid(x[:, h:2 * h] + forget_bias)
    o = jax.nn.sigmoid(x[:, 2 * h:3 * h])
    g = jnp.tanh(x[:, 3 * h:])
    new_c = c_prev * f + i * g
    new_h = jnp.tanh(new_c) * o
    return {"C": [new_c], "H": [new_h]}


@register_op("gru_unit")
def _gru_unit(ctx, ins, attrs):
    """≙ gru_unit_op.h: one GRU cell step. Input [B, 3H] (pre-projected
    x contributions for update/reset/candidate), HiddenPrev [B, H],
    Weight [H, 3H] (recurrent), Bias [3H] optional.

    Reference semantics (gru_unit_op.h:116): h = u*(c - h_prev) + h_prev,
    i.e. the update gate moves TOWARD the candidate. Gate output is the
    reference's [B, 3H] = (u, r, c)."""
    x = ins["Input"][0]
    h_prev = ins["HiddenPrev"][0]
    w = ins["Weight"][0]
    h = h_prev.shape[-1]
    bias = ins["Bias"][0] if ins.get("Bias") else jnp.zeros((3 * h,), x.dtype)
    xu, xr, xc = x[:, :h], x[:, h:2 * h], x[:, 2 * h:]
    hu = h_prev @ w[:, :h]
    hr = h_prev @ w[:, h:2 * h]
    u = jax.nn.sigmoid(xu + hu + bias[:h])
    r = jax.nn.sigmoid(xr + hr + bias[h:2 * h])
    c = jnp.tanh(xc + (r * h_prev) @ w[:, 2 * h:] + bias[2 * h:])
    new_h = u * c + (1 - u) * h_prev
    return {"Hidden": [new_h],
            "Gate": [jnp.concatenate([u, r, c], axis=-1)],
            "ResetHiddenPrev": [r * h_prev]}
