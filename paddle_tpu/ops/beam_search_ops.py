"""Beam-search decode ops.

≙ reference operators/beam_search_op.* and beam_search_decode_op.* (used by
layers/nn.py beam_search:2706 and the machine-translation book model). The
reference grows LoD beam trees dynamically; the TPU translation keeps the
beam dimension static ([B, K] everywhere) so the whole decode loop compiles
into one lax.scan, and the final tree backtrack is a reverse scan
(`gather_tree`, also the TF/XLA idiom for this op).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op

_NEG_INF = -1e9


@register_op("beam_search", stop_gradient=True)
def _beam_search(ctx, ins, attrs):
    """One beam-growth step (≙ beam_search_op.cc).

    Inputs: PreIds [B, K] (tokens selected last step), PreScores [B, K]
    (accumulated log-probs; initialize beams 1..K-1 to a large negative so
    the first expansion starts from beam 0 only), Scores [B, K, V] per-step
    log-probabilities. attr end_id.

    Finished beams (PreIds == end_id) survive unchanged: their only
    continuation is end_id at the accumulated score.
    Outputs: SelectedIds [B, K], SelectedScores [B, K], ParentIdx [B, K].
    """
    pre_ids = ins["PreIds"][0].astype(jnp.int32)     # [B, K]
    pre_scores = ins["PreScores"][0]                 # [B, K]
    scores = ins["Scores"][0]                        # [B, K, V] log-probs
    end_id = attrs["end_id"]
    B, K, V = scores.shape

    finished = pre_ids == end_id                     # [B, K]
    total = pre_scores[:, :, None] + scores          # [B, K, V]
    # finished beams: only end_id continuation, score frozen
    onehot_end = jnp.arange(V)[None, None, :] == end_id
    frozen = jnp.where(onehot_end, pre_scores[:, :, None], _NEG_INF)
    total = jnp.where(finished[:, :, None], frozen, total)

    flat = total.reshape(B, K * V)
    top_scores, top_idx = jax.lax.top_k(flat, K)     # [B, K]
    parent = top_idx // V
    token = top_idx % V
    return {"SelectedIds": [token.astype(jnp.int64)],
            "SelectedScores": [top_scores],
            "ParentIdx": [parent.astype(jnp.int64)]}


@register_op("gather_tree", stop_gradient=True)
def _gather_tree(ctx, ins, attrs):
    """Backtrack beam parent pointers into full sequences
    (≙ beam_search_decode_op.cc building the LoD beam tree; same semantics
    as XLA/TF gather_tree). Ids/Parents [B, T, K] -> Out [B, T, K] where
    Out[b, :, k] is the k-th final beam's token sequence."""
    ids = ins["Ids"][0].astype(jnp.int32)            # [B, T, K]
    parents = ins["Parents"][0].astype(jnp.int32)    # [B, T, K]
    B, T, K = ids.shape
    ids_t = jnp.moveaxis(ids, 1, 0)                  # [T, B, K]
    par_t = jnp.moveaxis(parents, 1, 0)

    beam = jnp.tile(jnp.arange(K)[None, :], (B, 1))  # beams to follow

    def back(beam, xs):
        step_ids, step_parents = xs                  # [B, K]
        tok = jnp.take_along_axis(step_ids, beam, axis=1)
        prev = jnp.take_along_axis(step_parents, beam, axis=1)
        return prev, tok

    _, toks = jax.lax.scan(back, beam, (ids_t, par_t), reverse=True)
    return {"Out": [jnp.moveaxis(toks, 0, 1).astype(jnp.int64)]}
