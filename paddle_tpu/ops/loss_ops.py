"""Sampled / hierarchical loss ops: NCE and hierarchical sigmoid.

≙ reference operators/nce_op.cc and operators/hsigmoid_op.cc (+
operators/math/matrix_bit_code.h). The rest of the loss family
(rank/margin_rank/hinge/log/cos_sim/bilinear/squared_l2*) lives in
nn_ops.py / reduce_ops.py. Gradients come from the executor's vjp region.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..framework.registry import register_op


def hsigmoid_code_length(num_classes: int) -> int:
    """Max root-to-leaf path length of the complete binary tree used by
    hierarchical_sigmoid (shared by the op lowering and the layer wrapper
    so declared shapes can't drift from produced shapes)."""
    return int(math.ceil(math.log2(num_classes))) + 1


@register_op("nce")
def _nce(ctx, ins, attrs):
    """Noise-contrastive estimation with a uniform negative sampler
    (≙ nce_op.cc with sampler=uniform). Negatives are drawn per step from
    ctx's PRNG; the logit correction log(S/C) makes the objective a
    consistent estimator of softmax CE."""
    x = ins["Input"][0]                     # [N, D]
    label = ins["Label"][0].reshape(-1)     # [N]
    w = ins["Weight"][0]                    # [C, D]
    num_total = attrs["num_total_classes"]
    num_neg = attrs.get("num_neg_samples", 10)
    bias = ins["Bias"][0] if ins.get("Bias") else None

    key = ctx.next_key()
    neg = jax.random.randint(key, (x.shape[0], num_neg), 0, num_total)

    def logit(cls):  # cls: [...] int → [...] logits
        lg = jnp.einsum("nd,n...d->n...", x, w[cls])
        if bias is not None:
            lg = lg + bias.reshape(-1)[cls].reshape(lg.shape)
        return lg

    pos_logit = logit(label)                            # [N]
    neg_logit = logit(neg)                              # [N, num_neg]
    corr = math.log(num_neg / num_total)                # log expected count
    pos_cost = jax.nn.softplus(-(pos_logit - corr))
    neg_cost = jnp.sum(jax.nn.softplus(neg_logit - corr), axis=-1)
    cost = (pos_cost + neg_cost).reshape(-1, 1)
    if ins.get("SampleWeight"):
        cost = cost * ins["SampleWeight"][0].reshape(-1, 1)
    return {"Cost": [cost],
            "SampleLogits": [jnp.concatenate(
                [pos_logit[:, None], neg_logit], axis=1)],
            "SampleLabels": [jnp.concatenate(
                [label[:, None], neg], axis=1)]}


@register_op("hierarchical_sigmoid")
def _hsigmoid(ctx, ins, attrs):
    """SimpleCodeTable semantics of the reference
    (operators/math/matrix_bit_code.h): label's path code is
    label + num_classes in a complete binary tree; bit j (LSB-up) targets
    internal node (code >> (j+1)) - 1, with sigmoid-CE target bit j's value.
    Vectorized over a fixed max path length with masking — static shapes
    for XLA."""
    x = ins["X"][0]                          # [N, D]
    label = ins["Label"][0].reshape(-1)      # [N]
    w = ins["W"][0]                          # [C-1, D]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    num_classes = attrs["num_classes"]
    max_len = hsigmoid_code_length(num_classes)

    code = label + num_classes               # [N]
    js = jnp.arange(max_len)                 # [L]
    node = (code[:, None] >> (js[None, :] + 1)) - 1        # [N, L]
    bit = (code[:, None] >> js[None, :]) & 1               # [N, L]
    valid = node >= 0
    node_c = jnp.where(valid, node, 0)
    logits = jnp.einsum("nd,nld->nl", x, w[node_c])        # [N, L]
    if bias is not None:
        logits = logits + bias.reshape(-1)[node_c]
    ce = jax.nn.softplus(logits) - bit.astype(x.dtype) * logits
    cost = jnp.sum(jnp.where(valid, ce, 0.0), axis=1, keepdims=True)
    return {"Out": [cost], "PreOut": [logits]}
