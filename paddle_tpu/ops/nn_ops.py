"""NN op lowerings: matmul/fc, conv, pool, norms, softmax/losses.

≙ reference operators/{mul,matmul,conv,conv_transpose,pool,batch_norm,
layer_norm,softmax,cross_entropy,softmax_with_cross_entropy,lrn,fc}_op.*
(SURVEY §2.2 NN family). MXU notes: matmuls/convs go through
lax.dot_general/lax.conv_general_dilated so XLA tiles them onto the systolic
array; `use_bf16` attr lets layers request bfloat16 accumulation inputs while
keeping fp32 params (the TPU-native analogue of the reference's fp16 kernels).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import dim_prod, register_op


def _maybe_bf16(x, attrs):
    if attrs.get("use_bf16", False) and x.dtype == jnp.float32:
        from ..core import flags
        if not flags.get_flag("use_bf16_matmul"):
            return x   # global kill-switch (PTPU_USE_BF16_MATMUL=0)
        return x.astype(jnp.bfloat16)
    return x


def _bf16_active(attrs):
    if not attrs.get("use_bf16", False):
        return False
    from ..core import flags
    return bool(flags.get_flag("use_bf16_matmul"))


def _matmul_out_dtype(in_dtype, attrs):
    """Output dtype for a use_bf16 matmul/conv: bfloat16 stays bfloat16.

    Keeping activations in bf16 END TO END (params fp32, fp32 MXU
    accumulation) is the TPU-native mixed-precision recipe: it halves the
    HBM traffic of every downstream elementwise/norm op and removes the
    per-op bf16<->fp32 convert pairs, which profiling showed cost ~30% of
    a ResNet-50 train step. Norm statistics and the loss still compute in
    fp32 (see _batch_norm/_softmax_with_cross_entropy)."""
    if _bf16_active(attrs):
        return jnp.bfloat16
    return in_dtype


@register_op("mul")
def _mul(ctx, ins, attrs):
    """≙ mul_op.cc — the fc matmul core: flattens x to 2-D by x_num_col_dims."""
    x, y = ins["X"][0], ins["Y"][0]
    xd = attrs.get("x_num_col_dims", 1)
    yd = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = jnp.reshape(x, (dim_prod(xs[:xd]), -1))
    y2 = jnp.reshape(y, (dim_prod(ys[:yd]), -1))
    x2, y2 = _maybe_bf16(x2, attrs), _maybe_bf16(y2, attrs)
    out = jnp.dot(x2, y2, preferred_element_type=jnp.float32)
    out = jnp.reshape(out, xs[:xd] + ys[yd:]).astype(
        _matmul_out_dtype(x.dtype, attrs))
    return {"Out": [out]}


@register_op("qmatmul")
def _qmatmul(ctx, ins, attrs):
    """Weight-only quantized fc matmul (quantize_params_pass rewrite of
    `mul`): dequantizes the block-scaled int8/int4 payload per-tile inside
    the kernel — XLA fuses the scale-multiply into the dot's operand read,
    so no f32 copy of the weight ever lands in HBM — then follows the
    `mul` path exactly (same bf16 policy, same accumulation dtype), so
    quantized decode differs from f32 only by the quantization error."""
    from ..parallel.collective import dequantize_blocks_2d
    x, qw, scales = ins["X"][0], ins["QW"][0], ins["Scales"][0]
    y = dequantize_blocks_2d(qw, scales, bits=attrs.get("bits", 8))
    xd = attrs.get("x_num_col_dims", 1)
    xs = x.shape
    x2 = jnp.reshape(x, (dim_prod(xs[:xd]), -1))
    x2, y2 = _maybe_bf16(x2, attrs), _maybe_bf16(y, attrs)
    out = jnp.dot(x2, y2, preferred_element_type=jnp.float32)
    out = jnp.reshape(out, xs[:xd] + y.shape[1:]).astype(
        _matmul_out_dtype(x.dtype, attrs))
    return {"Out": [out]}


@register_op("matmul")
def _matmul(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    if attrs.get("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2)
    if attrs.get("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2)
    x, y = _maybe_bf16(x, attrs), _maybe_bf16(y, attrs)
    out = jnp.matmul(x, y, preferred_element_type=jnp.float32)
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": [out.astype(
        _matmul_out_dtype(ins["X"][0].dtype, attrs))]}


def _conv_dimension_numbers(data_format, ndim):
    if ndim == 4:
        if data_format == "NHWC":
            return ("NHWC", "HWIO", "NHWC")
        return ("NCHW", "OIHW", "NCHW")
    if data_format == "NDHWC":
        return ("NDHWC", "DHWIO", "NDHWC")
    return ("NCDHW", "OIDHW", "NCDHW")


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _conv1x1_mixed(x, w, dn):
    """1x1 stride-1 NHWC conv with a mixed-emitter backward: dgrad runs
    as ONE dot_general (a 1x1 conv IS a matmul; the matmul emitter beats
    the conv emitter 1.33x on it and skips its 64->128 lane padding),
    wgrad stays on the conv emitter (which wins the huge-K skinny GEMM).
    Measured 1.52x on the ISOLATED fwd+bwd unit of the flagship's
    worst-traffic conv shape — but 1.43x SLOWER inside the full train
    step (+30 GB cost-model traffic): the [BHW,C] reshapes materialize
    layout copies of every 1x1 activation and the custom_vjp boundary
    breaks the BN-backward fusions the conv path enjoys. Default OFF
    (flag conv1x1_mixed_vjp); kept as the committed falsification probe
    for PROF_r04's irreducibility claim (tools/probe_dgrad.py --exp mixed_1x1,
    tools/ab_conv1x1.py, PROBE_DGRAD_r05.json)."""
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=[(0, 0), (0, 0)],
        dimension_numbers=dn)


def _conv1x1_mixed_fwd(x, w, dn):
    return _conv1x1_mixed(x, w, dn), (x, w)


def _conv1x1_mixed_bwd(dn, res, dy):
    x, w = res
    ci, co = w.shape[2], w.shape[3]            # HWIO
    dx = jax.lax.dot_general(
        dy.reshape(-1, co), w.reshape(ci, co), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dy.dtype)
    dx = dx.reshape(x.shape)
    _, wgrad = jax.vjp(
        lambda w_: jax.lax.conv_general_dilated(
            x, w_, window_strides=(1, 1), padding=[(0, 0), (0, 0)],
            dimension_numbers=dn), w)
    return dx, wgrad(dy)[0]


_conv1x1_mixed.defvjp(_conv1x1_mixed_fwd, _conv1x1_mixed_bwd)


@register_op("conv2d")
def _conv2d(ctx, ins, attrs):
    """≙ conv_op.cc / conv_cudnn_op.cu.cc. Filter layout is OIHW as in the
    reference; groups>1 supported (depthwise = groups == C_in)."""
    x, w = ins["Input"][0], ins["Filter"][0]
    nd = x.ndim - 2  # spatial rank: 2 for conv2d, 3 for conv3d
    strides = tuple(attrs.get("strides", [1] * nd))
    pads = attrs.get("paddings", [0] * nd)
    dilations = tuple(attrs.get("dilations", [1] * nd))
    groups = attrs.get("groups", 1) or 1
    data_format = attrs.get("data_format", "NCHW")
    dn = _conv_dimension_numbers(data_format, x.ndim)
    if data_format in ("NHWC", "NDHWC"):
        # framework stores filters OI<spatial>; convert to <spatial>IO
        w = jnp.transpose(w, tuple(range(2, 2 + nd)) + (1, 0))
    padding = [(p, p) for p in pads]
    x, w = _maybe_bf16(x, attrs), _maybe_bf16(w, attrs)
    # No preferred_element_type here: a f32-upcast output makes the conv vjp
    # see a f32 cotangent against bf16 operands, which lax.conv rejects. The
    # MXU accumulates bf16 convs in fp32 internally regardless; the explicit
    # astype below restores the program dtype.
    from ..core import flags as _flags
    if (nd == 2 and data_format == "NHWC" and groups == 1
            and tuple(w.shape[:2]) == (1, 1) and strides == (1, 1)
            and all(p == 0 for p in pads) and dilations == (1, 1)
            and _flags.get_flag("conv1x1_mixed_vjp")):
        out = _conv1x1_mixed(x, w, dn)
    else:
        out = jax.lax.conv_general_dilated(
            x, w, window_strides=strides, padding=padding,
            rhs_dilation=dilations, dimension_numbers=dn,
            feature_group_count=groups)
    return {"Output": [out.astype(
        _matmul_out_dtype(ins["Input"][0].dtype, attrs))]}


register_op("conv3d")(_conv2d.__wrapped__ if hasattr(_conv2d, "__wrapped__")
                      else _conv2d)


@register_op("depthwise_conv2d")
def _depthwise_conv2d(ctx, ins, attrs):
    attrs = dict(attrs)
    x = ins["Input"][0]
    c_in = x.shape[1] if attrs.get("data_format", "NCHW") == "NCHW" else x.shape[-1]
    attrs["groups"] = c_in
    return _conv2d(ctx, ins, attrs)


@register_op("conv2d_transpose")
def _conv2d_transpose(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    nd = x.ndim - 2  # spatial rank: 2 for conv2d_transpose, 3 for conv3d_
    strides = tuple(attrs.get("strides", [1] * nd))
    pads = attrs.get("paddings", [0] * nd)
    dilations = tuple(attrs.get("dilations", [1] * nd))
    # filter stored as (C_in, C_out, *spatial) per reference
    # conv_transpose_op; transpose_kernel=True expects the *forward* conv
    # kernel layout, i.e. <spatial>IO with O = C_in of x (the forward conv
    # maps C_out -> C_in). jax applies `padding` to the stride-dilated
    # input, so the reference's deconv padding p becomes kernel_extent-1-p,
    # giving out = (i-1)*s - 2p + kernel_extent as in conv_transpose_op.cc.
    ks = w.shape[2:]
    padding = [(d * (k - 1) - p, d * (k - 1) - p)
               for k, p, d in zip(ks, pads, dilations)]
    dn = (("NCHW", "HWIO", "NCHW") if nd == 2
          else ("NCDHW", "DHWIO", "NCDHW"))
    out = jax.lax.conv_transpose(
        x, jnp.transpose(w, tuple(range(2, 2 + nd)) + (1, 0)),
        strides=strides, padding=padding,
        rhs_dilation=dilations,
        dimension_numbers=dn,
        transpose_kernel=True)
    return {"Output": [out]}


register_op("conv3d_transpose")(_conv2d_transpose)


@register_op("pool2d")
def _pool2d(ctx, ins, attrs):
    """≙ pool_op.cc: max/avg, global_pooling, ceil_mode, exclusive avg.
    Rank-general: serves pool3d too (NCDHW / NDHWC)."""
    x = ins["X"][0]
    nd = x.ndim - 2  # spatial rank
    ptype = attrs.get("pooling_type", "max")
    ksize = list(attrs.get("ksize", [2] * nd))
    strides = list(attrs.get("strides", ksize))
    pads = list(attrs.get("paddings", [0] * nd))
    data_format = attrs.get("data_format", "NCHW")
    channels_last = data_format in ("NHWC", "NDHWC")
    spatial = tuple(range(1, 1 + nd)) if channels_last \
        else tuple(range(2, 2 + nd))
    if attrs.get("global_pooling", False):
        ksize = [x.shape[d] for d in spatial]
        strides = ksize
        pads = [0] * nd
    window = [1] * x.ndim
    stride_full = [1] * x.ndim
    pad_full = [(0, 0)] * x.ndim
    ceil_mode = attrs.get("ceil_mode", False)
    for i, d in enumerate(spatial):
        window[d] = ksize[i]
        stride_full[d] = strides[i]
        hi = pads[i]
        if ceil_mode:
            # extra high padding so the last partial window is included
            span = x.shape[d] + 2 * pads[i] - ksize[i]
            rem = span % strides[i]
            if rem != 0:
                hi += strides[i] - rem
        pad_full[d] = (pads[i], hi)
    if ptype == "max":
        init = -jnp.inf
        out = jax.lax.reduce_window(x, init, jax.lax.max, window,
                                    stride_full, pad_full)
    else:
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, stride_full,
                                  pad_full)
        padded = any(lo > 0 or hi > 0 for lo, hi in pad_full)
        if attrs.get("exclusive", True) and padded:
            ones = jnp.ones_like(x)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                        stride_full, pad_full)
            out = s / cnt
        else:
            out = s / float(np.prod(ksize))
    return {"Out": [out]}


register_op("pool3d")(_pool2d)


def _bn_stats(x, shift, reduce_axes, bshape):
    """Shifted single-pass fp32 moments over `reduce_axes`.

    Statistics always accumulate in fp32 — with bf16 activations the
    variance would otherwise lose most of its bits to cancellation. Both
    reductions are independent so XLA fuses them into one read of x (BN is
    bandwidth-bound and x is the big activation tensor). The shift is the
    running mean, which kills the E[x^2]-E[x]^2 cancellation for data with
    |mean| >> std; early steps, when the running mean still lags, have
    near-zero-mean conv activations anyway."""
    x32 = x.astype(jnp.float32) if x.dtype != jnp.float32 else x
    xs_ = x32 - shift.reshape(bshape)
    m1s = jnp.mean(xs_, axis=reduce_axes)
    m2s = jnp.mean(jnp.square(xs_), axis=reduce_axes)
    mean = m1s + shift
    var = jnp.maximum(m2s - jnp.square(m1s), 0.0)
    return mean, var


def _bn_apply_math(x, scale, bias, shift, reduce_axes, bshape, eps):
    mean, var = _bn_stats(x, shift, reduce_axes, bshape)
    inv = jax.lax.rsqrt(var + eps)
    # ONE per-channel fma in the activation dtype: a/b are precomputed in
    # fp32 ([C]-sized, cheap) so the only activation-sized work stays bf16.
    a32 = inv * scale
    b32 = bias - mean * a32
    y = x * a32.astype(x.dtype).reshape(bshape) \
        + b32.astype(x.dtype).reshape(bshape)
    return y, mean, inv


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _bn_train_apply(reduce_axes, bshape, eps, x, scale, bias, shift):
    """Train-mode BN normalize+affine with a closed-form backward.

    Plain autodiff of the stats path stores the fp32 activation-sized
    intermediate (x32 - shift) as a residual for the variance backward —
    on ResNet-50 bs256 those are 822 MB f32 buffers and the top source of
    HBM traffic (round-3 profile). The closed-form VJP saves only x (bf16,
    already live) plus [C]-sized stats and recomputes xhat inside fused
    backward loops, so fwd+bwd each read the activations exactly once at
    activation width."""
    y, _, _ = _bn_apply_math(x, scale, bias, shift, reduce_axes, bshape, eps)
    return y


def _bn_train_apply_fwd(reduce_axes, bshape, eps, x, scale, bias, shift):
    y, mean, inv = _bn_apply_math(x, scale, bias, shift, reduce_axes, bshape,
                                  eps)
    return y, (x, mean, inv, scale, shift)


def _bn_train_apply_bwd(reduce_axes, bshape, eps, res, dy):
    x, mean, inv, scale, shift = res
    n = float(np.prod([x.shape[a] for a in reduce_axes]))
    # Reductions accumulate in f32; the elementwise operands convert inside
    # the fused reduction loops, so x/dy are each read once at bf16 width.
    x32 = x.astype(jnp.float32)
    dy32 = dy.astype(jnp.float32)
    xc = x32 - mean.reshape(bshape)
    sum_dy = jnp.sum(dy32, axis=reduce_axes)
    sum_dy_xc = jnp.sum(dy32 * xc, axis=reduce_axes)
    dscale = inv * sum_dy_xc
    dbias = sum_dy
    # dx = (scale*inv) * (dy - mean(dy) - xhat * mean(dy*xhat))
    c0 = (scale * inv).reshape(bshape)
    c1 = (sum_dy / n).reshape(bshape)
    c2 = (inv * inv * sum_dy_xc / n).reshape(bshape)
    dx = (c0 * (dy32 - c1 - xc * c2)).astype(x.dtype)
    return (dx, dscale.astype(scale.dtype), dbias.astype(dy32.dtype),
            jnp.zeros_like(shift))


_bn_train_apply.defvjp(_bn_train_apply_fwd, _bn_train_apply_bwd)


@register_op("batch_norm")
def _batch_norm(ctx, ins, attrs):
    """≙ batch_norm_op.cc: train mode uses batch stats and emits updated
    moving stats; test mode uses the running estimates."""
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean, var = ins["Mean"][0], ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    data_layout = attrs.get("data_layout", "NCHW")
    is_test = attrs.get("is_test", False) or ctx.is_test
    axis = 1 if data_layout == "NCHW" else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != axis)
    bshape = tuple(x.shape[i] if i == axis else 1 for i in range(x.ndim))

    if is_test:
        use_mean, use_var = mean, var
        inv = jax.lax.rsqrt(use_var + eps)
        a32 = inv * scale
        b32 = bias - use_mean * a32
        y = x * a32.astype(x.dtype).reshape(bshape) \
            + b32.astype(x.dtype).reshape(bshape)
        return {"Y": [y], "MeanOut": [mean], "VarianceOut": [var],
                "SavedMean": [use_mean], "SavedVariance": [inv]}

    shift_v = jax.lax.stop_gradient(mean)
    y = _bn_train_apply(reduce_axes, bshape, eps, x, scale, bias, shift_v)
    # Stats for the running-average update and the Saved* outputs: computed
    # from stop_gradient(x) so no second differentiable path (and no second
    # set of residuals) exists — HLO-wise these reductions are identical to
    # the ones inside the custom-vjp forward, so XLA CSEs them away.
    use_mean, use_var = _bn_stats(jax.lax.stop_gradient(x), shift_v,
                                  reduce_axes, bshape)
    inv = jax.lax.rsqrt(use_var + eps)
    mean_out = momentum * mean + (1 - momentum) * use_mean
    var_out = momentum * var + (1 - momentum) * use_var
    return {"Y": [y], "MeanOut": [mean_out], "VarianceOut": [var_out],
            "SavedMean": [use_mean], "SavedVariance": [inv]}


@register_op("layer_norm")
def _layer_norm(ctx, ins, attrs):
    """≙ layer_norm_op.cc: normalize over dims >= begin_norm_axis."""
    x = ins["X"][0]
    begin = attrs.get("begin_norm_axis", 1)
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(begin, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    norm_shape = x.shape[begin:]
    if ins.get("Scale"):
        y = y * jnp.reshape(ins["Scale"][0], norm_shape)
    if ins.get("Bias"):
        y = y + jnp.reshape(ins["Bias"][0], norm_shape)
    return {"Y": [y], "Mean": [jnp.reshape(mean, mean.shape[:begin])],
            "Variance": [jnp.reshape(var, var.shape[:begin])]}


@register_op("softmax")
def _softmax(ctx, ins, attrs):
    return {"Out": [jax.nn.softmax(ins["X"][0], axis=-1)]}


@register_op("log_softmax")
def _log_softmax(ctx, ins, attrs):
    return {"Out": [jax.nn.log_softmax(ins["X"][0],
                                       axis=attrs.get("axis", -1))]}


def _float0_zero(x):
    import numpy as _np
    return _np.zeros(x.shape, dtype=jax.dtypes.float0)


@jax.custom_vjp
def _ce_hard(logits, lbl, valid):
    """Hard-label softmax cross entropy with a closed-form backward.

    Plain autodiff stores the fp32 [rows, vocab] log-softmax as a residual
    — on the transformer-LM bench config that is a 1 GB buffer (round-4
    profile: the CE chain is ~20% of the step's HBM traffic). This VJP
    saves only the bf16 logits (already live) + a [rows]-sized fp32 lse
    and recomputes p = exp(logit - lse) inside the fused backward, so the
    vocab-sized work stays at activation width in both directions."""
    loss, _ = _ce_hard_fwd_math(logits, lbl, valid)
    return loss


def _ce_hard_fwd_math(logits, lbl, valid):
    l32 = logits.astype(jnp.float32)
    m = jnp.max(l32, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(l32 - m[..., None]), axis=-1))
    logit_at = jnp.take_along_axis(l32, lbl[..., None], axis=-1)[..., 0]
    nll = lse - logit_at
    loss = jnp.where(valid, nll, 0.0)[..., None]
    return loss, lse


def _ce_hard_fwd(logits, lbl, valid):
    loss, lse = _ce_hard_fwd_math(logits, lbl, valid)
    return loss, (logits, lbl, valid, lse)


def _ce_hard_bwd(res, dl):
    logits, lbl, valid, lse = res
    g = dl[..., 0] * valid
    # p - onehot via an iota compare: fused elementwise, nothing
    # vocab-sized materializes in fp32
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    oh = (jax.lax.broadcasted_iota(lbl.dtype, logits.shape,
                                   logits.ndim - 1) == lbl[..., None])
    dlogits = ((p - oh.astype(jnp.float32))
               * g[..., None]).astype(logits.dtype)
    return dlogits, _float0_zero(lbl), _float0_zero(valid)


_ce_hard.defvjp(_ce_hard_fwd, _ce_hard_bwd)


@register_op("softmax_with_cross_entropy")
def _softmax_with_cross_entropy(ctx, ins, attrs):
    """≙ softmax_with_cross_entropy_op.cc (fused, numerically stable)."""
    logits = ins["Logits"][0]
    label = ins["Label"][0]
    if attrs.get("soft_label", False):
        l32 = logits.astype(jnp.float32) \
            if logits.dtype != jnp.float32 else logits
        logp = jax.nn.log_softmax(l32, axis=-1)
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
        return {"Loss": [loss], "Softmax": [jnp.exp(logp)]}
    lbl = label
    if lbl.ndim == logits.ndim and lbl.shape[-1] == 1:
        lbl = jnp.squeeze(lbl, axis=-1)
    # labels equal to ignore_index (default -100, commonly -1 for
    # padding) contribute zero loss and zero gradient
    ignore = attrs.get("ignore_index", -100)
    valid = (lbl != ignore)
    safe = jnp.where(valid, lbl, 0)
    loss = _ce_hard(logits, safe, valid)
    # Softmax output: a separate differentiable branch (distillation /
    # entropy terms differentiate through it). Unused -> the whole branch
    # is DCE'd, so the custom-vjp loss path stays residual-lean in the
    # common loss-only programs.
    sm = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return {"Loss": [loss], "Softmax": [sm]}


@register_op("cross_entropy")
def _cross_entropy(ctx, ins, attrs):
    """≙ cross_entropy_op.cc over probabilities (not logits)."""
    x = ins["X"][0]
    label = ins["Label"][0]
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(jnp.maximum(x, 1e-20)), axis=-1,
                        keepdims=True)
    else:
        lbl = label
        if lbl.ndim == x.ndim and lbl.shape[-1] == 1:
            lbl = jnp.squeeze(lbl, axis=-1)
        ignore = attrs.get("ignore_index", -100)
        valid = (lbl != ignore)
        safe = jnp.where(valid, lbl, 0)
        p = jnp.take_along_axis(x, safe[..., None], axis=-1)
        loss = jnp.where(valid[..., None],
                         -jnp.log(jnp.maximum(p, 1e-20)), 0.0)
    return {"Y": [loss]}


@register_op("sigmoid_cross_entropy_with_logits")
def _sigmoid_ce(ctx, ins, attrs):
    x = ins["X"][0]
    label = ins["Label"][0]
    # max(x,0) - x*z + log(1+exp(-|x|)) — stable formulation
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return {"Out": [loss]}


@register_op("lrn")
def _lrn(ctx, ins, attrs):
    x = ins["X"][0]  # NCHW
    n = attrs.get("n", 5)
    k = attrs.get("k", 2.0)
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + x.shape[1]] for i in range(n))
    mid = k + alpha * acc
    return {"Out": [x / jnp.power(mid, beta)], "MidOut": [mid]}


@register_op("l2_normalize")
def _l2_normalize(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": [x / norm], "Norm": [norm]}


@register_op("huber_loss")
def _huber_loss(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    delta = attrs.get("delta", 1.0)
    r = y - x
    a = jnp.abs(r)
    loss = jnp.where(a <= delta, 0.5 * jnp.square(r),
                     delta * (a - 0.5 * delta))
    return {"Out": [loss], "Residual": [r]}


@register_op("smooth_l1_loss")
def _smooth_l1(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    diff = x - y
    if ins.get("InsideWeight"):
        diff = diff * ins["InsideWeight"][0]
    a = jnp.abs(diff)
    loss = jnp.where(a < 1.0 / s2, 0.5 * s2 * jnp.square(diff), a - 0.5 / s2)
    if ins.get("OutsideWeight"):
        loss = loss * ins["OutsideWeight"][0]
    return {"Out": [jnp.sum(loss, axis=tuple(range(1, loss.ndim)),
                            keepdims=False)[..., None]],
            "Diff": [diff]}


@register_op("log_loss")
def _log_loss(ctx, ins, attrs):
    p = ins["Predicted"][0]
    y = ins["Labels"][0]
    eps = attrs.get("epsilon", 1e-4)
    loss = -y * jnp.log(p + eps) - (1 - y) * jnp.log(1 - p + eps)
    return {"Loss": [loss]}


@register_op("hinge_loss")
def _hinge_loss(ctx, ins, attrs):
    logits = ins["Logits"][0]
    labels = ins["Labels"][0]
    return {"Loss": [jnp.maximum(0.0, 1.0 - (2 * labels - 1) * logits)]}


@register_op("rank_loss")
def _rank_loss(ctx, ins, attrs):
    label = ins["Label"][0]
    left, right = ins["Left"][0], ins["Right"][0]
    d = left - right
    return {"Out": [jnp.log1p(jnp.exp(d)) - label * d]}


@register_op("margin_rank_loss")
def _margin_rank_loss(ctx, ins, attrs):
    label = ins["Label"][0]
    x1, x2 = ins["X1"][0], ins["X2"][0]
    margin = attrs.get("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": [out], "Activated": [(out > 0).astype(x1.dtype)]}


@register_op("mse_loss")
def _mse_loss(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [jnp.square(x - y)]}


@register_op("bilinear_tensor_product")
def _bilinear_tensor_product(ctx, ins, attrs):
    x, y, w = ins["X"][0], ins["Y"][0], ins["Weight"][0]
    # w: [out, dx, dy]
    out = jnp.einsum("bi,oij,bj->bo", x, w, y)
    if ins.get("Bias"):
        out = out + ins["Bias"][0]
    return {"Out": [out]}


@register_op("bilinear_interp")
def _bilinear_interp(ctx, ins, attrs):
    x = ins["X"][0]  # NCHW
    out_h = attrs["out_h"]
    out_w = attrs["out_w"]
    out = jax.image.resize(x, (x.shape[0], x.shape[1], out_h, out_w),
                           method="bilinear")
    return {"Out": [out]}


@register_op("im2sequence")
def _im2sequence(ctx, ins, attrs):
    # unfold image into patch sequence (≙ im2sequence_op)
    x = ins["X"][0]  # NCHW
    kh, kw = attrs["kernels"]
    sh, sw = attrs.get("strides", [1, 1])
    n, c, h, w = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # [N, C*kh*kw, OH, OW] -> [N*OH*OW, C*kh*kw]
    out = jnp.transpose(patches, (0, 2, 3, 1)).reshape(n * oh * ow, -1)
    return {"Out": [out]}


@register_op("grid_sampler")
def _grid_sampler(ctx, ins, attrs):
    x = ins["X"][0]          # [N, C, H, W]
    grid = ins["Grid"][0]    # [N, H', W', 2] in [-1, 1]
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1) * (w - 1) / 2
    gy = (grid[..., 1] + 1) * (h - 1) / 2
    x0 = jnp.floor(gx).astype(jnp.int32)
    y0 = jnp.floor(gy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1
    wx, wy = gx - x0, gy - y0

    def sample(xi, yi):
        xi = jnp.clip(xi, 0, w - 1)
        yi = jnp.clip(yi, 0, h - 1)
        batch_idx = jnp.arange(n)[:, None, None]
        return x[batch_idx, :, yi, xi]  # [N, H', W', C]

    val = (sample(x0, y0) * ((1 - wx) * (1 - wy))[..., None] +
           sample(x1, y0) * (wx * (1 - wy))[..., None] +
           sample(x0, y1) * ((1 - wx) * wy)[..., None] +
           sample(x1, y1) * (wx * wy)[..., None])
    return {"Output": [jnp.transpose(val, (0, 3, 1, 2))]}


@register_op("spp")
def _spp(ctx, ins, attrs):
    """≙ spp_op.cc (spatial pyramid pooling): pool the [N,C,H,W] input at
    pyramid levels 1x1, 2x2, ... 2^(L-1) grids and concat the flattened
    bins -> [N, C * sum(4^l)]."""
    x = ins["X"][0]
    levels = attrs.get("pyramid_height", 3)
    pool_type = attrs.get("pooling_type", "max")
    n, c, h, w = x.shape
    outs = []
    def bounds(extent, bins):
        # nearly-even sections, never empty: when extent < bins the bins
        # overlap (each still >= 1 element) so the output bin count — and
        # the layer's declared shape — stays C * sum(4^l)
        out = []
        for i in range(bins):
            lo = min(extent - 1, extent * i // bins)
            hi = max(lo + 1, -(-extent * (i + 1) // bins))
            out.append((lo, min(hi, extent)))
        return out

    for lvl in range(levels):
        bins = 2 ** lvl
        hb = bounds(h, bins)
        wb = bounds(w, bins)
        for (h0, h1) in hb:
            for (w0, w1) in wb:
                sl = x[:, :, h0:h1, w0:w1]
                red = (jnp.max if pool_type == "max" else jnp.mean)(
                    sl, axis=(2, 3))
                outs.append(red)
    return {"Out": [jnp.concatenate(outs, axis=1)]}
