"""Random op lowerings.

≙ reference operators/{uniform_random,gaussian_random,random_crop,sampling_id,
dropout}_op.cc. Keys derive from the per-step LowerCtx PRNG (fold_in per op),
so runs are reproducible given the program seed — replacing the reference's
per-op `seed` attr + global generator.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dtypes import convert_dtype
from ..framework.registry import register_effects, register_op


def _rng_effect(op):
    """Dataflow effect rule (framework/dataflow.py): the op draws from the
    per-step PRNG — whose key the manual-mode executor decorrelates across
    dp shards — UNLESS a fixed `seed` attr pins the stream (then every
    shard draws the identical value and nothing diverges)."""
    return {"rng": not op.attrs.get("seed")}


def _register_rng(op_type, rule=_rng_effect):
    register_effects(op_type)(rule)


@register_op("uniform_random", stop_gradient=True)
def _uniform_random(ctx, ins, attrs):
    dtype = convert_dtype(attrs.get("dtype", "float32"))
    shape = attrs["shape"]
    lo = attrs.get("min", -1.0)
    hi = attrs.get("max", 1.0)
    key = (jax.random.PRNGKey(attrs["seed"]) if attrs.get("seed")
           else ctx.next_key())
    return {"Out": [jax.random.uniform(key, shape, dtype=jnp.float32,
                                       minval=lo, maxval=hi).astype(dtype)]}


@register_op("gaussian_random", stop_gradient=True)
def _gaussian_random(ctx, ins, attrs):
    dtype = convert_dtype(attrs.get("dtype", "float32"))
    shape = attrs["shape"]
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    key = (jax.random.PRNGKey(attrs["seed"]) if attrs.get("seed")
           else ctx.next_key())
    return {"Out": [(mean + std * jax.random.normal(key, shape,
                                                    dtype=jnp.float32))
                    .astype(dtype)]}


@register_op("truncated_gaussian_random", stop_gradient=True)
def _truncated_gaussian_random(ctx, ins, attrs):
    dtype = convert_dtype(attrs.get("dtype", "float32"))
    shape = attrs["shape"]
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    key = (jax.random.PRNGKey(attrs["seed"]) if attrs.get("seed")
           else ctx.next_key())
    out = jax.random.truncated_normal(key, -2.0, 2.0, shape,
                                      dtype=jnp.float32)
    return {"Out": [(mean + std * out).astype(dtype)]}


@register_op("dropout")
def _dropout(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs.get("dropout_prob", 0.5)
    is_test = attrs.get("is_test", False) or ctx.is_test
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if is_test:
        # ≙ dropout_op.cc infer path
        if impl == "upscale_in_train":
            return {"Out": [x], "Mask": [jnp.ones_like(x)]}
        return {"Out": [x * (1.0 - p)], "Mask": [jnp.ones_like(x)]}
    key = (jax.random.PRNGKey(attrs["seed"]) if attrs.get("seed")
           else ctx.next_key())
    mask = jax.random.bernoulli(key, 1.0 - p, x.shape).astype(x.dtype)
    if impl == "upscale_in_train":
        out = x * mask / jnp.maximum(1.0 - p, 1e-8)
    else:
        out = x * mask
    return {"Out": [out], "Mask": [mask]}


@register_op("sampling_id", stop_gradient=True)
def _sampling_id(ctx, ins, attrs):
    x = ins["X"][0]  # [batch, n] probabilities
    key = ctx.next_key()
    return {"Out": [jax.random.categorical(key, jnp.log(x + 1e-20), axis=-1)
                    .astype(jnp.int64)]}


@register_op("random_crop", stop_gradient=True)
def _random_crop(ctx, ins, attrs):
    x = ins["X"][0]
    shape = attrs["shape"]  # crop shape for trailing dims
    key = ctx.next_key()
    lead = x.ndim - len(shape)
    starts = []
    for i, s in enumerate(shape):
        limit = x.shape[lead + i] - s
        k = jax.random.fold_in(key, i)
        starts.append(jax.random.randint(k, (), 0, max(limit, 0) + 1))
    start_idx = [0] * lead + [int(0)] * len(shape)
    slices = [jnp.asarray(0)] * lead + starts
    sizes = list(x.shape[:lead]) + list(shape)
    return {"Out": [jax.lax.dynamic_slice(x, slices, sizes)]}


def _bsl_shape(ins, attrs):
    """Resolve the shape of a *_batch_size_like op: copy the batch dim from
    the Input reference (≙ the reference's BatchSizeLikeOp base)."""
    ref = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = \
        ref.shape[attrs.get("input_dim_idx", 0)]
    return shape


@register_op("uniform_random_batch_size_like", stop_gradient=True)
def _uniform_random_bsl(ctx, ins, attrs):
    """≙ uniform_random_batch_size_like_op.cc."""
    dtype = convert_dtype(attrs.get("dtype", "float32"))
    shape = _bsl_shape(ins, attrs)
    key = (jax.random.PRNGKey(attrs["seed"]) if attrs.get("seed")
           else ctx.next_key())
    return {"Out": [jax.random.uniform(
        key, shape, dtype=jnp.float32, minval=attrs.get("min", -1.0),
        maxval=attrs.get("max", 1.0)).astype(dtype)]}


@register_op("gaussian_random_batch_size_like", stop_gradient=True)
def _gaussian_random_bsl(ctx, ins, attrs):
    """≙ gaussian_random_batch_size_like_op.cc."""
    dtype = convert_dtype(attrs.get("dtype", "float32"))
    shape = _bsl_shape(ins, attrs)
    key = (jax.random.PRNGKey(attrs["seed"]) if attrs.get("seed")
           else ctx.next_key())
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * \
        jax.random.normal(key, shape, dtype=jnp.float32)
    return {"Out": [out.astype(dtype)]}


for _t in ("uniform_random", "gaussian_random",
           "truncated_gaussian_random", "sampling_id", "random_crop",
           "uniform_random_batch_size_like",
           "gaussian_random_batch_size_like"):
    _register_rng(_t)

# dropout's inference path is deterministic (mask of ones / (1-p) scale):
# only the training path draws
_register_rng("dropout",
              lambda op: {"rng": not op.attrs.get("seed")
                          and not op.attrs.get("is_test")})
