"""Builtin op lowerings — importing this package registers every op.

≙ the reference's static REGISTER_OPERATOR initializers across
paddle/fluid/operators/ (SURVEY §2.2). Modules self-register via
framework.registry.register_op.
"""

from . import (control_ops, elementwise, metric_ops, nn_ops,  # noqa: F401
               optimizer_ops, random_ops, reduce_ops, sequence_ops,
               tensor_ops)
