"""Metric op lowerings.

≙ reference operators/{accuracy,auc,precision_recall,mean_iou}_op.cc and
edit_distance / chunk_eval from the sequence family.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op


@register_op("accuracy", stop_gradient=True)
def _accuracy(ctx, ins, attrs):
    """≙ accuracy_op.cc: Out=(Indices hit rate), inputs are top-k indices."""
    indices = ins["Indices"][0]  # [N, k]
    label = ins["Label"][0]      # [N, 1]
    if label.ndim == 1:
        label = label[:, None]
    hit = jnp.any(indices == label, axis=1)
    correct = jnp.sum(hit.astype(jnp.float32))
    total = jnp.asarray(indices.shape[0], dtype=jnp.float32)
    return {"Accuracy": [correct / total],
            "Correct": [correct.astype(jnp.int32)],
            "Total": [total.astype(jnp.int32)]}


@register_op("auc", stop_gradient=True)
def _auc(ctx, ins, attrs):
    """Streaming AUC via threshold buckets (≙ auc_op.cc)."""
    preds = ins["Predict"][0]  # [N, 2] probabilities
    label = ins["Label"][0].reshape(-1)
    stat_pos = ins["StatPos"][0]
    stat_neg = ins["StatNeg"][0]
    num_thresholds = attrs.get("num_thresholds", 200)
    pos_prob = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
    bucket = jnp.clip((pos_prob * num_thresholds).astype(jnp.int32),
                      0, num_thresholds)
    is_pos = (label > 0).astype(stat_pos.dtype)
    stat_pos = stat_pos.at[bucket].add(is_pos)
    stat_neg = stat_neg.at[bucket].add(1 - is_pos)
    # integrate: for each threshold t, tp = sum_{b>=t} pos, fp = sum_{b>=t} neg
    tp = jnp.cumsum(stat_pos[::-1])[::-1]
    fp = jnp.cumsum(stat_neg[::-1])[::-1]
    tot_pos = tp[0]
    tot_neg = fp[0]
    # trapezoid over ROC points (sorted by threshold descending)
    tpr = tp / jnp.maximum(tot_pos, 1)
    fpr = fp / jnp.maximum(tot_neg, 1)
    auc = -jnp.trapezoid(tpr, fpr)
    return {"AUC": [auc], "StatPosOut": [stat_pos], "StatNegOut": [stat_neg]}


@register_op("precision_recall", stop_gradient=True)
def _precision_recall(ctx, ins, attrs):
    preds = ins["MaxProbs"][0] if "MaxProbs" in ins else None
    indices = ins["Indices"][0].reshape(-1)
    labels = ins["Labels"][0].reshape(-1)
    cls_num = attrs["class_number"]
    states = ins["StatesInfo"][0] if ins.get("StatesInfo") else \
        jnp.zeros((cls_num, 4))
    tp = jnp.zeros((cls_num,)).at[labels].add(
        (indices == labels).astype(jnp.float32))
    fp = jnp.zeros((cls_num,)).at[indices].add(
        (indices != labels).astype(jnp.float32))
    fn = jnp.zeros((cls_num,)).at[labels].add(
        (indices != labels).astype(jnp.float32))
    states = states + jnp.stack(
        [tp, fp, jnp.zeros((cls_num,)), fn], axis=1)
    tp_t, fp_t, fn_t = states[:, 0], states[:, 1], states[:, 3]
    precision = tp_t / jnp.maximum(tp_t + fp_t, 1e-12)
    recall = tp_t / jnp.maximum(tp_t + fn_t, 1e-12)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-12)
    macro = jnp.stack([jnp.mean(precision), jnp.mean(recall), jnp.mean(f1)])
    micro_p = jnp.sum(tp_t) / jnp.maximum(jnp.sum(tp_t + fp_t), 1e-12)
    micro_r = jnp.sum(tp_t) / jnp.maximum(jnp.sum(tp_t + fn_t), 1e-12)
    micro_f1 = 2 * micro_p * micro_r / jnp.maximum(micro_p + micro_r, 1e-12)
    metrics = jnp.concatenate([macro, jnp.stack([micro_p, micro_r, micro_f1])])
    return {"BatchMetrics": [metrics], "AccumMetrics": [metrics],
            "AccumStatesInfo": [states]}


@register_op("mean_iou", stop_gradient=True)
def _mean_iou(ctx, ins, attrs):
    pred = ins["Predictions"][0].reshape(-1)
    label = ins["Labels"][0].reshape(-1)
    n = attrs["num_classes"]
    idx = label * n + pred
    cm = jnp.zeros((n * n,)).at[idx].add(1.0).reshape(n, n)
    inter = jnp.diag(cm)
    union = jnp.sum(cm, axis=0) + jnp.sum(cm, axis=1) - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1e-12), 0.0)
    mean_iou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1)
    # OutWrong counts each mismatch against BOTH its predicted and its
    # label class (FP + FN, ≙ mean_iou_op.h:95-97); OutWrong + OutCorrect
    # is then exactly the per-class union the IoU divides by
    wrong = jnp.sum(cm, 0) + jnp.sum(cm, 1) - 2 * inter
    return {"OutMeanIou": [mean_iou], "OutWrong": [wrong],
            "OutCorrect": [inter]}
