"""Control-flow and misc framework op lowerings.

≙ reference operators/{compare,is_empty,get_places}_op plus select/where and
the quantization fake ops. Structured control flow (while/cond) lowers to
lax.while_loop/lax.cond via layers/control_flow.py builders — no interpreter
involvement (replacing the reference's sub-block executors in while_op.cc:36,
conditional_block_op.cc).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op


@register_op("where")
def _where(ctx, ins, attrs):
    return {"Out": [jnp.where(ins["Condition"][0], ins["X"][0], ins["Y"][0])]}


@register_op("is_empty", stop_gradient=True)
def _is_empty(ctx, ins, attrs):
    return {"Out": [jnp.asarray(ins["X"][0].size == 0)]}


@register_op("fake_quantize_abs_max")
def _fake_quantize_abs_max(ctx, ins, attrs):
    """≙ fake_quantize_op.cc — quantize-dequantize for QAT."""
    x = ins["X"][0]
    bit_length = attrs.get("bit_length", 8)
    s = jnp.max(jnp.abs(x))
    bnt = (1 << (bit_length - 1)) - 1
    inv_s = bnt / jnp.maximum(s, 1e-12)
    q = jnp.round(x * inv_s) / inv_s
    # straight-through estimator
    out = x + jax.lax.stop_gradient(q - x)
    return {"Out": [out], "OutScale": [s]}


@register_op("fake_dequantize_max_abs")
def _fake_dequantize_max_abs(ctx, ins, attrs):
    x = ins["X"][0]
    scale = ins["Scale"][0]
    bnt = (1 << (attrs.get("bit_length", 8) - 1)) - 1
    return {"Out": [x.astype(jnp.float32) * scale / bnt]}


@register_op("fake_quantize_moving_average_abs_max")
def _fake_quantize_moving_avg(ctx, ins, attrs):
    x = ins["X"][0]
    state = ins["InScale"][0]
    bit_length = attrs.get("bit_length", 8)
    rate = attrs.get("moving_rate", 0.9)
    cur = jnp.max(jnp.abs(x))
    s = rate * state + (1 - rate) * cur
    bnt = (1 << (bit_length - 1)) - 1
    inv_s = bnt / jnp.maximum(s, 1e-12)
    q = jnp.round(x * inv_s) / inv_s
    out = x + jax.lax.stop_gradient(q - x)
    return {"Out": [out], "OutScale": [s]}


# ---- structured control flow over sub-blocks ------------------------------
# Sub-blocks are real program blocks (≙ the BLOCK attr type in the reference
# proto, framework.proto:35); lowering runs their plan inside lax control-flow
# primitives instead of a sub-Executor (reference while_op.cc:36,
# conditional_block_op.cc, recurrent_op.cc:222).

def _sub_block_plan(ctx, attrs, key="sub_block"):
    from ..framework.lowering import build_plan
    program = ctx.extras.get("program")
    if program is None:
        raise RuntimeError(
            "control-flow op needs LowerCtx.extras['program'] (set by the "
            "executor); direct op invocation cannot resolve sub-blocks")
    block = program.blocks[attrs[key]]
    return block, build_plan(block)


@register_op("while")
def _while(ctx, ins, attrs):
    """≙ while_op.cc:36. Forward-only on TPU: lax.while_loop is not
    reverse-differentiable — use StaticRNN/DynamicRNN (lax.scan) for
    differentiable recurrences."""
    from ..framework.lowering import run_plan
    block, plan = _sub_block_plan(ctx, attrs)
    carry_names = list(attrs["carry_names"])
    capture_names = list(attrs["capture_names"])
    cond_name = attrs["cond_name"]
    cond_idx = carry_names.index(cond_name)
    captures = dict(zip(capture_names, ins.get("Captures", [])))

    def cond_fn(carry):
        return jnp.reshape(carry[cond_idx], ()).astype(bool)

    def body_fn(carry):
        env = dict(captures)
        env.update(zip(carry_names, carry))
        run_plan(plan, env, block, ctx)
        return tuple(env[n] for n in carry_names)

    out = jax.lax.while_loop(cond_fn, body_fn, tuple(ins["Carry"]))
    return {"Out": list(out)}


@register_op("static_rnn")
def _static_rnn(ctx, ins, attrs):
    """≙ recurrent_op.cc:222 (StaticRNN) — lax.scan over the time dim.
    Fully differentiable; XLA unrolls/fuses the step body.

    With `seq_lens` provided (DynamicRNN), memories freeze and outputs
    zero-mask past each sequence's length (≙ shrink_rnn_memory +
    lod_rank_table machinery, reference layers/control_flow.py:741-1148)."""
    from ..framework.lowering import run_plan
    block, plan = _sub_block_plan(ctx, attrs)
    step_in_names = list(attrs["step_input_names"])
    pre_names = list(attrs["pre_mem_names"])
    new_names = list(attrs["new_mem_names"])
    out_names = list(attrs["step_output_names"])
    capture_names = list(attrs["capture_names"])
    reverse = attrs.get("is_reverse", False)
    captures = dict(zip(capture_names, ins.get("Captures", [])))
    init_mems = tuple(ins.get("InitMems", []))
    step_inputs = [jnp.swapaxes(x, 0, 1) for x in ins["StepInputs"]]
    t = step_inputs[0].shape[0]
    seq_lens = ins.get("SeqLens", [None])[0]
    if reverse:
        step_inputs = [jnp.flip(x, axis=0) for x in step_inputs]

    def body(carry, xt_and_t):
        xts, tpos = xt_and_t
        env = dict(captures)
        env.update(zip(pre_names, carry))
        env.update(zip(step_in_names, xts))
        run_plan(plan, env, block, ctx)
        new_carry = tuple(env[n] for n in new_names)
        if seq_lens is not None:
            pos = (t - 1 - tpos) if reverse else tpos
            valid = pos < seq_lens  # [B]
            def keep(new, old):
                v = valid.reshape((-1,) + (1,) * (new.ndim - 1))
                return jnp.where(v, new, old)
            new_carry = tuple(keep(n, o) for n, o in zip(new_carry, carry))
        outs = tuple(env[n] for n in out_names)
        if seq_lens is not None:
            pos = (t - 1 - tpos) if reverse else tpos
            valid = pos < seq_lens
            outs = tuple(o * valid.reshape(
                (-1,) + (1,) * (o.ndim - 1)).astype(o.dtype) for o in outs)
        return new_carry, outs

    final, ys = jax.lax.scan(body, init_mems,
                             (tuple(step_inputs), jnp.arange(t)))
    ys = [jnp.swapaxes(y, 0, 1) for y in ys]
    if reverse:
        ys = [jnp.flip(y, axis=1) for y in ys]
    return {"Out": ys, "FinalMems": list(final)}


@register_op("cond_block")
def _cond_block(ctx, ins, attrs):
    """Batched IfElse (≙ conditional_block_op.cc + layers IfElse:1412).
    TPU-first translation: the reference gathers the true/false subsets of
    the batch and runs each branch on its subset (dynamic shapes); here BOTH
    branches run on the full batch and outputs merge by jnp.where mask —
    static shapes, XLA-friendly, differentiable."""
    from ..framework.lowering import run_plan
    cond = ins["Cond"][0]
    t_block, t_plan = _sub_block_plan(ctx, attrs, "true_block")
    f_block, f_plan = _sub_block_plan(ctx, attrs, "false_block")
    captures = dict(zip(attrs["capture_names"], ins.get("Captures", [])))
    t_names = list(attrs["true_out_names"])
    f_names = list(attrs["false_out_names"])

    env_t = dict(captures)
    run_plan(t_plan, env_t, t_block, ctx)
    env_f = dict(captures)
    run_plan(f_plan, env_f, f_block, ctx)
    outs = []
    for tn, fn in zip(t_names, f_names):
        tv, fv = env_t[tn], env_f[fn]
        c = cond
        if c.ndim < tv.ndim:
            c = c.reshape(c.shape + (1,) * (tv.ndim - c.ndim))
        elif c.ndim > tv.ndim:
            # [B, 1] cond vs rank-1 [B] branch output: drop trailing
            # singleton dims so where() broadcasts per-row, not [B, B]
            while c.ndim > tv.ndim and c.shape[-1] == 1:
                c = c.reshape(c.shape[:-1])
        outs.append(jnp.where(c, tv, fv))
    return {"Out": outs}


@register_op("lazy_cond")
def _lazy_cond(ctx, ins, attrs):
    """Scalar-predicate conditional via lax.cond — only ONE branch executes
    (≙ the functional `layers.cond`). Differentiable."""
    from ..framework.lowering import run_plan
    pred = jnp.reshape(ins["Cond"][0], ()).astype(bool)
    t_block, t_plan = _sub_block_plan(ctx, attrs, "true_block")
    f_block, f_plan = _sub_block_plan(ctx, attrs, "false_block")
    captures = tuple(ins.get("Captures", []))
    capture_names = list(attrs["capture_names"])
    t_names = list(attrs["true_out_names"])
    f_names = list(attrs["false_out_names"])

    def t_fn(caps):
        env = dict(zip(capture_names, caps))
        run_plan(t_plan, env, t_block, ctx)
        return tuple(env[n] for n in t_names)

    def f_fn(caps):
        env = dict(zip(capture_names, caps))
        run_plan(f_plan, env, f_block, ctx)
        return tuple(env[n] for n in f_names)

    outs = jax.lax.cond(pred, t_fn, f_fn, captures)
    return {"Out": list(outs)}


@register_op("switch_case")
def _switch_case(ctx, ins, attrs):
    """≙ layers.Switch (reference control_flow.py:1286): first case whose
    scalar condition holds wins; the default block runs otherwise. All case
    blocks execute (they are tiny — lr schedules); selection is a chain of
    jnp.where."""
    from ..framework.lowering import run_plan
    conds = ins["Conds"]  # scalar bools, one per case
    captures = dict(zip(attrs["capture_names"], ins.get("Captures", [])))
    case_blocks = attrs["case_blocks"]
    case_out_names = attrs["case_out_names"]

    vals = []
    for bidx, out_name in zip(case_blocks, case_out_names):
        block, plan = _sub_block_plan(ctx, {"sub_block": bidx})
        env = dict(captures)
        run_plan(plan, env, block, ctx)
        vals.append(env[out_name])

    # default = last entry when len(case_blocks) == len(conds) + 1; with no
    # default block the target keeps its pre-switch value (reference Switch
    # semantics: the assigned var is simply left untouched)
    if len(vals) > len(conds):
        result = vals[-1]
    elif ins.get("Prev"):
        result = ins["Prev"][0]
    else:
        result = jnp.zeros_like(vals[0])
    for c, v in zip(reversed(conds), reversed(vals[:len(conds)])):
        pred = jnp.reshape(c, ()).astype(bool)
        result = jnp.where(pred, v, result)
    return {"Out": [result]}
