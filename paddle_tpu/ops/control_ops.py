"""Control-flow and misc framework op lowerings.

≙ reference operators/{compare,is_empty,get_places}_op plus select/where and
the quantization fake ops. Structured control flow (while/cond) lowers to
lax.while_loop/lax.cond via layers/control_flow.py builders — no interpreter
involvement (replacing the reference's sub-block executors in while_op.cc:36,
conditional_block_op.cc).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op


@register_op("where")
def _where(ctx, ins, attrs):
    return {"Out": [jnp.where(ins["Condition"][0], ins["X"][0], ins["Y"][0])]}


@register_op("is_empty", stop_gradient=True)
def _is_empty(ctx, ins, attrs):
    return {"Out": [jnp.asarray(ins["X"][0].size == 0)]}


@register_op("fake_quantize_abs_max")
def _fake_quantize_abs_max(ctx, ins, attrs):
    """≙ fake_quantize_op.cc — quantize-dequantize for QAT."""
    x = ins["X"][0]
    bit_length = attrs.get("bit_length", 8)
    s = jnp.max(jnp.abs(x))
    bnt = (1 << (bit_length - 1)) - 1
    inv_s = bnt / jnp.maximum(s, 1e-12)
    q = jnp.round(x * inv_s) / inv_s
    # straight-through estimator
    out = x + jax.lax.stop_gradient(q - x)
    return {"Out": [out], "OutScale": [s]}


@register_op("fake_dequantize_max_abs")
def _fake_dequantize_max_abs(ctx, ins, attrs):
    x = ins["X"][0]
    scale = ins["Scale"][0]
    bnt = (1 << (attrs.get("bit_length", 8) - 1)) - 1
    return {"Out": [x.astype(jnp.float32) * scale / bnt]}


@register_op("fake_quantize_moving_average_abs_max")
def _fake_quantize_moving_avg(ctx, ins, attrs):
    x = ins["X"][0]
    state = ins["InScale"][0]
    bit_length = attrs.get("bit_length", 8)
    rate = attrs.get("moving_rate", 0.9)
    cur = jnp.max(jnp.abs(x))
    s = rate * state + (1 - rate) * cur
    bnt = (1 << (bit_length - 1)) - 1
    inv_s = bnt / jnp.maximum(s, 1e-12)
    q = jnp.round(x * inv_s) / inv_s
    out = x + jax.lax.stop_gradient(q - x)
    return {"Out": [out], "OutScale": [s]}
