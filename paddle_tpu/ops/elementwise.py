"""Elementwise / scale / compare / logical op lowerings.

≙ reference paddle/fluid/operators/elementwise_*.cc, scale_op.cc, clip_op.cc,
compare_op.cc, logical_op.cc, activation_op.cc. Each lowering emits jax ops;
XLA fuses chains of these into single kernels (replacing the reference's
hand-fused CUDA elementwise kernels).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op


def _broadcast_y(x, y, axis):
    """Reference elementwise broadcast semantics: align y's dims to x starting
    at `axis` (reference operators/elementwise_op_function.h)."""
    if jnp.ndim(y) == jnp.ndim(x):
        return y
    if axis is None or axis == -1:
        return y  # trailing-aligned: numpy broadcasting handles it
    # leading-aligned at `axis`: pad y with trailing singleton dims
    pad = jnp.ndim(x) - axis - jnp.ndim(y)
    return jnp.reshape(y, y.shape + (1,) * pad)


def _binary(fn):
    def lower(ctx, ins, attrs):
        x, y = ins["X"][0], ins["Y"][0]
        if attrs.get("use_bf16", False) and x.dtype != y.dtype and \
                str(x.dtype) == "bfloat16":
            # bias/residual add on the bf16 activation path: cast the fp32
            # side down instead of letting jnp promotion lift the whole
            # activation tensor back to fp32 (which would undo the bf16
            # pipeline right after every matmul/conv bias)
            y = y.astype(x.dtype)
        y = _broadcast_y(x, y, attrs.get("axis", -1))
        return {"Out": [fn(x, y)]}
    return lower


register_op("elementwise_add")(_binary(jnp.add))
register_op("elementwise_sub")(_binary(jnp.subtract))
register_op("elementwise_mul")(_binary(jnp.multiply))
register_op("elementwise_div")(_binary(jnp.divide))
register_op("elementwise_max")(_binary(jnp.maximum))
register_op("elementwise_min")(_binary(jnp.minimum))
register_op("elementwise_pow")(_binary(jnp.power))
register_op("elementwise_mod")(_binary(jnp.mod))
register_op("elementwise_floordiv")(_binary(jnp.floor_divide))

register_op("less_than", stop_gradient=True)(_binary(jnp.less))
register_op("less_equal", stop_gradient=True)(_binary(jnp.less_equal))
register_op("greater_than", stop_gradient=True)(_binary(jnp.greater))
register_op("greater_equal", stop_gradient=True)(_binary(jnp.greater_equal))
register_op("equal", stop_gradient=True)(_binary(jnp.equal))
register_op("not_equal", stop_gradient=True)(_binary(jnp.not_equal))

register_op("logical_and", stop_gradient=True)(_binary(jnp.logical_and))
register_op("logical_or", stop_gradient=True)(_binary(jnp.logical_or))
register_op("logical_xor", stop_gradient=True)(_binary(jnp.logical_xor))


@register_op("logical_not", stop_gradient=True)
def _logical_not(ctx, ins, attrs):
    return {"Out": [jnp.logical_not(ins["X"][0])]}


@register_op("scale")
def _scale(ctx, ins, attrs):
    # ≙ scale_op.cc: out = scale * (x + bias) or scale*x + bias
    x = ins["X"][0]
    scale = attrs.get("scale", 1.0)
    bias = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        return {"Out": [x * scale + bias]}
    return {"Out": [(x + bias) * scale]}


@register_op("clip")
def _clip(ctx, ins, attrs):
    return {"Out": [jnp.clip(ins["X"][0], attrs["min"], attrs["max"])]}


@register_op("clip_by_norm")
def _clip_by_norm(ctx, ins, attrs):
    x = ins["X"][0]
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": [x * scale]}


@register_op("sign")
def _sign(ctx, ins, attrs):
    return {"Out": [jnp.sign(ins["X"][0])]}


@register_op("isfinite", stop_gradient=True)
def _isfinite(ctx, ins, attrs):
    # ≙ isfinite_op: reduces to a single bool over all inputs
    vals = [jnp.all(jnp.isfinite(x)) for x in ins["X"]]
    out = vals[0]
    for v in vals[1:]:
        out = jnp.logical_and(out, v)
    return {"Out": [out]}


# --- activations (≙ activation_op.cc ~20 kernels) ---

def _unary(fn):
    def lower(ctx, ins, attrs):
        return {"Out": [fn(ins["X"][0])]}
    return lower


register_op("sigmoid")(_unary(jax.nn.sigmoid))
register_op("logsigmoid")(_unary(jax.nn.log_sigmoid))
register_op("exp")(_unary(jnp.exp))
register_op("tanh")(_unary(jnp.tanh))
register_op("tanh_shrink")(_unary(lambda x: x - jnp.tanh(x)))
register_op("sqrt")(_unary(jnp.sqrt))
register_op("rsqrt")(_unary(jax.lax.rsqrt))
register_op("abs")(_unary(jnp.abs))
register_op("ceil")(_unary(jnp.ceil))
register_op("floor")(_unary(jnp.floor))
register_op("cos")(_unary(jnp.cos))
register_op("sin")(_unary(jnp.sin))
register_op("round")(_unary(jnp.round))
register_op("reciprocal")(_unary(jnp.reciprocal))
register_op("log")(_unary(jnp.log))
register_op("square")(_unary(jnp.square))
register_op("relu")(_unary(jax.nn.relu))
register_op("relu6")(_unary(jax.nn.relu6))
register_op("softplus")(_unary(jax.nn.softplus))
register_op("softsign")(_unary(lambda x: x / (1 + jnp.abs(x))))
register_op("gelu")(_unary(jax.nn.gelu))
register_op("silu")(_unary(jax.nn.silu))


@register_op("leaky_relu")
def _leaky_relu(ctx, ins, attrs):
    alpha = attrs.get("alpha", 0.02)
    x = ins["X"][0]
    return {"Out": [jnp.where(x >= 0, x, alpha * x)]}


@register_op("elu")
def _elu(ctx, ins, attrs):
    return {"Out": [jax.nn.elu(ins["X"][0], alpha=attrs.get("alpha", 1.0))]}


@register_op("pow")
def _pow(ctx, ins, attrs):
    return {"Out": [jnp.power(ins["X"][0], attrs.get("factor", 1.0))]}


@register_op("hard_sigmoid")
def _hard_sigmoid(ctx, ins, attrs):
    slope = attrs.get("slope", 0.2)
    offset = attrs.get("offset", 0.5)
    return {"Out": [jnp.clip(ins["X"][0] * slope + offset, 0.0, 1.0)]}


@register_op("hard_shrink")
def _hard_shrink(ctx, ins, attrs):
    t = attrs.get("threshold", 0.5)
    x = ins["X"][0]
    return {"Out": [jnp.where(jnp.abs(x) > t, x, 0.0)]}


@register_op("soft_shrink")
def _soft_shrink(ctx, ins, attrs):
    lam = attrs.get("lambda", 0.5)
    x = ins["X"][0]
    return {"Out": [jnp.where(x > lam, x - lam, jnp.where(x < -lam, x + lam, 0.0))]}


@register_op("thresholded_relu")
def _thresholded_relu(ctx, ins, attrs):
    t = attrs.get("threshold", 1.0)
    x = ins["X"][0]
    return {"Out": [jnp.where(x > t, x, 0.0)]}


@register_op("swish")
def _swish(ctx, ins, attrs):
    beta = attrs.get("beta", 1.0)
    x = ins["X"][0]
    return {"Out": [x * jax.nn.sigmoid(beta * x)]}


@register_op("brelu")
def _brelu(ctx, ins, attrs):
    t_min = attrs.get("t_min", 0.0)
    t_max = attrs.get("t_max", 24.0)
    return {"Out": [jnp.clip(ins["X"][0], t_min, t_max)]}


@register_op("prelu")
def _prelu(ctx, ins, attrs):
    x = ins["X"][0]
    alpha = ins["Alpha"][0]
    mode = attrs.get("mode", "all")
    if mode == "channel":
        alpha = jnp.reshape(alpha, (1, -1) + (1,) * (x.ndim - 2))
    return {"Out": [jnp.where(x >= 0, x, alpha * x)]}


@register_op("maxout")
def _maxout(ctx, ins, attrs):
    # ≙ maxout_op: NCHW, channel groups
    x = ins["X"][0]
    groups = attrs["groups"]
    n, c, h, w = x.shape
    return {"Out": [jnp.max(jnp.reshape(x, (n, c // groups, groups, h, w)),
                            axis=2)]}
