"""Reduction / argmax / topk / sum op lowerings.

≙ reference operators/reduce_op.cc (sum/mean/max/min/prod), mean_op.cc,
sum_op.cc (multi-input add_n incl. SelectedRows mixing), arg_max/min, top_k,
argsort, cos_sim.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op


def _reduce(fn):
    def lower(ctx, ins, attrs):
        x = ins["X"][0]
        dim = attrs.get("dim")
        keep = attrs.get("keep_dim", False)
        if attrs.get("reduce_all", False) or dim is None:
            axis = None
        else:
            axis = tuple(dim) if isinstance(dim, (list, tuple)) else (dim,)
        return {"Out": [fn(x, axis=axis, keepdims=keep)]}
    return lower


register_op("reduce_sum")(_reduce(jnp.sum))
register_op("reduce_mean")(_reduce(jnp.mean))
register_op("reduce_max")(_reduce(jnp.max))
register_op("reduce_min")(_reduce(jnp.min))
register_op("reduce_prod")(_reduce(jnp.prod))


@register_op("mean")
def _mean(ctx, ins, attrs):
    return {"Out": [jnp.mean(ins["X"][0])]}


@register_op("sum")
def _sum(ctx, ins, attrs):
    # ≙ sum_op.cc add_n over N inputs
    out = ins["X"][0]
    for x in ins["X"][1:]:
        out = out + x
    return {"Out": [out]}


@register_op("arg_max", stop_gradient=True)
def _arg_max(ctx, ins, attrs):
    return {"Out": [jnp.argmax(ins["X"][0], axis=attrs.get("axis", -1))
                    .astype(jnp.int64)]}


@register_op("arg_min", stop_gradient=True)
def _arg_min(ctx, ins, attrs):
    return {"Out": [jnp.argmin(ins["X"][0], axis=attrs.get("axis", -1))
                    .astype(jnp.int64)]}


@register_op("top_k", stop_gradient=True)
def _top_k(ctx, ins, attrs):
    vals, idx = jax.lax.top_k(ins["X"][0], attrs["k"])
    return {"Out": [vals], "Indices": [idx.astype(jnp.int64)]}


@register_op("argsort", stop_gradient=True)
def _argsort(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    idx = jnp.argsort(x, axis=axis)
    return {"Out": [jnp.sort(x, axis=axis)], "Indices": [idx.astype(jnp.int64)]}


@register_op("cos_sim")
def _cos_sim(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / jnp.maximum(xn * yn, 1e-12)
    return {"Out": [out], "XNorm": [xn], "YNorm": [yn]}


@register_op("squared_l2_norm")
def _squared_l2_norm(ctx, ins, attrs):
    return {"Out": [jnp.sum(jnp.square(ins["X"][0]))[None]]}


@register_op("squared_l2_distance")
def _squared_l2_distance(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    sub = x - y
    return {"Out": [jnp.sum(jnp.square(sub), axis=-1, keepdims=True)],
            "sub_result": [sub]}


@register_op("norm")
def _norm(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": [x / norm], "Norm": [norm]}
