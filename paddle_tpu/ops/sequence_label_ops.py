"""Sequence-labeling ops: CTC loss/decode, linear-chain CRF, chunk eval.

≙ reference operators/warpctc_op.* (CTC loss via libwarpctc),
ctc_align_op.*, linear_chain_crf_op.*, crf_decoding_op.*, chunk_eval_op.*
(SURVEY.md §2.2 "Sequence/LoD" family). The reference represents ragged
batches as LoDTensors and calls hand-written CPU/CUDA DP kernels; here the
batch is dense-padded with explicit length vectors (the framework's LoD
translation) and the dynamic programs are lax.scan recurrences, so XLA
fuses them and jax autodiff provides exact gradients (the reference ships
hand-derived backward kernels).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op

_NEG_INF = -1e30


def _logsumexp2(a, b):
    m = jnp.maximum(a, b)
    dead = m <= _NEG_INF / 2
    m_safe = jnp.where(dead, 0.0, m)
    s = jnp.exp(a - m_safe) + jnp.exp(b - m_safe)
    # double-where: the dead branch must never see log(0), whose grad is
    # inf*0=NaN even though `where` discards the value
    out = m_safe + jnp.log(jnp.where(dead, 1.0, s))
    return jnp.where(dead, _NEG_INF, out)


def _logsumexp3(a, b, c):
    return _logsumexp2(_logsumexp2(a, b), c)


# ---------------------------------------------------------------------------
# CTC
# ---------------------------------------------------------------------------

@register_op("warpctc")
def _warpctc(ctx, ins, attrs):
    """CTC loss (≙ warpctc_op.cc, which wraps libwarpctc).

    Inputs: Logits [B, T, C] unnormalized; Label [B, L] int; LogitsLength [B];
    LabelLength [B]. attr blank (default 0), norm_by_times.
    Output Loss [B, 1] = -log p(label | logits). The log-space forward
    algorithm runs as a lax.scan over time; jax.grad of it reproduces the
    soft-alignment gradient warpctc computes by hand.
    """
    logits = ins["Logits"][0]                    # [B, T, C]
    label = ins["Label"][0].astype(jnp.int32)    # [B, L]
    logit_len = ins["LogitsLength"][0].reshape(-1).astype(jnp.int32)
    label_len = ins["LabelLength"][0].reshape(-1).astype(jnp.int32)
    blank = attrs.get("blank", 0)

    B, T, C = logits.shape
    L = label.shape[1]
    S = 2 * L + 1

    logp = jax.nn.log_softmax(logits, axis=-1)   # [B, T, C]

    # extended label sequence: blank, l1, blank, l2, ..., lL, blank
    ext = jnp.full((B, S), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(label)             # [B, S]
    s_idx = jnp.arange(S)
    # skip transition allowed into odd (label) states differing from s-2
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=blank)[:, :S]
    allow_skip = (s_idx[None, :] >= 2) & (ext != blank) & (ext != ext_m2)

    def emit(t):
        return jnp.take_along_axis(logp[:, t, :], ext, axis=1)  # [B, S]

    alpha0 = jnp.full((B, S), _NEG_INF)
    e0 = emit(0)
    alpha0 = alpha0.at[:, 0].set(e0[:, 0])
    if S > 1:
        alpha0 = alpha0.at[:, 1].set(jnp.where(label_len > 0, e0[:, 1],
                                               _NEG_INF))

    def step(alpha, t):
        a1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                     constant_values=_NEG_INF)[:, :S]
        a2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                     constant_values=_NEG_INF)[:, :S]
        a2 = jnp.where(allow_skip, a2, _NEG_INF)
        new = _logsumexp3(alpha, a1, a2) + emit(t)
        active = (t < logit_len)[:, None]
        return jnp.where(active, new, alpha), None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))

    s_end = 2 * label_len                        # index of final blank
    last_blank = jnp.take_along_axis(alpha, s_end[:, None], axis=1)[:, 0]
    lbl_idx = jnp.maximum(s_end - 1, 0)[:, None]
    last_label = jnp.where(
        label_len > 0,
        jnp.take_along_axis(alpha, lbl_idx, axis=1)[:, 0], _NEG_INF)
    loglik = _logsumexp2(last_blank, last_label)
    loss = -loglik
    if attrs.get("norm_by_times"):
        loss = loss / jnp.maximum(logit_len.astype(loss.dtype), 1)
    return {"Loss": [loss.reshape(-1, 1)]}


@register_op("ctc_align", stop_gradient=True)
def _ctc_align(ctx, ins, attrs):
    """≙ ctc_align_op.cc: merge repeated tokens then drop blanks.

    Input [B, T] int + InputLength [B]; outputs Output [B, T] left-packed and
    padded with `padding_value`, and OutputLength [B].
    """
    x = ins["Input"][0].astype(jnp.int32)        # [B, T]
    xlen = ins["InputLength"][0].reshape(-1).astype(jnp.int32)
    blank = attrs.get("blank", 0)
    pad_val = attrs.get("padding_value", 0)
    B, T = x.shape

    t_idx = jnp.arange(T)[None, :]
    valid = t_idx < xlen[:, None]
    prev = jnp.pad(x, ((0, 0), (1, 0)), constant_values=-1)[:, :T]
    keep = valid & (x != blank) & (x != prev)
    # left-pack kept tokens: target position = cumsum(keep) - 1
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    out_len = jnp.where(keep, pos + 1, 0).max(axis=1)
    # scatter each kept token to its packed slot (dump dropped ones to T)
    scatter_pos = jnp.where(keep, pos, T)
    b_idx = jnp.arange(B)[:, None].repeat(T, 1)
    out = jnp.zeros((B, T + 1), dtype=x.dtype).at[
        b_idx.reshape(-1), scatter_pos.reshape(-1)].set(x.reshape(-1))[:, :T]
    out = jnp.where(jnp.arange(T)[None, :] < out_len[:, None], out, pad_val)
    return {"Output": [out.astype(ins["Input"][0].dtype)],
            "OutputLength": [out_len.astype(jnp.int64).reshape(-1, 1)]}


# ---------------------------------------------------------------------------
# Linear-chain CRF
# ---------------------------------------------------------------------------

def _crf_unpack(transition):
    """Reference layout (linear_chain_crf_op.h): row 0 = start weights,
    row 1 = end weights, rows 2..D+1 = transition matrix [D, D]."""
    return transition[0], transition[1], transition[2:]


@register_op("linear_chain_crf")
def _linear_chain_crf(ctx, ins, attrs):
    """≙ linear_chain_crf_op.cc. Emission [B, T, D], Transition [D+2, D],
    Label [B, T], Length [B]. Output LogLikelihood [B, 1] = logZ - score
    (the negative log-likelihood the reference minimizes directly).
    """
    emission = ins["Emission"][0]                # [B, T, D]
    transition = ins["Transition"][0]            # [D+2, D]
    label = ins["Label"][0]
    if label.ndim == 3:
        label = label[..., 0]
    label = label.astype(jnp.int32)              # [B, T]
    length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    B, T, D = emission.shape
    start_w, end_w, trans = _crf_unpack(transition)

    # --- partition function: forward algorithm over time -----------------
    alpha0 = start_w[None, :] + emission[:, 0, :]          # [B, D]

    def fwd(alpha, t):
        # alpha[b, i] + trans[i, j] -> logsumexp over i, + emission[t, j]
        scores = alpha[:, :, None] + trans[None, :, :]     # [B, D, D]
        new = jax.nn.logsumexp(scores, axis=1) + emission[:, t, :]
        active = (t < length)[:, None]
        return jnp.where(active, new, alpha), None

    alpha, _ = jax.lax.scan(fwd, alpha0, jnp.arange(1, T))
    logz = jax.nn.logsumexp(alpha + end_w[None, :], axis=1)  # [B]

    # --- gold path score -------------------------------------------------
    t_idx = jnp.arange(T)[None, :]
    in_seq = t_idx < length[:, None]                       # [B, T]
    emit_scores = jnp.take_along_axis(
        emission, label[:, :, None], axis=2)[:, :, 0]      # [B, T]
    emit_sum = jnp.sum(jnp.where(in_seq, emit_scores, 0.0), axis=1)
    prev_lbl = label[:, :-1]
    next_lbl = label[:, 1:]
    trans_scores = trans[prev_lbl, next_lbl]               # [B, T-1]
    trans_mask = (t_idx[:, 1:] < length[:, None])
    trans_sum = jnp.sum(jnp.where(trans_mask, trans_scores, 0.0), axis=1)
    first = label[:, 0]
    last = jnp.take_along_axis(
        label, jnp.maximum(length - 1, 0)[:, None], axis=1)[:, 0]
    score = start_w[first] + emit_sum + trans_sum + end_w[last]

    nll = (logz - score).reshape(-1, 1)
    return {"LogLikelihood": [nll], "Alpha": [alpha],
            "EmissionExps": [jnp.exp(emission)],
            "TransitionExps": [jnp.exp(transition)]}


@register_op("crf_decoding", stop_gradient=True)
def _crf_decoding(ctx, ins, attrs):
    """≙ crf_decoding_op.cc: Viterbi decode. With Input(Label) given, the
    output marks positions where the decoded tag equals the label (1/0),
    as in the reference kernel (crf_decoding_op.h).
    """
    emission = ins["Emission"][0]                # [B, T, D]
    transition = ins["Transition"][0]
    length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    B, T, D = emission.shape
    start_w, end_w, trans = _crf_unpack(transition)

    v0 = start_w[None, :] + emission[:, 0, :]              # [B, D]

    def fwd(v, t):
        scores = v[:, :, None] + trans[None, :, :]         # [B, D, D]
        best_prev = jnp.argmax(scores, axis=1)             # [B, D]
        new = jnp.max(scores, axis=1) + emission[:, t, :]
        active = (t < length)[:, None]
        v_out = jnp.where(active, new, v)
        # inactive steps record identity backpointers
        bp = jnp.where(active, best_prev,
                       jnp.arange(D)[None, :].repeat(B, 0))
        return v_out, bp

    v, bps = jax.lax.scan(fwd, v0, jnp.arange(1, T))       # bps [T-1, B, D]
    last_tag = jnp.argmax(v + end_w[None, :], axis=1)      # [B]

    def back(tag, bp):
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev, tag

    first_tag, path_rev = jax.lax.scan(back, last_tag, bps, reverse=True)
    # path_rev[t] is the tag at time t+1; the final carry is the t=0 tag
    path = jnp.concatenate([first_tag[None, :], path_rev], axis=0).T  # [B, T]
    t_idx = jnp.arange(T)[None, :]
    path = jnp.where(t_idx < length[:, None], path, 0)

    if ins.get("Label"):
        label = ins["Label"][0]
        if label.ndim == 3:
            label = label[..., 0]
        ok = (path == label.astype(path.dtype)) & (t_idx < length[:, None])
        return {"ViterbiPath": [ok.astype(jnp.int64)]}
    return {"ViterbiPath": [path.astype(jnp.int64)]}


# ---------------------------------------------------------------------------
# Chunk evaluation
# ---------------------------------------------------------------------------

_SCHEMES = {
    # scheme: (num_tag_types, begin, inside, end, single); -1 = absent
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, 0),
}


def _chunk_bounds(tag, typ, is_other, scheme, num_chunk_types):
    """is_begin[b,t] / is_end[b,t] per the reference's ChunkBegin/ChunkEnd
    (chunk_eval_op.h). Sentinel positions outside the sequence are 'other'."""
    num_tag, t_begin, t_inside, t_end, t_single = _SCHEMES[scheme]

    def shift_prev(a, fill):
        return jnp.pad(a, ((0, 0), (1, 0)), constant_values=fill)[:, :-1]

    def shift_next(a, fill):
        return jnp.pad(a, ((0, 0), (0, 1)), constant_values=fill)[:, 1:]

    prev_tag = shift_prev(tag, -1)
    prev_typ = shift_prev(typ, -1)
    prev_other = shift_prev(is_other, True)
    next_tag = shift_next(tag, -1)
    next_typ = shift_next(typ, -1)
    next_other = shift_next(is_other, True)

    # ChunkBegin(prev, cur): cur not other AND (prev other, or type change,
    # or cur tag is B/S, or prev tag was E/S)
    begin = (~is_other) & (
        prev_other | (typ != prev_typ) |
        (tag == t_begin) | (tag == t_single) |
        ((prev_tag == t_end) & ~prev_other) |
        ((prev_tag == t_single) & ~prev_other))
    # ChunkEnd(cur, next): cur not other AND (next other, or type change,
    # or cur tag is E/S, or next tag is B/S)
    end = (~is_other) & (
        next_other | (typ != next_typ) |
        (tag == t_end) | (tag == t_single) |
        ((next_tag == t_begin) & ~next_other) |
        ((next_tag == t_single) & ~next_other))
    return begin, end


def _next_end_index(is_end, T):
    """next_end[b,t] = smallest t' >= t with is_end[b,t'] (else T)."""
    idx = jnp.where(is_end, jnp.arange(T)[None, :], T)
    return jax.lax.associative_scan(jnp.minimum, idx, axis=1, reverse=True)


@register_op("chunk_eval", stop_gradient=True)
def _chunk_eval(ctx, ins, attrs):
    """≙ chunk_eval_op.cc: precision/recall/F1 of chunk detection.

    Inference [B, T], Label [B, T], Length [B]. attrs: num_chunk_types,
    chunk_scheme (IOB/IOE/IOBES/plain), excluded_chunk_types. Tag encoding
    matches the reference: tag = chunk_type * num_tag_types + tag_type;
    anything outside [0, num_chunk_types*num_tag_types) is 'other' (O).
    """
    inference = ins["Inference"][0]
    label = ins["Label"][0]
    if inference.ndim == 3:
        inference = inference[..., 0]
    if label.ndim == 3:
        label = label[..., 0]
    inference = inference.astype(jnp.int32)
    label = label.astype(jnp.int32)
    length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    scheme = attrs.get("chunk_scheme", "IOB")
    num_chunk_types = attrs["num_chunk_types"]
    excluded = tuple(attrs.get("excluded_chunk_types", ()) or ())
    num_tag = _SCHEMES[scheme][0]
    B, T = label.shape
    t_idx = jnp.arange(T)[None, :]
    in_seq = t_idx < length[:, None]

    def analyze(tags):
        typ = tags // num_tag
        tag_type = tags % num_tag
        other = (~in_seq) | (tags < 0) | (typ >= num_chunk_types)
        for ex in excluded:
            other = other | (typ == ex)
        begin, end = _chunk_bounds(
            jnp.where(other, -1, tag_type), jnp.where(other, -1, typ),
            other, scheme, num_chunk_types)
        return typ, other, begin & in_seq, end & in_seq

    i_typ, i_oth, i_beg, i_end = analyze(inference)
    l_typ, l_oth, l_beg, l_end = analyze(label)

    num_infer = jnp.sum(i_beg)
    num_label = jnp.sum(l_beg)
    i_next_end = _next_end_index(i_end, T)
    l_next_end = _next_end_index(l_end, T)
    correct = (i_beg & l_beg & (i_typ == l_typ)
               & (i_next_end == l_next_end))
    num_correct = jnp.sum(correct)

    ni = num_infer.astype(jnp.float32)
    nl = num_label.astype(jnp.float32)
    nc = num_correct.astype(jnp.float32)
    precision = jnp.where(ni > 0, nc / jnp.maximum(ni, 1), 0.0)
    recall = jnp.where(nl > 0, nc / jnp.maximum(nl, 1), 0.0)
    f1 = jnp.where(nc > 0,
                   2 * precision * recall /
                   jnp.maximum(precision + recall, 1e-12), 0.0)
    as64 = lambda x: x.astype(jnp.int64).reshape(1)  # noqa: E731
    return {"Precision": [precision.reshape(1)],
            "Recall": [recall.reshape(1)],
            "F1-Score": [f1.reshape(1)],
            "NumInferChunks": [as64(num_infer)],
            "NumLabelChunks": [as64(num_label)],
            "NumCorrectChunks": [as64(num_correct)]}
