"""Detection/vision ops: priors, box coding, matching, NMS, ROI pooling.

≙ reference paddle/fluid/operators/detection/ (prior_box_op, density_prior_box
_op, box_coder_op, iou_similarity_op, bipartite_match_op, target_assign_op,
multiclass_nms_op, anchor_generator_op) and roi_pool_op.cc (SURVEY.md §2.2
"Detection/vision"). The reference kernels loop over LoD'd boxes on CPU/GPU;
here everything is static-shape vectorized jax: matching and NMS run as
lax.fori_loop/scan with masking (outputs padded, counts returned), which is
the form XLA can compile for TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import register_op

_NEG = -1e9


def expand_aspect_ratios(aspect_ratios, flip):
    """Dedup'd prior aspect-ratio list incl. the implicit 1.0 and optional
    flips — shared by the kernel and the layer so declared prior counts
    always match emitted shapes."""
    ars = [1.0]
    for ar in aspect_ratios or [1.0]:
        if any(abs(float(ar) - a) < 1e-6 for a in ars):
            continue
        ars.append(float(ar))
        if flip and not any(abs(1.0 / float(ar) - a) < 1e-6 for a in ars):
            ars.append(1.0 / float(ar))
    return ars


# ---------------------------------------------------------------------------
# similarity + coding
# ---------------------------------------------------------------------------

def _iou(x, y):
    """x [N,4], y [M,4] (xmin,ymin,xmax,ymax) -> [N,M] IoU."""
    area_x = jnp.maximum(x[:, 2] - x[:, 0], 0) * \
        jnp.maximum(x[:, 3] - x[:, 1], 0)
    area_y = jnp.maximum(y[:, 2] - y[:, 0], 0) * \
        jnp.maximum(y[:, 3] - y[:, 1], 0)
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_x[:, None] + area_y[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


@register_op("iou_similarity", stop_gradient=True)
def _iou_similarity(ctx, ins, attrs):
    """≙ iou_similarity_op: X [N,4] or [B,N,4] vs Y [M,4]."""
    x, y = ins["X"][0], ins["Y"][0]
    if x.ndim == 3:
        return {"Out": [jax.vmap(lambda xb: _iou(xb, y))(x)]}
    return {"Out": [_iou(x, y)]}


def _center_size(box):
    w = box[..., 2] - box[..., 0]
    h = box[..., 3] - box[..., 1]
    cx = box[..., 0] + w / 2
    cy = box[..., 1] + h / 2
    return cx, cy, w, h


@register_op("box_coder", stop_gradient=True)
def _box_coder(ctx, ins, attrs):
    """≙ box_coder_op.cc: encode/decode boxes against priors with variances.

    PriorBox [M,4], PriorBoxVar [M,4] (optional), TargetBox:
      encode_center_size: TargetBox [N,4] -> Out [N,M,4]
      decode_center_size: TargetBox [N,M,4] (offsets) -> Out [N,M,4] boxes
    """
    prior = ins["PriorBox"][0]
    pvar = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None
    target = ins["TargetBox"][0]
    code_type = attrs.get("code_type", "encode_center_size")
    box_normalized = attrs.get("box_normalized", True)
    norm = 0.0 if box_normalized else 1.0

    pcx, pcy, pw, ph = _center_size(prior)          # [M]
    pw = pw + norm
    ph = ph + norm
    if pvar is None:
        pvar = jnp.ones((prior.shape[0], 4), prior.dtype)

    if code_type == "encode_center_size":
        tcx, tcy, tw, th = _center_size(target)     # [N]
        tw = tw + norm
        th = th + norm
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(jnp.maximum(tw[:, None] / pw[None, :], 1e-10))
        dh = jnp.log(jnp.maximum(th[:, None] / ph[None, :], 1e-10))
        out = jnp.stack([dx, dy, dw, dh], axis=-1) / pvar[None, :, :]
    else:  # decode_center_size
        d = target * pvar[None, :, :]
        cx = d[..., 0] * pw[None, :] + pcx[None, :]
        cy = d[..., 1] * ph[None, :] + pcy[None, :]
        w = jnp.exp(d[..., 2]) * pw[None, :]
        h = jnp.exp(d[..., 3]) * ph[None, :]
        out = jnp.stack([cx - w / 2, cy - h / 2,
                         cx + w / 2 - norm, cy + h / 2 - norm], axis=-1)
    return {"OutputBox": [out]}


# ---------------------------------------------------------------------------
# priors / anchors
# ---------------------------------------------------------------------------

@register_op("prior_box", stop_gradient=True)
def _prior_box(ctx, ins, attrs):
    """≙ prior_box_op.cc (SSD priors). Input [N,C,H,W] or [N,H,W,C] feature
    map + Image; outputs Boxes [H,W,P,4] and Variances [H,W,P,4]."""
    feat = ins["Input"][0]
    img = ins["Image"][0]
    data_format = attrs.get("data_format", "NCHW")
    if data_format == "NCHW":
        fh, fw = feat.shape[2], feat.shape[3]
        ih, iw = img.shape[2], img.shape[3]
    else:
        fh, fw = feat.shape[1], feat.shape[2]
        ih, iw = img.shape[1], img.shape[2]
    min_sizes = list(attrs["min_sizes"])
    max_sizes = list(attrs.get("max_sizes", []) or [])
    if max_sizes and len(max_sizes) != len(min_sizes):
        raise ValueError(
            f"prior_box: len(max_sizes)={len(max_sizes)} must equal "
            f"len(min_sizes)={len(min_sizes)}")
    ars = expand_aspect_ratios(attrs.get("aspect_ratios", [1.0]),
                               attrs.get("flip", True))
    step_w = attrs.get("step_w", 0.0) or iw / fw
    step_h = attrs.get("step_h", 0.0) or ih / fh
    offset = attrs.get("offset", 0.5)

    # per-cell prior sizes (order matches the reference: for each min_size:
    # all aspect ratios, then the sqrt(min*max) square)
    widths, heights = [], []
    for i, ms in enumerate(min_sizes):
        for ar in ars:
            widths.append(ms * np.sqrt(ar))
            heights.append(ms / np.sqrt(ar))
        if max_sizes:
            mx = max_sizes[i]
            widths.append(np.sqrt(ms * mx))
            heights.append(np.sqrt(ms * mx))
    pw = jnp.asarray(widths, feat.dtype)           # [P]
    ph = jnp.asarray(heights, feat.dtype)

    cx = (jnp.arange(fw, dtype=feat.dtype) + offset) * step_w   # [W]
    cy = (jnp.arange(fh, dtype=feat.dtype) + offset) * step_h   # [H]
    cxg = jnp.broadcast_to(cx[None, :, None], (fh, fw, pw.shape[0]))
    cyg = jnp.broadcast_to(cy[:, None, None], (fh, fw, pw.shape[0]))
    boxes = jnp.stack([(cxg - pw / 2) / iw, (cyg - ph / 2) / ih,
                       (cxg + pw / 2) / iw, (cyg + ph / 2) / ih], axis=-1)
    if attrs.get("clip", True):
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.asarray(attrs.get("variances", [0.1, 0.1, 0.2, 0.2]),
                      feat.dtype)
    variances = jnp.broadcast_to(var, boxes.shape)
    return {"Boxes": [boxes], "Variances": [variances]}


@register_op("density_prior_box", stop_gradient=True)
def _density_prior_box(ctx, ins, attrs):
    """≙ density_prior_box_op.cc: dense grid of priors per cell with
    per-size densities."""
    feat, img = ins["Input"][0], ins["Image"][0]
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    fixed_sizes = list(attrs["fixed_sizes"])
    fixed_ratios = list(attrs.get("fixed_ratios", [1.0]))
    densities = list(attrs["densities"])
    if len(densities) != len(fixed_sizes):
        raise ValueError(
            f"density_prior_box: len(densities)={len(densities)} must "
            f"equal len(fixed_sizes)={len(fixed_sizes)}")
    step_w = attrs.get("step_w", 0.0) or iw / fw
    step_h = attrs.get("step_h", 0.0) or ih / fh
    offset = attrs.get("offset", 0.5)

    ws, hs, sx, sy = [], [], [], []
    for size, dens in zip(fixed_sizes, densities):
        for ar in fixed_ratios:
            w = size * np.sqrt(ar)
            h = size / np.sqrt(ar)
            shift = 1.0 / dens
            for di in range(dens):
                for dj in range(dens):
                    ws.append(w)
                    hs.append(h)
                    sx.append((dj + 0.5) * shift - 0.5)  # cell-rel offsets
                    sy.append((di + 0.5) * shift - 0.5)
    pw = jnp.asarray(ws, feat.dtype)
    ph = jnp.asarray(hs, feat.dtype)
    ox = jnp.asarray(sx, feat.dtype) * step_w
    oy = jnp.asarray(sy, feat.dtype) * step_h
    P = pw.shape[0]
    cx = (jnp.arange(fw, dtype=feat.dtype) + offset) * step_w
    cy = (jnp.arange(fh, dtype=feat.dtype) + offset) * step_h
    cxg = cx[None, :, None] + ox[None, None, :]
    cyg = cy[:, None, None] + oy[None, None, :]
    cxg = jnp.broadcast_to(cxg, (fh, fw, P))
    cyg = jnp.broadcast_to(cyg, (fh, fw, P))
    boxes = jnp.stack([(cxg - pw / 2) / iw, (cyg - ph / 2) / ih,
                       (cxg + pw / 2) / iw, (cyg + ph / 2) / ih], axis=-1)
    if attrs.get("clip", True):
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.asarray(attrs.get("variances", [0.1, 0.1, 0.2, 0.2]),
                      feat.dtype)
    return {"Boxes": [boxes],
            "Variances": [jnp.broadcast_to(var, boxes.shape)]}


@register_op("anchor_generator", stop_gradient=True)
def _anchor_generator(ctx, ins, attrs):
    """≙ anchor_generator_op.cc (RPN anchors, absolute pixel coords)."""
    feat = ins["Input"][0]
    fh, fw = feat.shape[2], feat.shape[3]
    sizes = list(attrs.get("anchor_sizes", [64., 128., 256., 512.]))
    ratios = list(attrs.get("aspect_ratios", [0.5, 1.0, 2.0]))
    stride = list(attrs.get("stride", [16.0, 16.0]))
    offset = attrs.get("offset", 0.5)
    ws, hs = [], []
    for r in ratios:
        for s in sizes:
            area = stride[0] * stride[1]
            scale = s / np.sqrt(area)
            base_w = np.round(np.sqrt(area / r))
            base_h = np.round(base_w * r)
            ws.append(scale * base_w)
            hs.append(scale * base_h)
    pw = jnp.asarray(ws, feat.dtype)
    ph = jnp.asarray(hs, feat.dtype)
    cx = (jnp.arange(fw, dtype=feat.dtype) + offset) * stride[0]
    cy = (jnp.arange(fh, dtype=feat.dtype) + offset) * stride[1]
    P = pw.shape[0]
    cxg = jnp.broadcast_to(cx[None, :, None], (fh, fw, P))
    cyg = jnp.broadcast_to(cy[:, None, None], (fh, fw, P))
    anchors = jnp.stack([cxg - pw / 2, cyg - ph / 2,
                         cxg + pw / 2, cyg + ph / 2], axis=-1)
    var = jnp.asarray(attrs.get("variances", [0.1, 0.1, 0.2, 0.2]),
                      feat.dtype)
    return {"Anchors": [anchors],
            "Variances": [jnp.broadcast_to(var, anchors.shape)]}


# ---------------------------------------------------------------------------
# matching + target assignment
# ---------------------------------------------------------------------------

def _bipartite_match_single(dist, match_type, overlap_threshold):
    """dist [N, M] (rows = ground truth, cols = priors). Returns
    (match_indices [M] int32 row-or-−1, match_dist [M])."""
    N, M = dist.shape
    steps = min(N, M)

    def body(_, carry):
        midx, mdist, row_used, col_used = carry
        masked = jnp.where(row_used[:, None] | col_used[None, :], _NEG, dist)
        flat = jnp.argmax(masked)
        r, c = flat // M, flat % M
        best = masked[r, c]
        valid = best > 0
        midx = jnp.where(valid, midx.at[c].set(r.astype(jnp.int32)), midx)
        mdist = jnp.where(valid, mdist.at[c].set(best), mdist)
        row_used = jnp.where(valid, row_used.at[r].set(True), row_used)
        col_used = jnp.where(valid, col_used.at[c].set(True), col_used)
        return midx, mdist, row_used, col_used

    init = (jnp.full((M,), -1, jnp.int32), jnp.zeros((M,), dist.dtype),
            jnp.zeros((N,), bool), jnp.zeros((M,), bool))
    midx, mdist, _, _ = jax.lax.fori_loop(0, steps, body, init)

    if match_type == "per_prediction":
        # unmatched cols additionally match their best row if it clears the
        # overlap threshold (≙ bipartite_match_op.cc match_type attr)
        best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
        best_val = jnp.max(dist, axis=0)
        extra = (midx < 0) & (best_val > overlap_threshold)
        midx = jnp.where(extra, best_row, midx)
        mdist = jnp.where(extra, best_val, mdist)
    return midx, mdist


@register_op("bipartite_match", stop_gradient=True)
def _bipartite_match(ctx, ins, attrs):
    """≙ bipartite_match_op.cc. DistMat [B,N,M] (or [N,M]); outputs
    ColToRowMatchIndices [B,M] (-1 = unmatched) and ColToRowMatchDist."""
    dist = ins["DistMat"][0]
    match_type = attrs.get("match_type", "bipartite")
    thr = attrs.get("dist_threshold", 0.5)
    squeeze = dist.ndim == 2
    if squeeze:
        dist = dist[None]
    midx, mdist = jax.vmap(
        lambda d: _bipartite_match_single(d, match_type, thr))(dist)
    if squeeze:
        midx, mdist = midx[0], mdist[0]
    return {"ColToRowMatchIndices": [midx], "ColToRowMatchDist": [mdist]}


@register_op("target_assign", stop_gradient=True)
def _target_assign(ctx, ins, attrs):
    """≙ target_assign_op.cc: scatter per-gt rows to matched priors.

    X [B,N,K] per-gt values (boxes or labels), MatchIndices [B,M];
    Out [B,M,K] with mismatch_value where unmatched, OutWeight [B,M,1]."""
    x = ins["X"][0]
    match = ins["MatchIndices"][0]
    mismatch = attrs.get("mismatch_value", 0)
    safe = jnp.maximum(match, 0)
    gathered = jax.vmap(lambda xb, mb: xb[mb])(x, safe)   # [B,M,K]
    matched = (match >= 0)[..., None]
    out = jnp.where(matched, gathered,
                    jnp.asarray(mismatch, x.dtype))
    weight = matched.astype(jnp.float32)
    return {"Out": [out], "OutWeight": [weight]}


# ---------------------------------------------------------------------------
# NMS
# ---------------------------------------------------------------------------

def _nms_single(boxes, scores, iou_threshold, top_k):
    """boxes [M,4], scores [M] -> keep mask [M] after greedy NMS limited to
    top_k selections (static-shape suppression loop)."""
    M = scores.shape[0]
    order_scores = scores
    iou = _iou(boxes, boxes)

    def body(_, carry):
        keep, alive = carry
        idx = jnp.argmax(jnp.where(alive, order_scores, _NEG))
        ok = jnp.where(alive[idx], order_scores[idx] > _NEG / 2, False)
        keep = jnp.where(ok, keep.at[idx].set(True), keep)
        # suppress overlaps with the selected box
        suppress = iou[idx] >= iou_threshold
        alive = jnp.where(ok, alive & ~suppress, alive)
        alive = alive.at[idx].set(False)
        return keep, alive

    steps = min(top_k, M) if top_k > 0 else M
    keep, _ = jax.lax.fori_loop(
        0, steps, body,
        (jnp.zeros((M,), bool), jnp.ones((M,), bool)))
    return keep


@register_op("multiclass_nms", stop_gradient=True)
def _multiclass_nms(ctx, ins, attrs):
    """≙ multiclass_nms_op.cc. BBoxes [B,M,4], Scores [B,C,M].

    Static-shape output: Out [B, keep_top_k, 6] rows (label, score, x1, y1,
    x2, y2) sorted by score, padded with -1 labels; NmsRoisNum [B].
    (The reference emits a LoD tensor; the padded form + count is the
    static translation.)
    """
    bboxes = ins["BBoxes"][0]
    scores = ins["Scores"][0]
    score_threshold = attrs.get("score_threshold", 0.01)
    nms_top_k = attrs.get("nms_top_k", 400)
    keep_top_k = attrs.get("keep_top_k", 200)
    nms_threshold = attrs.get("nms_threshold", 0.3)
    background_label = attrs.get("background_label", 0)
    B, C, M = scores.shape
    K = keep_top_k if keep_top_k > 0 else C * M

    def per_image(boxes, sc):
        def per_class(c_scores):
            valid = c_scores > score_threshold
            s = jnp.where(valid, c_scores, _NEG)
            keep = _nms_single(boxes, s, nms_threshold, nms_top_k)
            return jnp.where(keep & valid, c_scores, _NEG)

        kept = jax.vmap(per_class)(sc)                  # [C,M]
        labels = jnp.broadcast_to(jnp.arange(C)[:, None], (C, M))
        kept = jnp.where(labels == background_label, _NEG, kept)
        flat_scores = kept.reshape(-1)                  # [C*M]
        k = min(K, C * M)
        top_scores, top_idx = jax.lax.top_k(flat_scores, k)
        top_label = (top_idx // M).astype(jnp.float32)
        top_box = boxes[top_idx % M]
        valid = top_scores > _NEG / 2
        row = jnp.concatenate(
            [jnp.where(valid, top_label, -1.0)[:, None],
             jnp.where(valid, top_scores, -1.0)[:, None],
             jnp.where(valid[:, None], top_box, -1.0)], axis=1)  # [k,6]
        if k < K:
            row = jnp.pad(row, ((0, K - k), (0, 0)), constant_values=-1.0)
        return row, jnp.sum(valid.astype(jnp.int32))

    out, num = jax.vmap(per_image)(bboxes, scores)
    return {"Out": [out], "NmsRoisNum": [num]}


# ---------------------------------------------------------------------------
# ROI pooling
# ---------------------------------------------------------------------------

@register_op("roi_pool")
def _roi_pool(ctx, ins, attrs):
    """≙ roi_pool_op.cc: quantized max-pool per ROI bin.

    X [N,C,H,W]; ROIs [R,5] rows (batch_idx, x1, y1, x2, y2) in image
    coords. Out [R, C, ph, pw]. Bin membership is computed as a static
    [ph*pw, H] x [pw, W] mask pair per ROI — O(R·C·H·W·ph·pw) like the
    reference kernel, fully vectorized for XLA."""
    x = ins["X"][0]
    rois = ins["ROIs"][0]
    ph = attrs["pooled_height"]
    pw = attrs["pooled_width"]
    scale = attrs.get("spatial_scale", 1.0)
    N, C, H, W = x.shape

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale)
        y1 = jnp.round(roi[2] * scale)
        x2 = jnp.round(roi[3] * scale)
        y2 = jnp.round(roi[4] * scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        hi = jnp.arange(ph, dtype=x.dtype)
        wi = jnp.arange(pw, dtype=x.dtype)
        hstart = jnp.clip(jnp.floor(hi * bin_h) + y1, 0, H)
        hend = jnp.clip(jnp.ceil((hi + 1) * bin_h) + y1, 0, H)
        wstart = jnp.clip(jnp.floor(wi * bin_w) + x1, 0, W)
        wend = jnp.clip(jnp.ceil((wi + 1) * bin_w) + x1, 0, W)
        hpos = jnp.arange(H, dtype=x.dtype)
        wpos = jnp.arange(W, dtype=x.dtype)
        hmask = (hpos[None, :] >= hstart[:, None]) & \
            (hpos[None, :] < hend[:, None])          # [ph,H]
        wmask = (wpos[None, :] >= wstart[:, None]) & \
            (wpos[None, :] < wend[:, None])          # [pw,W]
        mask = hmask[:, None, :, None] & wmask[None, :, None, :]  # [ph,pw,H,W]
        feat = x[b]                                   # [C,H,W]
        vals = jnp.where(mask[None], feat[:, None, None, :, :], _NEG)
        out = jnp.max(vals, axis=(3, 4))              # [C,ph,pw]
        empty = ~jnp.any(mask, axis=(2, 3))           # [ph,pw]
        return jnp.where(empty[None], 0.0, out)

    return {"Out": [jax.vmap(one_roi)(rois).astype(x.dtype)]}


# ---------------------------------------------------------------------------
# SSD multibox loss
# ---------------------------------------------------------------------------

def _smooth_l1(x):
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0, 0.5 * x * x, ax - 0.5)


@register_op("ssd_loss")
def _ssd_loss(ctx, ins, attrs):
    """≙ the composite the reference builds in layers/detection.py ssd_loss
    (iou_similarity -> bipartite_match -> target_assign -> smooth_l1 +
    softmax CE with hard negative mining), fused into one differentiable
    lowering. Matching/mining indices are stop-gradient; loss flows through
    Location and Confidence.
    """
    loc = ins["Location"][0]            # [B,M,4]
    conf = ins["Confidence"][0]         # [B,M,C]
    gt_box = ins["GTBox"][0]            # [B,G,4] zero-area rows = padding
    gt_label = ins["GTLabel"][0]        # [B,G]
    prior = ins["PriorBox"][0]          # [M,4]
    pvar = (ins["PriorBoxVar"][0] if ins.get("PriorBoxVar")
            else jnp.broadcast_to(
                jnp.asarray([0.1, 0.1, 0.2, 0.2], loc.dtype),
                (prior.shape[0], 4)))
    bg = attrs.get("background_label", 0)
    thr = attrs.get("overlap_threshold", 0.5)
    ratio = attrs.get("neg_pos_ratio", 3.0)
    w_loc = attrs.get("loc_loss_weight", 1.0)
    w_conf = attrs.get("conf_loss_weight", 1.0)
    B, M, C = conf.shape
    if gt_label.ndim == 3:
        gt_label = gt_label[..., 0]

    pcx, pcy, pw, ph = _center_size(prior)

    def per_image(loc_b, conf_b, gtb, gtl):
        area = jnp.maximum(gtb[:, 2] - gtb[:, 0], 0) * \
            jnp.maximum(gtb[:, 3] - gtb[:, 1], 0)
        valid_gt = area > 0
        iou = _iou(gtb, prior)                        # [G,M]
        iou = jnp.where(valid_gt[:, None], iou, _NEG)
        match, _ = _bipartite_match_single(iou, "per_prediction", thr)
        match = jax.lax.stop_gradient(match)          # [M]
        pos = match >= 0
        safe = jnp.maximum(match, 0)

        # --- localization targets (encode_center_size w/ variances) ------
        mb = gtb[safe]                                # [M,4]
        gcx, gcy, gw, gh = _center_size(mb)
        tx = (gcx - pcx) / pw / pvar[:, 0]
        ty = (gcy - pcy) / ph / pvar[:, 1]
        tw = jnp.log(jnp.maximum(gw / pw, 1e-10)) / pvar[:, 2]
        th = jnp.log(jnp.maximum(gh / ph, 1e-10)) / pvar[:, 3]
        t = jax.lax.stop_gradient(
            jnp.stack([tx, ty, tw, th], axis=-1))     # [M,4]
        loc_l = jnp.sum(_smooth_l1(loc_b - t), axis=-1) * pos

        # --- confidence loss with hard negative mining -------------------
        target_lbl = jnp.where(pos, gtl.astype(jnp.int32)[safe], bg)
        logp = jax.nn.log_softmax(conf_b, axis=-1)
        ce = -jnp.take_along_axis(logp, target_lbl[:, None], axis=1)[:, 0]
        num_pos = jnp.sum(pos)
        num_neg = jnp.minimum((ratio * num_pos).astype(jnp.int32),
                              M - num_pos)
        neg_score = jnp.where(pos, _NEG, jax.lax.stop_gradient(ce))
        order = jnp.argsort(-neg_score)               # hardest first
        rank = jnp.zeros((M,), jnp.int32).at[order].set(jnp.arange(M,
                                                        dtype=jnp.int32))
        neg_sel = (~pos) & (rank < num_neg)
        conf_l = jnp.sum(ce * (pos | neg_sel))
        return jnp.sum(loc_l), conf_l, num_pos

    loc_l, conf_l, npos = jax.vmap(per_image)(loc, conf, gt_box, gt_label)
    denom = jnp.maximum(jnp.sum(npos).astype(loc.dtype), 1.0)
    total = (w_loc * jnp.sum(loc_l) + w_conf * jnp.sum(conf_l)) / denom
    return {"Loss": [total]}


# ---------------------------------------------------------------------------
# RPN: anchor target assignment + proposal generation
# ---------------------------------------------------------------------------

def _rank_desc(score):
    """rank[i] = position of i when sorting score descending (0 = best)."""
    order = jnp.argsort(-score)
    return jnp.zeros_like(order).at[order].set(jnp.arange(order.shape[0]))


@register_op("rpn_target_assign", stop_gradient=True)
def _rpn_target_assign(ctx, ins, attrs):
    """≙ rpn_target_assign_op.cc (reference layers/detection.py
    rpn_target_assign). Anchor [N,4]; GtBox [G,4] with zero-area padding
    rows.

    Static-shape translation: instead of gathering sampled indices (dynamic
    shapes), emits per-anchor Labels [N] in {-1 ignore, 0 bg, 1 fg}, encoded
    BoxDeltas [N,4] toward each anchor's best gt, and BoxInsideWeight [N,4]
    (1 for kept fg anchors). Subsampling to rpn_batch_size_per_im caps the
    fg/bg sets deterministically by IoU rank (≙ use_random=False)."""
    anchor = ins["Anchor"][0]
    gt = ins["GtBox"][0]
    pos_thr = attrs.get("rpn_positive_overlap", 0.7)
    neg_thr = attrs.get("rpn_negative_overlap", 0.3)
    batch = attrs.get("rpn_batch_size_per_im", 256)
    fg_frac = attrs.get("rpn_fg_fraction", 0.5)

    gt_area = jnp.maximum(gt[:, 2] - gt[:, 0], 0) * \
        jnp.maximum(gt[:, 3] - gt[:, 1], 0)
    valid_gt = gt_area > 0
    iou = jnp.where(valid_gt[None, :], _iou(anchor, gt), -1.0)  # [N,G]
    max_iou = jnp.max(iou, axis=1)
    best_gt = jnp.argmax(iou, axis=1)

    # an anchor is fg if IoU >= pos_thr with any gt, or it is some gt's
    # best anchor (guarantees every gt owns at least one anchor)
    gt_best_anchor = jnp.argmax(iou, axis=0)                    # [G]
    is_gt_best = jnp.zeros((anchor.shape[0],), bool).at[
        gt_best_anchor].max(valid_gt, mode="drop")
    fg = (max_iou >= pos_thr) | is_gt_best
    # an image with no valid gt has max_iou == -1 everywhere: every anchor
    # is background (the reference still samples negatives there, it does
    # not drop the image from the classification loss)
    bg = (~fg) & (max_iou < neg_thr)

    fg_cap = int(batch * fg_frac)
    fg_rank = _rank_desc(jnp.where(fg, max_iou, _NEG))
    fg_keep = fg & (fg_rank < fg_cap)
    bg_cap = batch - jnp.sum(fg_keep)
    # hardest negatives first (highest IoU below the negative threshold),
    # like the ssd_loss negative mining above
    bg_rank = _rank_desc(jnp.where(bg, max_iou, _NEG))
    bg_keep = bg & (bg_rank < bg_cap)

    labels = jnp.where(fg_keep, 1, jnp.where(bg_keep, 0, -1)).astype(
        jnp.int32)

    # encode anchor -> matched gt as center-size deltas (unit variances,
    # ≙ the reference's default)
    mg = gt[jnp.clip(best_gt, 0, gt.shape[0] - 1)]
    acx, acy, aw, ah = _center_size(anchor)
    gcx, gcy, gw, gh = _center_size(mg)
    deltas = jnp.stack([
        (gcx - acx) / jnp.maximum(aw, 1e-8),
        (gcy - acy) / jnp.maximum(ah, 1e-8),
        jnp.log(jnp.maximum(gw / jnp.maximum(aw, 1e-8), 1e-10)),
        jnp.log(jnp.maximum(gh / jnp.maximum(ah, 1e-8), 1e-10)),
    ], axis=-1)
    inside_w = jnp.broadcast_to(fg_keep[:, None], deltas.shape).astype(
        anchor.dtype)
    return {"Labels": [labels], "BoxDeltas": [deltas * inside_w],
            "BoxInsideWeight": [inside_w]}


@register_op("generate_proposals", stop_gradient=True)
def _generate_proposals(ctx, ins, attrs):
    """≙ generate_proposals_op.cc. Scores [B,A], BboxDeltas [B,A,4],
    Anchors [A,4], ImInfo [B,3] (h, w, scale).

    Static-shape: per image, top pre_nms_top_n by score -> decode ->
    clip to image -> min_size mask -> NMS -> RpnRois [B,post,4],
    RpnRoiProbs [B,post,1], RpnRoisNum [B] (valid counts; tail rows zero)."""
    scores = ins["Scores"][0]
    deltas = ins["BboxDeltas"][0]
    anchors = ins["Anchors"][0]
    im_info = ins["ImInfo"][0]
    pre_n = min(attrs.get("pre_nms_top_n", 6000), anchors.shape[0])
    post_n = attrs.get("post_nms_top_n", 1000)
    nms_thresh = attrs.get("nms_thresh", 0.5)
    min_size = attrs.get("min_size", 0.1)

    acx, acy, aw, ah = _center_size(anchors)

    def per_image(sc, dl, info):
        top_sc, idx = jax.lax.top_k(sc, pre_n)
        d = dl[idx]
        cx = d[:, 0] * aw[idx] + acx[idx]
        cy = d[:, 1] * ah[idx] + acy[idx]
        w = jnp.exp(jnp.minimum(d[:, 2], 10.0)) * aw[idx]
        h = jnp.exp(jnp.minimum(d[:, 3], 10.0)) * ah[idx]
        boxes = jnp.stack([cx - w / 2, cy - h / 2,
                           cx + w / 2, cy + h / 2], axis=-1)
        ih, iw = info[0], info[1]
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, iw - 1), jnp.clip(boxes[:, 1], 0, ih - 1),
            jnp.clip(boxes[:, 2], 0, iw - 1), jnp.clip(boxes[:, 3], 0, ih - 1),
        ], axis=-1)
        bw = boxes[:, 2] - boxes[:, 0]
        bh = boxes[:, 3] - boxes[:, 1]
        ms = min_size * info[2]
        ok = (bw >= ms) & (bh >= ms)
        sc_f = jnp.where(ok, top_sc, _NEG)
        keep = _nms_single(boxes, sc_f, nms_thresh, post_n)
        sel_sc = jnp.where(keep, sc_f, _NEG)
        if pre_n < post_n:
            # fewer candidates than the declared static output rows: pad so
            # the emitted shape always matches the layer's [post_n, 4]
            pad = post_n - pre_n
            sel_sc = jnp.concatenate([sel_sc, jnp.full((pad,), _NEG)])
            boxes = jnp.concatenate([boxes, jnp.zeros((pad, 4))])
            top_sc = jnp.concatenate([top_sc, jnp.zeros((pad,))])
        order = jnp.argsort(-sel_sc)[:post_n]
        valid = sel_sc[order] > _NEG / 2
        rois = boxes[order] * valid[:, None]
        probs = (top_sc[order] * valid)[:, None]
        return rois, probs, jnp.sum(valid.astype(jnp.int32))

    rois, probs, nums = jax.vmap(per_image)(scores, deltas, im_info)
    return {"RpnRois": [rois], "RpnRoiProbs": [probs], "RpnRoisNum": [nums]}


# ---------------------------------------------------------------------------
# in-graph evaluation: detection mAP + positive/negative pair
# ---------------------------------------------------------------------------

@register_op("detection_map", stop_gradient=True)
def _detection_map(ctx, ins, attrs):
    """≙ detection_map_op.cc, in-graph. DetectRes [B,K,6] rows
    (label, score, xmin, ymin, xmax, ymax) — the multiclass_nms layout,
    label < 0 padding; GtLabel [B,G,5] rows (label, box), zero-area padding.

    Integral average precision per class (ap_type='integral'), averaged
    over classes that have ground truth. Matching is greedy by score with
    one-to-one gt assignment at overlap_threshold, the reference rule."""
    det = ins["DetectRes"][0]
    gt = ins["Label"][0]
    thr = attrs.get("overlap_threshold", 0.5)
    class_num = attrs["class_num"]
    B, K, _ = det.shape
    G = gt.shape[1]

    gt_area = jnp.maximum(gt[..., 3] - gt[..., 1], 0) * \
        jnp.maximum(gt[..., 4] - gt[..., 2], 0)
    gt_valid = gt_area > 0

    def ap_for_class(c):
        det_c = det[..., 0] == c            # [B,K]
        gt_c = gt_valid & (gt[..., 0] == c)  # [B,G]
        npos = jnp.sum(gt_c)
        # flatten detections, order globally by score
        score = jnp.where(det_c, det[..., 1], _NEG).reshape(-1)   # [B*K]
        order = jnp.argsort(-score)

        iou_bg = jax.vmap(_iou)(det[..., 2:6], gt[..., 1:5])      # [B,K,G]
        iou_flat = iou_bg.reshape(B * K, G)
        img_of = jnp.repeat(jnp.arange(B), K)

        def body(i, carry):
            matched, tp, fp = carry        # matched [B,G]
            di = order[i]
            b = img_of[di]
            cand = gt_c[b] & ~matched[b]
            iou_row = jnp.where(cand, iou_flat[di], -1.0)
            gi = jnp.argmax(iou_row)
            hit = (iou_row[gi] >= thr) & (score[di] > _NEG / 2)
            miss = (~hit) & (score[di] > _NEG / 2)
            matched = matched.at[b, gi].set(matched[b, gi] | hit)
            tp = tp.at[i].set(hit)
            fp = fp.at[i].set(miss)
            return matched, tp, fp

        _, tp, fp = jax.lax.fori_loop(
            0, B * K, body,
            (jnp.zeros((B, G), bool), jnp.zeros((B * K,), bool),
             jnp.zeros((B * K,), bool)))
        ctp = jnp.cumsum(tp.astype(jnp.float32))
        cfp = jnp.cumsum(fp.astype(jnp.float32))
        recall = ctp / jnp.maximum(npos.astype(jnp.float32), 1.0)
        precision = ctp / jnp.maximum(ctp + cfp, 1.0)
        rec_prev = jnp.concatenate([jnp.zeros((1,)), recall[:-1]])
        ap = jnp.sum((recall - rec_prev) * precision)
        return ap, npos > 0

    aps, has_gt = jax.vmap(ap_for_class)(jnp.arange(class_num))
    n_classes = jnp.maximum(jnp.sum(has_gt.astype(jnp.float32)), 1.0)
    m_ap = jnp.sum(jnp.where(has_gt, aps, 0.0)) / n_classes
    return {"MAP": [m_ap]}


@register_op("positive_negative_pair", stop_gradient=True)
def _positive_negative_pair(ctx, ins, attrs):
    """≙ positive_negative_pair_op.cc: within each query group, count pairs
    ranked correctly (positive), incorrectly (negative), or tied (neutral)
    by Score relative to the Label ordering. Score/Label/QueryID: [N,1]."""
    s = ins["Score"][0].reshape(-1)
    l = ins["Label"][0].reshape(-1)
    q = ins["QueryID"][0].reshape(-1)
    pair = (q[:, None] == q[None, :]) & (l[:, None] > l[None, :])
    ds = s[:, None] - s[None, :]
    pos = jnp.sum((pair & (ds > 0)).astype(jnp.float32))
    neg = jnp.sum((pair & (ds < 0)).astype(jnp.float32))
    neu = jnp.sum((pair & (ds == 0)).astype(jnp.float32))
    if ins.get("AccumulatePositivePair"):
        pos = pos + ins["AccumulatePositivePair"][0].reshape(())
        neg = neg + ins["AccumulateNegativePair"][0].reshape(())
        neu = neu + ins["AccumulateNeutralPair"][0].reshape(())
    return {"PositivePair": [pos.reshape(1)],
            "NegativePair": [neg.reshape(1)],
            "NeutralPair": [neu.reshape(1)]}
