"""Tensor-manipulation op lowerings.

≙ reference paddle/fluid/operators/{reshape,transpose,concat,split,slice,
gather,scatter,stack,squeeze,unsqueeze,flatten,expand,pad,one_hot,cast,
fill_constant,fill_zeros_like,assign,shape,reverse,multiplex,crop,
label_smooth,lookup_table}_op.cc (SURVEY §2.2 tensor-manip family).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtypes import convert_dtype
from ..framework.registry import dim_prod, register_op


@register_op("reshape")
def _reshape(ctx, ins, attrs):
    x = ins["X"][0]
    shape = list(attrs["shape"])
    # reference reshape semantics: 0 means copy dim from input, -1 inferred
    for i, d in enumerate(shape):
        if d == 0:
            shape[i] = x.shape[i]
    return {"Out": [jnp.reshape(x, shape)]}


@register_op("transpose")
def _transpose(ctx, ins, attrs):
    return {"Out": [jnp.transpose(ins["X"][0], attrs["axis"])]}


@register_op("concat")
def _concat(ctx, ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))]}


@register_op("split")
def _split(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    if attrs.get("sections"):
        idx = np.cumsum(attrs["sections"])[:-1]
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, attrs["num"], axis=axis)
    return {"Out": list(outs)}


@register_op("slice")
def _slice(ctx, ins, attrs):
    x = ins["X"][0]
    axes, starts, ends = attrs["axes"], attrs["starts"], attrs["ends"]
    idx = [slice(None)] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        idx[ax] = slice(s, e)
    return {"Out": [x[tuple(idx)]]}


@register_op("gather")
def _gather(ctx, ins, attrs):
    return {"Out": [jnp.take(ins["X"][0], ins["Index"][0], axis=0)]}


@register_op("scatter")
def _scatter(ctx, ins, attrs):
    x, index, updates = ins["X"][0], ins["Ids"][0], ins["Updates"][0]
    if attrs.get("overwrite", True):
        return {"Out": [x.at[index].set(updates)]}
    return {"Out": [x.at[index].add(updates)]}


@register_op("stack")
def _stack(ctx, ins, attrs):
    return {"Y": [jnp.stack(ins["X"], axis=attrs.get("axis", 0))]}


@register_op("unstack")
def _unstack(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    n = x.shape[axis]
    return {"Y": [jnp.squeeze(s, axis=axis)
                  for s in jnp.split(x, n, axis=axis)]}


@register_op("squeeze")
def _squeeze(ctx, ins, attrs):
    axes = attrs.get("axes") or None
    return {"Out": [jnp.squeeze(ins["X"][0],
                                axis=tuple(axes) if axes else None)]}


@register_op("unsqueeze")
def _unsqueeze(ctx, ins, attrs):
    return {"Out": [jnp.expand_dims(ins["X"][0], axis=tuple(attrs["axes"]))]}


@register_op("flatten")
def _flatten(ctx, ins, attrs):
    x = ins["X"][0]
    ax = attrs.get("axis", 1)
    lead = dim_prod(x.shape[:ax]) if ax > 0 else 1
    return {"Out": [jnp.reshape(x, (lead, -1))]}


@register_op("expand")
def _expand(ctx, ins, attrs):
    x = ins["X"][0]
    times = attrs["expand_times"]
    return {"Out": [jnp.tile(x, times)]}


@register_op("expand_as")
def _expand_as(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [jnp.broadcast_to(x, y.shape)]}


@register_op("pad")
def _pad(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs["paddings"]  # flat [before0, after0, before1, after1, ...]
    pads = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": [jnp.pad(x, pads, constant_values=attrs.get("pad_value", 0.0))]}


@register_op("pad_constant_like")
def _pad_constant_like(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    pads = [(0, xd - yd) for xd, yd in zip(x.shape, y.shape)]
    return {"Out": [jnp.pad(y, pads, constant_values=attrs.get("pad_value", 0.0))]}


def _uniform_pos_guard(pos_flat):
    """cache_write's contract: ONE scalar position for the whole batch
    (`Pos.reshape(-1)[0]` is what gets used). A caller feeding per-row
    positions (ragged prompt lengths) would silently have every row
    written at row 0's position — enforce instead (ADVICE r5 #3). Host
    callbacks are a CPU-debug facility (see _nan_guard): the check is
    active on CPU — where the whole test tier runs — and a no-op on the
    tunneled TPU backend."""
    if pos_flat.shape[0] <= 1 or jax.default_backend() != "cpu":
        return
    lo = jnp.min(pos_flat)
    hi = jnp.max(pos_flat)

    def _report(lo_v, hi_v):
        if int(lo_v) != int(hi_v):
            raise ValueError(
                f"cache_write requires a uniform position across rows "
                f"(contract: Pos is one scalar broadcast to the batch), "
                f"got per-row positions spanning [{int(lo_v)}, "
                f"{int(hi_v)}]; write ragged rows via separate "
                f"cache_write calls or a vmapped update")

    jax.debug.callback(_report, lo, hi)


@register_op("cache_write", stop_gradient=True)
def _cache_write(ctx, ins, attrs):
    """Write `New` (size-1 on `axis`) into `Cache` at position `Pos` along
    `axis` via dynamic_update_slice — the KV-cache decode idiom. Inside a
    scan carry XLA performs the update in place, so the per-step cache
    cost is one row write + the attention read, not a full read+rewrite of
    the cache (the one-hot outer-product formulation's cost). No reference
    analogue: the reference's while_op decoder re-runs attention over
    growing LoD tensors instead of caching.

    Two position modes, selected by the `batch_axis` attr:

    - batch_axis None (default): `Pos` must be UNIFORM — a single
      position (any tensor; every element equal). Non-uniform per-row
      positions raise on CPU (enforced via host callback — inactive on
      TPU, where host send/recv is unavailable).
    - batch_axis set: `Pos` holds ONE position PER ROW of `Cache` along
      `batch_axis` (`Pos.reshape(-1)` length == that dim) and each row is
      written at its own position — the slot-indexed KV cache the
      continuous-batching serving engine needs (a slot mid-prompt and a
      slot mid-generation share one compiled tick). Lowers to a vmapped
      dynamic_update_slice over the batch axis."""
    cache = ins["Cache"][0]
    new = ins["New"][0].astype(cache.dtype)
    pos_flat = ins["Pos"][0].reshape(-1)
    axis = attrs["axis"] % cache.ndim
    batch_axis = attrs.get("batch_axis", None)
    if batch_axis is None:
        _uniform_pos_guard(pos_flat)
        pos = pos_flat[0].astype(jnp.int32)
        starts = [jnp.int32(0)] * cache.ndim
        starts[axis] = pos
        return {"Out": [jax.lax.dynamic_update_slice(cache, new,
                                                     tuple(starts))]}
    ba = batch_axis % cache.ndim
    if ba == axis:
        raise ValueError("cache_write: batch_axis must differ from axis")
    if pos_flat.shape[0] != cache.shape[ba]:
        raise ValueError(
            f"cache_write: per-slot Pos has {pos_flat.shape[0]} entries "
            f"but Cache dim {ba} is {cache.shape[ba]}")
    pos = pos_flat.astype(jnp.int32)
    row_axis = axis - (1 if axis > ba else 0)

    def _write_row(c, n, p):
        starts = [jnp.int32(0)] * c.ndim
        starts[row_axis] = p
        return jax.lax.dynamic_update_slice(c, n, tuple(starts))

    out = jax.vmap(_write_row, in_axes=(ba, ba, 0),
                   out_axes=ba)(cache, new, pos)
    return {"Out": [out]}


@register_op("paged_cache_write", stop_gradient=True)
def _paged_cache_write(ctx, ins, attrs):
    """Block-granular KV write for the paged cache (serving/kv_pager.py):
    scatter one new token row per slot into a device-resident block POOL
    instead of a per-slot cache row. `Cache` is the pool
    [n_blocks, nh, block_size, dh]; `New` is [S, nh, dh] (one row per
    tick slot); `BlockIds`/`Offsets` are [S] — slot s lands at
    pool[BlockIds[s], :, Offsets[s], :]. Inactive slots are steered at
    the reserved null block 0 (never mapped by a live block table), so
    one fixed-shape compiled tick serves any mix of live/idle slots —
    the same trick the slot tick plays with its zeroed feeds. Duplicate
    (block, offset) targets are only ever the null block, where any
    write order is acceptable. Lowers to one XLA scatter; inside the
    executor's donated-state path the pool updates in place."""
    pool = ins["Cache"][0]
    new = ins["New"][0].astype(pool.dtype)
    blocks = ins["BlockIds"][0].reshape(-1).astype(jnp.int32)
    offs = ins["Offsets"][0].reshape(-1).astype(jnp.int32)
    if new.ndim != pool.ndim - 1:
        raise ValueError(
            f"paged_cache_write: New must drop exactly the pool's "
            f"block-size axis (pool {pool.shape}, New {new.shape})")
    if blocks.shape != offs.shape:
        raise ValueError(
            f"paged_cache_write: BlockIds {blocks.shape} and Offsets "
            f"{offs.shape} must agree")
    return {"Out": [pool.at[blocks, :, offs, :].set(new)]}


@register_op("paged_cache_write_quant", stop_gradient=True)
def _paged_cache_write_quant(ctx, ins, attrs):
    """int8 variant of `paged_cache_write`: the pool stores int8 payloads
    plus a per-row f32 scale pool (`Scales`, [n_blocks, nh, block_size, 1])
    and each incoming f32 row is quantized symmetrically over its dh
    vector on the way in — amax/127 scale per (slot, head) row, zero rows
    pinned to scale 1.0 so dequantization is exact for them. The payload
    scatter and the scale scatter are the same one-XLA-scatter shape as
    the f32 write; the engine-side win is the pool's RESIDENT bytes
    (f32 -> int8 + one scale per dh row), which the pager hands back as
    extra admitted blocks. Same null-block steering contract as
    `paged_cache_write`."""
    pool = ins["Cache"][0]
    scales = ins["Scales"][0]
    new = jnp.asarray(ins["New"][0], jnp.float32)
    blocks = ins["BlockIds"][0].reshape(-1).astype(jnp.int32)
    offs = ins["Offsets"][0].reshape(-1).astype(jnp.int32)
    if new.ndim != pool.ndim - 1:
        raise ValueError(
            f"paged_cache_write_quant: New must drop exactly the pool's "
            f"block-size axis (pool {pool.shape}, New {new.shape})")
    if blocks.shape != offs.shape:
        raise ValueError(
            f"paged_cache_write_quant: BlockIds {blocks.shape} and "
            f"Offsets {offs.shape} must agree")
    amax = jnp.max(jnp.abs(new), axis=-1, keepdims=True)
    sc = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(new / sc), -127, 127).astype(jnp.int8)
    return {"Out": [pool.at[blocks, :, offs, :].set(q)],
            "ScalesOut": [scales.at[blocks, :, offs, :].set(sc)]}


@register_op("one_hot", stop_gradient=True)
def _one_hot(ctx, ins, attrs):
    x = ins["X"][0]
    depth = attrs["depth"]
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = jnp.squeeze(x, axis=-1)
    return {"Out": [jax.nn.one_hot(x, depth, dtype=jnp.float32)]}


@register_op("cast")
def _cast(ctx, ins, attrs):
    dtype = convert_dtype(attrs["out_dtype"])
    return {"Out": [ins["X"][0].astype(dtype)]}


@register_op("fill_constant", stop_gradient=True)
def _fill_constant(ctx, ins, attrs):
    dtype = convert_dtype(attrs.get("dtype", "float32"))
    shape = attrs["shape"]
    return {"Out": [jnp.full(shape, attrs["value"], dtype=dtype)]}


@register_op("fill_constant_batch_size_like", stop_gradient=True)
def _fill_constant_bsl(ctx, ins, attrs):
    ref = ins["Input"][0]
    shape = list(attrs["shape"])
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    dtype = convert_dtype(attrs.get("dtype", "float32"))
    return {"Out": [jnp.full(shape, attrs["value"], dtype=dtype)]}


@register_op("fill_zeros_like", stop_gradient=True)
def _fill_zeros_like(ctx, ins, attrs):
    return {"Out": [jnp.zeros_like(ins["X"][0])]}


@register_op("assign")
def _assign(ctx, ins, attrs):
    return {"Out": [ins["X"][0]]}


@register_op("assign_value", stop_gradient=True)
def _assign_value(ctx, ins, attrs):
    values = np.asarray(attrs["values"], dtype=convert_dtype(attrs["dtype"]))
    return {"Out": [jnp.asarray(values.reshape(attrs["shape"]))]}


@register_op("shape", stop_gradient=True)
def _shape(ctx, ins, attrs):
    return {"Out": [jnp.asarray(ins["Input"][0].shape, dtype=jnp.int64)]}


@register_op("reverse")
def _reverse(ctx, ins, attrs):
    return {"Out": [jnp.flip(ins["X"][0], axis=tuple(attrs["axis"]))]}


@register_op("multiplex")
def _multiplex(ctx, ins, attrs):
    ids = ins["Ids"][0].reshape(-1)
    stacked = jnp.stack(ins["X"], axis=0)  # [n_candidates, batch, ...]
    return {"Out": [stacked[ids, jnp.arange(stacked.shape[1])]]}


@register_op("crop")
def _crop(ctx, ins, attrs):
    x = ins["X"][0]
    offsets = attrs["offsets"]
    shape = attrs["shape"]
    idx = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return {"Out": [x[idx]]}


@register_op("label_smooth")
def _label_smooth(ctx, ins, attrs):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 0.0)
    if "PriorDist" in ins and ins["PriorDist"]:
        prior = ins["PriorDist"][0]
        return {"Out": [(1 - eps) * x + eps * prior]}
    return {"Out": [(1 - eps) * x + eps / x.shape[-1]]}


@register_op("lookup_table")
def _lookup_table(ctx, ins, attrs):
    """Embedding lookup (≙ lookup_table_op.cc:21). `is_sparse`/`is_distributed`
    attrs are accepted for parity; on TPU the table is a dense sharded array
    and sparse gradient aggregation is handled by XLA scatter-add in the VJP."""
    w = ins["W"][0]
    ids = ins["Ids"][0]
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = jnp.squeeze(ids, axis=-1)
    padding_idx = attrs.get("padding_idx", None)
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None:
        if padding_idx < 0:  # negative indexes from the end, as in reference
            padding_idx += w.shape[0]
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return {"Out": [out]}


@register_op("qlookup")
def _qlookup(ctx, ins, attrs):
    """Weight-only quantized embedding lookup (quantize_params_pass rewrite
    of `lookup_table`): gathers int8/int4 payload ROWS plus their row-block
    scales and dequantizes only the gathered rows — the full f32 table is
    never materialized on device."""
    qw, scales, ids = ins["QW"][0], ins["Scales"][0], ins["Ids"][0]
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = jnp.squeeze(ids, axis=-1)
    rows = jnp.take(qw, ids, axis=0)
    if attrs.get("bits", 8) == 4:
        from ..parallel.collective import unpack_int4
        lead, c2 = rows.shape[:-1], rows.shape[-1]
        rows = unpack_int4(rows.reshape(-1, c2)).reshape(lead + (2 * c2,))
    nr, nc = scales.shape
    br = qw.shape[0] // nr
    bc = rows.shape[-1] // nc
    s = jnp.take(scales, ids // br, axis=0)          # [..., nc]
    out = (rows.astype(jnp.float32).reshape(rows.shape[:-1] + (nc, bc))
           * s[..., :, None]).reshape(rows.shape)
    padding_idx = attrs.get("padding_idx", None)
    if padding_idx is not None:
        if padding_idx < 0:
            padding_idx += qw.shape[0]
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return {"Out": [out]}


@register_op("increment")
def _increment(ctx, ins, attrs):
    x = ins["X"][0]
    # keep x's dtype: int counters must not promote to float (the carry of a
    # lax.while_loop requires stable dtypes)
    return {"Out": [x + jnp.asarray(attrs.get("step", 1.0), x.dtype)]}


@register_op("print", stop_gradient=True)
def _print(ctx, ins, attrs):
    # ≙ print_op (debug tensor dump, reference layers/control_flow.py:147)
    x = ins["In"][0]
    jax.debug.print(attrs.get("message", "print_op") + ": {}", x)
    return {"Out": [x]}


@register_op("arange", stop_gradient=True)
def _arange(ctx, ins, attrs):
    return {"Out": [jnp.arange(attrs["start"], attrs["end"], attrs["step"],
                               dtype=convert_dtype(attrs.get("dtype", "int64")))]}


@register_op("cumsum")
def _cumsum(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    if attrs.get("reverse", False):
        x = jnp.flip(x, axis=axis)
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        pad = [(0, 0)] * x.ndim
        pad[axis] = (1, 0)
        sl = [slice(None)] * x.ndim
        sl[axis] = slice(0, x.shape[axis])
        out = jnp.pad(out, pad)[tuple(sl)]
    if attrs.get("reverse", False):
        out = jnp.flip(out, axis=axis)
    return {"Out": [out]}


@register_op("piecewise_decay", stop_gradient=True)
def _piecewise_decay(ctx, ins, attrs):
    # branch-free piecewise-constant LR lookup (≙ reference
    # learning_rate_scheduler.py piecewise_decay's Switch construct)
    step = ins["Step"][0]
    boundaries = jnp.asarray(attrs["boundaries"], dtype=step.dtype)
    values = jnp.asarray(attrs["values"], dtype=jnp.float32)
    idx = jnp.searchsorted(boundaries, step.reshape(()), side="right")
    return {"Out": [values[idx].reshape(1)]}


_guards_warned = []


def _warn_guards_inactive():
    if not _guards_warned:
        import warnings
        warnings.warn(
            "check_nan_inf runtime guards are a CPU-debug facility; they "
            "are INACTIVE on this backend (no host callbacks). Rerun under "
            "JAX_PLATFORMS=cpu to localize the failure.")
        _guards_warned.append(True)


def _as_id32(ids):
    """Ids live in the int32 space (the framework runs without x64). Under
    jax_enable_x64 an id beyond int32 range is mapped to the INVALID
    sentinel (negative) instead of silently wrapping into someone else's
    row: lookups return zero rows and dispatch routes it to the padded
    class, so corruption is visible rather than plausible."""
    if ids.dtype == jnp.int64:   # only possible with x64 enabled
        ids = jnp.where(jnp.abs(ids) > 2**31 - 1, -(2**31 - 1), ids)
    return ids.astype(jnp.int32)


def _array_bounds_guard(i, cap, what):
    """XLA clamps out-of-range dynamic indices; under the debug flag
    (PTPU_CHECK_NAN_INF — the framework's runtime-guards mode) report them
    instead of silently reading/writing the last slot. Host callbacks are a
    CPU-debug facility: the tunneled TPU backend has no host send/recv, so
    the guard is a no-op there (run the repro under JAX_PLATFORMS=cpu)."""
    from ..core import flags as _flags
    if not _flags.get_flag("check_nan_inf"):
        return
    if jax.default_backend() != "cpu":
        _warn_guards_inactive()
        return
    bad = (i < 0) | (i >= cap)

    def _report(bad_flag, i_val, what=what, cap=cap):
        if bool(bad_flag):
            raise IndexError(
                f"{what} index {int(i_val)} outside preallocated "
                f"capacity {cap}")

    jax.debug.callback(_report, bad, i)


@register_op("array_write")
def _array_write(ctx, ins, attrs):
    """≙ tensor_array_read_write.cc WriteToArray: functional index write
    into a preallocated [max_len, ...] array (the static-shape translation
    of the reference's dynamically-growing LoDTensorArray). NOTE: XLA
    clamps an out-of-range index to the last slot; enable the
    check_nan_inf debug flag to fail loudly instead."""
    arr = ins["Array"][0]
    x = ins["X"][0]
    i = ins["I"][0].reshape(()).astype(jnp.int32)
    _array_bounds_guard(i, arr.shape[0], "array_write")
    return {"Out": [jax.lax.dynamic_update_index_in_dim(
        arr, x.astype(arr.dtype), i, axis=0)]}


@register_op("array_read")
def _array_read(ctx, ins, attrs):
    """≙ ReadFromArray: dynamic index read (same clamping caveat as
    array_write; debug flag reports out-of-range)."""
    arr = ins["Array"][0]
    i = ins["I"][0].reshape(()).astype(jnp.int32)
    _array_bounds_guard(i, arr.shape[0], "array_read")
    return {"Out": [jax.lax.dynamic_index_in_dim(arr, i, axis=0,
                                                 keepdims=False)]}


@register_op("array_length", stop_gradient=True)
def _array_length(ctx, ins, attrs):
    """≙ lod_array_length_op: the array's capacity (static translation —
    preallocated arrays have fixed leading extent)."""
    return {"Out": [jnp.asarray(ins["X"][0].shape[0], jnp.int64)]}


# ---------------------------------------------------------------------------
# sparse/dist helpers (≙ split_ids_op / merge_ids_op /
# lookup_sparse_table_op / split_selected_rows_op — the pserver row-dispatch
# family, SURVEY.md §2.2 "Sparse/dist helpers"). Static-shape translation:
# shard membership is a mask, outputs are padded to the input length with
# sentinel -1 ids and zero rows; counts come back alongside.
# ---------------------------------------------------------------------------

@register_op("split_ids", stop_gradient=True)
def _split_ids(ctx, ins, attrs):
    """Partition ids across `num_shards` by modulo (the reference's hash
    dispatch). Out: one [N] padded id tensor per shard + [num_shards]
    counts; order within a shard is preserved."""
    # int32 id space (the framework runs without x64; ids >= 2**31 are
    # outside the supported vocab range)
    ids = _as_id32(ins["Ids"][0].reshape(-1))
    n = attrs["num_shards"]
    outs, counts = [], []
    for s in range(n):
        mask = (ids % n) == s
        cnt = jnp.sum(mask.astype(jnp.int32))
        pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
        scatter_pos = jnp.where(mask, pos, ids.shape[0])
        buf = jnp.full((ids.shape[0] + 1,), -1, jnp.int32)
        buf = buf.at[scatter_pos].set(ids)
        outs.append(buf[:-1])
        counts.append(cnt)
    return {"Out": outs, "Count": [jnp.stack(counts)]}


@register_op("merge_ids", stop_gradient=True)
def _merge_ids(ctx, ins, attrs):
    """≙ merge_ids_op: route per-shard row values back to the original id
    order. Ids [N] (the original query), per-shard padded ids + rows as
    produced by split_ids + a sharded lookup."""
    ids = _as_id32(ins["Ids"][0].reshape(-1))
    shard_ids = ins["X"]            # list of [N] padded id tensors
    shard_rows = ins["Rows"]        # list of [N, D] row values
    n = len(shard_ids)
    d = shard_rows[0].shape[-1]
    out = jnp.zeros((ids.shape[0], d), shard_rows[0].dtype)
    for s in range(n):
        mask = (ids % n) == s
        pos = jnp.cumsum(mask.astype(jnp.int32)) - 1   # index into shard
        gathered = shard_rows[s][jnp.maximum(pos, 0)]
        out = jnp.where(mask[:, None], gathered, out)
    return {"Out": [out]}


@register_op("lookup_sparse_table", stop_gradient=True)
def _lookup_sparse_table(ctx, ins, attrs):
    """≙ lookup_sparse_table_op: gather rows by id from a table shard;
    padded (-1) ids yield zero rows (the reference auto-grows unseen rows —
    static translation returns the init value 0)."""
    w = ins["W"][0]
    ids = _as_id32(ins["Ids"][0].reshape(-1))
    valid = ids >= 0
    safe = jnp.where(valid, ids, 0)
    rows = w[safe]
    return {"Out": [jnp.where(valid[:, None], rows, 0.0)]}
