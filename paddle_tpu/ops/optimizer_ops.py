"""Optimizer update ops.

≙ reference operators/{sgd,momentum,adam,adamax,adagrad,decayed_adagrad,
adadelta,rmsprop,ftrl,proximal_gd,proximal_adagrad}_op.cc — each optimizer is
an op consuming Param/Grad/accumulators and emitting updated values
(functional on TPU: the executor writes outputs back to the scope, with buffer
donation making the update in-place on device).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.registry import register_op
from ..framework.selected_rows import TracedSelectedRows


def _merge_sparse_rows(g: TracedSelectedRows):
    """Coalesce duplicate rows inside the trace (≙ math::scatter::MergeAdd,
    reference math/selected_rows_functor.cc). Returns (rows_u, values_u)
    where rows_u is SORTED and every entry UNIQUE: padding entries carry
    DISTINCT out-of-bounds indices (height, height+1, ...), so gather sites
    must clip, scatter sites must use mode='drop', and both may assert
    indices_are_sorted/unique_indices — on TPU that lets XLA drop the
    generic (serializing) scatter path, which round-4 profiling showed
    dominating the sparse-embedding train step."""
    rows_u, inv = jnp.unique(g.rows, return_inverse=True,
                             size=g.rows.shape[0], fill_value=g.height)
    vals_u = jnp.zeros((rows_u.shape[0],) + tuple(g.value.shape[1:]),
                       dtype=g.value.dtype).at[inv.reshape(-1)].add(g.value)
    # unique() pads the tail with `height` REPEATED — spread the padding
    # over distinct OOB indices (still sorted: the tail is the maximum)
    n = rows_u.shape[0]
    pad = rows_u >= g.height
    rows_u = jnp.where(pad, g.height + jnp.arange(n, dtype=rows_u.dtype),
                       rows_u)
    return rows_u, vals_u


def _gather_rows(x, rows, height):
    return x.at[jnp.clip(rows, 0, height - 1)].get(indices_are_sorted=True)


@register_op("sgd")
def _sgd(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    lr = ins["LearningRate"][0]
    if isinstance(g, TracedSelectedRows):
        # linear update: scatter-add handles duplicate rows directly
        # (≙ sgd_op.h SelectedRows kernel)
        return {"ParamOut": [p.at[g.rows].add(
            -(lr * g.value).astype(p.dtype), mode="drop")]}
    return {"ParamOut": [p - lr * g.astype(p.dtype)]}


@register_op("momentum")
def _momentum(ctx, ins, attrs):
    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    lr = ins["LearningRate"][0]
    mu = attrs["mu"]
    if isinstance(g, TracedSelectedRows):
        # ≙ momentum_op.h SparseMomentumFunctor — NOT lazy: the reference
        # decays velocity for every row (rows absent from the grad see g=0),
        # so only the gradient arrives sparse; the apply is table-wide.
        # (Unlike adam, momentum has no lazy reference mode — freezing
        # untouched rows would silently change training results.)
        rows, g_rows = _merge_sparse_rows(g)
        flags = dict(mode="drop", unique_indices=True,
                     indices_are_sorted=True)
        v_out = (mu * v).at[rows].add(g_rows.astype(v.dtype), **flags)
        if attrs.get("use_nesterov", False):
            # dense form p - (g + mu*v_out)*lr with g zero off-rows
            p_out = (p - lr * mu * v_out).at[rows].add(
                -(lr * g_rows).astype(p.dtype), **flags)
        else:
            p_out = p - lr * v_out
        return {"ParamOut": [p_out], "VelocityOut": [v_out]}
    v_out = mu * v + g
    if attrs.get("use_nesterov", False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": [p_out], "VelocityOut": [v_out]}


@register_op("adam")
def _adam(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m, v = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    lr = ins["LearningRate"][0]
    b1, b2, eps = attrs["beta1"], attrs["beta2"], attrs["epsilon"]
    if isinstance(g, TracedSelectedRows):
        # ≙ adam_op.h SparseAdamFunctor (lazy mode): only looked-up rows of
        # param and both moments move; beta pows advance globally.
        from ..core import flags as _flags
        table_bytes = int(np.prod(p.shape)) * p.dtype.itemsize
        if table_bytes <= _flags.get_flag("sparse_dense_apply_max_bytes"):
            # dense-MASKED lazy apply: scatter-add the raw duplicate rows
            # (no sort — round-4 profiling: the merge's 160k-id sort alone
            # is ~12 ms on a v5e while full-table elementwise passes over
            # a sub-GB table are ~1-4 ms), then update under a touched-row
            # mask. Semantics identical to the merged-rows path: untouched
            # rows keep stale moments and do not move; duplicate grads sum
            # BEFORE the nonlinear update.
            g_sum = jnp.zeros(p.shape, g.value.dtype).at[g.rows].add(
                g.value, mode="drop")
            touched = jnp.zeros((p.shape[0],), jnp.bool_).at[g.rows].set(
                True, mode="drop")[:, None]
            m_new = b1 * m + (1 - b1) * g_sum
            v_new = b2 * v + (1 - b2) * jnp.square(g_sum)
            lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
            p_new = p - lr_t * m_new / (jnp.sqrt(v_new) + eps)
            return {"ParamOut": [jnp.where(touched, p_new.astype(p.dtype),
                                           p)],
                    "Moment1Out": [jnp.where(touched,
                                             m_new.astype(m.dtype), m)],
                    "Moment2Out": [jnp.where(touched,
                                             v_new.astype(v.dtype), v)],
                    "Beta1PowOut": [b1p * b1], "Beta2PowOut": [b2p * b2]}
        rows, g_rows = _merge_sparse_rows(g)
        m_rows = b1 * _gather_rows(m, rows, g.height) + (1 - b1) * g_rows
        v_rows = (b2 * _gather_rows(v, rows, g.height)
                  + (1 - b2) * jnp.square(g_rows))
        lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
        p_rows = _gather_rows(p, rows, g.height) \
            - lr_t * m_rows / (jnp.sqrt(v_rows) + eps)
        flags = dict(mode="drop", unique_indices=True,
                     indices_are_sorted=True)
        return {"ParamOut": [p.at[rows].set(p_rows.astype(p.dtype),
                                            **flags)],
                "Moment1Out": [m.at[rows].set(m_rows.astype(m.dtype),
                                              **flags)],
                "Moment2Out": [v.at[rows].set(v_rows.astype(v.dtype),
                                              **flags)],
                "Beta1PowOut": [b1p * b1], "Beta2PowOut": [b2p * b2]}
    m_out = b1 * m + (1 - b1) * g
    v_out = b2 * v + (1 - b2) * jnp.square(g)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    p_out = p - lr_t * m_out / (jnp.sqrt(v_out) + eps)
    return {"ParamOut": [p_out], "Moment1Out": [m_out], "Moment2Out": [v_out],
            "Beta1PowOut": [b1p * b1], "Beta2PowOut": [b2p * b2]}


@register_op("adamax")
def _adamax(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m, inf = ins["Moment"][0], ins["InfNorm"][0]
    b1p = ins["Beta1Pow"][0]
    lr = ins["LearningRate"][0]
    b1, b2, eps = attrs["beta1"], attrs["beta2"], attrs["epsilon"]
    m_out = b1 * m + (1 - b1) * g
    inf_out = jnp.maximum(b2 * inf, jnp.abs(g))
    p_out = p - (lr / (1 - b1p)) * (m_out / (inf_out + eps))
    return {"ParamOut": [p_out], "MomentOut": [m_out], "InfNormOut": [inf_out],
            "Beta1PowOut": [b1p * b1]}


@register_op("adagrad")
def _adagrad(ctx, ins, attrs):
    p, g, mom = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    lr = ins["LearningRate"][0]
    eps = attrs.get("epsilon", 1e-6)
    mom_out = mom + jnp.square(g)
    p_out = p - lr * g / (jnp.sqrt(mom_out) + eps)
    return {"ParamOut": [p_out], "MomentOut": [mom_out]}


@register_op("decayed_adagrad")
def _decayed_adagrad(ctx, ins, attrs):
    p, g, mom = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    lr = ins["LearningRate"][0]
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    mom_out = decay * mom + (1 - decay) * jnp.square(g)
    p_out = p - lr * g / (jnp.sqrt(mom_out) + eps)
    return {"ParamOut": [p_out], "MomentOut": [mom_out]}


@register_op("adadelta")
def _adadelta(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    avg_sq_g, avg_sq_u = ins["AvgSquaredGrad"][0], ins["AvgSquaredUpdate"][0]
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    g2 = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((avg_sq_u + eps) / (g2 + eps)) * g
    u2 = rho * avg_sq_u + (1 - rho) * jnp.square(update)
    return {"ParamOut": [p + update], "AvgSquaredGradOut": [g2],
            "AvgSquaredUpdateOut": [u2]}


@register_op("rmsprop")
def _rmsprop(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    ms, mom = ins["MeanSquare"][0], ins["Moment"][0]
    lr = ins["LearningRate"][0]
    rho = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    mu = attrs.get("momentum", 0.0)
    centered = attrs.get("centered", False)
    ms_out = rho * ms + (1 - rho) * jnp.square(g)
    if centered:
        mg = ins["MeanGrad"][0]
        mg_out = rho * mg + (1 - rho) * g
        denom = ms_out - jnp.square(mg_out) + eps
    else:
        mg_out = None
        denom = ms_out + eps
    mom_out = mu * mom + lr * g / jnp.sqrt(denom)
    out = {"ParamOut": [p - mom_out], "MeanSquareOut": [ms_out],
           "MomentOut": [mom_out]}
    if centered:
        out["MeanGradOut"] = [mg_out]
    return out


@register_op("ftrl")
def _ftrl(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    sq, lin = ins["SquaredAccumulator"][0], ins["LinearAccumulator"][0]
    lr = ins["LearningRate"][0]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    power = attrs.get("lr_power", -0.5)
    new_sq = sq + jnp.square(g)
    if power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -power) - jnp.power(sq, -power)) / lr
    lin_out = lin + g - sigma * p
    if power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = jnp.power(new_sq, -power) / lr + 2 * l2
    pre = jnp.clip(lin_out, -l1, l1) - lin_out
    p_out = pre / denom
    return {"ParamOut": [p_out], "SquaredAccumOut": [new_sq],
            "LinearAccumOut": [lin_out]}


@register_op("proximal_gd")
def _proximal_gd(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    lr = ins["LearningRate"][0]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    prox = p - lr * g
    p_out = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
             / (1.0 + lr * l2))
    return {"ParamOut": [p_out]}


@register_op("proximal_adagrad")
def _proximal_adagrad(ctx, ins, attrs):
    p, g, mom = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    lr = ins["LearningRate"][0]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    mom_out = mom + jnp.square(g)
    adapted_lr = lr / jnp.sqrt(mom_out)
    prox = p - adapted_lr * g
    p_out = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - adapted_lr * l1, 0.0)
             / (1.0 + adapted_lr * l2))
    return {"ParamOut": [p_out], "MomentOut": [mom_out]}


@register_op("lamb")
def _lamb(ctx, ins, attrs):
    """LAMB — TPU-era large-batch optimizer (new capability beyond the
    reference's 2018 set; used for big-batch ResNet/BERT runs)."""
    p, g = ins["Param"][0], ins["Grad"][0]
    m, v = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    lr = ins["LearningRate"][0]
    b1, b2, eps = attrs["beta1"], attrs["beta2"], attrs["epsilon"]
    wd = attrs.get("weight_decay", 0.0)
    m_out = b1 * m + (1 - b1) * g
    v_out = b2 * v + (1 - b2) * jnp.square(g)
    m_hat = m_out / (1 - b1p)
    v_hat = v_out / (1 - b2p)
    update = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    u_norm = jnp.sqrt(jnp.sum(jnp.square(update)))
    trust = jnp.where(jnp.logical_and(p_norm > 0, u_norm > 0),
                      p_norm / u_norm, 1.0)
    return {"ParamOut": [p - lr * trust * update], "Moment1Out": [m_out],
            "Moment2Out": [v_out], "Beta1PowOut": [b1p * b1],
            "Beta2PowOut": [b2p * b2]}
