"""Static memory planner: liveness-driven op scheduling, interference-graph
buffer coloring, and the remat-vs-stash search.

ROADMAP item 2's planning half, standing on the two sensor layers built
for it: the r13 dataflow analysis (whole-program lifetimes with the
backward-region rule, the interference graph, and the always-on
`buffer-reuse-race`/`buffer-war-race` detectors that make liveness-driven
reuse *verifiable*) and the r17 measured memory census
(`Executor.memory_census()` + the ledger accounting identity that proves
where every byte went). Three cooperating passes over a CLONE of the
program, applied by `memory_plan_pass` (and therefore under the pass
sanitizer, so every apply is proven race- and invariant-free):

1. **Liveness-minimizing scheduling** (`schedule_block`): reorder block
   0's ops within the def-use partial order — greedy list scheduling that
   prefers the ready op freeing the most transient bytes — to shrink the
   static peak-live estimate. The backward-region rule is respected (a
   forward-segment value stays live until its region executes, so moving
   segment ops never "frees" them early); collectives, RNG ops, and
   control-flow binders keep their relative order (the r13
   `collective-order` contract and the seed stream depend on it). Kept
   only when the predicted peak actually improves.

2. **Interference-graph buffer coloring** (`color_buffer_slots`):
   transient vars of one shape class (same resolved shape + dtype) whose
   live intervals are disjoint get one shared `Variable.buffer_slot` id —
   the plan the r13 detectors verify on every sanitized apply (two
   interfering vars in one slot = `buffer-reuse-race` BY NAME). XLA's
   buffer assignment realizes the sharing inside the compiled step; the
   slot table is the named prediction of the bytes it gives back.

3. **Remat-vs-stash search** (`search_remat`): Checkmate-style
   segmentation of the `vjp_region` forward — candidate (segment-count,
   checkpoint-policy) plans are priced with the ONE analytic cost model
   (`costs.op_cost_flops_bytes` roofline for the recompute seconds,
   declared-shape liveness for the stash bytes freed), and the best
   predicted peak whose recompute fits the step-time budget wins. The
   chosen plan is EXECUTABLE: `remat_segments` makes
   `lowering.run_vjp_region` run the forward as a chain of per-segment
   `jax.checkpoint` functions, so the backward recomputes one segment's
   activations at a time instead of stashing all of them. For pipeline
   programs the same search runs per STAGE against the 1F1B stash census
   (`pipeline.schedule_census`) — the engine's stage-granular
   checkpointing is one point on the curve; the report says whether each
   stage's recompute pays for its stash at the budget.

`plan_report()` emits the whole decision record: the slot table, the
predicted peak before/after, and the per-stage remat decisions —
`tools/bench_mem.py --plan` commits the MEASURED census deltas next to it
(BENCH_MEMPLAN_r18.json). Kill switch: PTPU_MEMORY_PLAN=0 (in the
executor's compile cache key). docs/static_analysis.md carries the
scheduling rule, the coloring invariant, and the search's acceptance
contract.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..core.enforce import InvalidArgumentError, enforce
from . import dataflow as _dataflow
from .program import Program
from .registry import lookup_effect_rule

__all__ = [
    "MemoryPlanPass", "color_buffer_slots", "plan_program", "plan_report",
    "schedule_block", "search_remat",
]

#: op types whose outputs a `dots_saveable` checkpoint policy keeps
#: stashed (MXU results — expensive to recompute); everything else is
#: recomputed from the segment boundary during the backward
_DOT_OPS = frozenset({"mul", "matmul", "conv2d", "conv3d",
                      "conv2d_transpose", "conv3d_transpose",
                      "depthwise_conv2d", "dynamic_lstm", "fused_lstm",
                      "dynamic_gru", "fused_gru", "lookup_table"})

#: remat candidates: (segment count, jax.checkpoint policy name or None
#: for full recompute). Segment counts are capped by the region length.
_REMAT_CANDIDATES: Tuple[Tuple[int, Optional[str]], ...] = (
    (2, None), (3, None), (4, None), (6, None), (8, None),
    (2, "dots_saveable"), (4, "dots_saveable"), (8, "dots_saveable"),
)

#: the CSE-able execution mode's candidates: with prevent_cse=False XLA
#: may fold any recompute that would cost wall-clock back into the
#: forward, so the plan is a liveness HINT more than a recompute
#: mandate — measured returns decay past a handful of segments (the
#: boundary overhead and partial CSE eat them; BENCH_MEMPLAN_r18.json
#: carries the curve), so the shallow cuts are the honest candidate set
_REMAT_CANDIDATES_CSEABLE: Tuple[Tuple[int, Optional[str]], ...] = (
    (2, None), (3, None), (4, None),
)


# the ONE declared-shape pricing rule, shared with peak_live_bytes
_var_bytes = _dataflow.declared_var_bytes


def _transient(block, name: str) -> bool:
    v = block.vars.get(name)
    return v is not None and not v.persistable and not v.is_data


# ---------------------------------------------------------------------------
# 1. liveness-minimizing scheduling
# ---------------------------------------------------------------------------


def _ordered_chain_member(block, op) -> bool:
    """Ops whose RELATIVE order the scheduler must not change: collectives
    (the r13 collective-order contract — a reordered pp_send/dp_grad_comm
    is a static deadlock on some shard), RNG draws (the seed stream folds
    per execution order), control-flow / TensorArray binders (their
    sub-block environment is stateful), and region ops themselves."""
    from .analysis import _SUB_KEYS, INFER_WAIVED
    if op.type in INFER_WAIVED or op.type in _dataflow.REGION_OPS:
        return True
    if any(k in op.attrs for k in _SUB_KEYS):
        return True
    rule = lookup_effect_rule(op.type)
    if rule is None:
        return False
    eff = _dataflow.op_effects(op)
    return bool(eff.collective_axes or eff.rng)


def _constraint_graph(block):
    """(succ, pred) adjacency over op indices: RAW/WAR/WAW name
    dependencies, the ordered-chain edges, and the region containment
    edges (every forward-segment op precedes its region op; segment ops
    keep their relative order — the region runner replays them in index
    order)."""
    n = len(block.ops)
    succ: List[Set[int]] = [set() for _ in range(n)]
    pred: List[Set[int]] = [set() for _ in range(n)]

    def edge(a: int, b: int):
        if a != b and b not in succ[a]:
            succ[a].add(b)
            pred[b].add(a)

    last_writer: Dict[str, int] = {}
    readers_since: Dict[str, List[int]] = {}
    chain_prev = None
    for i, op in enumerate(block.ops):
        for nm in op.input_names():
            if nm in last_writer:
                edge(last_writer[nm], i)
            readers_since.setdefault(nm, []).append(i)
        for nm in op.output_names():
            if nm in last_writer:
                edge(last_writer[nm], i)          # WAW: writer order
            for r in readers_since.get(nm, ()):
                edge(r, i)                        # WAR: readers first
            last_writer[nm] = i
            readers_since[nm] = []
        if _ordered_chain_member(block, op):
            if chain_prev is not None:
                edge(chain_prev, i)
            chain_prev = i
    for ridx, op in enumerate(block.ops):
        if op.type not in _dataflow.REGION_OPS:
            continue
        seg = [i for i in op.attrs.get("fwd_ops", ())
               if isinstance(i, (int, np.integer)) and 0 <= i < n]
        for a, b in zip(seg, seg[1:]):
            edge(a, b)                            # keep segment order
        for i in seg:
            edge(i, ridx)                         # segment before region
    return succ, pred


def schedule_block(block, nominal_batch: int = 8) -> Optional[List[int]]:
    """A liveness-minimizing valid topological order of `block`'s ops
    (old indices in new execution order), or None when the block is not
    schedulable (a pipeline region pins its stage index lists to the
    partitioner's order). Greedy list scheduling: among ready ops, pick
    the one with the best freed-minus-allocated transient bytes; ties
    break on the original index, so an already-optimal program comes
    back unchanged."""
    n = len(block.ops)
    if n <= 2 or any(op.type == "pp_pipeline_region" for op in block.ops):
        return None
    succ, pred = _constraint_graph(block)

    # remaining-reader counts, with every region op counted as a reader
    # of everything its forward segment touches (the backward-region
    # rule: those values are backward inputs, so scheduling can never
    # free them before the region)
    remaining: Dict[str, int] = {}
    for i, op in enumerate(block.ops):
        for nm in op.input_names():
            remaining[nm] = remaining.get(nm, 0) + 1
        if op.type in _dataflow.REGION_OPS:
            for j in op.attrs.get("fwd_ops", ()):
                if isinstance(j, (int, np.integer)) and 0 <= j < n:
                    fop = block.ops[j]
                    for nm in set(fop.output_names() + fop.input_names()):
                        remaining[nm] = remaining.get(nm, 0) + 1

    sizes = {nm: (_var_bytes(block, nm, nominal_batch)
                  if _transient(block, nm) else 0)
             for op in block.ops
             for nm in op.input_names() + op.output_names()}

    def score(i: int) -> Tuple[int, int]:
        op = block.ops[i]
        alloc = sum(sizes.get(nm, 0) for nm in set(op.output_names()))
        freed = sum(sizes.get(nm, 0) for nm in set(op.input_names())
                    if remaining.get(nm, 0) == 1)
        return (alloc - freed, i)

    indeg = [len(p) for p in pred]
    ready = sorted(i for i in range(n) if indeg[i] == 0)
    order: List[int] = []
    while ready:
        i = min(ready, key=score)
        ready.remove(i)
        order.append(i)
        op = block.ops[i]
        for nm in op.input_names():
            if nm in remaining:
                remaining[nm] -= 1
        if op.type in _dataflow.REGION_OPS:
            for j in op.attrs.get("fwd_ops", ()):
                if isinstance(j, (int, np.integer)) and 0 <= j < n:
                    fop = block.ops[j]
                    for nm in set(fop.output_names() + fop.input_names()):
                        if nm in remaining:
                            remaining[nm] -= 1
        for j in succ[i]:
            indeg[j] -= 1
            if indeg[j] == 0:
                ready.append(j)
    enforce(len(order) == n,
            f"memory_plan scheduler produced a partial order "
            f"({len(order)}/{n} ops) — cyclic constraint graph?",
            exc=InvalidArgumentError)
    return order if order != list(range(n)) else None


def _apply_order(block, order: List[int]):
    """Reorder block.ops to `order` (old indices in new positions) and
    remap every region op's recorded fwd_ops indices."""
    remap = {old: new for new, old in enumerate(order)}
    block.ops = [block.ops[i] for i in order]
    for op in block.ops:
        if op.type in _dataflow.REGION_OPS:
            op.attrs["fwd_ops"] = sorted(
                remap[i] for i in op.attrs.get("fwd_ops", ())
                if isinstance(i, (int, np.integer)) and i in remap)
    block.program._bump()


# ---------------------------------------------------------------------------
# 2. interference-graph buffer coloring
# ---------------------------------------------------------------------------


def color_buffer_slots(block, protected: Sequence[str] = (),
                       nominal_batch: int = 8) -> List[Dict]:
    """Assign shared `Variable.buffer_slot` ids to compatible transient
    vars: one shape class (resolved shape + dtype), strictly disjoint
    live intervals (greedy interval coloring). Only colors with >= 2
    members are materialized — a slot table row per shared buffer, each
    one a named prediction of bytes XLA's assignment gives back. The r13
    `buffer-reuse-race` detector is the soundness proof: the pass
    sanitizer re-verifies the whole program after the pass, so a
    mis-colored pair fails the apply BY NAME instead of racing at
    runtime."""
    lifetimes = _dataflow.var_lifetimes(block)
    writers: Dict[str, int] = {}
    for op in block.ops:
        for nm in op.output_names():
            writers[nm] = writers.get(nm, 0) + 1
    skip = set(protected)
    classes: Dict[Tuple, List[Tuple[int, int, str]]] = {}
    for name, (s, e) in lifetimes.items():
        v = block.vars.get(name)
        if (v is None or v.persistable or v.is_data or v.shape is None
                or name in skip or writers.get(name, 0) != 1
                or getattr(v, "buffer_slot", None) is not None):
            continue
        key = (tuple(v.shape), str(np.dtype(v.dtype)))
        classes.setdefault(key, []).append((s, e, name))

    table: List[Dict] = []
    for key, items in sorted(classes.items(), key=lambda kv: repr(kv[0])):
        if len(items) < 2:
            continue
        items.sort()
        colors: List[Dict] = []     # {end, members}
        for s, e, name in items:
            placed = None
            for c in colors:
                if c["end"] < s:     # STRICT: the detector's WAR boundary
                    placed = c       # case (write at the last read) needs
                    break            # a serializing copy we don't emit
            if placed is None:
                placed = {"end": e, "members": []}
                colors.append(placed)
            placed["end"] = e
            placed["members"].append(name)
        shape, dtype = key
        for k, c in enumerate(colors):
            if len(c["members"]) < 2:
                continue
            # block-scoped id: an identical shape class in two blocks must
            # NOT form one cross-block slot group (the r18 cross-binder
            # detector rightly flags a sub-block var sharing a slot with
            # a parent var live across its binder)
            slot = (f"b{block.idx}:{dtype}:"
                    + "x".join(str(d) for d in shape) + f"#{k}")
            for name in c["members"]:
                block.vars[name].buffer_slot = slot
            table.append({
                "slot": slot,
                "block": block.idx,
                "vars": list(c["members"]),
                "bytes": _var_bytes(block, c["members"][0], nominal_batch),
                "reuses": len(c["members"]) - 1,
            })
    if table:
        block.program._bump()
    return table


# ---------------------------------------------------------------------------
# 3. remat-vs-stash search
# ---------------------------------------------------------------------------


def _region_live_out(block, ridx: int, seg: Sequence[int],
                     protected: Set[str]) -> Set[str]:
    """Names the region must keep publishing: read by any op outside the
    consumed forward segment at/after the region's execution point,
    persistable values written inside the segment (moving BN stats), and
    the caller's protected set (fetch targets the planner can see).
    The sibling of transpiler.memory_optimization._liveness_after_region
    — run-time fetch names are ADDED by the region runner, so a fetch the
    planner never saw still comes out of its segment."""
    consumed = set(seg)
    live: Set[str] = set(protected)
    for j, op in enumerate(block.ops):
        if j == ridx or j in consumed:
            continue
        if j > min(seg):
            live |= set(op.input_names())
    for j in seg:
        for name in block.ops[j].output_names():
            v = block.vars.get(name)
            if v is not None and getattr(v, "persistable", False):
                live.add(name)
    return live


def _candidate_cuts(costs: List[float], k: int) -> List[Tuple[int, int]]:
    from .passes import _balanced_partition
    return _balanced_partition(costs, k)


def search_remat(block, region_op, *, nominal_batch: int = 8,
                 protected: Sequence[str] = (),
                 time_budget_s: Optional[float] = None,
                 time_budget_frac: float = 0.02,
                 prevent_cse: bool = False,
                 stash_to_host: bool = False) -> Dict:
    """Search the remat-vs-stash curve of ONE vjp_region and apply the
    winner. Candidates: `_REMAT_CANDIDATES` (segment count x checkpoint
    policy) plus "stash" (no remat — keep every activation, the status
    quo). Each candidate is priced with the analytic model:

      stash_freed  declared-shape bytes of segment-internal values that
                   stop being carried to the backward (non-boundary,
                   non-published; under `dots_saveable` the MXU outputs
                   stay stashed and only the cheap-to-recompute rest is
                   freed)
      extra_s      roofline seconds of the recomputed forward ops (full
                   segment for the default policy, the non-dot subset
                   under `dots_saveable`)

    With `stash_to_host` a THIRD candidate class competes (ISSUE r23:
    BuildStrategy.memory_plan_stash_to_host): keep every activation but
    park the stash in the pinned host pool (framework/offload.py),
    priced on the PCIe roofline (`costs.V5E_PCIE_BPS`) — freed bytes are
    the whole stash minus a two-deep resident window (the in-flight d2h
    at the forward edge plus the h2d restore beside its backward
    consumer), and the round-trip must hide inside ~3x the forward's
    roofline (forward + ~2x backward = the overlap window). Unlike the
    CSE-able recompute bound, the PCIe transfer is real wire, so the
    window ALWAYS gates this candidate.

    The best stash_freed whose extra_s fits the budget wins; the budget
    is `time_budget_s` when the caller measured a real step (CPU-mesh
    benches, where dispatch dominates the roofline) and
    `time_budget_frac` x the program's roofline step otherwise. Returns
    the decision record (chosen plan + every candidate's prediction);
    sets `remat_segments`/`remat_policy`/`live_out` on the region op when
    a remat plan wins, `stash_to_host`/`live_out` when the host stash
    wins (ADVISORY on this backend: jit consumes the whole stash at
    dispatch, so the streamed per-value round-trip is priced and
    recorded — the same discipline as the planner's pp stage decisions —
    while the TPU lowering through the shared transfer stream remains
    ROADMAP item 5(a); the record says so via `executed`)."""
    from .costs import op_cost_flops_bytes, op_time_cost
    from .lowering import remat_boundaries

    ridx = block.ops.index(region_op)
    seg = [i for i in region_op.attrs.get("fwd_ops", ())
           if isinstance(i, (int, np.integer)) and 0 <= i < len(block.ops)]
    record: Dict = {"region": ridx, "chosen": "stash", "segments": 0,
                    "policy": None, "stash_freed_bytes": 0,
                    "extra_seconds_bound": 0.0, "candidates": []}
    if len(seg) < 4:
        record["skipped"] = "region too short to segment"
        return record
    if any(block.ops[i].type == "lookup_table"
           and block.ops[i].attrs.get("is_sparse") for i in seg):
        record["skipped"] = ("sparse embedding lookups need the "
                            "un-segmented trace (selected-rows grads)")
        return record
    coll = sorted({block.ops[i].type for i in seg
                   if _dataflow.op_effects(block.ops[i]).collective_axes})
    if coll:
        # recomputing a checkpointed segment re-issues every collective
        # inside it (a tp_allreduce replayed in the backward is real
        # extra wire the compute-only cost model cannot price) —
        # measured on the tp2 bench cell as a net regression, so
        # collective-bearing forwards keep the stash
        record["skipped"] = (f"forward segment issues collectives "
                             f"({coll[:4]}): recompute would re-issue "
                             f"them on the wire")
        return record

    live_out = _region_live_out(block, ridx, seg, set(protected))
    live_out.add(region_op.attrs["loss"])
    out_need = (live_out & {n for i in seg
                            for n in block.ops[i].output_names()}) \
        | {region_op.attrs["loss"]}

    op_costs = [op_time_cost(*op_cost_flops_bytes(block.ops[i], block,
                                                  nominal_batch))
                for i in seg]
    total_s = sum(op_costs)
    if time_budget_s is None:
        # roofline-step reference: forward + ~2x backward + update — the
        # conservative TPU-faithful budget base (callers on a
        # dispatch-dominated mesh pass the measured step instead)
        from .costs import program_flops_bytes
        step_s = program_flops_bytes(block.program,
                                     nominal_batch)["roofline_s"]
        time_budget_s = time_budget_frac * max(step_s, 1e-12)
    record["time_budget_s"] = time_budget_s

    # the stash the un-segmented region carries to the backward: every
    # transient the segment produces and does not publish
    stash_vars = [
        (nm, _var_bytes(block, nm, nominal_batch))
        for i in seg for nm in set(block.ops[i].output_names())
        if _transient(block, nm) and nm not in out_need]
    stash_total = sum(b for _, b in stash_vars)
    cost_at = {i: c for i, c in zip(seg, op_costs)}

    best = None
    candidates = (_REMAT_CANDIDATES if prevent_cse
                  else _REMAT_CANDIDATES_CSEABLE)
    record["prevent_cse"] = bool(prevent_cse)
    for k, policy in candidates:
        if k > len(seg):
            continue
        bounds = _candidate_cuts(op_costs, k)
        seg_lists = [seg[a:b] for a, b in bounds]
        boundaries = remat_boundaries(
            [[block.ops[i] for i in lst] for lst in seg_lists], out_need)
        carried = set().union(*[set(b) for b in boundaries])
        freed = 0
        extra = 0.0
        internal = []               # per-segment recompute working set
        for lst in seg_lists:
            seg_internal = 0
            for i in lst:
                op = block.ops[i]
                if policy == "dots_saveable" and op.type in _DOT_OPS:
                    continue        # stays stashed, never recomputed
                extra += cost_at[i]
                for nm in set(op.output_names()):
                    if nm in carried or not _transient(block, nm):
                        continue
                    nb = _var_bytes(block, nm, nominal_batch)
                    freed += nb
                    seg_internal += nb
            internal.append(seg_internal)
        # predicted stash after segmentation: what stays carried to the
        # backward (stash_total minus the freed internals — boundary
        # values stay counted once, inside stash_total) plus the LARGEST
        # segment's internals twice over, for its recompute + backward
        # window (value + cotangent)
        predicted_stash = (stash_total - freed) \
            + 2 * max(internal, default=0)
        # prevent_cse=False: the recompute is advisory (XLA folds back
        # whatever would cost wall-clock), so `extra` is an upper bound
        # and the budget never rejects; prevent_cse=True mandates the
        # recompute and the roofline delta gates it
        cand = {"segments": k, "policy": policy,
                "stash_freed_bytes": int(freed),
                "predicted_stash_bytes": int(predicted_stash),
                "extra_seconds_bound": float(extra),
                "boundary_vars": [len(b) for b in boundaries],
                "fits_budget": (extra <= time_budget_s
                                if prevent_cse else True)}
        record["candidates"].append(cand)
        if cand["fits_budget"] and predicted_stash < stash_total and (
                best is None
                or predicted_stash < best["predicted_stash_bytes"]):
            best = dict(cand, seg_lists=seg_lists)
    if stash_to_host and stash_total > 0:
        from .costs import V5E_PCIE_BPS
        biggest = max((b for _, b in stash_vars), default=0)
        resident = min(stash_total, 2 * biggest)
        transfer_s = 2.0 * stash_total / V5E_PCIE_BPS
        window = 3.0 * total_s
        cand = {"segments": 0, "policy": "stash_to_host",
                "stash_freed_bytes": int(stash_total - resident),
                "predicted_stash_bytes": int(resident),
                "extra_seconds_bound": float(max(0.0,
                                                 transfer_s - window)),
                "pcie_transfer_s": float(transfer_s),
                "overlap_window_s": float(window),
                "fits_budget": transfer_s <= window}
        record["candidates"].append(cand)
        if cand["fits_budget"] and resident < stash_total and (
                best is None
                or resident < best["predicted_stash_bytes"]):
            best = dict(cand, seg_lists=None)
    record["stash_bytes_unsegmented"] = int(stash_total)
    if best is None or best["stash_freed_bytes"] <= 0:
        return record

    if best["policy"] == "stash_to_host":
        region_op.attrs["stash_to_host"] = True
        region_op.attrs["live_out"] = sorted(live_out)
        block.program._bump()
        record.update(chosen="stash_to_host", segments=0,
                      policy="stash_to_host",
                      stash_freed_bytes=best["stash_freed_bytes"],
                      predicted_stash_bytes=best["predicted_stash_bytes"],
                      extra_seconds_bound=best["extra_seconds_bound"],
                      executed="advisory")
        return record

    region_op.attrs["remat_segments"] = [list(lst)
                                         for lst in best["seg_lists"]]
    if best["policy"]:
        region_op.attrs["remat_policy"] = best["policy"]
    else:
        region_op.attrs.pop("remat_policy", None)
    region_op.attrs["remat_prevent_cse"] = bool(prevent_cse)
    region_op.attrs["live_out"] = sorted(live_out)
    block.program._bump()
    record.update(chosen="remat", segments=best["segments"],
                  policy=best["policy"],
                  stash_freed_bytes=best["stash_freed_bytes"],
                  predicted_stash_bytes=best["predicted_stash_bytes"],
                  extra_seconds_bound=best["extra_seconds_bound"])
    return record


def _pp_stage_decisions(program, region_op, *, nominal_batch: int = 8,
                        time_budget_s: Optional[float] = None,
                        time_budget_frac: float = 0.02) -> List[Dict]:
    """The per-STAGE remat-vs-stash curve of a pipeline region. The 1F1B
    engine already executes the "recompute" point (stage-granular
    checkpointing: the backward replays the stage forward from the
    stashed boundary input — parallel/pipeline.py run_pp_region); this
    search prices the alternative per stage: KEEPING the stage's
    activations for every in-flight microbatch costs
    act_stash_depth x stage activation bytes, recomputing costs
    M x stage-forward roofline seconds per step. The report names the
    winner at the budget; a "keep" verdict is advisory (the engine's
    executed point stays recompute — flagged so the gap is explicit)."""
    from ..parallel.pipeline import schedule_census
    from .costs import op_cost_flops_bytes, op_time_cost, \
        program_flops_bytes

    block = program.global_block()
    m = int(region_op.attrs["num_microbatches"])
    k = int(region_op.attrs["num_stages"])
    sched = schedule_census(region_op.attrs["schedule"], m, k)
    if time_budget_s is None:
        step_s = program_flops_bytes(program, nominal_batch)["roofline_s"]
        time_budget_s = time_budget_frac * max(step_s, 1e-12)
    mb_rows = max(1, nominal_batch // m)
    decisions = []
    for si, idxs in enumerate(region_op.attrs["stages"]):
        ops = [block.ops[i] for i in idxs if isinstance(i, (int,
                                                           np.integer))]
        fwd_s = sum(op_time_cost(*op_cost_flops_bytes(op, block, mb_rows))
                    for op in ops)
        act_bytes = sum(_var_bytes(block, nm, mb_rows)
                        for op in ops for nm in set(op.output_names())
                        if _transient(block, nm))
        depth = int(sched["peak_stash_per_stage"][si]) or 1
        recompute_s = fwd_s * m      # one replay per microbatch backward
        keep_bytes = act_bytes * depth
        chosen = "recompute" if recompute_s <= time_budget_s or \
            keep_bytes == 0 else "keep"
        decisions.append({
            "stage": si, "executed": "recompute", "chosen": chosen,
            "advisory": chosen != "recompute",
            "keep_stash_bytes": int(keep_bytes),
            "recompute_extra_seconds": float(recompute_s),
            "stash_depth": depth,
        })
    return decisions


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------

#: program markers the planner's clone must carry forward — the executor's
#: placement/gate logic and the cost models read them off the FINAL program
_RIDE_MARKERS = ("_dp_comm_applied", "_pp_applied", "_pp_hidden",
                 "_pp_microbatches", "_pp_stages")


def plan_program(program: Program, *, protected: Sequence[str] = (),
                 nominal_batch: int = 8,
                 time_budget_s: Optional[float] = None,
                 time_budget_frac: float = 0.02,
                 schedule: bool = True, color: bool = True,
                 remat: bool = True,
                 remat_prevent_cse: bool = False,
                 stash_to_host: bool = False) -> Program:
    """Apply the full static memory plan to a CLONE of `program` (the
    caller's program is never mutated): scheduling, coloring, and the
    remat-vs-stash search, in that order. Idempotent (`
    _memory_plan_applied` marker); the decision record lands on the
    planned program as `_memory_plan_report` (see `plan_report`)."""
    if getattr(program, "_memory_plan_applied", False):
        return program
    from .analysis import peak_live_bytes
    out = program.clone()
    for marker in _RIDE_MARKERS:
        if hasattr(program, marker):
            setattr(out, marker, getattr(program, marker))
    block = out.global_block()
    before = peak_live_bytes(out, nominal_batch=nominal_batch)
    report: Dict = {
        "nominal_batch": nominal_batch,
        "predicted_peak_before": int(before["peak_transient_bytes"]),
        "schedule": {"reordered": False, "moved_ops": 0},
        "slots": [], "remat": None, "pp_stages": None,
    }

    if schedule:
        order = schedule_block(block, nominal_batch=nominal_batch)
        if order is not None:
            trial = peak_live_bytes  # evaluated on the mutated clone
            _apply_order(block, order)
            after_sched = trial(out, nominal_batch=nominal_batch)
            if after_sched["peak_transient_bytes"] \
                    < before["peak_transient_bytes"]:
                report["schedule"] = {
                    "reordered": True,
                    "moved_ops": sum(1 for new, old in enumerate(order)
                                     if new != old),
                    "predicted_peak": int(
                        after_sched["peak_transient_bytes"]),
                }
            else:
                # scheduling must never regress the estimate: restore
                inverse = [0] * len(order)
                for new, old in enumerate(order):
                    inverse[old] = new
                _apply_order(block, inverse)

    remat_records: List[Dict] = []
    if remat:
        for op in list(block.ops):
            if op.type == "vjp_region":
                remat_records.append(search_remat(
                    block, op, nominal_batch=nominal_batch,
                    protected=protected, time_budget_s=time_budget_s,
                    time_budget_frac=time_budget_frac,
                    prevent_cse=remat_prevent_cse,
                    stash_to_host=stash_to_host))
            elif op.type == "pp_pipeline_region":
                # exactly one per block (the partition pass enforces it)
                report["pp_stages"] = _pp_stage_decisions(
                    out, op, nominal_batch=nominal_batch,
                    time_budget_s=time_budget_s,
                    time_budget_frac=time_budget_frac)
        # the common single-region shape stays flat; multi-loss programs
        # (two vjp_regions over one trunk) report every region's decision
        report["remat"] = (remat_records[0] if len(remat_records) == 1
                          else None)
        if len(remat_records) > 1:
            report["remat_regions"] = remat_records

    if color:
        for b in out.blocks:
            report["slots"] += color_buffer_slots(
                b, protected=protected, nominal_batch=nominal_batch)

    after = peak_live_bytes(out, nominal_batch=nominal_batch)
    remat_saved = sum(
        max(0, rm.get("stash_bytes_unsegmented", 0)
            - rm.get("predicted_stash_bytes", 0))
        for rm in remat_records if rm.get("chosen") == "remat")
    # a winning stash-to-host decision is ADVISORY on this backend (see
    # search_remat): its freed bytes ride in a NAMED key instead of the
    # executed predicted_peak_after, so the prediction never claims a
    # reduction the runtime does not deliver
    host_stash_freed = sum(
        rm.get("stash_freed_bytes", 0) for rm in remat_records
        if rm.get("chosen") == "stash_to_host")
    if host_stash_freed:
        report["stash_to_host_freed_bytes"] = int(host_stash_freed)
    # slots are deliberately NOT subtracted here: coloring only pairs
    # strictly-disjoint lifetimes, which the max-live walk already never
    # counts together — the slot table names bytes XLA's assignment can
    # alias, not a further cut to this estimate
    report["predicted_peak_after"] = max(
        0, int(after["peak_transient_bytes"]) - remat_saved)
    report["predicted_reduction_bytes"] = (
        report["predicted_peak_before"] - report["predicted_peak_after"])
    report["n_slots"] = len(report["slots"])
    report["shared_vars"] = sum(len(r["vars"]) for r in report["slots"])
    out._memory_plan_applied = True
    out._memory_plan_report = report
    out._bump()
    return out


def plan_report(program: Program) -> Dict:
    """The decision record of a planned program: slot table, predicted
    peak before/after, remat-vs-stash choice (and the rejected
    candidates, each with its predicted bytes/seconds), per-stage
    pipeline decisions. Raises on an unplanned program — run
    memory_plan_pass (or plan_program) first."""
    enforce(getattr(program, "_memory_plan_applied", False),
            "plan_report: program carries no memory plan — apply "
            "memory_plan_pass first", exc=InvalidArgumentError)
    return dict(program._memory_plan_report)


from .passes import Pass, register_pass  # noqa: E402


@register_pass("memory_plan_pass")
class MemoryPlanPass(Pass):
    """The registered form of `plan_program` — running it through
    Pass.__call__ puts every apply under the pass sanitizer, so the r13
    buffer-reuse/WAR detectors re-verify the colored program and any
    violation is attributed to this pass BY NAME. attrs: protected
    (names the plan must keep addressable — fetch targets), nominal_batch,
    time_budget_s / time_budget_frac (the remat search's step-time
    budget), schedule / color / remat (per-pass toggles, default on)."""

    allowed_attrs = ("protected", "nominal_batch", "time_budget_s",
                     "time_budget_frac", "schedule", "color", "remat",
                     "remat_prevent_cse", "stash_to_host")

    def apply(self, program, scope=None):
        return plan_program(
            program,
            protected=self.attrs.get("protected", ()),
            nominal_batch=int(self.attrs.get("nominal_batch", 8)),
            time_budget_s=self.attrs.get("time_budget_s"),
            time_budget_frac=float(self.attrs.get("time_budget_frac",
                                                  0.02)),
            schedule=bool(self.attrs.get("schedule", True)),
            color=bool(self.attrs.get("color", True)),
            remat=bool(self.attrs.get("remat", True)),
            remat_prevent_cse=bool(self.attrs.get("remat_prevent_cse",
                                                  False)),
            stash_to_host=bool(self.attrs.get("stash_to_host", False)))
