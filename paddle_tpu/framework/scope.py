"""Scope: hierarchical name → value store.

≙ reference framework/scope.h:39 (Scope::Var/FindVar/NewScope/DropKids) and
framework/variable.h:26. Values are jax arrays (device-resident) or numpy
arrays; the executor moves them as needed.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..core.enforce import NotFoundError


class Scope:
    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, Any] = {}
        self._parent = parent
        self._kids: List["Scope"] = []

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids.clear()

    def set_var(self, name: str, value: Any):
        self._vars[name] = value

    def find_var(self, name: str):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s._parent
        return None

    def has_var(self, name: str) -> bool:
        return self.find_var(name) is not None

    def get(self, name: str):
        v = self.find_var(name)
        if v is None:
            raise NotFoundError(f"variable {name!r} not found in scope")
        return v

    def erase(self, name: str):
        self._vars.pop(name, None)

    def local_var_names(self) -> List[str]:
        return list(self._vars)

    def __contains__(self, name):
        return self.has_var(name)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def reset_global_scope():
    global _global_scope
    _global_scope = Scope()
