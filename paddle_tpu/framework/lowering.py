"""Block → jax function tracing.

This replaces the reference's op-by-op interpreting Executor hot loop
(reference: paddle/fluid/framework/executor.cc:321-340 "for op in ctx->ops_:
op->Run(scope, place)") with a single trace of the whole block into one jax
function, which XLA compiles and fuses. The `vjp_region` pseudo-op (appended by
backward.append_backward) is executed via jax.vjp — compiler-native source
transformation replacing the reference's per-op GradOpDescMaker pipeline
(reference python/paddle/fluid/backward.py:469).
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Set

import jax
import jax.numpy as jnp

from ..core import flags
from ..core.enforce import EnforceError, NotFoundError
from .program import Block, Operator
from .registry import LowerCtx, lookup_op

SEQLEN_SUFFIX = "@SEQLEN"
GRAD_SUFFIX = "@GRAD"

# Region op type -> runner(region_op, seg_indices, env, block, ctx). A
# region op consumes a recorded segment of forward ops (attrs["fwd_ops"])
# and executes it specially: vjp_region under jax.vjp (below);
# pp_pipeline_region under the pipeline schedule engine (registered by
# parallel/pipeline.py at import).
REGION_RUNNERS: Dict[str, Any] = {}


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


def _gather_inputs(op: Operator, env: Dict[str, Any]) -> Dict[str, List[Any]]:
    ins = {}
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            if n not in env:
                raise NotFoundError(
                    f"op {op.type!r} reads variable {n!r} (slot {slot!r}) "
                    f"which is not initialized — run the startup program or "
                    f"feed it")
            vals.append(env[n])
        ins[slot] = vals
    return ins


def _scatter_outputs(op: Operator, outs: Dict[str, List[Any]],
                     env: Dict[str, Any], block: Block):
    check_nan = flags.get_flag("check_nan_inf")
    for slot, names in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        for n, v in zip(names, vals):
            if v is None:
                continue
            try:
                var = block.var(n)
                if var.stop_gradient and not var.persistable:
                    v = jax.lax.stop_gradient(v)
            except NotFoundError:
                pass
            if check_nan and hasattr(v, "dtype") and jnp.issubdtype(
                    v.dtype, jnp.floating):
                _nan_guard(op.type, n, v)
            env[n] = v


def _nan_guard(op_type: str, name: str, value):
    """Debug-mode NaN/Inf scan (≙ FLAGS_check_nan_inf + CheckTensorNANOrInf,
    reference framework/operator.cc:651,726-736). Host callbacks are a
    CPU-debug facility — the tunneled TPU backend has no host send/recv, so
    the guard no-ops off-CPU (rerun under JAX_PLATFORMS=cpu to localize)."""
    if jax.default_backend() != "cpu":
        from ..ops.tensor_ops import _warn_guards_inactive
        _warn_guards_inactive()
        return
    bad = jnp.logical_not(jnp.all(jnp.isfinite(value)))

    def _report(bad_flag, op_type=op_type, name=name):
        if bool(bad_flag):
            raise FloatingPointError(
                f"NaN/Inf detected in output {name!r} of op {op_type!r}")

    jax.debug.callback(_report, bad)


def run_op(op: Operator, env: Dict[str, Any], block: Block, ctx: LowerCtx):
    opdef = lookup_op(op.type)
    ins = _gather_inputs(op, env)
    try:
        outs = opdef.lower(ctx, ins, op.attrs)
    except EnforceError:
        raise
    except Exception as e:  # re-raise with op context, keep traceback
        raise type(e)(f"[while lowering op {op.type!r} "
                      f"{op.inputs} -> {op.outputs}] {e}") from e
    _scatter_outputs(op, outs or {}, env, block)


def _ancestor_op_indices(block: Block, upto: int, roots: Set[str]) -> List[int]:
    """Indices (< upto) of ops needed to compute vars in `roots`
    (≙ _find_op_path_, reference python/paddle/fluid/backward.py:645)."""
    needed = set(roots)
    keep = []
    for i in range(upto - 1, -1, -1):
        op = block.ops[i]
        if needed & set(op.output_names()):
            keep.append(i)
            needed |= set(op.input_names())
    keep.reverse()
    return keep


def build_plan(block: Block):
    """Pre-scan the block into an execution plan.

    Ops consumed by a vjp_region execute *inside* jax.vjp; the region runs at
    the position of its earliest forward op so downstream consumers (metric
    ops etc.) see the forward values.
    """
    regions: Dict[int, list] = {}  # first_fwd_index -> [(region_op, seg), ...]
    consumed: Set[int] = set()
    region_ops: Set[int] = set()
    for i, op in enumerate(block.ops):
        if op.type in REGION_RUNNERS:
            seg = op.attrs["fwd_ops"]
            if not seg:
                continue
            # multiple regions may share the earliest forward op (two losses
            # over one trunk) — keep them all, in program order
            regions.setdefault(min(seg), []).append((op, list(seg)))
            consumed |= set(seg)
            region_ops.add(i)

    plan = []
    for i, op in enumerate(block.ops):
        for region_op, seg in regions.get(i, ()):
            plan.append(("region", region_op, seg))
        if i in consumed or i in region_ops:
            continue
        plan.append(("op", op))
    return plan


# Optimizer ops with a SelectedRows (sparse) apply branch — the only
# sanctioned consumers of a sparse grad (≙ the reference's SelectedRows
# optimizer kernels, adam_op.h / math/selected_rows_functor.cc).
SPARSE_CAPABLE_OPT_OPS = frozenset({"sgd", "momentum", "adam"})


def _find_sparse_embedding_specs(seg_ops, target_names, env, block, ctx):
    """Params whose gradient can ship as (rows, values) instead of a dense
    [vocab, dim] array: an is_sparse lookup_table param, read exactly once in
    the segment, ids available before the region, every block-level consumer
    of its @GRAD a sparse-capable optimizer op, and the grad not fetched."""
    fetches = set(ctx.extras.get("fetch_names", ()))
    specs = []
    for op in seg_ops:
        if op.type != "lookup_table" or not op.attrs.get("is_sparse"):
            continue
        w = op.inputs["W"][0]
        gname = grad_var_name(w)
        if w not in target_names or gname in fetches:
            continue
        ids_name = op.inputs["Ids"][0]
        if ids_name not in env:
            continue  # ids computed inside the region: dense fallback
        reads = sum(n == w for o in seg_ops
                    for ns in o.inputs.values() for n in ns)
        if reads != 1:
            continue  # table also read elsewhere: grads would be partial
        consumers = [o.type for o in block.ops
                     if gname in {n for ns in o.inputs.values() for n in ns}]
        if not consumers or any(t not in SPARSE_CAPABLE_OPT_OPS
                                for t in consumers):
            continue
        specs.append((w, op.outputs["Out"][0], ids_name,
                      op.attrs.get("padding_idx", None)))
    return specs


def remat_boundaries(seg_op_lists, out_need: Set[str]):
    """Per-segment carried-out name lists for a segmented-remat region:
    segment i's boundary = names produced at/before segment i that a
    LATER segment reads, or that the region must publish (`out_need` —
    the narrowed live-out set plus the loss). Everything else a segment
    produces is recomputed from its boundary input during the backward
    (jax.checkpoint per segment). The ONE copy shared by the executing
    runner below and the planner's predicted-peak model
    (framework/memory_plan.py) — prediction and execution cannot drift."""
    reads_after = []
    acc: Set[str] = set()
    for ops in reversed(seg_op_lists):
        reads_after.insert(0, set(acc))
        for op in ops:
            acc |= set(op.input_names())
    boundaries = []
    avail: Set[str] = set()
    for i, ops in enumerate(seg_op_lists):
        for op in ops:
            avail |= set(op.output_names())
        boundaries.append(sorted((reads_after[i] | out_need) & avail))
    return boundaries


def _run_vjp_region_segmented(region_op, seg_indices, env, block, ctx,
                              segments):
    """Segmented-remat execution of a vjp_region (attrs set by the memory
    planner, framework/memory_plan.py): the forward runs as a chain of
    jax.checkpoint'd segment functions, so the backward of segment i
    recomputes ONLY segment i's activations from its carried boundary —
    the executable form of the remat-vs-stash plan (Checkmate-style
    segmentation; the pipeline engine's stage-granular checkpointing is
    the same idea at stage boundaries). attrs consulted:
      remat_segments     list of block-op-index lists partitioning fwd_ops
      remat_policy       optional jax.checkpoint_policies name per segment
      remat_prevent_cse  default True (real recompute); False lets XLA CSE
                         recomputation back into the forward where that
                         wins wall-clock (documented tradeoff)
    """
    attrs = region_op.attrs
    target_names: List[str] = attrs["targets"]
    loss_name: str = attrs["loss"]
    seg_ops_all = [block.ops[i] for i in seg_indices]
    produced: List[str] = []
    for op in seg_ops_all:
        for n in op.output_names():
            if n not in produced:
                produced.append(n)
    live_out = attrs.get("live_out")
    if live_out is not None:
        live = set(live_out) | set(ctx.extras.get("fetch_names", ()))
        produced = [n for n in produced if n in live]
    base_env = {k: v for k, v in env.items()}
    dense_names = list(target_names)
    seg_op_lists = [[block.ops[i] for i in seg] for seg in segments]
    # boundaries computed at TRACE time (not plan time) so run-specific
    # fetch targets are carried out of their producing segment
    boundaries = remat_boundaries(seg_op_lists,
                                  set(produced) | {loss_name})
    policy_name = attrs.get("remat_policy")
    policy = (getattr(jax.checkpoint_policies, policy_name)
              if policy_name else None)
    prevent_cse = bool(attrs.get("remat_prevent_cse", True))

    def fwd(dense_vals, perturb_vals):
        carried_names: List[str] = []
        carried_vals = ()
        for i, ops in enumerate(seg_op_lists):
            bn = boundaries[i]

            def seg_fn(dv, cv, _ops=ops, _cn=list(carried_names), _bn=bn):
                e = dict(base_env)
                e.update(zip(dense_names, dv))
                e.update(zip(_cn, cv))
                for op in _ops:
                    run_op(op, e, block, ctx)
                return tuple(e[n] for n in _bn)

            seg_fn = jax.checkpoint(seg_fn, policy=policy,
                                    prevent_cse=prevent_cse)
            carried_vals = seg_fn(dense_vals, carried_vals)
            carried_names = bn
        e = dict(zip(carried_names, carried_vals))
        loss = e[loss_name]
        aux = tuple(e[n] for n in produced)
        return loss, aux

    missing = [n for n in dense_names if n not in env]
    if missing:
        raise NotFoundError(
            f"vjp_region differentiates wrt {missing} which are not "
            f"initialized — run the startup program or feed them")
    dense_vals = tuple(env[n] for n in dense_names)
    loss_val, vjp_fn, aux = jax.vjp(fwd, dense_vals, (), has_aux=True)
    seed = jnp.ones_like(loss_val)
    dgrads, _ = vjp_fn(seed)
    env.update(zip(produced, aux))
    env[grad_var_name(loss_name)] = seed
    for name, g in zip(dense_names, dgrads):
        env[grad_var_name(name)] = g


def run_vjp_region(region_op: Operator, seg_indices: Sequence[int],
                   env: Dict[str, Any], block: Block, ctx: LowerCtx):
    """Execute a forward segment under jax.vjp, producing forward vars AND
    gradients (≙ append_backward's emitted grad-op chain, reference
    backward.py:315-469, executed by the compiler instead)."""
    attrs = region_op.attrs
    segments = attrs.get("remat_segments")
    if segments:
        # the planner refuses to segment regions with sparse-capable
        # embedding lookups (the perturbation trick below needs the
        # un-segmented trace); re-check here so a hand-set attr degrades
        # to the plain path instead of mis-training
        sparse_free = not any(
            block.ops[i].type == "lookup_table"
            and block.ops[i].attrs.get("is_sparse")
            for i in seg_indices)
        if sparse_free and sorted(i for s in segments for i in s) == \
                sorted(seg_indices):
            return _run_vjp_region_segmented(region_op, seg_indices, env,
                                             block, ctx, segments)
    target_names: List[str] = attrs["targets"]        # vars to differentiate wrt
    loss_name: str = attrs["loss"]
    seg_ops = [block.ops[i] for i in seg_indices]
    produced: List[str] = []
    for op in seg_ops:
        for n in op.output_names():
            if n not in produced:
                produced.append(n)

    # memory_optimize (transpiler/memory_optimization.py) narrows the forward
    # vars published out of the region to the live-out set it computed, plus
    # whatever this run actually fetches (liveness can't see fetch lists).
    live_out = attrs.get("live_out")
    if live_out is not None:
        live = set(live_out) | set(ctx.extras.get("fetch_names", ()))
        produced = [n for n in produced if n in live]

    # Snapshot of everything the segment may read, minus the diff targets.
    base_env = {k: v for k, v in env.items()}

    # Sparse embedding grads: differentiate wrt a zero perturbation ADDED to
    # the lookup output instead of wrt the [vocab, dim] table — the
    # perturbation's cotangent IS the per-row gradient values, and the rows
    # are the ids. The table never takes a dense gradient.
    sparse_specs = _find_sparse_embedding_specs(seg_ops, target_names, env,
                                                block, ctx)
    sparse_names = {w for w, _, _, _ in sparse_specs}
    dense_names = [n for n in target_names if n not in sparse_names]
    perturb_for = {out: i for i, (_, out, _, _) in enumerate(sparse_specs)}
    perturbs = []
    for w, _, ids_name, _ in sparse_specs:
        wval, ids = env[w], env[ids_name]
        idshape = (ids.shape[:-1] if ids.ndim >= 2 and ids.shape[-1] == 1
                   else ids.shape)
        perturbs.append(jnp.zeros(idshape + (wval.shape[1],),
                                  dtype=wval.dtype))

    def fwd(dense_vals, perturb_vals):
        env2 = dict(base_env)
        env2.update(zip(dense_names, dense_vals))
        for op in seg_ops:
            run_op(op, env2, block, ctx)
            for n in op.output_names():
                i = perturb_for.get(n)
                if i is not None:
                    env2[n] = env2[n] + perturb_vals[i]
        loss = env2[loss_name]
        aux = tuple(env2[n] for n in produced)
        return loss, aux

    # Rematerialization (set by transpiler.memory_optimize ≙ the reference's
    # memory_optimization_transpiler): trade FLOPs for HBM by recomputing the
    # forward in the backward pass under the chosen checkpoint policy.
    if attrs.get("remat"):
        policy_name = attrs.get("remat_policy")
        policy = (getattr(jax.checkpoint_policies, policy_name)
                  if policy_name else None)
        fwd = jax.checkpoint(fwd, policy=policy)

    missing = [n for n in dense_names if n not in env]
    if missing:
        raise NotFoundError(
            f"vjp_region differentiates wrt {missing} which are not "
            f"initialized — run the startup program or feed them")
    dense_vals = tuple(env[n] for n in dense_names)
    loss_val, vjp_fn, aux = jax.vjp(fwd, dense_vals, tuple(perturbs),
                                    has_aux=True)
    seed = jnp.ones_like(loss_val)  # ≙ fill_constant loss@GRAD=1 (backward.py:566)
    dgrads, pgrads = vjp_fn(seed)
    env.update(zip(produced, aux))
    env[grad_var_name(loss_name)] = seed
    for name, g in zip(dense_names, dgrads):
        env[grad_var_name(name)] = g
    if sparse_specs:
        from .selected_rows import TracedSelectedRows
        for (w, _, ids_name, padding_idx), pg in zip(sparse_specs, pgrads):
            ids = env[ids_name]
            if ids.ndim >= 2 and ids.shape[-1] == 1:
                ids = jnp.squeeze(ids, axis=-1)
            rows = ids.reshape(-1)
            vals = pg.reshape((-1, pg.shape[-1]))
            if padding_idx is not None:
                pad = (padding_idx if padding_idx >= 0
                       else padding_idx + env[w].shape[0])
                vals = vals * (rows != pad)[:, None].astype(vals.dtype)
            env[grad_var_name(w)] = TracedSelectedRows(
                rows, vals, env[w].shape[0])


REGION_RUNNERS["vjp_region"] = run_vjp_region


from .registry import register_op  # noqa: E402


@register_op("vjp_region", stop_gradient=True)
def _vjp_region_stub(ctx, ins, attrs):
    # Never lowered directly — handled by build_plan/run_vjp_region. Appears in
    # the registry so Operator construction validates (≙ OpInfoMap entry).
    raise RuntimeError("vjp_region must be executed via the block planner")


def run_plan(plan, env: Dict[str, Any], block: Block, ctx: LowerCtx):
    for step in plan:
        if step[0] == "op":
            run_op(step[1], env, block, ctx)
        else:
            _, region_op, seg = step
            REGION_RUNNERS[region_op.type](region_op, seg, env, block, ctx)
    return env
