"""Static sharding propagation over the Program IR + the tp_shard_pass.

The subsystem that makes tensor parallelism *first-class* instead of an
enforce gate: per-variable PartitionSpec-style shardings are seeded from
``ParamAttr(sharding_spec=...)`` / ``parallel.auto_shard.annotate_tp`` and
propagated GSPMD-style through the whole program (the role the reference's
multi_devices_graph_pass plays for placement decisions, and XLA's
sharding-propagation pass plays for SPMD — done here statically, on the
Program IR, so the *manual* execution modes can splice explicit collectives).

Three cooperating layers, mirroring framework/analysis.py one level up:

1. **Propagation** (`propagate_sharding`): walks the op DAG with per-op
   propagation rules (``registry.register_shard_spec`` — the sharding-layer
   sibling of ``register_infer_spec``). Each rule maps input specs to output
   specs and may record *collective actions*: a partial-sum output that
   needs a tp all-reduce (row-parallel matmul), a replicated activation
   entering sharded compute that needs Megatron's f-operator
   (identity-forward / psum-backward), a replicated operand that must be
   split to the local chunk, or a sharded value that must be all-gathered
   back (the tp<->dp boundary reshard, "Memory-efficient array
   redistribution", PAPERS.md). Conflicts report as error diagnostics with
   the same block/op#/op.type provenance as the analyzer.

2. **Verification**: `analyze_program` folds the propagation diagnostics in
   whenever a program carries live tp annotations, so an inconsistent
   annotation (a sharded bias on a replicated activation, a non-divisible
   dim) surfaces as a provenance-carrying analyzer diagnostic, not a wrong
   number.

3. **The pass** (`tp_shard_pass`): makes the propagated specs *executable*
   for the full-manual shard_map executor — splices explicit
   ``tp_allreduce`` / ``tp_ident`` / ``tp_split`` / ``tp_allgather`` ops
   (parallel/tensor_parallel.py) into the program exactly the way
   grad_comm.comm_optimize_pass splices ``dp_grad_comm``, rewrites
   vocab-sharded embedding lookups to ``tp_vocab_lookup``, re-maps the
   vjp_region's recorded fwd_ops indices, and marks every sharded variable
   with ``tp_spec`` so the executor places it and the analyzer cross-checks
   it at the tp-local shape.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.enforce import InvalidArgumentError, NotFoundError, enforce
from .analysis import (BATCH_SENTINEL, Diagnostic, ProgramAnalysisError,
                       _subst, op_loc)
from .passes import Pass, register_pass
from .program import Block, Operator, Program
from .registry import lookup_shard_rule, register_shard_spec

__all__ = [
    "TP_AXIS", "TP_PART_SUFFIX", "ShardCtx", "ShardingResult",
    "TpShardPass", "has_tp_annotations", "propagate_sharding",
    "tp_analytic_wire_bytes", "tp_component", "tp_local_shape",
]

# The model-parallel mesh axis name (== parallel.mesh.MODEL_AXIS; duplicated
# here so the framework layer does not import the parallel package).
TP_AXIS = "tp"

TP_PART_SUFFIX = "@TPPART"    # raw partial-sum output awaiting tp_allreduce
TP_IDENT_SUFFIX = "@TPID"     # identity-fwd / psum-bwd wrapped activation
TP_SPLIT_SUFFIX = "@TPSPLIT"  # local chunk of a replicated operand
TP_GATHER_SUFFIX = "@TPGATH"  # re-assembled (resharded) value


def tp_component(spec) -> Optional[tuple]:
    """Reduce a general sharding_spec (which may name dp/sp axes or axis
    tuples) to its tp component: a per-dim tuple of TP_AXIS-or-None, or
    None when no dim is tp-sharded."""
    if spec is None:
        return None
    out, any_tp = [], False
    for s in spec:
        names = s if isinstance(s, (tuple, list)) else (s,)
        if TP_AXIS in names:
            out.append(TP_AXIS)
            any_tp = True
        else:
            out.append(None)
    return tuple(out) if any_tp else None


def tp_local_shape(shape, tp_spec, tp: int) -> Optional[tuple]:
    """The per-shard shape of a var declared at `shape` and sharded per
    `tp_spec` over a tp axis of size `tp` (tp-sharded dims divide)."""
    if shape is None:
        return None
    if not tp_spec or tp <= 1:
        return tuple(shape)
    out = []
    for d, s in zip(shape, tuple(tp_spec) + (None,) * len(shape)):
        if s == TP_AXIS and d not in (-1, None) and d % tp == 0:
            out.append(d // tp)
        else:
            out.append(d)
    return tuple(out)


def has_tp_annotations(program: Program) -> bool:
    """Does any block-0 var carry a sharding_spec with a tp component?"""
    for v in program.global_block().vars.values():
        if tp_component(getattr(v, "sharding_spec", None)) is not None:
            return True
    return False


def _is_sharded(spec) -> bool:
    return spec is not None and any(s is not None for s in spec)


def _repl(rank: Optional[int]) -> Optional[tuple]:
    return None if rank is None else (None,) * rank


# ---------------------------------------------------------------------------
# propagation context + result
# ---------------------------------------------------------------------------


@dataclass
class OpActions:
    """Collective actions one op needs to execute its propagated sharding
    (consumed by tp_shard_pass; ignored by pure verification)."""
    op_idx: int
    psums: List[Tuple[str, int]] = field(default_factory=list)  # slot, i
    idents: List[Tuple[str, int]] = field(default_factory=list)
    splits: List[Tuple[str, int, int]] = field(default_factory=list)  # +dim
    gathers: List[Tuple[str, int, int]] = field(default_factory=list)
    replace: Optional[str] = None       # swap op.type (tp_vocab_lookup)

    def any(self):
        return bool(self.psums or self.idents or self.splits
                    or self.gathers or self.replace)


@dataclass
class ShardCtx:
    """Context handed to shard-propagation rules (the sharding-layer
    InferCtx): op provenance, the tp axis name/size, declared-shape lookup,
    and the action/diagnostic recorders."""
    block: Block
    op: Operator
    op_idx: int
    axis: str = TP_AXIS
    size: Optional[int] = None          # None = size-agnostic verification
    nominal_batch: int = BATCH_SENTINEL
    actions: OpActions = None
    diagnostics: List[Diagnostic] = None

    @property
    def loc(self) -> str:
        return op_loc(self.block, self.op_idx, self.op)

    def shape_of(self, name: str) -> Optional[tuple]:
        try:
            v = self.block.var(name)
        except NotFoundError:
            return None
        if v.shape is None:
            return None
        return _subst(v.shape, self.nominal_batch)

    def in_shape(self, slot: str, i: int = 0) -> Optional[tuple]:
        names = self.op.inputs.get(slot, ())
        return self.shape_of(names[i]) if i < len(names) else None

    # -- recorders --------------------------------------------------------
    def conflict(self, message: str, code: str = "shard-conflict"):
        self.diagnostics.append(Diagnostic(code, self.loc, message))

    def warn(self, message: str, code: str = "shard-reshard"):
        self.diagnostics.append(
            Diagnostic(code, self.loc, message, severity="warning"))

    def check_divisible(self, dim_size, what: str) -> bool:
        if (self.size and dim_size not in (None, -1)
                and dim_size % self.size != 0):
            self.diagnostics.append(Diagnostic(
                "shard-divisibility", self.loc,
                f"{what}: dim of size {dim_size} is not divisible by "
                f"tp={self.size}"))
            return False
        return True

    def psum(self, slot: str = "Out", i: int = 0):
        """Mark output (slot, i) as a PARTIAL sum: tp_allreduce follows."""
        self.actions.psums.append((slot, i))

    def ident_input(self, slot: str, i: int = 0):
        """Wrap replicated input (slot, i) entering sharded compute with
        tp_ident (Megatron's f: identity forward, psum backward)."""
        self.actions.idents.append((slot, i))

    def split_input(self, slot: str, i: int, dim: int):
        """Slice replicated input (slot, i) to the local chunk on `dim`."""
        self.actions.splits.append((slot, i, dim))

    def gather_input(self, slot: str, i: int, dim: int):
        """All-gather sharded input (slot, i) back to replicated (the
        reshard at a tp boundary)."""
        self.actions.gathers.append((slot, i, dim))

    def replace_op(self, new_type: str):
        self.actions.replace = new_type


@dataclass
class ShardingResult:
    specs: Dict[str, tuple]             # block-0 var name -> propagated spec
    diagnostics: List[Diagnostic]
    actions: List[OpActions]            # only entries with any() True
    seeded: Dict[str, tuple]            # annotation-seeded var -> tp spec

    @property
    def errors(self):
        return [d for d in self.diagnostics if d.severity == "error"]

    def sharded_vars(self) -> Dict[str, tuple]:
        return {n: s for n, s in self.specs.items() if _is_sharded(s)}


# ---------------------------------------------------------------------------
# the driver
# ---------------------------------------------------------------------------

# control-flow binder ops cannot consume tp-sharded values: the sub-block is
# traced by the lowering with no sharding model of its own
_CTRL_OPS = frozenset({"cond_block", "lazy_cond", "while", "switch_case",
                       "static_rnn", "array_read", "array_write"})

_REGION_TYPES = frozenset({"vjp_region", "pp_pipeline_region"})


def propagate_sharding(program: Program, tp_size: Optional[int] = None,
                       nominal_batch: int = BATCH_SENTINEL
                       ) -> ShardingResult:
    """Whole-program sharding propagation over the global block.

    Seeds from every var carrying a ``sharding_spec`` with a tp component,
    walks ops in order applying the registered per-op rules, and returns
    the propagated spec environment, conflict/divisibility diagnostics, and
    the collective actions tp_shard_pass would splice. `tp_size=None` runs
    size-agnostic (divisibility checks skipped)."""
    block = program.global_block()
    res = ShardingResult(specs={}, diagnostics=[], actions=[], seeded={})
    env = res.specs

    for name, v in block.vars.items():
        spec = tp_component(getattr(v, "sharding_spec", None))
        if spec is None:
            continue
        if v.shape is not None and len(spec) != len(v.shape):
            res.diagnostics.append(Diagnostic(
                "shard-spec-arity", name,
                f"sharding_spec {list(spec)} has {len(spec)} entries for "
                f"declared rank {len(v.shape)}"))
            continue
        env[name] = spec
        res.seeded[name] = spec
        if v.shape is not None and tp_size:
            for d, s in zip(v.shape, spec):
                if s == TP_AXIS and d not in (-1,) and d % tp_size != 0:
                    res.diagnostics.append(Diagnostic(
                        "shard-divisibility", name,
                        f"annotated dim of size {d} is not divisible by "
                        f"tp={tp_size}"))

    # optimizer accumulators carry no annotation of their own but live at
    # their param's placement (the r08 dp-sharded-update discipline, here on
    # the tp axis): same-shaped accumulators inherit the param's spec;
    # shape-mismatched state (Beta1Pow-style scalars) stays replicated
    for name, v in block.vars.items():
        owner = getattr(v, "accumulator_of", None)
        if owner is None or owner not in res.seeded:
            continue
        try:
            pvar = block.var(owner)
        except NotFoundError:
            continue
        if v.shape is not None and v.shape == pvar.shape:
            env[name] = res.seeded[owner]
            res.seeded[name] = res.seeded[owner]

    from .lowering import grad_var_name

    def _spec_for(name: str) -> Optional[tuple]:
        s = env.get(name)
        if s is not None:
            return s
        try:
            v = block.var(name)
        except NotFoundError:
            return None
        return _repl(len(v.shape)) if v.shape is not None else None

    for idx, op in enumerate(block.ops):
        if op.type in _REGION_TYPES:
            # gradients mirror their targets' shardings; the loss grad is
            # replicated (the engine executes the region itself)
            for t in op.attrs.get("targets", ()):
                s = env.get(t)
                if s is not None:
                    env[grad_var_name(t)] = s
            loss = op.attrs.get("loss")
            if loss:
                ls = _spec_for(loss)
                if ls is not None:
                    env[grad_var_name(loss)] = ls
            continue

        if op.type in ("pp_send", "pp_recv"):
            # pipeline boundary ops move values between pp shards and
            # re-bind the crossing names on the consuming stage; the names
            # keep their producers' specs (the pp axis is orthogonal to
            # the tp component being propagated — letting the default
            # replicated rule overwrite them manufactures conflicts on
            # pipelined tp-annotated programs)
            continue

        in_specs: Dict[str, List[Optional[tuple]]] = {}
        any_tp = False
        for slot, names in op.inputs.items():
            specs = [_spec_for(n) for n in names]
            in_specs[slot] = specs
            any_tp = any_tp or any(_is_sharded(s) for s in specs)

        actions = OpActions(op_idx=idx)
        sctx = ShardCtx(block=block, op=op, op_idx=idx, size=tp_size,
                        nominal_batch=nominal_batch, actions=actions,
                        diagnostics=res.diagnostics)

        out_specs: Dict[str, List[Optional[tuple]]] = {}
        if (op.attrs.get("op_role") == "optimize"
                and "Param" in op.inputs):
            out_specs = _optimize_rule(sctx, in_specs, op.attrs)
        elif not any_tp and lookup_shard_rule(op.type) is None:
            out_specs = {}                       # replicated fast path
        elif op.type in _CTRL_OPS and any_tp:
            sctx.conflict(
                f"control-flow op {op.type!r} consumes a tp-sharded "
                f"value; sub-block programs have no sharding model — "
                f"reshard or drop the annotation")
        else:
            rule = lookup_shard_rule(op.type)
            if rule is None:
                # GSPMD-style reshard-to-replicated fallback: correct, but
                # worth a warning — every gather is wire bytes
                gathered = []
                for slot, specs in in_specs.items():
                    for i, s in enumerate(specs):
                        if _is_sharded(s):
                            dim = next(d for d, a in enumerate(s)
                                       if a is not None)
                            sctx.gather_input(slot, i, dim)
                            gathered.append(op.inputs[slot][i])
                sctx.warn(
                    f"no sharding rule for op {op.type!r}: tp-sharded "
                    f"input(s) {gathered[:4]} will be all-gathered back "
                    f"to replicated (add a register_shard_spec rule to "
                    f"keep them sharded)")
            else:
                out_specs = rule(sctx, in_specs, dict(op.attrs)) or {}

        for slot, names in op.outputs.items():
            specs = out_specs.get(slot)
            for i, n in enumerate(names):
                s = specs[i] if specs is not None and i < len(specs) \
                    else None
                if s is None:
                    try:
                        v = block.var(n)
                        s = _repl(len(v.shape)) if v.shape is not None \
                            else None
                    except NotFoundError:
                        s = None
                # a seeded (annotated) var written with a different
                # sharding than its annotation is a conflict, not a
                # silent re-placement
                seeded = res.seeded.get(n)
                if seeded is not None and s is not None \
                        and tuple(seeded) != tuple(s):
                    sctx.conflict(
                        f"output {n!r} is annotated {list(seeded)} but "
                        f"the op produces sharding {list(s)}")
                    s = seeded
                if s is not None:
                    env[n] = s
        if actions.any():
            res.actions.append(actions)
    return res


def _optimize_rule(sctx, in_specs, attrs):
    """Optimizer ops update per-shard state elementwise: every output
    mirrors its same-named input slot (ParamOut <- Param, MomentOut <-
    Moment, ...); Grad and same-shaped accumulators must agree with Param's
    sharding."""
    pspec = in_specs.get("Param", [None])[0]
    pshape = sctx.in_shape("Param")
    for slot, specs in in_specs.items():
        if slot in ("Param", "LearningRate"):
            continue
        for i, s in enumerate(specs):
            if s is None or pspec is None:
                continue
            # only same-SHAPED state must agree (Beta1Pow-style [1]
            # scalars are replicated by construction)
            if sctx.op.inputs[slot][i:i + 1] and \
                    sctx.in_shape(slot, i) != pshape:
                continue
            if len(s) == len(pspec) and _is_sharded(s) != _is_sharded(pspec):
                sctx.conflict(
                    f"optimizer input {sctx.op.inputs[slot][i]!r} (slot "
                    f"{slot!r}) sharding {list(s)} disagrees with Param "
                    f"sharding {list(pspec) if pspec else None}")
    outs = {}
    for slot, names in sctx.op.outputs.items():
        src = slot[:-3] if slot.endswith("Out") else slot
        specs = in_specs.get(src) or in_specs.get("Param", [None])
        outs[slot] = [specs[i] if i < len(specs) else specs[0]
                      for i in range(len(names))]
    return outs


# ---------------------------------------------------------------------------
# propagation rules (registry.register_shard_spec — the sharding-layer
# sibling of register_infer_spec)
# ---------------------------------------------------------------------------


@register_shard_spec("mul")
def _shard_mul(sctx, in_specs, attrs):
    """fc matmul: [lead.., K] x [K, N]. Column-parallel (Y sharded on N):
    local matmul, output feature-sharded, replicated X wrapped in tp_ident.
    Row-parallel (Y sharded on K): X must arrive contraction-sharded (from
    a preceding column layer) or be split locally; the local product is a
    partial sum -> tp_allreduce."""
    xs = in_specs["X"][0]
    ys = in_specs["Y"][0]
    xd = int(attrs.get("x_num_col_dims", 1))
    yd = int(attrs.get("y_num_col_dims", 1))
    if xs is None or ys is None:
        return {}
    x_lead, x_con = list(xs[:xd]), list(xs[xd:])
    y_con, y_out = list(ys[:yd]), list(ys[yd:])
    y_con_sh = any(s is not None for s in y_con)
    y_out_sh = any(s is not None for s in y_out)
    if y_con_sh and y_out_sh:
        sctx.conflict("weight is sharded on BOTH its contraction and "
                      "output dims; shard exactly one")
        return {}
    if y_out_sh:                                   # column-parallel
        if any(s is not None for s in x_con):
            sctx.conflict(
                "column-parallel weight (output dim sharded) fed a "
                "contraction-sharded activation; only one side of the "
                "contraction may be sharded")
            return {}
        yshape = sctx.in_shape("Y")
        if yshape is not None:
            for d, s in zip(yshape[yd:], y_out):
                if s is not None:
                    sctx.check_divisible(d, "column-parallel output dim")
        sctx.ident_input("X", 0)
        return {"Out": [tuple(x_lead + y_out)]}
    if y_con_sh:                                   # row-parallel
        if len(y_con) != 1:
            sctx.conflict("row-parallel weight with y_num_col_dims > 1 "
                          "is unsupported")
            return {}
        xshape = sctx.in_shape("X")
        if xshape is not None:
            sctx.check_divisible(xshape[-1], "row-parallel contraction dim")
        if x_con and x_con[-1] is not None \
                and all(s is None for s in x_con[:-1]):
            pass                         # arrives sharded from column layer
        elif all(s is None for s in x_con):
            if len(x_con) != 1:
                sctx.conflict(
                    "row-parallel weight fed a flattened multi-dim "
                    "contraction; cannot split the activation locally")
                return {}
            sctx.split_input("X", 0, dim=len(xs) - 1)
        else:
            sctx.conflict(
                f"row-parallel contraction mismatch: activation spec "
                f"{list(xs)} does not align with weight spec {list(ys)}")
            return {}
        sctx.psum("Out", 0)
        return {"Out": [tuple(x_lead) + (None,) * len(y_out)]}
    # Y fully replicated
    if any(s is not None for s in x_con):
        sctx.gather_input("X", 0, dim=xd + next(
            k for k, s in enumerate(x_con) if s is not None))
        sctx.warn("contraction-sharded activation into a replicated "
                  "weight: all-gathering it back (annotate the weight "
                  "row-parallel to keep it sharded)")
        x_lead = [None] * len(x_lead)
    if any(s is not None for s in x_lead):
        sctx.ident_input("Y", 0)         # tp-data-parallel: w grad partial
    return {"Out": [tuple(x_lead) + (None,) * len(y_out)]}


@register_shard_spec("matmul")
def _shard_matmul(sctx, in_specs, attrs):
    """Batched matmul: batch dims sharded identically ride through
    (head-sharded attention); sharded contraction on both sides is a
    partial -> psum; mixed contraction sharding is a conflict."""
    xs, ys = in_specs["X"][0], in_specs["Y"][0]
    if xs is None or ys is None:
        return {}
    tx, ty = bool(attrs.get("transpose_X")), bool(attrs.get("transpose_Y"))
    if len(xs) < 2 or len(ys) < 2:
        return {}
    xm, xk = (xs[-1], xs[-2]) if tx else (xs[-2], xs[-1])
    yk, yn = (ys[-1], ys[-2]) if ty else (ys[-2], ys[-1])
    xb, yb = list(xs[:-2]), list(ys[:-2])
    nb = max(len(xb), len(yb))
    xb = [None] * (nb - len(xb)) + xb
    yb = [None] * (nb - len(yb)) + yb
    out_b = []
    for a, b in zip(xb, yb):
        if a is not None and b is not None and a != b:
            sctx.conflict(f"batched-matmul batch dims sharded "
                          f"inconsistently: {a} vs {b}")
        out_b.append(a if a is not None else b)
    out = tuple(out_b) + (xm, yn)
    if xk is not None and yk is not None:
        sctx.psum("Out", 0)
        return {"Out": [out]}
    if (xk is None) != (yk is None):
        sctx.conflict("matmul contraction dim sharded on one operand "
                      "only; shard both (partial+psum) or neither")
        return {}
    return {"Out": [out]}


def _shard_elementwise(sctx, in_specs, attrs):
    """Binary elementwise with the reference broadcast semantics: the
    output follows X; Y dims align trailing (axis=-1) or at `axis`. A
    sharded dim meeting a full-size replicated dim is a conflict (a
    sharded bias on a replicated activation — the classic annotation
    bug); a replicated broadcast operand entering a sharded result rides
    through (each shard broadcasts locally) but is tp_ident-wrapped so
    its backward cotangent is reduced."""
    xs = in_specs["X"][0]
    ys = in_specs["Y"][0]
    if xs is None:
        return {}
    if ys is None:
        return {"Out": [xs]}
    xshape = sctx.in_shape("X")
    yshape = sctx.in_shape("Y")
    axis = attrs.get("axis", -1)
    nx, ny = len(xs), len(ys)
    if axis is None or axis == -1:
        off = nx - ny                     # trailing-aligned
    else:
        off = int(axis)                   # leading-aligned at axis
    out = list(xs)
    y_broadcast_into_sharded = False
    x_broadcast_into_sharded = False
    for j in range(ny):
        d = off + j
        if d < 0 or d >= nx:
            continue
        xsp, ysp = xs[d], ys[j]
        x_sz = xshape[d] if xshape else None
        y_sz = yshape[j] if yshape else None
        if xsp is not None and ysp is None:
            if y_sz not in (1, None):
                sctx.conflict(
                    f"elementwise dim {d}: X is sharded but Y is "
                    f"replicated at full size {y_sz}; shard Y's dim the "
                    f"same way (or keep both replicated)")
            else:
                y_broadcast_into_sharded = True
        elif xsp is None and ysp is not None:
            if x_sz == 1:
                out[d] = ysp
                x_broadcast_into_sharded = True
            else:
                sctx.conflict(
                    f"elementwise dim {d}: Y is sharded but X is "
                    f"replicated at full size {x_sz}; shard X's dim the "
                    f"same way (or keep both replicated)")
    # a replicated broadcast operand entering a sharded result: its
    # backward cotangent sums over the sharded dim, so each shard's
    # contribution is partial — wrap with the f operator (both sides:
    # a size-1 X dim broadcast into a sharded Y dim is the mirror case)
    if _is_sharded(tuple(out)) and not _is_sharded(ys) \
            and (y_broadcast_into_sharded or ny < nx):
        sctx.ident_input("Y", 0)
    if _is_sharded(tuple(out)) and not _is_sharded(xs) \
            and x_broadcast_into_sharded:
        sctx.ident_input("X", 0)
    return {"Out": [tuple(out)]}


for _t in ("elementwise_add", "elementwise_sub", "elementwise_mul",
           "elementwise_div", "elementwise_max", "elementwise_min",
           "elementwise_pow", "less_than", "less_equal", "greater_than",
           "greater_equal", "equal", "not_equal"):
    register_shard_spec(_t)(_shard_elementwise)


def _shard_passthrough(sctx, in_specs, attrs):
    """Elementwise unary: every output mirrors X's sharding."""
    xs = in_specs.get("X", [None])[0]
    return {slot: [xs] * len(names)
            for slot, names in sctx.op.outputs.items()}


for _t in ("relu", "gelu", "tanh", "sigmoid", "exp", "log", "sqrt",
           "rsqrt", "square", "abs", "scale", "cast", "clip", "dropout",
           "softsign", "softplus", "leaky_relu", "relu6", "elu",
           "fill_zeros_like", "assign"):
    register_shard_spec(_t)(_shard_passthrough)


@register_shard_spec("sum")
def _shard_sum(sctx, in_specs, attrs):
    specs = in_specs.get("X", [])
    base = next((s for s in specs if s is not None), None)
    for s in specs:
        if s is not None and base is not None and tuple(s) != tuple(base):
            sctx.conflict(f"sum inputs sharded inconsistently: "
                          f"{list(s)} vs {list(base)}")
    return {"Out": [base]}


@register_shard_spec("reshape")
def _shard_reshape(sctx, in_specs, attrs):
    """Greedy factor-matching between in and out shapes: a sharded dim
    that maps 1:1 keeps its axis; a sharded dim that splits shards the
    OUTERMOST out dim of its group (head split: [B,T,D@tp] ->
    [B,T,nh@tp,dh]); a merged group may only be sharded on its outermost
    dim (head merge back). Anything else is a conflict."""
    xs = in_specs["X"][0]
    if xs is None or not _is_sharded(xs):
        out_shape = sctx.shape_of(sctx.op.outputs["Out"][0])
        return {"Out": [_repl(len(out_shape)) if out_shape else None]}
    in_shape = sctx.in_shape("X")
    out_shape = sctx.shape_of(sctx.op.outputs["Out"][0])
    if in_shape is None or out_shape is None:
        sctx.conflict("reshape of a tp-sharded value with undeclared "
                      "shapes cannot be propagated")
        return {}
    out = [None] * len(out_shape)
    i = j = 0
    ok = True
    while i < len(in_shape) and j < len(out_shape) and ok:
        gi, gj = [i], [j]
        pa, pb = in_shape[i], out_shape[j]
        while pa != pb:
            if pa < pb and gi[-1] + 1 < len(in_shape):
                gi.append(gi[-1] + 1)
                pa *= in_shape[gi[-1]]
            elif pa > pb and gj[-1] + 1 < len(out_shape):
                gj.append(gj[-1] + 1)
                pb *= out_shape[gj[-1]]
            else:
                ok = False
                break
        if not ok:
            break
        sharded = [k for k in gi if xs[k] is not None]
        if sharded:
            k = sharded[0]
            if len(sharded) > 1:
                sctx.conflict("reshape merges two tp-sharded dims")
            elif len(gi) == 1 and len(gj) == 1:
                out[gj[0]] = xs[k]
            elif k != gi[0]:
                sctx.conflict(
                    f"reshape: sharded dim {k} is not the outermost of "
                    f"its factor group {gi} -> {gj}; the local chunks "
                    f"would interleave")
            else:
                if sctx.check_divisible(out_shape[gj[0]],
                                        "reshape split of a sharded dim"):
                    out[gj[0]] = xs[k]
        i, j = gi[-1] + 1, gj[-1] + 1
    if not ok:
        sctx.conflict("reshape factor groups do not align; cannot "
                      "propagate the tp sharding through")
        return {}
    return {"Out": [tuple(out)]}


@register_shard_spec("transpose")
def _shard_transpose(sctx, in_specs, attrs):
    xs = in_specs["X"][0]
    if xs is None:
        return {}
    perm = list(attrs.get("axis", range(len(xs))))
    return {"Out": [tuple(xs[p] for p in perm)]}


@register_shard_spec("unsqueeze")
def _shard_unsqueeze(sctx, in_specs, attrs):
    xs = in_specs["X"][0]
    if xs is None:
        return {}
    out = list(xs)
    for a in sorted(int(a) for a in attrs.get("axes", ())):
        a = a if a >= 0 else a + len(out) + 1
        out.insert(a, None)
    return {"Out": [tuple(out)]}


@register_shard_spec("squeeze")
def _shard_squeeze(sctx, in_specs, attrs):
    xs = in_specs["X"][0]
    if xs is None:
        return {}
    axes = [int(a) if a >= 0 else int(a) + len(xs)
            for a in attrs.get("axes", ())]
    out = [s for d, s in enumerate(xs) if d not in axes]
    return {"Out": [tuple(out)]}


@register_shard_spec("softmax")
def _shard_softmax(sctx, in_specs, attrs):
    xs = in_specs["X"][0]
    if xs is None:
        return {}
    ax = int(attrs.get("axis", -1))
    if xs[ax] is not None:
        sctx.conflict("softmax over a tp-sharded axis cannot be computed "
                      "locally; keep the normalized axis replicated")
    return {"Out": [xs]}


@register_shard_spec("log_softmax")
def _shard_log_softmax(sctx, in_specs, attrs):
    xs = in_specs["X"][0]
    if xs is None:
        return {}
    if xs[-1] is not None:
        sctx.conflict("log_softmax over a tp-sharded axis cannot be "
                      "computed locally")
    return {"Out": [xs]}


@register_shard_spec("layer_norm")
def _shard_layer_norm(sctx, in_specs, attrs):
    xs = in_specs["X"][0]
    if xs is None:
        return {}
    begin = int(attrs.get("begin_norm_axis", 1))
    if any(s is not None for s in xs[begin:]):
        sctx.conflict("layer_norm normalizes over a tp-sharded dim; "
                      "normalization axes must stay replicated "
                      "(psum the activation first — Megatron row-parallel)")
    for slot in ("Scale", "Bias"):
        s = in_specs.get(slot, [None])[0]
        if _is_sharded(s):
            sctx.conflict(f"layer_norm {slot} is tp-sharded but the "
                          f"normalized activation is replicated")
    return {"Y": [xs], "Mean": [tuple(xs[:begin])],
            "Variance": [tuple(xs[:begin])]}


@register_shard_spec("softmax_with_cross_entropy")
def _shard_sce(sctx, in_specs, attrs):
    ls = in_specs["Logits"][0]
    if ls is None:
        return {}
    if ls[-1] is not None:
        sctx.conflict("softmax_with_cross_entropy over tp-sharded logits "
                      "is unsupported; the row-parallel lm head psums "
                      "logits back to replicated first")
        return {}
    return {"Loss": [tuple(ls[:-1]) + (None,)], "Softmax": [ls]}


@register_shard_spec("fused_attention")
def _shard_fused_attention(sctx, in_specs, attrs):
    qs = in_specs["Q"][0]
    ks = in_specs["K"][0]
    vs = in_specs["V"][0]
    if qs is None:
        return {}
    for name, s in (("K", ks), ("V", vs)):
        if s is not None and tuple(s) != tuple(qs):
            sctx.conflict(f"fused_attention {name} sharding {list(s)} "
                          f"!= Q sharding {list(qs)}")
    if len(qs) >= 2 and any(s is not None for s in qs[-2:]):
        sctx.conflict("fused_attention sequence/head-depth dims may not "
                      "be tp-sharded (shard the head COUNT dim)")
    return {"Out": [qs]}


@register_shard_spec("lookup_table")
def _shard_lookup_table(sctx, in_specs, attrs):
    ws = in_specs["W"][0]
    if ws is None or not _is_sharded(ws):
        return {}
    ids_shape = sctx.in_shape("Ids")
    rank = len(ids_shape) if ids_shape else 2
    if ids_shape and len(ids_shape) >= 2 and ids_shape[-1] == 1:
        rank -= 1
    if ws[0] is not None and any(s is not None for s in ws[1:]):
        sctx.conflict("embedding table sharded on BOTH vocab and feature "
                      "dims; shard exactly one")
        return {}
    if ws[0] is not None:
        # vocab-row-sharded (the EP analogue): masked local lookup +
        # psum, executed by the tp_vocab_lookup op
        wshape = sctx.in_shape("W")
        if wshape:
            sctx.check_divisible(wshape[0], "vocab-sharded embedding")
        sctx.replace_op("tp_vocab_lookup")
        return {"Out": [(None,) * rank + (None,) * (len(ws) - 1)]}
    # feature-column-sharded: local lookup, output feature-sharded
    return {"Out": [(None,) * rank + tuple(ws[1:])]}


def _reduce_dims(attrs, rank):
    dims = attrs.get("dim")
    if dims is None:
        return list(range(rank))
    if isinstance(dims, (int, np.integer)):
        dims = [dims]
    return [int(d) if d >= 0 else int(d) + rank for d in dims]


@register_shard_spec("reduce_sum")
def _shard_reduce_sum(sctx, in_specs, attrs):
    xs = in_specs["X"][0]
    if xs is None:
        return {}
    dims = _reduce_dims(attrs, len(xs))
    if any(xs[d] is not None for d in dims):
        sctx.psum("Out", 0)          # local sum is a partial over tp
    keep = bool(attrs.get("keep_dim", False))
    if keep:
        out = tuple(None if d in dims else s for d, s in enumerate(xs))
    else:
        out = tuple(s for d, s in enumerate(xs) if d not in dims)
    return {"Out": [out]}


@register_shard_spec("reduce_mean")
def _shard_reduce_mean(sctx, in_specs, attrs):
    xs = in_specs["X"][0]
    if xs is None:
        return {}
    dims = _reduce_dims(attrs, len(xs))
    if any(xs[d] is not None for d in dims):
        sctx.conflict("reduce_mean over a tp-sharded dim is unsupported; "
                      "psum the value back to replicated first")
        return {}
    keep = bool(attrs.get("keep_dim", False))
    if keep:
        out = tuple(None if d in dims else s for d, s in enumerate(xs))
    else:
        out = tuple(s for d, s in enumerate(xs) if d not in dims)
    return {"Out": [out]}


@register_shard_spec("mean")
def _shard_mean(sctx, in_specs, attrs):
    xs = in_specs["X"][0]
    if _is_sharded(xs):
        sctx.conflict("mean over a tp-sharded value is unsupported; psum "
                      "it back to replicated first")
    return {"Out": [()]}


@register_shard_spec("concat")
def _shard_concat(sctx, in_specs, attrs):
    specs = in_specs.get("X", [])
    base = next((s for s in specs if _is_sharded(s)), None)
    if base is None:
        return {}
    ax = int(attrs.get("axis", 0))
    if base[ax] is not None:
        sctx.conflict("concat along a tp-sharded axis is unsupported")
    for s in specs:
        if s is not None and tuple(s) != tuple(base):
            sctx.conflict(f"concat inputs sharded inconsistently: "
                          f"{list(s)} vs {list(base)}")
    return {"Out": [base]}


# explicit-pipeline ops (present when linting a dp-comm/pipeline-rewritten
# program): shardings ride through untouched
@register_shard_spec("dp_grad_comm")
def _shard_dp_grad_comm(sctx, in_specs, attrs):
    return {"Out": list(in_specs.get("X", [])),
            "ErrOut": list(in_specs.get("ErrIn", []))}


@register_shard_spec("dp_shard_slice")
def _shard_dp_shard_slice(sctx, in_specs, attrs):
    return {"Out": [in_specs["X"][0]]}


@register_shard_spec("dp_shard_all_gather")
def _shard_dp_shard_all_gather(sctx, in_specs, attrs):
    return {"Out": [in_specs["X"][0]]}


@register_shard_spec("pp_send")
def _shard_pp_send(sctx, in_specs, attrs):
    return {"Out": [(None,)]}


@register_shard_spec("pp_recv")
def _shard_pp_recv(sctx, in_specs, attrs):
    # re-binds crossing names: their specs are already in the environment
    return {}


# ---------------------------------------------------------------------------
# tp_shard_pass: make the propagated shardings executable
# ---------------------------------------------------------------------------


@register_pass("tp_shard_pass")
class TpShardPass(Pass):
    """Splice explicit tp collectives into a tp-annotated program so the
    full-manual shard_map executor computes exactly the single-device math
    (the way comm_optimize_pass splices dp_grad_comm). attrs:

      tp: the tp mesh-axis size (local shapes divide by it).
      nominal_batch: stand-in for -1 dims in divisibility checks.

    The rewrite, per propagated action:
      - partial-sum outputs are renamed to <name>@TPPART and a
        ``tp_allreduce`` restores <name> (row-parallel psum);
      - replicated activations entering sharded compute are wrapped in
        ``tp_ident`` (identity fwd / psum bwd — Megatron's f), deduped per
        variable so one backward all-reduce serves all consumers;
      - replicated operands of a row-parallel contraction are sliced with
        ``tp_split`` (fwd slice / bwd all-gather — Megatron's lm-head
        entry);
      - rule-less consumers of sharded values get a ``tp_allgather``
        reshard;
      - vocab-sharded embedding lookups become ``tp_vocab_lookup``.

    Every tp-sharded variable (params, activations, their grads) is marked
    with ``tp_spec``; vjp_region fwd_ops indices are re-mapped around the
    insertions. Raises on propagation conflicts; a clean no-annotation
    program is returned untouched."""

    allowed_attrs = ("tp", "nominal_batch")

    def apply(self, program, scope=None):
        from ..parallel import tensor_parallel  # registers the tp_* ops
        tp = int(self.attrs["tp"])
        enforce(tp >= 2, f"tp_shard_pass needs tp >= 2, got {tp}",
                exc=InvalidArgumentError)
        if getattr(program, "_tp_applied", False):
            return program
        if not has_tp_annotations(program):
            return program
        nb = int(self.attrs.get("nominal_batch", BATCH_SENTINEL))
        res = propagate_sharding(program, tp_size=tp, nominal_batch=nb)
        if res.errors:
            raise ProgramAnalysisError(
                "tp_shard_pass: sharding propagation found conflicts:\n  "
                + "\n  ".join(str(d) for d in res.errors), res.errors)

        out = program.clone()
        out._dp_comm_applied = getattr(program, "_dp_comm_applied", False)
        block = out.global_block()
        sharded = res.sharded_vars()

        from .lowering import grad_var_name
        for name, spec in sharded.items():
            v = block.vars.get(name)
            if v is not None:
                v.tp_spec = tuple(spec)
            g = block.vars.get(grad_var_name(name))
            if g is not None and g.shape == (v.shape if v else None):
                g.tp_spec = tuple(spec)

        actions_by_idx = {a.op_idx: a for a in res.actions}
        pre_by_idx: Dict[int, List[Operator]] = {}
        post_by_idx: Dict[int, List[Operator]] = {}
        derived: Dict[Tuple[str, str], str] = {}   # (kind, src) -> name

        def _local_shape(name):
            v = block.vars.get(name)
            if v is None or v.shape is None:
                return None
            return list(tp_local_shape(
                v.shape, sharded.get(name), tp))

        def _mk_var(name, like, tp_spec=None):
            src = block.var(like)
            nv = block.create_var(name=name, shape=src.shape,
                                  dtype=src.dtype)
            nv.stop_gradient = bool(getattr(src, "stop_gradient", False))
            if tp_spec is not None and _is_sharded(tp_spec):
                nv.tp_spec = tuple(tp_spec)
            return nv

        n_psum = 0
        for idx, op in sorted(actions_by_idx.items()):
            a = actions_by_idx[idx]
            oper = block.ops[idx]
            if a.replace == "tp_vocab_lookup":
                wname = oper.inputs["W"][0]
                wshape = block.var(wname).shape
                oper.attrs = dict(oper.attrs)
                oper.attrs.update({"axis": TP_AXIS, "parts": tp,
                                   "vocab": int(wshape[0])})
                oper.type = "tp_vocab_lookup"
            for slot, i, dim in a.splits:
                src = oper.inputs[slot][i]
                key = ("split%d" % dim, src)
                nname = derived.get(key)
                if nname is None:
                    nname = src + TP_SPLIT_SUFFIX
                    spec = [None] * len(block.var(src).shape or ())
                    spec[dim] = TP_AXIS
                    _mk_var(nname, src, tp_spec=tuple(spec))
                    pre_by_idx.setdefault(idx, []).append(Operator(
                        block, "tp_split", inputs={"X": [src]},
                        outputs={"Out": [nname]},
                        attrs={"axis": TP_AXIS, "dim": dim, "parts": tp,
                               "op_role": oper.attrs.get("op_role")}))
                    derived[key] = nname
                oper.inputs[slot] = list(oper.inputs[slot])
                oper.inputs[slot][i] = nname
            for slot, i in a.idents:
                src = oper.inputs[slot][i]
                key = ("ident", src)
                nname = derived.get(key)
                if nname is None:
                    nname = src + TP_IDENT_SUFFIX
                    _mk_var(nname, src, tp_spec=sharded.get(src))
                    pre_by_idx.setdefault(idx, []).append(Operator(
                        block, "tp_ident", inputs={"X": [src]},
                        outputs={"Out": [nname]},
                        attrs={"axis": TP_AXIS,
                               "op_role": oper.attrs.get("op_role")}))
                    derived[key] = nname
                oper.inputs[slot] = list(oper.inputs[slot])
                oper.inputs[slot][i] = nname
            for slot, i, dim in a.gathers:
                src = oper.inputs[slot][i]
                key = ("gather", src)
                nname = derived.get(key)
                if nname is None:
                    nname = src + TP_GATHER_SUFFIX
                    _mk_var(nname, src)       # replicated (global shape)
                    pre_by_idx.setdefault(idx, []).append(Operator(
                        block, "tp_allgather", inputs={"X": [src]},
                        outputs={"Out": [nname]},
                        attrs={"axis": TP_AXIS, "dim": dim, "parts": tp,
                               "op_role": oper.attrs.get("op_role")}))
                    derived[key] = nname
                oper.inputs[slot] = list(oper.inputs[slot])
                oper.inputs[slot][i] = nname
            for slot, i in a.psums:
                out_name = oper.outputs[slot][i]
                part = out_name + TP_PART_SUFFIX
                _mk_var(part, out_name)
                oper.outputs[slot] = list(oper.outputs[slot])
                oper.outputs[slot][i] = part
                post_by_idx.setdefault(idx, []).append(Operator(
                    block, "tp_allreduce", inputs={"X": [part]},
                    outputs={"Out": [out_name]},
                    attrs={"axis": TP_AXIS,
                           "op_role": oper.attrs.get("op_role")}))
                n_psum += 1

        # --- localize shape-bearing attrs on the sharded path ------------
        # reshape carries its target shape as a concrete attr; per-shard
        # execution sees the tp-local input, so sharded target dims divide
        # by tp (the head-split [B,T,D@tp] -> [B,T,nh/tp,dh] case)
        for op in block.ops:
            if op.type != "reshape":
                continue
            spec = sharded.get(op.outputs["Out"][0])
            if not spec:
                continue
            shape = list(op.attrs.get("shape", ()))
            for d, s in enumerate(spec):
                if s is not None and d < len(shape) and shape[d] > 0:
                    enforce(shape[d] % tp == 0,
                            f"reshape target dim {d} ({shape[d]}) not "
                            f"divisible by tp={tp}",
                            exc=InvalidArgumentError)
                    shape[d] //= tp
            op.attrs = dict(op.attrs)
            op.attrs["shape"] = shape

        # --- rebuild the op list with the insertions ---------------------
        new_ops: List[Operator] = []
        inserted_anchor: Dict[int, int] = {}       # id(new op) -> old idx
        for idx, op in enumerate(block.ops):
            for nop in pre_by_idx.get(idx, ()):
                inserted_anchor[id(nop)] = idx
                new_ops.append(nop)
            new_ops.append(op)
            for nop in post_by_idx.get(idx, ()):
                inserted_anchor[id(nop)] = idx
                new_ops.append(nop)
        newidx = {id(op): i for i, op in enumerate(new_ops)}

        # re-map region fwd_ops: old indices -> new, plus inserted ops
        # anchored inside the segment (the collectives ARE forward ops)
        for op in new_ops:
            if op.type not in _REGION_TYPES:
                continue
            seg = set(int(i) for i in op.attrs.get("fwd_ops", ()))
            mapped = [newidx[id(block.ops[i])] for i in sorted(seg)]
            for nop_id, anchor in inserted_anchor.items():
                if anchor in seg:
                    mapped.append(newidx[nop_id])
            op.attrs["fwd_ops"] = sorted(mapped)
        block.ops = new_ops

        out._bump()
        out._tp_applied = True
        out._tp_size = tp
        out._tp_n_collectives = n_psum
        return out


# ---------------------------------------------------------------------------
# analytic wire model (ring accounting, shared discipline with
# grad_comm.analytic_wire_bytes / probe_common.collective_wire_bytes)
# ---------------------------------------------------------------------------


def _var_numel(block, name, nominal_batch):
    v = block.vars.get(name)
    if v is None or v.shape is None:
        return 0
    n = 1
    for d in _subst(v.shape, nominal_batch):
        n *= d
    return n


def tp_analytic_wire_bytes(program: Program, tp: int,
                           nominal_batch: int = 8) -> Optional[Dict]:
    """Per-device interconnect bytes per TRAIN step of the tp collectives a
    tp_shard_pass-rewritten program executes — the analytic side the HLO
    census is asserted against (tests/test_ztp_exec.py, tools/benchmark.py
    --tp rows). Ring accounting (probe_common.collective_wire_bytes):

      tp_allreduce (fwd psum):        2 n (tp-1)/tp
      tp_ident (BWD psum of its
        cotangent, same numel):       2 n (tp-1)/tp
      tp_split (BWD all-gather of
        the full cotangent):            n (tp-1)/tp
      tp_allgather (fwd):               n (tp-1)/tp
      tp_vocab_lookup (fwd psum):     2 n_out (tp-1)/tp

    Sizes are LOCAL-shape-independent (psum/all-gather outputs are the
    replicated/global tensors). -1 dims count as `nominal_batch` rows.
    Backward entries are counted only when their input is differentiable
    (stop_gradient values never get a cotangent). Returns None for
    programs the pass did not rewrite."""
    if not getattr(program, "_tp_applied", False):
        return None
    block = program.global_block()
    f = (tp - 1) / tp
    ar = ag = 0.0
    counts = {"tp_allreduce": 0, "tp_ident": 0, "tp_split": 0,
              "tp_allgather": 0, "tp_vocab_lookup": 0}
    for op in block.ops:
        if op.type not in counts:
            continue
        counts[op.type] += 1
        if op.type in ("tp_allreduce", "tp_vocab_lookup"):
            n = _var_numel(block, op.outputs["Out"][0], nominal_batch)
            ar += 2.0 * n * 4 * f
        elif op.type == "tp_ident":
            src = block.vars.get(op.inputs["X"][0])
            if src is not None and not getattr(src, "stop_gradient", False):
                n = _var_numel(block, op.inputs["X"][0], nominal_batch)
                ar += 2.0 * n * 4 * f
        elif op.type == "tp_split":
            src = block.vars.get(op.inputs["X"][0])
            if src is not None and not getattr(src, "stop_gradient", False):
                n = _var_numel(block, op.inputs["X"][0], nominal_batch)
                ag += n * 4 * f
        elif op.type == "tp_allgather":
            n = _var_numel(block, op.outputs["Out"][0], nominal_batch)
            ag += n * 4 * f
    return {"tp": tp,
            "tp_allreduce_wire_bytes": int(ar),
            "tp_allgather_wire_bytes": int(ag),
            "tp_wire_bytes": int(ar + ag),
            "tp_op_counts": counts}
