"""Op registry: op type → jax lowering rule.

Capability equivalent of the reference's operator registry + kernel dispatch
(reference: paddle/fluid/framework/op_registry.h:185-236, op_kernel_type.h:27,
operator.cc:657-737). Where the reference dispatches at *runtime* to a
(place, dtype, layout, library) kernel per op, here each op registers ONE
lowering rule that emits jax/XLA operations at *trace* time; XLA then does the
per-backend kernel selection, layout assignment, and fusion. Pallas kernels
plug in as alternative lowerings gated on backend.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..core.enforce import AlreadyExistsError, NotFoundError

# A lowering takes (ctx, ins, attrs) where ins: slot -> list of jax values, and
# returns outs: slot -> list of jax values.
LowerFn = Callable[["LowerCtx", Dict[str, List[Any]], Dict[str, Any]],
                   Dict[str, List[Any]]]

# An infer_spec takes (ctx, in_shapes, in_dtypes, attrs) where in_shapes /
# in_dtypes mirror the lowering's ins layout (slot -> list of shape tuples /
# numpy dtypes) and returns outs: slot -> list of (shape, dtype) pairs.
# `ctx` is an analysis.InferCtx (declared-shape lookups, mesh axis sizes).
# Most ops don't need one: the analyzer derives shapes by abstract-evaluating
# the lowering itself (jax.eval_shape), so the kernel IS the shape function
# and the two can never drift. An explicit spec is only registered where the
# lowering cannot be abstractly evaluated standalone (collectives that need a
# mesh axis, region pseudo-ops, sub-block control flow).
InferFn = Callable[[Any, Dict[str, List[tuple]], Dict[str, List[Any]],
                    Dict[str, Any]], Dict[str, List[tuple]]]


@dataclass
class OpDef:
    type: str
    lower: LowerFn
    # ops whose outputs must never be differentiated through (metrics, prints)
    stop_gradient: bool = False
    # extra metadata for passes/inspection
    tags: tuple = ()
    # optional explicit shape/dtype rule (see InferFn above); None = derive
    # from the lowering via jax.eval_shape (framework/analysis.py)
    infer_spec: Optional[InferFn] = None


_OPS: Dict[str, OpDef] = {}


def dim_prod(dims) -> Any:
    """Product of shape dims WITHOUT int() coercion: under jax.export a
    leading dim may be symbolic, and int() on it raises. Use this in any
    lowering that flattens leading dims."""
    out = 1
    for d in dims:
        out = out * d
    return out


def register_op(op_type: str, *, stop_gradient: bool = False, tags=(),
                infer_spec: Optional[InferFn] = None):
    """Decorator registering a lowering rule (≙ REGISTER_OPERATOR +
    REGISTER_OP_*_KERNEL, reference op_registry.h:185-217)."""

    def deco(fn: LowerFn) -> LowerFn:
        if op_type in _OPS:
            raise AlreadyExistsError(f"op {op_type!r} already registered")
        _OPS[op_type] = OpDef(op_type, fn, stop_gradient=stop_gradient,
                              tags=tuple(tags), infer_spec=infer_spec)
        return fn

    return deco


def register_infer_spec(op_type: str):
    """Decorator attaching an explicit shape/dtype inference rule to an
    already-registered op (≙ the reference's InferShape functions living
    next to each OpMaker, framework/operator.h InferShapeContext) — used
    where the analyzer cannot abstract-evaluate the lowering itself."""

    def deco(fn: InferFn) -> InferFn:
        op = _OPS.get(op_type)
        if op is None:
            raise NotFoundError(
                f"cannot attach infer_spec: op {op_type!r} not registered")
        if op.infer_spec is not None:
            raise AlreadyExistsError(
                f"op {op_type!r} already has an infer_spec")
        op.infer_spec = fn
        return fn

    return deco


# A shard-propagation rule mirrors infer_spec at the sharding layer
# (framework/sharding.py): (ShardCtx, in_specs, attrs) -> out_specs, where a
# spec is a per-dim tuple of mesh-axis-or-None. Rules are registered in a
# side table (not on OpDef) so sharding rules for generic ops can be
# declared without forcing the op module import graph; lookup falls back to
# the default replicated rule in framework/sharding.py.
_SHARD_RULES: Dict[str, Any] = {}


def register_shard_spec(op_type: str):
    """Decorator registering the sharding-propagation rule for `op_type`
    (lives alongside register_infer_spec: same per-op contract, one layer
    up — how shardings flow through the op instead of shapes)."""

    def deco(fn):
        if op_type in _SHARD_RULES:
            raise AlreadyExistsError(
                f"op {op_type!r} already has a shard-propagation rule")
        _SHARD_RULES[op_type] = fn
        return fn

    return deco


def lookup_shard_rule(op_type: str):
    """The registered shard-propagation rule for `op_type`, or None."""
    return _SHARD_RULES.get(op_type)


# An effect rule refines the dataflow effect set of one op
# (framework/dataflow.py): (op) -> dict with any of the keys
#   collective_axes: tuple of mesh axis names the op communicates over
#                    (a collective both orders execution across shards of
#                    those axes AND makes its outputs axis-consistent),
#   rng:             True when the op draws per-step randomness (per-shard
#                    decorrelated seeds on the dp axis),
#   inplace:         ((in_name, out_name), ...) aliased buffer pairs beyond
#                    the same-name read+write default.
# reads/writes always derive from op.inputs/op.outputs; rules only ADD the
# semantics the slot lists cannot express. Registered in a side table like
# _SHARD_RULES so parallel modules can declare effects without forcing the
# op module import graph.
_EFFECT_RULES: Dict[str, Any] = {}


def register_effects(op_type: str):
    """Decorator registering the dataflow effect rule for `op_type` (lives
    alongside register_infer_spec/register_shard_spec: same per-op
    contract, one layer up — what the op DOES to buffers and mesh axes
    instead of what shapes/shardings it emits)."""

    def deco(fn):
        if op_type in _EFFECT_RULES:
            raise AlreadyExistsError(
                f"op {op_type!r} already has an effect rule")
        _EFFECT_RULES[op_type] = fn
        return fn

    return deco


def lookup_effect_rule(op_type: str):
    """The registered effect rule for `op_type`, or None (pure compute:
    reads its inputs, writes its outputs, no collectives, no rng)."""
    return _EFFECT_RULES.get(op_type)


def lookup_op(op_type: str) -> OpDef:
    op = _OPS.get(op_type)
    if op is None:
        # Make sure all builtin op modules are imported (they self-register).
        _ensure_builtin_ops()
        op = _OPS.get(op_type)
    if op is None:
        raise NotFoundError(f"no op registered with type {op_type!r}; "
                            f"known ops: {sorted(_OPS)[:20]}...")
    return op


def registered_ops() -> List[str]:
    _ensure_builtin_ops()
    return sorted(_OPS)


_builtins_loaded = False


def _ensure_builtin_ops():
    global _builtins_loaded
    if _builtins_loaded:
        return
    _builtins_loaded = True
    # import for registration side effects
    from ..ops import (elementwise, nn_ops, tensor_ops, reduce_ops,  # noqa: F401
                       optimizer_ops, random_ops, sequence_ops, metric_ops,
                       control_ops, loss_ops, sequence_label_ops,
                       beam_search_ops, detection_ops, pallas_kernels)
    from ..fusion import decode_attention, recurrent  # noqa: F401


@dataclass
class LowerCtx:
    """Per-trace context handed to lowerings (≙ ExecutionContext,
    reference framework/operator.h ExecutionContext).

    rng_key: base PRNG key for this step; ops take fresh keys via next_key().
    is_test: inference mode (dropout/batch-norm behave accordingly).
    mesh / axis info is used by parallel-aware lowerings.
    """
    rng_key: Any = None
    is_test: bool = False
    mesh: Any = None
    _rng_counter: int = 0
    extras: dict = field(default_factory=dict)

    def next_key(self):
        import jax
        self._rng_counter += 1
        return jax.random.fold_in(self.rng_key, self._rng_counter)
