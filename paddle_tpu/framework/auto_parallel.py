"""Auto-parallel planner: cost-model-guided search over the dp x pp x tp
strategy space.

Every prior subsystem turned a parallelism decision into an option —
dp comm modes (r08), pipeline schedules (r09), tp sharding (r11), memory
plans (r18) — and every benched configuration was still hand-picked.
This module closes ROADMAP item 1: it ENUMERATES the joint space
(mesh factorization x reduce mode x quantized wire x bucket size x
pipeline schedule/microbatches x memory plan), PRUNES infeasible points
with `costs.strategy_is_feasible` (the executor/pass gates run
statically, named rejection reasons), SCORES survivors with
`costs.predict` scalarized by `costs.predicted_step_seconds` under a
per-device HBM budget (`costs.predicted_device_bytes`), and REFINES the
frontier with simulated annealing over the discrete knobs — the
TVM-style cost-model-guided autotuning loop (PAPERS.md), with GDP's
learned placement policy as the named future refinement.

Two consumers:

- `ParallelExecutor` behind `BuildStrategy.auto_parallel` (kill switch
  PTPU_AUTO_PARALLEL=0, in the compile cache key): the executor plans on
  first prepare and adopts the chosen strategy AND mesh factorization.
- `parallel/elastic.py` on restore to a CHANGED world size
  (`replan_on_restore`): the kept strategy and the re-planned one are
  both priced — predicted step seconds plus the one-time redistribution
  wire bytes of each restore layout (`parallel/reshard.py`, validated
  exactly against `costs.reshard_wire_bytes`) — and the executor adopts
  the re-plan only when it wins, with the break-even step count
  recorded. This is what makes an elastic resize PROFITABLE, not just
  correct.

The search is DETERMINISTIC for a fixed seed (the annealer is the only
stochastic part and draws from `random.Random(seed)`), so a re-plan on
restore reproduces bit-identically across retries. An optional
measured refinement (`measure_fn`/`measure_k`) re-ranks the top of the
predicted frontier by real step time — the TVM move for meshes whose
constants differ from the v5e model (the CPU bench mesh above all);
`tools/bench_plan.py` uses it, the executor path stays model-only.
"""

from __future__ import annotations

import dataclasses
import time
from collections import Counter
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core.enforce import InvalidArgumentError, enforce
from . import costs as _costs

_DEFAULT_BUCKET = 4 << 20


# ---------------------------------------------------------------------------
# the strategy point: one candidate assignment of every searched knob
# ---------------------------------------------------------------------------


@dataclass(frozen=True, order=True)
class StrategyPoint:
    """One point of the joint strategy space. Frozen + ordered so points
    are hashable cache keys and ties sort deterministically."""
    dp: int = 1
    pp: int = 1
    tp: int = 1
    microbatches: int = 1
    schedule: str = "1f1b"
    reduce: str = "allreduce"        # allreduce | reduce | reduce_scatter
    quant: str = ""                  # '' | int8 | bf16
    bucket_bytes: int = _DEFAULT_BUCKET
    memory_plan: bool = False
    # host-offload tier (framework/offload.py): '' = device-resident,
    # 'optimizer' = ZeRO-offload the accumulator shards to the pinned
    # host pool between steps. Priced by costs.predict's `offload`
    # section — predicted_step_seconds charges the unhidden PCIe
    # residual, so a point whose round-trip cannot overlap loses here
    # instead of at runtime. Numerics-preserving (the round-trip is
    # bitwise), so executor adoption inherits rather than searches it.
    offload: str = ""                # '' | optimizer

    @property
    def explicit(self) -> bool:
        return self.reduce == "reduce_scatter" or bool(self.quant)

    def canonical(self) -> "StrategyPoint":
        """Zero out knobs that do not change the executed program, so
        equivalent points dedupe to ONE evaluation: microbatches and
        schedule without a pipeline, quant/bucket outside the explicit
        comm path, and the reduce mode on a 1-device data axis (the
        Reduce heuristic and the explicit pipeline are both no-ops
        there — except under tp, whose rewrite runs only in the manual
        modes)."""
        p = self
        if p.pp < 2:
            p = dataclasses.replace(p, microbatches=1, schedule="1f1b")
        if not p.explicit:
            p = dataclasses.replace(p, quant="",
                                    bucket_bytes=_DEFAULT_BUCKET)
        if p.quant and p.reduce == "reduce":
            # under a quantized wire the pipeline is explicit either
            # way and shard_update is keyed on ReduceScatter alone, so
            # reduce+quant executes IDENTICALLY to allreduce+quant
            p = dataclasses.replace(p, reduce="allreduce")
        if p.dp == 1 and p.tp == 1 and p.reduce != "allreduce" \
                and not p.quant:
            p = dataclasses.replace(p, reduce="allreduce",
                                    bucket_bytes=_DEFAULT_BUCKET)
        return p

    def mesh_axes(self) -> Dict[str, int]:
        axes = {"dp": self.dp}
        if self.pp > 1:
            axes["pp"] = self.pp
        if self.tp > 1:
            axes["tp"] = self.tp
        return axes

    def to_build_strategy(self, base=None):
        """The executable BuildStrategy for this point: the searched
        knobs overwrite `base` (a BuildStrategy or None), every
        un-searched field (error feedback, quant block, memory-plan
        budgets, auto_parallel itself) is inherited."""
        from ..parallel.strategy import BuildStrategy, ReduceStrategy
        base = base or BuildStrategy()
        reduce_enum = {"allreduce": ReduceStrategy.AllReduce,
                       "reduce": ReduceStrategy.Reduce,
                       "reduce_scatter": ReduceStrategy.ReduceScatter
                       }[self.reduce]
        return dataclasses.replace(
            base,
            reduce_strategy=reduce_enum,
            quant_comm=self.quant,
            comm_bucket_bytes=int(self.bucket_bytes),
            pipeline_stages=self.pp if self.pp >= 2 else 0,
            num_microbatches=(self.microbatches if self.pp >= 2 else
                              base.num_microbatches),
            pipeline_schedule=self.schedule,
            memory_plan=self.memory_plan,
            offload_optimizer_state=(self.offload == "optimizer"),
        )

    def census_exact(self) -> bool:
        """Whether this point's wire model is structurally EXACT against
        the HLO census: the explicit pipeline and plain SPMD allreduce
        are (r08/r12 discipline); the SPMD `reduce` (ZeRO-1) lowering is
        XLA-owned and only approximately modeled."""
        return self.reduce != "reduce"

    def family(self) -> Tuple:
        """The coarse identity of a point — mesh factorization + comm
        mode. Measured refinement samples the best-predicted point of
        each family so a frontier dominated by near-identical variants
        (bucket sizes, microbatch counts) still measures genuinely
        different strategies."""
        return (self.dp, self.pp, self.tp, self.reduce, self.quant)

    def describe(self) -> str:
        parts = [f"dp{self.dp}"]
        if self.pp > 1:
            parts.append(f"pp{self.pp}({self.schedule},m{self.microbatches})")
        if self.tp > 1:
            parts.append(f"tp{self.tp}")
        parts.append({"allreduce": "ar", "reduce": "zero1",
                      "reduce_scatter": "rs"}[self.reduce])
        if self.quant:
            parts.append(self.quant)
        if self.explicit and self.bucket_bytes != _DEFAULT_BUCKET:
            parts.append(f"b{self.bucket_bytes >> 20}MiB")
        if self.memory_plan:
            parts.append("memplan")
        if self.offload:
            parts.append(f"offl-{self.offload[:3]}")
        return "x".join(parts[:1]) + "-" + "-".join(parts[1:])


@dataclass
class SearchSpace:
    """The discrete option sets the planner enumerates/anneals over.
    The defaults cover every knob the executor exposes; a consumer can
    pin any of them (replan_on_restore pins quant to the saved wire
    dtype so residual error-feedback state stays transferable)."""
    reduce_modes: Tuple[str, ...] = ("allreduce", "reduce",
                                     "reduce_scatter")
    # bf16 wire is deliberately NOT in the default space: this
    # container's jaxlib-0.4.x CPU collectives promote bf16 payloads to
    # f32 (census-measured, parallel/collective.py _pin_wire), so the
    # 0.5x wire model would mispredict by exactly 2x on the mesh the
    # benches run on. Pass quant_modes=("", "int8", "bf16") explicitly
    # on a backend whose collectives carry bf16 natively.
    quant_modes: Tuple[str, ...] = ("", "int8")
    schedules: Tuple[str, ...] = ("1f1b", "gpipe")
    microbatches: Tuple[int, ...] = (2, 4, 8)
    bucket_bytes: Tuple[int, ...] = (1 << 20, _DEFAULT_BUCKET, 16 << 20)
    memory_plan: Tuple[bool, ...] = (False, True)
    # '' only by default: the HBM budget this container's planner prices
    # against is the v5e constant, and offloading optimizer state is a
    # capacity lever the operator pulls (bench_plan / lint --strategy
    # pass offload_modes=("", "optimizer") to search it); the annealer
    # reaches it in one move once it is in the space.
    offload_modes: Tuple[str, ...] = ("",)
    max_pp: int = 8
    max_tp: int = 8


def numerics_preserving_space(strategy_base=None) -> SearchSpace:
    """The search space the EXECUTOR adoption and the elastic re-plan
    use: every knob except the quantized wire dtype, which stays pinned
    to the user's own setting. int8/bf16 gradient compression changes
    the training math (r08 committed the convergence deltas: int8+EF
    max |Δloss| ~0.03), so the planner never flips it on implicitly —
    it remains a searched knob on the tooling surfaces (bench_plan,
    lint --strategy) where the operator asked for the full space."""
    quant = getattr(strategy_base, "quant_comm", "") or ""
    # offload is numerics-preserving but stays PINNED to the user's own
    # setting here too: it is a capacity/latency trade the operator
    # chose, not a knob adoption should silently flip either way
    offload = "optimizer" if getattr(strategy_base,
                                     "offload_optimizer_state", False) \
        else ""
    return SearchSpace(quant_modes=(quant,), offload_modes=(offload,))


def mesh_factorizations(n_devices: int, *, max_pp: int = 8,
                        max_tp: int = 8) -> List[Tuple[int, int, int]]:
    """Every (dp, pp, tp) with dp*pp*tp == n_devices within the pp/tp
    caps, dp-major order (the all-dp point first)."""
    out = []
    for pp in range(1, min(n_devices, max_pp) + 1):
        if n_devices % pp:
            continue
        rest = n_devices // pp
        for tp in range(1, min(rest, max_tp) + 1):
            if rest % tp:
                continue
            out.append((rest // tp, pp, tp))
    return sorted(out, key=lambda f: (-f[0], f[1], f[2]))


# ---------------------------------------------------------------------------
# evaluation: feasibility -> predict -> scalarize, memoized per point
# ---------------------------------------------------------------------------


class _Evaluator:
    """Memoized point evaluation over ONE (program, batch, budget). The
    rewritten programs strategy_is_feasible produces are cached inside
    each row; predict() runs once per canonical point."""

    def __init__(self, program, nominal_batch, hbm_bytes, strategy_base):
        self.program = program
        self.nominal_batch = int(nominal_batch)
        self.hbm_bytes = int(hbm_bytes)
        self.strategy_base = strategy_base
        self.rows: Dict[StrategyPoint, Dict] = {}
        self.rejections: Counter = Counter()

    def evaluate(self, point: StrategyPoint) -> Dict:
        point = point.canonical()
        row = self.rows.get(point)
        if row is not None:
            return row
        strategy = point.to_build_strategy(self.strategy_base)
        axes = point.mesh_axes()
        feas = _costs.strategy_is_feasible(
            self.program, strategy, mesh_axes=axes,
            nominal_batch=self.nominal_batch)
        row = {"point": point, "feasible": feas.ok,
               "reasons": feas.reasons, "strategy": strategy}
        if feas.ok:
            report = _costs.predict(feas.program, strategy,
                                    dp=point.dp, tp=point.tp,
                                    nominal_batch=self.nominal_batch)
            breakdown = _costs.predicted_step_seconds(
                report, mesh_axes=axes, strategy=strategy)
            dev_bytes = _costs.predicted_device_bytes(report)
            row.update({"report": report, "breakdown": breakdown,
                        "predicted_s": breakdown["total_s"],
                        "device_bytes": dev_bytes})
            if dev_bytes > self.hbm_bytes:
                row["feasible"] = False
                row["reasons"] = [{
                    "code": "hbm-budget",
                    "message": (f"predicted per-device footprint "
                                f"{dev_bytes} exceeds the HBM budget "
                                f"{self.hbm_bytes}")}]
            elif point.tp > 1 and not report.get("tp_comm"):
                # the executor WOULD run this (a tp axis nothing shards
                # over is just replication), but a planner that "wins"
                # by idling devices has found a loophole, not a
                # strategy — planner policy, distinct from the
                # executor-gate reasons strategy_is_feasible names
                row["feasible"] = False
                row["reasons"] = [{
                    "code": "tp-unsharded",
                    "message": (f"tp={point.tp} but the rewrite shards "
                                f"nothing over it (no tp_comm model): "
                                f"the axis would run replicated, "
                                f"wasting its devices")}]
        if not row["feasible"]:
            for r in row["reasons"]:
                self.rejections[r["code"]] += 1
        self.rows[point] = row
        return row

    def feasible_rows(self) -> List[Dict]:
        rows = [r for r in self.rows.values() if r["feasible"]]
        # deterministic total order: predicted seconds first, an
        # unplanned point beats a planned one at equal time (the plan
        # costs a rewrite and buys nothing the budget needed), smaller
        # footprint next, the point's own field order last
        return sorted(rows, key=lambda r: (r["predicted_s"],
                                           r["point"].memory_plan,
                                           r["device_bytes"],
                                           r["point"]))


# ---------------------------------------------------------------------------
# the planner
# ---------------------------------------------------------------------------


@dataclass
class PlanResult:
    point: StrategyPoint
    strategy: Any
    mesh_axes: Dict[str, int]
    predicted: Dict
    predicted_step_s: float
    breakdown: Dict
    device_bytes: int
    ranking: List[Dict]
    rejections: Dict[str, int]
    n_enumerated: int
    n_feasible: int
    n_annealed: int
    search_s: float
    seed: int
    nominal_batch: int
    measured: bool = False
    measured_step_s: Optional[float] = None

    def rank_of(self, point: StrategyPoint) -> Optional[int]:
        """1-based rank of a point in the predicted frontier (None when
        the point was not evaluated feasible)."""
        point = point.canonical()
        for i, row in enumerate(self.ranking):
            if row["point"] == point:
                return i + 1
        return None

    def summary(self) -> Dict:
        return {
            "chosen": self.point.describe(),
            "mesh_axes": dict(self.mesh_axes),
            "predicted_step_ms": round(self.predicted_step_s * 1e3, 6),
            "breakdown_us": {k: round(v * 1e6, 3)
                             for k, v in self.breakdown.items()
                             if k.endswith("_s")},
            "device_bytes": int(self.device_bytes),
            "n_enumerated": self.n_enumerated,
            "n_feasible": self.n_feasible,
            "n_annealed": self.n_annealed,
            "rejections": dict(self.rejections),
            "search_s": round(self.search_s, 3),
            "seed": self.seed,
            "nominal_batch": self.nominal_batch,
            "measured": self.measured,
            "measured_step_ms": (round(self.measured_step_s * 1e3, 3)
                                 if self.measured_step_s is not None
                                 else None),
            "frontier": [{"point": r["point"].describe(),
                          "predicted_ms":
                              round(r["predicted_s"] * 1e3, 6),
                          **({"measured_ms":
                              round(r["measured_s"] * 1e3, 3)}
                             if r.get("measured_s") is not None else {})}
                         for r in self.ranking[:8]],
        }


def _coarse_points(factors, space: SearchSpace, nominal_batch: int
                   ) -> List[StrategyPoint]:
    """The enumeration grid the annealer refines from: every mesh
    factorization x reduce/quant mode, pipelined points at each
    admissible microbatch count under the default schedule/bucket.
    Deliberately coarse — gpipe, bucket sizes, bf16 wire and the memory
    plan are one annealing move away from any of these."""
    points = []
    # the space's quant set VERBATIM: a numerics-preserving space pins
    # it to the user's wire dtype, and the grid must neither drop the
    # pin nor smuggle unquantized points back in
    quants = list(space.quant_modes) or [""]
    for dp, pp, tp in factors:
        combos = [(mode, q) for mode in space.reduce_modes
                  for q in quants]
        if tp > 1:
            # the tp rewrite runs only under the manual (explicit-comm)
            # modes; SPMD tp is unmodeled, so the planner does not
            # enumerate it
            combos = [c for c in combos if c[0] == "reduce_scatter"
                      or c[1]]
            if not combos:
                continue
        mbs = [1]
        if pp >= 2:
            mbs = [m for m in space.microbatches
                   if nominal_batch % max(dp * m, 1) == 0] or \
                  [max(space.microbatches)]
        for reduce, quant in combos:
            for m in mbs:
                points.append(StrategyPoint(
                    dp=dp, pp=pp, tp=tp, microbatches=m,
                    schedule=space.schedules[0], reduce=reduce,
                    quant=quant,
                    offload=(space.offload_modes or ("",))[0],
                    ).canonical())
    # dedupe preserving order
    seen, out = set(), []
    for p in points:
        if p not in seen:
            seen.add(p)
            out.append(p)
    return out


def _neighbors(point: StrategyPoint, factors, space: SearchSpace
               ) -> List[StrategyPoint]:
    """Every single-knob mutation of `point` inside the space — the
    annealer's move set. Deterministically ordered."""
    out = []
    # re-factor the mesh: any other factorization keeping total devices
    for dp, pp, tp in factors:
        if (dp, pp, tp) != (point.dp, point.pp, point.tp):
            out.append(dataclasses.replace(point, dp=dp, pp=pp, tp=tp))
    if point.pp >= 2:
        for s in space.schedules:
            if s != point.schedule:
                out.append(dataclasses.replace(point, schedule=s))
        for m in space.microbatches:
            if m != point.microbatches:
                out.append(dataclasses.replace(point, microbatches=m))
    for mode in space.reduce_modes:
        if mode != point.reduce:
            out.append(dataclasses.replace(point, reduce=mode))
    if point.explicit:
        for q in space.quant_modes:
            if q != point.quant:
                out.append(dataclasses.replace(point, quant=q))
        for b in space.bucket_bytes:
            if b != point.bucket_bytes:
                out.append(dataclasses.replace(point, bucket_bytes=b))
    for mp in space.memory_plan:
        if mp != point.memory_plan:
            out.append(dataclasses.replace(point, memory_plan=mp))
    for om in space.offload_modes:
        if om != point.offload:
            out.append(dataclasses.replace(point, offload=om))
    return [p.canonical() for p in out]


def plan(program, mesh_shape, *, nominal_batch: int = 8,
         strategy_base=None,
         hbm_bytes: int = _costs.V5E_HBM_BYTES,
         space: Optional[SearchSpace] = None,
         anneal_iters: int = 64,
         seed: int = 0,
         measure_fn: Optional[Callable] = None,
         measure_k: int = 0,
         measure_band: float = 0.10,
         speculative: Optional[Dict] = None) -> PlanResult:
    """Choose a BuildStrategy + mesh factorization for `program`.

    `mesh_shape`: an int device count (the planner owns the
    factorization) or a {"dp":, "pp":, "tp":} dict pinning the mesh (the
    planner then searches only the non-mesh knobs). `strategy_base`
    supplies every un-searched BuildStrategy field. `measure_fn(row) ->
    seconds` with `measure_k > 0` re-ranks the top of the predicted
    frontier by measurement (TVM-style; `row` is a frontier entry whose
    "strategy"/"point" fields describe the candidate).

    `speculative` describes a speculative-decoding serving deployment
    ({"gamma":, "acceptance":, ...} — `costs.speculative_expectation`'s
    signature); the expectation is attached to the chosen report's
    `speculative` section. An `acceptance` callable is evaluated HERE —
    the hook that feeds a live engine's measured acceptance rate into
    the plan, the same measured-refinement idea as measure_fn.

    Returns a PlanResult; raises InvalidArgumentError naming the tallied
    rejection reasons when NO point of the space is feasible."""
    import math
    import random

    from ..observability import tracing as _tracing

    t0 = time.perf_counter()
    space = space or SearchSpace()
    if isinstance(mesh_shape, dict):
        axes = dict(mesh_shape)
        factors = [(int(axes.get("dp", 1)), int(axes.get("pp", 1)),
                    int(axes.get("tp", 1)))]
    else:
        n = int(mesh_shape)
        enforce(n >= 1, f"plan() needs a positive device count, got {n}",
                exc=InvalidArgumentError)
        factors = mesh_factorizations(n, max_pp=space.max_pp,
                                      max_tp=space.max_tp)

    n_devices = factors[0][0] * factors[0][1] * factors[0][2]
    ev = _Evaluator(program, nominal_batch, hbm_bytes, strategy_base)
    with _tracing.span("pass", "auto_parallel/plan",
                       devices=n_devices, seed=seed) as sp:
        for p in _coarse_points(factors, space, nominal_batch):
            ev.evaluate(p)
        frontier = ev.feasible_rows()
        enforce(frontier,
                f"auto_parallel.plan: no feasible strategy in the "
                f"search space for this program/mesh — rejections: "
                f"{dict(ev.rejections)}", exc=InvalidArgumentError)

        # simulated-annealing refinement over the discrete knobs:
        # Metropolis on predicted step seconds, geometric temperature
        # decay, deterministic for a fixed seed
        rng = random.Random(seed)
        current = frontier[0]
        n_annealed = 0
        t_scale = max(current["predicted_s"], 1e-9)
        # every evaluation lands in the evaluator's memo, so the
        # post-loop feasible_rows() re-sort IS the best-seen tracking
        for i in range(max(anneal_iters, 0)):
            temp = 0.35 * t_scale * (0.92 ** i)
            moves = _neighbors(current["point"], factors, space)
            cand = ev.evaluate(rng.choice(moves))
            n_annealed += 1
            if not cand["feasible"]:
                continue
            delta = cand["predicted_s"] - current["predicted_s"]
            if delta <= 0 or rng.random() < math.exp(
                    -delta / max(temp, 1e-12)):
                current = cand

        ranking = ev.feasible_rows()
        chosen = ranking[0]
        measured = False
        measured_s = None
        if measure_fn is not None and measure_k > 0:
            # measure the best-predicted representative of the top
            # `measure_k` strategy FAMILIES (mesh x comm mode), not the
            # raw top-k rows — the predicted frontier often packs many
            # near-identical variants of one family
            top, seen_families = [], set()
            for row in ranking:
                fam = row["point"].family()
                if fam in seen_families:
                    continue
                seen_families.add(fam)
                top.append(row)
                if len(top) >= measure_k:
                    break
            for row in top:
                row["measured_s"] = float(measure_fn(row))
            # within the measurement noise band of the fastest point,
            # prefer a strategy whose wire model is census-EXACT (the
            # XLA-owned `reduce` lowering is only approximately priced):
            # no measured evidence separates them, and the exact one is
            # the auditable choice
            fastest = min(r["measured_s"] for r in top)
            eligible = [r for r in top
                        if r["measured_s"] <= fastest * (1 + measure_band)]
            exact = [r for r in eligible if r["point"].census_exact()]
            chosen = min(exact or eligible,
                         key=lambda r: (r["measured_s"],
                                        r["predicted_s"],
                                        r["point"]))
            measured = True
            measured_s = chosen["measured_s"]
        sp.attrs["chosen"] = chosen["point"].describe()
        sp.attrs["n_points"] = len(ev.rows)
        if speculative is not None:
            chosen["report"]["speculative"] = \
                _costs.speculative_expectation(**speculative)

    result = PlanResult(
        point=chosen["point"],
        strategy=chosen["strategy"],
        mesh_axes=chosen["point"].mesh_axes(),
        predicted=chosen["report"],
        predicted_step_s=chosen["predicted_s"],
        breakdown=chosen["breakdown"],
        device_bytes=chosen["device_bytes"],
        ranking=[{k: r[k] for k in ("point", "predicted_s",
                                    "device_bytes", "breakdown",
                                    "strategy")}
                 | ({"measured_s": r["measured_s"]}
                    if r.get("measured_s") is not None else {})
                 for r in ranking],
        rejections=dict(ev.rejections),
        n_enumerated=len(ev.rows),
        n_feasible=len(ranking),
        n_annealed=n_annealed,
        search_s=time.perf_counter() - t0,
        seed=seed,
        nominal_batch=int(nominal_batch),
        measured=measured,
        measured_step_s=measured_s,
    )
    return result


# ---------------------------------------------------------------------------
# re-plan on elastic resize (ROADMAP items 1 + 4's joint closing move)
# ---------------------------------------------------------------------------


def replan_on_restore(executor, program, scope, meta, snapshot_dir, *,
                      seed: int = 0,
                      nominal_batch: Optional[int] = None,
                      amortize_horizon: float = 10_000.0) -> Dict:
    """Price keeping the restored strategy vs re-planning for the NEW
    world, adopt the winner onto `executor`, and return the decision
    record (rides restore_train_state's meta["replan"]).

    Pricing: predicted step seconds of each side
    (`costs.predicted_step_seconds`) PLUS each side's one-time restore
    redistribution — `reshard.plan_restore`'s schedule, whose wire bytes
    are validated EXACTLY against `costs.reshard_wire_bytes`. Both
    prices are computed BEFORE the decision: the re-plan is adopted only
    when the kept strategy is infeasible on the new world, or its
    per-step gain pays back any extra one-time reshard wire within
    `amortize_horizon` steps (the break-even rides the record as
    `amortize_steps`). A "keep" decision leaves the executor exactly as
    it was. The searched space pins the quantized wire dtype to the
    executor's (saved) config so error-feedback residual layouts stay
    transferable across the resize. Deterministic for a fixed `seed`."""
    from ..parallel import reshard as _reshard
    from ..parallel.mesh import DeviceMesh
    from ..sharded_checkpoint import ShardedCheckpoint

    t0 = time.perf_counter()
    devices = list(executor.mesh.jax_mesh.devices.flat)
    base = executor.build_strategy
    batch = int(nominal_batch or max(
        (s[0] for s in (getattr(executor, "_feed_shapes", None) or {})
         .values() if len(s) >= 1), default=8))
    ckpt = ShardedCheckpoint(snapshot_dir)

    def _reshard_wire(prepared) -> Optional[float]:
        try:
            rp = _reshard.plan_restore(ckpt, meta, prepared, executor)
            return float(rp.wire_bytes)
        except Exception:
            return None

    # pricing must not trigger the executor's own prepare-time planner:
    # prepare_program below would otherwise adopt a plan MID-pricing and
    # the kept side would be priced on the re-planned layout
    executor._auto_plan_suspended = True
    try:
        # the KEPT side: the restored strategy on the new device count
        kept_axes = dict(executor.mesh.axes)
        kept_feas = _costs.strategy_is_feasible(
            program, base, mesh_axes=kept_axes, nominal_batch=batch)
        kept = {"axes": kept_axes, "feasible": kept_feas.ok,
                "reasons": kept_feas.reason_codes(),
                "predicted_step_s": None, "reshard_wire_bytes": None}
        if kept_feas.ok:
            report = _costs.predict(kept_feas.program, base,
                                    dp=kept_axes.get("dp", 1),
                                    tp=kept_axes.get("tp", 1),
                                    nominal_batch=batch)
            kept["predicted_step_s"] = _costs.predicted_step_seconds(
                report, mesh_axes=kept_axes, strategy=base)["total_s"]
            kept["reshard_wire_bytes"] = _reshard_wire(
                executor.prepare_program(program, scope))

        # the RE-PLANNED side: full search over the new world, quant
        # pinned; its reshard price needs the executor temporarily on
        # the chosen config (reverted below if "keep" wins)
        result = plan(program, len(devices), nominal_batch=batch,
                      strategy_base=base,
                      space=numerics_preserving_space(base), seed=seed)
        kept_mesh = executor.mesh
        executor.build_strategy = result.strategy
        if dict(result.mesh_axes) != kept_axes:
            executor.mesh = DeviceMesh(devices, result.mesh_axes)
            executor._dp = executor.mesh.axis_size("dp")
        new_wire = _reshard_wire(executor.prepare_program(program, scope))

        kept_s = kept["predicted_step_s"]
        gain = (kept_s - result.predicted_step_s) \
            if kept_s is not None else float("inf")
        amortize_steps = None
        if (new_wire is not None
                and kept["reshard_wire_bytes"] is not None):
            extra_s = max(0.0, new_wire - kept["reshard_wire_bytes"]) \
                / _costs.V5E_ICI_BPS
            if gain > 0:
                amortize_steps = extra_s / gain
        replanned = (not kept_feas.ok) or (
            gain > 1e-12 and (amortize_steps is None
                              or amortize_steps <= amortize_horizon))
        if not replanned:
            executor.build_strategy = base
            executor.mesh = kept_mesh
            executor._dp = executor.mesh.axis_size("dp")
    finally:
        executor._auto_plan_suspended = False

    summary = {
        "replanned": bool(replanned),
        "kept": {**kept, "strategy": _describe_strategy(base, kept_axes)},
        "chosen": {"point": result.point.describe(),
                   "axes": dict(result.mesh_axes),
                   "predicted_step_s": result.predicted_step_s,
                   "reshard_wire_bytes": new_wire},
        "gain_s_per_step": (None if kept_s is None
                            else kept_s - result.predicted_step_s),
        "amortize_steps": amortize_steps,
        "amortize_horizon": amortize_horizon,
        "plan": result.summary(),
    }
    # the decision above IS this (program, world, batch)'s auto-plan:
    # mark the executor's prepare-time planner done so the next
    # _prepare_program neither re-searches nor overrides a deliberate
    # "keep" (ParallelExecutor._maybe_auto_plan keys)
    if hasattr(executor, "_maybe_auto_plan"):
        done = getattr(executor, "_auto_plan_keys", None)
        if done is None:
            done = executor._auto_plan_keys = set()
        # batch=None = ANY batch: restore priced the decision against
        # the one-time reshard cost, which a later prepare (whose feed
        # batch the restore could not know) must not re-litigate — a
        # batch-keyed re-plan would silently override a deliberate
        # "keep" without ever pricing the reshard
        done.add((id(program), program._version,
                  executor.mesh.num_devices, None))
        executor._auto_plan = result if replanned else None
        if not hasattr(executor, "_auto_orig"):
            executor._auto_orig = (base, kept_mesh)
        executor._auto_adopted = bool(replanned)
    summary["search_s"] = round(time.perf_counter() - t0, 3)
    return summary


def _describe_strategy(strategy, axes: Dict[str, int]) -> str:
    """A StrategyPoint-shaped description of an arbitrary BuildStrategy
    on a mesh — so kept-vs-chosen reads uniformly in the replan record."""
    from ..parallel.strategy import ReduceStrategy
    reduce = {ReduceStrategy.AllReduce: "allreduce",
              ReduceStrategy.Reduce: "reduce",
              ReduceStrategy.ReduceScatter: "reduce_scatter"}[
        strategy.reduce_strategy]
    return StrategyPoint(
        dp=int(axes.get("dp", 1)), pp=int(axes.get("pp", 1)),
        tp=int(axes.get("tp", 1)),
        microbatches=int(strategy.num_microbatches or 1),
        schedule=strategy.pipeline_schedule,
        reduce=reduce, quant=strategy.quant_comm or "",
        bucket_bytes=int(strategy.comm_bucket_bytes),
        memory_plan=bool(strategy.memory_plan),
        offload=("optimizer" if getattr(strategy, "offload_optimizer_state",
                                        False) else "")
        ).canonical().describe()
