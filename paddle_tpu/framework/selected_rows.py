"""SelectedRows: sparse row-slice representation.

≙ reference framework/selected_rows.h:32 — {rows, value tensor, height},
the reference's sparse-gradient carrier (embedding grads, sparse optimizer
updates, pserver row dispatch). TPU translation: under XLA, embedding
gradients are produced by scatter-add in the VJP and arrive dense, so
SelectedRows is NOT the autodiff carrier here; it is the host-side exchange
format for the sharded-embedding/parameter-service path (which rows moved,
their values) and for row-sparse checkpoint deltas. Ops split_ids /
merge_ids / split_selected_rows / lookup_sparse_table operate on the same
shapes the reference's pserver helpers do.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..core.enforce import InvalidArgumentError, enforce


class TracedSelectedRows:
    """In-trace sparse-gradient carrier: {rows, value, height} where rows and
    value are traced jax arrays (duplicate rows NOT yet merged).

    ≙ the reference's SelectedRows flowing from lookup_table_grad into the
    optimizer's SelectedRows kernels (reference operators/adam_op.h
    SparseAdamFunctor, math/selected_rows_functor.cc). Produced by
    run_vjp_region for is_sparse embedding params; consumed by the sparse
    branches of the sgd/momentum/adam lowerings, which touch only the looked-
    up rows instead of rewriting the whole [vocab, dim] table + accumulators.
    """

    __slots__ = ("rows", "value", "height")

    def __init__(self, rows, value, height: int):
        self.rows = rows          # [n] int traced
        self.value = value        # [n, width] traced
        self.height = int(height)

    def to_dense(self):
        import jax.numpy as jnp
        out = jnp.zeros((self.height,) + tuple(self.value.shape[1:]),
                        dtype=self.value.dtype)
        return out.at[self.rows].add(self.value)


class SelectedRows:
    """{rows, value, height} sparse row set (≙ selected_rows.h:32)."""

    def __init__(self, rows: Sequence[int], value, height: int):
        rows = np.asarray(rows, dtype=np.int64)
        value = np.asarray(value)
        enforce(rows.ndim == 1, "rows must be 1-D",
                exc=InvalidArgumentError)
        enforce(value.shape[0] == rows.shape[0],
                f"value rows {value.shape[0]} != len(rows) {rows.shape[0]}",
                exc=InvalidArgumentError)
        enforce(height >= 0, "height must be >= 0",
                exc=InvalidArgumentError)
        if rows.size:
            enforce(int(rows.min()) >= 0 and int(rows.max()) < height,
                    f"rows must lie in [0, {height}); got "
                    f"[{rows.min()}, {rows.max()}]",
                    exc=InvalidArgumentError)
        self.rows = rows
        self.value = value
        self.height = int(height)

    def to_dense(self) -> np.ndarray:
        """Materialize [height, width] with duplicate rows summed
        (≙ math::scatter::MergeAdd)."""
        out = np.zeros((self.height,) + self.value.shape[1:],
                       dtype=self.value.dtype)
        np.add.at(out, self.rows, self.value)
        return out

    @staticmethod
    def from_dense(dense: np.ndarray, nonzero_only: bool = True):
        dense = np.asarray(dense)
        if nonzero_only:
            mask = np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1)
            rows = np.nonzero(mask)[0]
        else:
            rows = np.arange(dense.shape[0])
        return SelectedRows(rows, dense[rows], dense.shape[0])

    def merge_add(self) -> "SelectedRows":
        """Coalesce duplicate rows (≙ MergeAdd) keeping sparsity."""
        uniq, inv = np.unique(self.rows, return_inverse=True)
        val = np.zeros((uniq.shape[0],) + self.value.shape[1:],
                       dtype=self.value.dtype)
        np.add.at(val, inv, self.value)
        return SelectedRows(uniq, val, self.height)

    def __repr__(self):
        return (f"SelectedRows(rows={self.rows.tolist()}, "
                f"height={self.height}, value.shape={self.value.shape})")
