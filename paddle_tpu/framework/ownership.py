"""Block-lifetime ownership model of the paged-KV serving protocol.

The serving tier (r20-r23) is ~3,400 LoC of stateful host-side protocol
code — `BlockPool` refcounts, radix-index pins, CoW beam forks,
speculative rollback, two-tier spill/prefetch — whose invariants were
only exercised dynamically by `check()` calls sprinkled through tests.
This module gives that protocol the same static treatment the program
IR got in r10/r13: every operation is a declarative transition
(pre/postconditions over an abstract state of refcounts, free list,
index pins and device/host residency), every named invariant is a
diagnostic code, and a depth-bounded exhaustive model checker
(`ModelChecker`) enumerates ALL op interleavings over a small pool and
proves the shipped protocol clean — or names the op, block and
invariant a seeded mutation breaks.

Two consumers:

- `ModelChecker` — static exhaustive exploration at small scope
  (`lint_program --serving`, the CI serving-verifier stanza, and the
  mutation matrix in tests/test_ownership.py);
- `serving/sanitizer.py` — the runtime shadow: it mirrors every real
  `BlockPool`/`KVPager` mutation into an `AbstractState` and raises
  `OwnershipViolation` on divergence (`PTPU_KV_SANITIZE=1`).

The abstraction is exact, not approximate: the model's transitions are
line-by-line mirrors of `serving/kv_pager.py` (try_admit's pin-first /
rollback-on-dry order, note_block_filled's full-prompt-block gate,
rollback's ceil/floor block arithmetic, evict_table_to_host's
content-bearing host charge). The one deliberate reduction is the
radix index: the checker models a SINGLE prompt family, so the tree
degenerates to one chain (`index_chain`) whose LRU leaf is the tail —
interleavings across distinct prefixes add blocks but no new
transition structure.
"""

from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core.enforce import InvalidArgumentError

__all__ = [
    "DIAGNOSTICS", "MUTATIONS", "OwnershipViolation", "TableState",
    "AbstractState", "ModelChecker", "CheckResult",
]

# ---------------------------------------------------------------------------
# diagnostic catalog — the named invariants (r13 discipline: every code
# has exactly one meaning, one trigger and one mutation test)
# ---------------------------------------------------------------------------

DIAGNOSTICS: Dict[str, str] = {
    "kv-accounting-identity":
        "used + free != n_blocks - 1 (or the null block 0 left the "
        "reserved state) — the device pool lost or invented a block",
    "kv-free-refcount":
        "a block is on the free list with refcount > 0, or off it "
        "with refcount 0 — free-iff-refcount-0 broken",
    "kv-use-after-free":
        "an operation touched a block whose refcount is 0 (alloc of a "
        "live block, share/write of a freed one, or a table mapping a "
        "block it no longer holds)",
    "kv-double-free":
        "release of a block that is already free (or of the reserved "
        "null block 0)",
    "kv-write-shared-block":
        "a cache write targeted a block with refcount > 1 — CoW "
        "violation: shared content mutated in place under every other "
        "holder",
    "kv-block-leak":
        "a block's refcount exceeds its holders (live block-table "
        "entries + radix-index pins) — some release was skipped and "
        "the block can never return to the free list",
    "kv-double-spill":
        "evict_table_to_host on a table that is already host-resident "
        "— the second spill would double-charge the host tier and "
        "snapshot dead (zeroed) mappings",
    "kv-host-accounting":
        "the host-tier ledger went negative, exceeded host_blocks, or "
        "disagrees with the sum of live spill records — the two-tier "
        "identity used_dev+used_host+free_dev+free_host == total broke",
    "kv-prefetch-after-use":
        "spilled content was committed/consumed before its transfer "
        "ticket arrived — offload-use-before-arrival at the block "
        "granularity (a resume would scatter stale or torn rows)",
    "serving-cache-write-alias":
        "a tick-program cache write breaks the donated in-place "
        "contract: the pool var is written more than once per tick, or "
        "a persistable pool's write lands in a forked temporary while "
        "readers keep gathering the stale pool",
    "serving-cache-stale-read":
        "an op still reads the old pool var after the tick's cache "
        "write forked it into a different output var — the consumer "
        "sees last tick's rows for the position being decoded",
    "offload-stale-after-rollback":
        "a transfer issued before a speculative rollback is consumed "
        "after it — the staged bytes snapshot rejected-span content "
        "the rollback already remapped",
}

# the K-bug matrix of the r24 ISSUE: seeded protocol mutations and the
# diagnostic each MUST be caught by (by name), both statically by the
# checker and dynamically by the sanitizer
MUTATIONS: Dict[str, str] = {
    "leaked-release": "kv-block-leak",
    "write-shared-block": "kv-write-shared-block",
    "prefetch-after-use": "kv-prefetch-after-use",
    "rollback-double-free": "kv-double-free",
}


class OwnershipViolation(InvalidArgumentError):
    """A named protocol-invariant breach: `code` is a DIAGNOSTICS key,
    `op` the transition that tripped it, `block` the physical block
    involved (None for whole-state invariants)."""

    def __init__(self, code: str, op: str, message: str,
                 block: Optional[int] = None):
        assert code in DIAGNOSTICS, code
        self.code = code
        self.op = op
        self.block = block
        self.invariant = DIAGNOSTICS[code]
        self.raw_message = message      # re-wrappable (SanitizerDivergence)
        at = f" block {block}" if block is not None else ""
        super().__init__(f"[{code}] op {op}{at}: {message}")


# ---------------------------------------------------------------------------
# abstract state
# ---------------------------------------------------------------------------


class TableState:
    """One request's abstract block table: the logical->physical map
    (0 = dead mapping while spilled), the read-only shared prefix, the
    write frontier, and host-tier residency."""

    __slots__ = ("blocks", "n_shared", "shared_len", "written_len",
                 "prompt_len", "spilled", "arrived", "forked")

    def __init__(self, blocks: List[int], n_shared: int, shared_len: int,
                 prompt_len: int):
        self.blocks = list(blocks)
        self.n_shared = int(n_shared)
        self.shared_len = int(shared_len)
        self.written_len = int(shared_len)   # writes resume after the
        #                                      shared span (engine: fed)
        self.prompt_len = int(prompt_len)
        self.spilled: Optional[List[int]] = None  # logical js on host
        self.arrived = True                  # transfer ticket landed
        self.forked = False                  # holds fork-shared blocks

    def clone(self) -> "TableState":
        t = TableState(self.blocks, self.n_shared, self.shared_len,
                       self.prompt_len)
        t.written_len = self.written_len
        t.spilled = None if self.spilled is None else list(self.spilled)
        t.arrived = self.arrived
        t.forked = self.forked
        return t

    def key(self) -> tuple:
        return (tuple(self.blocks), self.n_shared, self.shared_len,
                self.written_len, self.prompt_len,
                None if self.spilled is None else tuple(self.spilled),
                self.arrived, self.forked)


class AbstractState:
    """The declarative pager state: per-block refcounts + free list
    (device tier), the single-family radix chain, per-table records and
    the host-tier ledger. Primitive transitions (`alloc_at`, `share`,
    `release`, `note_write`) carry the per-op preconditions; composed
    protocol transitions (`admit` .. `reload`) mirror `KVPager` method
    for method; `check_invariants` proves the whole-state identities.

    Every precondition failure raises `OwnershipViolation` with the
    diagnostic code the catalog assigns — this class never asserts
    anonymously."""

    def __init__(self, n_blocks: int, block_size: int,
                 host_blocks: int = 0):
        assert n_blocks >= 2 and block_size >= 1
        self.n_blocks = int(n_blocks)
        self.block_size = int(block_size)
        self.host_blocks = int(host_blocks)
        self.ref = [0] * self.n_blocks        # ref[0] stays 0 (null)
        self.free = set(range(1, self.n_blocks))
        self.index_chain: List[int] = []      # checker's radix reduction
        self.tables: Dict[int, TableState] = {}
        self.host_used = 0

    # -- primitives (the sanitizer mirrors real pool calls onto these) --
    def alloc_at(self, block: int, op: str = "alloc"):
        """The pool handed out `block` (refcount 0 -> 1)."""
        b = int(block)
        if not (0 < b < self.n_blocks) or b not in self.free:
            raise OwnershipViolation(
                "kv-use-after-free", op,
                f"alloc returned block {b} which is "
                f"{'the reserved null block' if b == 0 else 'not free'} "
                f"(refcount {self.ref[b] if 0 <= b < self.n_blocks else '?'})",
                block=b)
        self.free.discard(b)
        self.ref[b] = 1

    def share(self, block: int, op: str = "share"):
        b = int(block)
        if not (0 < b < self.n_blocks) or self.ref[b] <= 0:
            raise OwnershipViolation(
                "kv-use-after-free", op,
                f"share of unallocated block {b}", block=b)
        self.ref[b] += 1

    def release(self, block: int, op: str = "release") -> bool:
        b = int(block)
        if not (0 < b < self.n_blocks) or self.ref[b] <= 0:
            raise OwnershipViolation(
                "kv-double-free", op,
                f"release of {'null block 0' if b == 0 else f'block {b}'}"
                f" with refcount "
                f"{self.ref[b] if 0 < b < self.n_blocks else 0}", block=b)
        self.ref[b] -= 1
        if self.ref[b] == 0:
            self.free.add(b)
            return True
        return False

    def note_write(self, blocks: List[int], pos: int,
                   op: str = "write") -> int:
        """One cache row lands at token position `pos` of a table whose
        physical map is `blocks`. The CoW contract: the target block
        must be held exactly once (refcount 1) — shared blocks are
        read-only to every holder."""
        b = blocks[pos // self.block_size]
        if b == 0 or self.ref[b] == 0:
            raise OwnershipViolation(
                "kv-use-after-free", op,
                f"write at position {pos} targets "
                f"{'the dead (spilled) mapping' if b == 0 else f'freed block {b}'}",
                block=b)
        if self.ref[b] > 1:
            raise OwnershipViolation(
                "kv-write-shared-block", op,
                f"write at position {pos} targets block {b} with "
                f"refcount {self.ref[b]} — shared content mutated in "
                f"place", block=b)
        return b

    def host_charge(self, n: int, op: str):
        if self.host_used + n > self.host_blocks:
            raise OwnershipViolation(
                "kv-host-accounting", op,
                f"host charge of {n} blocks exceeds capacity "
                f"({self.host_used} used of {self.host_blocks})")
        self.host_used += n

    def host_refund(self, n: int, op: str):
        if n > self.host_used:
            raise OwnershipViolation(
                "kv-host-accounting", op,
                f"host refund of {n} blocks underflows the ledger "
                f"({self.host_used} used)")
        self.host_used -= n

    # -- composed protocol transitions (mirrors of KVPager) -------------
    def _alloc_or_evict(self, op: str) -> Optional[int]:
        """KVPager._alloc_or_evict over the single-family chain:
        allocate, evicting LRU index leaves (chain tail) under
        pressure; None when dry even after the index is empty."""
        while True:
            if self.free:
                b = min(self.free)           # deterministic pick; block
                #                              identity is symmetric
                self.alloc_at(b, op)
                return b
            if not self.index_chain:
                return None
            self.release(self.index_chain.pop(), op=op + "/evict-index")

    def admit(self, tid: int, prompt_len: int, need_len: int,
              mutation: Optional[str] = None) -> bool:
        """try_admit: pin the matched prefix chain FIRST, then allocate
        the private remainder; full rollback (shares released) on dry.
        The shared span is capped at block-aligned prompt_len-1 so the
        first write always lands in a private block."""
        op = f"admit(t{tid})"
        bs = self.block_size
        n_logical = -(-need_len // bs)
        max_shared = (prompt_len - 1) // bs
        chain = self.index_chain[:min(max_shared, n_logical)]
        blocks: List[int] = []
        for b in chain:
            self.share(b, op)
            blocks.append(b)
        for _ in range(n_logical - len(chain)):
            b = self._alloc_or_evict(op)
            if b is None:                    # rollback, stay pending
                for held in blocks:
                    self.release(held, op)
                return False
            blocks.append(b)
        rec = TableState(blocks, len(chain), len(chain) * bs, prompt_len)
        if mutation == "write-shared-block" and rec.n_shared:
            # seeded off-by-one: the write frontier replays the LAST
            # shared block's positions instead of starting after them
            rec.written_len = (rec.n_shared - 1) * bs
        self.tables[tid] = rec
        return True

    def write(self, tid: int, mutation: Optional[str] = None):
        """One tick's cache write at the table's frontier, plus
        note_block_filled: a just-completed FULL PROMPT block (not
        itself served from the index) is offered to the prefix chain,
        which takes its own retention ref."""
        rec = self.tables[tid]
        op = f"write(t{tid})"
        pos = rec.written_len
        self.note_write(rec.blocks, pos, op)
        rec.written_len = pos + 1
        bs = self.block_size
        if (pos + 1) % bs:
            return
        j = pos // bs                        # block just filled
        if j < rec.n_shared or (j + 1) * bs > rec.prompt_len:
            return                           # not a sharable prompt block
        if j == len(self.index_chain):       # ancestor chain intact,
            self.index_chain.append(rec.blocks[j])   # node is new
            self.share(rec.blocks[j], op + "/register")

    def fork(self, tid: int, new_tid: int) -> bool:
        """Beam fork: share fully-written blocks, CoW the partial
        divergence block, fresh private blocks for the remainder;
        helds released on dry (KVPager.fork raises there — the model
        folds that into a refusal, the release path is identical)."""
        rec = self.tables[tid]
        op = f"fork(t{tid}->t{new_tid})"
        n_full, rem = divmod(rec.written_len, self.block_size)
        blocks: List[int] = []
        for j, b in enumerate(rec.blocks):
            if j < n_full:
                self.share(b, op)
                blocks.append(b)
                continue
            nb = self._alloc_or_evict(op)
            if nb is None:
                for held in blocks:
                    self.release(held, op)
                return False
            blocks.append(nb)
        child = TableState(blocks, rec.n_shared, rec.shared_len,
                           rec.prompt_len)
        child.written_len = rec.written_len
        child.forked = rec.forked = True
        self.tables[new_tid] = child
        return True

    def release_table(self, tid: int, mutation: Optional[str] = None):
        """Completion: drop the table's ref on every live mapping
        (dead/spilled entries are 0 and skipped) and refund any host
        charge the spill record still holds (_release_request)."""
        rec = self.tables[tid]
        op = f"release(t{tid})"
        live = [b for b in rec.blocks if b]
        if mutation == "leaked-release" and live:
            live = live[:-1]                 # seeded bug: one release
            #                                  skipped, record dropped
        for b in live:
            self.release(b, op)
        if rec.spilled:
            self.host_refund(len(rec.spilled), op)
        del self.tables[tid]

    def rollback(self, tid: int, keep_len: int,
                 mutation: Optional[str] = None):
        """Speculative rejection: every block FULLY inside
        [keep_len, written_len) is released (must free — written blocks
        are private by the admission cap) and remapped fresh; the
        boundary block holding keep_len-1 stays."""
        rec = self.tables[tid]
        op = f"rollback(t{tid},keep={keep_len})"
        bs = self.block_size
        first = -(-keep_len // bs)
        last = (rec.written_len - 1) // bs
        for j in range(first, min(last + 1, len(rec.blocks))):
            freed = self.release(rec.blocks[j], op)
            if mutation == "rollback-double-free":
                self.release(rec.blocks[j], op)   # seeded copy-paste bug
            if not freed:
                raise OwnershipViolation(
                    "kv-write-shared-block", op,
                    f"rollback hit shared block {rec.blocks[j]} "
                    f"(logical {j}) — writes must never land in shared "
                    f"blocks", block=rec.blocks[j])
            nb = self._alloc_or_evict(op)
            assert nb is not None            # release-first guarantees
            rec.blocks[j] = nb
        rec.written_len = keep_len

    def spill(self, tid: int) -> bool:
        """evict_table_to_host: release every private device block,
        zero its mapping, charge the content-bearing ones to the host
        tier; shared prefix blocks stay pinned on device. Refused
        (False, no state change) when the host tier cannot hold the
        content. The in-flight d2h means the content has NOT arrived
        anywhere consumable yet — `arrived` clears until the stream
        ticket lands."""
        rec = self.tables[tid]
        op = f"spill(t{tid})"
        if rec.spilled is not None:
            raise OwnershipViolation(
                "kv-double-spill", op,
                f"table t{tid} is already host-resident "
                f"(spilled blocks {rec.spilled})")
        bs = self.block_size
        n_content = -(-rec.written_len // bs)
        spilled = list(range(rec.n_shared,
                             min(n_content, len(rec.blocks))))
        if self.host_used + len(spilled) > self.host_blocks:
            return False
        for j in range(rec.n_shared, len(rec.blocks)):
            self.release(rec.blocks[j], op)
            rec.blocks[j] = 0
        self.host_used += len(spilled)
        rec.spilled = spilled
        rec.arrived = not spilled            # empty spill: nothing in
        #                                      flight on the stream
        return True

    def arrive(self, tid: int):
        """The transfer stream completed this table's d2h+h2d chain —
        the staged bytes are now consumable."""
        self.tables[tid].arrived = True

    def reload(self, tid: int, wait: bool = True) -> bool:
        """reload_table_from_host: re-acquire a device block per
        private entry (alloc-or-rollback), refund the host charge, and
        COMMIT the staged content into the cache arrays. The correct
        protocol waits on the transfer ticket before the commit
        (`wait=True` == TransferTicket.wait); committing while the
        ticket is in flight is the prefetch-after-use bug."""
        rec = self.tables[tid]
        op = f"reload(t{tid})"
        got: List[int] = []
        for j in range(rec.n_shared, len(rec.blocks)):
            b = self._alloc_or_evict(op)
            if b is None:                    # roll back, stay suspended
                for held in got:
                    self.release(held, op)
                return False
            got.append(b)
        for j, b in zip(range(rec.n_shared, len(rec.blocks)), got):
            rec.blocks[j] = b
        self.host_refund(len(rec.spilled), op)
        if rec.spilled:
            if wait:
                rec.arrived = True           # ticket.wait()
            if not rec.arrived:
                raise OwnershipViolation(
                    "kv-prefetch-after-use", op,
                    f"h2d commit for table t{tid} ran before its "
                    f"transfer ticket arrived — the scatter would "
                    f"write stale or torn rows")
        rec.spilled = None
        return True

    # -- whole-state invariants -----------------------------------------
    def check_invariants(self, op: str = "check",
                         pins: Optional[Dict[int, int]] = None,
                         detached_host: int = 0):
        """The named identities over the full state. `pins` maps
        block -> index-pin multiplicity; defaults to the checker's
        single-family chain (the sanitizer passes a walk of the real
        radix tree). `detached_host` covers host blocks whose spill
        record was dropped but whose ledger refund is still pending —
        the window between `KVPager.release` and
        `refund_host_charge` inside `_release_request`."""
        n = self.n_blocks
        if self.ref[0] != 0 or 0 in self.free:
            raise OwnershipViolation(
                "kv-accounting-identity", op,
                "null block 0 left the reserved state "
                f"(refcount {self.ref[0]}, on-free-list {0 in self.free})",
                block=0)
        n_live = sum(1 for b in range(1, n) if self.ref[b] > 0)
        if n_live + len(self.free) != n - 1:
            raise OwnershipViolation(
                "kv-accounting-identity", op,
                f"used({n_live}) + free({len(self.free)}) != {n - 1}")
        for b in range(1, n):
            if (self.ref[b] == 0) != (b in self.free):
                raise OwnershipViolation(
                    "kv-free-refcount", op,
                    f"block {b}: refcount {self.ref[b]} vs free-list "
                    f"membership {b in self.free}", block=b)
        if pins is None:
            pins = {}
            for b in self.index_chain:
                pins[b] = pins.get(b, 0) + 1
        holders = dict(pins)
        for tid, rec in self.tables.items():
            for b in rec.blocks:
                if b:
                    holders[b] = holders.get(b, 0) + 1
        for b in range(1, n):
            h = holders.get(b, 0)
            if self.ref[b] > h:
                raise OwnershipViolation(
                    "kv-block-leak", op,
                    f"block {b} refcount {self.ref[b]} exceeds its "
                    f"{h} holder(s) — a release was skipped", block=b)
            if self.ref[b] < h:
                raise OwnershipViolation(
                    "kv-use-after-free", op,
                    f"block {b} has {h} holder(s) but refcount "
                    f"{self.ref[b]} — a table maps a block it no "
                    f"longer holds", block=b)
        if not (0 <= self.host_used <= self.host_blocks):
            raise OwnershipViolation(
                "kv-host-accounting", op,
                f"host ledger {self.host_used} outside "
                f"[0, {self.host_blocks}]")
        spill_sum = sum(len(rec.spilled) for rec in self.tables.values()
                        if rec.spilled is not None) + detached_host
        if spill_sum != self.host_used:
            raise OwnershipViolation(
                "kv-host-accounting", op,
                f"host ledger {self.host_used} != {spill_sum} blocks "
                f"across live spill records")
        # two-tier identity (the r23 extension): device used+free plus
        # the host split must cover exactly total capacity
        used_host, free_host = self.host_used, \
            self.host_blocks - self.host_used
        if n_live + len(self.free) + used_host + free_host \
                != (n - 1) + self.host_blocks:
            raise OwnershipViolation(
                "kv-host-accounting", op,
                f"two-tier identity broke: {n_live}+{len(self.free)}+"
                f"{used_host}+{free_host} != {n - 1}+{self.host_blocks}")

    # -- structural ------------------------------------------------------
    def clone(self) -> "AbstractState":
        st = AbstractState.__new__(AbstractState)
        st.n_blocks = self.n_blocks
        st.block_size = self.block_size
        st.host_blocks = self.host_blocks
        st.ref = list(self.ref)
        st.free = set(self.free)
        st.index_chain = list(self.index_chain)
        st.tables = {tid: rec.clone() for tid, rec in self.tables.items()}
        st.host_used = self.host_used
        return st

    def snapshot(self) -> tuple:
        return (tuple(self.ref), tuple(self.index_chain), self.host_used,
                tuple(sorted((tid, rec.key())
                             for tid, rec in self.tables.items())))


# ---------------------------------------------------------------------------
# depth-bounded exhaustive model checker
# ---------------------------------------------------------------------------


class CheckResult:
    """One exploration's verdict: how much of the protocol state space
    was covered and every (deduplicated) named violation found."""

    __slots__ = ("states_explored", "transitions", "depth", "violations")

    def __init__(self, states_explored: int, transitions: int, depth: int,
                 violations: List[Dict[str, str]]):
        self.states_explored = states_explored
        self.transitions = transitions
        self.depth = depth
        self.violations = violations

    @property
    def ok(self) -> bool:
        return not self.violations

    def codes(self) -> List[str]:
        return sorted({v["code"] for v in self.violations})

    def __repr__(self):
        return (f"CheckResult(states={self.states_explored}, "
                f"transitions={self.transitions}, depth={self.depth}, "
                f"violations={self.codes() or 'none'})")


class ModelChecker:
    """Enumerate ALL interleavings of the pager protocol's operations
    over a small pool, depth-bounded and state-deduplicated, checking
    every invariant after every transition. `mutation=None` proves the
    shipped protocol; a MUTATIONS key seeds that named bug into the
    transition relation and the exploration must surface its diagnostic
    code (the K-bug matrix).

    Scope defaults are the smallest configuration that exercises every
    transition: prefix sharing (prompt spans >1 block), pool contention
    (2 tables cannot both fully allocate), CoW forks, speculative
    rollback past the prompt, and a 2-block host tier."""

    def __init__(self, n_blocks: int = 5, block_size: int = 2,
                 host_blocks: int = 2, max_tables: int = 2,
                 prompt_len: int = 3, need_len: int = 5,
                 depth: int = 8, mutation: Optional[str] = None):
        assert mutation is None or mutation in MUTATIONS, mutation
        self.n_blocks = n_blocks
        self.block_size = block_size
        self.host_blocks = host_blocks
        self.max_tables = max_tables
        self.prompt_len = prompt_len
        self.need_len = need_len
        self.depth = depth
        self.mutation = mutation

    # -- transition relation --------------------------------------------
    def enabled_ops(self, st: AbstractState) -> List[tuple]:
        ops: List[tuple] = []
        live = st.tables
        for tid in range(self.max_tables):
            if tid not in live:
                ops.append(("admit", tid))
                break                        # tids are symmetric: one
                #                              fresh admission suffices
        for tid, rec in live.items():
            resident = rec.spilled is None
            if resident and rec.written_len < self.need_len:
                ops.append(("write", tid))
            ops.append(("release", tid))
            if resident and rec.written_len >= 1:
                for new_tid in range(self.max_tables):
                    if new_tid not in live:
                        ops.append(("fork", tid, new_tid))
                        break
            # rollback never composes with live fork shares: beam
            # search (the only fork producer) and speculative rollback
            # are separate engines — PagedKVEngine enforces the
            # analogous host_tier/speculative exclusion at construction
            if resident and not rec.forked \
                    and rec.written_len > self.prompt_len:
                keeps = {self.prompt_len, rec.written_len - 1}
                for keep in sorted(keeps):
                    if 1 <= keep < rec.written_len:
                        ops.append(("rollback", tid, keep))
            if self.host_blocks and resident:
                ops.append(("spill", tid))
            if rec.spilled is not None:
                ops.append(("reload", tid))
                if not rec.arrived:
                    ops.append(("arrive", tid))
        if st.index_chain:
            ops.append(("evict-index",))
        return ops

    def apply(self, st: AbstractState, op: tuple):
        kind = op[0]
        m = self.mutation
        if kind == "admit":
            st.admit(op[1], self.prompt_len, self.need_len,
                     mutation=m if m == "write-shared-block" else None)
        elif kind == "write":
            st.write(op[1])
        elif kind == "release":
            st.release_table(
                op[1], mutation=m if m == "leaked-release" else None)
        elif kind == "fork":
            st.fork(op[1], op[2])
        elif kind == "rollback":
            st.rollback(
                op[1], op[2],
                mutation=m if m == "rollback-double-free" else None)
        elif kind == "spill":
            st.spill(op[1])
        elif kind == "arrive":
            st.arrive(op[1])
        elif kind == "reload":
            st.reload(op[1], wait=(m != "prefetch-after-use"))
        elif kind == "evict-index":
            st.release(st.index_chain.pop(), op="evict-index")
        else:                                # pragma: no cover
            raise AssertionError(op)

    # -- exploration -----------------------------------------------------
    def run(self) -> CheckResult:
        from collections import deque
        init = AbstractState(self.n_blocks, self.block_size,
                             self.host_blocks)
        seen = {init.snapshot()}
        queue = deque([(init, 0)])           # BFS: every state is first
        #   discovered at its MINIMAL depth, so the depth bound prunes
        #   no state that any <=depth interleaving can reach (a DFS
        #   would mark deep discoveries `seen` and skip their shallow
        #   revisits — silently unsound)
        violations: Dict[Tuple[str, str], Dict[str, str]] = {}
        transitions = 0
        while queue:
            st, d = queue.popleft()
            if d >= self.depth:
                continue
            for op in self.enabled_ops(st):
                child = st.clone()
                transitions += 1
                try:
                    self.apply(child, op)
                    child.check_invariants(op="/".join(map(str, op)))
                except OwnershipViolation as v:
                    violations.setdefault(
                        (v.code, v.op),
                        {"code": v.code, "op": v.op, "message": str(v)})
                    continue                 # prune the broken branch
                snap = child.snapshot()
                if snap in seen:
                    continue
                seen.add(snap)
                queue.append((child, d + 1))
        return CheckResult(len(seen), transitions, self.depth,
                           sorted(violations.values(),
                                  key=lambda v: (v["code"], v["op"])))
