"""Static program analysis over the Program IR.

Three cooperating layers (≙ the reference's multi_devices_check_pass +
ir::HasCircle asserts and each OpMaker's InferShape, plus the role the HLO
verifier plays between XLA passes; TVM's typed/verifiable IR treats the same
checks as the precondition for safe graph rewriting):

1. **Shape/dtype inference** (`infer_program`): propagates ShapeDtypeStructs
   block-by-block through the op DAG *before* trace time and cross-checks
   every inferred output against the declared `Variable.shape`/`dtype`,
   reporting mismatches with `block/op#/op.type` provenance. The default
   per-op rule abstract-evaluates the registered lowering itself
   (`jax.eval_shape`) — the kernel IS the shape function, so rule and kernel
   cannot drift; explicit `infer_spec` rules (registry.py) cover ops whose
   lowering cannot run standalone (mesh collectives, region pseudo-ops).
   Symbolic batch dims (-1) ride through as a sentinel prime and are
   rendered back as ``B`` in diagnostics.

2. **Structural + parallel consistency verification** (`verify_program`):
   def-before-use (absorbing the old CheckPass), duplicate-writer hazards,
   region attribute schemas, and the parallel invariants — every `pp_send`
   paired with its `pp_recv` across a stage boundary, `dp_grad_comm` sitting
   between the backward region and every gradient consumer, dp divisibility
   of sharded gradients.

2b. **Dataflow detectors** (framework/dataflow.py, run inside
   `verify_program`): SPMD collective-consistency/deadlock checks, GSPMD-
   style replica-divergence taint propagation, and buffer-reuse/WAR race
   checks over the variable interference graph. Pure Python over the IR —
   the sanitizer gets them on every pass apply.

3. **Pass sanitizer** (`sanitized_apply`, wired into `Pass.__call__`): every
   pass apply runs verify-before/verify-after, attributing any NEW violation
   to the offending pass by name. Always on; kill switch
   ``PTPU_VERIFY_PASSES=0``.

`analyze_program` runs layers 1+2; `check_program` raises on errors.
`tools/lint_program.py` is the CLI over all of it.
"""

from __future__ import annotations

import re as _re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import flags
from ..core.enforce import EnforceError, NotFoundError, enforce
from .program import Block, Operator, Program

__all__ = [
    "BATCH_SENTINEL", "Diagnostic", "InferCtx", "InferResult",
    "INFER_WAIVED", "PassSanitizerError", "ProgramAnalysisError",
    "analyze_program", "check_program", "infer_coverage", "infer_op",
    "infer_program", "op_loc", "peak_live_bytes", "sanitized_apply",
    "sanitizer_enabled", "verify_program",
]

# Sentinel stand-in for the symbolic -1 batch dim: a prime large enough not
# to collide with real layer widths in practice, small enough that lowerings
# which loop over a (mis-declared) batch-led dim stay cheap to trace.
BATCH_SENTINEL = 61

flags.define_bool(
    "verify_passes", True,
    "Run the structural program verifier before/after every Pass apply and "
    "attribute new violations to the pass by name (the role the HLO "
    "verifier plays between XLA passes). Kill switch: PTPU_VERIFY_PASSES=0.")


# ---------------------------------------------------------------------------
# diagnostics
# ---------------------------------------------------------------------------


def op_loc(block: Block, idx: int, op: Operator) -> str:
    """Shared op-provenance formatter: ``block 0 op#12 'matmul'``. Used by
    every analyzer diagnostic and by the enforce raises in passes.py /
    grad_comm.py / pipeline.py, so errors from all layers read the same."""
    return f"block {block.idx} op#{idx} {op.type!r}"


@dataclass
class Diagnostic:
    code: str        # stable kebab-case id, e.g. "shape-mismatch"
    loc: str         # op_loc(...) or a var name
    message: str
    severity: str = "error"      # "error" | "warning"

    def __str__(self):
        return f"[{self.code}] {self.loc}: {self.message}"


class ProgramAnalysisError(EnforceError):
    """Raised by check_program when analysis finds error-severity
    diagnostics."""

    def __init__(self, msg, diagnostics=()):
        super().__init__(msg)
        self.diagnostics = list(diagnostics)


class PassSanitizerError(ProgramAnalysisError):
    """A pass apply introduced NEW verifier violations; carries the pass
    name (≙ the HLO verifier failing between two XLA passes)."""

    def __init__(self, pass_name, diagnostics):
        self.pass_name = pass_name
        super().__init__(
            f"pass {pass_name!r} broke program invariants "
            f"(PTPU_VERIFY_PASSES verify-after):\n  "
            + "\n  ".join(str(d) for d in diagnostics), diagnostics)


# ---------------------------------------------------------------------------
# shape/dtype inference
# ---------------------------------------------------------------------------

# Ops the engine interprets itself instead of calling a spec/lowering.
_REGION_OPS = frozenset({"vjp_region", "pp_pipeline_region"})

# Ops with no standalone shape semantics: sub-block control flow binds inner
# vars via attrs at lowering time, TensorArray ops need the array
# environment. Their outputs fall back to the declared var shapes (still
# cross-checkable by downstream consumers). Every entry carries its reason —
# test_op_coverage.py enforces the waiver list stays small (>= 90% of the
# registry must infer).
INFER_WAIVED: Dict[str, str] = {
    "cond_block": "sub-block control flow: shapes live in the bound block",
    "lazy_cond": "sub-block control flow: shapes live in the bound block",
    "while": "sub-block control flow: loop-carried shapes are bound vars",
    "switch_case": "sub-block control flow: shapes live in the bound blocks",
    "static_rnn": "sub-block control flow: step/memory shapes are bound vars",
    "array_read": "TensorArray environment: element shape is array state",
    "array_write": "TensorArray environment: element shape is array state",
    "array_length": "TensorArray environment: length is array state",
}


def _tp_localized(v, shape, program) -> tuple:
    """tp-sharded vars (tp_shard_pass marks them with `tp_spec`) are
    declared at their GLOBAL shape but execute per-shard at the tp-local
    shape: divide the sharded dims by the program's tp size (ONE rule,
    owned by framework/sharding.py — the comm planner uses the same)."""
    tp = int(getattr(program, "_tp_size", 0) or 0)
    spec = getattr(v, "tp_spec", None)
    if tp <= 1 or not spec or not getattr(program, "_tp_applied", False):
        return tuple(shape)
    from .sharding import tp_local_shape
    return tp_local_shape(tuple(shape), spec, tp)


@dataclass
class InferCtx:
    """Context handed to explicit infer_spec rules (≙ InferShapeContext)."""
    block: Block
    op: Operator
    op_idx: int
    nominal_batch: int = BATCH_SENTINEL
    extras: dict = field(default_factory=dict)

    def declared(self, name: str) -> Optional[Tuple[tuple, Any]]:
        """(shape, dtype) of a declared var with -1 -> sentinel (and
        tp-sharded dims localized), or None."""
        try:
            v = self.block.var(name)
        except NotFoundError:
            return None
        if v.shape is None:
            return None
        shape = _tp_localized(v, _subst(v.shape, self.nominal_batch),
                              self.block.program)
        return (shape, np.dtype(v.dtype))


def _subst(shape, nominal_batch) -> tuple:
    return tuple(nominal_batch if d == -1 else int(d) for d in shape)


def _render_dim(d, nominal_batch) -> str:
    if d == nominal_batch:
        return "B"
    if d and d % nominal_batch == 0:
        return f"{d // nominal_batch}*B"
    return str(d)


def _render_shape(shape, nominal_batch) -> str:
    return "[" + ", ".join(_render_dim(d, nominal_batch) for d in shape) + "]"


def _canon_dtype(dt):
    """Canonicalize a dtype the way the runtime will (x64 -> x32 unless
    jax_enable_x64): declared float64 vars execute as float32."""
    import jax
    return np.dtype(jax.dtypes.canonicalize_dtype(np.dtype(dt)))


def _dtypes_compatible(inferred, declared) -> bool:
    """Canonicalized-dtype equality, with one sanctioned relaxation: the
    mixed-precision matmul/conv path (use_bf16) legitimately computes
    bfloat16 values for vars declared float32 — the declaration is the
    LOGICAL dtype, the bf16 residency is an execution detail the next
    fp32 op absorbs. Everything else (int where float was declared, bool
    leaking into arithmetic) is a real lie and reports."""
    ci, cd = _canon_dtype(inferred), _canon_dtype(declared)
    if ci == cd:
        return True
    bf16_pair = {str(ci), str(cd)}
    return bf16_pair == {"bfloat16", "float32"}


_MEMO: Dict[tuple, Any] = {}


def _lower_ctx():
    import jax
    from .registry import LowerCtx
    return LowerCtx(rng_key=jax.random.PRNGKey(0))


def infer_op(op_type: str, in_structs: Dict[str, List[Any]],
             attrs: Dict[str, Any], ictx: Optional[InferCtx] = None
             ) -> Dict[str, List[Any]]:
    """Infer output ShapeDtypeStructs of one op from input structs.

    Uses the op's explicit `infer_spec` when registered, else derives the
    result by abstract-evaluating the lowering (`jax.eval_shape` — no FLOPs,
    no buffers). in_structs: slot -> list of jax.ShapeDtypeStruct (or
    anything with .shape/.dtype). Raises on ops in INFER_WAIVED."""
    import jax
    from .registry import lookup_op

    if op_type in INFER_WAIVED:
        raise NotImplementedError(
            f"op {op_type!r} is waived from static inference: "
            f"{INFER_WAIVED[op_type]}")
    opdef = lookup_op(op_type)
    in_structs = {k: [jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
                      for v in vs] for k, vs in in_structs.items()}
    if opdef.infer_spec is not None:
        in_shapes = {k: [tuple(v.shape) for v in vs]
                     for k, vs in in_structs.items()}
        in_dtypes = {k: [np.dtype(v.dtype) for v in vs]
                     for k, vs in in_structs.items()}
        out = opdef.infer_spec(ictx, in_shapes, in_dtypes, dict(attrs))
        return {k: [jax.ShapeDtypeStruct(tuple(s), np.dtype(d))
                    for s, d in vs] for k, vs in out.items()}

    # memoize eval-derived results: real programs repeat the same op shape
    # (every resnet block's conv) and eval_shape re-traces per call
    memo_key = None
    try:
        attr_key = tuple(sorted((k, v if not isinstance(v, (list, np.ndarray))
                                 else repr(np.asarray(v).tolist()))
                                for k, v in attrs.items()))
        memo_key = (op_type, attr_key,
                    tuple((k, tuple((tuple(v.shape), str(v.dtype))
                                    for v in vs))
                          for k, vs in sorted(in_structs.items())))
        hash(memo_key)
    except TypeError:
        memo_key = None
    if memo_key is not None and memo_key in _MEMO:
        return _MEMO[memo_key]

    ctx = _lower_ctx()
    ctx.is_test = bool(attrs.get("is_test", False))

    def f(ins):
        return opdef.lower(ctx, ins, dict(attrs)) or {}

    out = jax.eval_shape(f, in_structs)
    if memo_key is not None:
        _MEMO[memo_key] = out
    return out


@dataclass
class InferResult:
    types: Dict[Tuple[int, str], Any]      # (block idx, var name) -> struct
    diagnostics: List[Diagnostic]
    n_ops: int = 0
    n_inferred: int = 0
    n_skipped: int = 0

    @property
    def errors(self):
        return [d for d in self.diagnostics if d.severity == "error"]


def _shapes_compatible(inferred: tuple, declared: tuple) -> bool:
    if len(inferred) != len(declared):
        # a declared leading batch dim of -1 with the value reshaped flat
        # is still a mismatch; ranks must agree
        return False
    for di, dd in zip(inferred, declared):
        if dd == -1:
            continue                       # declared wildcard
        if di == dd:
            continue
        return False
    return True


def infer_program(program: Program, nominal_batch: int = BATCH_SENTINEL,
                  extra_feeds: Sequence[str] = ()) -> InferResult:
    """Whole-program shape/dtype inference + declared-shape cross-check.

    Walks every block in op order. Feeds (is_data), persistables, and
    `extra_feeds` seed the environment from their declared shapes with -1
    batch dims replaced by the sentinel; each op's outputs are inferred and
    compared against declared Variable shapes, with mismatches reported as
    error diagnostics carrying op provenance. Ops whose inputs are unknown
    (sub-block bindings, waived producers) degrade to their declared output
    shapes and are counted as skipped, never mis-reported."""
    import jax

    res = InferResult(types={}, diagnostics=[])
    diags = res.diagnostics

    for block in program.blocks:
        env: Dict[str, Any] = {}
        # sharded-update state (r08 ZeRO-1): vars marked dp_shard_update
        # are declared at their GLOBAL shape but execute per-shard at
        # [dim0/dp, ...] — seed and cross-check them at the shard shape
        dp = max((int(op.attrs.get("dp", 1)) for op in block.ops
                  if op.type == "dp_grad_comm"), default=1)

        def _shard_aware_shape(v):
            # tp localization first (tp_shard_pass marks), then the r08
            # dp-sharded-update split of (the tp-local) dim 0
            shape = _tp_localized(v, _subst(v.shape, nominal_batch),
                                  program)
            if (getattr(v, "dp_shard_update", False) and dp > 1
                    and shape and shape[0] % dp == 0):
                shape = (shape[0] // dp,) + shape[1:]
            return shape

        def _seed(name):
            """Struct from the declared shape, or None."""
            try:
                v = block.var(name)
            except NotFoundError:
                return None
            if v.shape is None:
                return None
            return jax.ShapeDtypeStruct(_shard_aware_shape(v),
                                        _canon_dtype(v.dtype))

        b = block
        while b is not None:
            for name, v in b.vars.items():
                if v.is_data or v.persistable or name in set(extra_feeds):
                    s = _seed(name)
                    if s is not None and name not in env:
                        env[name] = s
            b = b.parent

        def _fallback_outputs(op):
            for name in op.output_names():
                s = _seed(name)
                if s is not None:
                    env[name] = s

        for idx, op in enumerate(block.ops):
            res.n_ops += 1
            loc = op_loc(block, idx, op)

            if op.type in _REGION_OPS:
                # Grads outputs mirror the diff targets' structs; LossGrad
                # mirrors the loss (backward.py append_backward layout)
                targets = list(op.attrs.get("targets", ()))
                gnames = list(op.outputs.get("Grads", ()))
                for gname, tname in zip(gnames, targets):
                    s = env.get(tname)
                    if s is None:
                        s = _seed(tname)
                    if s is not None:
                        env[gname] = s
                loss = op.attrs.get("loss")
                ls = env.get(loss) if loss else None
                if ls is None and loss:
                    ls = _seed(loss)
                for lg in op.outputs.get("LossGrad", ()):
                    if ls is not None:
                        env[lg] = ls
                res.n_inferred += 1
                continue

            if op.type in INFER_WAIVED:
                _fallback_outputs(op)
                res.n_skipped += 1
                continue

            in_structs, unknown = {}, False
            for slot, names in op.inputs.items():
                vals = []
                for n in names:
                    s = env.get(n)
                    if s is None:
                        s = _seed(n)
                    if s is None:
                        unknown = True
                        break
                    vals.append(s)
                if unknown:
                    break
                in_structs[slot] = vals
            if unknown:
                _fallback_outputs(op)
                res.n_skipped += 1
                continue

            ictx = InferCtx(block=block, op=op, op_idx=idx,
                            nominal_batch=nominal_batch)
            try:
                out = infer_op(op.type, in_structs, op.attrs, ictx)
            except Exception as e:  # noqa: BLE001 — degrade, don't abort
                diags.append(Diagnostic(
                    "infer-error", loc,
                    f"shape inference over the lowering failed: "
                    f"{type(e).__name__}: {str(e)[:300]}",
                    severity="warning"))
                _fallback_outputs(op)
                res.n_skipped += 1
                continue
            res.n_inferred += 1

            for slot, names in op.outputs.items():
                vals = out.get(slot)
                if vals is None:
                    for n in names:
                        s = _seed(n)
                        if s is not None:
                            env[n] = s
                    continue
                if len(vals) < len(names):
                    # spec/lowering arity drift must not silently starve
                    # downstream inference via zip truncation
                    diags.append(Diagnostic(
                        "infer-arity", loc,
                        f"slot {slot!r}: rule returned {len(vals)} "
                        f"value(s) for {len(names)} declared outputs",
                        severity="warning"))
                    for n in names[len(vals):]:
                        s = _seed(n)
                        if s is not None:
                            env[n] = s
                for n, s in zip(names, vals):
                    if s is None:
                        continue
                    env[n] = s
                    v = block.vars.get(n)
                    if v is None or v.shape is None:
                        continue
                    declared = _tp_localized(v, tuple(v.shape), program)
                    if (getattr(v, "dp_shard_update", False) and dp > 1
                            and declared and declared[0] % dp == 0):
                        declared = (declared[0] // dp,) + declared[1:]
                    if not _shapes_compatible(tuple(s.shape), declared):
                        diags.append(Diagnostic(
                            "shape-mismatch", loc,
                            f"output {n!r} (slot {slot!r}): inferred "
                            f"{_render_shape(s.shape, nominal_batch)} != "
                            f"declared {list(v.shape)}"))
                    if not _dtypes_compatible(s.dtype, v.dtype):
                        diags.append(Diagnostic(
                            "dtype-mismatch", loc,
                            f"output {n!r} (slot {slot!r}): inferred "
                            f"{np.dtype(s.dtype).name} != declared "
                            f"{np.dtype(v.dtype).name}"))

        for name, s in env.items():
            res.types[(block.idx, name)] = s
    return res


def infer_coverage() -> Tuple[List[str], Dict[str, str]]:
    """(ops static inference covers, waived op -> reason). Coverage =
    explicit infer_spec, engine-interpreted region op, or eval_shape over
    the lowering; the floor test in test_op_coverage.py asserts the covered
    fraction stays >= 90% and every waiver carries its reason."""
    from .registry import registered_ops
    ops = registered_ops()
    covered = [op for op in ops if op not in INFER_WAIVED]
    return covered, {op: r for op, r in INFER_WAIVED.items() if op in ops}


# ---------------------------------------------------------------------------
# structural + parallel verification
# ---------------------------------------------------------------------------

# control-flow ops binding sub-block var names via attrs (see the def-
# before-use walk): their string/string-list attrs name vars defined inside
# the referenced block
_SUB_KEYS = ("sub_block", "true_block", "false_block",
             "case_blocks", "default_block")


def _binder_names(program: Program) -> Dict[int, set]:
    bound: Dict[int, set] = {}
    for blk in program.blocks:
        for op in blk.ops:
            sub_idxs = []
            for key in _SUB_KEYS:
                v = op.attrs.get(key)
                if isinstance(v, int) and not isinstance(v, bool):
                    sub_idxs.append(v)
                elif isinstance(v, (list, tuple)):
                    sub_idxs.extend(x for x in v if isinstance(x, int))
            if not sub_idxs:
                continue
            names = set()
            for v in op.attrs.values():
                if isinstance(v, str):
                    names.add(v)
                elif isinstance(v, (list, tuple)) and \
                        all(isinstance(x, str) for x in v):
                    names.update(v)
            for si in sub_idxs:
                if 0 < si < len(program.blocks):
                    bound.setdefault(si, set()).update(names)
    return bound


def _check_def_before_use(program, extra_feeds, diags):
    """Every op input produced earlier, fed (is_data), persistable, or a
    recognized companion/binder var (absorbed from the old CheckPass ≙
    multi_devices_check_pass + ir::HasCircle,
    reference parallel_executor.cc:91 / multi_devices_graph_pass.cc:465)."""
    bound = _binder_names(program)
    for block in program.blocks:
        defined = set(extra_feeds) | bound.get(block.idx, set())
        for name, var in block.vars.items():
            if (getattr(var, "persistable", False)
                    or getattr(var, "is_data", False)):
                defined.add(name)
                defined.add(name + "@SEQLEN")
        b = block
        while b.parent is not None:
            b = b.parent
            defined |= set(b.vars)
        for idx, op in enumerate(block.ops):
            for name in op.input_names():
                if name not in defined:
                    diags.append(Diagnostic(
                        "def-before-use", op_loc(block, idx, op),
                        f"reads {name!r} before any producer/feed"))
            defined.update(op.output_names())


def _check_duplicate_writers(program, diags):
    """A non-persistable var written by two ops is a rewrite hazard (which
    value do readers see?). Sanctioned second writers: pp_recv (the
    partition pass deliberately re-binds crossing names on the consuming
    stage), TensorArray writes (append semantics), and self-updating ops
    that also READ the var they rewrite (increment(in_place=True),
    switch_case re-binding a produced target via its Prev input) — those
    are ordered in-place updates, not ambiguous rebindings."""
    exempt_types = {"pp_recv", "array_write"}
    for block in program.blocks:
        # record ALL writers (exempt ones included, so a non-exempt second
        # writer after an array_write/pp_recv first writer still reports);
        # only the exempt op itself is never flagged as the duplicate
        writers: Dict[str, List[int]] = {}
        for idx, op in enumerate(block.ops):
            for name in op.output_names():
                writers.setdefault(name, []).append(idx)
        for name, idxs in writers.items():
            if len(idxs) < 2:
                continue
            try:
                v = block.var(name)
                if v.persistable:
                    continue
            except NotFoundError:
                pass
            first = idxs[0]
            for idx in idxs[1:]:
                op = block.ops[idx]
                if op.type in exempt_types:
                    continue
                if name in op.input_names():
                    continue                  # in-place self-update
                diags.append(Diagnostic(
                    "duplicate-writer", op_loc(block, idx, op),
                    f"re-writes non-persistable {name!r} already produced "
                    f"by op#{first} {block.ops[first].type!r}"))


def _check_attr_schemas(program, diags):
    """Structural attribute invariants of region/boundary ops: recorded op
    indices must address real, earlier ops; stage lists must partition the
    region; dp_grad_comm's plan arrays must stay aligned."""
    for block in program.blocks:
        n = len(block.ops)
        for idx, op in enumerate(block.ops):
            loc = op_loc(block, idx, op)
            role = op.attrs.get("op_role")
            if role is not None and not isinstance(role, str):
                diags.append(Diagnostic(
                    "attr-schema", loc,
                    f"op_role must be a string, got {type(role).__name__}"))
            if op.type in _REGION_OPS:
                seg = op.attrs.get("fwd_ops")
                if not isinstance(seg, (list, tuple)):
                    diags.append(Diagnostic(
                        "attr-schema", loc, "missing fwd_ops index list"))
                    continue
                bad = [i for i in seg
                       if not isinstance(i, (int, np.integer))
                       or i < 0 or i >= n or i == idx]
                if bad:
                    diags.append(Diagnostic(
                        "attr-schema", loc,
                        f"fwd_ops indices out of range: {bad[:6]}"))
                if not isinstance(op.attrs.get("targets"), (list, tuple)) \
                        or "loss" not in op.attrs:
                    diags.append(Diagnostic(
                        "attr-schema", loc,
                        "region op missing targets/loss attrs"))
            if op.type == "pp_pipeline_region":
                stages = op.attrs.get("stages") or []
                k = op.attrs.get("num_stages")
                if len(stages) != k or any(not s for s in stages):
                    diags.append(Diagnostic(
                        "attr-schema", loc,
                        f"stages must be {k} non-empty op-index lists, got "
                        f"{[len(s) for s in stages]}"))
                flat = sorted(i for s in stages for i in s)
                if flat != sorted(op.attrs.get("fwd_ops", ())):
                    diags.append(Diagnostic(
                        "attr-schema", loc,
                        "stages do not partition fwd_ops"))
            if op.type in ("pp_send", "pp_recv") and \
                    not isinstance(op.attrs.get("cut"),
                                   (int, np.integer)):
                diags.append(Diagnostic(
                    "attr-schema", loc, "missing integer 'cut' attr"))
            if op.type == "dp_grad_comm":
                kinds = op.attrs.get("kinds", [])
                numels = op.attrs.get("numels", [])
                shapes = op.attrs.get("shapes", [])
                xs = op.inputs.get("X", [])
                outs = op.outputs.get("Out", [])
                if not (len(kinds) == len(numels) == len(shapes)
                        == len(xs) == len(outs)):
                    diags.append(Diagnostic(
                        "attr-schema", loc,
                        f"plan arrays misaligned: kinds={len(kinds)} "
                        f"numels={len(numels)} shapes={len(shapes)} "
                        f"X={len(xs)} Out={len(outs)}"))
                    continue
                covered = set()
                for b in op.attrs.get("buckets", []):
                    for i in b:
                        if i in covered or i >= len(kinds) \
                                or kinds[i] != "bucket":
                            diags.append(Diagnostic(
                                "attr-schema", loc,
                                f"bucket entry {i} invalid (dup, out of "
                                f"range, or not kind='bucket')"))
                        covered.add(i)
                missing = [i for i, k in enumerate(kinds)
                           if k == "bucket" and i not in covered]
                if missing:
                    diags.append(Diagnostic(
                        "attr-schema", loc,
                        f"bucket-kind gradients not in any bucket: "
                        f"{missing[:6]}"))


def _check_pipeline_invariants(program, diags):
    """Every stage cut carries exactly one matched pp_send/pp_recv pair:
    same cut id, send before recv, send inputs == recv outputs (the names
    re-bound on the consuming stage); a pp_pipeline_region of K stages owns
    cuts 0..K-2 — and boundary ops without a region are orphans."""
    for block in program.blocks:
        sends: Dict[Any, List[int]] = {}
        recvs: Dict[Any, List[int]] = {}
        regions = []
        for idx, op in enumerate(block.ops):
            if op.type == "pp_send":
                sends.setdefault(op.attrs.get("cut"), []).append(idx)
            elif op.type == "pp_recv":
                recvs.setdefault(op.attrs.get("cut"), []).append(idx)
            elif op.type == "pp_pipeline_region":
                regions.append(idx)
        if not (sends or recvs or regions):
            continue
        if (sends or recvs) and not regions:
            idx = min(v[0] for v in (list(sends.values())
                                     + list(recvs.values())))
            diags.append(Diagnostic(
                "pp-orphan-boundary", op_loc(block, idx, block.ops[idx]),
                "pp_send/pp_recv present but no pp_pipeline_region "
                "executes them"))
        for cut in sorted(set(sends) | set(recvs), key=repr):
            s, r = sends.get(cut, []), recvs.get(cut, [])
            if len(s) != 1 or len(r) != 1:
                idx = (s or r)[0]
                diags.append(Diagnostic(
                    "pp-unmatched-boundary",
                    op_loc(block, idx, block.ops[idx]),
                    f"cut {cut}: expected exactly one pp_send and one "
                    f"pp_recv, found {len(s)} send(s) / {len(r)} recv(s)"))
                continue
            si, ri = s[0], r[0]
            if si >= ri:
                diags.append(Diagnostic(
                    "pp-unmatched-boundary",
                    op_loc(block, si, block.ops[si]),
                    f"cut {cut}: pp_send (op#{si}) must precede its "
                    f"pp_recv (op#{ri})"))
            snames = list(block.ops[si].inputs.get("X", ()))
            rnames = list(block.ops[ri].outputs.get("Out", ()))
            if snames != rnames:
                diags.append(Diagnostic(
                    "pp-unmatched-boundary",
                    op_loc(block, ri, block.ops[ri]),
                    f"cut {cut}: pp_recv outputs {rnames} != pp_send "
                    f"inputs {snames}"))
        for ridx in regions:
            rop = block.ops[ridx]
            k = int(rop.attrs.get("num_stages", 0))
            m = int(rop.attrs.get("num_microbatches", 0))
            loc = op_loc(block, ridx, rop)
            if k < 2:
                diags.append(Diagnostic(
                    "pp-config", loc, f"num_stages must be >= 2, got {k}"))
            if m < 1:
                diags.append(Diagnostic(
                    "pp-config", loc,
                    f"num_microbatches must be >= 1, got {m}"))
            want = set(range(max(0, k - 1)))
            have = {c for c in sends if isinstance(c, (int, np.integer))}
            if k >= 2 and want != have:
                diags.append(Diagnostic(
                    "pp-unmatched-boundary", loc,
                    f"{k} stages need cuts {sorted(want)}, pp_send ops "
                    f"cover {sorted(have)}"))


def _check_dp_comm_invariants(program, diags):
    """dp_grad_comm must sit BETWEEN the backward region and every gradient
    consumer: raw region gradients flow only into the comm op, every
    consumer of a comm'd gradient runs after it, and sharded-path entries
    stay dp-divisible (≙ the placement contract of
    fuse_all_reduce_op_pass + multi_devices_graph_pass)."""
    from .lowering import grad_var_name
    for block in program.blocks:
        comms = [(i, op) for i, op in enumerate(block.ops)
                 if op.type == "dp_grad_comm"]
        if not comms:
            continue
        region_idxs = [i for i, op in enumerate(block.ops)
                       if op.type in _REGION_OPS]
        for cidx, comm in comms:
            loc = op_loc(block, cidx, comm)
            if not region_idxs or min(region_idxs) > cidx:
                diags.append(Diagnostic(
                    "dp-comm-misplaced", loc,
                    "no backward region (vjp_region/pp_pipeline_region) "
                    "precedes dp_grad_comm"))
                continue
            rop = block.ops[max(i for i in region_idxs if i < cidx)]
            target_grads = {grad_var_name(t)
                            for t in rop.attrs.get("targets", ())}
            raw = [n for n in comm.inputs.get("X", ())]
            stray = [n for n in raw if n not in target_grads]
            if stray:
                diags.append(Diagnostic(
                    "dp-comm-misplaced", loc,
                    f"inputs {stray[:4]} are not gradients of the "
                    f"preceding region's targets"))
            outs = set(comm.outputs.get("Out", ()))
            raw_set = set(raw)
            for idx, op in enumerate(block.ops):
                if op is comm or op.type in _REGION_OPS:
                    continue
                reads = set(op.input_names())
                bypass = sorted(reads & raw_set)
                if bypass:
                    diags.append(Diagnostic(
                        "dp-comm-bypass", op_loc(block, idx, op),
                        f"reads raw (un-reduced) gradient(s) {bypass[:4]} "
                        f"— consumers must read the dp_grad_comm outputs"))
                early = sorted(reads & outs) if idx < cidx else []
                if early:
                    diags.append(Diagnostic(
                        "dp-comm-misplaced", op_loc(block, idx, op),
                        f"consumes comm'd gradient(s) {early[:4]} before "
                        f"dp_grad_comm (op#{cidx}) produces them"))
            dp = int(comm.attrs.get("dp", 1))
            kinds = comm.attrs.get("kinds", ())
            shapes = comm.attrs.get("shapes", ())
            xs = comm.inputs.get("X", ())
            if not (len(kinds) == len(shapes) == len(xs)):
                continue    # misaligned plan: attr-schema already reported
            for i, kind in enumerate(kinds):
                if kind != "sharded":
                    continue
                shape = shapes[i]
                if not shape or int(shape[0]) % max(dp, 1) != 0:
                    diags.append(Diagnostic(
                        "dp-divisibility", loc,
                        f"sharded gradient {xs[i]!r} dim0 "
                        f"{shape and shape[0]} not divisible by dp={dp}"))


def verify_program(program: Program,
                   extra_feeds: Sequence[str] = ()) -> List[Diagnostic]:
    """Layer-2 structural + parallel consistency verification. Returns the
    full diagnostic list (empty = clean); never raises. The dataflow
    detectors (framework/dataflow.py: collective consistency/deadlock,
    replica divergence, buffer-reuse races) run here too — pure Python
    over the IR, so every sanitized pass apply gets them for free."""
    diags: List[Diagnostic] = []
    _check_def_before_use(program, extra_feeds, diags)
    _check_duplicate_writers(program, diags)
    _check_attr_schemas(program, diags)
    _check_pipeline_invariants(program, diags)
    _check_dp_comm_invariants(program, diags)
    from . import dataflow as _dataflow     # lazy: dataflow imports us
    diags += _dataflow.dataflow_checks(program)
    return diags


def analyze_program(program: Program, extra_feeds: Sequence[str] = (),
                    nominal_batch: int = BATCH_SENTINEL,
                    infer: bool = True,
                    tp_size: Optional[int] = None) -> List[Diagnostic]:
    """Full static analysis: structural verification + (optionally)
    whole-program shape/dtype inference + — whenever the program carries
    tp sharding annotations (or `tp_size` is given) — sharding propagation
    (framework/sharding.py), so annotation conflicts surface with the same
    op provenance as every other diagnostic. Returns all diagnostics."""
    diags = verify_program(program, extra_feeds=extra_feeds)
    if infer:
        diags += infer_program(program, nominal_batch=nominal_batch,
                               extra_feeds=extra_feeds).diagnostics
    from . import sharding as _sharding
    if tp_size is not None or _sharding.has_tp_annotations(program):
        diags += _sharding.propagate_sharding(
            program, tp_size=tp_size,
            nominal_batch=nominal_batch).diagnostics
    return diags


def check_program(program: Program, extra_feeds: Sequence[str] = (),
                  infer: bool = True) -> None:
    """Raise ProgramAnalysisError when analysis finds error-severity
    diagnostics (warnings pass)."""
    diags = analyze_program(program, extra_feeds=extra_feeds, infer=infer)
    errors = [d for d in diags if d.severity == "error"]
    if errors:
        raise ProgramAnalysisError(
            "program analysis failed:\n  "
            + "\n  ".join(str(d) for d in errors), errors)


# ---------------------------------------------------------------------------
# pass sanitizer
# ---------------------------------------------------------------------------


def sanitizer_enabled() -> bool:
    return bool(flags.get_flag("verify_passes"))


_OPNUM = _re.compile(r"op#\d+")


def _attribution_key(d: Diagnostic) -> tuple:
    """Diagnostic identity for the before/after comparison, with op indices
    masked out: a pass that inserts or removes ops renumbers every later
    op#, and a pre-existing violation whose loc merely shifted must stay
    the caller's, not be blamed on the pass."""
    return (d.code, _OPNUM.sub("op#*", d.loc), _OPNUM.sub("op#*", d.message))


def sanitized_apply(pass_obj, program: Program, scope=None):
    """Run one Pass apply under verify-before/verify-after (wired into
    Pass.__call__). Violations present BEFORE the pass are the caller's —
    only NEW error-severity diagnostics are attributed, by name, to the
    pass. Shape inference is not run here (it needs jax tracing; the
    structural verifier is pure Python and cheap enough for every apply) —
    lint/tests run the full analyzer."""
    if not sanitizer_enabled() or getattr(pass_obj, "name", "") == "check_pass":
        return pass_obj.apply(program, scope)
    before = {_attribution_key(d) for d in verify_program(program)}
    out = pass_obj.apply(program, scope)
    target = out if isinstance(out, Program) else program
    new = [d for d in verify_program(target)
           if d.severity == "error" and _attribution_key(d) not in before]
    if new:
        raise PassSanitizerError(pass_obj.name, new)
    return out


# ---------------------------------------------------------------------------
# static memory estimate (lint_program's peak-live-bytes table)
# ---------------------------------------------------------------------------


def peak_live_bytes(program: Program, nominal_batch: int = 8) -> Dict:
    """Static peak-live-bytes estimate from variable lifetimes: a transient
    var is live from its first writer to its last reader (inclusive);
    feeds/persistables are live for the whole program. -1 dims count as
    `nominal_batch` rows. An *estimate* — XLA's buffer assignment reuses
    and fuses further — but it ranks programs and partitionings the same
    way (the lifetime census discipline of
    transpiler/memory_optimization.py).

    The walk covers the WHOLE program, not just block 0's op list:

    - backward regions (`vjp_region`/`pp_pipeline_region`) keep every
      value their forward segment touches live until the region executes
      (the backward re-runs the segment under jax.vjp, so activations are
      backward inputs — dataflow.var_lifetimes owns this rule). The pp
      region's *schedule-dependent* stash (≤K in-flight microbatches under
      1F1B, =M under GPipe) is NOT modeled here — parallel/pipeline.py's
      stash census owns that number;
    - sub-blocks (while/cond_block/static_rnn/switch_case bodies) are
      walked recursively: a sub-block's own transient peak is attributed
      at its binder op's index in the parent — live for exactly the ops
      that execute it.

    Returns the block-0 keys of the r10 shape plus `sub_block_peaks`
    ({block idx: transient bytes} for every bound sub-block)."""
    from . import dataflow as _dataflow

    def nbytes(block, name):
        # only vars DECLARED in this block: parent vars are the parent
        # sweep's to count (persistables/feeds are block 0's); ONE
        # pricing rule shared with the memory planner
        return _dataflow.declared_var_bytes(block, name, nominal_batch)

    block0 = program.global_block()
    persistent, feed = 0, 0
    for name, v in block0.vars.items():
        if v.persistable:
            persistent += nbytes(block0, name)
        elif v.is_data:
            feed += nbytes(block0, name)

    # binder op -> sub-block indices (while/cond_block/... attrs)
    def sub_idxs(op):
        out = []
        for key in _SUB_KEYS:
            v = op.attrs.get(key)
            if isinstance(v, int) and not isinstance(v, bool):
                out.append(v)
            elif isinstance(v, (list, tuple)):
                out.extend(x for x in v if isinstance(x, int))
        return [i for i in out if 0 < i < len(program.blocks)]

    sub_peaks: Dict[int, int] = {}

    def block_peak(bidx, chain=()):
        enforce(bidx not in chain,
                f"peak_live_bytes: sub-block {bidx} binds itself "
                f"(binder chain {chain}) — the lifetime walk cannot "
                f"terminate on a cyclic block graph",
                exc=EnforceError)
        block = program.blocks[bidx]
        n = len(block.ops)
        lifetimes = _dataflow.var_lifetimes(block)
        alloc: Dict[int, int] = {}
        free: Dict[int, int] = {}
        for name, (w, end) in lifetimes.items():
            v = block.vars.get(name)
            if v is not None and (v.persistable or v.is_data):
                continue
            size = nbytes(block, name)
            if not size:
                continue
            alloc[w] = alloc.get(w, 0) + size
            free[end + 1] = free.get(end + 1, 0) + size
        for idx, op in enumerate(block.ops):
            for si in sub_idxs(op):
                sp = block_peak(si, chain + (bidx,))
                sub_peaks[si] = sp
                alloc[idx] = alloc.get(idx, 0) + sp
                free[idx + 1] = free.get(idx + 1, 0) + sp
        peak, peak_at, live = 0, None, 0
        for t in range(n):
            live += alloc.get(t, 0) - free.get(t, 0)
            if live > peak:
                peak, peak_at = live, t
        return (peak, peak_at) if bidx == 0 else peak

    peak, peak_at = block_peak(0)
    loc = (op_loc(block0, peak_at, block0.ops[peak_at])
           if peak_at is not None else None)
    return {"persistent_bytes": persistent,
            "feed_bytes": feed,
            "peak_transient_bytes": peak,
            "peak_total_bytes": persistent + feed + peak,
            "peak_at": loc,
            "sub_block_peaks": dict(sorted(sub_peaks.items())),
            "nominal_batch": nominal_batch}
