"""Host-offload substrate: the pinned host pool + overlapped d2h/h2d
transfer stream that turn host RAM into a planned second memory tier
(ROADMAP item 5(a), ISSUE r23 tentpole).

The r20 paged KV pool, the r18 memory planner, and the ZeRO-1 reduce
mode all stop at the HBM boundary. The reference framework's pinned
host allocator + async memcpy streams (PAPER.md §L0/L1) make host
memory a first-class tier instead; "Memory-efficient array
redistribution through portable collective communication" (PAPERS.md)
is the framing — shards move between memory *tiers* with the same
planned-transfer discipline `reshard.py` uses between meshes. This
module is the shared substrate; three consumers ride it:

- **two-tier paged KV** (`serving/kv_pager.py`): `PagedKVEngine(
  host_tier=HostTierConfig(...))` spills cold requests' private blocks
  to the host pool and prefetches them back ahead of scheduled reads,
  so admitted concurrency at a fixed device pool-byte budget exceeds
  the r20/r21 device-only ceiling (BENCH_OFFLOAD_r23.json).
- **host-resident optimizer state** (`HostOptimizerState`, wired into
  `ParallelExecutor.run` behind `BuildStrategy.offload_optimizer_state`):
  ZeRO-1 accumulator shards live on host between steps and round-trip
  per step, priced by the `offload` section of `costs.predict` so the
  planner can refuse the mode when the PCIe transfer doesn't hide.
- **memory-plan stash tier** (`framework/memory_plan.py`): the
  remat-vs-stash search gains a stash-to-host alternative priced
  against the same `V5E_PCIE_BPS` roofline.

Three deliberate disciplines, inherited from earlier rounds:

- one accounting source (r17): every host-resident byte — KV spill,
  checkpoint staging (`elastic.save_train_state`), optimizer shards —
  goes through the ONE `shared_host_pool()` ledger, which publishes
  the `host_*_bytes` watermark channels. The census cannot
  double-count what a single ledger emits.
- exact wire census (r08/r11): `TransferStream` counts the actual
  bytes each job moves; BENCH_OFFLOAD_r23.json asserts predicted
  d2h/h2d bytes == these counters EXACTLY, per cell.
- named-diagnostic lint (r13): `check_schedule` turns a transfer
  scheduled after its read into the error-severity
  `offload-use-before-arrival` diagnostic (`tools/lint_program.py
  --offload`), with a mutation test per code.

CPU-mesh caveat, stated once here and repeated in every artifact that
prices the roofline: on this container's CPU backend "device" and
"host" are the same DRAM, so `np.asarray` (d2h) and `jnp.asarray`
(h2d) are memcpys, not PCIe DMA — transfer *overlap* is real (the
stream thread runs while the compute thread ticks; numpy releases the
GIL on large copies) but transfer *time* is not PCIe time. The
`V5E_PCIE_BPS` roofline prices the TPU case; measured cells carry an
explicit `cpu_mesh_caveat`.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..core.enforce import InvalidArgumentError, enforce

__all__ = [
    "HostTierConfig", "PinnedHostPool", "HostBuffer", "HostLease",
    "TransferStream", "TransferTicket", "shared_host_pool",
    "shared_stream", "HostOptimizerState", "optimizer_state_names",
    "TransferEvent", "prefetch_issue_tick", "kv_prefetch_events",
    "optimizer_roundtrip_events", "check_schedule", "offload_metrics",
    "offload_stats", "reset_offload",
]


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------


@dataclass
class HostTierConfig:
    """Policy knobs for the two-tier paged KV cache.

    host_blocks        capacity of the host tier in KV blocks (the same
                       `block_size`-token pages the device BlockPool
                       holds). The pager enforces the two-pool identity
                       used_dev + used_host + free_dev + free_host ==
                       total over both tiers.
    prefetch_distance  start the h2d prefetch of a suspended request's
                       spilled blocks when the earliest projected
                       resume is this many ticks away (the issue tick
                       is `prefetch_issue_tick(read, distance)` — the
                       SAME helper `lint_program --offload` checks, so
                       the linted policy is the executed policy).
    rotate_quantum     anti-starvation: when a suspended request has
                       waited this many ticks with no capacity, evict
                       the resident request with the most remaining
                       work to host and hand its blocks over. 0
                       disables rotation (run-to-completion; suspended
                       requests resume only when a resident finishes).
    pin_index_nodes    prefix-sharing radix-index blocks never spill
                       (they are the highest-fanout bytes on the
                       device tier; evicting them trades one request's
                       latency for every sharer's).
    """
    host_blocks: int = 64
    prefetch_distance: int = 2
    rotate_quantum: int = 8
    pin_index_nodes: bool = True

    def __post_init__(self):
        enforce(self.host_blocks >= 1,
                f"HostTierConfig.host_blocks must be >= 1, got "
                f"{self.host_blocks}", exc=InvalidArgumentError)
        enforce(self.prefetch_distance >= 0,
                f"HostTierConfig.prefetch_distance must be >= 0, got "
                f"{self.prefetch_distance}", exc=InvalidArgumentError)
        enforce(self.rotate_quantum >= 0,
                f"HostTierConfig.rotate_quantum must be >= 0, got "
                f"{self.rotate_quantum}", exc=InvalidArgumentError)


# ---------------------------------------------------------------------------
# pinned host pool — the ONE host-byte ledger
# ---------------------------------------------------------------------------

#: ledger category -> watermark channel (observability/memory.CHANNELS).
#: `stash` has no live channel yet — the stash tier executes advisorily
#: on this backend (see memory_plan.search_remat) — but the category
#: still rows in `host_tier_rows()` so the census names the bytes.
_CATEGORY_CHANNEL = {
    "kv": "host_kv_bytes",
    "staging": "host_staging_bytes",
    "optimizer": "host_optimizer_bytes",
    "stash": None,
}


class HostBuffer:
    """One pool-owned host allocation (a numpy array standing in for a
    pinned-host region; on TPU this is where `pinned=True` would land)."""

    __slots__ = ("array", "category", "nbytes", "_freed")

    def __init__(self, array: np.ndarray, category: str):
        self.array = array
        self.category = category
        self.nbytes = int(array.nbytes)
        self._freed = False


class HostLease:
    """Accounting-only adoption of host bytes the caller already holds
    (e.g. `collect_chunks` staging in elastic.save_train_state): the
    bytes enter the pool ledger without a copy, and leave on
    `release()` (idempotent — the elastic writer threads release in
    `finally` blocks that can race a sync-path release)."""

    __slots__ = ("_pool", "nbytes", "category", "_released")

    def __init__(self, pool: "PinnedHostPool", nbytes: int, category: str):
        self._pool = pool
        self.nbytes = int(nbytes)
        self.category = category
        self._released = False

    def release(self):
        if not self._released:
            self._released = True
            self._pool._credit(self.category, -self.nbytes)


class PinnedHostPool:
    """The host-tier byte ledger + allocator. Every consumer of host
    RAM as a memory tier allocates (or leases) through here, so the
    `host_*_bytes` watermark channels, `host_tier_rows()` in the
    census, and /healthz all report from one accounting source
    (ISSUE r23 satellite 6: no double-count).

    `capacity_bytes == 0` means unbounded (the KV tier bounds itself
    in blocks via HostTierConfig; checkpoint staging is bounded by the
    snapshot size)."""

    def __init__(self, capacity_bytes: int = 0):
        enforce(capacity_bytes >= 0,
                f"PinnedHostPool capacity_bytes must be >= 0, got "
                f"{capacity_bytes}", exc=InvalidArgumentError)
        self.capacity_bytes = int(capacity_bytes)
        self._lock = threading.Lock()
        self._used: Dict[str, int] = {c: 0 for c in _CATEGORY_CHANNEL}
        self._peak_total = 0

    # -- accounting core ----------------------------------------------------

    def _credit(self, category: str, delta: int):
        enforce(category in _CATEGORY_CHANNEL,
                f"unknown host-pool category {category!r}; known: "
                f"{sorted(_CATEGORY_CHANNEL)}", exc=InvalidArgumentError)
        with self._lock:
            nv = self._used[category] + int(delta)
            enforce(nv >= 0,
                    f"host pool category {category!r} under-released: "
                    f"{self._used[category]} + {delta} < 0",
                    exc=InvalidArgumentError)
            total = sum(self._used.values()) + int(delta)
            if delta > 0 and self.capacity_bytes:
                enforce(total <= self.capacity_bytes,
                        f"host pool over capacity: {total} > "
                        f"{self.capacity_bytes} allocating {delta} "
                        f"bytes of {category!r}",
                        exc=InvalidArgumentError)
            self._used[category] = nv
            if total > self._peak_total:
                self._peak_total = total
            current = nv
        channel = _CATEGORY_CHANNEL[category]
        if channel is not None:
            from ..observability import memory as _memory
            _memory.update_watermark(channel, current)

    # -- allocation ---------------------------------------------------------

    def alloc(self, shape, dtype, category: str) -> HostBuffer:
        """A pool-owned host buffer; the ledger (and the category's
        watermark channel) moves before the caller sees the array."""
        arr = np.empty(shape, dtype=dtype)
        self._credit(category, int(arr.nbytes))
        return HostBuffer(arr, category)

    def free(self, buf: HostBuffer):
        if buf._freed:
            return
        buf._freed = True
        self._credit(buf.category, -buf.nbytes)

    def lease(self, nbytes: int, category: str) -> HostLease:
        """Adopt caller-held host bytes into the ledger (no copy)."""
        lease = HostLease(self, nbytes, category)
        self._credit(category, lease.nbytes)
        return lease

    # -- census surface -----------------------------------------------------

    def used_bytes(self, category: Optional[str] = None) -> int:
        with self._lock:
            if category is None:
                return sum(self._used.values())
            return self._used.get(category, 0)

    def rows(self) -> Dict[str, Any]:
        """The host-tier census rows `device_memory_census` embeds and
        the watermark board mirrors (one shape on both surfaces, r16/r17
        convention): per-category bytes + total + peak + capacity."""
        with self._lock:
            out: Dict[str, Any] = {
                f"host_{c}_bytes": int(v) for c, v in self._used.items()}
            out["host_total_bytes"] = int(sum(self._used.values()))
            out["host_peak_bytes"] = int(self._peak_total)
            out["capacity_bytes"] = int(self.capacity_bytes)
        return out


_shared_pool: Optional[PinnedHostPool] = None
_shared_pool_lock = threading.Lock()


def shared_host_pool() -> PinnedHostPool:
    """The process-wide host-tier ledger. KV spill, checkpoint staging
    and host-resident optimizer state all account here; tests reset it
    via `reset_offload()`."""
    global _shared_pool
    with _shared_pool_lock:
        if _shared_pool is None:
            _shared_pool = PinnedHostPool()
        return _shared_pool


# ---------------------------------------------------------------------------
# transfer stream — overlapped d2h/h2d with an exact byte census
# ---------------------------------------------------------------------------


class TransferTicket:
    """Completion handle for one submitted transfer. `wait()` re-raises
    the job's exception on the caller's thread (the r14 async-d2h
    discipline: a failed background copy surfaces at the join, never
    silently)."""

    __slots__ = ("direction", "nbytes", "tag", "result", "error",
                 "_done", "submitted_s", "finished_s")

    def __init__(self, direction: str, nbytes: int, tag: str):
        self.direction = direction
        self.nbytes = int(nbytes)
        self.tag = tag
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self._done = threading.Event()
        self.submitted_s = time.perf_counter()
        self.finished_s = 0.0

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None):
        ok = self._done.wait(timeout)
        enforce(ok, f"offload transfer {self.direction}/{self.tag} did "
                f"not complete within {timeout}s", exc=TimeoutError)
        if self.error is not None:
            raise self.error
        return self.result


class TransferStream:
    """One FIFO worker thread moving bytes between tiers while the
    compute thread keeps ticking — the shared stream scheduler all
    three offload consumers submit to. Each job runs under an
    `offload` span (kind added to tracing.SPAN_KINDS this round) and
    lands on the exact byte census (`counters()`), which
    BENCH_OFFLOAD_r23.json diffs against the predicted wire bytes.

    The job callable runs ON THE STREAM THREAD: d2h jobs materialize
    jax arrays (`np.asarray` blocks there, overlapping the compute
    thread), h2d jobs stage `jnp.asarray` placements ahead of the
    tick that reads them. Device-side commits (`.at[].set` +
    `scope.set_var`) stay on the compute thread between ticks — jax
    scope mutation is single-writer by design (see
    `PagedKVEngine._pre_tick`)."""

    def __init__(self, name: str = "offload"):
        self.name = name
        self._q: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._counters = {"d2h_bytes": 0, "h2d_bytes": 0,
                          "d2h_jobs": 0, "h2d_jobs": 0, "busy_s": 0.0}
        self._closed = False
        self._thread = threading.Thread(
            target=self._worker, name=f"ptpu-{name}-stream", daemon=True)
        self._thread.start()

    def submit(self, direction: str, fn: Callable[[], Any],
               nbytes: int, tag: str = "") -> TransferTicket:
        enforce(direction in ("d2h", "h2d"),
                f"transfer direction must be 'd2h' or 'h2d', got "
                f"{direction!r}", exc=InvalidArgumentError)
        enforce(not self._closed, "TransferStream is closed",
                exc=InvalidArgumentError)
        t = TransferTicket(direction, nbytes, tag)
        self._q.put((t, fn))
        return t

    def _worker(self):
        from ..observability import tracing as _tracing
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            ticket, fn = item
            t0 = time.perf_counter()
            try:
                with _tracing.span("offload",
                                   f"offload/{ticket.direction}",
                                   bytes=ticket.nbytes,
                                   tag=ticket.tag):
                    ticket.result = fn()
            except BaseException as e:  # surfaces at ticket.wait()
                ticket.error = e
            t1 = time.perf_counter()
            with self._lock:
                self._counters[f"{ticket.direction}_bytes"] += ticket.nbytes
                self._counters[f"{ticket.direction}_jobs"] += 1
                self._counters["busy_s"] += t1 - t0
            _note_bytes(ticket.direction, ticket.nbytes)
            ticket.finished_s = t1
            ticket._done.set()
            self._q.task_done()

    def drain(self):
        """Block until every submitted job has run (errors stay on
        their tickets)."""
        self._q.join()

    def counters(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._counters)

    def close(self):
        if not self._closed:
            self._closed = True
            self._q.put(None)
            self._thread.join(timeout=5.0)


_shared_stream: Optional[TransferStream] = None
_shared_stream_lock = threading.Lock()


def shared_stream() -> TransferStream:
    """The process-wide transfer stream (one FIFO: KV spill, optimizer
    round-trips and stash traffic serialize here the way one DMA
    engine would)."""
    global _shared_stream
    with _shared_stream_lock:
        if _shared_stream is None or _shared_stream._closed:
            _shared_stream = TransferStream()
        return _shared_stream


# ---------------------------------------------------------------------------
# global offload stats -> ptpu_offload_* gauges
# ---------------------------------------------------------------------------

_stats_lock = threading.Lock()
_stats = {"evictions_total": 0, "prefetch_hits_total": 0,
          "prefetch_misses_total": 0, "d2h_bytes_total": 0,
          "h2d_bytes_total": 0}
_gauges = None


def note_eviction(n_blocks: int = 1):
    with _stats_lock:
        _stats["evictions_total"] += int(n_blocks)


def note_prefetch(hit: bool):
    with _stats_lock:
        _stats["prefetch_hits_total" if hit
               else "prefetch_misses_total"] += 1


def _note_bytes(direction: str, nbytes: int):
    with _stats_lock:
        _stats[f"{direction}_bytes_total"] += int(nbytes)


def offload_stats() -> Dict[str, int]:
    with _stats_lock:
        return dict(_stats)


def offload_metrics():
    """The `ptpu_offload_*` series, registered (idempotently) into
    `metrics.default_registry()` next to `ptpu_memory_*` and
    `ptpu_engine_*` (r16 unified-registry discipline)."""
    global _gauges
    if _gauges is None:
        from ..observability import metrics as m
        r = m.default_registry()
        helps = {
            "evictions_total": "KV blocks evicted device -> host "
                               "(two-tier pager).",
            "prefetch_hits_total": "Suspended-request resumes whose h2d "
                                   "prefetch had already landed.",
            "prefetch_misses_total": "Resumes that had to wait on the "
                                     "h2d transfer (prefetch too late "
                                     "or never issued).",
            "d2h_bytes_total": "Bytes moved device -> host by the "
                               "offload transfer stream.",
            "h2d_bytes_total": "Bytes moved host -> device by the "
                               "offload transfer stream.",
        }
        _gauges = {
            k: m.get_or_create(r, "gauge", f"ptpu_offload_{k}", h,
                               fn=(lambda k=k: _stats[k]))
            for k, h in helps.items()}
    return _gauges


def reset_offload():
    """Test isolation: zero the stats and replace the shared pool (the
    shared stream survives — it is stateless beyond its counters)."""
    global _shared_pool
    with _stats_lock:
        for k in _stats:
            _stats[k] = 0
    with _shared_pool_lock:
        _shared_pool = PinnedHostPool()


# ---------------------------------------------------------------------------
# host-resident optimizer state (ZeRO-offload, consumer b)
# ---------------------------------------------------------------------------


def optimizer_state_names(program, scope) -> List[str]:
    """The scope vars that are optimizer state per the ONE classifier
    (`costs.state_category` — the same walk the census and the ledger
    use, so the offloaded set cannot drift from the priced set)."""
    from . import costs as _costs
    names: List[str] = []
    seen = set()
    for b in program.blocks:
        for name, v in b.vars.items():
            if name in seen or not scope.has_var(name):
                continue
            seen.add(name)
            if _costs.state_category(v, name) == "optimizer_state":
                names.append(name)
    return sorted(names)


class HostOptimizerState:
    """ZeRO-offload one tier further: between steps the ZeRO-1
    accumulator shards live ONLY in the pinned host pool; `restore()`
    materializes them back into the scope before the next dispatch and
    `offload()` drops the device copies after the step, with the d2h
    running on the transfer stream behind whatever the host does next
    (next-batch prep, dispatch assembly).

    The round-trip is bitwise (numpy staging preserves exact bytes),
    so offload-on training is loss-identical to offload-off — asserted
    by tests/test_offload.py and the BENCH_OFFLOAD_r23.json optimizer
    cell.

    CPU-mesh caveat: jit consumes every argument at dispatch, so the
    full shard is device-resident DURING the step; the streamed
    per-bucket round-trip the `costs.predict` offload section prices
    (resident working set = one comm bucket) needs the TPU runtime's
    per-bucket donation. Between steps the device census genuinely
    shows optimizer_state == 0 — that part is measurable here."""

    def __init__(self, scope, names: Sequence[str],
                 stream: Optional[TransferStream] = None,
                 pool: Optional[PinnedHostPool] = None):
        enforce(len(names) > 0,
                "HostOptimizerState: no optimizer-state vars to offload "
                "(run the built train step once so the accumulators "
                "exist, or drop offload_optimizer_state)",
                exc=InvalidArgumentError)
        self.scope = scope
        self.names = list(names)
        self.stream = stream or shared_stream()
        self.pool = pool or shared_host_pool()
        self._bufs: Dict[str, HostBuffer] = {}
        self._tickets: Dict[str, TransferTicket] = {}
        self.offloaded = False
        self.roundtrips = 0
        self.last_restore_wait_s = 0.0
        self.bytes_per_direction = 0

    def offload(self):
        """Async d2h: snapshot every accumulator into its pool buffer
        on the stream thread, then erase the device copies from the
        scope (the next `restore()` is what puts them back — the
        ParallelExecutor.run wiring guarantees the order)."""
        if self.offloaded:
            return
        total = 0
        for name in self.names:
            arr = self.scope.get(name)
            nb = int(getattr(arr, "nbytes", 0))
            buf = self._bufs.get(name)
            if buf is None or buf.array.nbytes != nb \
                    or buf.array.dtype != arr.dtype:
                if buf is not None:
                    self.pool.free(buf)
                buf = self.pool.alloc(arr.shape, arr.dtype, "optimizer")
                self._bufs[name] = buf

            def _copy(arr=arr, buf=buf):
                # np.asarray blocks on the step's async result HERE,
                # on the stream thread — the overlap the census times
                np.copyto(buf.array, np.asarray(arr))

            self._tickets[name] = self.stream.submit(
                "d2h", _copy, buf.nbytes, tag=name)
            total += buf.nbytes
            self.scope.erase(name)
        self.bytes_per_direction = total
        self.offloaded = True

    def restore(self):
        """h2d: wait the in-flight d2h (usually long done — the wait
        time is the measured non-overlap) and place each shard back on
        device. Bytes move on the stream so the census counts them."""
        if not self.offloaded:
            return
        import jax.numpy as jnp
        t0 = time.perf_counter()
        for name in self.names:
            t = self._tickets.pop(name, None)
            if t is not None:
                t.wait(timeout=60.0)
        self.last_restore_wait_s = time.perf_counter() - t0
        for name in self.names:
            buf = self._bufs[name]
            ticket = self.stream.submit(
                "h2d", (lambda b=buf: jnp.asarray(b.array)),
                buf.nbytes, tag=name)
            self.scope.set_var(name, ticket.wait(timeout=60.0))
        self.offloaded = False
        self.roundtrips += 1

    def release(self):
        """Return the scratch buffers to the pool (state must be
        device-resident — call `restore()` first)."""
        enforce(not self.offloaded,
                "HostOptimizerState.release while state is host-resident"
                " — restore() first", exc=InvalidArgumentError)
        for buf in self._bufs.values():
            self.pool.free(buf)
        self._bufs.clear()


# ---------------------------------------------------------------------------
# transfer schedules — the lintable policy surface
# ---------------------------------------------------------------------------


@dataclass
class TransferEvent:
    """One planned tier move, in tick (serving) or op-index (training)
    time: issued at `issue_tick`, data resident by `arrive_tick`, first
    consumed at `read_tick`. The invariant `lint_program --offload`
    enforces: arrival strictly before-or-at the read."""
    var: str
    direction: str            # "d2h" | "h2d"
    issue_tick: int
    arrive_tick: int
    read_tick: int


def prefetch_issue_tick(read_tick: int, prefetch_distance: int) -> int:
    """When to start the h2d prefetch of blocks scheduled to be read at
    `read_tick` — the ONE policy helper the two-tier engine executes
    and `lint_program --offload` checks (shared code, not a copy, so
    the linted schedule is the shipped schedule)."""
    return int(read_tick) - int(prefetch_distance)


def kv_prefetch_events(read_ticks: Dict[str, int],
                       prefetch_distance: int) -> List[TransferEvent]:
    """The two-tier KV prefetch schedule for suspended requests whose
    projected resume ticks are `read_ticks` ({request -> tick})."""
    out = []
    for var, read in sorted(read_ticks.items()):
        issue = prefetch_issue_tick(read, prefetch_distance)
        out.append(TransferEvent(var=var, direction="h2d",
                                 issue_tick=issue, arrive_tick=read,
                                 read_tick=read))
    return out


def optimizer_roundtrip_events(program, *, restore_at: int = 0
                               ) -> List[TransferEvent]:
    """The host-resident optimizer round-trip as op-index events over
    one train step: every accumulator must be back on device at
    `restore_at` (step entry — jit consumes all arguments at dispatch)
    and spills after its LAST access. A restore point after an op that
    reads the var is exactly `offload-use-before-arrival`."""
    from . import costs as _costs
    events: List[TransferEvent] = []
    block = program.blocks[0]
    acc = {name for name, v in block.vars.items()
           if _costs.state_category(v, name) == "optimizer_state"}
    if not acc:
        return events
    first_read: Dict[str, int] = {}
    last_access: Dict[str, int] = {}
    for idx, op in enumerate(block.ops):
        names = set()
        for ns in getattr(op, "inputs", {}).values():
            names.update(ns)
        read = {n for n in names if n in acc}
        for ns in getattr(op, "outputs", {}).values():
            names.update(ns)
        for n in names:
            if n in acc:
                last_access[n] = idx
        for n in read:
            first_read.setdefault(n, idx)
    n_ops = len(block.ops)
    for name in sorted(acc):
        events.append(TransferEvent(
            var=name, direction="h2d", issue_tick=restore_at,
            arrive_tick=restore_at,
            read_tick=first_read.get(name, n_ops)))
        events.append(TransferEvent(
            var=name, direction="d2h",
            issue_tick=last_access.get(name, n_ops),
            arrive_tick=n_ops, read_tick=n_ops))
    return events


def check_schedule(events: Sequence[TransferEvent],
                   rollback_windows: Optional[Dict[str, Sequence[int]]]
                   = None) -> List[Any]:
    """r13 named-diagnostic discipline: a transfer that arrives (or is
    even issued) after its first read is the error-severity
    `offload-use-before-arrival` diagnostic. Returns
    `analysis.Diagnostic` rows for `lint_program --offload`.

    r24: `rollback_windows` ({var -> rollback ticks}) extends the check
    to speculative serving. A rollback at tick t rewrites the var's
    device blocks; any in-flight transfer issued BEFORE t but consumed
    AT-OR-AFTER t carries the pre-rollback bytes — the reader would see
    tokens the verifier already rejected. That is
    `offload-stale-after-rollback`: the transfer must be re-issued
    after the rollback it straddles."""
    from .analysis import Diagnostic
    out = []
    for ev in events:
        if ev.arrive_tick > ev.read_tick or ev.issue_tick > ev.read_tick:
            out.append(Diagnostic(
                code="offload-use-before-arrival",
                loc=ev.var,
                message=(f"{ev.direction} scheduled at tick "
                         f"{ev.issue_tick} (arrives {ev.arrive_tick}) "
                         f"but first read is tick {ev.read_tick} — the "
                         f"consumer would see the stale tier"),
                severity="error"))
    for ev in events:
        for t in (rollback_windows or {}).get(ev.var, ()):
            if ev.issue_tick < t <= ev.read_tick:
                out.append(Diagnostic(
                    code="offload-stale-after-rollback",
                    loc=ev.var,
                    message=(f"{ev.direction} issued at tick "
                             f"{ev.issue_tick} straddles the rollback "
                             f"at tick {t} (read at {ev.read_tick}) — "
                             f"the transfer carries rejected "
                             f"speculative bytes and must be re-issued "
                             f"after the rollback"),
                    severity="error"))
    return out
