"""Analytic cost models as a first-class framework API.

Five generations of probes each carried a private copy of some slice of
this: the flop/byte roofline (tools/probe_common, r03+), the collective
wire-byte ring model (r08), the pipeline bubble model (r09), the static
peak-live-bytes estimator (r10), and the tp collective model (r11). This
module is now the ONE home: `tools/probe_common` re-exports from here (so
the r08/r09/r11 exact-census test assertions flow through this API
unchanged), `framework/passes.py` balances pipeline stages with it, and
`predict(program, ...)` joins every model into a single CostReport — the
queryable substrate the auto-parallel planner (ROADMAP item 2) searches
over and `observability/ledger.py` reconciles against measured traces.

Accounting disciplines (unchanged from the probes they came from):

- per-op (flops, bytes) from declared var shapes, -1 batch dims resolved
  to `nominal_batch`; roofline combine max(flops/peak, bytes/bw) at the
  v5e constants;
- per-device interconnect bytes per collective from its (per-device)
  OUTPUT bytes in the partitioned HLO — standard ring-algorithm costs;
- pipeline bubbles from the executed schedule tables, not the closed
  form (they agree exactly: (K-1)/(M+K-1));
- peak live bytes from variable lifetimes (first writer .. last reader).
"""

from __future__ import annotations

import re
from typing import Dict, Optional, Tuple

# ---------------------------------------------------------------------------
# hardware constants (v5e) — the probes, the pipeline partitioner, and the
# benchmark roofline fields all quote the same peaks so one number means
# one thing everywhere
# ---------------------------------------------------------------------------

V5E_PEAK_TFLOPS = 197e12
V5E_HBM_BPS = 819e9

# dtype byte widths for parsing XLA shape strings — the ONE copy shared by
# the probes (probe_caps) and the comm-structure tests. Covers every XLA
# scalar type that can appear in a typed shape (ADVICE r5 #4); an
# unrecognized typed-shape token RAISES instead of silently counting 0
# bytes (which would let byte-balance assertions pass/fail misleadingly
# if dtypes drift).
HLO_ITEM_BYTES = {"pred": 1,
                  "s2": 1, "u2": 1, "s4": 1, "u4": 1,     # sub-byte types
                  "s8": 1, "u8": 1, "s16": 2, "u16": 2,   # pack >= 1 byte
                  "s32": 4, "u32": 4, "s64": 8, "u64": 8,
                  "f8e4m3": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1,
                  "f8e4m3fnuz": 1, "f8e5m2": 1, "f8e5m2fnuz": 1,
                  "f8e3m4": 1, "f8e8m0fnu": 1,
                  "bf16": 2, "f16": 2, "f32": 4, "f64": 8,
                  "c64": 8, "c128": 16}

# typed-shape tokens that are legitimately byte-free
_HLO_ZERO_BYTE_TYPES = frozenset({"token", "opaque"})


def hlo_shape_bytes(sh: str) -> int:
    """Total bytes of every typed array in one HLO shape string (tuple
    shapes sum their elements). Raises on a typed-shape token whose
    element type is not in HLO_ITEM_BYTES."""
    total = 0
    matched_any = False
    for m in re.finditer(r"([a-zA-Z][a-zA-Z0-9]*)\[([0-9,]*)\]", sh):
        matched_any = True
        dtype = m.group(1)
        if dtype in _HLO_ZERO_BYTE_TYPES:
            continue
        if dtype not in HLO_ITEM_BYTES:
            raise ValueError(
                f"hlo_shape_bytes: unrecognized element type {dtype!r} in "
                f"shape string {sh!r}; add it to HLO_ITEM_BYTES")
        n = 1
        for d in m.group(2).split(","):
            if d:
                n *= int(d)
        total += n * HLO_ITEM_BYTES[dtype]
    if not matched_any and "[" in sh:
        raise ValueError(
            f"hlo_shape_bytes: no typed shape recognized in {sh!r} "
            f"(dynamic dims or unexpected syntax?)")
    return total


def collective_census(hlo: str) -> Dict[str, list]:
    """{kind: [(output_bytes, line)]} for every collective instruction in a
    compiled (per-device) HLO module. Async pairs are counted once, at the
    -start; tuple-shaped outputs (all-to-all emits one operand per peer,
    with /*index=N*/ comments past 5 elements) sum their elements."""
    out: Dict[str, list] = {}
    for line in hlo.splitlines():
        # tuple shapes may nest one paren level INSIDE the tuple: TPU
        # layouts print as {1,0:T(8,128)} — [^()] alone would stop there
        # and silently drop the instruction from the census
        m = re.match(
            r"\s*(?:ROOT )?%?[\w.\-]+ = "
            r"(\((?:[^()]|\([^()]*\))*\)|\S+)\s+"
            r"(all-reduce|reduce-scatter|all-gather|collective-permute|"
            r"all-to-all)(-start|-done)?\(", line)
        if not m:
            continue
        if m.group(3) == "-done":
            continue
        kind = m.group(2)
        out.setdefault(kind, []).append((hlo_shape_bytes(m.group(1)), line))
    return out


# Per-device bytes each collective puts on the interconnect, as a function
# of its (per-device) OUTPUT bytes in the partitioned HLO — the standard
# ring-algorithm accounting, shared by the comm-structure tests and the
# benchmark's grad_bytes_on_wire field so both quote the same model:
#   all-reduce out=n:        ring RS+AG, sends 2n(N-1)/N
#   reduce-scatter out=c:    input N*c, sends c(N-1)
#   all-gather out=n:        contributes n/N, sends n(N-1)/N
#   all-to-all out total=t:  keeps its own chunk, sends t(N-1)/N
#   collective-permute out=n: sends n
def collective_wire_bytes(kind: str, out_bytes: int, n_devices: int) -> float:
    n = n_devices
    return {
        "all-reduce": 2.0 * out_bytes * (n - 1) / n,
        "reduce-scatter": float(out_bytes) * (n - 1),
        "all-gather": float(out_bytes) * (n - 1) / n,
        "all-to-all": float(out_bytes) * (n - 1) / n,
        "collective-permute": float(out_bytes),
    }[kind]


def reshard_wire_bytes(nbytes: int, old_factors, new_factors) -> float:
    """Per-device interconnect bytes of the CANONICAL mesh-resize
    redistribution of one array (parallel/reshard.py emits the matching
    schedule; elastic restore is its checkpoint-mediated form):

    - a dim whose new shard factor is a multiple of its current one
      refines by dynamic-slice — 0 wire;
    - every remaining incompatible dim all-gathers over its old group
      (ring accounting, `collective_wire_bytes`), output priced at the
      CURRENT factors of the other dims (refinement first — the
      memory-efficient ordering), then slices to the new factor.

    Closed-form twin of reshard.schedule_steps: the step-priced schedule
    and this prediction must agree exactly (pinned by test)."""
    cur = list(old_factors)
    new = list(new_factors)
    if len(cur) != len(new):
        raise ValueError(f"reshard_wire_bytes: factor ranks differ "
                         f"({len(cur)} vs {len(new)})")
    for d in range(len(cur)):
        if new[d] % max(cur[d], 1) == 0:
            cur[d] = new[d]
    total = 0.0
    for d in range(len(cur)):
        if cur[d] == new[d]:
            continue
        others = 1
        for d2 in range(len(cur)):
            if d2 != d:
                others *= cur[d2]
        out = nbytes // others
        total += collective_wire_bytes("all-gather", out, cur[d])
        cur[d] = new[d]
    return total


_HLO_COMP_HEAD = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\([^)]*\)\s*->.*\{\s*$")
_HLO_INSTR = re.compile(
    r"^\s*(ROOT\s+)?%?([\w.\-]+)\s+=\s+"
    r"(\((?:[^()]|\([^()]*\))*\)|\S+)\s+"
    r"([\w\-]+)\(")


def _parse_hlo_computations(hlo: str) -> Dict[str, list]:
    """{computation name: [(is_root, value name, shape str, opcode,
    referenced names)]} for every computation in an HLO text dump. The
    ENTRY computation is additionally indexed under \"ENTRY\"."""
    comps: Dict[str, list] = {}
    cur: Optional[list] = None
    for line in hlo.splitlines():
        if cur is None:
            m = _HLO_COMP_HEAD.match(line.strip())
            if m:
                cur = comps[m.group(1)] = []
                if line.lstrip().startswith("ENTRY"):
                    comps["ENTRY"] = cur
            continue
        if line.strip() == "}":
            cur = None
            continue
        m = _HLO_INSTR.match(line)
        if not m:
            continue
        # strip metadata={...} before collecting %refs: op_name strings
        # can quote anything
        body = line.split("metadata=", 1)[0]
        refs = re.findall(r"%([\w.\-]+)", body)
        cur.append((bool(m.group(1)), m.group(2), m.group(3),
                    m.group(4), refs[1:]))  # refs[0] is the def itself
    return comps


def hlo_liveness_temp_bytes(hlo: str) -> int:
    """Peak live TEMP bytes of a compiled HLO module from a liveness walk
    over its (scheduled) instruction sequences — the DOCUMENTED fallback
    for backends whose `CompiledMemoryStats.temp_size_in_bytes` reads 0
    (this container's jaxlib-0.4.x CPU backend reports it only for some
    programs). A value is live from its defining instruction to its last
    textual use; called computations (fusion/while/reduce `to_apply`,
    `body`, `condition`...) contribute their own peak while the calling
    instruction is live. Parameters are argument buffers (counted in
    `argument_size_in_bytes`) and roots are the caller's (or, for ENTRY,
    the output) buffer, so both are excluded. An ESTIMATE: real buffer
    assignment aliases compatible buffers, so this bounds the measured
    temp from above — it exists so the measured census never silently
    reads a 0 the backend merely declined to report, and the ledger's
    accounting identity only charges measured bytes that EXCEED the
    prediction (observability/ledger.py check_memory_identity)."""
    comps = _parse_hlo_computations(hlo)
    entry = comps.get("ENTRY")
    if not entry:
        return 0
    memo: Dict[int, int] = {}

    def comp_peak(instrs, is_entry, chain):
        key = id(instrs)
        if not is_entry and key in memo:
            return memo[key]
        if key in chain:
            return 0   # recursive call graph: bound the walk
        n = len(instrs)
        defs: Dict[str, int] = {}
        sizes: Dict[str, int] = {}
        called_at: Dict[int, int] = {}
        for i, (is_root, name, shape, opcode, refs) in enumerate(instrs):
            if opcode == "parameter" or is_root:
                continue
            defs[name] = i
            try:
                sizes[name] = hlo_shape_bytes(shape)
            except ValueError:
                sizes[name] = 0
        last_use = dict(defs)
        for i, (_, _, _, _, refs) in enumerate(instrs):
            for r in refs:
                if r in defs:
                    last_use[r] = max(last_use[r], i)
                elif r in comps:
                    called_at[i] = called_at.get(i, 0) + comp_peak(
                        comps[r], False, chain + (key,))
        alloc: Dict[int, int] = {}
        free: Dict[int, int] = {}
        for name, d in defs.items():
            alloc[d] = alloc.get(d, 0) + sizes[name]
            free[last_use[name] + 1] = (free.get(last_use[name] + 1, 0)
                                        + sizes[name])
        peak = live = 0
        for t in range(n):
            live += alloc.get(t, 0) - free.get(t, 0)
            peak = max(peak, live + called_at.get(t, 0))
        if not is_entry:
            memo[key] = peak
        return peak

    return comp_peak(entry, True, ())


def census_wire_bytes(census: Dict[str, list], n_devices: int,
                      min_bytes: int = 0) -> float:
    """Total per-device interconnect bytes for one step, from a
    collective_census; instructions with output below `min_bytes` can be
    excluded (scalar loss/metric reductions)."""
    total = 0.0
    for kind, items in census.items():
        for b, _ in items:
            if b >= min_bytes:
                total += collective_wire_bytes(kind, b, n_devices)
    return total


# ---------------------------------------------------------------------------
# analytic per-op cost model — the balancing signal for the pipeline
# partitioner (framework/passes.py pipeline_partition_pass) and the
# per-stage compute model of tools/probe_bubble.py. Costs are RELATIVE
# (batch dims unknown until feed time use `nominal_batch`).
# ---------------------------------------------------------------------------

# ops that are pure markers / bookkeeping: zero device cost
_ZERO_COST_OPS = frozenset({"pp_send", "pp_recv", "feed", "fetch"})

# per-output-element flop weights for transcendental-ish elementwise ops
_ELEMENTWISE_FLOPS = {"softmax": 5.0, "exp": 4.0, "log": 4.0, "tanh": 6.0,
                      "sigmoid": 5.0, "relu": 1.0, "sqrt": 4.0, "pow": 4.0,
                      "elementwise_pow": 4.0, "gelu": 8.0,
                      "layer_norm": 8.0, "batch_norm": 6.0,
                      "softmax_with_cross_entropy": 8.0,
                      "cross_entropy": 4.0, "dropout": 2.0}


def _var_numel(block, name, nominal_batch):
    try:
        v = block.var(name)
    except Exception:
        return 0
    shape = getattr(v, "shape", None) or ()
    n = 1
    for d in shape:
        n *= (nominal_batch if d == -1 else int(d))
    return n


def _var_shape(block, name, nominal_batch):
    try:
        v = block.var(name)
    except Exception:
        return None
    shape = getattr(v, "shape", None)
    if shape is None:
        return None
    return [nominal_batch if d == -1 else int(d) for d in shape]


def op_cost_flops_bytes(op, block, nominal_batch: int = 8) -> Tuple[float,
                                                                    float]:
    """(flops, bytes) estimate for one program op, from declared var shapes
    (-1 batch dims resolved to `nominal_batch` — the model only needs to be
    RELATIVELY right to balance contiguous stages)."""
    if op.type in _ZERO_COST_OPS:
        return 0.0, 0.0
    in_n = sum(_var_numel(block, n, nominal_batch)
               for n in op.input_names())
    out_n = sum(_var_numel(block, n, nominal_batch)
                for n in op.output_names())
    bytes_ = 4.0 * (in_n + out_n)
    t = op.type
    if t in ("mul", "matmul"):
        xs = _var_shape(block, op.inputs["X"][0], nominal_batch)
        k = 1.0
        if xs:
            k = float(xs[-2] if op.attrs.get("transpose_X") and len(xs) >= 2
                      else xs[-1])
        return 2.0 * out_n * k, bytes_
    if t in ("conv2d", "conv3d", "conv2d_transpose", "conv3d_transpose",
             "depthwise_conv2d"):
        # filter is [num_filters, cin/groups, k...] in both layouts, so
        # per-output-element work = 2 * numel(filter) / num_filters
        fn = _var_numel(block, op.inputs["Filter"][0], nominal_batch)
        fs = _var_shape(block, op.inputs["Filter"][0], nominal_batch)
        nf = float(fs[0]) if fs else 1.0
        return 2.0 * out_n * (fn / max(nf, 1.0)), bytes_
    if t in ("dynamic_lstm", "fused_lstm", "dynamic_gru", "fused_gru"):
        wn = sum(_var_numel(block, n, nominal_batch)
                 for slot in ("Weight", "WeightX", "WeightH")
                 for n in op.inputs.get(slot, []))
        return 2.0 * max(out_n, in_n) * max(wn, 1) ** 0.5, bytes_
    if t == "lookup_table":
        return float(out_n), bytes_
    return _ELEMENTWISE_FLOPS.get(t, 1.0) * out_n, bytes_


def op_time_cost(flops: float, bytes_: float) -> float:
    """Roofline combine of one op's (flops, bytes): seconds on the v5e
    peak — whichever engine bounds it."""
    return max(flops / V5E_PEAK_TFLOPS, bytes_ / V5E_HBM_BPS)


def program_flops_bytes(program, nominal_batch: int = 8) -> Dict:
    """Whole-program (block 0) analytic flops/bytes + roofline seconds —
    the per-op model summed, with the per-op roofline combine (so
    compute-bound and memory-bound ops each contribute their binding
    engine's time, the same combine the pipeline partitioner balances)."""
    block = program.global_block()
    flops = bytes_ = secs = 0.0
    for op in block.ops:
        f, b = op_cost_flops_bytes(op, block, nominal_batch)
        flops += f
        bytes_ += b
        secs += op_time_cost(f, b)
    return {"flops": flops, "bytes": bytes_,
            "roofline_s": secs, "n_ops": len(block.ops),
            "nominal_batch": nominal_batch}


def roofline_fields(step_s: float, flops: float, bytes_acc: float) -> Dict:
    """The shared attribution fields; None where the cost model gave 0."""
    out = {
        "step_ms": round(step_s * 1e3, 2),
        "bytes_GB": round(bytes_acc / 1e9, 2) if bytes_acc else None,
        "flops_G": round(flops / 1e9, 1) if flops else None,
        "intensity_flops_per_byte":
            round(flops / bytes_acc, 1) if flops and bytes_acc else None,
        "ideal_mxu_ms":
            round(flops / V5E_PEAK_TFLOPS * 1e3, 3) if flops else None,
        "ideal_hbm_ms":
            round(bytes_acc / V5E_HBM_BPS * 1e3, 3) if bytes_acc else None,
        "mfu": round(mfu(flops, step_s), 4) if flops else None,
    }
    return out


def mfu(flops: float, step_s: float,
        peak_flops: float = V5E_PEAK_TFLOPS) -> float:
    """Model-flops utilization: predicted step flops over measured step
    time, as a fraction of the hardware peak — the `ptpu_mfu` gauge and
    the benchmark row column (ROADMAP items 1 and 3(d) share this
    sensor)."""
    if not flops or step_s <= 0:
        return 0.0
    return flops / step_s / peak_flops


def state_category(v, name: str) -> str:
    """The ONE state-category classifier — the predicted walk
    (memory_categories) and the measured census
    (observability.memory.state_census) both call it, so the ledger's
    exact per-category checks can never fail from classifier drift.
    `v` may be None (an undeclared scope var): other_state."""
    if v is not None and (getattr(v, "dp_replica_state", False)
                          or name.startswith("dp_comm_err")):
        return "ef_residual"
    if v is not None and (getattr(v, "is_optimizer_state", False)
                          or getattr(v, "accumulator_of", None)):
        return "optimizer_state"
    if v is not None and getattr(v, "trainable", False):
        return "params"
    return "other_state"


# per-device byte prediction for one persistable var, from its declared
# shape + the rewrite markers that decide its placement (the static twin
# of ParallelExecutor._state_sharding)
def _state_per_device_bytes(v, dp: int, tp: int,
                            nominal_batch: int) -> int:
    shape = [nominal_batch if d == -1 else int(d) for d in (v.shape or ())]
    if tp > 1 and getattr(v, "tp_spec", None):
        from .sharding import tp_local_shape
        shape = list(tp_local_shape(shape, v.tp_spec, tp))
    import jax
    import numpy as np
    # canonical dtype: resident state narrows int64/f64 under jax's
    # default config, and the measured census counts resident bytes
    n = int(np.dtype(jax.dtypes.canonicalize_dtype(np.dtype(v.dtype))
                     ).itemsize)
    for d in shape:
        n *= d
    if dp > 1 and (getattr(v, "dp_shard_update", False)
                   or getattr(v, "dp_replica_state", False)):
        n //= dp
    return n


def memory_categories(program, *, dp: int = 1, tp: int = 0,
                      nominal_batch: int = 8) -> Dict:
    """Predicted PER-DEVICE memory by category for one (rewritten)
    program — the prediction side of the memory ledger's accounting
    identity (observability/ledger.py check_memory_identity):

      params           trainable persistable state (replicated; tp-local
                       when the tp pass marked a `tp_spec`)
      optimizer_state  accumulators (`is_optimizer_state`/`accumulator_of`);
                       dim 0 / dp when `dp_shard_update` (ZeRO-1)
      ef_residual      per-replica error-feedback state
                       (`dp_replica_state`, declared [dp, n] over dp)
      other_state      remaining persistables (counters, caches)
      feeds            declared data vars: batch-led ([-1, ...]) rows
                       split over dp, fixed-shape aux feeds replicated —
                       the manual-mode placement rule. Undeclared sidecar
                       feeds (`@SEQLEN`) cannot be predicted statically;
                       they surface in the ledger's named residual bucket
      seed             the step's uint32 RNG seed (4 bytes)
      transient_peak   static peak-live estimate at the per-device batch
                       (analysis.peak_live_bytes at nominal_batch // dp)

    Placement rules mirror ParallelExecutor._state_sharding exactly; the
    SPMD Reduce heuristic (un-marked accumulator sharding) is NOT
    modeled — predict for the manual/explicit modes or dp=1."""
    cats = {"params": 0, "optimizer_state": 0, "ef_residual": 0,
            "other_state": 0, "feeds": 0, "seed": 4}
    if tp <= 1 and getattr(program, "_tp_applied", False):
        tp = int(getattr(program, "_tp_size", 0) or 0)
    seen = set()
    for b in program.blocks:
        for name, v in b.vars.items():
            if name in seen:
                continue
            seen.add(name)
            if v.persistable:
                nb = _state_per_device_bytes(v, dp, tp, nominal_batch)
                cats[state_category(v, name)] += nb
            elif getattr(v, "is_data", False):
                shape = list(v.shape or ())
                # canonical dtype: the device buffer narrows int64/f64
                # feeds under jax's default config, and the measured side
                # (memory.device_memory_census) counts what is resident
                import jax
                import numpy as np
                nb = int(np.dtype(
                    jax.dtypes.canonicalize_dtype(np.dtype(v.dtype))
                ).itemsize)
                for d in shape:
                    nb *= (nominal_batch if d == -1 else int(d))
                if shape and shape[0] == -1 and dp > 1:
                    nb //= dp
                cats["feeds"] += nb
    local_batch = max(1, nominal_batch // max(dp, 1))
    from .analysis import peak_live_bytes
    cats["transient_peak"] = int(peak_live_bytes(
        program, nominal_batch=local_batch)["peak_transient_bytes"])
    # the QUANTIZED gradient pipeline's working set is internal to the
    # dp_grad_comm lowering (quantize -> all_to_all -> f32 dequant-sum
    # -> quantized all_gather, parallel/collective.py) and invisible to
    # the program-level lifetime walk; the f32 dequant buffer dominates
    # at ~= the flat gradient bytes. Named separately so the ledger
    # artifact shows what was added and why.
    comm_ws = 0
    for b in program.blocks:
        for op in b.ops:
            if op.type != "dp_grad_comm" or not op.attrs.get("quant"):
                continue
            for name in op.input_names():
                v = None
                for b2 in program.blocks:
                    if b2.has_var(name):
                        v = b2.var(name)
                        break
                if v is None or v.shape is None:
                    continue
                nb = 4
                for d in v.shape:
                    nb *= (local_batch if d == -1 else int(d))
                comm_ws += nb
    cats["dp_comm_working_set"] = comm_ws
    cats["transient_peak"] += comm_ws
    # the PIPELINE region's executed working set is schedule state the
    # lifetime walk cannot see either (peak_live_bytes explicitly defers
    # it to the pipeline stash census): the activation + gradient stash
    # buffers at their census depths (one boundary buffer per in-flight
    # microbatch), and the per-stage gradient accumulator plus its
    # update copy (the scan carry's new-value buffer co-resides with
    # the old one while the backward adds into it).
    pp_ws = 0
    if getattr(program, "_pp_applied", False):
        region = next((op for op in program.global_block().ops
                       if op.type == "pp_pipeline_region"), None)
        if region is not None:
            from ..parallel.pipeline import (pp_boundary_wire_bytes,
                                             schedule_census)
            m = int(region.attrs["num_microbatches"])
            k = int(region.attrs["num_stages"])
            sched = schedule_census(region.attrs["schedule"], m, k)
            mb_rows = max(1, nominal_batch // max(1, dp * m))
            wire = pp_boundary_wire_bytes(program, mb_rows)
            boundary = (int(wire["buffer_numel"]) * 4) if wire else 0
            grad_bytes = 0
            for b in program.blocks:
                for v in b.vars.values():
                    if not (getattr(v, "trainable", False)
                            and v.persistable):
                        continue
                    shape = list(v.shape or ())
                    if tp > 1 and getattr(v, "tp_spec", None):
                        from .sharding import tp_local_shape
                        shape = list(tp_local_shape(shape, v.tp_spec, tp))
                    nb = 4
                    for d in shape:
                        nb *= d
                    grad_bytes += nb
            pp_ws = (boundary * (int(sched["act_stash_depth"])
                                 + int(sched["grad_stash_depth"]))
                     + 2 * grad_bytes)
    cats["pp_working_set"] = pp_ws
    cats["transient_peak"] += pp_ws
    cats["dp"] = dp
    cats["tp"] = tp
    cats["nominal_batch"] = nominal_batch
    return cats


# ---------------------------------------------------------------------------
# predict(): one call joining every analytic model for a (possibly
# rewrite-passed) program — the ledger's prediction side and the planner's
# objective function
# ---------------------------------------------------------------------------


def predict(program, strategy=None, *, dp: int = 1, tp: int = 0,
            nominal_batch: int = 8) -> Dict:
    """Joined analytic cost prediction for one program.

    `program` should be the program the executor will actually run — for
    the manual modes that is the REWRITTEN program
    (`ParallelExecutor._prepare_program(prog, scope)`), whose markers
    (`_dp_comm_applied`, `_pp_applied`, `_tp_applied`) select which wire
    models apply. `strategy` (a BuildStrategy) is only consulted for
    documentation fields; every byte/bubble number comes from the program
    itself so prediction and execution cannot drift.

    Returns a CostReport dict with sections:
      compute:   program_flops_bytes (flop/byte roofline)
      dp_comm:   grad_comm.analytic_wire_bytes (explicit pipeline) or
                 spmd_allreduce_wire_bytes (SPMD), when dp > 1
      tp_comm:   sharding.tp_analytic_wire_bytes, when the tp pass ran
      pipeline:  schedule_census bubble/stash model +
                 pp_boundary_wire_bytes, when the pp pass ran
      memory:    analysis.peak_live_bytes
    Sections that don't apply are None — a ledger row records that the
    model was consulted and judged inapplicable, not silently skipped.
    """
    from ..parallel import grad_comm as _gc
    from . import analysis as _analysis
    from . import sharding as _sharding

    report: Dict = {
        "nominal_batch": nominal_batch,
        "dp": dp,
        "compute": program_flops_bytes(program, nominal_batch),
        "dp_comm": None,
        "tp_comm": None,
        "pipeline": None,
        "memory": {
            **_analysis.peak_live_bytes(program,
                                        nominal_batch=nominal_batch),
            # the MEASURED counterpart's attribution target: per-device
            # state/feed/transient bytes by category
            # (ledger.check_memory_identity reconciles a
            # device_memory_census against exactly these buckets)
            "per_device": memory_categories(program, dp=dp, tp=tp,
                                            nominal_batch=nominal_batch),
        },
    }
    if getattr(program, "_memory_plan_applied", False):
        # the static memory plan's decision record rides the prediction:
        # the ledger's conservative transient estimate stays UNPLANNED
        # (so a planned cell's measured reduction surfaces in the NAMED
        # unrealized:transient_peak bucket, never the residual), and this
        # section says what the plan predicted it bought and how
        plan = dict(getattr(program, "_memory_plan_report", {}) or {})
        report["memory"]["plan"] = {
            "predicted_peak_before": plan.get("predicted_peak_before"),
            "predicted_peak_after": plan.get("predicted_peak_after"),
            "predicted_reduction_bytes":
                plan.get("predicted_reduction_bytes"),
            "n_slots": plan.get("n_slots"),
            "shared_vars": plan.get("shared_vars"),
            "remat": plan.get("remat"),
            "pp_stages": plan.get("pp_stages"),
            "schedule": plan.get("schedule"),
        }
    if dp > 1:
        report["dp_comm"] = (_gc.analytic_wire_bytes(program, dp)
                             or _gc.spmd_allreduce_wire_bytes(program, dp))
        report["dp_comm"]["explicit"] = bool(
            getattr(program, "_dp_comm_applied", False))
    if getattr(program, "_tp_applied", False):
        tpn = tp or int(getattr(program, "_tp_size", 0) or 0)
        if tpn > 1:
            report["tp_comm"] = _sharding.tp_analytic_wire_bytes(
                program, tpn, nominal_batch=nominal_batch)
    if getattr(program, "_pp_applied", False):
        from ..parallel.pipeline import (pp_boundary_wire_bytes,
                                         schedule_census)
        region = next((op for op in program.global_block().ops
                       if op.type == "pp_pipeline_region"), None)
        if region is not None:
            m = int(region.attrs["num_microbatches"])
            k = int(region.attrs["num_stages"])
            sched = schedule_census(region.attrs["schedule"], m, k)
            mb_rows = max(1, nominal_batch // max(1, dp * m))
            wire = pp_boundary_wire_bytes(program, mb_rows)
            report["pipeline"] = {**sched,
                                  "boundary": wire,
                                  "microbatch_rows": mb_rows,
                                  "grad_psum_wire_bytes":
                                      _pp_grad_psum_bytes(program, k)}
    if strategy is not None:
        report["strategy"] = {
            "reduce_strategy": str(getattr(strategy, "reduce_strategy", "")),
            "quant_comm": getattr(strategy, "quant_comm", ""),
            "pipeline_stages": getattr(strategy, "pipeline_stages", 0),
            "num_microbatches": getattr(strategy, "num_microbatches", 0),
            "pipeline_schedule": getattr(strategy, "pipeline_schedule", ""),
        }
    return report


def _pp_grad_psum_bytes(program, k: int) -> int:
    """Per-device wire bytes of the pipeline region's ONE gradient psum
    over the pp axis (run_pp_region: grads accumulate per stage, one
    psum over pp replicates them for the optimizer) — an all-reduce of
    every trainable gradient, ring 2n(K-1)/K. Grads live at tp-LOCAL
    shapes when the tp pass rewrote the program."""
    tp = int(getattr(program, "_tp_size", 0) or 0) \
        if getattr(program, "_tp_applied", False) else 0
    total = 0.0
    for b in program.blocks:
        for v in b.vars.values():
            if not (getattr(v, "trainable", False) and v.persistable):
                continue
            shape = list(v.shape or ())
            if tp > 1 and getattr(v, "tp_spec", None):
                from .sharding import tp_local_shape
                shape = list(tp_local_shape(shape, v.tp_spec, tp))
            n = 4
            for d in shape:
                n *= d
            total += 2.0 * n * (k - 1) / k
    return int(total)


def predicted_wire_bytes(report: Dict) -> float:
    """Predicted per-device wire bytes per step on the ONCE-PER-STEP
    collectives (dp gradient pipeline + tp collectives) — the number the
    ledger reconciles EXACTLY with the HLO census. The pipeline's
    boundary collective-permutes are deliberately excluded: they execute
    2(M+K-1) times inside the tick scan but appear once in the static
    HLO, so they are reconciled structurally instead
    (ledger.check_pp_boundary: instruction count == 2, per-instruction
    bytes == the predicted cut buffer)."""
    total = 0.0
    if report.get("dp_comm"):
        total += report["dp_comm"].get("wire_bytes", 0)
    if report.get("tp_comm"):
        total += report["tp_comm"].get("tp_wire_bytes", 0)
    pipe = report.get("pipeline")
    if pipe:
        total += pipe.get("grad_psum_wire_bytes", 0)
    return total
