"""Analytic cost models as a first-class framework API.

Five generations of probes each carried a private copy of some slice of
this: the flop/byte roofline (tools/probe_common, r03+), the collective
wire-byte ring model (r08), the pipeline bubble model (r09), the static
peak-live-bytes estimator (r10), and the tp collective model (r11). This
module is now the ONE home: `tools/probe_common` re-exports from here (so
the r08/r09/r11 exact-census test assertions flow through this API
unchanged), `framework/passes.py` balances pipeline stages with it, and
`predict(program, ...)` joins every model into a single CostReport — the
queryable substrate the auto-parallel planner (ROADMAP item 2) searches
over and `observability/ledger.py` reconciles against measured traces.

Accounting disciplines (unchanged from the probes they came from):

- per-op (flops, bytes) from declared var shapes, -1 batch dims resolved
  to `nominal_batch`; roofline combine max(flops/peak, bytes/bw) at the
  v5e constants;
- per-device interconnect bytes per collective from its (per-device)
  OUTPUT bytes in the partitioned HLO — standard ring-algorithm costs;
- pipeline bubbles from the executed schedule tables, not the closed
  form (they agree exactly: (K-1)/(M+K-1));
- peak live bytes from variable lifetimes (first writer .. last reader).
"""

from __future__ import annotations

import re
from typing import Dict, Optional, Tuple

# ---------------------------------------------------------------------------
# hardware constants (v5e) — the probes, the pipeline partitioner, and the
# benchmark roofline fields all quote the same peaks so one number means
# one thing everywhere
# ---------------------------------------------------------------------------

V5E_PEAK_TFLOPS = 197e12
V5E_HBM_BPS = 819e9
# per-chip HBM capacity and per-device ICI (inter-chip interconnect)
# bandwidth — the auto-parallel planner's budget and wire-time constants
# (framework/auto_parallel.py). 45 GB/s is the one-direction per-link v5e
# figure the ring models' per-device byte counts divide through.
V5E_HBM_BYTES = 16 * (1 << 30)
V5E_ICI_BPS = 45e9
# host<->device PCIe bandwidth the offload roofline divides through
# (framework/offload.py consumers: ZeRO-offload optimizer state, the
# memory planner's stash-to-host candidate). v5e chips sit on PCIe
# gen4 x16 (~32 GB/s one direction); like the constants above this is a
# RELATIVE ranking figure, not a wall-clock forecast.
V5E_PCIE_BPS = 32e9

# dtype byte widths for parsing XLA shape strings — the ONE copy shared by
# the probes (probe_caps) and the comm-structure tests. Covers every XLA
# scalar type that can appear in a typed shape (ADVICE r5 #4); an
# unrecognized typed-shape token RAISES instead of silently counting 0
# bytes (which would let byte-balance assertions pass/fail misleadingly
# if dtypes drift).
HLO_ITEM_BYTES = {"pred": 1,
                  "s2": 1, "u2": 1, "s4": 1, "u4": 1,     # sub-byte types
                  "s8": 1, "u8": 1, "s16": 2, "u16": 2,   # pack >= 1 byte
                  "s32": 4, "u32": 4, "s64": 8, "u64": 8,
                  "f8e4m3": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1,
                  "f8e4m3fnuz": 1, "f8e5m2": 1, "f8e5m2fnuz": 1,
                  "f8e3m4": 1, "f8e8m0fnu": 1,
                  "bf16": 2, "f16": 2, "f32": 4, "f64": 8,
                  "c64": 8, "c128": 16}

# typed-shape tokens that are legitimately byte-free
_HLO_ZERO_BYTE_TYPES = frozenset({"token", "opaque"})


def hlo_shape_bytes(sh: str) -> int:
    """Total bytes of every typed array in one HLO shape string (tuple
    shapes sum their elements). Raises on a typed-shape token whose
    element type is not in HLO_ITEM_BYTES."""
    total = 0
    matched_any = False
    for m in re.finditer(r"([a-zA-Z][a-zA-Z0-9]*)\[([0-9,]*)\]", sh):
        matched_any = True
        dtype = m.group(1)
        if dtype in _HLO_ZERO_BYTE_TYPES:
            continue
        if dtype not in HLO_ITEM_BYTES:
            raise ValueError(
                f"hlo_shape_bytes: unrecognized element type {dtype!r} in "
                f"shape string {sh!r}; add it to HLO_ITEM_BYTES")
        n = 1
        for d in m.group(2).split(","):
            if d:
                n *= int(d)
        total += n * HLO_ITEM_BYTES[dtype]
    if not matched_any and "[" in sh:
        raise ValueError(
            f"hlo_shape_bytes: no typed shape recognized in {sh!r} "
            f"(dynamic dims or unexpected syntax?)")
    return total


def collective_census(hlo: str) -> Dict[str, list]:
    """{kind: [(output_bytes, line)]} for every collective instruction in a
    compiled (per-device) HLO module. Async pairs are counted once, at the
    -start; tuple-shaped outputs (all-to-all emits one operand per peer,
    with /*index=N*/ comments past 5 elements) sum their elements."""
    out: Dict[str, list] = {}
    for line in hlo.splitlines():
        # tuple shapes may nest one paren level INSIDE the tuple: TPU
        # layouts print as {1,0:T(8,128)} — [^()] alone would stop there
        # and silently drop the instruction from the census
        m = re.match(
            r"\s*(?:ROOT )?%?[\w.\-]+ = "
            r"(\((?:[^()]|\([^()]*\))*\)|\S+)\s+"
            r"(all-reduce|reduce-scatter|all-gather|collective-permute|"
            r"all-to-all)(-start|-done)?\(", line)
        if not m:
            continue
        if m.group(3) == "-done":
            continue
        kind = m.group(2)
        out.setdefault(kind, []).append((hlo_shape_bytes(m.group(1)), line))
    return out


# Per-device bytes each collective puts on the interconnect, as a function
# of its (per-device) OUTPUT bytes in the partitioned HLO — the standard
# ring-algorithm accounting, shared by the comm-structure tests and the
# benchmark's grad_bytes_on_wire field so both quote the same model:
#   all-reduce out=n:        ring RS+AG, sends 2n(N-1)/N
#   reduce-scatter out=c:    input N*c, sends c(N-1)
#   all-gather out=n:        contributes n/N, sends n(N-1)/N
#   all-to-all out total=t:  keeps its own chunk, sends t(N-1)/N
#   collective-permute out=n: sends n
def collective_wire_bytes(kind: str, out_bytes: int, n_devices: int) -> float:
    n = n_devices
    return {
        "all-reduce": 2.0 * out_bytes * (n - 1) / n,
        "reduce-scatter": float(out_bytes) * (n - 1),
        "all-gather": float(out_bytes) * (n - 1) / n,
        "all-to-all": float(out_bytes) * (n - 1) / n,
        "collective-permute": float(out_bytes),
    }[kind]


def reshard_wire_bytes(nbytes: int, old_factors, new_factors) -> float:
    """Per-device interconnect bytes of the CANONICAL mesh-resize
    redistribution of one array (parallel/reshard.py emits the matching
    schedule; elastic restore is its checkpoint-mediated form):

    - a dim whose new shard factor is a multiple of its current one
      refines by dynamic-slice — 0 wire;
    - every remaining incompatible dim all-gathers over its old group
      (ring accounting, `collective_wire_bytes`), output priced at the
      CURRENT factors of the other dims (refinement first — the
      memory-efficient ordering), then slices to the new factor.

    Closed-form twin of reshard.schedule_steps: the step-priced schedule
    and this prediction must agree exactly (pinned by test)."""
    cur = list(old_factors)
    new = list(new_factors)
    if len(cur) != len(new):
        raise ValueError(f"reshard_wire_bytes: factor ranks differ "
                         f"({len(cur)} vs {len(new)})")
    for d in range(len(cur)):
        if new[d] % max(cur[d], 1) == 0:
            cur[d] = new[d]
    total = 0.0
    for d in range(len(cur)):
        if cur[d] == new[d]:
            continue
        others = 1
        for d2 in range(len(cur)):
            if d2 != d:
                others *= cur[d2]
        out = nbytes // others
        total += collective_wire_bytes("all-gather", out, cur[d])
        cur[d] = new[d]
    return total


_HLO_COMP_HEAD = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\([^)]*\)\s*->.*\{\s*$")
_HLO_INSTR = re.compile(
    r"^\s*(ROOT\s+)?%?([\w.\-]+)\s+=\s+"
    r"(\((?:[^()]|\([^()]*\))*\)|\S+)\s+"
    r"([\w\-]+)\(")


def _parse_hlo_computations(hlo: str) -> Dict[str, list]:
    """{computation name: [(is_root, value name, shape str, opcode,
    referenced names)]} for every computation in an HLO text dump. The
    ENTRY computation is additionally indexed under \"ENTRY\"."""
    comps: Dict[str, list] = {}
    cur: Optional[list] = None
    for line in hlo.splitlines():
        if cur is None:
            m = _HLO_COMP_HEAD.match(line.strip())
            if m:
                cur = comps[m.group(1)] = []
                if line.lstrip().startswith("ENTRY"):
                    comps["ENTRY"] = cur
            continue
        if line.strip() == "}":
            cur = None
            continue
        m = _HLO_INSTR.match(line)
        if not m:
            continue
        # strip metadata={...} before collecting %refs: op_name strings
        # can quote anything
        body = line.split("metadata=", 1)[0]
        refs = re.findall(r"%([\w.\-]+)", body)
        cur.append((bool(m.group(1)), m.group(2), m.group(3),
                    m.group(4), refs[1:]))  # refs[0] is the def itself
    return comps


def hlo_liveness_temp_bytes(hlo: str) -> int:
    """Peak live TEMP bytes of a compiled HLO module from a liveness walk
    over its (scheduled) instruction sequences — the DOCUMENTED fallback
    for backends whose `CompiledMemoryStats.temp_size_in_bytes` reads 0
    (this container's jaxlib-0.4.x CPU backend reports it only for some
    programs). A value is live from its defining instruction to its last
    textual use; called computations (fusion/while/reduce `to_apply`,
    `body`, `condition`...) contribute their own peak while the calling
    instruction is live. Parameters are argument buffers (counted in
    `argument_size_in_bytes`) and roots are the caller's (or, for ENTRY,
    the output) buffer, so both are excluded. An ESTIMATE: real buffer
    assignment aliases compatible buffers, so this bounds the measured
    temp from above — it exists so the measured census never silently
    reads a 0 the backend merely declined to report, and the ledger's
    accounting identity only charges measured bytes that EXCEED the
    prediction (observability/ledger.py check_memory_identity)."""
    comps = _parse_hlo_computations(hlo)
    entry = comps.get("ENTRY")
    if not entry:
        return 0
    memo: Dict[int, int] = {}

    def comp_peak(instrs, is_entry, chain):
        key = id(instrs)
        if not is_entry and key in memo:
            return memo[key]
        if key in chain:
            return 0   # recursive call graph: bound the walk
        n = len(instrs)
        defs: Dict[str, int] = {}
        sizes: Dict[str, int] = {}
        called_at: Dict[int, int] = {}
        for i, (is_root, name, shape, opcode, refs) in enumerate(instrs):
            if opcode == "parameter" or is_root:
                continue
            defs[name] = i
            try:
                sizes[name] = hlo_shape_bytes(shape)
            except ValueError:
                sizes[name] = 0
        last_use = dict(defs)
        for i, (_, _, _, _, refs) in enumerate(instrs):
            for r in refs:
                if r in defs:
                    last_use[r] = max(last_use[r], i)
                elif r in comps:
                    called_at[i] = called_at.get(i, 0) + comp_peak(
                        comps[r], False, chain + (key,))
        alloc: Dict[int, int] = {}
        free: Dict[int, int] = {}
        for name, d in defs.items():
            alloc[d] = alloc.get(d, 0) + sizes[name]
            free[last_use[name] + 1] = (free.get(last_use[name] + 1, 0)
                                        + sizes[name])
        peak = live = 0
        for t in range(n):
            live += alloc.get(t, 0) - free.get(t, 0)
            peak = max(peak, live + called_at.get(t, 0))
        if not is_entry:
            memo[key] = peak
        return peak

    return comp_peak(entry, True, ())


def census_wire_bytes(census: Dict[str, list], n_devices: int,
                      min_bytes: int = 0) -> float:
    """Total per-device interconnect bytes for one step, from a
    collective_census; instructions with output below `min_bytes` can be
    excluded (scalar loss/metric reductions)."""
    total = 0.0
    for kind, items in census.items():
        for b, _ in items:
            if b >= min_bytes:
                total += collective_wire_bytes(kind, b, n_devices)
    return total


# ---------------------------------------------------------------------------
# analytic per-op cost model — the balancing signal for the pipeline
# partitioner (framework/passes.py pipeline_partition_pass) and the
# per-stage compute model of tools/probe_bubble.py. Costs are RELATIVE
# (batch dims unknown until feed time use `nominal_batch`).
# ---------------------------------------------------------------------------

# ops that are pure markers / bookkeeping: zero device cost
_ZERO_COST_OPS = frozenset({"pp_send", "pp_recv", "feed", "fetch"})

# per-output-element flop weights for transcendental-ish elementwise ops
_ELEMENTWISE_FLOPS = {"softmax": 5.0, "exp": 4.0, "log": 4.0, "tanh": 6.0,
                      "sigmoid": 5.0, "relu": 1.0, "sqrt": 4.0, "pow": 4.0,
                      "elementwise_pow": 4.0, "gelu": 8.0,
                      "layer_norm": 8.0, "batch_norm": 6.0,
                      "softmax_with_cross_entropy": 8.0,
                      "cross_entropy": 4.0, "dropout": 2.0}


def _var_numel(block, name, nominal_batch):
    try:
        v = block.var(name)
    except Exception:
        return 0
    shape = getattr(v, "shape", None) or ()
    n = 1
    for d in shape:
        n *= (nominal_batch if d == -1 else int(d))
    return n


def _var_shape(block, name, nominal_batch):
    try:
        v = block.var(name)
    except Exception:
        return None
    shape = getattr(v, "shape", None)
    if shape is None:
        return None
    return [nominal_batch if d == -1 else int(d) for d in shape]


def op_cost_flops_bytes(op, block, nominal_batch: int = 8) -> Tuple[float,
                                                                    float]:
    """(flops, bytes) estimate for one program op, from declared var shapes
    (-1 batch dims resolved to `nominal_batch` — the model only needs to be
    RELATIVELY right to balance contiguous stages)."""
    if op.type in _ZERO_COST_OPS:
        return 0.0, 0.0
    in_n = sum(_var_numel(block, n, nominal_batch)
               for n in op.input_names())
    out_n = sum(_var_numel(block, n, nominal_batch)
                for n in op.output_names())
    bytes_ = 4.0 * (in_n + out_n)
    t = op.type
    if t in ("mul", "matmul"):
        xs = _var_shape(block, op.inputs["X"][0], nominal_batch)
        k = 1.0
        if xs:
            k = float(xs[-2] if op.attrs.get("transpose_X") and len(xs) >= 2
                      else xs[-1])
        return 2.0 * out_n * k, bytes_
    if t in ("conv2d", "conv3d", "conv2d_transpose", "conv3d_transpose",
             "depthwise_conv2d"):
        # filter is [num_filters, cin/groups, k...] in both layouts, so
        # per-output-element work = 2 * numel(filter) / num_filters
        fn = _var_numel(block, op.inputs["Filter"][0], nominal_batch)
        fs = _var_shape(block, op.inputs["Filter"][0], nominal_batch)
        nf = float(fs[0]) if fs else 1.0
        return 2.0 * out_n * (fn / max(nf, 1.0)), bytes_
    if t in ("dynamic_lstm", "fused_lstm", "dynamic_gru", "fused_gru"):
        wn = sum(_var_numel(block, n, nominal_batch)
                 for slot in ("Weight", "WeightX", "WeightH")
                 for n in op.inputs.get(slot, []))
        return 2.0 * max(out_n, in_n) * max(wn, 1) ** 0.5, bytes_
    if t == "lookup_table":
        return float(out_n), bytes_
    return _ELEMENTWISE_FLOPS.get(t, 1.0) * out_n, bytes_


def op_time_cost(flops: float, bytes_: float) -> float:
    """Roofline combine of one op's (flops, bytes): seconds on the v5e
    peak — whichever engine bounds it."""
    return max(flops / V5E_PEAK_TFLOPS, bytes_ / V5E_HBM_BPS)


def program_flops_bytes(program, nominal_batch: int = 8) -> Dict:
    """Whole-program (block 0) analytic flops/bytes + roofline seconds —
    the per-op model summed, with the per-op roofline combine (so
    compute-bound and memory-bound ops each contribute their binding
    engine's time, the same combine the pipeline partitioner balances)."""
    block = program.global_block()
    flops = bytes_ = secs = 0.0
    for op in block.ops:
        f, b = op_cost_flops_bytes(op, block, nominal_batch)
        flops += f
        bytes_ += b
        secs += op_time_cost(f, b)
    return {"flops": flops, "bytes": bytes_,
            "roofline_s": secs, "n_ops": len(block.ops),
            "nominal_batch": nominal_batch}


def roofline_fields(step_s: float, flops: float, bytes_acc: float) -> Dict:
    """The shared attribution fields; None where the cost model gave 0."""
    out = {
        "step_ms": round(step_s * 1e3, 2),
        "bytes_GB": round(bytes_acc / 1e9, 2) if bytes_acc else None,
        "flops_G": round(flops / 1e9, 1) if flops else None,
        "intensity_flops_per_byte":
            round(flops / bytes_acc, 1) if flops and bytes_acc else None,
        "ideal_mxu_ms":
            round(flops / V5E_PEAK_TFLOPS * 1e3, 3) if flops else None,
        "ideal_hbm_ms":
            round(bytes_acc / V5E_HBM_BPS * 1e3, 3) if bytes_acc else None,
        "mfu": round(mfu(flops, step_s), 4) if flops else None,
    }
    return out


def mfu(flops: float, step_s: float,
        peak_flops: float = V5E_PEAK_TFLOPS) -> float:
    """Model-flops utilization: predicted step flops over measured step
    time, as a fraction of the hardware peak — the `ptpu_mfu` gauge and
    the benchmark row column (ROADMAP items 1 and 3(d) share this
    sensor)."""
    if not flops or step_s <= 0:
        return 0.0
    return flops / step_s / peak_flops


def state_category(v, name: str) -> str:
    """The ONE state-category classifier — the predicted walk
    (memory_categories) and the measured census
    (observability.memory.state_census) both call it, so the ledger's
    exact per-category checks can never fail from classifier drift.
    `v` may be None (an undeclared scope var): other_state."""
    if v is not None and (getattr(v, "dp_replica_state", False)
                          or name.startswith("dp_comm_err")):
        return "ef_residual"
    if v is not None and (getattr(v, "is_optimizer_state", False)
                          or getattr(v, "accumulator_of", None)):
        return "optimizer_state"
    if name.startswith("draft_") and (
            name.endswith("@qparam") or name.endswith("@qscale")
            or (v is not None and getattr(v, "trainable", False))):
        # speculative-decoding draft-model weights (serving/speculative.py
        # copies target weights under the reserved `draft_` prefix): their
        # own census category, so the target-weight claims (params /
        # params_quantized) stay unchanged when a draft rides along. The
        # prefix check precedes the suffix check — a quantized draft
        # weight `draft_*@qparam` is params_draft, not params_quantized
        return "params_draft"
    if name.endswith("@qparam") or name.endswith("@qscale"):
        # quantize_params_pass payload/scale pairs: classified by NAME
        # suffix (the pass's census contract) because Program.clone() only
        # preserves whitelisted extra var attrs
        return "params_quantized"
    if v is not None and getattr(v, "trainable", False):
        return "params"
    return "other_state"


# per-device byte prediction for one persistable var, from its declared
# shape + the rewrite markers that decide its placement (the static twin
# of ParallelExecutor._state_sharding)
def _state_per_device_bytes(v, dp: int, tp: int,
                            nominal_batch: int) -> int:
    shape = [nominal_batch if d == -1 else int(d) for d in (v.shape or ())]
    if tp > 1 and getattr(v, "tp_spec", None):
        from .sharding import tp_local_shape
        shape = list(tp_local_shape(shape, v.tp_spec, tp))
    import jax
    import numpy as np
    # canonical dtype: resident state narrows int64/f64 under jax's
    # default config, and the measured census counts resident bytes
    n = int(np.dtype(jax.dtypes.canonicalize_dtype(np.dtype(v.dtype))
                     ).itemsize)
    for d in shape:
        n *= d
    if dp > 1 and (getattr(v, "dp_shard_update", False)
                   or getattr(v, "dp_replica_state", False)):
        n //= dp
    return n


def memory_categories(program, *, dp: int = 1, tp: int = 0,
                      nominal_batch: int = 8) -> Dict:
    """Predicted PER-DEVICE memory by category for one (rewritten)
    program — the prediction side of the memory ledger's accounting
    identity (observability/ledger.py check_memory_identity):

      params           trainable persistable state (replicated; tp-local
                       when the tp pass marked a `tp_spec`)
      params_quantized block-scaled weight payload+scale pairs left by
                       quantize_params_pass (`@qparam`/`@qscale` suffix)
      params_draft     speculative-decoding draft-model weights (the
                       reserved `draft_` name prefix minted by
                       serving/speculative.py; quantized draft payloads
                       `draft_*@qparam` land here, not params_quantized)
      optimizer_state  accumulators (`is_optimizer_state`/`accumulator_of`);
                       dim 0 / dp when `dp_shard_update` (ZeRO-1)
      ef_residual      per-replica error-feedback state
                       (`dp_replica_state`, declared [dp, n] over dp)
      other_state      remaining persistables (counters, caches)
      feeds            declared data vars: batch-led ([-1, ...]) rows
                       split over dp, fixed-shape aux feeds replicated —
                       the manual-mode placement rule. Undeclared sidecar
                       feeds (`@SEQLEN`) cannot be predicted statically;
                       they surface in the ledger's named residual bucket
      seed             the step's uint32 RNG seed (4 bytes)
      transient_peak   static peak-live estimate at the per-device batch
                       (analysis.peak_live_bytes at nominal_batch // dp)

    Placement rules mirror ParallelExecutor._state_sharding exactly; the
    SPMD Reduce heuristic (un-marked accumulator sharding) is NOT
    modeled — predict for the manual/explicit modes or dp=1."""
    cats = {"params": 0, "params_quantized": 0, "params_draft": 0,
            "optimizer_state": 0, "ef_residual": 0, "other_state": 0,
            "feeds": 0, "seed": 4}
    if tp <= 1 and getattr(program, "_tp_applied", False):
        tp = int(getattr(program, "_tp_size", 0) or 0)
    seen = set()
    for b in program.blocks:
        for name, v in b.vars.items():
            if name in seen:
                continue
            seen.add(name)
            if v.persistable:
                nb = _state_per_device_bytes(v, dp, tp, nominal_batch)
                cats[state_category(v, name)] += nb
            elif getattr(v, "is_data", False):
                shape = list(v.shape or ())
                # canonical dtype: the device buffer narrows int64/f64
                # feeds under jax's default config, and the measured side
                # (memory.device_memory_census) counts what is resident
                import jax
                import numpy as np
                nb = int(np.dtype(
                    jax.dtypes.canonicalize_dtype(np.dtype(v.dtype))
                ).itemsize)
                for d in shape:
                    nb *= (nominal_batch if d == -1 else int(d))
                if shape and shape[0] == -1 and dp > 1:
                    nb //= dp
                cats["feeds"] += nb
    local_batch = max(1, nominal_batch // max(dp, 1))
    from .analysis import peak_live_bytes
    cats["transient_peak"] = int(peak_live_bytes(
        program, nominal_batch=local_batch)["peak_transient_bytes"])
    # the QUANTIZED gradient pipeline's working set is internal to the
    # dp_grad_comm lowering (quantize -> all_to_all -> f32 dequant-sum
    # -> quantized all_gather, parallel/collective.py) and invisible to
    # the program-level lifetime walk; the f32 dequant buffer dominates
    # at ~= the flat gradient bytes. Named separately so the ledger
    # artifact shows what was added and why.
    comm_ws = 0
    for b in program.blocks:
        for op in b.ops:
            if op.type != "dp_grad_comm" or not op.attrs.get("quant"):
                continue
            for name in op.input_names():
                v = None
                for b2 in program.blocks:
                    if b2.has_var(name):
                        v = b2.var(name)
                        break
                if v is None or v.shape is None:
                    continue
                nb = 4
                for d in v.shape:
                    nb *= (local_batch if d == -1 else int(d))
                comm_ws += nb
    cats["dp_comm_working_set"] = comm_ws
    cats["transient_peak"] += comm_ws
    # the PIPELINE region's executed working set is schedule state the
    # lifetime walk cannot see either (peak_live_bytes explicitly defers
    # it to the pipeline stash census): the activation + gradient stash
    # buffers at their census depths (one boundary buffer per in-flight
    # microbatch), and the per-stage gradient accumulator plus its
    # update copy (the scan carry's new-value buffer co-resides with
    # the old one while the backward adds into it).
    pp_ws = 0
    if getattr(program, "_pp_applied", False):
        region = next((op for op in program.global_block().ops
                       if op.type == "pp_pipeline_region"), None)
        if region is not None:
            from ..parallel.pipeline import (pp_boundary_wire_bytes,
                                             schedule_census)
            m = int(region.attrs["num_microbatches"])
            k = int(region.attrs["num_stages"])
            sched = schedule_census(region.attrs["schedule"], m, k)
            mb_rows = max(1, nominal_batch // max(1, dp * m))
            wire = pp_boundary_wire_bytes(program, mb_rows)
            boundary = (int(wire["buffer_numel"]) * 4) if wire else 0
            grad_bytes = 0
            for b in program.blocks:
                for v in b.vars.values():
                    if not (getattr(v, "trainable", False)
                            and v.persistable):
                        continue
                    shape = list(v.shape or ())
                    if tp > 1 and getattr(v, "tp_spec", None):
                        from .sharding import tp_local_shape
                        shape = list(tp_local_shape(shape, v.tp_spec, tp))
                    nb = 4
                    for d in shape:
                        nb *= d
                    grad_bytes += nb
            pp_ws = (boundary * (int(sched["act_stash_depth"])
                                 + int(sched["grad_stash_depth"]))
                     + 2 * grad_bytes)
    cats["pp_working_set"] = pp_ws
    cats["transient_peak"] += pp_ws
    cats["dp"] = dp
    cats["tp"] = tp
    cats["nominal_batch"] = nominal_batch
    return cats


# ---------------------------------------------------------------------------
# predict(): one call joining every analytic model for a (possibly
# rewrite-passed) program — the ledger's prediction side and the planner's
# objective function
# ---------------------------------------------------------------------------


def speculative_expectation(gamma: int, acceptance,
                            draft_cost_ratio: Optional[float] = None,
                            draft_layers: Optional[int] = None,
                            num_layers: Optional[int] = None,
                            draft_bits: int = 32,
                            verify_widening: float = 0.05) -> Dict:
    """Analytic expectation for speculative decoding (the `speculative`
    section of `predict`): expected committed tokens per round under
    per-token acceptance rate α is the truncated geometric sum
    (1-α^(γ+1))/(1-α) — every round commits at least one token (the
    target's own output) and at most γ+1 (full acceptance + bonus).

    `acceptance` is a probability OR a zero-arg callable returning one —
    the hook that feeds a MEASURED rate (e.g. a serving engine's
    `spec.acceptance_rate`) into the model, TVM-style like
    auto_parallel.plan's measure_fn. Costs are in PLAIN-TICK units: the
    draft tick ratio defaults to (draft_layers/num_layers)·(bits/32) —
    the memory-bound weight-read scaling of serving/speculative.py's
    truncated, quantized draft — and the verify forward pays a widening
    term per extra query position (the γ+1-wide window reads the same
    weights/KV once; only activation compute widens)."""
    from ..core.enforce import InvalidArgumentError, enforce
    a = float(acceptance() if callable(acceptance) else acceptance)
    enforce(0.0 <= a <= 1.0,
            f"acceptance must be a probability, got {a}",
            exc=InvalidArgumentError)
    g = int(gamma)
    enforce(g >= 1, "gamma must be >= 1", exc=InvalidArgumentError)
    expected = (g + 1.0 if a >= 1.0
                else (1.0 - a ** (g + 1)) / (1.0 - a))
    if draft_cost_ratio is None:
        lr = (float(draft_layers) / float(num_layers)
              if draft_layers and num_layers else 1.0)
        draft_cost_ratio = lr * (float(draft_bits) / 32.0)
    draft_cost = (g + 1) * float(draft_cost_ratio)
    verify_cost = 1.0 + float(verify_widening) * g
    round_cost = draft_cost + verify_cost
    return {
        "gamma": g,
        "acceptance": a,
        "expected_tokens_per_round": expected,
        # one target forward (the verify) per round: the amortization
        # headline tools/bench_spec.py measures
        "tokens_per_target_forward": expected,
        "draft_ticks_per_round": g + 1,
        "draft_cost_ratio": float(draft_cost_ratio),
        "draft_cost_ticks": draft_cost,
        "verify_widening": float(verify_widening),
        "verify_cost_ticks": verify_cost,
        "round_cost_ticks": round_cost,
        "speedup_vs_plain_decode": expected / round_cost,
    }


def predict(program, strategy=None, *, dp: int = 1, tp: int = 0,
            nominal_batch: int = 8,
            speculative: Optional[Dict] = None) -> Dict:
    """Joined analytic cost prediction for one program.

    `program` should be the program the executor will actually run — for
    the manual modes that is the REWRITTEN program
    (`ParallelExecutor._prepare_program(prog, scope)`), whose markers
    (`_dp_comm_applied`, `_pp_applied`, `_tp_applied`) select which wire
    models apply. `strategy` (a BuildStrategy) is only consulted for
    documentation fields; every byte/bubble number comes from the program
    itself so prediction and execution cannot drift.

    Returns a CostReport dict with sections:
      compute:   program_flops_bytes (flop/byte roofline)
      dp_comm:   grad_comm.analytic_wire_bytes (explicit pipeline) or
                 spmd_allreduce_wire_bytes (SPMD), when dp > 1
      tp_comm:   sharding.tp_analytic_wire_bytes, when the tp pass ran
      pipeline:  schedule_census bubble/stash model +
                 pp_boundary_wire_bytes, when the pp pass ran
      memory:    analysis.peak_live_bytes
      speculative: speculative_expectation(**speculative), when the
                 caller describes a speculative-decoding deployment
                 ({"gamma":, "acceptance":, ...} — acceptance may be a
                 callable reading a measured rate)
    Sections that don't apply are None — a ledger row records that the
    model was consulted and judged inapplicable, not silently skipped.
    """
    from ..parallel import grad_comm as _gc
    from . import analysis as _analysis
    from . import sharding as _sharding

    report: Dict = {
        "nominal_batch": nominal_batch,
        "dp": dp,
        "compute": program_flops_bytes(program, nominal_batch),
        "dp_comm": None,
        "tp_comm": None,
        "pipeline": None,
        "offload": None,
        "speculative": (speculative_expectation(**speculative)
                        if speculative else None),
        "memory": {
            **_analysis.peak_live_bytes(program,
                                        nominal_batch=nominal_batch),
            # the MEASURED counterpart's attribution target: per-device
            # state/feed/transient bytes by category
            # (ledger.check_memory_identity reconciles a
            # device_memory_census against exactly these buckets)
            "per_device": memory_categories(program, dp=dp, tp=tp,
                                            nominal_batch=nominal_batch),
        },
    }
    if getattr(program, "_memory_plan_applied", False):
        # the static memory plan's decision record rides the prediction:
        # the ledger's conservative transient estimate stays UNPLANNED
        # (so a planned cell's measured reduction surfaces in the NAMED
        # unrealized:transient_peak bucket, never the residual), and this
        # section says what the plan predicted it bought and how
        plan = dict(getattr(program, "_memory_plan_report", {}) or {})
        report["memory"]["plan"] = {
            "predicted_peak_before": plan.get("predicted_peak_before"),
            "predicted_peak_after": plan.get("predicted_peak_after"),
            "predicted_reduction_bytes":
                plan.get("predicted_reduction_bytes"),
            "n_slots": plan.get("n_slots"),
            "shared_vars": plan.get("shared_vars"),
            "remat": plan.get("remat"),
            "pp_stages": plan.get("pp_stages"),
            "schedule": plan.get("schedule"),
        }
        if strategy is not None and getattr(strategy, "memory_plan", False):
            # PLAN-AWARE memory pricing (the auto-parallel planner's
            # view): the ledger's conservative estimates above stay
            # UNPLANNED on purpose — a planned cell's measured reduction
            # must keep landing in the NAMED unrealized:transient_peak
            # bucket, so the identity checks never change — and the
            # planned expectation rides in NEW keys instead. The plan's
            # peak_before/after ratio is scale-invariant, so it applies
            # to the per-device transient (priced at the local batch)
            # as well as the whole-program peak; the dp-comm/pipeline
            # working sets are schedule state the plan cannot touch.
            before = float(plan.get("predicted_peak_before") or 0)
            after = float(plan.get("predicted_peak_after") or 0)
            if before > 0:
                frac = min(max(after / before, 0.0), 1.0)
                per_dev = report["memory"]["per_device"]
                fixed_ws = (per_dev.get("dp_comm_working_set", 0)
                            + per_dev.get("pp_working_set", 0))
                base = max(0, per_dev["transient_peak"] - fixed_ws)
                per_dev["transient_peak_planned"] = int(base * frac
                                                        + fixed_ws)
                mem = report["memory"]
                mem["planned_peak_total_bytes"] = int(
                    mem["persistent_bytes"] + mem["feed_bytes"]
                    + mem["peak_transient_bytes"] * frac)
    if dp > 1:
        spmd_model = _gc.spmd_allreduce_wire_bytes
        try:
            from ..parallel.strategy import ReduceStrategy
            if (strategy is not None
                    and getattr(strategy, "reduce_strategy", None)
                    == ReduceStrategy.Reduce):
                # the ZeRO-1 SPMD mode costs MORE wire than plain
                # allreduce on this backend (grad allreduce + sharded-
                # update param all-gather, census-measured); an
                # allreduce-priced Reduce point would win planner
                # comparisons unfairly
                spmd_model = _gc.spmd_zero1_wire_bytes
        except Exception:
            pass
        report["dp_comm"] = (_gc.analytic_wire_bytes(program, dp)
                             or spmd_model(program, dp))
        report["dp_comm"]["explicit"] = bool(
            getattr(program, "_dp_comm_applied", False))
    if getattr(program, "_tp_applied", False):
        tpn = tp or int(getattr(program, "_tp_size", 0) or 0)
        if tpn > 1:
            report["tp_comm"] = _sharding.tp_analytic_wire_bytes(
                program, tpn, nominal_batch=nominal_batch)
    if getattr(program, "_pp_applied", False):
        from ..parallel.pipeline import (pp_boundary_wire_bytes,
                                         schedule_census)
        region = next((op for op in program.global_block().ops
                       if op.type == "pp_pipeline_region"), None)
        if region is not None:
            m = int(region.attrs["num_microbatches"])
            k = int(region.attrs["num_stages"])
            sched = schedule_census(region.attrs["schedule"], m, k)
            mb_rows = max(1, nominal_batch // max(1, dp * m))
            wire = pp_boundary_wire_bytes(program, mb_rows)
            report["pipeline"] = {**sched,
                                  "boundary": wire,
                                  "microbatch_rows": mb_rows,
                                  "grad_psum_wire_bytes":
                                      _pp_grad_psum_bytes(program, k)}
    if strategy is not None and getattr(strategy, "offload_optimizer_state",
                                        False):
        # host-offload pricing (framework/offload.py): the optimizer
        # state's per-step PCIe round-trip (restore h2d before the step,
        # spill d2h after) against the step's compute window. HBM keeps
        # only ~one in-flight transfer bucket resident; the rest moves
        # to the host tier. `hides` is the planner's verdict — when the
        # round-trip exceeds the per-device compute window the residual
        # is CHARGED to predicted_step_seconds, so an offload point that
        # cannot overlap loses the search instead of lying about it.
        per_dev = report["memory"]["per_device"]
        opt_bytes = int(per_dev.get("optimizer_state", 0))
        bucket = int(getattr(strategy, "comm_bucket_bytes", 0) or 0)
        resident = min(opt_bytes, bucket) if bucket else opt_bytes
        pcie_s = 2.0 * opt_bytes / V5E_PCIE_BPS
        window = report["compute"]["roofline_s"] / max(dp, 1)
        report["offload"] = {
            "optimizer_state_bytes": opt_bytes,
            "resident_bytes": resident,
            "hbm_freed_bytes": max(0, opt_bytes - resident),
            "pcie_bps": V5E_PCIE_BPS,
            "pcie_roundtrip_s": pcie_s,
            "overlap_window_s": window,
            "residual_s": max(0.0, pcie_s - window),
            "hides": pcie_s <= window,
        }
    if strategy is not None:
        report["strategy"] = {
            "reduce_strategy": str(getattr(strategy, "reduce_strategy", "")),
            "quant_comm": getattr(strategy, "quant_comm", ""),
            "pipeline_stages": getattr(strategy, "pipeline_stages", 0),
            "num_microbatches": getattr(strategy, "num_microbatches", 0),
            "pipeline_schedule": getattr(strategy, "pipeline_schedule", ""),
        }
    return report


def _pp_grad_psum_bytes(program, k: int) -> int:
    """Per-device wire bytes of the pipeline region's ONE gradient psum
    over the pp axis (run_pp_region: grads accumulate per stage, one
    psum over pp replicates them for the optimizer) — an all-reduce of
    every trainable gradient, ring 2n(K-1)/K. Grads live at tp-LOCAL
    shapes when the tp pass rewrote the program."""
    tp = int(getattr(program, "_tp_size", 0) or 0) \
        if getattr(program, "_tp_applied", False) else 0
    total = 0.0
    for b in program.blocks:
        for v in b.vars.values():
            if not (getattr(v, "trainable", False) and v.persistable):
                continue
            shape = list(v.shape or ())
            if tp > 1 and getattr(v, "tp_spec", None):
                from .sharding import tp_local_shape
                shape = list(tp_local_shape(shape, v.tp_spec, tp))
            n = 4
            for d in shape:
                n *= d
            total += 2.0 * n * (k - 1) / k
    return int(total)


def predicted_wire_bytes(report: Dict) -> float:
    """Predicted per-device wire bytes per step on the ONCE-PER-STEP
    collectives (dp gradient pipeline + tp collectives) — the number the
    ledger reconciles EXACTLY with the HLO census. The pipeline's
    boundary collective-permutes are deliberately excluded: they execute
    2(M+K-1) times inside the tick scan but appear once in the static
    HLO, so they are reconciled structurally instead
    (ledger.check_pp_boundary: instruction count == 2, per-instruction
    bytes == the predicted cut buffer)."""
    total = 0.0
    if report.get("dp_comm"):
        total += report["dp_comm"].get("wire_bytes", 0)
    if report.get("tp_comm"):
        total += report["tp_comm"].get("tp_wire_bytes", 0)
    pipe = report.get("pipeline")
    if pipe:
        total += pipe.get("grad_psum_wire_bytes", 0)
    return total


# ---------------------------------------------------------------------------
# planner-facing scalarization: one CostReport -> predicted seconds/bytes.
# The auto-parallel planner (framework/auto_parallel.py) minimizes
# predicted_step_seconds subject to predicted_device_bytes <= HBM; both
# read ONLY the report, so prediction and search can never disagree on
# what a strategy costs.
# ---------------------------------------------------------------------------


def predicted_device_bytes(report: Dict, planned: bool = True) -> int:
    """Predicted per-device footprint of one step from a predict()
    report: the per-device state/feed/seed categories plus the transient
    peak — the memory-PLANNED transient (`transient_peak_planned`,
    priced by predict() when the strategy set memory_plan) when present
    and `planned` is True, the unplanned estimate otherwise."""
    per_dev = report["memory"]["per_device"]
    total = sum(int(per_dev.get(c, 0))
                for c in ("params", "optimizer_state", "ef_residual",
                          "other_state", "feeds", "seed"))
    transient = per_dev["transient_peak"]
    if planned and "transient_peak_planned" in per_dev:
        transient = per_dev["transient_peak_planned"]
    off = report.get("offload")
    if off:
        # host-offloaded optimizer state: only the resident transfer
        # window stays on device — the capacity lever the offload knob
        # buys (the freed bytes are priced, not assumed: the same
        # report's residual_s charges any unhidden round-trip time)
        total -= int(off.get("hbm_freed_bytes", 0))
    return int(max(0, total) + transient)


def predicted_step_seconds(report: Dict, *, mesh_axes: Optional[Dict] = None,
                           strategy=None,
                           ici_bps: float = V5E_ICI_BPS,
                           hbm_bps: float = V5E_HBM_BPS,
                           coll_launch_s: float = 2e-6) -> Dict:
    """Scalarize one predict() report into predicted step seconds on the
    v5e constants — the auto-parallel planner's objective. A RELATIVE
    model (like the pipeline partitioner's balance signal): it only has
    to rank strategies, not to forecast wall-clock on any particular
    host. Terms:

      compute_s   roofline seconds of the whole program divided over
                  dp*tp*K (dp splits the batch, tp the sharded matmuls,
                  pipeline stages run concurrently)
      bubble_s    the schedule's fill/drain overhead on that compute:
                  compute * ((M+K-1)/M - 1), the executed-table bubble
      dp_comm_s / tp_comm_s / pp_comm_s
                  per-device wire bytes / ici_bps (ring models; the pp
                  term adds the boundary permutes — 2 per tick — and the
                  pp-axis gradient psum)
      quant_s     the quantized pipeline's quantize -> f32 dequant-sum
                  -> requantize working-set passes (~3x the flat f32
                  gradient bytes at HBM speed) — what makes int8 wire a
                  LOSS for models whose gradients are small enough that
                  the saved wire never amortizes it (the measured r08
                  CPU-mesh attribution, priced instead of ignored)
      launch_s    per-collective launch overhead x the plan's launch
                  count — what makes comm_bucket_bytes a searched knob
                  (fewer, larger transfers) instead of a free one
      offload_s   the unhidden residual of the offloaded optimizer
                  state's PCIe round-trip (report `offload` section)
                  after overlapping this point's per-device compute —
                  zero when the transfer hides entirely
    """
    axes = dict(mesh_axes or {})
    dp = int(axes.get("dp", report.get("dp", 1)) or 1)
    # credit the tp split ONLY when the tp rewrite actually ran (the
    # report carries a tp_comm section): a tp mesh axis over a program
    # without executable sharding runs REPLICATED — charging tp-divided
    # compute for it would make wasted devices look free
    tp = int(axes.get("tp", 1) or 1) if report.get("tp_comm") else 1
    pipe = report.get("pipeline")
    k = int(pipe["num_stages"]) if pipe else 1
    compute = report["compute"]["roofline_s"] / max(dp * tp * max(k, 1), 1)
    bubble = 0.0
    if pipe:
        m = int(pipe["num_microbatches"])
        bubble = compute * ((m + k - 1) / m - 1.0)
    dp_comm_s = tp_comm_s = pp_comm_s = quant_s = 0.0
    launches = 0
    dpc = report.get("dp_comm")
    if dpc:
        dp_comm_s = dpc.get("wire_bytes", 0) / ici_bps
        launches += int(dpc.get("n_transfers", 0))
        if (strategy is not None and getattr(strategy, "quant_comm", "")
                and dpc.get("explicit")):
            quant_s = 3.0 * dpc.get("grad_f32_bytes", 0) / hbm_bps
    tpc = report.get("tp_comm")
    if tpc:
        tp_comm_s = tpc.get("tp_wire_bytes", 0) / ici_bps
        launches += int(sum((tpc.get("tp_op_counts") or {}).values()))
    if pipe:
        pp_comm_s = pipe.get("grad_psum_wire_bytes", 0) / ici_bps
        boundary = pipe.get("boundary") or {}
        pp_comm_s += boundary.get("pp_boundary_bytes", 0) / ici_bps
        launches += 2 * int(boundary.get("ticks_per_step", 0)) + 1
    launch_s = coll_launch_s * launches
    offload_s = 0.0
    off = report.get("offload")
    if off:
        # the optimizer-state PCIe round-trip overlaps THIS mesh point's
        # per-device compute; only the unhidden residual is charged
        # (recomputed against this point's compute so the term and the
        # search window can never disagree)
        offload_s = max(0.0, off.get("pcie_roundtrip_s", 0.0) - compute)
    total = (compute + bubble + dp_comm_s + tp_comm_s + pp_comm_s
             + quant_s + launch_s + offload_s)
    return {"compute_s": compute, "bubble_s": bubble,
            "dp_comm_s": dp_comm_s, "tp_comm_s": tp_comm_s,
            "pp_comm_s": pp_comm_s, "quant_s": quant_s,
            "launch_s": launch_s, "n_collective_launches": launches,
            "offload_s": offload_s,
            "total_s": total}


# ---------------------------------------------------------------------------
# compile-free strategy feasibility: the SAME gates the executor/pass
# stack raises at run time, surfaced statically with NAMED reasons — the
# auto-parallel planner's pruning predicate and the lint_program
# --strategy surface.
# ---------------------------------------------------------------------------


class Feasibility:
    """Result of strategy_is_feasible: `ok`, the named `reasons`
    ([{code, message}]) when not, and — for a feasible deep check — the
    `program` AS THE EXECUTOR WOULD RUN IT (tp/dp-comm/pipeline/
    memory-plan rewrites applied), ready for costs.predict."""

    def __init__(self, ok: bool, reasons, program=None):
        self.ok = bool(ok)
        self.reasons = list(reasons)
        self.program = program

    def reason_codes(self):
        return sorted({r["code"] for r in self.reasons})

    def __repr__(self):
        return (f"Feasibility(ok={self.ok}, "
                f"reasons={self.reason_codes()})")

    def __bool__(self):
        return self.ok


def _reason(code: str, message: str) -> Dict:
    return {"code": code, "message": message}


def strategy_is_feasible(program, strategy, *, mesh_axes: Dict,
                         nominal_batch: int = 8,
                         deep: bool = True) -> Feasibility:
    """Would `(strategy, mesh_axes)` execute this program? The checks are
    the executor/pass gates themselves, run statically (no XLA compile)
    and mapped to NAMED rejection codes — a config this function accepts
    cannot be rejected by ParallelExecutor at run time, and one it
    rejects names the same condition the run-time enforce would raise:

      quant-invalid          quant_comm outside {'', 'int8', 'bf16'}
      gradient-scale-unsupported  CoeffNumDevice (executor __init__)
      mesh-mismatch          pipeline_stages vs pp axis size, explicit
                             comm without a dp axis, schedule unknown
      batch-indivisible      batch % dp (explicit comm) or % (dp*M)
                             (pipeline) != 0 (_pad_for_dp)
      batch-norm             whole-batch statistics ops under a manual
                             mode (grad_comm/pipeline _BATCH_GLOBAL_OPS)
      non-mean-loss          manual modes need a MEAN-reduced loss
      sp-manual-conflict     enable_sequence_parallel + manual mode
      non-tp-sharded-param   parameter sharded over a live non-tp axis
                             (_gate_manual_mode)
      multi-region           pipeline needs exactly one vjp_region
      pp-too-few-ops         fewer forward ops than stages
      tp-unannotated         manual tp>1 on a program with no sharding
                             annotations
      tp-indivisible         an annotated dim does not divide by tp
      tp-spec-conflict       sharding propagation conflict diagnostics
      narrow-cut             pipeline_partition_pass boundary validation
                             (wide cut / persistable / non-float / sink)
      tp-gate / dp-gate / pp-gate / memory-plan-gate
                             any remaining pass enforce, verbatim

    With `deep=True` (default) the surviving config is pushed through
    the ACTUAL rewrite passes in executor order (tp -> dp-comm ->
    pipeline -> memory plan) so pass-internal gates — narrow-cut
    validity above all — run for real, and the rewritten program rides
    back on the result for costs.predict. `deep=False` stops after the
    cheap structural checks (the planner's first pruning sweep)."""
    from ..core.enforce import EnforceError
    from ..parallel.grad_comm import _BATCH_GLOBAL_OPS, _MEAN_LOSS_OPS
    from ..parallel.mesh import DATA_AXIS, MODEL_AXIS, PIPELINE_AXIS
    from ..parallel.pipeline import PIPELINE_SCHEDULES
    from ..parallel.strategy import GradientScaleStrategy, ReduceStrategy
    from . import sharding as _sharding
    from .analysis import ProgramAnalysisError

    axes = dict(mesh_axes or {})
    dp = int(axes.get(DATA_AXIS, 1) or 1)
    pp = int(axes.get(PIPELINE_AXIS, 1) or 1)
    tp = int(axes.get(MODEL_AXIS, 1) or 1)
    reasons = []

    quant = getattr(strategy, "quant_comm", "") or ""
    if quant not in ("", "int8", "bf16"):
        reasons.append(_reason(
            "quant-invalid",
            f"BuildStrategy.quant_comm must be '', 'int8' or 'bf16', "
            f"got {quant!r}"))
        quant = ""
    if (getattr(strategy, "gradient_scale_strategy",
                GradientScaleStrategy.One)
            == GradientScaleStrategy.CoeffNumDevice):
        reasons.append(_reason(
            "gradient-scale-unsupported",
            "GradientScaleStrategy.CoeffNumDevice is not implemented "
            "(the SPMD global-batch mean already scales the loss)"))

    stages = int(getattr(strategy, "pipeline_stages", 0) or 0)
    m = int(getattr(strategy, "num_microbatches", 1) or 1)
    schedule = getattr(strategy, "pipeline_schedule", "1f1b")
    explicit = (getattr(strategy, "reduce_strategy", None)
                == ReduceStrategy.ReduceScatter) or bool(quant)
    manual = explicit or stages >= 2

    if stages >= 2 and pp != stages:
        reasons.append(_reason(
            "mesh-mismatch",
            f"pipeline_stages={stages} needs a pp mesh axis of exactly "
            f"that size; mesh axes are {axes}"))
    if stages < 2 and pp > 1:
        reasons.append(_reason(
            "mesh-mismatch",
            f"mesh carries a pp axis of size {pp} but the strategy asks "
            f"for no pipeline (pipeline_stages={stages})"))
    if stages >= 2 and schedule not in PIPELINE_SCHEDULES:
        reasons.append(_reason(
            "mesh-mismatch",
            f"pipeline_schedule must be one of {PIPELINE_SCHEDULES}, "
            f"got {schedule!r}"))
    if explicit and DATA_AXIS not in axes:
        reasons.append(_reason(
            "mesh-mismatch",
            f"the explicit gradient pipeline (ReduceScatter/quant_comm) "
            f"needs a {DATA_AXIS!r} axis in the mesh, got {axes}"))

    if explicit and nominal_batch % max(dp, 1) != 0:
        reasons.append(_reason(
            "batch-indivisible",
            f"batch {nominal_batch} is not divisible by dp={dp}: the "
            f"explicit gradient pipeline derives the global-mean "
            f"gradient from EQUAL per-shard batches"))
    if stages >= 2 and nominal_batch % max(dp * m, 1) != 0:
        reasons.append(_reason(
            "batch-indivisible",
            f"batch {nominal_batch} is not divisible by dp * "
            f"num_microbatches = {dp} * {m}: the pipeline schedule "
            f"derives the global-mean loss from EQUAL microbatches"))

    if manual and getattr(strategy, "enable_sequence_parallel", False):
        reasons.append(_reason(
            "sp-manual-conflict",
            "sequence-parallel feed splitting cannot compose with the "
            "manual execution modes (whole per-shard sequences)"))

    block0 = program.global_block()
    if manual:
        bad = sorted({op.type for op in block0.ops
                      if op.type in _BATCH_GLOBAL_OPS})
        if bad:
            reasons.append(_reason(
                "batch-norm",
                f"ops {bad} fold statistics over the WHOLE batch and "
                f"would silently compute per-shard statistics under a "
                f"manual mode"))
        live = {a for a, s in axes.items() if int(s or 1) > 1}
        for b in program.blocks:
            for v in b.vars.values():
                spec = getattr(v, "sharding_spec", None)
                if not v.persistable or spec is None:
                    continue
                names = set()
                for s in spec:
                    if isinstance(s, (tuple, list)):
                        names.update(s)
                    elif s is not None:
                        names.add(s)
                non_tp = sorted((names & live) - {MODEL_AXIS})
                if non_tp:
                    reasons.append(_reason(
                        "non-tp-sharded-param",
                        f"parameter {v.name!r} is sharded over mesh "
                        f"axes {non_tp}; only the tp axis has a manual-"
                        f"mode rewrite pass"))

    regions = [op for op in block0.ops if op.type == "vjp_region"]
    if manual:
        for rop in regions:
            loss_name = rop.attrs["loss"]
            producer = next(
                (o for o in reversed(block0.ops)
                 if loss_name in o.output_names()
                 and o.type != "vjp_region"), None)
            if producer is None or producer.type not in _MEAN_LOSS_OPS:
                reasons.append(_reason(
                    "non-mean-loss",
                    f"loss {loss_name!r} is produced by "
                    f"{producer.type if producer else '<nothing>'}; the "
                    f"manual modes require a MEAN-reduced loss "
                    f"(layers.mean / reduce_mean)"))
    if stages >= 2:
        if len(regions) != 1:
            reasons.append(_reason(
                "multi-region",
                f"pipeline partitioning supports exactly one backward "
                f"region (vjp_region), found {len(regions)}"))
        elif len(list(regions[0].attrs["fwd_ops"])) < stages:
            reasons.append(_reason(
                "pp-too-few-ops",
                f"cannot cut {len(list(regions[0].attrs['fwd_ops']))} "
                f"forward ops into {stages} non-empty stages"))

    if tp > 1 and manual:
        if not _sharding.has_tp_annotations(program):
            reasons.append(_reason(
                "tp-unannotated",
                f"mesh carries a tp axis of size {tp} but the program "
                f"has no tp sharding annotations "
                f"(ParamAttr(sharding_spec=...) / annotate_tp)"))
        else:
            res = _sharding.propagate_sharding(program, tp_size=tp)
            for d in res.diagnostics:
                if d.severity != "error":
                    continue
                code = ("tp-indivisible"
                        if d.code == "shard-divisibility"
                        else "tp-spec-conflict")
                reasons.append(_reason(code, f"{d.loc}: {d.message}"))

    if reasons:
        return Feasibility(False, reasons)
    if not deep:
        return Feasibility(True, [])

    # -- deep check: the actual rewrite passes, executor order ------------
    from ..parallel import grad_comm as _gc
    from ..parallel import pipeline as _pipeline
    from .passes import get_pass

    rewritten = program
    try:
        if (tp > 1 and manual
                and _sharding.has_tp_annotations(rewritten)
                and not getattr(rewritten, "_tp_applied", False)):
            rewritten = get_pass("tp_shard_pass", tp=tp)(rewritten)
    except (EnforceError, ProgramAnalysisError) as e:
        return Feasibility(False, [_reason("tp-gate", str(e))])
    cfg = _gc.explicit_comm_config(strategy)
    if cfg is not None and not getattr(rewritten, "_dp_comm_applied",
                                       False):
        try:
            rewritten = _gc.comm_optimize_pass(rewritten, dp, cfg)
        except (EnforceError, ProgramAnalysisError) as e:
            return Feasibility(False, [_reason("dp-gate", str(e))])
    pcfg = _pipeline.pipeline_config(strategy)
    if pcfg is not None and not getattr(rewritten, "_pp_applied", False):
        try:
            rewritten = get_pass(
                "pipeline_partition_pass",
                num_stages=pcfg["stages"],
                num_microbatches=pcfg["microbatches"],
                schedule=pcfg["schedule"],
                nominal_batch=nominal_batch,
                dp_axis="dp" if "dp" in axes else "",
                reduce_dp=("dp" in axes
                           and not getattr(rewritten, "_dp_comm_applied",
                                           False)),
            )(rewritten)
        except (EnforceError, ProgramAnalysisError) as e:
            msg = str(e)
            code = ("narrow-cut"
                    if ("narrow activation cut" in msg
                        or "carries no activation" in msg
                        or "may cross a stage cut" in msg
                        or "cannot cross a pipeline cut" in msg
                        or "cannot be pruned" in msg)
                    else "pp-too-few-ops" if "cannot cut" in msg
                    else "pp-gate")
            return Feasibility(False, [_reason(code, msg)])
    if getattr(strategy, "memory_plan", False) \
            and not getattr(rewritten, "_memory_plan_applied", False):
        from . import memory_plan as _memory_plan  # noqa: F401 (registers)
        try:
            budget = float(getattr(strategy, "memory_plan_time_budget_s",
                                   0.0) or 0.0)
            rewritten = get_pass(
                "memory_plan_pass",
                nominal_batch=nominal_batch,
                time_budget_s=(budget or None),
                time_budget_frac=float(getattr(strategy,
                                               "memory_plan_time_frac",
                                               0.02)),
                remat_prevent_cse=bool(getattr(strategy,
                                               "memory_plan_prevent_cse",
                                               False)),
            )(rewritten)
        except (EnforceError, ProgramAnalysisError) as e:
            return Feasibility(False, [_reason("memory-plan-gate",
                                               str(e))])
    return Feasibility(True, [], rewritten)
