"""Program pass framework: Pass base, registry, builtin passes, Analyzer.

≙ reference framework/ir/ (ir::Pass + PassRegistry, ir/pass.h:32; fuse and
graph_viz passes) and the inference analysis pipeline
(inference/analysis/analyzer.h:53 — an ordered pass manager rewriting the
program before serving). TPU translation: passes rewrite the Program (and
Scope for constant-folding passes) directly; the heavy fusion work the
reference does in fc_fuse/TensorRT-subgraph passes belongs to XLA here, so
the pass set focuses on semantic rewrites XLA cannot do (constant-folding
batch norms, freezing quantization, pruning, rematerialization policy).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..core.enforce import AlreadyExistsError, NotFoundError, enforce
from .program import Program
from .scope import Scope, global_scope


class Pass:
    """A named program rewrite (≙ ir::Pass, reference ir/pass.h:32).
    Subclasses list `allowed_attrs`; unknown attrs raise instead of
    silently no-op'ing a mistyped option."""

    name = "pass"
    allowed_attrs: tuple = ()

    def __init__(self, **attrs):
        unknown = set(attrs) - set(self.allowed_attrs)
        if unknown:
            raise TypeError(
                f"pass {self.name!r} got unknown attrs {sorted(unknown)}; "
                f"allowed: {sorted(self.allowed_attrs)}")
        self.attrs = attrs

    def apply(self, program: Program, scope: Optional[Scope] = None) -> Program:
        raise NotImplementedError

    def __call__(self, program, scope=None):
        return self.apply(program, scope)


_REGISTRY: Dict[str, Callable[..., Pass]] = {}


def register_pass(name: str):
    """≙ REGISTER_PASS (reference ir/pass.h PassRegistry)."""

    def deco(cls):
        if name in _REGISTRY:
            raise AlreadyExistsError(f"pass {name!r} already registered")
        cls.name = name
        _REGISTRY[name] = cls
        return cls

    return deco


def get_pass(name: str, **attrs) -> Pass:
    if name not in _REGISTRY:
        raise NotFoundError(
            f"no pass named {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**attrs)


def registered_passes() -> List[str]:
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# builtin passes
# ---------------------------------------------------------------------------

@register_pass("prune_pass")
class PrunePass(Pass):
    """Keep only ops needed for `targets` (≙ framework/prune.cc via
    Program.prune). attrs: targets=[var names or Variables]."""

    allowed_attrs = ("targets",)

    def apply(self, program, scope=None):
        return program.prune(self.attrs["targets"])


@register_pass("bn_fold_pass")
class BNFoldPass(Pass):
    """Constant-fold inference batch_norm into the preceding conv/mul
    (≙ the mkldnn conv-bn fuse in inference_transpiler.py:24)."""

    allowed_attrs = ()

    def apply(self, program, scope=None):
        from ..transpiler import InferenceTranspiler
        InferenceTranspiler().transpile(program, scope=scope or global_scope())
        return program


@register_pass("quant_freeze_pass")
class QuantFreezePass(Pass):
    """Bake QAT weight quantization into stored weights (≙ the reference
    freeze flow over fake_quantize ops)."""

    allowed_attrs = ("weight_bits", "activation_bits")

    def apply(self, program, scope=None):
        from ..transpiler import QuantizeTranspiler
        QuantizeTranspiler(**self.attrs) \
            .freeze_program(program, scope=scope or global_scope())
        return program


@register_pass("memory_optimize_pass")
class MemoryOptimizePass(Pass):
    """Remat + live-out narrowing (≙ memory_optimization_transpiler)."""

    allowed_attrs = ("level", "skip_opt_set", "print_log")

    def apply(self, program, scope=None):
        from ..transpiler import memory_optimize
        return memory_optimize(
            program, level=self.attrs.get("level", 0),
            skip_opt_set=self.attrs.get("skip_opt_set"),
            print_log=self.attrs.get("print_log", False))


@register_pass("graph_viz_pass")
class GraphVizPass(Pass):
    """Dump the program graph as graphviz dot (≙ ir/graph_viz_pass.cc).
    attrs: path=...; block_idx=0."""

    allowed_attrs = ("path", "block_idx")

    def apply(self, program, scope=None):
        from ..debugger import draw_block_graphviz
        block = program.blocks[self.attrs.get("block_idx", 0)]
        draw_block_graphviz(block, self.attrs["path"])
        return program


# ---------------------------------------------------------------------------
# fusion passes (≙ the reference's fuse passes: framework/ir
# attention_lstm_fuse_pass.cc, operators/fusion_lstm_op.cc). These rewrite
# matched op-DAG subgraphs to the fused ops in paddle_tpu/fusion/ — users
# keep building dynamic_lstm / cached decode attention; the executor applies
# the passes at compile time behind the default-on fuse_* flags.
# ---------------------------------------------------------------------------


@register_pass("fuse_recurrent_cell_pass")
class FuseRecurrentCellPass(Pass):
    """Rewrite `dynamic_lstm` / `dynamic_gru` ops to their fused-cell
    equivalents (`fused_lstm` / `fused_gru`, paddle_tpu/fusion/recurrent.py)
    — the whole recurrence becomes ONE Pallas kernel on TPU instead of a
    per-tick dispatched scan body. Only default-activation instances are
    fusable; others are left untouched. The rewrite is 1:1 in the op list,
    so op indices (vjp_region fwd_ops segments) stay valid."""

    allowed_attrs = ()

    _REWRITES = {"dynamic_lstm": "fused_lstm", "dynamic_gru": "fused_gru"}

    def apply(self, program, scope=None):
        from ..fusion.recurrent import (gru_attrs_fusable,
                                        lstm_attrs_fusable)
        fusable = {"dynamic_lstm": lstm_attrs_fusable,
                   "dynamic_gru": gru_attrs_fusable}
        n = 0
        for block in program.blocks:
            for op in block.ops:
                target = self._REWRITES.get(op.type)
                if target is None or not fusable[op.type](op.attrs):
                    continue
                op.attrs["fused_from"] = op.type
                op.type = target
                n += 1
        if n:
            program._bump()
        return program


@register_pass("fuse_decode_attention_pass")
class FuseDecodeAttentionPass(Pass):
    """Fuse the cached-decode attention chain
    matmul(q, K^T, alpha) -> elementwise_add(bias) -> softmax -> matmul(V)
    (a SINGLE-position query over a KV cache, the `_attend_cached` idiom)
    into one `fused_decode_attention` op. attrs: protected=[var names that
    must survive — fetch targets]. Blocks containing a vjp_region are
    skipped: the region's fwd_ops segments index into the op list, which a
    multi-op splice would invalidate (decode graphs are inference-only)."""

    allowed_attrs = ("protected",)

    def apply(self, program, scope=None):
        protected = set(self.attrs.get("protected", ()))
        # a fused intermediate may not be read anywhere else in the program
        reads = {}
        for blk in program.blocks:
            for op in blk.ops:
                for name in op.input_names():
                    reads[name] = reads.get(name, 0) + 1
        n = 0
        for block in program.blocks:
            if any(op.type == "vjp_region" for op in block.ops):
                continue
            n += self._rewrite_block(block, reads, protected)
        if n:
            program._bump()
        return program

    @staticmethod
    def _shape(block, name):
        try:
            return block.var(name).shape
        except NotFoundError:
            return None

    def _match(self, block, ops, si, producer, reads, protected):
        """Try to match the 4-op chain whose softmax is ops[si]; returns
        (match dict) or None."""
        sm = ops[si]
        if sm.attrs.get("axis", -1) != -1:
            return None
        add = producer.get(sm.inputs.get("X", [None])[0])
        if add is None or add.type != "elementwise_add" or \
                add.attrs.get("axis", -1) != -1:
            return None
        m1 = producer.get(add.inputs["X"][0])
        if m1 is None or m1.type != "matmul" or \
                not m1.attrs.get("transpose_Y") or \
                m1.attrs.get("transpose_X") or m1.attrs.get("use_bf16"):
            return None
        # the single consumer of the softmax must be the context matmul
        sm_out = sm.outputs["Out"][0]
        m2 = None
        for op in ops:
            if sm_out in op.input_names():
                if m2 is not None:
                    return None
                m2 = op
        if m2 is None or m2.type != "matmul" or \
                m2.inputs["X"][0] != sm_out or \
                m2.attrs.get("transpose_X") or m2.attrs.get("transpose_Y") \
                or m2.attrs.get("alpha", 1.0) != 1.0 \
                or m2.attrs.get("use_bf16"):
            return None
        q, k = m1.inputs["X"][0], m1.inputs["Y"][0]
        v = m2.inputs["Y"][0]
        bias = add.inputs["Y"][0]
        qs, ks = self._shape(block, q), self._shape(block, k)
        vs, bs = self._shape(block, v), self._shape(block, bias)
        if qs is None or ks is None or vs is None or bs is None:
            return None
        # single-position query over an equal-layout cache (no beam
        # broadcast on K/V — that pattern reads better through XLA's own
        # batched matmul). Rank 3 ([B, 1, H] state over [B, T, H] encoder
        # outputs — the GRU-attention NMT idiom) fuses too: the batch rows
        # simply ride the fused kernel's head axis.
        if len(qs) < 3 or qs[-2] != 1 or len(ks) != len(qs) or \
                tuple(ks[:-2]) != tuple(qs[:-2]) or tuple(vs) != tuple(ks):
            return None
        tgt = tuple(qs[:-2]) + (1, ks[-2])
        if len(bs) != len(tgt) or any(
                bd != 1 and bd != td for bd, td in zip(bs, tgt)):
            return None
        # intermediates must be pure glue: consumed exactly once, by the
        # next op in the chain, and not fetched/protected
        for name, n_reads in ((m1.outputs["Out"][0], 1),
                              (add.outputs["Out"][0], 1), (sm_out, 1)):
            if reads.get(name, 0) != n_reads or name in protected:
                return None
            var = block.vars.get(name)
            if var is not None and (var.persistable or var.is_data):
                return None
        return {"m1": m1, "add": add, "sm": sm, "m2": m2,
                "q": q, "k": k, "v": v, "bias": bias,
                "scale": float(m1.attrs.get("alpha", 1.0))}

    def _rewrite_block(self, block, reads, protected):
        from .program import Operator
        ops = block.ops
        producer = {}
        for op in ops:
            for name in op.output_names():
                producer[name] = op
        matches = []
        claimed = set()
        for si, op in enumerate(ops):
            if op.type != "softmax":
                continue
            m = self._match(block, ops, si, producer, reads, protected)
            if m is None:
                continue
            group = {id(m["m1"]), id(m["add"]), id(m["sm"]), id(m["m2"])}
            if group & claimed:
                continue
            claimed |= group
            matches.append(m)
        if not matches:
            return 0
        # splice at the LAST op of the chain (m2): every fused input
        # (q/k/v/bias) is produced before it by construction — the bias
        # may legitimately be built between the score matmul and the add
        # (the NMT attention builds it mid-chain)
        by_anchor = {id(m["m2"]): m for m in matches}
        drop = set()
        for m in matches:
            drop |= {id(m["m1"]), id(m["add"]), id(m["sm"])}
        new_ops = []
        for op in ops:
            m = by_anchor.get(id(op))
            if m is not None:
                fused = Operator(
                    block, "fused_decode_attention",
                    inputs={"Q": [m["q"]], "K": [m["k"]], "V": [m["v"]],
                            "Bias": [m["bias"]]},
                    outputs={"Out": [m["m2"].outputs["Out"][0]]},
                    attrs={"scale": m["scale"]})
                new_ops.append(fused)
                out_name = m["m2"].outputs["Out"][0]
                if out_name in block.vars:
                    block.vars[out_name].op = fused
                for name in (m["m1"].outputs["Out"][0],
                             m["add"].outputs["Out"][0],
                             m["sm"].outputs["Out"][0]):
                    block.vars.pop(name, None)
                continue
            if id(op) in drop:
                continue
            new_ops.append(op)
        block.ops = new_ops
        return len(matches)


def apply_fusion_passes(program: Program, protected=()) -> Program:
    """Executor-compile-time entry: apply the flag-enabled fusion passes to
    a CLONE of `program` (the caller's program is never mutated). Returns
    the original program untouched when the flags are off or nothing can
    match — the common case costs one cheap op-type scan."""
    from ..core import flags
    do_rnn = flags.get_flag("fuse_recurrent_cells")
    do_dec = flags.get_flag("fuse_decode_attention")
    if not (do_rnn or do_dec):
        return program
    has_rnn = has_dec = False
    for blk in program.blocks:
        has_vjp = any(op.type == "vjp_region" for op in blk.ops)
        for op in blk.ops:
            if op.type in ("dynamic_lstm", "dynamic_gru"):
                has_rnn = True
            elif op.type == "softmax" and not has_vjp:
                has_dec = True
    if not ((do_rnn and has_rnn) or (do_dec and has_dec)):
        return program
    rewritten = program.clone()
    if do_rnn and has_rnn:
        get_pass("fuse_recurrent_cell_pass")(rewritten)
    if do_dec and has_dec:
        get_pass("fuse_decode_attention_pass",
                 protected=sorted(protected))(rewritten)
    return rewritten


class Analyzer:
    """Ordered pass manager preparing a trained program for serving
    (≙ inference/analysis/analyzer.h:53 running its pass pipeline over the
    data-flow graph; TensorRT-subgraph slots are XLA's job here).

        program = Analyzer(passes=["bn_fold_pass", "quant_freeze_pass"]) \
            .run(program, scope)
    """

    DEFAULT_PASSES = ["bn_fold_pass"]

    def __init__(self, passes: Optional[List[str]] = None, **pass_attrs):
        self.pass_names = list(passes or self.DEFAULT_PASSES)
        self.pass_attrs = pass_attrs

    def run(self, program: Program, scope: Optional[Scope] = None,
            targets=None) -> Program:
        scope = scope or global_scope()
        if targets is not None:
            program = get_pass("prune_pass", targets=targets)(program, scope)
        for name in self.pass_names:
            attrs = self.pass_attrs.get(name, {})
            program = get_pass(name, **attrs)(program, scope)
        return program


@register_pass("check_pass")
class CheckPass(Pass):
    """Validate program well-formedness before execution (≙ the
    multi_devices_check_pass + ir::HasCircle asserts the reference applies
    at parallel_executor.cc:91 / multi_devices_graph_pass.cc:465): every op
    input must be produced by an earlier op, fed (is_data), persistable, or
    a recognized companion var. Raises with the full violation list."""

    allowed_attrs = ("extra_feeds",)

    def apply(self, program, scope=None):
        extra = set(self.attrs.get("extra_feeds", ()))
        problems = []

        # Sub-block binder names: a control-flow op (while/static_rnn/
        # cond_block/...) binds inner vars (step views, carried memories,
        # captures) at lowering time via string/string-list attrs; those
        # names are defined inside the block the op references.
        # control-flow ops store sub-block references under these attr
        # keys (while/static_rnn/cond_block/switch_case); binder names are
        # the string/string-list attrs of THAT op only
        _SUB_KEYS = ("sub_block", "true_block", "false_block",
                     "case_blocks", "default_block")
        bound: dict = {}
        for blk in program.blocks:
            for op in blk.ops:
                sub_idxs = []
                for key in _SUB_KEYS:
                    v = op.attrs.get(key)
                    if isinstance(v, int) and not isinstance(v, bool):
                        sub_idxs.append(v)
                    elif isinstance(v, (list, tuple)):
                        sub_idxs.extend(x for x in v if isinstance(x, int))
                if not sub_idxs:
                    continue
                names = set()
                for v in op.attrs.values():
                    if isinstance(v, str):
                        names.add(v)
                    elif isinstance(v, (list, tuple)) and \
                            all(isinstance(x, str) for x in v):
                        names.update(v)
                for si in sub_idxs:
                    if 0 < si < len(program.blocks):
                        bound.setdefault(si, set()).update(names)

        for block in program.blocks:
            defined = set(extra) | bound.get(block.idx, set())
            for name, var in block.vars.items():
                if (getattr(var, "persistable", False)
                        or getattr(var, "is_data", False)):
                    defined.add(name)
                    defined.add(name + "@SEQLEN")
            # parent-block vars are visible in sub-blocks
            b = block
            while b.parent is not None:
                b = b.parent
                defined |= set(b.vars)
            for idx, op in enumerate(block.ops):
                for name in op.input_names():
                    if name not in defined:
                        problems.append(
                            f"block {block.idx} op#{idx} {op.type!r} reads "
                            f"{name!r} before any producer/feed")
                # vjp_region declares Grads/LossGrad outputs like any op;
                # registering them keeps later grad reads honest without a
                # blanket @GRAD exemption
                defined.update(op.output_names())
        if problems:
            raise NotFoundError(
                "program check failed:\n  " + "\n  ".join(problems))
        return program
