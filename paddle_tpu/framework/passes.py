"""Program pass framework: Pass base, registry, builtin passes, Analyzer.

≙ reference framework/ir/ (ir::Pass + PassRegistry, ir/pass.h:32; fuse and
graph_viz passes) and the inference analysis pipeline
(inference/analysis/analyzer.h:53 — an ordered pass manager rewriting the
program before serving). TPU translation: passes rewrite the Program (and
Scope for constant-folding passes) directly; the heavy fusion work the
reference does in fc_fuse/TensorRT-subgraph passes belongs to XLA here, so
the pass set focuses on semantic rewrites XLA cannot do (constant-folding
batch norms, freezing quantization, pruning, rematerialization policy).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..core.enforce import AlreadyExistsError, NotFoundError, enforce
from .program import Program
from .scope import Scope, global_scope


class Pass:
    """A named program rewrite (≙ ir::Pass, reference ir/pass.h:32).
    Subclasses list `allowed_attrs`; unknown attrs raise instead of
    silently no-op'ing a mistyped option."""

    name = "pass"
    allowed_attrs: tuple = ()

    def __init__(self, **attrs):
        unknown = set(attrs) - set(self.allowed_attrs)
        if unknown:
            raise TypeError(
                f"pass {self.name!r} got unknown attrs {sorted(unknown)}; "
                f"allowed: {sorted(self.allowed_attrs)}")
        self.attrs = attrs

    def apply(self, program: Program, scope: Optional[Scope] = None) -> Program:
        raise NotImplementedError

    def __call__(self, program, scope=None):
        return self.apply(program, scope)


_REGISTRY: Dict[str, Callable[..., Pass]] = {}


def register_pass(name: str):
    """≙ REGISTER_PASS (reference ir/pass.h PassRegistry)."""

    def deco(cls):
        if name in _REGISTRY:
            raise AlreadyExistsError(f"pass {name!r} already registered")
        cls.name = name
        _REGISTRY[name] = cls
        return cls

    return deco


def get_pass(name: str, **attrs) -> Pass:
    if name not in _REGISTRY:
        raise NotFoundError(
            f"no pass named {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**attrs)


def registered_passes() -> List[str]:
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# builtin passes
# ---------------------------------------------------------------------------

@register_pass("prune_pass")
class PrunePass(Pass):
    """Keep only ops needed for `targets` (≙ framework/prune.cc via
    Program.prune). attrs: targets=[var names or Variables]."""

    allowed_attrs = ("targets",)

    def apply(self, program, scope=None):
        return program.prune(self.attrs["targets"])


@register_pass("bn_fold_pass")
class BNFoldPass(Pass):
    """Constant-fold inference batch_norm into the preceding conv/mul
    (≙ the mkldnn conv-bn fuse in inference_transpiler.py:24)."""

    allowed_attrs = ()

    def apply(self, program, scope=None):
        from ..transpiler import InferenceTranspiler
        InferenceTranspiler().transpile(program, scope=scope or global_scope())
        return program


@register_pass("quant_freeze_pass")
class QuantFreezePass(Pass):
    """Bake QAT weight quantization into stored weights (≙ the reference
    freeze flow over fake_quantize ops)."""

    allowed_attrs = ("weight_bits", "activation_bits")

    def apply(self, program, scope=None):
        from ..transpiler import QuantizeTranspiler
        QuantizeTranspiler(**self.attrs) \
            .freeze_program(program, scope=scope or global_scope())
        return program


@register_pass("memory_optimize_pass")
class MemoryOptimizePass(Pass):
    """Remat + live-out narrowing (≙ memory_optimization_transpiler)."""

    allowed_attrs = ("level", "skip_opt_set", "print_log")

    def apply(self, program, scope=None):
        from ..transpiler import memory_optimize
        return memory_optimize(
            program, level=self.attrs.get("level", 0),
            skip_opt_set=self.attrs.get("skip_opt_set"),
            print_log=self.attrs.get("print_log", False))


@register_pass("graph_viz_pass")
class GraphVizPass(Pass):
    """Dump the program graph as graphviz dot (≙ ir/graph_viz_pass.cc).
    attrs: path=...; block_idx=0."""

    allowed_attrs = ("path", "block_idx")

    def apply(self, program, scope=None):
        from ..debugger import draw_block_graphviz
        block = program.blocks[self.attrs.get("block_idx", 0)]
        draw_block_graphviz(block, self.attrs["path"])
        return program


class Analyzer:
    """Ordered pass manager preparing a trained program for serving
    (≙ inference/analysis/analyzer.h:53 running its pass pipeline over the
    data-flow graph; TensorRT-subgraph slots are XLA's job here).

        program = Analyzer(passes=["bn_fold_pass", "quant_freeze_pass"]) \
            .run(program, scope)
    """

    DEFAULT_PASSES = ["bn_fold_pass"]

    def __init__(self, passes: Optional[List[str]] = None, **pass_attrs):
        self.pass_names = list(passes or self.DEFAULT_PASSES)
        self.pass_attrs = pass_attrs

    def run(self, program: Program, scope: Optional[Scope] = None,
            targets=None) -> Program:
        scope = scope or global_scope()
        if targets is not None:
            program = get_pass("prune_pass", targets=targets)(program, scope)
        for name in self.pass_names:
            attrs = self.pass_attrs.get(name, {})
            program = get_pass(name, **attrs)(program, scope)
        return program


@register_pass("check_pass")
class CheckPass(Pass):
    """Validate program well-formedness before execution (≙ the
    multi_devices_check_pass + ir::HasCircle asserts the reference applies
    at parallel_executor.cc:91 / multi_devices_graph_pass.cc:465): every op
    input must be produced by an earlier op, fed (is_data), persistable, or
    a recognized companion var. Raises with the full violation list."""

    allowed_attrs = ("extra_feeds",)

    def apply(self, program, scope=None):
        extra = set(self.attrs.get("extra_feeds", ()))
        problems = []

        # Sub-block binder names: a control-flow op (while/static_rnn/
        # cond_block/...) binds inner vars (step views, carried memories,
        # captures) at lowering time via string/string-list attrs; those
        # names are defined inside the block the op references.
        # control-flow ops store sub-block references under these attr
        # keys (while/static_rnn/cond_block/switch_case); binder names are
        # the string/string-list attrs of THAT op only
        _SUB_KEYS = ("sub_block", "true_block", "false_block",
                     "case_blocks", "default_block")
        bound: dict = {}
        for blk in program.blocks:
            for op in blk.ops:
                sub_idxs = []
                for key in _SUB_KEYS:
                    v = op.attrs.get(key)
                    if isinstance(v, int) and not isinstance(v, bool):
                        sub_idxs.append(v)
                    elif isinstance(v, (list, tuple)):
                        sub_idxs.extend(x for x in v if isinstance(x, int))
                if not sub_idxs:
                    continue
                names = set()
                for v in op.attrs.values():
                    if isinstance(v, str):
                        names.add(v)
                    elif isinstance(v, (list, tuple)) and \
                            all(isinstance(x, str) for x in v):
                        names.update(v)
                for si in sub_idxs:
                    if 0 < si < len(program.blocks):
                        bound.setdefault(si, set()).update(names)

        for block in program.blocks:
            defined = set(extra) | bound.get(block.idx, set())
            for name, var in block.vars.items():
                if (getattr(var, "persistable", False)
                        or getattr(var, "is_data", False)):
                    defined.add(name)
                    defined.add(name + "@SEQLEN")
            # parent-block vars are visible in sub-blocks
            b = block
            while b.parent is not None:
                b = b.parent
                defined |= set(b.vars)
            for idx, op in enumerate(block.ops):
                for name in op.input_names():
                    if name not in defined:
                        problems.append(
                            f"block {block.idx} op#{idx} {op.type!r} reads "
                            f"{name!r} before any producer/feed")
                # vjp_region declares Grads/LossGrad outputs like any op;
                # registering them keeps later grad reads honest without a
                # blanket @GRAD exemption
                defined.update(op.output_names())
        if problems:
            raise NotFoundError(
                "program check failed:\n  " + "\n  ".join(problems))
        return program
