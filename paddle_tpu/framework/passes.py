"""Program pass framework: Pass base, registry, builtin passes, Analyzer.

≙ reference framework/ir/ (ir::Pass + PassRegistry, ir/pass.h:32; fuse and
graph_viz passes) and the inference analysis pipeline
(inference/analysis/analyzer.h:53 — an ordered pass manager rewriting the
program before serving). TPU translation: passes rewrite the Program (and
Scope for constant-folding passes) directly; the heavy fusion work the
reference does in fc_fuse/TensorRT-subgraph passes belongs to XLA here, so
the pass set focuses on semantic rewrites XLA cannot do (constant-folding
batch norms, freezing quantization, pruning, rematerialization policy).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..core.enforce import (AlreadyExistsError, InvalidArgumentError,
                            NotFoundError, enforce)
from .program import Program
from .scope import Scope, global_scope


class Pass:
    """A named program rewrite (≙ ir::Pass, reference ir/pass.h:32).
    Subclasses list `allowed_attrs`; unknown attrs raise instead of
    silently no-op'ing a mistyped option."""

    name = "pass"
    allowed_attrs: tuple = ()

    def __init__(self, **attrs):
        unknown = set(attrs) - set(self.allowed_attrs)
        if unknown:
            raise TypeError(
                f"pass {self.name!r} got unknown attrs {sorted(unknown)}; "
                f"allowed: {sorted(self.allowed_attrs)}")
        self.attrs = attrs

    def apply(self, program: Program, scope: Optional[Scope] = None) -> Program:
        raise NotImplementedError

    def __call__(self, program, scope=None):
        # Every pass apply runs under the pass sanitizer (verify-before /
        # verify-after, framework/analysis.py): a rewrite that breaks a
        # structural invariant is attributed to THIS pass by name instead
        # of surfacing later as an opaque trace error — the role the HLO
        # verifier plays between XLA passes. Kill switch PTPU_VERIFY_PASSES=0.
        # The apply is also recorded as a "pass" span carrying the pass
        # name + attrs, so compile-time rewrite cost is attributable per
        # pass in the trace (observability/tracing.py).
        from ..observability import tracing as _tracing
        from .analysis import sanitized_apply
        with _tracing.span("pass", f"pass/{self.name}",
                           **{k: v for k, v in self.attrs.items()
                              if isinstance(v, (str, int, float, bool))}):
            return sanitized_apply(self, program, scope)


_REGISTRY: Dict[str, Callable[..., Pass]] = {}


def register_pass(name: str):
    """≙ REGISTER_PASS (reference ir/pass.h PassRegistry)."""

    def deco(cls):
        if name in _REGISTRY:
            raise AlreadyExistsError(f"pass {name!r} already registered")
        cls.name = name
        _REGISTRY[name] = cls
        return cls

    return deco


# passes registered by modules this package does not import eagerly (the
# module's import cost stays off the common path); get_pass resolves them
# on first use — the same "import registers" contract every caller-side
# `from ..framework import sharding  # registers` comment documents
_LAZY_PASS_MODULES = {"memory_plan_pass": "memory_plan"}


def get_pass(name: str, **attrs) -> Pass:
    if name not in _REGISTRY and name in _LAZY_PASS_MODULES:
        import importlib
        importlib.import_module("." + _LAZY_PASS_MODULES[name], __package__)
    if name not in _REGISTRY:
        raise NotFoundError(
            f"no pass named {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**attrs)


def registered_passes() -> List[str]:
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# builtin passes
# ---------------------------------------------------------------------------

@register_pass("prune_pass")
class PrunePass(Pass):
    """Keep only ops needed for `targets` (≙ framework/prune.cc via
    Program.prune). attrs: targets=[var names or Variables]."""

    allowed_attrs = ("targets",)

    def apply(self, program, scope=None):
        return program.prune(self.attrs["targets"])


@register_pass("bn_fold_pass")
class BNFoldPass(Pass):
    """Constant-fold inference batch_norm into the preceding conv/mul
    (≙ the mkldnn conv-bn fuse in inference_transpiler.py:24)."""

    allowed_attrs = ()

    def apply(self, program, scope=None):
        from ..transpiler import InferenceTranspiler
        InferenceTranspiler().transpile(program, scope=scope or global_scope())
        return program


@register_pass("quant_freeze_pass")
class QuantFreezePass(Pass):
    """Bake QAT weight quantization into stored weights (≙ the reference
    freeze flow over fake_quantize ops)."""

    allowed_attrs = ("weight_bits", "activation_bits")

    def apply(self, program, scope=None):
        from ..transpiler import QuantizeTranspiler
        QuantizeTranspiler(**self.attrs) \
            .freeze_program(program, scope=scope or global_scope())
        return program


@register_pass("memory_optimize_pass")
class MemoryOptimizePass(Pass):
    """Remat + live-out narrowing (≙ memory_optimization_transpiler)."""

    allowed_attrs = ("level", "skip_opt_set", "print_log")

    def apply(self, program, scope=None):
        from ..transpiler import memory_optimize
        return memory_optimize(
            program, level=self.attrs.get("level", 0),
            skip_opt_set=self.attrs.get("skip_opt_set"),
            print_log=self.attrs.get("print_log", False))


@register_pass("graph_viz_pass")
class GraphVizPass(Pass):
    """Dump the program graph as graphviz dot (≙ ir/graph_viz_pass.cc).
    attrs: path=...; block_idx=0."""

    allowed_attrs = ("path", "block_idx")

    def apply(self, program, scope=None):
        from ..debugger import draw_block_graphviz
        block = program.blocks[self.attrs.get("block_idx", 0)]
        draw_block_graphviz(block, self.attrs["path"])
        return program


# ---------------------------------------------------------------------------
# fusion passes (≙ the reference's fuse passes: framework/ir
# attention_lstm_fuse_pass.cc, operators/fusion_lstm_op.cc). These rewrite
# matched op-DAG subgraphs to the fused ops in paddle_tpu/fusion/ — users
# keep building dynamic_lstm / cached decode attention; the executor applies
# the passes at compile time behind the default-on fuse_* flags.
# ---------------------------------------------------------------------------


@register_pass("fuse_recurrent_cell_pass")
class FuseRecurrentCellPass(Pass):
    """Rewrite `dynamic_lstm` / `dynamic_gru` ops to their fused-cell
    equivalents (`fused_lstm` / `fused_gru`, paddle_tpu/fusion/recurrent.py)
    — the whole recurrence becomes ONE Pallas kernel on TPU instead of a
    per-tick dispatched scan body. Only default-activation instances are
    fusable; others are left untouched. The rewrite is 1:1 in the op list,
    so op indices (vjp_region fwd_ops segments) stay valid."""

    allowed_attrs = ()

    _REWRITES = {"dynamic_lstm": "fused_lstm", "dynamic_gru": "fused_gru"}

    def apply(self, program, scope=None):
        from ..fusion.recurrent import (gru_attrs_fusable,
                                        lstm_attrs_fusable)
        fusable = {"dynamic_lstm": lstm_attrs_fusable,
                   "dynamic_gru": gru_attrs_fusable}
        n = 0
        for block in program.blocks:
            for op in block.ops:
                target = self._REWRITES.get(op.type)
                if target is None or not fusable[op.type](op.attrs):
                    continue
                op.attrs["fused_from"] = op.type
                op.type = target
                n += 1
        if n:
            program._bump()
        return program


@register_pass("fuse_decode_attention_pass")
class FuseDecodeAttentionPass(Pass):
    """Fuse the cached-decode attention chain
    matmul(q, K^T, alpha) -> elementwise_add(bias) -> softmax -> matmul(V)
    (a SINGLE-position query over a KV cache, the `_attend_cached` idiom)
    into one `fused_decode_attention` op. attrs: protected=[var names that
    must survive — fetch targets]. Blocks containing a vjp_region (or a
    pp_pipeline_region) are skipped: those regions' fwd_ops/stages segments
    index into the op list, which a multi-op splice would invalidate
    (decode graphs are inference-only)."""

    allowed_attrs = ("protected",)

    def apply(self, program, scope=None):
        protected = set(self.attrs.get("protected", ()))
        # a fused intermediate may not be read anywhere else in the program
        reads = {}
        for blk in program.blocks:
            for op in blk.ops:
                for name in op.input_names():
                    reads[name] = reads.get(name, 0) + 1
        n = 0
        for block in program.blocks:
            if any(op.type in ("vjp_region", "pp_pipeline_region")
                   for op in block.ops):
                continue
            n += self._rewrite_block(block, reads, protected)
        if n:
            program._bump()
        return program

    @staticmethod
    def _shape(block, name):
        try:
            return block.var(name).shape
        except NotFoundError:
            return None

    def _match(self, block, ops, si, producer, reads, protected):
        """Try to match the 4-op chain whose softmax is ops[si]; returns
        (match dict) or None."""
        sm = ops[si]
        if sm.attrs.get("axis", -1) != -1:
            return None
        add = producer.get(sm.inputs.get("X", [None])[0])
        if add is None or add.type != "elementwise_add" or \
                add.attrs.get("axis", -1) != -1:
            return None
        m1 = producer.get(add.inputs["X"][0])
        if m1 is None or m1.type != "matmul" or \
                not m1.attrs.get("transpose_Y") or \
                m1.attrs.get("transpose_X") or m1.attrs.get("use_bf16"):
            return None
        # the single consumer of the softmax must be the context matmul
        sm_out = sm.outputs["Out"][0]
        m2 = None
        for op in ops:
            if sm_out in op.input_names():
                if m2 is not None:
                    return None
                m2 = op
        if m2 is None or m2.type != "matmul" or \
                m2.inputs["X"][0] != sm_out or \
                m2.attrs.get("transpose_X") or m2.attrs.get("transpose_Y") \
                or m2.attrs.get("alpha", 1.0) != 1.0 \
                or m2.attrs.get("use_bf16"):
            return None
        q, k = m1.inputs["X"][0], m1.inputs["Y"][0]
        v = m2.inputs["Y"][0]
        bias = add.inputs["Y"][0]
        qs, ks = self._shape(block, q), self._shape(block, k)
        vs, bs = self._shape(block, v), self._shape(block, bias)
        if qs is None or ks is None or vs is None or bs is None:
            return None
        # decode-width query over an equal-layout cache (no beam
        # broadcast on K/V — that pattern reads better through XLA's own
        # batched matmul). Width 1 is the plain decode tick; 1 < G < T is
        # a speculative verify window (γ+1 positions scored against the
        # cache in one forward). Full-sequence chains (Tq == Tk) are NOT
        # decode steps and stay unfused. Rank 3 ([B, 1, H] state over
        # [B, T, H] encoder outputs — the GRU-attention NMT idiom) fuses
        # too: the batch rows simply ride the fused kernel's head axis.
        if len(qs) < 3 or len(ks) != len(qs) or \
                not (qs[-2] == 1 or 1 < qs[-2] < ks[-2]) or \
                tuple(ks[:-2]) != tuple(qs[:-2]) or tuple(vs) != tuple(ks):
            return None
        tgt = tuple(qs[:-2]) + (qs[-2], ks[-2])
        if len(bs) != len(tgt) or any(
                bd != 1 and bd != td for bd, td in zip(bs, tgt)):
            return None
        # intermediates must be pure glue: consumed exactly once, by the
        # next op in the chain, and not fetched/protected
        for name, n_reads in ((m1.outputs["Out"][0], 1),
                              (add.outputs["Out"][0], 1), (sm_out, 1)):
            if reads.get(name, 0) != n_reads or name in protected:
                return None
            var = block.vars.get(name)
            if var is not None and (var.persistable or var.is_data):
                return None
        return {"m1": m1, "add": add, "sm": sm, "m2": m2,
                "q": q, "k": k, "v": v, "bias": bias,
                "scale": float(m1.attrs.get("alpha", 1.0))}

    def _rewrite_block(self, block, reads, protected):
        from .program import Operator
        ops = block.ops
        producer = {}
        for op in ops:
            for name in op.output_names():
                producer[name] = op
        matches = []
        claimed = set()
        for si, op in enumerate(ops):
            if op.type != "softmax":
                continue
            m = self._match(block, ops, si, producer, reads, protected)
            if m is None:
                continue
            group = {id(m["m1"]), id(m["add"]), id(m["sm"]), id(m["m2"])}
            if group & claimed:
                continue
            claimed |= group
            matches.append(m)
        if not matches:
            return 0
        # splice at the LAST op of the chain (m2): every fused input
        # (q/k/v/bias) is produced before it by construction — the bias
        # may legitimately be built between the score matmul and the add
        # (the NMT attention builds it mid-chain)
        by_anchor = {id(m["m2"]): m for m in matches}
        drop = set()
        for m in matches:
            drop |= {id(m["m1"]), id(m["add"]), id(m["sm"])}
        new_ops = []
        for op in ops:
            m = by_anchor.get(id(op))
            if m is not None:
                fused = Operator(
                    block, "fused_decode_attention",
                    inputs={"Q": [m["q"]], "K": [m["k"]], "V": [m["v"]],
                            "Bias": [m["bias"]]},
                    outputs={"Out": [m["m2"].outputs["Out"][0]]},
                    attrs={"scale": m["scale"]})
                new_ops.append(fused)
                out_name = m["m2"].outputs["Out"][0]
                if out_name in block.vars:
                    block.vars[out_name].op = fused
                for name in (m["m1"].outputs["Out"][0],
                             m["add"].outputs["Out"][0],
                             m["sm"].outputs["Out"][0]):
                    block.vars.pop(name, None)
                continue
            if id(op) in drop:
                continue
            new_ops.append(op)
        block.ops = new_ops
        return len(matches)


@register_pass("quantize_params_pass")
class QuantizeParamsPass(Pass):
    """Weight-only serving quantization: rewrite a serving program's
    persistable f32 weights into block-scaled (payload, scales) pairs and
    their consumer ops into the quantized kernels — `mul` -> `qmatmul`,
    `lookup_table` -> `qlookup` (whose lowerings dequantize per-tile inside
    the kernel; ops/nn_ops.py, ops/tensor_ops.py). attrs: bits (8 or 4),
    block (tile edge, parallel/collective.py QUANT_BLOCK_2D).

    Contract: MUTATES `program` and `scope` in place — the f32 weight array
    is dropped from the scope and its var from the block (its HBM is the
    freed headroom the serving engine hands to the KV pool), replaced by
    `<w>@qparam` (int8; nibble-packed columns at bits=4) and `<w>@qscale`
    (f32 tile grid). The name suffixes are the census contract:
    costs.state_category classifies them as `params_quantized` — suffixes,
    not var attrs, because Program.clone() only preserves whitelisted extra
    attrs. A weight is only quantized when NO op writes it and EVERY
    consumer reads it through a rewritable slot (mul.Y with
    y_num_col_dims=1 / lookup_table.W) — anything else keeps f32. The
    rewrite is 1:1 in the op list, so op indices stay valid."""

    allowed_attrs = ("bits", "block")

    def apply(self, program, scope=None):
        import numpy as np

        from ..parallel.collective import (QUANT_BLOCK_2D,
                                           quantize_blocks_2d)
        from .program import Operator

        scope = scope or global_scope()
        bits = int(self.attrs.get("bits", 8))
        tile = int(self.attrs.get("block", QUANT_BLOCK_2D))
        if bits not in (8, 4):
            raise InvalidArgumentError(
                f"quantize_params_pass supports bits in (8, 4), got {bits}")

        written, consumers = set(), {}
        for blk in program.blocks:
            for op in blk.ops:
                written.update(op.output_names())
                for name in op.input_names():
                    consumers.setdefault(name, []).append(op)

        def weight_slot(op):
            if op.type == "mul" and op.attrs.get("y_num_col_dims", 1) == 1:
                return "Y"
            if op.type == "lookup_table":
                return "W"
            return None

        chosen = {}
        for blk in program.blocks:
            for name, var in blk.vars.items():
                if (not var.persistable or name in written
                        or var.shape is None or len(var.shape) != 2
                        or -1 in var.shape or str(var.dtype) != "float32"):
                    continue
                # A twin program (e.g. a speculative verify forward sharing
                # weights by name with an already-quantized serving program)
                # sees the f32 payload gone from the scope but the quantized
                # pair present: reuse the existing payloads instead of
                # skipping, so both programs read the same HBM arrays.
                reuse = not scope.has_var(name)
                if reuse and not (scope.has_var(name + "@qparam")
                                  and scope.has_var(name + "@qscale")):
                    continue
                if bits == 4 and var.shape[1] % 2:
                    continue     # nibble packing needs even columns
                ops = consumers.get(name, [])
                if not ops:
                    continue
                ok = True
                for op in ops:
                    slot = weight_slot(op)
                    if slot is None or op.inputs.get(slot) != [name]:
                        ok = False
                        break
                    if any(name in vs for s, vs in op.inputs.items()
                           if s != slot):
                        ok = False
                        break
                if ok:
                    chosen[name] = (blk, reuse)
        if not chosen:
            return program

        for name, (blk, reuse) in chosen.items():
            qname, sname = name + "@qparam", name + "@qscale"
            if reuse:
                var = blk.vars[name]
                q = np.asarray(scope.get(qname))
                s = np.asarray(scope.get(sname))
                want_cols = var.shape[1] // 2 if bits == 4 else var.shape[1]
                if tuple(q.shape) != (var.shape[0], want_cols):
                    raise InvalidArgumentError(
                        f"existing quantized payload {qname} has shape "
                        f"{tuple(q.shape)}, incompatible with {name} "
                        f"{tuple(var.shape)} at bits={bits} — the twin "
                        f"program must be quantized at the same bits as "
                        f"the scope's resident payloads")
            else:
                w = np.asarray(scope.get(name), np.float32)
                q, s = quantize_blocks_2d(w, bits=bits, block=tile)
            blk.create_var(name=qname, shape=tuple(q.shape), dtype="int8",
                           persistable=True, stop_gradient=True)
            blk.create_var(name=sname, shape=tuple(s.shape),
                           dtype="float32", persistable=True,
                           stop_gradient=True)
            if not reuse:
                scope.set_var(qname, q)
                scope.set_var(sname, s)
                scope.erase(name)
            blk.vars.pop(name, None)

        for blk in program.blocks:
            for i, op in enumerate(blk.ops):
                if op.type == "mul":
                    wname = op.inputs["Y"][0]
                    if wname not in chosen:
                        continue
                    attrs = {"bits": bits, "x_num_col_dims":
                             op.attrs.get("x_num_col_dims", 1)}
                    if op.attrs.get("use_bf16", False):
                        attrs["use_bf16"] = True
                    new = Operator(
                        blk, "qmatmul",
                        inputs={"X": op.inputs["X"],
                                "QW": [wname + "@qparam"],
                                "Scales": [wname + "@qscale"]},
                        outputs={"Out": op.outputs["Out"]}, attrs=attrs)
                elif op.type == "lookup_table":
                    wname = op.inputs["W"][0]
                    if wname not in chosen:
                        continue
                    attrs = {"bits": bits}
                    if op.attrs.get("padding_idx") is not None:
                        attrs["padding_idx"] = op.attrs["padding_idx"]
                    new = Operator(
                        blk, "qlookup",
                        inputs={"Ids": op.inputs["Ids"],
                                "QW": [wname + "@qparam"],
                                "Scales": [wname + "@qscale"]},
                        outputs={"Out": op.outputs["Out"]}, attrs=attrs)
                else:
                    continue
                blk.ops[i] = new
                out = new.outputs["Out"][0]
                if out in blk.vars:
                    blk.vars[out].op = new
        program._bump()
        return program


# ---------------------------------------------------------------------------
# pipeline partitioning (≙ the reference's pipeline_trainer program-section
# splitting: the transpiler that cuts a program into per-device sections and
# runs them as a microbatched pipeline). The pass cuts the single
# vjp_region's forward segment into K contiguous stages balanced by the
# analytic flop/byte cost model (tools/probe_common.op_cost_flops_bytes),
# validates every boundary is a narrow activation cut, splices explicit
# `pp_send`/`pp_recv` ops at the cuts (the census-able collectives — same
# discipline as dp_grad_comm), and replaces the vjp_region with a
# `pp_pipeline_region` executed by the GPipe/1F1B schedule engine
# (parallel/pipeline.py run_pp_region).
# ---------------------------------------------------------------------------


def _pipeline_cost_fns():
    """(op_cost_flops_bytes, op_time_cost) from framework/costs.py — the
    ONE analytic cost model, shared with the probes (tools/probe_common
    re-exports it) and the predict() ledger API."""
    from .costs import op_cost_flops_bytes, op_time_cost
    return op_cost_flops_bytes, op_time_cost


def _balanced_partition(costs: List[float], k: int) -> List[Tuple[int, int]]:
    """Split `costs` into k contiguous NON-EMPTY segments minimizing the
    max segment sum (classic linear-partition DP, the 1-D special case of
    GDP's cost-modeled graph placement). Returns [start, end) pairs."""
    n = len(costs)
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + c)
    inf = float("inf")
    dp = [[inf] * (n + 1) for _ in range(k + 1)]
    cut = [[0] * (n + 1) for _ in range(k + 1)]
    dp[0][0] = 0.0
    for j in range(1, k + 1):
        for i in range(j, n - (k - j) + 1):
            best, where = inf, j - 1
            for c in range(j - 1, i):
                if dp[j - 1][c] == inf:
                    continue
                v = max(dp[j - 1][c], prefix[i] - prefix[c])
                if v < best:
                    best, where = v, c
            dp[j][i] = best
            cut[j][i] = where
    bounds = []
    i = n
    for j in range(k, 0, -1):
        c = cut[j][i]
        bounds.append((c, i))
        i = c
    bounds.reverse()
    return bounds


@register_pass("pipeline_partition_pass")
class PipelinePartitionPass(Pass):
    """Program-level pipeline partitioning. attrs:
      num_stages (K >= 2), num_microbatches, schedule ('gpipe'|'1f1b'),
      dp_axis ('' when the mesh has no data axis), reduce_dp (pmean grads
      over dp inside the region — False when the r08 dp_grad_comm pipeline
      owns the dp reduction), max_boundary_vars (narrow-cut gate),
      nominal_batch (cost-model batch stand-in for -1 dims).

    Gates (rejected, not mis-trained): multiple backward regions;
    batch-global ops (batch_norm folds statistics over the whole batch —
    per-microbatch execution would silently change them); non-MEAN losses
    (per-microbatch means average to the global mean only for equal
    microbatches of a mean-reduced loss); wide/non-float boundary cuts;
    load-bearing downstream consumers of forward activations (pipeline
    publishes only the loss + parameter gradients; pure metric-head sinks
    are pruned instead, and fetching them raises the clear error)."""

    allowed_attrs = ("num_stages", "num_microbatches", "schedule",
                     "dp_axis", "reduce_dp", "max_boundary_vars",
                     "nominal_batch")

    @staticmethod
    def _batch_led(block, name):
        try:
            v = block.var(name)
        except NotFoundError:
            return True     # undeclared sidecars (@SEQLEN) are batch-led
        shape = getattr(v, "shape", None)
        return shape is None or (bool(shape) and shape[0] == -1)

    def apply(self, program, scope=None):
        import numpy as np
        from ..parallel.grad_comm import _BATCH_GLOBAL_OPS, _MEAN_LOSS_OPS
        from ..parallel.mesh import PIPELINE_AXIS
        from ..parallel.pipeline import PP_REGION_TYPE  # registers pp ops
        from .program import Operator

        if getattr(program, "_pp_applied", False):
            return program
        K = int(self.attrs["num_stages"])
        M = int(self.attrs.get("num_microbatches", 1))
        schedule = self.attrs.get("schedule", "1f1b")
        max_bvars = int(self.attrs.get("max_boundary_vars", 8))
        enforce(K >= 2, f"pipeline_partition_pass needs num_stages >= 2, "
                f"got {K}", exc=InvalidArgumentError)

        out = program.clone()
        out._dp_comm_applied = getattr(program, "_dp_comm_applied", False)
        block = out.global_block()
        regions = [i for i, op in enumerate(block.ops)
                   if op.type == "vjp_region"]
        enforce(len(regions) == 1,
                f"pipeline partitioning supports exactly one backward "
                f"region (vjp_region), found {len(regions)}: multi-loss "
                f"programs cannot be cut into one faithful stage chain. "
                f"Run without pipeline_stages",
                exc=InvalidArgumentError)
        rop = block.ops[regions[0]]
        seg = list(rop.attrs["fwd_ops"])
        loss_name = rop.attrs["loss"]
        targets = list(rop.attrs["targets"])
        enforce(len(seg) >= K,
                f"cannot cut {len(seg)} forward ops into {K} non-empty "
                f"pipeline stages", exc=InvalidArgumentError)
        seg_ops = [block.ops[i] for i in seg]

        bad = sorted({op.type for op in seg_ops
                      if op.type in _BATCH_GLOBAL_OPS})
        enforce(not bad,
                f"pipeline execution runs the forward per-microbatch, but "
                f"ops {bad} fold statistics over the WHOLE batch and would "
                f"silently compute per-microbatch statistics instead. Run "
                f"this program without pipeline_stages",
                exc=InvalidArgumentError)
        from .analysis import op_loc
        producer = next((o for o in reversed(seg_ops)
                         if loss_name in o.output_names()), None)
        if producer is None or producer.type not in _MEAN_LOSS_OPS:
            # provenance built only on the failing path: the index scan +
            # formatting must not run on every successful apply
            desc = (op_loc(block, block.ops.index(producer), producer)
                    if producer else "<nothing>")
            enforce(False,
                    f"pipeline execution requires a MEAN-reduced loss (got "
                    f"{loss_name!r} produced by {desc}): "
                    f"per-microbatch mean losses average to the global-batch "
                    f"mean only for equal microbatches of a mean reduction. "
                    f"Reduce the loss with layers.mean / reduce_mean",
                    exc=InvalidArgumentError)

        # --- cost-balanced contiguous partition -------------------------
        cost_fn, combine = _pipeline_cost_fns()
        nb = int(self.attrs.get("nominal_batch", 8))
        costs = [combine(*cost_fn(op, block, nb)) for op in seg_ops]
        bounds = _balanced_partition(costs, K)
        stage_pos = [seg[a:b] for a, b in bounds]

        # --- boundary (cut) computation + narrow-cut validation ----------
        produced, prod_pos = {}, {}
        for k, idxs in enumerate(stage_pos):
            for i in idxs:
                for n in block.ops[i].output_names():
                    if n not in produced:
                        produced[n] = k
                        prod_pos[n] = i
        reads_by_stage = [set() for _ in range(K)]
        for k, idxs in enumerate(stage_pos):
            for i in idxs:
                reads_by_stage[k] |= set(block.ops[i].input_names())
        seg_produced = set(produced)
        ext_reads = set().union(*reads_by_stage) - seg_produced
        enforce(produced.get(loss_name) == K - 1,
                f"loss {loss_name!r} is not produced by the last stage — "
                f"partitioner bug", exc=InvalidArgumentError)

        crossings = []
        for c in range(K - 1):
            later_reads = set().union(*reads_by_stage[c + 1:])
            names = sorted((n for n, pk in produced.items()
                            if pk <= c and n in later_reads),
                           key=lambda n: prod_pos[n])
            enforce(names, f"stage cut {c} carries no activation — the "
                    f"loss would not depend on stages <= {c} "
                    f"(partitioner bug)", exc=InvalidArgumentError)
            enforce(len(names) <= max_bvars,
                    f"stage boundary {c} is not a narrow activation cut: "
                    f"{len(names)} variables would cross it "
                    f"({names[:6]}{'...' if len(names) > 6 else ''}). "
                    f"Pick a different num_stages or restructure the "
                    f"model so stage boundaries carry one activation",
                    exc=InvalidArgumentError)
            for n in names:
                v = block.var(n)
                enforce(not v.persistable,
                        f"boundary var {n!r} at cut {c} is persistable — "
                        f"state cannot cross a pipeline cut",
                        exc=InvalidArgumentError)
                enforce(np.issubdtype(np.dtype(v.dtype), np.floating),
                        f"boundary var {n!r} at cut {c} has non-float "
                        f"dtype {v.dtype}; only floating activations may "
                        f"cross a stage cut (ids/labels are feeds — they "
                        f"reach every stage directly)",
                        exc=InvalidArgumentError)
            crossings.append(names)

        # --- downstream consumers of forward activations -----------------
        # Forward values only ever exist per-microbatch on their stage's
        # device, so ops outside the region cannot read them. Pure sink
        # chains (metric heads: accuracy/top_k over the logits) are PRUNED
        # transitively — fetching their outputs raises the clear pipeline
        # error at compile (_pp_hidden). Anything load-bearing (an
        # optimize/backward-role op) reading a hidden activation cannot be
        # pruned and is rejected instead.
        hidden = set(seg_produced) - {loss_name}
        seg_set = set(seg)
        dropped_ops = set()
        for i, op in enumerate(block.ops):
            if i in seg_set or op is rop:
                continue
            bad_reads = sorted(set(op.input_names()) & hidden)
            if not bad_reads:
                continue
            from .analysis import op_loc
            enforce(op.attrs.get("op_role") not in ("optimize", "backward"),
                    f"{op_loc(block, i, op)} (role "
                    f"{op.attrs.get('op_role')!r}) reads forward "
                    f"activation(s) {bad_reads} computed inside the "
                    f"pipeline region and cannot be pruned: pipeline mode "
                    f"publishes only the loss and parameter gradients. "
                    f"Run this program without pipeline_stages",
                    exc=InvalidArgumentError)
            dropped_ops.add(id(op))
            hidden |= set(op.output_names())

        # --- splice pp_send/pp_recv at every cut -------------------------
        # both sides of a cut share one correlation id: a merged
        # cross-rank timeline (tools/trace_merge.py) pairs the sender's
        # and receiver's spans by it, so "who waited on whom" reads off
        # the matched corr_id lanes
        sends, recvs = [], []
        for c in range(K - 1):
            corr = f"ppcut-{c}-s{c}to{c + 1}"
            buf = block.create_var(name=f"pp_cut{c}@PP", shape=None,
                                   dtype="float32", stop_gradient=True)
            sends.append(Operator(
                block, "pp_send", inputs={"X": list(crossings[c])},
                outputs={"Out": [buf.name]},
                attrs={"cut": c, "corr_id": corr, "op_role": "forward"}))
            recvs.append(Operator(
                block, "pp_recv", inputs={"X": [buf.name]},
                outputs={"Out": list(crossings[c])},
                attrs={"cut": c, "corr_id": corr, "op_role": "forward"}))
        ins_by_pos: Dict[int, list] = {}
        for c in range(K - 1):
            ins_by_pos.setdefault(stage_pos[c][-1] + 1, []).append(sends[c])
            ins_by_pos.setdefault(stage_pos[c + 1][0], []).append(recvs[c])
        new_ops = []
        for i, op in enumerate(block.ops):
            # a send (insert AFTER op i-1) sorts before a recv (insert
            # BEFORE op i) at the same position: sends were appended first
            for nop in ins_by_pos.get(i, []):
                new_ops.append(nop)
            if id(op) not in dropped_ops:
                new_ops.append(op)

        stage_objs = []
        for k in range(K):
            objs = ([recvs[k - 1]] if k > 0 else []) \
                + [block.ops[i] for i in stage_pos[k]] \
                + ([sends[k]] if k < K - 1 else [])
            stage_objs.append(objs)
        newidx = {id(op): i for i, op in enumerate(new_ops)}
        stage_idx_lists = [[newidx[id(o)] for o in objs]
                           for objs in stage_objs]

        # --- replace the vjp_region with the pipeline region -------------
        x_names = sorted(ext_reads | set(targets))
        batch_led = [n for n in x_names
                     if n not in set(targets) and self._batch_led(block, n)]
        region = Operator(
            block, PP_REGION_TYPE,
            inputs={"X": x_names},
            outputs={"Grads": list(rop.outputs["Grads"]),
                     "LossGrad": list(rop.outputs["LossGrad"])},
            attrs={"fwd_ops": sorted(i for lst in stage_idx_lists
                                     for i in lst),
                   "stages": stage_idx_lists,
                   "num_stages": K, "num_microbatches": M,
                   "schedule": schedule, "axis": PIPELINE_AXIS,
                   "dp_axis": self.attrs.get("dp_axis", ""),
                   "reduce_dp": bool(self.attrs.get("reduce_dp", False)),
                   "targets": targets, "loss": loss_name,
                   "x_names": x_names, "batch_led": batch_led,
                   "stage_costs": [float(sum(costs[a:b]))
                                   for a, b in bounds],
                   "op_role": "backward"})
        new_ops[newidx[id(rop)]] = region
        block.ops = new_ops

        out._bump()
        out._pp_applied = True
        out._pp_hidden = frozenset(hidden)
        out._pp_microbatches = M
        out._pp_stages = K
        return out


def apply_fusion_passes(program: Program, protected=()) -> Program:
    """Executor-compile-time entry: apply the flag-enabled fusion passes to
    a CLONE of `program` (the caller's program is never mutated). Returns
    the original program untouched when the flags are off or nothing can
    match — the common case costs one cheap op-type scan."""
    from ..core import flags
    do_rnn = flags.get_flag("fuse_recurrent_cells")
    do_dec = flags.get_flag("fuse_decode_attention")
    if not (do_rnn or do_dec):
        return program
    has_rnn = has_dec = False
    for blk in program.blocks:
        has_vjp = any(op.type in ("vjp_region", "pp_pipeline_region")
                      for op in blk.ops)
        for op in blk.ops:
            if op.type in ("dynamic_lstm", "dynamic_gru"):
                has_rnn = True
            elif op.type == "softmax" and not has_vjp:
                has_dec = True
    if not ((do_rnn and has_rnn) or (do_dec and has_dec)):
        return program
    rewritten = program.clone()
    if do_rnn and has_rnn:
        get_pass("fuse_recurrent_cell_pass")(rewritten)
    if do_dec and has_dec:
        get_pass("fuse_decode_attention_pass",
                 protected=sorted(protected))(rewritten)
    return rewritten


class Analyzer:
    """Ordered pass manager preparing a trained program for serving
    (≙ inference/analysis/analyzer.h:53 running its pass pipeline over the
    data-flow graph; TensorRT-subgraph slots are XLA's job here).

        program = Analyzer(passes=["bn_fold_pass", "quant_freeze_pass"]) \
            .run(program, scope)
    """

    DEFAULT_PASSES = ["bn_fold_pass"]

    def __init__(self, passes: Optional[List[str]] = None, **pass_attrs):
        self.pass_names = list(passes or self.DEFAULT_PASSES)
        self.pass_attrs = pass_attrs

    def run(self, program: Program, scope: Optional[Scope] = None,
            targets=None) -> Program:
        scope = scope or global_scope()
        if targets is not None:
            program = get_pass("prune_pass", targets=targets)(program, scope)
        for name in self.pass_names:
            attrs = self.pass_attrs.get(name, {})
            program = get_pass(name, **attrs)(program, scope)
        return program


@register_pass("check_pass")
class CheckPass(Pass):
    """Validate program well-formedness before execution (≙ the
    multi_devices_check_pass + ir::HasCircle asserts the reference applies
    at parallel_executor.cc:91 / multi_devices_graph_pass.cc:465).

    Folded into the static analyzer: this is now a thin alias over
    `framework.analysis.verify_program` (def-before-use, duplicate-writer
    hazards, attribute schemas, pipeline/dp-comm invariants), kept
    registered so Analyzer(passes=["check_pass"]) callers and existing
    tests keep working. Raises NotFoundError with the full violation list,
    every line carrying block/op#/op.type provenance."""

    allowed_attrs = ("extra_feeds",)

    def apply(self, program, scope=None):
        from .analysis import verify_program
        problems = [d for d in verify_program(
            program, extra_feeds=self.attrs.get("extra_feeds", ()))
            if d.severity == "error"]
        if problems:
            raise NotFoundError(
                "program check failed:\n  "
                + "\n  ".join(str(d) for d in problems))
        return program
