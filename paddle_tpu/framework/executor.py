"""Executor: compile-and-run programs on TPU.

Capability equivalent of the reference's Executor (reference:
paddle/fluid/framework/executor.cc:125,221 + python/paddle/fluid/executor.py:256).
Where the reference *interprets* a ProgramDesc op-by-op, this executor traces
the whole global block into one jax function (lowering.py) and XLA-compiles it,
caching executables keyed by (program version, feed signature, fetch list) —
the analogue of the reference's Prepare/RunPreparedContext caching
(executor.cc:294,321) but with whole-program fusion.

State handling is functional: persistable variables (parameters, optimizer
accumulators, counters) are inputs AND outputs of the compiled step; updated
values are written back to the Scope after each run. Buffers for read+written
state are donated to XLA so parameter updates are in-place on device.
"""

from __future__ import annotations

import operator
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flags
from ..core.enforce import InvalidArgumentError, NotFoundError, enforce
from ..core.places import Place, default_place
from .lowering import LowerCtx, build_plan, run_plan
from .program import (BATCH_ROW_MASK_NAME, Program, Variable,
                      default_main_program)
from .scope import Scope, global_scope


def _fusion_flags_key():
    """Flags that are inputs to compilation (apply_fusion_passes and the
    grad-comm rewrite read them at compile time): they must be part of the
    compile-cache key or toggling a kill switch at runtime would silently
    keep serving the previously compiled variant."""
    return (flags.get_flag("fuse_recurrent_cells"),
            flags.get_flag("fuse_decode_attention"),
            flags.get_flag("quant_comm"),
            flags.get_flag("quant_params"),
            flags.get_flag("pipeline"),
            flags.get_flag("tp_shard"),
            flags.get_flag("memory_plan"),
            flags.get_flag("auto_parallel"),
            # kv_sanitize rewrites nothing today (the shadow bookkeeping
            # is pure host-side), but the kill switch joins the key so a
            # toggled run can never share cached compiled state with its
            # instrumented twin
            flags.get_flag("kv_sanitize"))


def _feed_signature(feed: Dict[str, Any]):
    return tuple(sorted((k, tuple(np.shape(v)), str(np.asarray(v).dtype) if not
                         hasattr(v, "dtype") else str(v.dtype))
                        for k, v in feed.items()))


def as_numpy(x):
    return np.asarray(x)


class _CompiledStep:
    def __init__(self, fn, ro_names, rw_names, feed_names, fetch_names):
        self.fn = fn
        self.ro_names = ro_names
        self.rw_names = rw_names
        self.feed_names = feed_names
        self.fetch_names = fetch_names


_jit_cache_configured = []


def _configure_jit_cache():
    """Wire the PTPU_JIT_CACHE flag into jax's persistent compilation
    cache (once): compiled XLA executables survive process restarts, which
    on TPU turns 20-40s first compiles into millisecond cache loads."""
    if _jit_cache_configured:
        return
    _jit_cache_configured.append(True)
    path = flags.get_flag("jit_cache")
    if not path:
        return
    import os
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


class PreparedStep:
    """Bound (program, feed-signature, fetch, scope) handle with the
    per-call dispatch overhead stripped: no fetch validation, no feed
    signature hashing, no cache lookup, no batch-mask synthesis — those
    were all paid once in Executor.prepare. ≙ the reference's
    Prepare/RunPreparedContext split (executor.cc:294,321), whose whole
    point is hoisting per-run setup out of a hot serve loop; here the hot
    loop is the serving engine's decode tick, where the Python dispatch
    path IS the measured overhang (tools/probe_gap.py `host_dispatch`).

    State contract matches Executor.run: read-write persistable state is
    donated to XLA and written back to the scope after each call; the
    RNG seed follows the same (program.random_seed, run counter) stream,
    and feed keys prepare() synthesized beyond the caller's example
    (the reserved @batch_row_mask) are re-injected per call."""

    __slots__ = ("_compiled", "_scope", "_owner", "_random_seed",
                 "_injected", "_b_feed_vals", "_b_ro_vals", "_b_rw_vals",
                 "_b_rw_pick", "_b_state_names", "_b_scope_vars",
                 "_b_seed_base")

    def __init__(self, compiled, scope, owner, random_seed, injected):
        self._compiled = compiled
        self._scope = scope
        self._owner = owner
        self._random_seed = random_seed
        self._injected = injected      # name -> constant value (batch mask)
        self._b_rw_vals = None         # set by bind(): zero-dispatch state

    @property
    def fetch_names(self):
        return list(self._compiled.fetch_names)

    def run(self, feed, return_numpy=False):
        """feed: dict with EXACTLY the prepared names/shapes/dtypes (not
        re-validated — a drifted signature recompiles via jit's own shape
        check or fails inside XLA). Returns the fetch list (jax arrays
        unless return_numpy)."""
        compiled = self._compiled
        scope = self._scope
        injected = self._injected
        feed_vals = tuple(
            jnp.asarray(feed[n] if n in feed else injected[n])
            for n in compiled.feed_names)
        ro_vals = tuple(scope.get(n) for n in compiled.ro_names)
        rw_vals = tuple(scope.get(n) for n in compiled.rw_names)
        self._owner._run_counter += 1
        seed = np.uint32((self._random_seed * 1000003
                          + self._owner._run_counter) % (2 ** 31))
        fetches, new_state = compiled.fn(feed_vals, ro_vals, rw_vals, seed)
        for name, val in zip(compiled.state_out_names, new_state):
            scope.set_var(name, val)
        if self._b_rw_vals is not None:
            # a bound tick coexists with plain runs (paged_beam_search
            # drives the same compiled step through run()): the donated rw
            # buffers the binding held are dead now, so re-point it at the
            # state this call just produced
            self._b_rw_vals = self._b_rw_pick(new_state)
        if return_numpy:
            return [as_numpy(f) for f in fetches]
        return list(fetches)

    def bind(self, feed):
        """One-time setup of the zero-dispatch tick: capture the caller's
        feed buffers (the serving engine mutates them in place between
        ticks), pin the read-only state straight out of the scope, and
        precompute everything run() recomputes per call — the argument
        tuples, the rw<-new_state selection, and the seed stream base.
        After bind(), run_bound() is the hot path: no dict probes, no
        per-name scope lookups, no tuple-comprehension rebuilds.

        Contract: `feed` must hold the EXACT arrays fed forever after
        (mutate them in place; rebinding is required if they are
        replaced), and read-only persistables are pinned at bind time —
        swap weights in the scope -> bind() again."""
        compiled = self._compiled
        injected = self._injected
        scope = self._scope
        self._b_feed_vals = tuple(
            feed[n] if n in feed else injected[n]
            for n in compiled.feed_names)
        self._b_ro_vals = tuple(scope.get(n) for n in compiled.ro_names)
        self._b_rw_vals = tuple(scope.get(n) for n in compiled.rw_names)
        self._b_state_names = tuple(compiled.state_out_names)
        idx = tuple(compiled.state_out_names.index(n)
                    for n in compiled.rw_names)
        if len(idx) == 1:
            i0 = idx[0]
            self._b_rw_pick = lambda s, _i=i0: (s[_i],)
        elif idx:
            self._b_rw_pick = operator.itemgetter(*idx)
        else:
            self._b_rw_pick = lambda s: ()
        # Scope.set_var is a bare dict store; write the same dict directly
        # so the per-tick write-back is one store per state var, no method
        # dispatch (shadowing semantics identical to set_var)
        self._b_scope_vars = scope._vars
        self._b_seed_base = self._random_seed * 1000003
        return self

    def refresh_state(self):
        """Re-point the bound rw state at the scope's CURRENT arrays.

        Two bound steps sharing read-write state (the serving engine's
        plain decode tick and the speculative verify forward both own the
        target KV caches) each hold the donated buffers from their own
        last call — after step A writes the scope, step B's held tuple is
        stale (and donated-dead). Call this on B before run_bound() when A
        ran in between. No-op cost is len(rw_names) dict probes, so the
        single-step steady state stays zero-dispatch by simply not calling
        it."""
        if self._b_rw_vals is not None:
            scope = self._scope
            self._b_rw_vals = tuple(
                scope.get(n) for n in self._compiled.rw_names)
        return self

    def run_bound(self):
        """The zero-dispatch steady-state tick over the buffers captured by
        bind(): donated rw state threads call-to-call through a precomputed
        selector, feeds are the caller's in-place-mutated arrays, and the
        scope write-back is a raw dict store per state var. Returns the
        fetch tuple (jax arrays)."""
        compiled = self._compiled
        owner = self._owner
        owner._run_counter += 1
        seed = np.uint32((self._b_seed_base + owner._run_counter)
                         % 2147483648)
        fetches, new_state = compiled.fn(self._b_feed_vals, self._b_ro_vals,
                                         self._b_rw_vals, seed)
        self._b_rw_vals = self._b_rw_pick(new_state)
        sv = self._b_scope_vars
        for name, val in zip(self._b_state_names, new_state):
            sv[name] = val
        return fetches


class Executor:
    """≙ fluid.Executor (reference python/paddle/fluid/executor.py:256)."""

    def __init__(self, place: Optional[Place] = None):
        _configure_jit_cache()
        self.place = place or default_place()
        self._cache: Dict[Any, _CompiledStep] = {}
        self._persistable_cache: Dict[Any, list] = {}
        self._run_counter = 0

    # -- compilation ------------------------------------------------------
    def _scope_avail_key(self, program: Program, scope: Scope):
        pv = self._persistable_cache.get((id(program), program._version))
        if pv is None:
            pv = sorted({v.name for b in program.blocks
                         for v in b.vars.values() if v.persistable})
            self._persistable_cache[(id(program), program._version)] = pv
        return tuple(n for n in pv if scope.has_var(n))

    def _analyze_state(self, program: Program, scope: Scope, feed_names,
                       fetch_names):
        block = program.global_block()
        read, written = set(), set()
        for op in block.ops:
            read |= set(op.input_names())
            written |= set(op.output_names())
        referenced = read | written | set(fetch_names)
        persistable = {v.name for b in program.blocks
                       for v in b.vars.values() if v.persistable}
        feed_set = set(feed_names)
        state_in = sorted(n for n in persistable
                          if n in referenced and scope.has_var(n)
                          and n not in feed_set)
        state_written = sorted(n for n in persistable if n in written)
        rw = sorted(set(state_in) & set(state_written))
        ro = sorted(set(state_in) - set(rw))
        out_only = sorted(set(state_written) - set(state_in))
        return ro, rw, out_only

    def _build_step_fn(self, program: Program, feed_names, fetch_names,
                       ro, rw, state_out_names):
        """The pure per-step function both the single-step compile and the
        scan-fused run_steps build on."""
        # operator fusion (fused recurrent cells / decode attention): a
        # compile-time rewrite of a CLONE of the program, gated by the
        # default-on fuse_* flags (kill switch PTPU_FUSE_*=0). The caller's
        # program and the compile-cache key (original program version) are
        # untouched — the rewrite is deterministic per version.
        from .passes import apply_fusion_passes
        program = apply_fusion_passes(
            program, protected=set(fetch_names) | set(state_out_names))
        block = program.global_block()
        plan = build_plan(block)
        fetch_names = list(fetch_names)
        feed_names = list(feed_names)

        def step(feed_vals, ro_vals, rw_vals, seed):
            # fetch_names ride along so live-out-narrowed vjp regions
            # (transpiler.memory_optimize) never drop a fetch target
            ctx = LowerCtx(rng_key=jax.random.PRNGKey(seed),
                           extras={"program": program,
                                   "fetch_names": tuple(fetch_names)})
            env: Dict[str, Any] = {}
            env.update(zip(ro, ro_vals))
            env.update(zip(rw, rw_vals))
            for name, val in zip(feed_names, feed_vals):
                # byte-lean staging: a data var declared with a staging
                # dtype may be fed compact (e.g. uint8); de-quantize on
                # device so only wire_dtype bytes cross the host->device
                # link (≙ reference buffered_reader.h:27 whose job is
                # keeping the device fed)
                var = block.vars.get(name)
                if (var is not None and var.staging is not None
                        and hasattr(val, "dtype")
                        and str(val.dtype) != str(var.dtype)):
                    # de-quantize ONLY the declared wire dtype; any other
                    # mismatch is a caller bug and silently scaling it
                    # (e.g. int32 ones -> 0.0039) would corrupt the feed.
                    # float64 is exempt: jnp.asarray canonicalizes it to
                    # float32 before the step ever sees it.
                    if str(val.dtype) != str(var.staging[0]):
                        raise TypeError(
                            f"feed '{name}' has dtype {val.dtype} but the "
                            f"var is declared {var.dtype} with staging "
                            f"dtype {var.staging[0]}; feed either of those")
                    val = val.astype(var.dtype)
                    if var.staging[1] is not None:
                        val = val * jnp.asarray(var.staging[1], var.dtype)
                env[name] = val
            run_plan(plan, env, block, ctx)
            fetches = tuple(env[n] for n in fetch_names)
            new_state = tuple(env[n] for n in state_out_names)
            return fetches, new_state

        return step

    def _prepare_program(self, program: Program, scope: Scope) -> Program:
        """Hook: executor-level program rewrite before state analysis and
        tracing. ParallelExecutor applies the explicit gradient-comm rewrite
        here (parallel/grad_comm.py); the base executor is a no-op. MUST be
        idempotent — both _compile and run_steps call it."""
        return program

    def _stash_flops_estimate(self, compiled: _CompiledStep, program,
                              feed=None):
        """Cache the analytic per-STEP model flops on the compiled step
        for the `ptpu_mfu` gauge — a Python op walk, negligible next to
        the XLA compile. Batch dims resolve to the fed batch when a feed
        signature is at hand (self._feed_shapes is stashed by run()
        callers before compiling)."""
        shapes = (dict(getattr(self, "_feed_shapes", {}) or {}) if feed
                  is None else {n: np.shape(v) for n, v in feed.items()})
        batch = max((s[0] for s in shapes.values() if len(s) >= 1),
                    default=8)
        from .costs import program_flops_bytes
        try:
            compiled.flops_estimate = program_flops_bytes(
                program, nominal_batch=int(batch))["flops"]
        except Exception:
            compiled.flops_estimate = 0.0

    def _note_run_memory(self, compiled: _CompiledStep, step_s: float,
                         steps: int = 1):
        """Per-run memory/utilization sample: the device-state watermark
        (per-device bytes censused once per compiled step) and the
        `ptpu_mfu` gauge — predicted PER-DEVICE model flops (whole-step
        flops over the device count) over the dispatch-window wall time.
        Under donated-state backpressure successive dispatches track
        true step time; tools/benchmark.py rows carry the
        blocked-measured figure. O(1) per run."""
        from ..observability import memory as _memory
        sb = getattr(compiled, "census_state_bytes", None)
        if sb is not None:
            _memory.update_watermark("device_state_bytes", sb)
        flops = getattr(compiled, "flops_estimate", 0.0)
        # the dispatch window only tracks true step time when donated
        # rw state backpressures successive dispatches — an rw-less
        # (inference) step returns in dispatch time and would publish a
        # meaningless (even >1) utilization. Likewise skip the FIRST
        # window per compiled step: it reads warm-up, not steady state.
        if flops and step_s > 0 and compiled.rw_names:
            if getattr(compiled, "_mfu_warm", False):
                ndev = max(1, int(getattr(self, "device_count", 1)))
                _memory.note_mfu(flops * steps / ndev, step_s)
            else:
                compiled._mfu_warm = True

    def _compile(self, program: Program, scope: Scope, feed_names, fetch_names,
                 in_shardings=None, out_shardings=None, analysis=None):
        program = self._prepare_program(program, scope)
        ro, rw, out_only = analysis or self._analyze_state(
            program, scope, feed_names, fetch_names)
        state_out_names = sorted(set(rw) | set(out_only))
        fetch_names = list(fetch_names)
        feed_names = list(feed_names)
        step = self._build_step_fn(program, feed_names, fetch_names, ro, rw,
                                   state_out_names)

        flags.vlog(1, "compiling program id=%s version=%s feeds=%s "
                   "fetches=%s", id(program), program._version,
                   list(feed_names), list(fetch_names))
        jit_kwargs: Dict[str, Any] = {"donate_argnums": (2,)}
        if in_shardings is not None:
            jit_kwargs["in_shardings"] = in_shardings
        if out_shardings is not None:
            jit_kwargs["out_shardings"] = out_shardings
        fn = jax.jit(step, **jit_kwargs)
        compiled = _CompiledStep(fn, ro, rw, feed_names, fetch_names)
        compiled.state_out_names = state_out_names
        self._stash_flops_estimate(compiled, program)
        return compiled

    def _scan_shardings(self, program, feed_names, fetch_names, ro, rw,
                        state_out_names):
        """Hook for subclasses (ParallelExecutor) to shard the scan-fused
        run_steps executable; None = let jax place everything locally."""
        return None

    def _place_feed_stack(self, program, name, vals):
        """Hook: stack K per-step feed values for run_steps. Subclasses
        override to place the stack on a (possibly cross-process) mesh."""
        return jnp.stack([jnp.asarray(v) for v in vals])

    def _validate_fetches(self, program: Program, feed, fetch_names):
        block = program.global_block()
        defined = set(feed)
        for op in block.ops:
            defined.update(op.output_names())
        for name in fetch_names:
            if name not in defined and not block.has_var(name):
                raise NotFoundError(
                    f"fetch target {name!r} is not produced by the program "
                    f"and not fed")

    _isfinite_all_jit = None

    def _sweep_nonfinite(self, pairs, hint: str):
        """Raise FloatingPointError if any floating value in (name, value)
        pairs is non-finite. For global non-fully-addressable arrays
        (multi-process worlds) the check is a tiny jitted SPMD reduction
        that EVERY process executes and whose replicated result every
        process reads — so all processes reach the same verdict and raise
        together, instead of one process raising while its peers block in
        the next step's collectives."""
        cls = type(self)
        for name, val in pairs:
            if not (hasattr(val, "dtype")
                    and jnp.issubdtype(val.dtype, jnp.floating)):
                continue
            if getattr(val, "is_fully_addressable", True):
                ok = bool(jnp.isfinite(val).all())
            else:
                if cls._isfinite_all_jit is None:
                    cls._isfinite_all_jit = jax.jit(
                        lambda a: jnp.isfinite(a).all())
                ok = bool(cls._isfinite_all_jit(val))
            if not ok:
                raise FloatingPointError(
                    f"NaN/Inf detected in {name!r} (fetch-time sweep; "
                    f"{hint})")

    def _synthesize_batch_mask(self, program: Program,
                               feed: Dict[str, Any]) -> Dict[str, Any]:
        """If the program declares the reserved batch-row-mask data var
        (layers.batch_row_mask) and the caller didn't feed it, feed all-ones
        of the batch length: every row of a directly-run batch is real.
        ParallelExecutor overrides the synthesized value with zeros on rows
        it pads for dp divisibility."""
        block = program.global_block()
        if (BATCH_ROW_MASK_NAME not in block.vars
                or BATCH_ROW_MASK_NAME in feed):
            return feed
        bs = None
        for v in feed.values():
            if np.ndim(v) >= 1:
                bs = np.shape(v)[0]
                break
        if bs is not None:
            feed[BATCH_ROW_MASK_NAME] = np.ones((bs,), np.float32)
        return feed

    def _lookup_or_compile(self, program: Program, feed: Dict[str, Any],
                           fetch_names, scope: Scope) -> _CompiledStep:
        """Validate fetch targets and return the cached compiled step for
        (program, feed signature, fetches, scope contents), compiling on
        miss. The cache key includes which persistable vars currently exist
        in the scope: compiling before the startup program ran must not
        poison the cache for post-initialization runs."""
        self._validate_fetches(program, feed, fetch_names)
        avail_key = self._scope_avail_key(program, scope)
        key = (id(program), program._version, _feed_signature(feed),
               tuple(fetch_names), id(scope), avail_key,
               _fusion_flags_key())
        compiled = self._cache.get(key)
        if compiled is None:
            # feed shapes inform the flops estimate's batch resolution
            # (and ParallelExecutor's feed shardings, which stash the
            # same dict in run()); keep them current for this compile
            self._feed_shapes = {n: np.shape(v) for n, v in feed.items()}
            from ..observability import tracing as _tracing
            with _tracing.span("compile", "executor/trace_and_compile",
                               program_version=program._version,
                               n_fetches=len(fetch_names)):
                compiled = self._compile(program, scope, list(feed.keys()),
                                         fetch_names)
            self._cache[key] = compiled
        return compiled

    # -- execution --------------------------------------------------------
    def run(self,
            program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence[Union[str, Variable]]] = None,
            scope: Optional[Scope] = None,
            return_numpy: bool = True):
        """≙ Executor.run (reference executor.py:374-473). Missing fetch vars
        raise; feed arrays are validated against declared var dtypes."""
        program = program or default_main_program()
        feed = self._synthesize_batch_mask(program, dict(feed or {}))
        fetch_list = list(fetch_list or [])
        scope = scope or global_scope()
        fetch_names = [f.name if isinstance(f, Variable) else f
                       for f in fetch_list]

        from .. import profiler as _prof
        from ..observability import tracing as _tracing
        compiled = self._lookup_or_compile(program, feed, fetch_names, scope)

        with _tracing.span("feed_fetch", "executor/feed",
                           n_feeds=len(compiled.feed_names)):
            feed_vals = tuple(jnp.asarray(feed[n])
                              for n in compiled.feed_names)
            ro_vals = tuple(scope.get(n) for n in compiled.ro_names)
            rw_vals = tuple(scope.get(n) for n in compiled.rw_names)
        if getattr(compiled, "census_state_bytes", None) is None:
            # state shapes/placements are pinned by the compile: census
            # the per-device bytes ONCE, before the rw buffers are
            # donated, so the per-run watermark update is O(1)
            from ..observability.memory import per_device_bytes
            compiled.census_state_bytes = sum(
                per_device_bytes(v) for v in ro_vals + rw_vals)
        self._run_counter += 1
        seed = np.uint32((program.random_seed * 1000003 + self._run_counter)
                         % (2 ** 31))

        t0 = time.time()
        with _tracing.span("step", "executor/run",
                           program_version=program._version):
            fetches, new_state = compiled.fn(feed_vals, ro_vals, rw_vals, seed)
            if _prof.profiler_enabled():
                jax.block_until_ready(fetches)
        if flags.get_flag("check_nan_inf") and jax.default_backend() != "cpu":
            # TPU fallback for the in-graph nan guard (which needs host
            # callbacks and so no-ops off-CPU, lowering.py _nan_guard):
            # sweep every fetch and updated state for non-finite values
            # BEFORE the scope write-back, so the last-good parameters stay
            # checkpointable when the step diverges. Coarser than the per-op
            # guard — it names WHICH var went bad but not which op; rerun
            # under JAX_PLATFORMS=cpu to localize. ≙ reference
            # CheckTensorNANOrInf (framework/operator.cc:726-736).
            self._sweep_nonfinite(
                list(zip(compiled.fetch_names, fetches)) +
                list(zip(compiled.state_out_names, new_state)),
                "rerun under JAX_PLATFORMS=cpu with PTPU_CHECK_NAN_INF=1 "
                "to localize the op")
        with _tracing.span("feed_fetch", "executor/state_writeback",
                           n_state=len(compiled.state_out_names)):
            for name, val in zip(compiled.state_out_names, new_state):
                scope.set_var(name, val)
        self._note_run_memory(compiled, time.time() - t0)
        if flags.get_flag("benchmark"):
            jax.block_until_ready(fetches)
            print(f"[benchmark] program run took {time.time() - t0:.4f}s")
        if return_numpy:
            return [as_numpy(f) for f in fetches]
        return list(fetches)

    def run_steps(self,
                  feed_list: Sequence[Dict[str, Any]],
                  fetch_list: Optional[Sequence[Union[str, Variable]]] = None,
                  program: Optional[Program] = None,
                  scope: Optional[Scope] = None,
                  return_numpy: bool = True):
        """Run len(feed_list) train steps as ONE compiled XLA execution
        (lax.scan over the stacked feeds): the in-graph training loop.

        ≙ the reference's py_reader-driven executor loop (reference
        layers/io.py:474 + executor hot loop), where the device consumes a
        queue without a Python round-trip per step. On a remote/tunneled
        device this amortizes every per-call cost; on any device it lets
        XLA overlap adjacent steps' host interaction.

        All feeds must share one signature. Returns a list over
        fetch_list of arrays STACKED over steps (e.g. the per-step loss
        curve). Updated persistable state is written back once, from the
        final step.
        """
        program = program or default_main_program()
        feed_list = [self._synthesize_batch_mask(program, dict(f))
                     for f in feed_list]
        enforce(len(feed_list) >= 1, "run_steps needs at least one feed",
                exc=InvalidArgumentError)
        sig0 = _feed_signature(feed_list[0])
        for f in feed_list[1:]:
            enforce(_feed_signature(f) == sig0,
                    "run_steps feeds must share one signature "
                    "(same names, shapes, dtypes)",
                    exc=InvalidArgumentError)
        scope = scope or global_scope()
        fetch_names = [f.name if isinstance(f, Variable) else f
                       for f in (fetch_list or [])]

        k = len(feed_list)
        program = self._prepare_program(program, scope)
        self._validate_fetches(program, feed_list[0], fetch_names)
        avail_key = self._scope_avail_key(program, scope)
        key = ("scan", k, id(program), program._version, sig0,
               tuple(fetch_names), id(scope), avail_key,
               _fusion_flags_key())
        compiled = self._cache.get(key)
        if compiled is None:
            ro, rw, out_only = self._analyze_state(
                program, scope, list(feed_list[0].keys()), fetch_names)
            state_out_names = sorted(set(rw) | set(out_only))
            feed_names = list(feed_list[0].keys())
            step = self._build_step_fn(program, feed_names, fetch_names,
                                       ro, rw, state_out_names)
            rw_idx = {n: state_out_names.index(n) for n in rw}
            oo_idx = {n: state_out_names.index(n) for n in out_only}

            def loop(feed_stacks, ro_vals, rw_vals, seed):
                def body(carry, xs):
                    rw_vals, i = carry
                    fetches, new_state = step(xs, ro_vals, rw_vals,
                                              seed + i)
                    new_rw = tuple(new_state[rw_idx[n]] for n in rw)
                    # only the write-only slots ride the stacked ys — the
                    # big read-write state (params, accumulators) stays in
                    # the carry so the loop holds ONE copy, not K
                    oo = tuple(new_state[oo_idx[n]] for n in out_only)
                    return (new_rw, i + 1), (fetches, oo)

                (rw_final, _), (fetches, oo_stack) = jax.lax.scan(
                    body, (rw_vals, jnp.uint32(0)), feed_stacks)
                by_name = dict(zip(rw, rw_final))
                by_name.update({n: s[-1] for n, s in zip(out_only,
                                                         oo_stack)})
                final_state = tuple(by_name[n] for n in state_out_names)
                return fetches, final_state

            # donation/aliasing hints: rw state is always donated; a
            # memory-PLANNED program additionally donates the stacked
            # feeds — _place_feed_stack materializes a fresh stack every
            # call (jnp.stack / device_put of host values), so XLA may
            # fold the feed buffers into its temp arena for the planned
            # step without invalidating anything the caller holds
            jit_kwargs: Dict[str, Any] = {
                "donate_argnums": ((0, 2) if getattr(
                    program, "_memory_plan_applied", False) else (2,))}
            scan_sh = self._scan_shardings(program, feed_names, fetch_names,
                                           ro, rw, state_out_names)
            if scan_sh is not None:
                jit_kwargs["in_shardings"] = scan_sh[0]
                jit_kwargs["out_shardings"] = scan_sh[1]
            fn = jax.jit(loop, **jit_kwargs)
            compiled = _CompiledStep(fn, ro, rw,
                                     list(feed_list[0].keys()), fetch_names)
            compiled.state_out_names = state_out_names
            self._stash_flops_estimate(compiled, program,
                                       feed=feed_list[0])
            self._cache[key] = compiled

        feed_stacks = tuple(
            self._place_feed_stack(program, n, [f[n] for f in feed_list])
            for n in compiled.feed_names)
        ro_vals = tuple(scope.get(n) for n in compiled.ro_names)
        rw_vals = tuple(scope.get(n) for n in compiled.rw_names)
        # k seeds are consumed (seed+0 .. seed+k-1): advance the counter by
        # k so neither the next run_steps nor a plain run() reuses them
        seed = np.uint32((program.random_seed * 1000003
                          + self._run_counter + 1) % (2 ** 31))
        self._run_counter += k
        if getattr(compiled, "census_state_bytes", None) is None:
            from ..observability.memory import per_device_bytes
            compiled.census_state_bytes = sum(
                per_device_bytes(v) for v in ro_vals + rw_vals)
        from ..observability import tracing as _tracing
        t0 = time.time()
        with _tracing.span("step", "executor/run_steps", steps=k):
            fetches, final_state = compiled.fn(feed_stacks, ro_vals, rw_vals,
                                               seed)
        if flags.get_flag("check_nan_inf") and jax.default_backend() != "cpu":
            # same contract as run(): sweep BEFORE the scope write-back so
            # the last-good parameters stay checkpointable when a step in
            # the fused window diverges
            self._sweep_nonfinite(
                list(zip(compiled.fetch_names, fetches)) +
                list(zip(compiled.state_out_names, final_state)),
                "rerun the window step-by-step under JAX_PLATFORMS=cpu "
                "with PTPU_CHECK_NAN_INF=1 to localize")
        for name, val in zip(compiled.state_out_names, final_state):
            scope.set_var(name, val)
        self._note_run_memory(compiled, time.time() - t0, steps=k)
        if return_numpy:
            return [as_numpy(f) for f in fetches]
        return list(fetches)

    def prepare(self,
                program: Optional[Program] = None,
                feed: Optional[Dict[str, Any]] = None,
                fetch_list: Optional[Sequence[Union[str, Variable]]] = None,
                scope: Optional[Scope] = None) -> "PreparedStep":
        """Compile (or fetch from cache) the step for this exact
        (program, feed signature, fetch list, scope) and return a
        PreparedStep whose run() skips every per-call setup cost.

        `feed` is an EXAMPLE feed carrying the signature (names, shapes,
        dtypes) every later PreparedStep.run call must match."""
        program = program or default_main_program()
        user_names = set(feed or {})
        feed = self._synthesize_batch_mask(program, dict(feed or {}))
        fetch_names = [f.name if isinstance(f, Variable) else f
                       for f in (fetch_list or [])]
        scope = scope or global_scope()
        compiled = self._lookup_or_compile(program, feed, fetch_names, scope)
        # keys synthesize added beyond the caller's example feed (the
        # reserved @batch_row_mask) become per-call constants: the batch
        # size is pinned by the prepared signature, so the all-ones mask
        # is too
        injected = {n: jnp.asarray(v) for n, v in feed.items()
                    if n not in user_names}
        return PreparedStep(compiled, scope, self, program.random_seed,
                            injected)

    def _aot_compiled(self, compiled: _CompiledStep, feed, scope):
        """The AOT `lower().compile()` twin of a cached step, memoized on
        it: the object that exposes XLA's cost_analysis / memory_analysis
        / as_text. The AOT path bypasses the jit executable cache, so
        without the memo every analysis call would pay a full XLA
        compile. Feed names absent from `feed` fall back to scope values
        (the bench tools' convention)."""
        aot = getattr(compiled, "aot_cache", None)
        if aot is None:
            feed_vals = tuple(
                jnp.asarray(feed[n]) if n in feed else scope.get(n)
                for n in compiled.feed_names)
            ro_vals = tuple(scope.get(n) for n in compiled.ro_names)
            rw_vals = tuple(scope.get(n) for n in compiled.rw_names)
            aot = compiled.fn.lower(feed_vals, ro_vals, rw_vals,
                                    np.uint32(0)).compile()
            compiled.aot_cache = aot
        return aot

    def cost_analysis(self, program=None, feed=None, fetch_list=None,
                      scope=None):
        """XLA cost analysis (flops, bytes accessed) of the compiled step for
        the given (program, feed, fetch) — the evidence the reference
        publishes next to its benchmark tables (reference
        benchmark/README.md:33). Compiles if not already cached."""
        program = program or default_main_program()
        feed = dict(feed or {})
        scope = scope or global_scope()
        fetch_names = [f.name if isinstance(f, Variable) else f
                       for f in (fetch_list or [])]
        compiled = self._lookup_or_compile(program, feed, fetch_names, scope)
        ca = getattr(compiled, "cost_analysis_cache", None)
        if ca is None:
            ca = self._aot_compiled(compiled, feed, scope).cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            compiled.cost_analysis_cache = ca
        return ca

    def memory_analysis(self, program=None, feed=None, fetch_list=None,
                        scope=None):
        """Measured per-device memory of the compiled step from the XLA
        executable's buffer assignment: argument / output / temp / alias
        bytes (`observability.memory.executable_memory`, with the
        documented HLO liveness-walk fallback when the backend reports a
        zero temp figure). Compiles (AOT, memoized) if needed; updates
        the `executor_temp_bytes` watermark with what it measured."""
        program = program or default_main_program()
        feed = dict(feed or {})
        scope = scope or global_scope()
        fetch_names = [f.name if isinstance(f, Variable) else f
                       for f in (fetch_list or [])]
        compiled = self._lookup_or_compile(program, feed, fetch_names, scope)
        from ..observability import memory as _memory
        stats = _memory.executable_memory(
            self._aot_compiled(compiled, feed, scope))
        _memory.update_watermark("executor_temp_bytes",
                                 stats["temp_bytes"])
        return stats

    def memory_census(self, feed=None, program=None, scope=None,
                      kv_names=()):
        """The full measured memory census of the LAST compiled step
        (`observability.memory.device_memory_census`): per-device state
        bytes by category from the actual scope arrays, feed bytes, the
        XLA executable's argument/output/temp/alias figures, and a
        process-wide live-array sweep. Run the step once first."""
        from ..observability import memory as _memory
        return _memory.device_memory_census(
            self, dict(feed or {}), scope or global_scope(),
            program=program, dp=int(getattr(self, "_dp", 1)),
            kv_names=kv_names)

    def close(self):
        """≙ Executor::Close (reference executor.cc:48) — drop caches."""
        self._cache.clear()


def scope_initialize_from(program: Program, scope: Scope):
    """Ensure all persistable vars declared by `program` exist in scope as
    zero arrays — used by tests; real init runs the startup program."""
    for b in program.blocks:
        for v in b.vars.values():
            if v.persistable and not scope.has_var(v.name):
                enforce(v.shape is not None and -1 not in v.shape,
                        f"cannot zero-init var {v.name} with shape {v.shape}",
                        exc=InvalidArgumentError)
                scope.set_var(v.name, jnp.zeros(v.shape, dtype=v.dtype))
