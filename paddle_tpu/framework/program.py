"""Program IR: Program / Block / Operator / Variable / Parameter.

Capability equivalent of the reference's ProgramDesc protobuf IR and its Python
mirrors (reference: paddle/fluid/framework/framework.proto:35-183 and
python/paddle/fluid/framework.py:142,431,855,1339,1874). Differences are
deliberate and TPU-first:

- The program is a lightweight in-memory op DAG, not a protobuf; serialization
  is JSON (programs are small — the heavy artifact on TPU is the compiled XLA
  executable, cached by the runtime).
- Execution is NOT op-by-op interpretation: the executor traces the whole block
  into a single jax function and XLA-compiles it (see executor.py). The IR here
  is the *construction* surface, matching the reference's layered design where
  Python builds a program and a backend consumes it.
- Gradients are appended as a single `vjp_region` op (see backward.py) instead
  of per-op grad OpDescs — autodiff happens inside the XLA trace.
"""

from __future__ import annotations

import contextlib
import json
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..core import unique_name
from ..core.dtypes import convert_dtype, dtype_name
from ..core.enforce import (AlreadyExistsError, InvalidArgumentError,
                            NotFoundError, enforce)

# Reserved data-var name for the per-row batch validity mask (1.0 = real row,
# 0.0 = padding added to make a partial batch dp-divisible). Declared via
# layers.batch_row_mask(); the Executor feeds all-ones when the program
# declares it and the caller didn't feed it, and ParallelExecutor zeroes the
# rows it pads (≙ reference details/data_balance_op_handle.cc, whose job is
# making uneven last batches runnable across devices).
BATCH_ROW_MASK_NAME = "@batch_row_mask"


class Variable:
    """A named tensor slot in a block (≙ VarDesc + fluid.framework.Variable,
    reference python/paddle/fluid/framework.py:142).

    shape may contain -1 for dims unknown until feed time (batch dim).
    ``lod_level > 0`` marks a sequence variable: its runtime value is a padded
    dense array accompanied by a companion length variable ``<name>@SEQLEN``
    (the static-shape translation of the reference's LoD ragged offsets,
    reference paddle/fluid/framework/lod_tensor.h:58).
    """

    def __init__(self, block, name, shape=None, dtype="float32",
                 persistable=False, stop_gradient=True, lod_level=0,
                 is_data=False, trainable=False):
        self.block = block
        self.name = name
        self.shape = tuple(int(d) for d in shape) if shape is not None else None
        self.dtype = convert_dtype(dtype)
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.lod_level = lod_level
        self.is_data = is_data
        self.trainable = trainable
        self.op = None  # producer op, set by Block.append_op
        # Optional byte-lean staging spec for data vars: (wire_dtype, scale).
        # The host stages `wire_dtype` bytes (e.g. uint8 images at 1/4 the
        # fp32 footprint) and the compiled step casts to `self.dtype` and
        # multiplies by `scale` on device — the TPU translation of the
        # reference's buffered_reader keeping the device fed (reference
        # paddle/fluid/operators/reader/buffered_reader.h:27).
        self.staging = None

    # -- numpy-style conveniences (≙ math_op_patch.py operator overloads) --
    def __repr__(self):
        return (f"Variable(name={self.name!r}, shape={self.shape}, "
                f"dtype={dtype_name(self.dtype)}, persistable={self.persistable})")

    @property
    def ndim(self):
        return len(self.shape) if self.shape is not None else None

    def _binary(self, other, op_type, reverse=False):
        from ..layers import math_ops
        return math_ops.elementwise_binary_dispatch(self, other, op_type, reverse)

    def __add__(self, other):
        return self._binary(other, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "elementwise_sub")

    def __rsub__(self, other):
        return self._binary(other, "elementwise_sub", reverse=True)

    def __mul__(self, other):
        return self._binary(other, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "elementwise_div")

    def __rtruediv__(self, other):
        return self._binary(other, "elementwise_div", reverse=True)

    def __pow__(self, other):
        return self._binary(other, "elementwise_pow")

    def __neg__(self):
        from ..layers import math_ops
        return math_ops.scale(self, scale=-1.0)

    def __lt__(self, other):
        return self._binary(other, "less_than")

    def __le__(self, other):
        return self._binary(other, "less_equal")

    def __gt__(self, other):
        return self._binary(other, "greater_than")

    def __ge__(self, other):
        return self._binary(other, "greater_equal")

    def astype(self, dtype):
        from ..layers import tensor as tensor_layers
        return tensor_layers.cast(self, dtype)


class Parameter(Variable):
    """A trainable persistable variable (≙ fluid.framework.Parameter,
    reference python/paddle/fluid/framework.py:1874)."""

    def __init__(self, block, name, shape, dtype="float32", trainable=True,
                 regularizer=None, gradient_clip=None, **kw):
        super().__init__(block, name, shape=shape, dtype=dtype,
                         persistable=True, stop_gradient=not trainable,
                         trainable=trainable, **kw)
        self.regularizer = regularizer
        self.gradient_clip = gradient_clip
        self.optimize_attr = {"learning_rate": 1.0}


class Operator:
    """One op in a block (≙ OpDesc + fluid.framework.Operator,
    reference python/paddle/fluid/framework.py:431).

    inputs/outputs map slot name → list of variable names. attrs are plain
    JSON-able python values (plus numpy arrays for constant payloads).
    """

    def __init__(self, block, op_type: str,
                 inputs: Optional[Dict[str, Sequence]] = None,
                 outputs: Optional[Dict[str, Sequence]] = None,
                 attrs: Optional[Dict[str, Any]] = None):
        from .registry import lookup_op  # late import to avoid cycle
        lookup_op(op_type)  # raise early on unknown op type
        self.block = block
        self.type = op_type
        self.inputs = {k: [v.name if isinstance(v, Variable) else v
                           for v in _as_list(vs)]
                       for k, vs in (inputs or {}).items()}
        self.outputs = {k: [v.name if isinstance(v, Variable) else v
                            for v in _as_list(vs)]
                        for k, vs in (outputs or {}).items()}
        self.attrs = dict(attrs or {})

    def input_names(self) -> List[str]:
        return [n for vs in self.inputs.values() for n in vs]

    def output_names(self) -> List[str]:
        return [n for vs in self.outputs.values() for n in vs]

    def __repr__(self):
        return f"Operator({self.type}: {self.inputs} -> {self.outputs})"


def _as_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class Block:
    """Ordered ops + named vars (≙ BlockDesc, reference
    paddle/fluid/framework/framework.proto:164, block_desc.h)."""

    def __init__(self, program, idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent(self) -> Optional["Block"]:
        return None if self.parent_idx < 0 else self.program.blocks[self.parent_idx]

    def create_var(self, name=None, **kw) -> Variable:
        name = name or unique_name.generate("tmp")
        if name in self.vars:
            raise AlreadyExistsError(f"variable {name!r} already exists in block")
        v = Variable(self, name, **kw)
        self.vars[name] = v
        self.program._bump()
        return v

    def create_parameter(self, name=None, shape=None, dtype="float32",
                         **kw) -> Parameter:
        name = name or unique_name.generate("param")
        enforce(shape is not None, "parameter shape required",
                exc=InvalidArgumentError)
        p = Parameter(self, name, shape, dtype=dtype, **kw)
        self.vars[name] = p
        self.program._bump()
        return p

    def var(self, name: str) -> Variable:
        """Find var in this block or ancestors (≙ Scope-like desc lookup)."""
        b = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = b.parent
        raise NotFoundError(f"variable {name!r} not found in block {self.idx}")

    def has_var(self, name: str) -> bool:
        try:
            self.var(name)
            return True
        except NotFoundError:
            return False

    def append_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        for out_name in op.output_names():
            if out_name in self.vars:
                self.vars[out_name].op = op
        self.program._bump()
        return op

    def prepend_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self.program._bump()
        return op

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]


class Program:
    """A whole trainable/inference program (≙ ProgramDesc + fluid Program,
    reference python/paddle/fluid/framework.py:1339)."""

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self._current_block_idx = 0
        self._version = 0  # bumped on any mutation; part of the jit cache key
        self.random_seed = 0

    # -- mutation tracking --
    def _bump(self):
        self._version += 1

    # -- block management --
    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self._current_block_idx]

    def _create_block(self, parent_idx=None) -> Block:
        parent_idx = self._current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent_idx)
        self.blocks.append(b)
        self._current_block_idx = b.idx
        self._bump()
        return b

    def _rollback(self):
        self._current_block_idx = self.current_block().parent_idx

    def all_parameters(self) -> List[Parameter]:
        return [p for b in self.blocks for p in b.all_parameters()]

    # -- cloning / pruning (≙ Program.clone / Prune, reference
    #    framework.py:1339 area, framework/prune.cc) --
    def clone(self, for_test: bool = False) -> "Program":
        p = Program()
        p.random_seed = self.random_seed
        p.blocks = []
        for b in self.blocks:
            nb = Block(p, b.idx, b.parent_idx)
            for name, v in b.vars.items():
                cls = Parameter if isinstance(v, Parameter) else Variable
                if cls is Parameter:
                    nv = Parameter(nb, name, v.shape, dtype=v.dtype,
                                   trainable=v.trainable,
                                   regularizer=v.regularizer,
                                   gradient_clip=v.gradient_clip)
                else:
                    nv = Variable(nb, name, shape=v.shape, dtype=v.dtype,
                                  persistable=v.persistable,
                                  stop_gradient=v.stop_gradient,
                                  lod_level=v.lod_level, is_data=v.is_data)
                for extra in ("sharding_spec", "is_optimizer_state",
                              "optimize_attr", "staging", "accumulator_of",
                              "dp_shard_update", "dp_replica_state",
                              "tp_spec", "buffer_slot"):
                    if hasattr(v, extra):
                        setattr(nv, extra, getattr(v, extra))
                nb.vars[name] = nv
            for op in b.ops:
                attrs = dict(op.attrs)
                if for_test:
                    attrs["is_test"] = True
                nop = Operator(nb, op.type, {}, {}, attrs)
                nop.inputs = {k: list(v) for k, v in op.inputs.items()}
                nop.outputs = {k: list(v) for k, v in op.outputs.items()}
                nb.ops.append(nop)
            p.blocks.append(nb)
        # program-level rewrite markers ride through clones: downstream
        # passes clone the tp-rewritten program (grad_comm, pipeline), and
        # the executor's placement/gate logic reads these off the FINAL
        # program (framework/sharding.py tp_shard_pass sets them)
        for marker in ("_tp_applied", "_tp_size", "_tp_n_collectives"):
            if hasattr(self, marker):
                setattr(p, marker, getattr(self, marker))
        p._current_block_idx = 0
        return p

    def prune(self, targets: Sequence[Union[str, Variable]]) -> "Program":
        """Keep only ops needed to compute `targets` (≙ framework/prune.cc).

        Used by save_inference_model. Operates on block 0.
        """
        target_names = {t.name if isinstance(t, Variable) else t for t in targets}
        block = self.global_block()
        needed = set(target_names)
        keep: List[int] = []
        for i in range(len(block.ops) - 1, -1, -1):
            op = block.ops[i]
            # backward/optimize ops never survive pruning-to-targets: the
            # forward pass reads the parameter's *incoming* value, so the
            # update op that also "produces" the param name is not a true
            # producer for inference (≙ reference prune.cc + op roles
            # kBackward/kOptimize, op_proto_maker.h:25-31).
            if (op.type == "vjp_region"
                    or op.attrs.get("op_role") in ("optimize", "backward")):
                continue
            if needed & set(op.output_names()):
                keep.append(i)
                needed |= set(op.input_names())
        keep.reverse()
        pruned = self.clone()
        pb = pruned.global_block()
        pb.ops = [pb.ops[i] for i in keep]
        used = set()
        for op in pb.ops:
            used |= set(op.input_names()) | set(op.output_names())
        used |= target_names
        pb.vars = {n: v for n, v in pb.vars.items() if n in used}
        pruned._bump()
        return pruned

    # -- serialization (JSON stands in for the reference's protobuf) --
    def to_json(self) -> str:
        def enc_attr(v):
            if isinstance(v, np.ndarray):
                return {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
            if isinstance(v, np.generic):
                return v.item()
            return v

        data = {"random_seed": self.random_seed, "blocks": []}
        for b in self.blocks:
            data["blocks"].append({
                "idx": b.idx, "parent_idx": b.parent_idx,
                "vars": [{
                    "name": v.name,
                    "shape": list(v.shape) if v.shape is not None else None,
                    "dtype": dtype_name(v.dtype),
                    "persistable": v.persistable,
                    "stop_gradient": v.stop_gradient,
                    "lod_level": v.lod_level, "is_data": v.is_data,
                    "is_parameter": isinstance(v, Parameter),
                    "trainable": v.trainable,
                    "sharding_spec": list(getattr(v, "sharding_spec", None))
                    if getattr(v, "sharding_spec", None) is not None else None,
                    "is_optimizer_state": getattr(v, "is_optimizer_state",
                                                  False),
                } for v in b.vars.values()],
                "ops": [{
                    "type": op.type, "inputs": op.inputs,
                    "outputs": op.outputs,
                    "attrs": {k: enc_attr(v) for k, v in op.attrs.items()},
                } for op in b.ops],
            })
        return json.dumps(data)

    @staticmethod
    def from_json(s: str) -> "Program":
        def dec_attr(v):
            if isinstance(v, dict) and "__ndarray__" in v:
                return np.asarray(v["__ndarray__"], dtype=v["dtype"])
            return v

        data = json.loads(s)
        p = Program()
        p.random_seed = data.get("random_seed", 0)
        p.blocks = []
        for bd in data["blocks"]:
            b = Block(p, bd["idx"], bd["parent_idx"])
            for vd in bd["vars"]:
                if vd.get("is_parameter"):
                    v = Parameter(b, vd["name"], vd["shape"], dtype=vd["dtype"],
                                  trainable=vd.get("trainable", True))
                else:
                    v = Variable(b, vd["name"], shape=vd["shape"],
                                 dtype=vd["dtype"],
                                 persistable=vd["persistable"],
                                 stop_gradient=vd["stop_gradient"],
                                 lod_level=vd.get("lod_level", 0),
                                 is_data=vd.get("is_data", False))
                if vd.get("sharding_spec") is not None:
                    v.sharding_spec = tuple(vd["sharding_spec"])
                if vd.get("is_optimizer_state"):
                    v.is_optimizer_state = True
                b.vars[v.name] = v
            for od in bd["ops"]:
                op = Operator(b, od["type"], {}, {},
                              {k: dec_attr(v) for k, v in od["attrs"].items()})
                op.inputs = od["inputs"]
                op.outputs = od["outputs"]
                b.ops.append(op)
            p.blocks.append(b)
        return p

    def __repr__(self):
        lines = []
        for b in self.blocks:
            lines.append(f"block {b.idx} (parent {b.parent_idx}):")
            for v in b.vars.values():
                lines.append(f"  var {v.name}: shape={v.shape} "
                             f"dtype={dtype_name(v.dtype)}"
                             + (" persistable" if v.persistable else ""))
            for op in b.ops:
                lines.append(f"  op {op.type}: {op.inputs} -> {op.outputs}")
        return "\n".join(lines)


# --- default program registry (≙ fluid default_main_program/startup_program,
#     reference python/paddle/fluid/framework.py:1958-2026) ---

_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    """Switch default programs within a scope (≙ fluid.program_guard)."""
    global _main_program, _startup_program
    old_main, old_startup = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program, _startup_program = old_main, old_startup


def reset_default_programs():
    global _main_program, _startup_program
    _main_program = Program()
    _startup_program = Program()
