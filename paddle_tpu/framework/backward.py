"""Autodiff: append_backward / calc_gradient.

Capability equivalent of the reference's source-transform autodiff
(reference: python/paddle/fluid/backward.py:469 append_backward, :685
calc_gradient, with per-op grad descs from C++ GradOpDescMaker). TPU-native
design: instead of emitting one grad op per forward op into the program, we
append a single `vjp_region` op recording (forward op set, loss, diff targets);
at trace time the executor runs that segment under jax.vjp (lowering.py:
run_vjp_region), so XLA sees exact analytic gradients for the entire region and
can fuse forward+backward. Gradient variables named `<var>@GRAD` appear in the
program exactly as in the reference, so clip/regularizer/optimizer ops compose
unchanged.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple, Union

from ..core.enforce import InvalidArgumentError, enforce
from .lowering import _ancestor_op_indices, grad_var_name
from .program import Parameter, Program, Variable


def _resolve_targets(block, seg_indices, parameter_list, no_grad_set):
    read: Set[str] = set()
    for i in seg_indices:
        read |= set(block.ops[i].input_names())
    no_grad = {v.name if isinstance(v, Variable) else v
               for v in (no_grad_set or ())}
    if parameter_list is not None:
        names = [p.name if isinstance(p, Variable) else p
                 for p in parameter_list]
    else:
        names = [p.name for p in block.program.all_parameters()
                 if p.trainable and p.name in read]
    return [n for n in names if n not in no_grad]


def _make_grad_vars(block, names: Sequence[str]) -> List[Variable]:
    out = []
    for n in names:
        gname = grad_var_name(n)
        if gname not in block.vars:
            src = block.var(n)
            block.create_var(name=gname, shape=src.shape, dtype=src.dtype,
                             stop_gradient=True)
        out.append(block.vars[gname])
    return out


def append_backward(loss: Variable,
                    parameter_list: Optional[Sequence] = None,
                    no_grad_set: Optional[Set] = None,
                    callbacks=None) -> List[Tuple[Variable, Variable]]:
    """Append gradient computation for `loss` wrt trainable parameters.

    ≙ reference python/paddle/fluid/backward.py:469. Returns
    [(param, param@GRAD), ...] like the reference.
    """
    block = loss.block
    enforce(loss.shape is None or int(__import__("numpy").prod(
        [d for d in loss.shape if d != -1] or [1])) >= 1,
        "loss must be a tensor", exc=InvalidArgumentError)
    upto = len(block.ops)
    seg = _ancestor_op_indices(block, upto, {loss.name})
    enforce(len(seg) > 0, f"no ops produce loss var {loss.name!r}",
            exc=InvalidArgumentError)
    target_names = _resolve_targets(block, seg, parameter_list, no_grad_set)
    enforce(len(target_names) > 0,
            "no trainable parameters found on the path to the loss",
            exc=InvalidArgumentError)

    grad_vars = _make_grad_vars(block, target_names)
    loss_grad = _make_grad_vars(block, [loss.name])[0]
    block.append_op(
        type="vjp_region",
        inputs={"Fwd": [loss.name]},
        outputs={"Grads": [g.name for g in grad_vars],
                 "LossGrad": [loss_grad.name]},
        attrs={"fwd_ops": seg, "targets": target_names, "loss": loss.name})
    params_and_grads = [(block.var(n), block.var(grad_var_name(n)))
                        for n in target_names]
    return params_and_grads


def calc_gradient(targets: Union[Variable, Sequence[Variable]],
                  inputs: Union[Variable, Sequence[Variable]],
                  target_gradients=None,
                  no_grad_set: Optional[Set] = None) -> List[Variable]:
    """Gradients of `targets` (summed; cotangent seeded with ones) wrt
    `inputs`. ≙ reference backward.py:685 calc_gradient."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    enforce(len(targets) == 1,
            "calc_gradient currently supports a single target",
            exc=InvalidArgumentError)
    target = targets[0]
    block = target.block
    upto = len(block.ops)
    seg = _ancestor_op_indices(block, upto, {target.name})
    no_grad = {v.name if isinstance(v, Variable) else v
               for v in (no_grad_set or ())}
    input_names = [v.name if isinstance(v, Variable) else v for v in inputs]
    input_names = [n for n in input_names if n not in no_grad]
    grad_vars = _make_grad_vars(block, input_names)
    tgrad = _make_grad_vars(block, [target.name])[0]
    block.append_op(
        type="vjp_region",
        inputs={"Fwd": [target.name]},
        outputs={"Grads": [g.name for g in grad_vars],
                 "LossGrad": [tgrad.name]},
        attrs={"fwd_ops": seg, "targets": input_names, "loss": target.name})
    return grad_vars
