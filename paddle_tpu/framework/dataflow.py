"""Dataflow analysis over the Program IR.

The machinery layer under the whole-program SPMD detectors in
`framework/analysis.py` — and the liveness/interference foundation the
memory planner (ROADMAP item 4) schedules against. Four pieces:

1. **Effect sets** (`op_effects`): per-op read/write/in-place buffer
   effects plus the semantics the slot lists cannot express — which mesh
   axes the op communicates over (`collective_axes`), whether a collective
   makes its outputs axis-consistent (`resolves_axes`) or deliberately
   axis-varying (`shards_axes`), and whether the op draws per-step
   randomness (`rng`). Rules register per-op via
   `registry.register_effects` — the same side-table contract as
   `register_infer_spec`/`register_shard_spec`, one layer up.

2. **Def-use chains** (`def_use_chains`) and **variable lifetimes /
   interference** (`var_lifetimes`, `interference_graph`): a transient var
   is live from its first writer to its last reader; backward regions
   (`vjp_region`/`pp_pipeline_region`) re-run their forward segment under
   jax.vjp, so every value the segment touches stays live until the region
   executes. Two vars interfere when their live intervals overlap — the
   exact relation a liveness-driven buffer-reuse plan must respect.

3. **A generic forward taint/lattice engine** (`propagate`, `Taint`):
   walks blocks in op order propagating per-var taint sets; the default
   transfer is the union of input taints filtered through the op's effect
   set (collectives that `resolves_axes` drop those axes' taints,
   `shards_axes` ops add fresh shard taints), with per-analysis seed and
   transfer hooks for everything else.

4. **The three whole-program detectors** (`dataflow_checks`), folded into
   `analysis.verify_program` and therefore into the always-on pass
   sanitizer (≙ the role the reference's multi_devices_check_pass + the
   HLO verifier play between passes):
   - SPMD collective consistency / static deadlock (`collective-*`),
   - replica divergence (`replica-divergence`) — GSPMD-style "diverges
     over axis X" propagation from RNG ops and shard-local partials into
     replication-requiring sinks,
   - buffer-reuse / WAR race checks (`buffer-*`) over the interference
     graph — the safety gate that makes liveness-driven buffer reuse
     plannable.

docs/static_analysis.md carries the diagnostic catalog and the effect-set
registration guide.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import (Any, Callable, Dict, FrozenSet, List, Optional, Set,
                    Tuple)

from .analysis import _SUB_KEYS, Diagnostic, op_loc
from .program import Block, Operator, Program
from .registry import lookup_effect_rule

__all__ = [
    "CACHE_WRITE_OPS", "DefUse", "Effects", "Taint",
    "cache_write_aliasing", "dataflow_checks", "def_use_chains",
    "divergence_taints", "interference_graph", "op_effects", "propagate",
    "var_lifetimes",
]

# Backward regions: engine-interpreted ops that re-run a recorded forward
# segment under jax.vjp (framework/lowering.py REGION_RUNNERS).
REGION_OPS = ("vjp_region", "pp_pipeline_region")

# Canonical mesh-axis constants (parallel/mesh.py DATA_AXIS/MODEL_AXIS/
# PIPELINE_AXIS — duplicated literals because framework/ must not import
# parallel/; tests/test_dataflow.py pins the two in sync).
DP_AXIS, TP_AXIS, PP_AXIS = "dp", "tp", "pp"


# ---------------------------------------------------------------------------
# effect sets
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Effects:
    """What one op does to buffers and mesh axes.

    reads/writes: var names, derived from the op's input/output slots.
    inplace: (read_name, write_name) aliased-buffer pairs — same-name
        read+write (ordered in-place updates like increment(in_place=True))
        plus any pairs a registered rule adds.
    collective_axes: mesh axes the op communicates over. A collective both
        ORDERS execution across the shards of those axes (all shards must
        reach it, in the same sequence — else static deadlock) and makes
        its outputs a function of every shard's inputs.
    resolves_axes: axes whose divergence the outputs no longer carry (a
        psum/all-gather result is identical on every shard of that axis,
        whatever went in).
    shards_axes: axes over which the outputs deliberately VARY per shard
        (a slice of a replicated value, a local shard of an update).
    rng: the op draws per-step randomness. The manual-mode executor
        decorrelates seeds across dp shards (tp shards share the seed —
        parallel_executor r11), so rng outputs diverge over dp.
    """

    reads: Tuple[str, ...]
    writes: Tuple[str, ...]
    inplace: Tuple[Tuple[str, str], ...] = ()
    collective_axes: Tuple[str, ...] = ()
    resolves_axes: Tuple[str, ...] = ()
    shards_axes: Tuple[str, ...] = ()
    rng: bool = False


def op_effects(op: Operator) -> Effects:
    """The effect set of one op: slot-derived reads/writes refined by the
    registered effect rule (registry.register_effects), pure compute when
    none is registered."""
    reads = tuple(op.input_names())
    writes = tuple(op.output_names())
    rset = set(reads)
    inplace = tuple((n, n) for n in writes if n in rset)
    rule = lookup_effect_rule(op.type)
    if rule is None:
        return Effects(reads=reads, writes=writes, inplace=inplace)
    extra = rule(op) or {}
    return Effects(
        reads=reads, writes=writes,
        inplace=inplace + tuple(tuple(p) for p in extra.get("inplace", ())),
        collective_axes=tuple(a for a in extra.get("collective_axes", ())
                              if a),
        resolves_axes=tuple(extra.get("resolves_axes", ())),
        shards_axes=tuple(extra.get("shards_axes", ())),
        rng=bool(extra.get("rng", False)))


# ---------------------------------------------------------------------------
# def-use chains + lifetimes + interference
# ---------------------------------------------------------------------------


@dataclass
class DefUse:
    """Per-block def-use chains: var name -> op indices. `producers` lists
    every writer in op order (more than one only for sanctioned rebinding
    — pp_recv, in-place updates); `consumers` lists every reader."""

    block_idx: int
    producers: Dict[str, List[int]]
    consumers: Dict[str, List[int]]

    def uses_after(self, name: str, idx: int) -> List[int]:
        return [i for i in self.consumers.get(name, ()) if i > idx]


def def_use_chains(block: Block) -> DefUse:
    du = DefUse(block_idx=block.idx, producers={}, consumers={})
    for idx, op in enumerate(block.ops):
        for name in op.input_names():
            du.consumers.setdefault(name, []).append(idx)
        for name in op.output_names():
            du.producers.setdefault(name, []).append(idx)
    return du


def var_lifetimes(block: Block,
                  include_regions: bool = True) -> Dict[str, Tuple[int, int]]:
    """[first_write, last_read] op-index interval per var written in this
    block. With `include_regions` (the default), every value the forward
    segment of a `vjp_region`/`pp_pipeline_region` reads or produces stays
    live until the region op executes — the backward re-runs that segment
    under jax.vjp, so its activations are backward inputs even though no
    op list names them (this is what the r10 census under-counted by
    freeing activations at their last FORWARD reader)."""
    first_w: Dict[str, int] = {}
    last_r: Dict[str, int] = {}
    for idx, op in enumerate(block.ops):
        for name in op.output_names():
            first_w.setdefault(name, idx)
            last_r[name] = max(last_r.get(name, idx), idx)
        for name in op.input_names():
            last_r[name] = idx
        if include_regions and op.type in REGION_OPS:
            for i in op.attrs.get("fwd_ops", ()):
                if not isinstance(i, int) or not 0 <= i < len(block.ops):
                    continue        # attr-schema reports the bad index
                fop = block.ops[i]
                for name in fop.output_names() + fop.input_names():
                    last_r[name] = max(last_r.get(name, idx), idx)
    return {name: (w, last_r.get(name, w)) for name, w in first_w.items()}


def declared_var_bytes(block: Block, name: str,
                       nominal_batch: int = 8) -> int:
    """Declared-shape bytes of one var (-1 dims priced at
    `nominal_batch`) — the ONE pricing rule the lifetime walks
    (analysis.peak_live_bytes) and the memory planner
    (framework/memory_plan.py) share, so slot-table and stash estimates
    can never drift from the peak estimate they are compared against.
    0 for undeclared/shapeless names."""
    import numpy as np
    v = block.vars.get(name)
    if v is None or v.shape is None:
        return 0
    numel = 1
    for d in v.shape:
        numel *= (nominal_batch if d == -1 else int(d))
    return numel * np.dtype(v.dtype).itemsize


def interference_graph(block: Block,
                       lifetimes: Optional[Dict[str, Tuple[int, int]]] = None
                       ) -> Dict[str, Set[str]]:
    """Adjacency over TRANSIENT vars whose live intervals overlap — two
    interfering vars can never share a buffer. Feeds and persistables are
    excluded (they are live for the whole program; reusing them is never
    plannable). The memory planner's coloring input."""
    if lifetimes is None:
        lifetimes = var_lifetimes(block)

    def _transient(name):
        v = block.vars.get(name)
        return v is not None and not v.persistable and not v.is_data

    iv = sorted(((s, e, n) for n, (s, e) in lifetimes.items()
                 if _transient(n)), key=lambda t: (t[0], t[1]))
    graph: Dict[str, Set[str]] = {n: set() for _, _, n in iv}
    active: List[Tuple[int, str]] = []      # (end, name)
    for start, end, name in iv:
        active = [(e, n) for e, n in active if e >= start]
        for _, other in active:
            graph[other].add(name)
            graph[name].add(other)
        active.append((end, name))
    return graph


# ---------------------------------------------------------------------------
# generic forward taint propagation
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Taint:
    """One divergence fact: the value may differ across the shards of
    `axis`. kind: "rng" (decorrelated randomness), "grad" (shard-local
    gradient partial awaiting reduction), "shard" (deliberately per-shard
    slice/partial). `src` carries op_loc provenance — it rides into the
    diagnostic message, so every report names the op that introduced the
    divergence."""

    axis: str
    kind: str
    src: str = ""

    def __str__(self):
        return f"{self.kind} over {self.axis!r}" + \
            (f" from {self.src}" if self.src else "")


TaintEnv = Dict[Tuple[int, str], FrozenSet[Taint]]

# hooks: var_seeds(block, name, var) -> iterable of Taint (applied to every
# declared var before the block's ops run); op_seeds(block, idx, op,
# effects) -> {out_name: taints} merged into the op's outputs; transfer(
# block, idx, op, effects, in_taints_by_name) -> {out_name: taints} or None
# to use the default effect-driven rule.
VarSeedFn = Callable[[Block, str, Any], Any]
OpSeedFn = Callable[[Block, int, Operator, Effects], Optional[Dict]]
TransferFn = Callable[[Block, int, Operator, Effects, Dict], Optional[Dict]]


def propagate(program: Program,
              var_seeds: Optional[VarSeedFn] = None,
              op_seeds: Optional[OpSeedFn] = None,
              transfer: Optional[TransferFn] = None) -> TaintEnv:
    """Forward taint propagation over every block in op order.

    Default transfer: each output gets the union of all input taints,
    minus the axes the op `resolves_axes` (psum/all-gather results are
    axis-consistent whatever went in), plus a fresh shard taint per
    `shards_axes` axis. Parent-block taints are visible to sub-blocks
    (conservative: the whole parent env, not just the prefix before the
    binder). Returns {(block idx, var name) -> frozenset of Taint}."""
    env: TaintEnv = {}

    def lookup(block: Block, name: str) -> FrozenSet[Taint]:
        b = block
        while b is not None:
            key = (b.idx, name)
            if key in env:
                return env[key]
            if name in b.vars:
                return frozenset()
            b = b.parent
        return frozenset()

    for block in program.blocks:
        if var_seeds is not None:
            for name, v in block.vars.items():
                ts = var_seeds(block, name, v)
                if ts:
                    env[(block.idx, name)] = (
                        env.get((block.idx, name), frozenset())
                        | frozenset(ts))
        for idx, op in enumerate(block.ops):
            eff = op_effects(op)
            ins = {n: lookup(block, n) for n in eff.reads}
            outs = transfer(block, idx, op, eff, ins) \
                if transfer is not None else None
            if outs is None:
                u: FrozenSet[Taint] = frozenset()
                for ts in ins.values():
                    u = u | ts
                if eff.resolves_axes:
                    u = frozenset(t for t in u
                                  if t.axis not in eff.resolves_axes)
                if eff.shards_axes:
                    u = u | frozenset(
                        Taint(a, "shard", op_loc(block, idx, op))
                        for a in eff.shards_axes)
                outs = {n: u for n in eff.writes}
            if op_seeds is not None:
                for n, ts in (op_seeds(block, idx, op, eff) or {}).items():
                    outs[n] = frozenset(outs.get(n, frozenset())) \
                        | frozenset(ts)
            for n, ts in outs.items():
                env[(block.idx, n)] = frozenset(ts)
    return env


# ---------------------------------------------------------------------------
# the replica-divergence lattice instantiation
# ---------------------------------------------------------------------------


def _dp_active(program: Program) -> bool:
    """dp divergence exists only in the EXPLICIT per-shard execution mode
    (manual shard_map with decorrelated seeds and raw local gradients) —
    marked by a spliced dp_grad_comm. Default SPMD mode has one logical
    program whose collectives XLA owns: nothing to taint."""
    return any(op.type == "dp_grad_comm"
               for b in program.blocks for op in b.ops)


def _tp_active(program: Program) -> bool:
    """tp divergence exists once tp_shard_pass made the sharding
    executable (tp collectives spliced, vars marked tp_spec)."""
    if getattr(program, "_tp_applied", False):
        return True
    return any(op.type.startswith("tp_")
               for b in program.blocks for op in b.ops)


def divergence_taints(program: Program) -> TaintEnv:
    """'Diverges over axis X' facts for every var (GSPMD-style spec
    propagation restricted to the consistency lattice). Sources: RNG ops
    (dp-decorrelated seeds), backward-region raw gradients (shard-local
    partials before dp_grad_comm), tp-sharded params and tp_split /
    dp_shard_slice outputs. Collectives clear their axis per the effect
    table; dp_grad_comm clears dp on bucket outputs and re-marks sharded
    outputs as deliberate dp shards."""
    dp_on = _dp_active(program)
    tp_on = _tp_active(program)
    if not dp_on and not tp_on:
        return {}

    def var_seeds(block, name, v):
        ts = []
        if tp_on and getattr(v, "tp_spec", None):
            ts.append(Taint(TP_AXIS, "shard", f"tp-sharded var {name!r}"))
        if dp_on and (getattr(v, "dp_shard_update", False)
                      or getattr(v, "dp_replica_state", False)):
            ts.append(Taint(DP_AXIS, "shard", f"dp-sharded state {name!r}"))
        return ts

    def op_seeds(block, idx, op, eff):
        # the rng effect rule already accounts for fixed seeds and
        # inference-mode dropout (ops/random_ops.py)
        if eff.rng and dp_on:
            t = Taint(DP_AXIS, "rng", op_loc(block, idx, op))
            return {n: (t,) for n in eff.writes}
        return None

    def transfer(block, idx, op, eff, ins):
        loc = op_loc(block, idx, op)
        if op.type in REGION_OPS:
            # Grads are gradients of the LOCAL mean loss: dp partials in
            # explicit mode unless the region pmeans them itself
            # (reduce_dp). Over tp the f/g custom VJPs (tensor_parallel.py)
            # guarantee replicated-param cotangents are psum'd; gradients
            # of tp-sharded params stay tp-local like their params.
            reduce_dp = bool(op.attrs.get("reduce_dp", False))
            outs = {}
            targets = list(op.attrs.get("targets", ()))
            for g, t in zip(op.outputs.get("Grads", ()), targets):
                ts = set()
                if dp_on and not reduce_dp:
                    ts.add(Taint(DP_AXIS, "grad", loc))
                if tp_on and block.has_var(t) \
                        and getattr(block.var(t), "tp_spec", None):
                    ts.add(Taint(TP_AXIS, "shard", loc))
                outs[g] = ts
            for lg in op.outputs.get("LossGrad", ()):
                outs[lg] = set()           # the replicated 1.0 seed
            return outs
        if op.type == "dp_grad_comm":
            xs = list(op.inputs.get("X", ()))
            kinds = list(op.attrs.get("kinds", ()))
            outs = {}
            for i, on in enumerate(op.outputs.get("Out", ())):
                tin = ins.get(xs[i], frozenset()) if i < len(xs) \
                    else frozenset()
                keep = {t for t in tin if t.axis != DP_AXIS}
                if i < len(kinds) and kinds[i] == "sharded":
                    keep.add(Taint(DP_AXIS, "shard", loc))
                outs[on] = keep
            for en in op.outputs.get("ErrOut", ()):
                outs[en] = {Taint(DP_AXIS, "shard", loc)}
            return outs
        return None

    return propagate(program, var_seeds=var_seeds, op_seeds=op_seeds,
                     transfer=transfer)


def _lookup_taints(env: TaintEnv, block: Block,
                   name: str) -> FrozenSet[Taint]:
    b = block
    while b is not None:
        key = (b.idx, name)
        if key in env:
            return env[key]
        if name in b.vars:
            return frozenset()
        b = b.parent
    return frozenset()


# ---------------------------------------------------------------------------
# detector 1: SPMD collective consistency / static deadlock
# ---------------------------------------------------------------------------

# op family -> the one mesh axis its collectives may ride. tp_* ops carry
# Megatron f/g semantics over the model axis, dp_* ops the r08 gradient
# pipeline over the data axis; an axis-swapped attr would psum across the
# WRONG shards — numerically silent corruption (or, shard counts differing,
# a hang). dp_shard_slice performs no comm but derives its slice index from
# the axis, so a mismatch mis-places the ZeRO shard the same way.
_CANONICAL_AXIS = {
    "tp_allreduce": TP_AXIS, "tp_ident": TP_AXIS, "tp_split": TP_AXIS,
    "tp_allgather": TP_AXIS, "tp_vocab_lookup": TP_AXIS,
    "dp_grad_comm": DP_AXIS, "dp_shard_slice": DP_AXIS,
    "dp_shard_all_gather": DP_AXIS,
}


def _check_collective_axes(program, diags):
    for block in program.blocks:
        for idx, op in enumerate(block.ops):
            want = _CANONICAL_AXIS.get(op.type)
            if want is None:
                if op.type == "pp_pipeline_region" and \
                        op.attrs.get("axis") not in (PP_AXIS,):
                    diags.append(Diagnostic(
                        "collective-axis-mismatch", op_loc(block, idx, op),
                        f"pipeline region must run over axis "
                        f"{PP_AXIS!r}, got {op.attrs.get('axis')!r}"))
                continue
            got = op.attrs.get("axis")
            if got != want:
                diags.append(Diagnostic(
                    "collective-axis-mismatch", op_loc(block, idx, op),
                    f"{op.type} must ride mesh axis {want!r}, got "
                    f"{got!r}: shards of {want!r} would wait on a "
                    f"collective the program issues over {got!r}"))


def _check_pp_stage_order(program, diags):
    """Stage-partition placement of the pipeline boundary collectives: the
    schedule executes stage k's op list on pp shard k, so cut c's pp_send
    must belong to stage c and its pp_recv to stage c+1, and within a
    stage the recv (binding the stage's inputs) must precede the send
    (emitting its outputs). A boundary op assigned to the wrong stage —
    or re-ordered within its stage — means some pp shard never issues the
    transfer its peer is blocked on: a static deadlock. (Global
    send/recv PAIRING is pp-unmatched-boundary's job; this check is about
    WHERE in the partition the pair sits.)"""
    for block in program.blocks:
        for ridx, rop in enumerate(block.ops):
            if rop.type != "pp_pipeline_region":
                continue
            stages = rop.attrs.get("stages") or []
            loc = op_loc(block, ridx, rop)
            stage_of = {}
            for k, idxs in enumerate(stages):
                for i in idxs:
                    if isinstance(i, int):
                        stage_of[i] = k
            sends = {}
            recvs = {}
            for i, op in enumerate(block.ops):
                if op.type == "pp_send":
                    sends[op.attrs.get("cut")] = i
                elif op.type == "pp_recv":
                    recvs[op.attrs.get("cut")] = i
            for cut, si in sorted(sends.items(), key=lambda kv: repr(kv[0])):
                if si not in stage_of:
                    diags.append(Diagnostic(
                        "collective-order", op_loc(block, si, block.ops[si]),
                        f"pp_send for cut {cut} is not in any stage of the "
                        f"pipeline region at {loc}: no pp shard ever "
                        f"issues it — static deadlock"))
                elif stage_of[si] != cut:
                    diags.append(Diagnostic(
                        "collective-order", op_loc(block, si, block.ops[si]),
                        f"pp_send for cut {cut} assigned to stage "
                        f"{stage_of[si]} (must be stage {cut}): stage "
                        f"{cut + 1}'s pp_recv waits on a send its peer "
                        f"stage never issues — static deadlock"))
            for cut, ri in sorted(recvs.items(), key=lambda kv: repr(kv[0])):
                if ri not in stage_of:
                    diags.append(Diagnostic(
                        "collective-order", op_loc(block, ri, block.ops[ri]),
                        f"pp_recv for cut {cut} is not in any stage of the "
                        f"pipeline region at {loc}: no pp shard ever "
                        f"issues it — static deadlock"))
                elif stage_of[ri] != cut + 1:
                    diags.append(Diagnostic(
                        "collective-order", op_loc(block, ri, block.ops[ri]),
                        f"pp_recv for cut {cut} assigned to stage "
                        f"{stage_of[ri]} (must be stage {cut + 1}): the "
                        f"consuming stage never receives its boundary "
                        f"activation — static deadlock"))
            # within one stage: every recv (cut k-1) precedes every send
            # (cut k) in the stage's own execution order
            for k, idxs in enumerate(stages):
                pos = {i: p for p, i in enumerate(idxs)
                       if isinstance(i, int)}
                r = [pos[i] for c, i in recvs.items()
                     if stage_of.get(i) == k and i in pos]
                s = [pos[i] for c, i in sends.items()
                     if stage_of.get(i) == k and i in pos]
                if r and s and max(r) > min(s):
                    i = idxs[min(s)]
                    diags.append(Diagnostic(
                        "collective-order", op_loc(block, i, block.ops[i]),
                        f"stage {k} issues its pp_send before its pp_recv: "
                        f"the send's inputs depend on the boundary "
                        f"activation the stage has not received — "
                        f"static deadlock"))


def _sub_block_map(program) -> Dict[int, Tuple[Block, int, Operator]]:
    """sub-block idx -> (binder block, binder op idx, binder op)."""
    out = {}
    for block in program.blocks:
        for idx, op in enumerate(block.ops):
            for key in _SUB_KEYS:
                v = op.attrs.get(key)
                if isinstance(v, int) and not isinstance(v, bool):
                    subs = [v]
                elif isinstance(v, (list, tuple)):
                    subs = [x for x in v if isinstance(x, int)]
                else:
                    subs = []
                for si in subs:
                    if 0 < si < len(program.blocks):
                        out.setdefault(si, (block, idx, op))
    return out


def _binder_condition_names(bop) -> List[str]:
    """The names the binder BRANCHES on — not its captures/carries, which
    legitimately hold shard-varying state (a ZeRO accumulator captured
    into a branch body is fine; a divergent CONDITION is the deadlock).
    cond_block/lazy_cond use the Cond slot, switch_case Conds, while
    names its condition inside Carry via the cond_name attr; static_rnn
    has no condition (its trip count is shape-static, shard-invariant)."""
    conds = list(bop.inputs.get("Cond", ())) \
        + list(bop.inputs.get("Conds", ()))
    cn = bop.attrs.get("cond_name")
    if cn:
        conds.append(cn)
    return conds


def _check_divergent_control(program, env, diags):
    """A collective under control flow entered per a shard-divergent
    condition: shards of the collective's axis disagree on taking the
    branch (or on the trip count), so some issue the collective and some
    never do — the canonical SPMD deadlock. The binder chain is walked
    transitively: a collective in a nested block deadlocks on ANY
    divergent condition above it."""
    binders = _sub_block_map(program)
    for block in program.blocks:
        if block.idx == 0:
            continue
        for idx, op in enumerate(block.ops):
            eff = op_effects(op)
            if not eff.collective_axes:
                continue
            si = block.idx
            seen = set()
            while si in binders and si not in seen:
                seen.add(si)
                bblock, bidx, bop = binders[si]
                for cond in _binder_condition_names(bop):
                    bad = [t for t in _lookup_taints(env, bblock, cond)
                           if t.axis in eff.collective_axes]
                    if bad:
                        diags.append(Diagnostic(
                            "collective-divergent-control",
                            op_loc(block, idx, op),
                            f"collective over axis "
                            f"{bad[0].axis!r} executes under "
                            f"{bop.type!r} (block {bblock.idx} "
                            f"op#{bidx}) whose condition {cond!r} "
                            f"diverges ({bad[0]}): shards disagree on "
                            f"entering the branch — static deadlock"))
                si = bblock.idx
    return diags


# ---------------------------------------------------------------------------
# detector 2: replica divergence into replication-requiring sinks
# ---------------------------------------------------------------------------

# the r08 ZeRO-1 rewrite's name suffixes (parallel/grad_comm.py
# SHARD_SUFFIX) — duplicated literal for the same layering reason as the
# axis names; test_dataflow.py pins them in sync
_DP_SHARD_SUFFIX = "@DP_SHARD"


def _check_tp_partials(program, diags):
    """A raw tp partial sum (the `@TPPART` output tp_shard_pass renames a
    contraction over a tp-sharded dim to — framework/sharding.py
    TP_PART_SUFFIX) is correct exactly once through `tp_allreduce`
    (psum_once, the Megatron g operator). Any other consumer reads a
    shard-local partial as if it were the replicated value — the
    silent-corruption half of the replica-divergence bug class (a later
    psum on some OTHER path would launder the divergence without fixing
    the number, so this must be caught at the consuming op, not at a
    sink). The same contract dp-comm-bypass enforces for `@COMM`
    gradients, one axis over."""
    from .sharding import TP_PART_SUFFIX
    for block in program.blocks:
        for idx, op in enumerate(block.ops):
            if op.type == "tp_allreduce" or op.type in REGION_OPS:
                continue
            bad = sorted(n for n in set(op.input_names())
                         if n.endswith(TP_PART_SUFFIX))
            if bad:
                diags.append(Diagnostic(
                    "replica-divergence", op_loc(block, idx, op),
                    f"reads raw tp partial sum(s) {bad[:4]} — a "
                    f"{TP_PART_SUFFIX} value is a shard-local partial "
                    f"awaiting its one tp_allreduce; consuming it "
                    f"anywhere else silently treats a partial as the "
                    f"replicated value"))


def _check_replica_divergence(program, env, diags):
    """Parameter updates must consume replica-consistent values: every
    optimizer input carrying a divergence taint — other than the
    sanctioned ZeRO-1 dp shards on a sharded-update op and tp-local
    gradients of a tp-sharded param — reports, with the source op in the
    message. The region loss must additionally be tp-consistent (tp
    shards see the SAME batch; a tp-divergent loss means a missing
    tp collective — the executor's scalar pmean over dp is a mean over
    DIFFERENT batch slices, sanctioned; over tp it would silently
    average a partial). Raw `@TPPART` partials get the stricter
    consumed-exactly-by-tp_allreduce contract (`_check_tp_partials`)."""
    _check_tp_partials(program, diags)
    for block in program.blocks:
        for idx, op in enumerate(block.ops):
            if op.type in REGION_OPS:
                loss = op.attrs.get("loss")
                if loss:
                    bad = sorted((t for t in _lookup_taints(env, block, loss)
                                  if t.axis == TP_AXIS), key=str)
                    if bad:
                        diags.append(Diagnostic(
                            "replica-divergence", op_loc(block, idx, op),
                            f"loss {loss!r} diverges over {TP_AXIS!r} "
                            f"({bad[0]}): tp shards compute identical "
                            f"data, so a tp-divergent loss means a "
                            f"missing tp collective on its path"))
                continue
            if op.attrs.get("op_role") != "optimize":
                continue
            eff = op_effects(op)
            if eff.collective_axes or eff.resolves_axes or eff.shards_axes:
                continue        # the comm/placement ops of the update path
            params = list(op.inputs.get("Param", ()))
            sharded_update = any(n.endswith(_DP_SHARD_SUFFIX)
                                 for n in params)
            base = [n[:-len(_DP_SHARD_SUFFIX)]
                    if n.endswith(_DP_SHARD_SUFFIX) else n for n in params]
            param_tp = any(block.has_var(p)
                           and getattr(block.var(p), "tp_spec", None)
                           for p in base)
            for name in eff.reads:
                bad = []
                for t in _lookup_taints(env, block, name):
                    if t.axis == DP_AXIS and t.kind == "shard" \
                            and sharded_update:
                        continue     # ZeRO-1: update runs on the dp slice
                    if t.axis == TP_AXIS and t.kind == "shard" and param_tp:
                        continue     # tp-sharded param: grad sharded alike
                    bad.append(t)
                if bad:
                    bad.sort(key=str)
                    diags.append(Diagnostic(
                        "replica-divergence", op_loc(block, idx, op),
                        f"optimizer input {name!r} diverges across "
                        f"replicas ({bad[0]}): parameter updates must "
                        f"consume replica-consistent values or replicas "
                        f"drift apart silently"))


# ---------------------------------------------------------------------------
# detector 3: buffer-reuse / WAR races over the interference graph
# ---------------------------------------------------------------------------


def _check_cross_block_slots(program, groups, diags):
    """Slot groups that CROSS a block boundary (r18 planner satellite):
    the per-block scan above compares live intervals inside one op list,
    so a planner slot shared between a parent-block var and a var inside
    a bound sub-block (while/cond/static_rnn body — or any region a
    binder op executes) was never verified. The sub-block var's effective
    live window in an ancestor block is its BINDER op's index — the
    binder (re-)executes the whole sub-block, possibly per iteration, so
    the var is live whenever the binder is. Walk each member's binder
    chain to the deepest common ancestor and report overlap there as the
    same `buffer-reuse-race` the in-block scan raises. Sibling sub-blocks
    of ONE binder (cond/switch branches) are mutually exclusive and
    sanctioned."""
    cross = {s: ms for s, ms in groups.items()
             if len({b.idx for b, _ in ms}) > 1}
    if not cross:
        return
    binders = _sub_block_map(program)
    lifetimes_cache: Dict[int, Dict] = {}

    def lifetimes(block):
        lt = lifetimes_cache.get(block.idx)
        if lt is None:
            lt = lifetimes_cache[block.idx] = var_lifetimes(block)
        return lt

    def spans(block, name):
        """{ancestor block idx: (start, end)} — the var's own lifetime in
        its block, then its binder op's point interval per ancestor."""
        iv = lifetimes(block).get(name)
        if iv is None:
            return None                   # never written: nothing to race
        out = {block.idx: iv}
        b = block
        seen = set()
        while b.idx in binders and b.idx not in seen:
            seen.add(b.idx)
            pb, pidx, _pop = binders[b.idx]
            out[pb.idx] = (pidx, pidx)
            b = pb
        return out

    for slot, members in sorted(cross.items(), key=lambda kv: repr(kv[0])):
        for i in range(len(members)):
            for j in range(i + 1, len(members)):
                (b1, n1), (b2, n2) = members[i], members[j]
                if b1.idx == b2.idx:
                    continue              # the per-block scan owns these
                s1, s2 = spans(b1, n1), spans(b2, n2)
                if s1 is None or s2 is None:
                    continue
                common = set(s1) & set(s2)
                if not common:
                    continue
                cb = max(common)          # deepest common ancestor
                (a1, e1), (a2, e2) = s1[cb], s2[cb]
                if cb not in (b1.idx, b2.idx) and (a1, e1) == (a2, e2):
                    continue    # sibling branches of one binder: exclusive
                if a1 <= e2 and a2 <= e1:
                    block = program.blocks[cb]
                    bidx = a1 if cb != b1.idx else a2
                    diags.append(Diagnostic(
                        "buffer-reuse-race",
                        op_loc(block, bidx, block.ops[bidx]),
                        f"buffer slot {slot!r}: {n1!r} (block {b1.idx}) "
                        f"and {n2!r} (block {b2.idx}) overlap in ancestor "
                        f"block {cb} — a sub-block var is live whenever "
                        f"its region binder executes, so a slot crossing "
                        f"the boundary must not overlap the binder's "
                        f"live window"))


def _check_buffer_reuse(program, diags):
    """The safety gate for liveness-driven buffer reuse (ROADMAP item 4):
    vars the planner assigns one buffer (`Variable.buffer_slot`) must not
    interfere. A proper live-interval overlap is a reuse race (two live
    values, one buffer); a write landing exactly on the op still reading
    the previous occupant is the WAR boundary case — legal only with a
    serializing copy, so it reports separately. Cross-name in-place
    aliases from effect rules get the same WAR treatment. Programs with
    no annotations (everything today outside the planner and its tests)
    short-circuit to zero cost."""
    all_groups: Dict[Any, List[Tuple[Any, str]]] = {}
    for block in program.blocks:
        groups: Dict[Any, List[str]] = {}
        for name, v in block.vars.items():
            slot = getattr(v, "buffer_slot", None)
            if slot is not None:
                groups.setdefault(slot, []).append(name)
                all_groups.setdefault(slot, []).append((block, name))
        # cross-name in-place aliases can only come from a REGISTERED
        # effect rule (the slot-derived default is same-name only), so the
        # scan touches just the ops that have one — everything else keeps
        # the advertised zero-cost path
        aliased = []
        for idx, op in enumerate(block.ops):
            if lookup_effect_rule(op.type) is None:
                continue
            for rin, rout in op_effects(op).inplace:
                if rin != rout:
                    aliased.append((idx, op, rin, rout))
        if not any(len(g) > 1 for g in groups.values()) and not aliased:
            continue
        lifetimes = var_lifetimes(block)
        du = def_use_chains(block)
        for slot, names in sorted(groups.items(), key=lambda kv: repr(kv[0])):
            if len(names) < 2:
                continue
            iv = []
            for name in sorted(names):
                v = block.vars[name]
                if v.persistable or v.is_data:
                    diags.append(Diagnostic(
                        "buffer-reuse-race", name,
                        f"buffer slot {slot!r}: {name!r} is "
                        f"{'persistable' if v.persistable else 'a feed'} "
                        f"— live for the whole program, never reusable"))
                    continue
                if name in lifetimes:
                    iv.append((lifetimes[name], name))
            iv.sort()
            # compare each interval against EVERY still-active occupant
            # (adjacent-only would miss a short-lived mate nested inside a
            # long-lived one); groups are small, the active list smaller
            active: List[Tuple[int, int, str]] = []   # (end, start, name)
            for (s1, e1), n1 in iv:
                active = [(e0, s0, n0) for e0, s0, n0 in active
                          if e0 >= s1]
                for e0, s0, n0 in active:
                    writer = block.ops[s1]
                    if s1 == e0 and n0 in writer.input_names():
                        diags.append(Diagnostic(
                            "buffer-war-race", op_loc(block, s1, writer),
                            f"buffer slot {slot!r}: writes {n1!r} into "
                            f"the buffer while the same op still reads "
                            f"the previous occupant {n0!r} — needs a "
                            f"serializing copy before the slot can be "
                            f"reused"))
                    else:
                        diags.append(Diagnostic(
                            "buffer-reuse-race", op_loc(block, s1, writer),
                            f"buffer slot {slot!r}: {n1!r} (live "
                            f"[{s1}, {e1}]) overlaps {n0!r} (live "
                            f"[{s0}, {e0}]) — interfering vars cannot "
                            f"share a buffer"))
                active.append((e1, s1, n1))
        for idx, op, rin, rout in aliased:
            late = du.uses_after(rin, idx)
            if late:
                j = late[0]
                diags.append(Diagnostic(
                    "buffer-war-race", op_loc(block, idx, op),
                    f"in-place alias {rin!r} -> {rout!r}: op#{j} "
                    f"{block.ops[j].type!r} still reads {rin!r} after "
                    f"the aliasing write overwrote its buffer"))
    _check_cross_block_slots(program, all_groups, diags)


# ---------------------------------------------------------------------------
# serving cache-write aliasing (r24) — opt-in via lint_program --serving
# ---------------------------------------------------------------------------

# The executor's donated-state path rebinds each persistable KV pool in
# place: builders pass `out=pool` so Cache and Out are the SAME var and
# the dispatch loop can donate the buffer. Either aliasing mistake
# silently corrupts serving state instead of crashing, which is why this
# is a static check and not a runtime assert.
CACHE_WRITE_OPS = ("cache_write", "paged_cache_write",
                   "paged_cache_write_quant")


def cache_write_aliasing(program: Program) -> List[Diagnostic]:
    """Serving-tier cache-write aliasing checks (lint_program --serving).

    Two named diagnostics over the tick/prefill program's cache-write
    ops (`CACHE_WRITE_OPS`; the Scales plane of the quantized write is
    checked as its own (Scales, ScalesOut) pair):

    - `serving-cache-write-alias`: a pool var with more than one writer
      in a block (two scatters race on one donated buffer — the executor
      aliases Out onto Cache, so op order stops being observable), or a
      PERSISTABLE pool written to a different Out var (the update lands
      in a temporary; the persistable state the next tick reads never
      advances — a silent fork of the serving cache).
    - `serving-cache-stale-read`: an op after the write still reading
      the old Cache name when Out is a fresh var — the reader sees the
      pre-write bytes (exactly the offload-use-before-arrival hazard,
      one tier up).
    """
    diags: List[Diagnostic] = []
    for block in program.blocks:
        writers: Dict[str, List[Tuple[int, Operator, str]]] = {}
        for idx, op in enumerate(block.ops):
            if op.type not in CACHE_WRITE_OPS:
                continue
            pairs = [("Cache", "Out")]
            if op.type == "paged_cache_write_quant":
                pairs.append(("Scales", "ScalesOut"))
            for cin, cout in pairs:
                cache = (op.inputs.get(cin) or [None])[0]
                outn = (op.outputs.get(cout) or [None])[0]
                if cache is None or outn is None:
                    continue
                writers.setdefault(cache, []).append((idx, op, outn))
        for cache, ws in sorted(writers.items()):
            if len(ws) > 1:
                idx, op, _ = ws[1]
                diags.append(Diagnostic(
                    "serving-cache-write-alias", op_loc(block, idx, op),
                    f"cache var {cache!r} has {len(ws)} writers in one "
                    f"block (first at op#{ws[0][0]}) — scatters race on "
                    f"the donated pool buffer"))
            for idx, op, outn in ws:
                if outn == cache:
                    continue
                var = block.vars.get(cache)
                if var is not None and getattr(var, "persistable", False):
                    diags.append(Diagnostic(
                        "serving-cache-write-alias", op_loc(block, idx, op),
                        f"persistable cache {cache!r} written to a "
                        f"different var {outn!r} — the serving state "
                        f"forks into a temporary and never advances"))
                for j in range(idx + 1, len(block.ops)):
                    later = block.ops[j]
                    if cache in later.input_names():
                        diags.append(Diagnostic(
                            "serving-cache-stale-read",
                            op_loc(block, j, later),
                            f"op#{j} {later.type!r} reads {cache!r} "
                            f"after op#{idx} rewrote it into {outn!r} — "
                            f"the reader sees the pre-write cache"))
                        break
    return diags


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def dataflow_checks(program: Program) -> List[Diagnostic]:
    """All three dataflow detectors; called from analysis.verify_program
    (and therefore from every sanitized pass apply). Pure Python over the
    IR — no jax, no tracing; cost is linear in op count."""
    diags: List[Diagnostic] = []
    env = divergence_taints(program)
    _check_collective_axes(program, diags)
    _check_pp_stage_order(program, diags)
    _check_divergent_control(program, env, diags)
    _check_replica_divergence(program, env, diags)
    _check_buffer_reuse(program, diags)
    return diags
