"""CSP concurrency: Go-style channels, select, go.

≙ reference framework/channel.h:33 / channel_impl.h (buffered + unbuffered
channels with close semantics), operators channel_create/send/recv/close,
select_op.cc, go_op.cc, and the Python surface fluid/concurrency.py:28,196,282
(Go/Select/make_channel).

Design note: the reference threads channels *through programs* (CHANNEL
variables executed by interpreting executors). Under XLA there is no
interpreter to block inside a compiled step, so the capability lands where it
is actually used on TPU: host-side coordination between Python threads
(input pipelines, async checkpointing, parameter servers). Semantics mirror
Go precisely: unbuffered channels rendezvous; receive on a closed, drained
channel returns (zero, False); send on a closed channel raises; select picks
uniformly among ready cases and supports a default.
"""

from __future__ import annotations

import random
import threading
from collections import deque
from typing import Any, Callable, List, Optional, Sequence, Tuple

from .core.enforce import InvalidArgumentError, enforce

__all__ = ["Channel", "ChannelClosedError", "ChannelTimeout", "Go", "Select",
           "channel_close", "channel_recv", "channel_send", "go",
           "make_channel"]


class ChannelClosedError(RuntimeError):
    """Send on a closed channel (≙ PADDLE_ENFORCE in ChannelImpl::Send)."""


class ChannelTimeout(TimeoutError):
    """recv/send gave up after `timeout` — distinct from close so drain
    loops (`while ok`) can't mistake a slow producer for end-of-stream."""


class Channel:
    """Buffered (capacity > 0) or unbuffered (capacity == 0) channel
    (≙ ChannelImpl, reference framework/channel_impl.h)."""

    def __init__(self, capacity: int = 0, dtype=None, name: str = ""):
        enforce(capacity >= 0, "channel capacity must be >= 0",
                exc=InvalidArgumentError)
        self.capacity = capacity
        self.dtype = dtype
        self.name = name
        self._buf: deque = deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        # unbuffered rendezvous bookkeeping: parked senders / receivers
        self._recv_waiting = 0
        self._send_waiting = 0

    # -- probes used by Select (called under no lock; advisory) -----------
    def _can_send(self) -> bool:
        if self._closed:
            return True   # send will raise — still "ready" so select surfaces it
        if self.capacity > 0:
            return len(self._buf) < self.capacity
        return self._recv_waiting > 0

    def _can_recv(self) -> bool:
        return bool(self._buf) or self._closed or self._send_waiting > 0

    # -- core ops ---------------------------------------------------------
    def send(self, value: Any, timeout: Optional[float] = None) -> bool:
        """Blocks until delivered (unbuffered: until a receiver takes it).
        Raises ChannelClosedError if the channel is/becomes closed.
        Returns False on timeout; `timeout` bounds the WHOLE call."""
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout

        def remaining():
            if deadline is None:
                return None
            return max(deadline - _time.monotonic(), 0.0)

        with self._cond:
            if self._closed:
                raise ChannelClosedError(f"send on closed channel {self.name}")
            if self.capacity > 0:
                ok = self._cond.wait_for(
                    lambda: self._closed or len(self._buf) < self.capacity,
                    remaining())
                if not ok:
                    return False
                if self._closed:
                    raise ChannelClosedError(
                        f"send on closed channel {self.name}")
                self._buf.append(value)
                self._cond.notify_all()
                return True
            # unbuffered rendezvous: advertise the blocked sender, wait for
            # a receiver + empty slot, park a tokened value, wait for pickup
            self._send_waiting += 1
            self._cond.notify_all()
            try:
                ok = self._cond.wait_for(
                    lambda: self._closed or (self._recv_waiting > 0
                                             and not self._buf), remaining())
                if not ok:
                    return False
                if self._closed:
                    raise ChannelClosedError(
                        f"send on closed channel {self.name}")
                token = object()
                self._buf.append((token, value))
                self._cond.notify_all()
                self._cond.wait_for(
                    lambda: self._closed or not any(
                        t is token for t, _ in self._buf), remaining())
                still_parked = any(t is token for t, _ in self._buf)
                if still_parked:
                    self._buf = deque((t, v) for t, v in self._buf
                                      if t is not token)
                    if self._closed:
                        raise ChannelClosedError(
                            f"send on closed channel {self.name}")
                    return False   # timeout before rendezvous completed
                return True
            finally:
                self._send_waiting -= 1

    def recv(self, timeout: Optional[float] = None) -> Tuple[Any, bool]:
        """Blocks for a value. Returns (value, True), or (None, False) ONLY
        once the channel is closed and drained (Go semantics; ≙ Receive
        returning false, channel_impl.h). A timeout raises ChannelTimeout so
        drain loops can't mistake a slow producer for end-of-stream."""
        with self._cond:
            self._recv_waiting += 1
            self._cond.notify_all()
            try:
                ok = self._cond.wait_for(
                    lambda: self._buf or self._closed, timeout)
                if not ok:
                    raise ChannelTimeout(
                        f"recv on channel {self.name!r} timed out "
                        f"after {timeout}s")
                if self._buf:
                    v = self._buf.popleft()
                    if self.capacity == 0:
                        v = v[1]          # unwrap (token, value)
                    self._cond.notify_all()
                    return v, True
                return None, False    # closed and drained
            finally:
                self._recv_waiting -= 1

    def close(self):
        """Wake all blocked senders/receivers (≙ ChannelImpl::Close)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def __len__(self):
        with self._lock:
            return len(self._buf)


def make_channel(dtype=None, capacity: int = 0, name: str = "") -> Channel:
    """≙ fluid.concurrency.make_channel (concurrency.py:282)."""
    return Channel(capacity=capacity, dtype=dtype, name=name)


def channel_send(channel: Channel, value, timeout=None) -> bool:
    return channel.send(value, timeout=timeout)


def channel_recv(channel: Channel, timeout=None) -> Tuple[Any, bool]:
    return channel.recv(timeout=timeout)


def channel_close(channel: Channel):
    channel.close()


class Go:
    """Run a block concurrently (≙ go_op.cc / fluid.concurrency.Go:28).
    Usable as a decorator or context manager:

        @Go
        def producer(): ...
        producer.join()
    """

    def __init__(self, fn: Callable = None):
        self._thread: Optional[threading.Thread] = None
        self.result = None
        self.exception: Optional[BaseException] = None
        if fn is not None:
            self._start(fn)

    def _start(self, fn, *args, **kwargs):
        def run():
            try:
                self.result = fn(*args, **kwargs)
            except BaseException as e:  # surfaced on join
                self.exception = e
        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def join(self, timeout: Optional[float] = None):
        if self._thread:
            self._thread.join(timeout)
        if self.exception is not None:
            raise self.exception
        return self.result


def go(fn: Callable, *args, **kwargs) -> Go:
    """go(fn, ...) — launch fn concurrently, return the handle."""
    g = Go.__new__(Go)
    g._thread = None
    g.result = None
    g.exception = None
    g._start(fn, *args, **kwargs)
    return g


class Select:
    """Multi-way channel select (≙ select_op.cc / fluid.concurrency.Select
    :196). Build cases then run():

        sel = Select()
        sel.case_recv(ch_a, lambda v, ok: ...)
        sel.case_send(ch_b, value, lambda: ...)
        sel.default(lambda: ...)          # optional, makes run() non-blocking
        which = sel.run(timeout=...)      # index of the fired case

    Ready-case choice is uniformly random (Go fairness).
    """

    _POLL_S = 0.0005

    def __init__(self):
        self._cases: List[tuple] = []
        self._default: Optional[Callable] = None

    def case_recv(self, ch: Channel, body: Callable[[Any, bool], Any]):
        self._cases.append(("recv", ch, None, body))
        return self

    def case_send(self, ch: Channel, value, body: Callable[[], Any]):
        self._cases.append(("send", ch, value, body))
        return self

    def default(self, body: Callable[[], Any]):
        self._default = body
        return self

    def run(self, timeout: Optional[float] = None) -> int:
        """Execute one ready case; returns its index (-1 for default).
        Raises TimeoutError when nothing becomes ready in `timeout`."""
        enforce(self._cases or self._default is not None,
                "select with no cases", exc=InvalidArgumentError)
        import time

        def attempt(i):
            """Try case i with a tiny timeout; return True if it fired."""
            kind, ch, value, body = self._cases[i]
            if kind == "recv":
                try:
                    v, ok = ch.recv(timeout=self._POLL_S)
                except ChannelTimeout:
                    return False   # lost the race; retry
                body(v, ok)
                return True
            try:
                sent = ch.send(value, timeout=self._POLL_S)
            except ChannelClosedError:
                raise            # surfaced to the caller, like Go's panic
            if sent:
                body()
                return True
            return False

        deadline = None if timeout is None else time.time() + timeout
        while True:
            ready = [i for i, (kind, ch, _, _) in enumerate(self._cases)
                     if (ch._can_recv() if kind == "recv"
                         else ch._can_send())]
            if ready:
                i = random.choice(ready)
                if attempt(i):
                    return i
            elif self._default is not None:
                self._default()
                return -1
            else:
                # nothing advertises readiness — actively attempt each case
                # briefly so two selects (send-side and recv-side) on an
                # unbuffered channel still rendezvous
                order = list(range(len(self._cases)))
                random.shuffle(order)
                fired = False
                for i in order:
                    if attempt(i):
                        fired = True
                        break
                if fired:
                    return i
            if deadline is not None and time.time() >= deadline:
                raise TimeoutError("select timed out")
