// Native record container + threaded loader for the TPU framework's data
// layer.
//
// Capability equivalent of the reference's RecordIO subsystem
// (reference: paddle/fluid/recordio/{chunk,scanner,writer}.h — chunked,
// CRC-checked, seekable record files) and its threaded reader stack
// (reference: paddle/fluid/operators/reader/buffered_reader.h:27 async
// prefetch + reader/lod_tensor_blocking_queue.h:31 bounded queue). Design is
// new: single translation unit, C ABI for ctypes (no pybind11 in this
// toolchain), chunk-resync on corruption, N producer threads feeding one
// bounded queue.
//
// File format (little-endian):
//   file   := chunk*
//   chunk  := MAGIC u32 | flags u32 | raw_len u32 | comp_len u32
//             | crc32(payload) u32 | num_records u32 | payload
//   payload:= (rec_len u32 | bytes)*     (zlib-deflated iff flags & 1)
// A corrupt chunk is skipped by scanning forward for the next MAGIC.

#include <zlib.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x50545052;  // "PTPR"
constexpr uint32_t kFlagDeflate = 1u;

struct Header {
  uint32_t magic, flags, raw_len, comp_len, crc, num_records;
};

uint32_t Crc(const char* data, size_t n) {
  return static_cast<uint32_t>(
      crc32(0L, reinterpret_cast<const Bytef*>(data), n));
}

// ---------------------------------------------------------------- writer
class Writer {
 public:
  Writer(const char* path, uint32_t max_chunk_bytes, bool compress)
      : f_(std::fopen(path, "wb")),
        max_chunk_bytes_(max_chunk_bytes ? max_chunk_bytes : (1u << 20)),
        compress_(compress) {}

  bool ok() const { return f_ != nullptr; }

  bool Write(const char* data, uint32_t len) {
    uint32_t n = len;
    buf_.append(reinterpret_cast<const char*>(&n), sizeof(n));
    buf_.append(data, len);
    ++num_records_;
    if (buf_.size() >= max_chunk_bytes_) return Flush();
    return true;
  }

  bool Flush() {
    if (num_records_ == 0) return true;
    std::string payload;
    uint32_t flags = 0;
    if (compress_) {
      uLongf bound = compressBound(buf_.size());
      payload.resize(bound);
      if (compress2(reinterpret_cast<Bytef*>(&payload[0]), &bound,
                    reinterpret_cast<const Bytef*>(buf_.data()), buf_.size(),
                    Z_DEFAULT_COMPRESSION) != Z_OK)
        return false;
      payload.resize(bound);
      flags |= kFlagDeflate;
    } else {
      payload = buf_;
    }
    Header h{kMagic, flags, static_cast<uint32_t>(buf_.size()),
             static_cast<uint32_t>(payload.size()),
             Crc(payload.data(), payload.size()), num_records_};
    if (std::fwrite(&h, sizeof(h), 1, f_) != 1) return false;
    if (!payload.empty() &&
        std::fwrite(payload.data(), payload.size(), 1, f_) != 1)
      return false;
    buf_.clear();
    num_records_ = 0;
    return true;
  }

  bool Close() {
    bool ok = true;
    if (f_) {
      ok = Flush();
      ok = std::fclose(f_) == 0 && ok;
      f_ = nullptr;
    }
    return ok;
  }

  ~Writer() { Close(); }

 private:
  std::FILE* f_;
  uint32_t max_chunk_bytes_;
  bool compress_;
  std::string buf_;
  uint32_t num_records_ = 0;
};

// --------------------------------------------------------------- scanner
class Scanner {
 public:
  explicit Scanner(const char* path) : f_(std::fopen(path, "rb")) {
    if (f_) {
      std::fseek(f_, 0, SEEK_END);
      file_size_ = ftello(f_);
      std::fseek(f_, 0, SEEK_SET);
    }
  }
  bool ok() const { return f_ != nullptr; }

  // Returns pointer/len valid until the next call; nullptr at EOF.
  const char* Next(uint32_t* len) {
    while (idx_ >= records_.size()) {
      if (!LoadChunk()) return nullptr;
    }
    const std::string& r = records_[idx_++];
    *len = static_cast<uint32_t>(r.size());
    return r.data();
  }

  uint32_t skipped_chunks() const { return skipped_; }

  ~Scanner() {
    if (f_) std::fclose(f_);
  }

 private:
  // Reads the next valid chunk into records_; resyncs past corruption.
  bool LoadChunk() {
    Header h;
    for (;;) {
      long long pos = ftello(f_);
      if (std::fread(&h, sizeof(h), 1, f_) != 1) return false;
      if (h.magic != kMagic) {
        // resync: advance one byte past `pos` and scan for magic
        ++skipped_;
        fseeko(f_, pos + 1, SEEK_SET);
        if (!Resync()) return false;
        continue;
      }
      // bound the untrusted length by the bytes actually left in the file
      // BEFORE allocating — a corrupt comp_len must become a skipped chunk,
      // not a std::bad_alloc escaping the C ABI
      long long here = ftello(f_);
      if (here < 0 ||
          static_cast<long long>(h.comp_len) > file_size_ - here) {
        ++skipped_;
        fseeko(f_, pos + 1, SEEK_SET);
        if (!Resync()) return false;
        continue;
      }
      std::string payload(h.comp_len, '\0');
      if (h.comp_len &&
          std::fread(&payload[0], h.comp_len, 1, f_) != 1) {
        // short read: corrupt length header or truncated file — count it
        // and resync instead of silently ending the scan
        ++skipped_;
        fseeko(f_, pos + 1, SEEK_SET);
        if (!Resync()) return false;
        continue;
      }
      if (Crc(payload.data(), payload.size()) != h.crc) {
        ++skipped_;
        fseeko(f_, pos + 1, SEEK_SET);
        if (!Resync()) return false;
        continue;
      }
      std::string raw;
      if (h.flags & kFlagDeflate) {
        raw.resize(h.raw_len);
        uLongf dlen = h.raw_len;
        if (uncompress(reinterpret_cast<Bytef*>(&raw[0]), &dlen,
                       reinterpret_cast<const Bytef*>(payload.data()),
                       payload.size()) != Z_OK ||
            dlen != h.raw_len) {
          ++skipped_;
          fseeko(f_, pos + 1, SEEK_SET);
          if (!Resync()) return false;
          continue;
        }
      } else {
        raw.swap(payload);
      }
      records_.clear();
      idx_ = 0;
      size_t off = 0;
      bool bad = false;
      for (uint32_t i = 0; i < h.num_records; ++i) {
        if (off + sizeof(uint32_t) > raw.size()) { bad = true; break; }
        uint32_t n;
        std::memcpy(&n, raw.data() + off, sizeof(n));
        off += sizeof(n);
        if (off + n > raw.size()) { bad = true; break; }
        records_.emplace_back(raw.data() + off, n);
        off += n;
      }
      if (bad) {
        ++skipped_;
        records_.clear();
        continue;
      }
      return !records_.empty();
    }
  }

  // Scan forward byte-by-byte (buffered) until MAGIC; leaves file pos at it.
  bool Resync() {
    uint32_t window = 0;
    int c;
    size_t got = 0;
    while ((c = std::fgetc(f_)) != EOF) {
      window = (window >> 8) | (static_cast<uint32_t>(c) << 24);
      if (++got >= 4 && window == kMagic) {
        std::fseek(f_, -4, SEEK_CUR);
        return true;
      }
    }
    return false;
  }

  std::FILE* f_;
  std::vector<std::string> records_;
  size_t idx_ = 0;
  uint32_t skipped_ = 0;
  long long file_size_ = 0;
};

// ------------------------------------------------- bounded blocking queue
// ≙ reference LoDTensorBlockingQueue (reader/lod_tensor_blocking_queue.h:31)
class BlockingQueue {
 public:
  explicit BlockingQueue(size_t cap) : cap_(cap) {}

  bool Push(std::string&& v) {
    std::unique_lock<std::mutex> lk(mu_);
    not_full_.wait(lk, [&] { return q_.size() < cap_ || closed_; });
    if (closed_) return false;
    q_.push_back(std::move(v));
    not_empty_.notify_one();
    return true;
  }

  // false => queue closed AND drained
  bool Pop(std::string* out) {
    std::unique_lock<std::mutex> lk(mu_);
    not_empty_.wait(lk, [&] { return !q_.empty() || closed_; });
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    not_full_.notify_one();
    return true;
  }

  void Close() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = true;
    not_empty_.notify_all();
    not_full_.notify_all();
  }

  size_t Size() {
    std::lock_guard<std::mutex> lk(mu_);
    return q_.size();
  }

 private:
  std::mutex mu_;
  std::condition_variable not_empty_, not_full_;
  std::deque<std::string> q_;
  size_t cap_;
  bool closed_ = false;
};

// ------------------------------------------------------- threaded loader
// N worker threads scan disjoint file subsets into one bounded queue
// (≙ open_files_op multi-file reading + double-buffer prefetch).
class Loader {
 public:
  Loader(const std::vector<std::string>& files, int num_threads,
         size_t queue_cap)
      : queue_(queue_cap) {
    if (files.empty()) {  // no workers will ever close the queue
      queue_.Close();
      return;
    }
    if (num_threads <= 0) num_threads = 1;
    if (num_threads > static_cast<int>(files.size()))
      num_threads = static_cast<int>(files.size());
    pending_workers_ = num_threads;
    for (int t = 0; t < num_threads; ++t) {
      std::vector<std::string> mine;
      for (size_t i = t; i < files.size();
           i += static_cast<size_t>(num_threads))
        mine.push_back(files[i]);
      workers_.emplace_back([this, mine] { Work(mine); });
    }
  }

  bool Next(std::string* out) { return queue_.Pop(out); }

  void Shutdown() {
    queue_.Close();
    for (auto& w : workers_)
      if (w.joinable()) w.join();
    workers_.clear();
  }

  ~Loader() { Shutdown(); }

  uint32_t failed_files() const { return failed_files_.load(); }
  uint32_t skipped_chunks() const { return skipped_chunks_.load(); }

 private:
  void Work(const std::vector<std::string>& files) {
    for (const auto& path : files) {
      Scanner s(path.c_str());
      if (!s.ok()) {
        ++failed_files_;  // surfaced via rio_loader_failed_files
        continue;
      }
      uint32_t len;
      const char* p;
      while ((p = s.Next(&len)) != nullptr) {
        if (!queue_.Push(std::string(p, len))) return;  // closed
      }
      skipped_chunks_ += s.skipped_chunks();
    }
    if (--pending_workers_ == 0) queue_.Close();  // EOF for consumers
  }

  BlockingQueue queue_;
  std::vector<std::thread> workers_;
  std::atomic<int> pending_workers_{0};
  std::atomic<uint32_t> failed_files_{0};
  std::atomic<uint32_t> skipped_chunks_{0};
};

thread_local std::string g_last;  // holds Pop/Next result for the C ABI

}  // namespace

// ---------------------------------------------------------------- C ABI
extern "C" {

void* rio_writer_open(const char* path, uint32_t max_chunk_bytes,
                      int compress) {
  auto* w = new Writer(path, max_chunk_bytes, compress != 0);
  if (!w->ok()) {
    delete w;
    return nullptr;
  }
  return w;
}

int rio_writer_write(void* h, const char* data, uint32_t len) {
  return static_cast<Writer*>(h)->Write(data, len) ? 0 : -1;
}

int rio_writer_flush(void* h) {
  return static_cast<Writer*>(h)->Flush() ? 0 : -1;
}

int rio_writer_close(void* h) {
  auto* w = static_cast<Writer*>(h);
  int rc = w->Close() ? 0 : -1;
  delete w;
  return rc;
}

void* rio_scanner_open(const char* path) {
  auto* s = new Scanner(path);
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

// returns pointer to record bytes (valid until next call on this scanner
// from the same thread) or nullptr at EOF
const char* rio_scanner_next(void* h, uint32_t* len) {
  const char* p = static_cast<Scanner*>(h)->Next(len);
  // Scanner::Next's pointer stays valid until the next call on this
  // scanner — no defensive copy needed.
  return p;
}

uint32_t rio_scanner_skipped(void* h) {
  return static_cast<Scanner*>(h)->skipped_chunks();
}

void rio_scanner_close(void* h) { delete static_cast<Scanner*>(h); }

void* rio_loader_open(const char** paths, int num_paths, int num_threads,
                      uint32_t queue_cap) {
  std::vector<std::string> files(paths, paths + num_paths);
  return new Loader(files, num_threads, queue_cap ? queue_cap : 64);
}

const char* rio_loader_next(void* h, uint32_t* len) {
  if (!static_cast<Loader*>(h)->Next(&g_last)) return nullptr;
  *len = static_cast<uint32_t>(g_last.size());
  return g_last.data();
}

uint32_t rio_loader_failed_files(void* h) {
  return static_cast<Loader*>(h)->failed_files();
}

uint32_t rio_loader_skipped(void* h) {
  return static_cast<Loader*>(h)->skipped_chunks();
}

void rio_loader_close(void* h) { delete static_cast<Loader*>(h); }

}  // extern "C"
