// ptpu_predict: native (C++) serving entry for exported paddle_tpu models.
//
// Loads the single-platform StableHLO artifact written by
// io.export_inference_model (__exported_native__.stablehlo +
// __exported_native__.meta), feeds it .npy input tensors, executes it
// through the TensorFlow eager C API's XlaCallModule kernel (which JIT
// compiles the module with XLA:CPU in-process), and writes each output as
// out<i>.npy.
//
// Capability equivalent of the reference's C++ inference stack: the
// deployable unit a C++ server loads with no Python anywhere in the
// process (reference paddle/fluid/inference/api/paddle_inference_api.h:1,
// api_impl.cc:126 NativePaddlePredictor::Run, inference/io.cc Load).
// The runtime library here is libtensorflow_cc's exported C API — chosen
// because this environment ships no standalone PJRT plugin .so; the
// XlaCallModule path is the same one jax2tf serving uses in production.
//
// Usage:
//   ptpu_predict <export_dir> <input0.npy> [<input1.npy> ...] [--out DIR]
//
// Inputs are positional in the meta's `in` order. Symbolic (-1) dims are
// refined from the actual inputs by the kernel.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <sstream>
#include <string>
#include <vector>

#include "tensorflow/c/c_api.h"
#include "tensorflow/c/eager/c_api.h"

namespace {

[[noreturn]] void Die(const std::string& msg) {
  std::fprintf(stderr, "ptpu_predict: %s\n", msg.c_str());
  std::exit(1);
}

void CheckOk(TF_Status* s, const char* what) {
  if (TF_GetCode(s) != TF_OK) {
    Die(std::string(what) + ": " + TF_Message(s));
  }
}

std::string ReadFile(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) Die("cannot open " + path);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

// -- dtype mapping ---------------------------------------------------------

struct DType {
  TF_DataType tf;
  const char* npy;    // .npy descr (little-endian)
  size_t size;
};

DType DTypeByName(const std::string& name) {
  if (name == "float32") return {TF_FLOAT, "<f4", 4};
  if (name == "float64") return {TF_DOUBLE, "<f8", 8};
  if (name == "int32") return {TF_INT32, "<i4", 4};
  if (name == "int64") return {TF_INT64, "<i8", 8};
  if (name == "uint8") return {TF_UINT8, "|u1", 1};
  if (name == "int8") return {TF_INT8, "|i1", 1};
  if (name == "bool") return {TF_BOOL, "|b1", 1};
  Die("unsupported dtype " + name);
}

// -- minimal .npy v1 reader/writer (C-order, little-endian) ----------------

struct Npy {
  std::string descr;
  std::vector<int64_t> shape;
  std::string data;
};

Npy ReadNpy(const std::string& path) {
  std::string raw = ReadFile(path);
  if (raw.size() < 10 || raw.compare(0, 6, "\x93NUMPY") != 0)
    Die(path + " is not a .npy file");
  int major = static_cast<unsigned char>(raw[6]);
  size_t hlen, hoff;
  if (major == 1) {
    hlen = static_cast<unsigned char>(raw[8]) |
           (static_cast<unsigned char>(raw[9]) << 8);
    hoff = 10;
  } else {
    hlen = 0;
    for (int i = 0; i < 4; ++i)
      hlen |= static_cast<size_t>(static_cast<unsigned char>(raw[8 + i]))
              << (8 * i);
    hoff = 12;
  }
  std::string header = raw.substr(hoff, hlen);
  Npy out;
  size_t d = header.find("'descr':");
  size_t q1 = header.find('\'', d + 8);
  size_t q2 = header.find('\'', q1 + 1);
  out.descr = header.substr(q1 + 1, q2 - q1 - 1);
  if (header.find("'fortran_order': False") == std::string::npos)
    Die(path + ": fortran_order arrays are not supported");
  size_t sh = header.find("'shape':");
  size_t p1 = header.find('(', sh);
  size_t p2 = header.find(')', p1);
  std::string dims = header.substr(p1 + 1, p2 - p1 - 1);
  std::stringstream ss(dims);
  std::string tok;
  while (std::getline(ss, tok, ',')) {
    if (tok.find_first_not_of(" \t") == std::string::npos) continue;
    out.shape.push_back(std::stoll(tok));
  }
  out.data = raw.substr(hoff + hlen);
  return out;
}

void WriteNpy(const std::string& path, const std::string& descr,
              const std::vector<int64_t>& shape, const void* data,
              size_t nbytes) {
  std::ostringstream hd;
  hd << "{'descr': '" << descr << "', 'fortran_order': False, 'shape': (";
  for (size_t i = 0; i < shape.size(); ++i) hd << shape[i] << ",";
  hd << "), }";
  std::string header = hd.str();
  size_t total = 10 + header.size() + 1;
  size_t pad = (64 - total % 64) % 64;
  header += std::string(pad, ' ');
  header += '\n';
  std::ofstream f(path, std::ios::binary);
  if (!f) Die("cannot write " + path);
  f << "\x93NUMPY" << '\x01' << '\x00';
  uint16_t hlen = static_cast<uint16_t>(header.size());
  f.write(reinterpret_cast<const char*>(&hlen), 2);
  f << header;
  f.write(static_cast<const char*>(data), nbytes);
}

// -- meta file (key-value lines written by io.export_inference_model) -----

struct TensorSpec {
  std::string name;
  std::string dtype;
  std::vector<int64_t> dims;
};

struct Meta {
  int version = 9;
  std::vector<TensorSpec> ins, outs;
};

Meta ReadMeta(const std::string& path) {
  std::ifstream f(path);
  if (!f) Die("cannot open " + path);
  Meta m;
  std::string line;
  while (std::getline(f, line)) {
    std::stringstream ss(line);
    std::string key;
    ss >> key;
    if (key == "version") {
      ss >> m.version;
    } else if (key == "in" || key == "out") {
      TensorSpec t;
      ss >> t.name >> t.dtype;
      int64_t d;
      while (ss >> d) t.dims.push_back(d);
      (key == "in" ? m.ins : m.outs).push_back(t);
    }
  }
  if (m.outs.empty()) Die("no outputs in " + path);
  return m;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr,
                 "usage: %s <export_dir> <input0.npy> [...] [--out DIR]\n",
                 argv[0]);
    return 2;
  }
  std::string dir = argv[1];
  std::string out_dir = ".";
  std::vector<std::string> input_paths;
  for (int i = 2; i < argc; ++i) {
    if (std::strcmp(argv[i], "--out") == 0 && i + 1 < argc) {
      out_dir = argv[++i];
    } else {
      input_paths.push_back(argv[i]);
    }
  }

  Meta meta = ReadMeta(dir + "/__exported_native__.meta");
  std::string module = ReadFile(dir + "/__exported_native__.stablehlo");
  if (input_paths.size() != meta.ins.size())
    Die("expected " + std::to_string(meta.ins.size()) + " inputs, got " +
        std::to_string(input_paths.size()));

  TF_Status* s = TF_NewStatus();
  TFE_ContextOptions* copts = TFE_NewContextOptions();
  TFE_Context* ctx = TFE_NewContext(copts, s);
  CheckOk(s, "TFE_NewContext");

  // stage inputs
  std::vector<TFE_TensorHandle*> handles;
  std::vector<TF_DataType> tin;
  for (size_t i = 0; i < input_paths.size(); ++i) {
    Npy npy = ReadNpy(input_paths[i]);
    DType dt = DTypeByName(meta.ins[i].dtype);
    if (npy.descr != dt.npy)
      Die(input_paths[i] + ": dtype " + npy.descr + " but model expects " +
          meta.ins[i].dtype + " (" + dt.npy + ")");
    TF_Tensor* t = TF_AllocateTensor(dt.tf, npy.shape.data(),
                                     static_cast<int>(npy.shape.size()),
                                     npy.data.size());
    std::memcpy(TF_TensorData(t), npy.data.data(), npy.data.size());
    handles.push_back(TFE_NewTensorHandle(t, s));
    CheckOk(s, "TFE_NewTensorHandle");
    tin.push_back(dt.tf);
  }

  // one XlaCallModule op = the whole model (params are constants inside)
  TFE_Op* op = TFE_NewOp(ctx, "XlaCallModule", s);
  CheckOk(s, "TFE_NewOp(XlaCallModule)");
  TFE_OpSetAttrString(op, "module", module.data(), module.size());
  TFE_OpSetAttrInt(op, "version", meta.version);
  TFE_OpSetAttrTypeList(op, "Tin", tin.data(),
                        static_cast<int>(tin.size()));
  std::vector<TF_DataType> tout;
  std::vector<const int64_t*> sout;
  std::vector<int> sout_ndims;
  for (const auto& o : meta.outs) {
    tout.push_back(DTypeByName(o.dtype).tf);
    sout.push_back(o.dims.data());
    sout_ndims.push_back(static_cast<int>(o.dims.size()));
  }
  TFE_OpSetAttrTypeList(op, "Tout", tout.data(),
                        static_cast<int>(tout.size()));
  TFE_OpSetAttrShapeList(op, "Sout", sout.data(), sout_ndims.data(),
                         static_cast<int>(sout.size()), s);
  CheckOk(s, "Sout");
  const void* plat[1] = {"CPU"};
  size_t plat_len[1] = {3};
  TFE_OpSetAttrStringList(op, "platforms", plat, plat_len, 1);
  TFE_OpSetAttrStringList(op, "dim_args_spec", nullptr, nullptr, 0);
  TFE_OpSetAttrStringList(op, "disabled_checks", nullptr, nullptr, 0);
  TFE_OpSetAttrFunctionList(op, "function_list", nullptr, 0);
  TFE_OpSetAttrBool(op, "has_token_input_output", 0);
  for (auto* h : handles) {
    TFE_OpAddInput(op, h, s);
    CheckOk(s, "TFE_OpAddInput");
  }

  std::vector<TFE_TensorHandle*> outs(meta.outs.size(), nullptr);
  int nout = static_cast<int>(outs.size());
  TFE_Execute(op, outs.data(), &nout, s);
  CheckOk(s, "TFE_Execute");

  for (int i = 0; i < nout; ++i) {
    TF_Tensor* t = TFE_TensorHandleResolve(outs[i], s);
    CheckOk(s, "TFE_TensorHandleResolve");
    std::vector<int64_t> shape(TF_NumDims(t));
    for (size_t d = 0; d < shape.size(); ++d)
      shape[d] = TF_Dim(t, static_cast<int>(d));
    DType dt = DTypeByName(meta.outs[i].dtype);
    std::string path = out_dir + "/out" + std::to_string(i) + ".npy";
    WriteNpy(path, dt.npy, shape, TF_TensorData(t), TF_TensorByteSize(t));
    std::printf("%s %s -> %s\n", meta.outs[i].name.c_str(),
                meta.outs[i].dtype.c_str(), path.c_str());
  }
  return 0;
}
