// ptpu_predict: native (C++) serving entry for exported paddle_tpu models.
//
// Loads the single-platform StableHLO artifact written by
// io.export_inference_model (__exported_native__.stablehlo +
// __exported_native__.meta) and executes it through the TensorFlow eager
// C API's XlaCallModule kernel (which JIT compiles the module with XLA:CPU
// in-process). Two modes:
//
//   ptpu_predict <export_dir> <input0.npy> [...] [--out DIR]
//     one-shot CLI: feed .npy tensors, write each output as out<i>.npy
//
//   ptpu_predict <export_dir> --serve [PORT]
//     server mode: long-lived TCP loop speaking the same length-prefixed
//     JSON + raw-tensor protocol as paddle_tpu.serving.PredictorServer, so
//     the Python PredictorClient (or any client of that protocol) talks to
//     this process directly. Each connection is served by a thread holding
//     its OWN TFE context over the shared module bytes — the
//     clone-per-thread contract of the reference's NativePaddlePredictor
//     (api_impl.cc:170 ::Clone), with a reader/worker split per connection
//     so pipelining clients cannot deadlock the pair (≙ serving.py).
//
// Capability equivalent of the reference's C++ inference stack: the
// deployable unit a C++ server loads with no Python anywhere in the
// process (reference paddle/fluid/inference/api/paddle_inference_api.h:1,
// api_impl.cc:126 NativePaddlePredictor::Run, inference/io.cc Load).
// The runtime library here is libtensorflow_cc's exported C API — chosen
// because this environment ships no standalone PJRT plugin .so; the
// XlaCallModule path is the same one jax2tf serving uses in production.
//
// Inputs are positional in the meta's `in` order (CLI) or matched by name
// (server). Symbolic (-1) dims are refined from the actual inputs by the
// kernel.

#include <arpa/inet.h>
#include <endian.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cctype>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <fstream>
#include <map>
#include <memory>
#include <mutex>
#include <sstream>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

#include "tensorflow/c/c_api.h"
#include "tensorflow/c/eager/c_api.h"

namespace {

[[noreturn]] void Die(const std::string& msg) {
  throw std::runtime_error(msg);
}

void CheckOk(TF_Status* s, const char* what) {
  if (TF_GetCode(s) != TF_OK) {
    Die(std::string(what) + ": " + TF_Message(s));
  }
}

std::string ReadFile(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) Die("cannot open " + path);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

// -- dtype mapping ---------------------------------------------------------

struct DType {
  TF_DataType tf;
  const char* npy;    // .npy descr (little-endian)
  size_t size;
};

DType DTypeByName(const std::string& name) {
  if (name == "float32") return {TF_FLOAT, "<f4", 4};
  if (name == "float64") return {TF_DOUBLE, "<f8", 8};
  if (name == "int32") return {TF_INT32, "<i4", 4};
  if (name == "int64") return {TF_INT64, "<i8", 8};
  if (name == "uint8") return {TF_UINT8, "|u1", 1};
  if (name == "int8") return {TF_INT8, "|i1", 1};
  if (name == "bool") return {TF_BOOL, "|b1", 1};
  Die("unsupported dtype " + name);
}

// -- minimal .npy v1 reader/writer (C-order, little-endian) ----------------

struct Npy {
  std::string descr;
  std::vector<int64_t> shape;
  std::string data;
};

Npy ReadNpy(const std::string& path) {
  std::string raw = ReadFile(path);
  if (raw.size() < 10 || raw.compare(0, 6, "\x93NUMPY") != 0)
    Die(path + " is not a .npy file");
  int major = static_cast<unsigned char>(raw[6]);
  size_t hlen, hoff;
  if (major == 1) {
    hlen = static_cast<unsigned char>(raw[8]) |
           (static_cast<unsigned char>(raw[9]) << 8);
    hoff = 10;
  } else {
    hlen = 0;
    for (int i = 0; i < 4; ++i)
      hlen |= static_cast<size_t>(static_cast<unsigned char>(raw[8 + i]))
              << (8 * i);
    hoff = 12;
  }
  std::string header = raw.substr(hoff, hlen);
  Npy out;
  size_t d = header.find("'descr':");
  size_t q1 = header.find('\'', d + 8);
  size_t q2 = header.find('\'', q1 + 1);
  out.descr = header.substr(q1 + 1, q2 - q1 - 1);
  if (header.find("'fortran_order': False") == std::string::npos)
    Die(path + ": fortran_order arrays are not supported");
  size_t sh = header.find("'shape':");
  size_t p1 = header.find('(', sh);
  size_t p2 = header.find(')', p1);
  std::string dims = header.substr(p1 + 1, p2 - p1 - 1);
  std::stringstream ss(dims);
  std::string tok;
  while (std::getline(ss, tok, ',')) {
    if (tok.find_first_not_of(" \t") == std::string::npos) continue;
    out.shape.push_back(std::stoll(tok));
  }
  out.data = raw.substr(hoff + hlen);
  return out;
}

void WriteNpy(const std::string& path, const std::string& descr,
              const std::vector<int64_t>& shape, const void* data,
              size_t nbytes) {
  std::ostringstream hd;
  hd << "{'descr': '" << descr << "', 'fortran_order': False, 'shape': (";
  for (size_t i = 0; i < shape.size(); ++i) hd << shape[i] << ",";
  hd << "), }";
  std::string header = hd.str();
  size_t total = 10 + header.size() + 1;
  size_t pad = (64 - total % 64) % 64;
  header += std::string(pad, ' ');
  header += '\n';
  std::ofstream f(path, std::ios::binary);
  if (!f) Die("cannot write " + path);
  f << "\x93NUMPY" << '\x01' << '\x00';
  uint16_t hlen = static_cast<uint16_t>(header.size());
  f.write(reinterpret_cast<const char*>(&hlen), 2);
  f << header;
  f.write(static_cast<const char*>(data), nbytes);
}

// -- meta file (key-value lines written by io.export_inference_model) -----

struct TensorSpec {
  std::string name;
  std::string dtype;
  std::vector<int64_t> dims;
};

struct Meta {
  int version = 9;
  std::vector<TensorSpec> ins, outs;
};

Meta ReadMeta(const std::string& path) {
  std::ifstream f(path);
  if (!f) Die("cannot open " + path);
  Meta m;
  std::string line;
  while (std::getline(f, line)) {
    std::stringstream ss(line);
    std::string key;
    ss >> key;
    if (key == "version") {
      ss >> m.version;
    } else if (key == "in" || key == "out") {
      TensorSpec t;
      ss >> t.name >> t.dtype;
      int64_t d;
      while (ss >> d) t.dims.push_back(d);
      (key == "in" ? m.ins : m.outs).push_back(t);
    }
  }
  if (m.outs.empty()) Die("no outputs in " + path);
  return m;
}

// -- minimal JSON (objects/arrays/strings/numbers), just enough for the
//    serving protocol's fixed request schema --------------------------------

struct Json {
  enum Kind { kNull, kBool, kNum, kStr, kArr, kObj } kind = kNull;
  bool b = false;
  double num = 0;
  std::string str;
  std::vector<Json> arr;
  std::map<std::string, Json> obj;

  bool Has(const std::string& k) const { return obj.count(k) != 0; }
  const Json& At(const std::string& k) const {
    auto it = obj.find(k);
    if (it == obj.end()) Die("missing JSON key '" + k + "'");
    return it->second;
  }
};

class JsonParser {
 public:
  explicit JsonParser(const std::string& s) : s_(s) {}
  Json Parse() {
    Json v = Value();
    Ws();
    if (p_ != s_.size()) Die("trailing JSON content");
    return v;
  }

 private:
  void Ws() { while (p_ < s_.size() && std::isspace((unsigned char)s_[p_])) ++p_; }
  char Peek() {
    Ws();
    if (p_ >= s_.size()) Die("unexpected end of JSON");
    return s_[p_];
  }
  void Expect(char c) {
    if (Peek() != c) Die(std::string("expected '") + c + "' in JSON");
    ++p_;
  }
  Json Value() {
    char c = Peek();
    if (c == '{') return Object();
    if (c == '[') return Array();
    if (c == '"') { Json v; v.kind = Json::kStr; v.str = String(); return v; }
    if (c == 't' || c == 'f') return Bool();
    if (c == 'n') { Lit("null"); return Json{}; }
    return Number();
  }
  void Lit(const char* lit) {
    size_t n = std::strlen(lit);
    if (s_.compare(p_, n, lit) != 0) Die("bad JSON literal");
    p_ += n;
  }
  Json Bool() {
    Json v; v.kind = Json::kBool;
    if (s_[p_] == 't') { Lit("true"); v.b = true; } else { Lit("false"); }
    return v;
  }
  Json Number() {
    size_t start = p_;
    while (p_ < s_.size() &&
           (std::isdigit((unsigned char)s_[p_]) || std::strchr("+-.eE", s_[p_])))
      ++p_;
    Json v; v.kind = Json::kNum;
    v.num = std::stod(s_.substr(start, p_ - start));
    return v;
  }
  std::string String() {
    Expect('"');
    std::string out;
    while (p_ < s_.size() && s_[p_] != '"') {
      char c = s_[p_++];
      if (c == '\\') {
        if (p_ >= s_.size()) Die("bad JSON escape");
        char e = s_[p_++];
        switch (e) {
          case 'n': out += '\n'; break;
          case 't': out += '\t'; break;
          case 'r': out += '\r'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'u': {  // BMP only; serving names are ASCII in practice
            if (p_ + 4 > s_.size()) Die("bad \\u escape");
            unsigned code = std::stoul(s_.substr(p_, 4), nullptr, 16);
            p_ += 4;
            if (code < 0x80) { out += (char)code; }
            else if (code < 0x800) {
              out += (char)(0xC0 | (code >> 6));
              out += (char)(0x80 | (code & 0x3F));
            } else {
              out += (char)(0xE0 | (code >> 12));
              out += (char)(0x80 | ((code >> 6) & 0x3F));
              out += (char)(0x80 | (code & 0x3F));
            }
            break;
          }
          default: out += e;
        }
      } else {
        out += c;
      }
    }
    Expect('"');
    return out;
  }
  Json Array() {
    Expect('[');
    Json v; v.kind = Json::kArr;
    if (Peek() == ']') { ++p_; return v; }
    while (true) {
      v.arr.push_back(Value());
      char c = Peek();
      if (c == ',') { ++p_; continue; }
      Expect(']');
      return v;
    }
  }
  Json Object() {
    Expect('{');
    Json v; v.kind = Json::kObj;
    if (Peek() == '}') { ++p_; return v; }
    while (true) {
      std::string key = String();
      Expect(':');
      v.obj[key] = Value();
      char c = Peek();
      if (c == ',') { ++p_; continue; }
      Expect('}');
      return v;
    }
  }

  const std::string& s_;
  size_t p_ = 0;
};

std::string JsonQuote(const std::string& s) {
  std::string out = "\"";
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      case '\r': out += "\\r"; break;
      default: out += c;
    }
  }
  return out + "\"";
}

// -- module runner: one per thread/context (clone-per-thread) --------------

struct HostTensor {
  std::string name;
  std::string dtype;
  std::vector<int64_t> shape;
  std::string data;
};

class Runner {
 public:
  Runner(const Meta& meta, const std::string& module)
      : meta_(meta), module_(module), status_(TF_NewStatus()) {
    TFE_ContextOptions* copts = TFE_NewContextOptions();
    ctx_ = TFE_NewContext(copts, status_);
    TFE_DeleteContextOptions(copts);
    CheckOk(status_, "TFE_NewContext");
  }
  ~Runner() {
    if (ctx_) TFE_DeleteContext(ctx_);
    TF_DeleteStatus(status_);
  }
  Runner(const Runner&) = delete;
  Runner& operator=(const Runner&) = delete;

  // inputs in meta.ins order, dtypes already validated by the caller
  std::vector<HostTensor> Run(const std::vector<HostTensor>& inputs) {
    TF_Status* s = status_;
    std::vector<TFE_TensorHandle*> handles;
    std::vector<TF_Tensor*> tensors;
    std::vector<TFE_TensorHandle*> outs;  // declared before cleanup binds it
    std::vector<TF_DataType> tin;
    TFE_Op* op = nullptr;
    auto cleanup = [&]() {
      for (auto* h : handles) TFE_DeleteTensorHandle(h);
      for (auto* t : tensors) TF_DeleteTensor(t);
      for (auto* o : outs)
        if (o) TFE_DeleteTensorHandle(o);  // slots not yet consumed
      if (op) TFE_DeleteOp(op);  // a CheckOk threw mid-op-construction
    };
    try {
      for (size_t i = 0; i < inputs.size(); ++i) {
        DType dt = DTypeByName(meta_.ins[i].dtype);
        TF_Tensor* t = TF_AllocateTensor(
            dt.tf, inputs[i].shape.data(),
            static_cast<int>(inputs[i].shape.size()), inputs[i].data.size());
        tensors.push_back(t);
        std::memcpy(TF_TensorData(t), inputs[i].data.data(),
                    inputs[i].data.size());
        handles.push_back(TFE_NewTensorHandle(t, s));
        CheckOk(s, "TFE_NewTensorHandle");
        tin.push_back(dt.tf);
      }

      // one XlaCallModule op = the whole model (params are constants inside)
      op = TFE_NewOp(ctx_, "XlaCallModule", s);
      CheckOk(s, "TFE_NewOp(XlaCallModule)");
      TFE_OpSetAttrString(op, "module", module_.data(), module_.size());
      TFE_OpSetAttrInt(op, "version", meta_.version);
      TFE_OpSetAttrTypeList(op, "Tin", tin.data(),
                            static_cast<int>(tin.size()));
      std::vector<TF_DataType> tout;
      std::vector<const int64_t*> sout;
      std::vector<int> sout_ndims;
      for (const auto& o : meta_.outs) {
        tout.push_back(DTypeByName(o.dtype).tf);
        sout.push_back(o.dims.data());
        sout_ndims.push_back(static_cast<int>(o.dims.size()));
      }
      TFE_OpSetAttrTypeList(op, "Tout", tout.data(),
                            static_cast<int>(tout.size()));
      TFE_OpSetAttrShapeList(op, "Sout", sout.data(), sout_ndims.data(),
                             static_cast<int>(sout.size()), s);
      CheckOk(s, "Sout");
      const void* plat[1] = {"CPU"};
      size_t plat_len[1] = {3};
      TFE_OpSetAttrStringList(op, "platforms", plat, plat_len, 1);
      TFE_OpSetAttrStringList(op, "dim_args_spec", nullptr, nullptr, 0);
      TFE_OpSetAttrStringList(op, "disabled_checks", nullptr, nullptr, 0);
      TFE_OpSetAttrFunctionList(op, "function_list", nullptr, 0);
      TFE_OpSetAttrBool(op, "has_token_input_output", 0);
      for (auto* h : handles) {
        TFE_OpAddInput(op, h, s);
        CheckOk(s, "TFE_OpAddInput");
      }

      outs.assign(meta_.outs.size(), nullptr);
      int nout = static_cast<int>(outs.size());
      TFE_Execute(op, outs.data(), &nout, s);
      TFE_DeleteOp(op);
      op = nullptr;
      CheckOk(s, "TFE_Execute");

      std::vector<HostTensor> results;
      for (int i = 0; i < nout; ++i) {
        TF_Tensor* t = TFE_TensorHandleResolve(outs[i], s);
        TFE_DeleteTensorHandle(outs[i]);
        outs[i] = nullptr;  // consumed; cleanup() frees the rest on throw
        CheckOk(s, "TFE_TensorHandleResolve");
        HostTensor ht;
        ht.name = meta_.outs[i].name;
        ht.dtype = meta_.outs[i].dtype;
        ht.shape.resize(TF_NumDims(t));
        for (size_t d = 0; d < ht.shape.size(); ++d)
          ht.shape[d] = TF_Dim(t, static_cast<int>(d));
        ht.data.assign(static_cast<const char*>(TF_TensorData(t)),
                       TF_TensorByteSize(t));
        TF_DeleteTensor(t);
        results.push_back(std::move(ht));
      }
      cleanup();
      return results;
    } catch (...) {
      cleanup();
      throw;
    }
  }

  const Meta& meta() const { return meta_; }

 private:
  const Meta& meta_;
  const std::string& module_;
  TF_Status* status_;
  TFE_Context* ctx_ = nullptr;
};

// -- server mode -----------------------------------------------------------

ssize_t RecvExact(int fd, char* buf, size_t n) {
  if (n == 0) return 1;  // nothing to read is success, not peer-close
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd, buf + got, n - got, 0);
    if (r <= 0) return r;  // 0 = peer closed, <0 = error
    got += static_cast<size_t>(r);
  }
  return static_cast<ssize_t>(got);
}

bool SendAll(int fd, const char* buf, size_t n) {
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = ::send(fd, buf + sent, n - sent, MSG_NOSIGNAL);
    if (r <= 0) return false;
    sent += static_cast<size_t>(r);
  }
  return true;
}

struct Request {
  Json header;
  std::vector<std::string> buffers;
};

// serving.py protocol: u32 header length, JSON header, raw tensor bytes for
// each feed in header order. Returns false when the peer closed cleanly.
bool RecvRequest(int fd, Request* out) {
  char lenbuf[4];
  ssize_t r = RecvExact(fd, lenbuf, 4);
  if (r <= 0) return false;
  uint32_t hlen;
  std::memcpy(&hlen, lenbuf, 4);
  // wire format is little-endian. NOTE: only the length fields are
  // byte-order-converted; raw tensor payloads are memcpy'd in native
  // order, so server and client must both be little-endian hosts (the
  // only kind this is built for) — a BE build would corrupt payloads
  // silently rather than fail fast here.
  hlen = le32toh(hlen);
  if (hlen == 0) Die("malformed request: zero-length header");
  if (hlen > (64u << 20)) Die("unreasonable header length");
  std::string hraw(hlen, '\0');
  if (RecvExact(fd, hraw.data(), hlen) <= 0) return false;
  out->header = JsonParser(hraw).Parse();
  out->buffers.clear();
  if (out->header.Has("feeds")) {
    for (const auto& spec : out->header.At("feeds").arr) {
      size_t n = DTypeByName(spec.At("dtype").str).size;
      for (const auto& d : spec.At("shape").arr) {
        // a concrete wire shape must be nonnegative integers (a negative
        // or fractional dim would be UB under the unsigned cast and can
        // CHECK-abort TF_AllocateTensor, killing every connection)
        if (!(d.num >= 0) || !(d.num <= 2147483648.0) ||
            d.num != static_cast<double>(static_cast<int64_t>(d.num)))
          Die("invalid tensor dim in feed shape");
        n *= static_cast<size_t>(d.num);
        // bound INSIDE the loop: n stays <= 2^30 before each multiply and
        // each dim <= 2^31, so the product fits 64 bits — a tail-of-loop
        // check could be bypassed by overflow wrapping past 2^64
        if (n > (1u << 30)) Die("unreasonable tensor size");
      }
      std::string buf(n, '\0');
      if (n && RecvExact(fd, buf.data(), n) <= 0) return false;
      out->buffers.push_back(std::move(buf));
    }
  }
  return true;
}

bool SendResponse(int fd, const std::string& header_json,
                  const std::vector<const HostTensor*>& outs) {
  uint32_t hlen = htole32(static_cast<uint32_t>(header_json.size()));
  char lenbuf[4];
  std::memcpy(lenbuf, &hlen, 4);
  if (!SendAll(fd, lenbuf, 4)) return false;
  if (!SendAll(fd, header_json.data(), header_json.size())) return false;
  for (const auto* t : outs)
    if (!SendAll(fd, t->data.data(), t->data.size())) return false;
  return true;
}

bool SendError(int fd, const std::string& msg) {
  return SendResponse(fd, "{\"error\": " + JsonQuote(msg) + "}", {});
}

void ServeConnection(int fd, const Meta& meta, const std::string& module) {
  // per-connection clone: a private TFE context over the shared module
  // bytes (weights are constants in the module, shared read-only) — the
  // reference's Clone contract (api_impl.cc:170)
  std::unique_ptr<Runner> runner;

  // reader/worker split with a bounded queue: the reader always drains
  // incoming requests so a client that pipelines faster than it reads
  // cannot deadlock the pair with both TCP buffers full (see serving.py)
  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  std::deque<Request> queue;
  bool eof = false, worker_dead = false;
  const size_t kMaxQueued = 128;

  std::thread worker([&]() {
    // on ANY exit: unblock a reader waiting on a full queue and kick a
    // reader blocked in recv, otherwise the pair can deadlock after a
    // send failure
    struct Guard {
      std::mutex& mu; std::condition_variable& cv; bool& dead; int fd;
      ~Guard() {
        { std::lock_guard<std::mutex> lk(mu); dead = true; }
        cv.notify_all();
        ::shutdown(fd, SHUT_RDWR);
      }
    } guard{mu, cv_put, worker_dead, fd};
    while (true) {
      Request req;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_get.wait(lk, [&] { return eof || !queue.empty(); });
        if (queue.empty()) return;  // eof and drained
        req = std::move(queue.front());
        queue.pop_front();
      }
      cv_put.notify_one();
      try {
        if (!runner) runner = std::make_unique<Runner>(meta, module);
        // match feeds to meta.ins BY NAME; every declared input required
        std::map<std::string, std::pair<const Json*, const std::string*>> by_name;
        if (!req.header.Has("feeds")) Die("request has no 'feeds'");
        const auto& feeds = req.header.At("feeds").arr;
        for (size_t i = 0; i < feeds.size(); ++i)
          by_name[feeds[i].At("name").str] = {&feeds[i], &req.buffers[i]};
        std::vector<HostTensor> inputs;
        for (const auto& spec : meta.ins) {
          auto it = by_name.find(spec.name);
          if (it == by_name.end()) Die("missing feed '" + spec.name + "'");
          const Json& fj = *it->second.first;
          HostTensor ht;
          ht.name = spec.name;
          ht.dtype = fj.At("dtype").str;
          if (ht.dtype != spec.dtype)
            Die("feed '" + spec.name + "': dtype " + ht.dtype +
                " but model expects " + spec.dtype);
          for (const auto& d : fj.At("shape").arr)
            ht.shape.push_back(static_cast<int64_t>(d.num));
          ht.data = *it->second.second;
          inputs.push_back(std::move(ht));
        }

        std::vector<HostTensor> results = runner->Run(inputs);

        // optional fetch subset by output name (≙ fetch_names)
        std::vector<const HostTensor*> selected;
        if (req.header.Has("fetch")) {
          for (const auto& want : req.header.At("fetch").arr) {
            const HostTensor* found = nullptr;
            for (const auto& r : results)
              if (r.name == want.str) { found = &r; break; }
            if (!found) Die("unknown fetch '" + want.str + "'");
            selected.push_back(found);
          }
        } else {
          for (const auto& r : results) selected.push_back(&r);
        }

        std::ostringstream hj;
        hj << "{\"outs\": [";
        for (size_t i = 0; i < selected.size(); ++i) {
          const auto& t = *selected[i];
          if (i) hj << ", ";
          hj << "{\"name\": " << JsonQuote(t.name)
             << ", \"dtype\": " << JsonQuote(t.dtype) << ", \"shape\": [";
          for (size_t d = 0; d < t.shape.size(); ++d) {
            if (d) hj << ", ";
            hj << t.shape[d];
          }
          hj << "]}";
        }
        hj << "]}";
        if (!SendResponse(fd, hj.str(), selected)) break;
      } catch (const std::exception& e) {
        // per-request error: report and keep the connection alive
        if (!SendError(fd, e.what())) break;
      }
    }
  });

  try {
    while (true) {
      Request req;
      if (!RecvRequest(fd, &req)) break;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_put.wait(lk, [&] {
          return queue.size() < kMaxQueued || worker_dead;
        });
        if (worker_dead) break;
        queue.push_back(std::move(req));
      }
      cv_get.notify_one();
    }
  } catch (const std::exception& e) {
    // framing lost (malformed header): the connection cannot continue
    std::fprintf(stderr, "ptpu_predict: connection error: %s\n", e.what());
  }
  {
    std::lock_guard<std::mutex> lk(mu);
    eof = true;
  }
  cv_get.notify_all();
  worker.join();
  ::close(fd);
}

int ServeMain(const Meta& meta, const std::string& module, int port) {
  int srv = ::socket(AF_INET, SOCK_STREAM, 0);
  if (srv < 0) Die("socket() failed");
  int one = 1;
  ::setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
    Die("bind() failed");
  if (::listen(srv, 64) != 0) Die("listen() failed");
  socklen_t alen = sizeof(addr);
  ::getsockname(srv, reinterpret_cast<sockaddr*>(&addr), &alen);
  // the startup line a supervisor (or the test) parses for the bound port
  std::printf("LISTENING %d\n", ntohs(addr.sin_port));
  std::fflush(stdout);
  while (true) {
    int fd = ::accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    std::thread(ServeConnection, fd, std::cref(meta), std::cref(module))
        .detach();
  }
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  try {
    if (argc < 3) {
      std::fprintf(
          stderr,
          "usage: %s <export_dir> <input0.npy> [...] [--out DIR]\n"
          "       %s <export_dir> --serve [PORT]\n",
          argv[0], argv[0]);
      return 2;
    }
    std::string dir = argv[1];
    std::string out_dir = ".";
    bool serve = false;
    int port = 0;
    std::vector<std::string> input_paths;
    for (int i = 2; i < argc; ++i) {
      if (std::strcmp(argv[i], "--out") == 0 && i + 1 < argc) {
        out_dir = argv[++i];
      } else if (std::strcmp(argv[i], "--serve") == 0) {
        serve = true;
        if (i + 1 < argc && std::isdigit((unsigned char)argv[i + 1][0]))
          port = std::atoi(argv[++i]);
      } else {
        input_paths.push_back(argv[i]);
      }
    }

    Meta meta = ReadMeta(dir + "/__exported_native__.meta");
    std::string module = ReadFile(dir + "/__exported_native__.stablehlo");

    if (serve) return ServeMain(meta, module, port);

    if (input_paths.size() != meta.ins.size())
      Die("expected " + std::to_string(meta.ins.size()) + " inputs, got " +
          std::to_string(input_paths.size()));

    std::vector<HostTensor> inputs;
    for (size_t i = 0; i < input_paths.size(); ++i) {
      Npy npy = ReadNpy(input_paths[i]);
      DType dt = DTypeByName(meta.ins[i].dtype);
      if (npy.descr != dt.npy)
        Die(input_paths[i] + ": dtype " + npy.descr + " but model expects " +
            meta.ins[i].dtype + " (" + dt.npy + ")");
      HostTensor ht;
      ht.name = meta.ins[i].name;
      ht.dtype = meta.ins[i].dtype;
      ht.shape = npy.shape;
      ht.data = std::move(npy.data);
      inputs.push_back(std::move(ht));
    }

    Runner runner(meta, module);
    std::vector<HostTensor> outs = runner.Run(inputs);
    for (size_t i = 0; i < outs.size(); ++i) {
      DType dt = DTypeByName(outs[i].dtype);
      std::string path = out_dir + "/out" + std::to_string(i) + ".npy";
      WriteNpy(path, dt.npy, outs[i].shape, outs[i].data.data(),
               outs[i].data.size());
      std::printf("%s %s -> %s\n", outs[i].name.c_str(),
                  outs[i].dtype.c_str(), path.c_str());
    }
    return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "ptpu_predict: %s\n", e.what());
    return 1;
  }
}
