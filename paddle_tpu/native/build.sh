#!/bin/sh
# Build the native runtime shared library. Invoked automatically on first
# import (paddle_tpu/data/recordio.py) when the .so is missing or stale.
set -e
cd "$(dirname "$0")"
g++ -O2 -std=c++17 -fPIC -shared -o libptpu_native.so recordio.cc tensor_store.cc -lz -lpthread
