#!/bin/sh
# Build the native runtime shared library. Invoked automatically on first
# import (paddle_tpu/data/recordio.py) when the .so is missing or stale.
set -e
cd "$(dirname "$0")"
# skip the base compile when the .so is already newer than its sources
if [ ! -f libptpu_native.so ] || [ recordio.cc -nt libptpu_native.so ] \
   || [ tensor_store.cc -nt libptpu_native.so ]; then
  g++ -O2 -std=c++17 -fPIC -shared -o libptpu_native.so recordio.cc tensor_store.cc -lz -lpthread
fi

# Native serving entry (ptpu_predict): links the TensorFlow C API for its
# XlaCallModule/XLA:CPU runtime. Built only on request ("./build.sh
# predict" or PTPU_BUILD_PREDICT=1) so the automatic import-time library
# build stays fast.
if [ "$1" = "predict" ] || [ -n "$PTPU_BUILD_PREDICT" ]; then
  TF_DIR="${PTPU_TF_DIR:-$(python3 -c 'import tensorflow, os; print(os.path.dirname(tensorflow.__file__))' 2>/dev/null || true)}"
  if [ -n "$TF_DIR" ] && [ -f "$TF_DIR/libtensorflow_cc.so.2" ]; then
    g++ -O2 -std=c++17 -I "$TF_DIR/include" -o ptpu_predict ptpu_predict.cc \
        "$TF_DIR/libtensorflow_cc.so.2" "$TF_DIR/libtensorflow_framework.so.2" \
        -Wl,-rpath,"$TF_DIR"
  else
    echo "build.sh: TF C++ libs not found; skipping ptpu_predict" >&2
  fi
fi

# Native training demo (ptpu_train): same runtime, drives K train steps
# carrying params/optimizer state between XlaCallModule executions.
if [ "$1" = "train" ] || [ -n "$PTPU_BUILD_TRAIN" ]; then
  TF_DIR="${PTPU_TF_DIR:-$(python3 -c 'import tensorflow, os; print(os.path.dirname(tensorflow.__file__))' 2>/dev/null || true)}"
  if [ -n "$TF_DIR" ] && [ -f "$TF_DIR/libtensorflow_cc.so.2" ]; then
    g++ -O2 -std=c++17 -I "$TF_DIR/include" -o ptpu_train ptpu_train.cc \
        "$TF_DIR/libtensorflow_cc.so.2" "$TF_DIR/libtensorflow_framework.so.2" \
        -Wl,-rpath,"$TF_DIR"
  else
    echo "build.sh: TF C++ libs not found; skipping ptpu_train" >&2
  fi
fi
