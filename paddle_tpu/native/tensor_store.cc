// Native tensor container: fast checkpoint/persistables I/O.
//
// Capability equivalent of the reference's LoDTensor (de)serialization used
// by save/load ops (reference: paddle/fluid/framework/lod_tensor.cc
// SerializeToStream / DeserializeFromStream, operators/save_combine_op.cc /
// load_combine_op.cc — one file holding many named tensors, streamed through
// C++ so checkpointing large models never round-trips Python objects).
// Design is new: single translation unit, C ABI for ctypes, CRC-checked
// entries, O(1) name lookup via an index footer, buffered sequential writes.
//
// File format (little-endian):
//   file   := MAGIC u32 | version u32 | entry* | index | index_off u64
//             | index_len u32 | crc32(index) u32 | MAGIC u32
//   entry  := name_len u16 | name | crc32(hdr) u32 | hdr | data
//   hdr    := dtype u8 | ndim u8 | dims u64*ndim | data_len u64
//             | crc32(data) u32
//   index  := count u32 | (name_len u16 | name | entry_off u64)*
//
// dtype codes match numpy kinds the framework uses:
//   0=f32 1=f64 2=i32 3=i64 4=u8 5=bool 6=bf16 7=f16 8=i16 9=u32 10=u64

#include <zlib.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x50545453;  // "PTTS"
constexpr uint32_t kVersion = 2;         // v2: per-entry header CRC
constexpr uint8_t kMaxDims = 16;

uint32_t Crc(const char* data, size_t n) {
  // zlib's length parameter is 32-bit: feed >4GiB payloads in chunks so a
  // 4GiB-aligned tensor is fully covered, not hashed as zero bytes
  uLong c = crc32(0L, nullptr, 0);
  while (n > 0) {
    uInt step = n > (1u << 30) ? (1u << 30) : static_cast<uInt>(n);
    c = crc32(c, reinterpret_cast<const Bytef*>(data), step);
    data += step;
    n -= step;
  }
  return static_cast<uint32_t>(c);
}

struct Entry {
  uint8_t dtype = 0;
  std::vector<uint64_t> dims;
  uint64_t data_off = 0;  // absolute file offset of raw data
  uint64_t data_len = 0;
  uint32_t crc = 0;
};

// ---------------------------------------------------------------- writer
class StoreWriter {
 public:
  explicit StoreWriter(const char* path) : f_(std::fopen(path, "wb")) {
    if (f_) {
      std::fwrite(&kMagic, 4, 1, f_);
      std::fwrite(&kVersion, 4, 1, f_);
    }
  }

  bool ok() const { return f_ != nullptr; }

  bool Add(const char* name, uint8_t dtype, const uint64_t* dims,
           uint8_t ndim, const char* data, uint64_t len) {
    if (!f_) return false;
    if (ndim > kMaxDims) return false;
    uint16_t name_len = static_cast<uint16_t>(std::strlen(name));
    long long off = ftello(f_);
    if (off < 0) return false;
    index_[std::string(name)] = static_cast<uint64_t>(off);
    // header blob (dtype|ndim|dims|data_len|data_crc) is itself CRC'd so
    // metadata corruption fails loudly instead of decoding garbage
    std::string hdr;
    hdr.append(reinterpret_cast<const char*>(&dtype), 1);
    hdr.append(reinterpret_cast<const char*>(&ndim), 1);
    hdr.append(reinterpret_cast<const char*>(dims), 8ull * ndim);
    hdr.append(reinterpret_cast<const char*>(&len), 8);
    uint32_t dcrc = Crc(data, len);
    hdr.append(reinterpret_cast<const char*>(&dcrc), 4);
    uint32_t hcrc = Crc(hdr.data(), hdr.size());
    std::fwrite(&name_len, 2, 1, f_);
    std::fwrite(name, 1, name_len, f_);
    std::fwrite(&hcrc, 4, 1, f_);
    std::fwrite(hdr.data(), 1, hdr.size(), f_);
    return std::fwrite(data, 1, len, f_) == len || len == 0;
  }

  bool Finish() {
    if (!f_) return false;
    long long ioff = ftello(f_);
    if (ioff < 0) return false;
    std::string idx;
    uint32_t count = static_cast<uint32_t>(index_.size());
    idx.append(reinterpret_cast<const char*>(&count), 4);
    for (const auto& kv : index_) {
      uint16_t nl = static_cast<uint16_t>(kv.first.size());
      idx.append(reinterpret_cast<const char*>(&nl), 2);
      idx.append(kv.first);
      idx.append(reinterpret_cast<const char*>(&kv.second), 8);
    }
    std::fwrite(idx.data(), 1, idx.size(), f_);
    uint64_t off64 = static_cast<uint64_t>(ioff);
    uint32_t ilen = static_cast<uint32_t>(idx.size());
    uint32_t icrc = Crc(idx.data(), idx.size());
    std::fwrite(&off64, 8, 1, f_);
    std::fwrite(&ilen, 4, 1, f_);
    std::fwrite(&icrc, 4, 1, f_);
    std::fwrite(&kMagic, 4, 1, f_);
    bool ok = std::fflush(f_) == 0;
    std::fclose(f_);
    f_ = nullptr;
    return ok;
  }

  ~StoreWriter() {
    if (f_) Finish();
  }

 private:
  std::FILE* f_;
  std::map<std::string, uint64_t> index_;
};

// ---------------------------------------------------------------- reader
class StoreReader {
 public:
  explicit StoreReader(const char* path) : f_(std::fopen(path, "rb")) {
    if (!f_) return;
    uint32_t magic = 0, version = 0;
    if (std::fread(&magic, 4, 1, f_) != 1 || magic != kMagic ||
        std::fread(&version, 4, 1, f_) != 1 || version != kVersion) {
      Close();
      return;
    }
    // footer: index_off u64 | index_len u32 | crc u32 | magic u32
    if (fseeko(f_, -20, SEEK_END) != 0) { Close(); return; }
    uint64_t ioff = 0;
    uint32_t ilen = 0, icrc = 0, tail = 0;
    if (std::fread(&ioff, 8, 1, f_) != 1 ||
        std::fread(&ilen, 4, 1, f_) != 1 ||
        std::fread(&icrc, 4, 1, f_) != 1 ||
        std::fread(&tail, 4, 1, f_) != 1 || tail != kMagic) {
      Close();
      return;
    }
    std::string idx(ilen, '\0');
    if (fseeko(f_, static_cast<long long>(ioff), SEEK_SET) != 0 ||
        (ilen && std::fread(&idx[0], 1, ilen, f_) != ilen) ||
        Crc(idx.data(), idx.size()) != icrc) {
      Close();
      return;
    }
    // parse index then load each entry header
    size_t p = 0;
    auto rd = [&](void* dst, size_t n) {
      if (p + n > idx.size()) return false;
      std::memcpy(dst, idx.data() + p, n);
      p += n;
      return true;
    };
    uint32_t count = 0;
    if (!rd(&count, 4)) { Close(); return; }
    for (uint32_t i = 0; i < count; ++i) {
      uint16_t nl = 0;
      if (!rd(&nl, 2)) { Close(); return; }
      if (p + nl > idx.size()) { Close(); return; }
      std::string name(idx.data() + p, nl);
      p += nl;
      uint64_t off = 0;
      if (!rd(&off, 8)) { Close(); return; }
      if (!LoadHeader(name, off)) { Close(); return; }
    }
    ok_ = true;
  }

  bool ok() const { return ok_; }
  size_t count() const { return entries_.size(); }

  // list names joined by '\n' into caller buffer; returns required size
  uint64_t Names(char* buf, uint64_t cap) const {
    std::string all;
    for (const auto& kv : entries_) {
      if (!all.empty()) all.push_back('\n');
      all.append(kv.first);
    }
    if (buf && cap >= all.size()) std::memcpy(buf, all.data(), all.size());
    return all.size();
  }

  // metadata: returns data_len; fills dtype/ndim/dims (dims cap 16)
  uint64_t Meta(const char* name, uint8_t* dtype, uint8_t* ndim,
                uint64_t* dims) const {
    auto it = entries_.find(name);
    if (it == entries_.end()) return UINT64_MAX;
    const Entry& e = it->second;
    *dtype = e.dtype;
    *ndim = static_cast<uint8_t>(e.dims.size());
    for (size_t i = 0; i < e.dims.size() && i < 16; ++i) dims[i] = e.dims[i];
    return e.data_len;
  }

  // read the tensor payload into caller buffer; verifies CRC
  bool Read(const char* name, char* dst, uint64_t cap) {
    auto it = entries_.find(name);
    if (it == entries_.end()) return false;
    const Entry& e = it->second;
    if (cap < e.data_len) return false;
    if (fseeko(f_, static_cast<long long>(e.data_off), SEEK_SET) != 0)
      return false;
    if (e.data_len &&
        std::fread(dst, 1, e.data_len, f_) != e.data_len) return false;
    return Crc(dst, e.data_len) == e.crc;
  }

  ~StoreReader() { Close(); }

 private:
  bool LoadHeader(const std::string& name, uint64_t off) {
    if (fseeko(f_, static_cast<long long>(off), SEEK_SET) != 0) return false;
    uint16_t nl = 0;
    if (std::fread(&nl, 2, 1, f_) != 1) return false;
    std::string stored(nl, '\0');
    if (nl && std::fread(&stored[0], 1, nl, f_) != nl) return false;
    if (stored != name) return false;  // index/entry mismatch = corruption
    Entry e;
    uint32_t hcrc = 0;
    if (std::fread(&hcrc, 4, 1, f_) != 1) return false;
    std::string hdr(2, '\0');
    if (std::fread(&hdr[0], 1, 2, f_) != 2) return false;
    uint8_t ndim = static_cast<uint8_t>(hdr[1]);
    if (ndim > kMaxDims) return false;
    size_t rest = 8ull * ndim + 8 + 4;
    hdr.resize(2 + rest);
    if (std::fread(&hdr[2], 1, rest, f_) != rest) return false;
    if (Crc(hdr.data(), hdr.size()) != hcrc) return false;
    e.dtype = static_cast<uint8_t>(hdr[0]);
    e.dims.resize(ndim);
    std::memcpy(e.dims.data(), hdr.data() + 2, 8ull * ndim);
    std::memcpy(&e.data_len, hdr.data() + 2 + 8ull * ndim, 8);
    std::memcpy(&e.crc, hdr.data() + 2 + 8ull * ndim + 8, 4);
    long long pos = ftello(f_);
    if (pos < 0) return false;
    e.data_off = static_cast<uint64_t>(pos);
    entries_[name] = e;
    return true;
  }

  void Close() {
    if (f_) std::fclose(f_);
    f_ = nullptr;
    ok_ = false;
  }

  std::FILE* f_ = nullptr;
  bool ok_ = false;
  std::map<std::string, Entry> entries_;
};

}  // namespace

// ------------------------------------------------------------------ C ABI
extern "C" {

void* ptpu_store_writer_open(const char* path) {
  auto* w = new StoreWriter(path);
  if (!w->ok()) {
    delete w;
    return nullptr;
  }
  return w;
}

int ptpu_store_writer_add(void* h, const char* name, uint8_t dtype,
                          const uint64_t* dims, uint8_t ndim,
                          const char* data, uint64_t len) {
  return static_cast<StoreWriter*>(h)->Add(name, dtype, dims, ndim, data,
                                           len) ? 1 : 0;
}

int ptpu_store_writer_finish(void* h) {
  auto* w = static_cast<StoreWriter*>(h);
  int ok = w->Finish() ? 1 : 0;
  delete w;
  return ok;
}

void* ptpu_store_reader_open(const char* path) {
  auto* r = new StoreReader(path);
  if (!r->ok()) {
    delete r;
    return nullptr;
  }
  return r;
}

uint64_t ptpu_store_reader_names(void* h, char* buf, uint64_t cap) {
  return static_cast<StoreReader*>(h)->Names(buf, cap);
}

uint64_t ptpu_store_reader_meta(void* h, const char* name, uint8_t* dtype,
                                uint8_t* ndim, uint64_t* dims) {
  return static_cast<StoreReader*>(h)->Meta(name, dtype, ndim, dims);
}

int ptpu_store_reader_read(void* h, const char* name, char* dst,
                           uint64_t cap) {
  return static_cast<StoreReader*>(h)->Read(name, dst, cap) ? 1 : 0;
}

void ptpu_store_reader_close(void* h) {
  delete static_cast<StoreReader*>(h);
}

uint32_t ptpu_store_version() { return kVersion; }

}  // extern "C"
