// ptpu_train: native (C++) TRAINING entry for exported paddle_tpu train
// steps.
//
// Loads the train-step StableHLO artifact written by
// io.export_train_program (__exported_train__.stablehlo +
// __exported_train__.meta + train_state_<i>.npy initial values), then
// drives K optimization steps with NO Python in the process: each step
// executes the module through the TensorFlow eager C API's XlaCallModule
// kernel (XLA:CPU JIT), prints the fetch (loss) values, and feeds the
// carried state outputs (updated parameters + optimizer accumulators)
// back as next-step inputs per the meta's `carry` mapping. Final state is
// written as state<i>.npy.
//
// Capability equivalent of the reference's pure-C++ trainer demo
// (reference paddle/fluid/train/demo/demo_trainer.cc:55-80: load
// startup+main ProgramDesc, run startup, loop executor.Run(main)). The
// TPU-native deployable unit is the fully-compiled train step with
// parameters as arguments, not an op-by-op interpreted program.
//
// Usage:
//   ptpu_train <export_dir> <input0.npy> [...] --steps K [--out DIR]
//
// Inputs are positional in the meta's non-state `in` order (the batch,
// reused every step — ≙ the demo trainer's fixed synthetic batch).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <map>
#include <sstream>
#include <string>
#include <vector>

#include "tensorflow/c/c_api.h"
#include "tensorflow/c/eager/c_api.h"

namespace {

[[noreturn]] void Die(const std::string& msg) {
  std::fprintf(stderr, "ptpu_train: %s\n", msg.c_str());
  std::exit(1);
}

void CheckOk(TF_Status* s, const char* what) {
  if (TF_GetCode(s) != TF_OK) {
    Die(std::string(what) + ": " + TF_Message(s));
  }
}

std::string ReadFile(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) Die("cannot open " + path);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

struct DType {
  TF_DataType tf;
  const char* npy;
  size_t size;
};

DType DTypeByName(const std::string& name) {
  if (name == "float32") return {TF_FLOAT, "<f4", 4};
  if (name == "float64") return {TF_DOUBLE, "<f8", 8};
  if (name == "int32") return {TF_INT32, "<i4", 4};
  if (name == "int64") return {TF_INT64, "<i8", 8};
  if (name == "uint32") return {TF_UINT32, "<u4", 4};
  if (name == "uint8") return {TF_UINT8, "|u1", 1};
  if (name == "int8") return {TF_INT8, "|i1", 1};
  if (name == "bool") return {TF_BOOL, "|b1", 1};
  Die("unsupported dtype " + name);
}

struct Npy {
  std::string descr;
  std::vector<int64_t> shape;
  std::string data;
};

Npy ReadNpy(const std::string& path) {
  std::string raw = ReadFile(path);
  if (raw.size() < 10 || raw.compare(0, 6, "\x93NUMPY") != 0)
    Die(path + " is not a .npy file");
  int major = static_cast<unsigned char>(raw[6]);
  size_t hlen, hoff;
  if (major == 1) {
    hlen = static_cast<unsigned char>(raw[8]) |
           (static_cast<unsigned char>(raw[9]) << 8);
    hoff = 10;
  } else {
    hlen = 0;
    for (int i = 0; i < 4; ++i)
      hlen |= static_cast<size_t>(static_cast<unsigned char>(raw[8 + i]))
              << (8 * i);
    hoff = 12;
  }
  std::string header = raw.substr(hoff, hlen);
  Npy out;
  size_t d = header.find("'descr':");
  size_t q1 = header.find('\'', d + 8);
  size_t q2 = header.find('\'', q1 + 1);
  out.descr = header.substr(q1 + 1, q2 - q1 - 1);
  if (header.find("'fortran_order': False") == std::string::npos)
    Die(path + ": fortran_order arrays are not supported");
  size_t sh = header.find("'shape':");
  size_t p1 = header.find('(', sh);
  size_t p2 = header.find(')', p1);
  std::string dims = header.substr(p1 + 1, p2 - p1 - 1);
  std::stringstream ss(dims);
  std::string tok;
  while (std::getline(ss, tok, ',')) {
    if (tok.find_first_not_of(" \t") == std::string::npos) continue;
    out.shape.push_back(std::stoll(tok));
  }
  out.data = raw.substr(hoff + hlen);
  return out;
}

void WriteNpy(const std::string& path, const std::string& descr,
              const std::vector<int64_t>& shape, const void* data,
              size_t nbytes) {
  std::ostringstream hd;
  hd << "{'descr': '" << descr << "', 'fortran_order': False, 'shape': (";
  for (size_t i = 0; i < shape.size(); ++i) hd << shape[i] << ",";
  hd << "), }";
  std::string header = hd.str();
  size_t total = 10 + header.size() + 1;
  size_t pad = (64 - total % 64) % 64;
  header += std::string(pad, ' ');
  header += '\n';
  std::ofstream f(path, std::ios::binary);
  if (!f) Die("cannot write " + path);
  f << "\x93NUMPY" << '\x01' << '\x00';
  uint16_t hlen = static_cast<uint16_t>(header.size());
  f.write(reinterpret_cast<const char*>(&hlen), 2);
  f << header;
  f.write(static_cast<const char*>(data), nbytes);
}

struct TensorSpec {
  std::string name;
  std::string dtype;
  std::vector<int64_t> dims;
};

struct TrainMeta {
  int version = 9;
  int nfetch = 0;
  std::vector<TensorSpec> ins, outs;
  std::map<int, int> carry;          // out index -> in index
  std::map<int, std::string> init;   // in index -> .npy file
};

TrainMeta ReadMeta(const std::string& path) {
  std::ifstream f(path);
  if (!f) Die("cannot open " + path);
  TrainMeta m;
  std::string line;
  while (std::getline(f, line)) {
    std::stringstream ss(line);
    std::string key;
    ss >> key;
    if (key == "version") {
      ss >> m.version;
    } else if (key == "nfetch") {
      ss >> m.nfetch;
    } else if (key == "in" || key == "out") {
      TensorSpec t;
      ss >> t.name >> t.dtype;
      int64_t d;
      while (ss >> d) t.dims.push_back(d);
      (key == "in" ? m.ins : m.outs).push_back(t);
    } else if (key == "carry") {
      int o, i;
      ss >> o >> i;
      m.carry[o] = i;
    } else if (key == "init") {
      int i;
      std::string file;
      ss >> i >> file;
      m.init[i] = file;
    }
  }
  if (m.outs.empty()) Die("no outputs in " + path);
  return m;
}

TFE_TensorHandle* HandleFromNpy(const Npy& npy, const DType& dt,
                                TF_Status* s) {
  TF_Tensor* t = TF_AllocateTensor(dt.tf, npy.shape.data(),
                                   static_cast<int>(npy.shape.size()),
                                   npy.data.size());
  std::memcpy(TF_TensorData(t), npy.data.data(), npy.data.size());
  TFE_TensorHandle* h = TFE_NewTensorHandle(t, s);
  CheckOk(s, "TFE_NewTensorHandle");
  TF_DeleteTensor(t);
  return h;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr,
                 "usage: %s <export_dir> <input0.npy> [...] --steps K "
                 "[--out DIR]\n", argv[0]);
    return 2;
  }
  std::string dir = argv[1];
  std::string out_dir = ".";
  int steps = 1;
  std::vector<std::string> input_paths;
  for (int i = 2; i < argc; ++i) {
    if (std::strcmp(argv[i], "--out") == 0 && i + 1 < argc) {
      out_dir = argv[++i];
    } else if (std::strcmp(argv[i], "--steps") == 0 && i + 1 < argc) {
      steps = std::atoi(argv[++i]);
    } else {
      input_paths.push_back(argv[i]);
    }
  }

  TrainMeta meta = ReadMeta(dir + "/__exported_train__.meta");
  std::string module = ReadFile(dir + "/__exported_train__.stablehlo");

  TF_Status* s = TF_NewStatus();
  TFE_ContextOptions* copts = TFE_NewContextOptions();
  TFE_Context* ctx = TFE_NewContext(copts, s);
  CheckOk(s, "TFE_NewContext");

  // slot assignment: in[0] is __seed__; state slots load from init files;
  // the rest take the positional input .npy paths
  size_t n_in = meta.ins.size();
  std::vector<TFE_TensorHandle*> in_handles(n_in, nullptr);
  size_t next_input = 0;
  for (size_t i = 0; i < n_in; ++i) {
    if (meta.ins[i].name == "__seed__") continue;  // per-step below
    DType dt = DTypeByName(meta.ins[i].dtype);
    auto it = meta.init.find(static_cast<int>(i));
    if (it != meta.init.end()) {
      Npy npy = ReadNpy(dir + "/" + it->second);
      in_handles[i] = HandleFromNpy(npy, dt, s);
    } else {
      if (next_input >= input_paths.size())
        Die("not enough input .npy files (need one per non-state input)");
      Npy npy = ReadNpy(input_paths[next_input++]);
      if (npy.descr != dt.npy)
        Die(meta.ins[i].name + ": dtype " + npy.descr +
            " but model expects " + meta.ins[i].dtype);
      in_handles[i] = HandleFromNpy(npy, dt, s);
    }
  }
  if (next_input != input_paths.size())
    Die("too many input .npy files");

  std::vector<TF_DataType> tin;
  for (const auto& t : meta.ins) tin.push_back(DTypeByName(t.dtype).tf);
  std::vector<TF_DataType> tout;
  std::vector<const int64_t*> sout;
  std::vector<int> sout_ndims;
  for (const auto& o : meta.outs) {
    tout.push_back(DTypeByName(o.dtype).tf);
    sout.push_back(o.dims.data());
    sout_ndims.push_back(static_cast<int>(o.dims.size()));
  }

  std::vector<TFE_TensorHandle*> outs(meta.outs.size(), nullptr);
  for (int step = 0; step < steps; ++step) {
    // fresh seed handle per step (step index = the seed)
    for (size_t i = 0; i < n_in; ++i) {
      if (meta.ins[i].name == "__seed__") {
        int32_t seed = step;
        TF_Tensor* t = TF_AllocateTensor(TF_INT32, nullptr, 0, 4);
        std::memcpy(TF_TensorData(t), &seed, 4);
        if (in_handles[i] != nullptr) TFE_DeleteTensorHandle(in_handles[i]);
        in_handles[i] = TFE_NewTensorHandle(t, s);
        CheckOk(s, "seed handle");
        TF_DeleteTensor(t);
      }
    }

    TFE_Op* op = TFE_NewOp(ctx, "XlaCallModule", s);
    CheckOk(s, "TFE_NewOp(XlaCallModule)");
    TFE_OpSetAttrString(op, "module", module.data(), module.size());
    TFE_OpSetAttrInt(op, "version", meta.version);
    TFE_OpSetAttrTypeList(op, "Tin", tin.data(),
                          static_cast<int>(tin.size()));
    TFE_OpSetAttrTypeList(op, "Tout", tout.data(),
                          static_cast<int>(tout.size()));
    TFE_OpSetAttrShapeList(op, "Sout", sout.data(), sout_ndims.data(),
                           static_cast<int>(sout.size()), s);
    CheckOk(s, "Sout");
    const void* plat[1] = {"CPU"};
    size_t plat_len[1] = {3};
    TFE_OpSetAttrStringList(op, "platforms", plat, plat_len, 1);
    TFE_OpSetAttrStringList(op, "dim_args_spec", nullptr, nullptr, 0);
    TFE_OpSetAttrStringList(op, "disabled_checks", nullptr, nullptr, 0);
    TFE_OpSetAttrFunctionList(op, "function_list", nullptr, 0);
    TFE_OpSetAttrBool(op, "has_token_input_output", 0);
    for (auto* h : in_handles) {
      TFE_OpAddInput(op, h, s);
      CheckOk(s, "TFE_OpAddInput");
    }
    int nout = static_cast<int>(outs.size());
    TFE_Execute(op, outs.data(), &nout, s);
    CheckOk(s, "TFE_Execute");
    TFE_DeleteOp(op);

    // print fetch (loss) values
    for (int i = 0; i < meta.nfetch; ++i) {
      TF_Tensor* t = TFE_TensorHandleResolve(outs[i], s);
      CheckOk(s, "resolve fetch");
      double v = 0.0;
      if (TF_TensorType(t) == TF_FLOAT)
        v = *static_cast<float*>(TF_TensorData(t));
      else if (TF_TensorType(t) == TF_DOUBLE)
        v = *static_cast<double*>(TF_TensorData(t));
      std::printf("step %d %s %.8f\n", step, meta.outs[i].name.c_str(), v);
      TF_DeleteTensor(t);
    }

    // carry updated state into the next step's inputs
    for (const auto& [out_idx, in_idx] : meta.carry) {
      TFE_DeleteTensorHandle(in_handles[in_idx]);
      in_handles[in_idx] = outs[out_idx];
      outs[out_idx] = nullptr;
    }
    for (auto*& h : outs) {
      if (h != nullptr) {
        TFE_DeleteTensorHandle(h);
        h = nullptr;
      }
    }
  }

  // final carried state -> state<in_idx>.npy
  for (const auto& [out_idx, in_idx] : meta.carry) {
    TF_Tensor* t = TFE_TensorHandleResolve(in_handles[in_idx], s);
    CheckOk(s, "resolve state");
    std::vector<int64_t> shape(TF_NumDims(t));
    for (size_t d = 0; d < shape.size(); ++d)
      shape[d] = TF_Dim(t, static_cast<int>(d));
    DType dt = DTypeByName(meta.ins[in_idx].dtype);
    std::string path = out_dir + "/state" + std::to_string(in_idx) + ".npy";
    WriteNpy(path, dt.npy, shape, TF_TensorData(t), TF_TensorByteSize(t));
    std::printf("state %s -> %s\n", meta.ins[in_idx].name.c_str(),
                path.c_str());
    TF_DeleteTensor(t);
  }
  return 0;
}
