"""Gradient clipping strategies.

≙ reference python/paddle/fluid/clip.py (ErrorClipByValue, GradientClipByValue,
GradientClipByNorm, GradientClipByGlobalNorm, set_gradient_clip).
"""

from __future__ import annotations

from .core.dtypes import dtype_name
from .layer_helper import LayerHelper
from .layers import nn as nn_layers
from .layers import tensor as tensor_layers


class BaseGradientClipAttr:
    def create_operators(self, param, grad):
        raise NotImplementedError

    def process_context(self, context, param, grad):
        pass


class NullGradientClipAttr(BaseGradientClipAttr):
    def create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def create_operators(self, param, grad):
        return param, nn_layers.clip(grad, self.min, self.max)


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def create_operators(self, param, grad):
        return param, nn_layers.clip_by_norm(grad, self.clip_norm)


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """Scale all grads by clip_norm/max(global_norm, clip_norm)."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def process_context(self, context, param, grad):
        norms = context.setdefault("global_norm_sq", [])
        helper = LayerHelper("global_norm")
        sq = helper.create_tmp_variable(dtype=dtype_name(grad.dtype),
                                        shape=[1], stop_gradient=True)
        grad.block.append_op("squared_l2_norm", inputs={"X": [grad]},
                             outputs={"Out": [sq]})
        norms.append(sq)

    def create_operators(self, param, grad):
        context = self._context
        # build the global-norm/scale subgraph ONCE and share it across all
        # parameters (the per-param version would be O(P^2) program ops)
        scale_var = context.get("global_norm_scale")
        if scale_var is None:
            helper = LayerHelper("global_norm_clip")
            total = tensor_layers.sums(context["global_norm_sq"])
            gn = helper.create_tmp_variable(dtype=dtype_name(grad.dtype),
                                            shape=[1], stop_gradient=True)
            grad.block.append_op("sqrt", inputs={"X": [total]},
                                 outputs={"Out": [gn]})
            denom = nn_layers.elementwise_max(
                gn, tensor_layers.fill_constant([1], dtype_name(grad.dtype),
                                                self.clip_norm))
            scale_var = nn_layers.elementwise_div(
                tensor_layers.fill_constant([1], dtype_name(grad.dtype),
                                            self.clip_norm), denom)
            context["global_norm_scale"] = scale_var
        return param, nn_layers.elementwise_mul(grad, scale_var)


class ErrorClipByValue:
    """≙ reference clip.py ErrorClipByValue — clip activations' gradients.

    With vjp-based autodiff there is no per-op grad var to clip mid-chain;
    the capability is preserved by clipping the final gradients instead."""

    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min


def set_gradient_clip(clip, param_list=None, program=None):
    from .framework.program import default_main_program
    program = program or default_main_program()
    params = param_list or program.all_parameters()
    for p in params:
        if not hasattr(p, "gradient_clip") or p.gradient_clip is None:
            p.gradient_clip = clip


def append_gradient_clip_ops(params_grads):
    """≙ reference clip.py append_gradient_clip_ops."""
    context = {}
    clips = []
    for p, g in params_grads:
        clip = getattr(p, "gradient_clip", None) or NullGradientClipAttr()
        clip._context = context
        clip.process_context(context, p, g)
        clips.append(clip)
    out = []
    for (p, g), clip in zip(params_grads, clips):
        out.append(clip.create_operators(p, g))
    return out
