"""Optimizers.

≙ reference python/paddle/fluid/optimizer.py (Optimizer base :38,
_create_optimization_pass :196, minimize :253, and the SGD/Momentum/Adagrad/
Adam/Adamax/DecayedAdagrad/Adadelta/RMSProp/Ftrl/ModelAverage family
:279-1119). Each optimizer appends accumulator vars + one update op per
parameter; the executor runs them functionally with donated buffers.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .clip import append_gradient_clip_ops
from .core import unique_name
from .core.dtypes import dtype_name
from .core.enforce import enforce
from .framework.backward import append_backward
from .framework.program import (Parameter, Program, Variable,
                                default_main_program,
                                default_startup_program)
from .regularizer import append_regularization_ops


class Optimizer:
    """Base optimizer (≙ reference optimizer.py:38)."""

    def __init__(self, learning_rate, regularization=None, name=None):
        self.regularization = regularization
        self._name = name
        self._learning_rate = learning_rate
        self._learning_rate_var: Optional[Variable] = None
        self._accumulators: Dict[str, Dict[str, Variable]] = {}

    # -- learning rate ----------------------------------------------------
    def _create_global_learning_rate(self):
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_var = self._learning_rate
            return
        if self._learning_rate_var is not None:
            return
        main_block = default_main_program().global_block()
        name = unique_name.generate("learning_rate")
        self._learning_rate_var = main_block.create_var(
            name=name, shape=[1], dtype="float32", persistable=True)
        self._learning_rate_var.stop_gradient = True
        sb = default_startup_program().global_block()
        sv = sb.create_var(name=name, shape=[1], dtype="float32",
                           persistable=True)
        sb.append_op("fill_constant", outputs={"Out": [sv.name]},
                     attrs={"shape": [1], "value": float(self._learning_rate),
                            "dtype": "float32"})

    def _global_learning_rate(self) -> Variable:
        return self._learning_rate_var

    # -- accumulators (≙ optimizer.py _add_accumulator) -------------------
    def _add_accumulator(self, name: str, param: Parameter,
                         fill_value: float = 0.0, shape=None, dtype=None):
        acc_map = self._accumulators.setdefault(name, {})
        if param.name in acc_map:
            return acc_map[param.name]
        shape = list(shape if shape is not None else param.shape)
        dtype = dtype or dtype_name(param.dtype)
        var_name = unique_name.generate(f"{param.name}_{name}_acc")
        main_block = default_main_program().global_block()
        var = main_block.create_var(name=var_name, shape=shape, dtype=dtype,
                                    persistable=True)
        var.stop_gradient = True
        # markers consumed by ParallelExecutor's Reduce (ZeRO-1) strategy
        # and the explicit gradient pipeline (parallel/grad_comm.py):
        # optimizer state may be sharded across the data axis, and the
        # backref says WHOSE state this is — the comm pass shards a
        # same-shaped accumulator with its parameter's update slice
        # without guessing from shape coincidences.
        var.is_optimizer_state = True
        var.accumulator_of = param.name
        # same-shaped accumulators of a TP/EP-sharded parameter live with
        # the same layout as the parameter.
        pspec = getattr(param, "sharding_spec", None)
        if pspec is not None and list(shape) == list(param.shape):
            var.sharding_spec = pspec
        sb = default_startup_program().global_block()
        sv = sb.create_var(name=var_name, shape=shape, dtype=dtype,
                           persistable=True)
        sb.append_op("fill_constant", outputs={"Out": [sv.name]},
                     attrs={"shape": shape, "value": float(fill_value),
                            "dtype": dtype})
        acc_map[param.name] = var
        return var

    def _get_accumulator(self, name: str, param: Parameter) -> Variable:
        return self._accumulators[name][param.name]

    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, params_grads):
        pass

    # -- the pass (≙ optimizer.py:196) ------------------------------------
    def _create_optimization_pass(self, params_grads, loss,
                                  startup_program=None):
        block = loss.block
        start = len(block.ops)
        self._create_global_learning_rate()
        self._create_accumulators(block, [p for p, _ in params_grads])
        for pg in params_grads:
            self._append_optimize_op(block, pg)
        self._finish_update(block, params_grads)
        # role marker (≙ OpRole::kOptimize, reference op_proto_maker.h:25-31):
        # lets clone(for_test)/prune strip the update ops for inference.
        for op in block.ops[start:]:
            op.attrs.setdefault("op_role", "optimize")
        return []

    def minimize(self, loss: Variable, startup_program: Optional[Program] = None,
                 parameter_list: Optional[Sequence] = None,
                 no_grad_set=None) -> Tuple[list, List[Tuple[Variable, Variable]]]:
        """≙ reference optimizer.py:253 — append_backward + clip +
        regularization + optimize ops, all into the loss's program."""
        params_grads = append_backward(loss, parameter_list, no_grad_set)
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        opt_ops = self._create_optimization_pass(params_grads, loss,
                                                 startup_program)
        return opt_ops, params_grads


class SGDOptimizer(Optimizer):
    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        block.append_op("sgd",
                        inputs={"Param": [p], "Grad": [g],
                                "LearningRate": [self._global_learning_rate()]},
                        outputs={"ParamOut": [p]})


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        block.append_op("momentum",
                        inputs={"Param": [p], "Grad": [g], "Velocity": [v],
                                "LearningRate": [self._global_learning_rate()]},
                        outputs={"ParamOut": [p], "VelocityOut": [v]},
                        attrs={"mu": self._momentum,
                               "use_nesterov": self._use_nesterov})


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        block.append_op("adagrad",
                        inputs={"Param": [p], "Grad": [g], "Moment": [m],
                                "LearningRate": [self._global_learning_rate()]},
                        outputs={"ParamOut": [p], "MomentOut": [m]},
                        attrs={"epsilon": self._epsilon})


class AdamOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow", p, fill_value=self._beta1,
                                  shape=[1])
            self._add_accumulator("beta2_pow", p, fill_value=self._beta2,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        block.append_op(
            "adam",
            inputs={"Param": [p], "Grad": [g],
                    "Moment1": [self._get_accumulator("moment1", p)],
                    "Moment2": [self._get_accumulator("moment2", p)],
                    "Beta1Pow": [self._get_accumulator("beta1_pow", p)],
                    "Beta2Pow": [self._get_accumulator("beta2_pow", p)],
                    "LearningRate": [self._global_learning_rate()]},
            outputs={"ParamOut": [p],
                     "Moment1Out": [self._get_accumulator("moment1", p)],
                     "Moment2Out": [self._get_accumulator("moment2", p)],
                     "Beta1PowOut": [self._get_accumulator("beta1_pow", p)],
                     "Beta2PowOut": [self._get_accumulator("beta2_pow", p)]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow", p, fill_value=self._beta1,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        block.append_op(
            "adamax",
            inputs={"Param": [p], "Grad": [g],
                    "Moment": [self._get_accumulator("moment", p)],
                    "InfNorm": [self._get_accumulator("inf_norm", p)],
                    "Beta1Pow": [self._get_accumulator("beta1_pow", p)],
                    "LearningRate": [self._global_learning_rate()]},
            outputs={"ParamOut": [p],
                     "MomentOut": [self._get_accumulator("moment", p)],
                     "InfNormOut": [self._get_accumulator("inf_norm", p)],
                     "Beta1PowOut": [self._get_accumulator("beta1_pow", p)]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        block.append_op("decayed_adagrad",
                        inputs={"Param": [p], "Grad": [g], "Moment": [m],
                                "LearningRate": [self._global_learning_rate()]},
                        outputs={"ParamOut": [p], "MomentOut": [m]},
                        attrs={"decay": self._decay,
                               "epsilon": self._epsilon})


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        block.append_op(
            "adadelta",
            inputs={"Param": [p], "Grad": [g],
                    "AvgSquaredGrad":
                        [self._get_accumulator("avg_squared_grad", p)],
                    "AvgSquaredUpdate":
                        [self._get_accumulator("avg_squared_update", p)]},
            outputs={"ParamOut": [p],
                     "AvgSquaredGradOut":
                         [self._get_accumulator("avg_squared_grad", p)],
                     "AvgSquaredUpdateOut":
                         [self._get_accumulator("avg_squared_update", p)]},
            attrs={"epsilon": self._epsilon, "rho": self._rho})


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("momentum", p)
            if self._centered:
                self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        inputs = {"Param": [p], "Grad": [g],
                  "MeanSquare": [self._get_accumulator("mean_square", p)],
                  "Moment": [self._get_accumulator("momentum", p)],
                  "LearningRate": [self._global_learning_rate()]}
        outputs = {"ParamOut": [p],
                   "MeanSquareOut": [self._get_accumulator("mean_square", p)],
                   "MomentOut": [self._get_accumulator("momentum", p)]}
        if self._centered:
            inputs["MeanGrad"] = [self._get_accumulator("mean_grad", p)]
            outputs["MeanGradOut"] = [self._get_accumulator("mean_grad", p)]
        block.append_op("rmsprop", inputs=inputs, outputs=outputs,
                        attrs={"decay": self._rho, "epsilon": self._epsilon,
                               "momentum": self._momentum,
                               "centered": self._centered})


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        block.append_op(
            "ftrl",
            inputs={"Param": [p], "Grad": [g],
                    "SquaredAccumulator": [self._get_accumulator("squared", p)],
                    "LinearAccumulator": [self._get_accumulator("linear", p)],
                    "LearningRate": [self._global_learning_rate()]},
            outputs={"ParamOut": [p],
                     "SquaredAccumOut": [self._get_accumulator("squared", p)],
                     "LinearAccumOut": [self._get_accumulator("linear", p)]},
            attrs={"l1": self._l1, "l2": self._l2,
                   "lr_power": self._lr_power})


class LambOptimizer(Optimizer):
    """Large-batch LAMB (TPU-era addition; see optimizer_ops.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, weight_decay=0.01, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2 = beta1, beta2
        self._epsilon, self._weight_decay = epsilon, weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow", p, fill_value=self._beta1,
                                  shape=[1])
            self._add_accumulator("beta2_pow", p, fill_value=self._beta2,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        block.append_op(
            "lamb",
            inputs={"Param": [p], "Grad": [g],
                    "Moment1": [self._get_accumulator("moment1", p)],
                    "Moment2": [self._get_accumulator("moment2", p)],
                    "Beta1Pow": [self._get_accumulator("beta1_pow", p)],
                    "Beta2Pow": [self._get_accumulator("beta2_pow", p)],
                    "LearningRate": [self._global_learning_rate()]},
            outputs={"ParamOut": [p],
                     "Moment1Out": [self._get_accumulator("moment1", p)],
                     "Moment2Out": [self._get_accumulator("moment2", p)],
                     "Beta1PowOut": [self._get_accumulator("beta1_pow", p)],
                     "Beta2PowOut": [self._get_accumulator("beta2_pow", p)]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon,
                   "weight_decay": self._weight_decay})


class ModelAverage(Optimizer):
    """≙ reference optimizer.py ModelAverage — maintains an EMA of parameters;
    apply()/restore() swap the averaged values in and out of the scope around
    evaluation (host-side swap, no program rebuild needed on TPU)."""

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000, **kw):
        super().__init__(0.0, **kw)
        self._rate = average_window_rate
        self._params: List[Parameter] = []

    def build(self, params: Sequence[Parameter]):
        self._params = list(params)
        for p in params:
            self._add_accumulator("ema", p)
        block = default_main_program().global_block()
        for p in params:
            ema = self._get_accumulator("ema", p)
            tmp = block.create_var(
                name=unique_name.generate(f"{p.name}_ema_new"),
                shape=p.shape, dtype=dtype_name(p.dtype))
            block.append_op("scale", inputs={"X": [ema]},
                            outputs={"Out": [tmp]},
                            attrs={"scale": 1 - self._rate})
            tmp2 = block.create_var(
                name=unique_name.generate(f"{p.name}_ema_p"),
                shape=p.shape, dtype=dtype_name(p.dtype))
            block.append_op("scale", inputs={"X": [p]},
                            outputs={"Out": [tmp2]},
                            attrs={"scale": self._rate})
            block.append_op("sum", inputs={"X": [tmp, tmp2]},
                            outputs={"Out": [ema]})

    def apply(self, scope=None):
        """Swap EMA values into the parameters (backup originals)."""
        from .framework.scope import global_scope
        scope = scope or global_scope()
        for p in self._params:
            ema = self._get_accumulator("ema", p)
            scope.set_var(p.name + "@MODEL_AVG_BACKUP", scope.get(p.name))
            scope.set_var(p.name, scope.get(ema.name))

    def restore(self, scope=None):
        """Restore the live parameter values saved by apply()."""
        from .framework.scope import global_scope
        scope = scope or global_scope()
        for p in self._params:
            backup = scope.find_var(p.name + "@MODEL_AVG_BACKUP")
            if backup is not None:
                scope.set_var(p.name, backup)
                scope.erase(p.name + "@MODEL_AVG_BACKUP")


# fluid-style aliases
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
