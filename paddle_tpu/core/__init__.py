from . import dtypes, enforce, flags, places, unique_name  # noqa: F401
from .enforce import (EnforceError, InvalidArgumentError, NotFoundError,  # noqa: F401
                      enforce, enforce_eq, enforce_ge, enforce_gt, enforce_le,
                      enforce_lt, enforce_ne)
from .flags import get_flag, set_flag, set_flags  # noqa: F401
from .places import (CPUPlace, Place, TPUPlace, default_place, device_count,  # noqa: F401
                     devices, is_compiled_with_tpu, place_to_device)
