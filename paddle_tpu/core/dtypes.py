"""Dtype system.

Capability equivalent of the reference's VarType dtype enum
(reference: paddle/fluid/framework/framework.proto:91-115) and the software
float16 type (reference: paddle/fluid/platform/float16.h:87). On TPU the
native low-precision type is bfloat16 (MXU-preferred); float16 is kept for
API parity.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .enforce import InvalidArgumentError

# Canonical names → jnp dtypes
_NAME_TO_DTYPE = {
    "bool": jnp.bool_,
    "int8": jnp.int8,
    "uint8": jnp.uint8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "fp16": jnp.float16,
    "bf16": jnp.bfloat16,
    "fp32": jnp.float32,
    "fp64": jnp.float64,
}

FLOAT_DTYPES = (jnp.float16, jnp.bfloat16, jnp.float32, jnp.float64)
INT_DTYPES = (jnp.int8, jnp.uint8, jnp.int16, jnp.int32, jnp.int64)


def convert_dtype(dtype) -> np.dtype:
    """Normalize a string/np/jnp dtype spec to a numpy dtype object."""
    if dtype is None:
        return np.dtype(jnp.float32)
    if isinstance(dtype, str):
        if dtype not in _NAME_TO_DTYPE:
            raise InvalidArgumentError(f"unknown dtype {dtype!r}")
        return np.dtype(_NAME_TO_DTYPE[dtype])
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    return np.dtype(dtype).name


def is_floating(dtype) -> bool:
    return np.dtype(dtype) in [np.dtype(d) for d in FLOAT_DTYPES]


def is_integer(dtype) -> bool:
    return np.dtype(dtype) in [np.dtype(d) for d in INT_DTYPES]
