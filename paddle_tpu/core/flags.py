"""Global typed flag registry.

Capability equivalent of the reference's gflags surface (DEFINE_bool/int/double in
C++, e.g. FLAGS_benchmark / FLAGS_check_nan_inf at reference
paddle/fluid/framework/executor.cc:27 and operator.cc:726) plus the Python env
bridge (`read_env_flags` in reference python/paddle/fluid/__init__.py:121-137).

Flags are typed, documented, and can be set from the environment with the
``PTPU_`` prefix, e.g. ``PTPU_CHECK_NAN_INF=1``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict

from .enforce import AlreadyExistsError, NotFoundError


@dataclass
class _FlagSpec:
    name: str
    default: Any
    parser: Callable[[str], Any]
    help: str
    value: Any


_REGISTRY: Dict[str, _FlagSpec] = {}

_ENV_PREFIX = "PTPU_"


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


def _define(name: str, default: Any, parser, help: str) -> None:
    if name in _REGISTRY:
        raise AlreadyExistsError(f"flag {name!r} already defined")
    value = default
    env = os.environ.get(_ENV_PREFIX + name.upper())
    if env is not None:
        value = parser(env)
    _REGISTRY[name] = _FlagSpec(name, default, parser, help, value)


def define_bool(name: str, default: bool, help: str = "") -> None:
    _define(name, default, _parse_bool, help)


def define_int(name: str, default: int, help: str = "") -> None:
    _define(name, default, int, help)


def define_float(name: str, default: float, help: str = "") -> None:
    _define(name, default, float, help)


def define_string(name: str, default: str, help: str = "") -> None:
    _define(name, default, str, help)


def get_flag(name: str) -> Any:
    spec = _REGISTRY.get(name)
    if spec is None:
        raise NotFoundError(f"unknown flag {name!r}")
    return spec.value


def set_flag(name: str, value: Any) -> None:
    spec = _REGISTRY.get(name)
    if spec is None:
        raise NotFoundError(f"unknown flag {name!r}")
    spec.value = value


def set_flags(mapping: Dict[str, Any]) -> None:
    for k, v in mapping.items():
        set_flag(k, v)


def all_flags() -> Dict[str, Any]:
    return {k: v.value for k, v in _REGISTRY.items()}


def vlog(level: int, msg: str, *args) -> None:
    """Verbose logging gated on the `vlog` flag (≙ glog VLOG(level) used
    throughout the reference's C++; enable with PTPU_VLOG=N)."""
    if get_flag("vlog") >= level:
        import sys
        print(f"[VLOG{level}] " + (msg % args if args else msg),
              file=sys.stderr)


# --- Core framework flags (≙ the reference's gflags config surface, SURVEY §5) ---
define_bool("check_nan_inf", False,
            "Scan every op's outputs for NaN/Inf during execution "
            "(≙ FLAGS_check_nan_inf, reference operator.cc:726-736).")
define_bool("benchmark", False,
            "Block on device after each program run and log timings "
            "(≙ FLAGS_benchmark, reference executor.cc:27).")
define_int("vlog", 0, "Verbose logging level (≙ glog VLOG).")
define_bool("use_bf16_matmul", True,
            "Prefer bfloat16 MXU matmul precision where layers opt in.")
define_string("jit_cache", "", "Persistent XLA compilation cache directory.")
define_bool("conv1x1_mixed_vjp", False,
            "Lower the backward of 1x1 stride-1 NHWC convs with a "
            "mixed-emitter custom_vjp (dgrad as one dot_general, wgrad "
            "on the conv emitter). Wins 1.52x on the ISOLATED fwd+bwd "
            "unit but LOSES 1.43x inside the full flagship step (+30 GB "
            "traffic: the [BHW,C] reshapes force layout copies of every "
            "1x1 activation and break BN-backward fusion) - default OFF; "
            "kept as the committed falsification probe "
            "(PROBE_DGRAD_r05.json, tools/ab_conv1x1.py).")
define_bool("disable_pallas", False,
            "Force XLA-composite lowerings for ops that default to Pallas "
            "kernels on TPU (escape hatch: PTPU_DISABLE_PALLAS=1).")
define_bool("fuse_recurrent_cells", True,
            "Executor-compile-time fuse_recurrent_cell_pass: rewrite "
            "dynamic_lstm/dynamic_gru to the fused whole-sequence cell "
            "kernels (paddle_tpu/fusion/recurrent.py — one Pallas kernel "
            "for the entire recurrence on TPU). Numerically equivalent "
            "fwd+grad; kill switch PTPU_FUSE_RECURRENT_CELLS=0.")
define_bool("fuse_decode_attention", True,
            "Executor-compile-time fuse_decode_attention_pass: rewrite the "
            "cached-decode QK^T->+bias->softmax->V op chain into one "
            "fused_decode_attention kernel per tick "
            "(paddle_tpu/fusion/decode_attention.py). Kill switch "
            "PTPU_FUSE_DECODE_ATTENTION=0.")
define_bool("pipeline", True,
            "Allow the program-level pipeline-parallel executor mode when "
            "the BuildStrategy requests it (pipeline_stages >= 2). Kill "
            "switch: PTPU_PIPELINE=0 runs the program unpartitioned (plain "
            "SPMD, replicated over the pp axis) — the escape hatch if "
            "partitioning ever misbehaves in production. Part of the "
            "executor's compile cache key (framework/executor.py "
            "_fusion_flags_key; resolved by parallel/pipeline.py "
            "pipeline_config).")
define_bool("tp_shard", True,
            "Allow the static sharding-propagation rewrite (framework/"
            "sharding.py tp_shard_pass) that makes tp-annotated parameters "
            "executable under the full-manual execution modes (explicit "
            "dp comm / pipeline). Kill switch: PTPU_TP_SHARD=0 restores "
            "the old enforce gate — tp-sharded programs are then rejected "
            "by the manual modes instead of rewritten. Part of the "
            "executor's compile cache key.")
define_bool("memory_plan", True,
            "Allow the static memory planner (framework/memory_plan.py) "
            "when the BuildStrategy requests it (memory_plan=True) or a "
            "caller applies memory_plan_pass: liveness-minimizing op "
            "scheduling, interference-graph buffer-slot coloring (verified "
            "race-free by the r13 buffer-reuse detectors on every apply), "
            "and the remat-vs-stash search that segments the backward "
            "region under jax.checkpoint. Kill switch: PTPU_MEMORY_PLAN=0 "
            "runs every program unplanned — the escape hatch if a plan "
            "ever misbehaves in production. Part of the executor's "
            "compile cache key (framework/executor.py _fusion_flags_key).")
define_bool("auto_parallel", True,
            "Allow the auto-parallel planner (framework/auto_parallel.py) "
            "when the BuildStrategy requests it (auto_parallel=True): "
            "cost-model-guided search over the dp x pp x tp strategy "
            "space that chooses the executor's BuildStrategy knobs and "
            "mesh factorization, and re-plans on elastic restore to a "
            "changed world size. Kill switch: PTPU_AUTO_PARALLEL=0 runs "
            "the user's strategy and mesh untouched — the escape hatch "
            "if a plan ever misbehaves in production. Part of the "
            "executor's compile cache key (framework/executor.py "
            "_fusion_flags_key).")
define_bool("kv_sanitize", False,
            "Shadow-state KV sanitizer (serving/sanitizer.py): mirror "
            "every BlockPool/KVPager/host-tier mutation into the abstract "
            "ownership model (framework/ownership.py) and raise "
            "SanitizerDivergence naming the op, block, and invariant on "
            "the first drift. Off by default in production (the shadow "
            "bookkeeping costs a few percent of the host tick loop); "
            "pinned ON for the whole test suite via PTPU_KV_SANITIZE=1 "
            "in tests/conftest.py, same discipline as PTPU_VERIFY_PASSES. "
            "Read at KVPager construction (attach-or-None), and part of "
            "the executor's compile cache key so a mid-process toggle "
            "never shares cached state with its instrumented twin.")
define_bool("quant_comm", True,
            "Allow quantized gradient collectives when the BuildStrategy "
            "requests them (quant_comm='int8'/'bf16'). Kill switch: "
            "PTPU_QUANT_COMM=0 forces fp32 gradient transfers everywhere "
            "while keeping the explicit reduce-scatter pipeline — the "
            "escape hatch if quantization ever hurts a model's "
            "convergence in production (parallel/grad_comm.py).")
define_bool("quant_params", True,
            "Allow weight-only quantized serving when an engine requests it "
            "(quant='int8'/'int4'): quantize_params_pass rewrites a serving "
            "program's persistable f32 weights into block-scaled (payload, "
            "scales) pairs consumed by qmatmul/qlookup (framework/passes.py, "
            "parallel/collective.py quantize_blocks_2d). Kill switch: "
            "PTPU_QUANT_PARAMS=0 serves full f32 weights — the escape hatch "
            "if quantization ever hurts decode quality in production. Part "
            "of the executor's compile cache key (framework/executor.py "
            "_fusion_flags_key).")
define_bool("trace", True,
            "Structured step tracing (observability/tracing.py): typed "
            "nested spans (compile/step/tick/pass/dp_comm/pp_tick/"
            "admission/feed_fetch) recorded into the in-process ring "
            "buffer, exportable as Chrome trace / aggregate tables and "
            "joined with analytic predictions by observability/ledger.py. "
            "Kill switch: PTPU_TRACE=0 makes every span a no-op (span "
            "enter/exit cost drops below the 0.5%%-of-step budget asserted "
            "in tests/test_observability.py).")
define_int("trace_ring", 65536,
           "Capacity of the span ring buffer (observability/tracing.py). "
           "Oldest spans are overwritten; the buffer is preallocated so "
           "recording never allocates on the hot path.")
# (num_iteration_per_drop_scope lives on ExecutionStrategy for API parity;
# the functional executor has no per-iteration kid scopes to drop)
define_int("sparse_dense_apply_max_bytes", 1 << 30,
           "Lazy sparse optimizer updates (adam) switch from the "
           "merged-rows path (sort + row gather/scatter, O(batch*dim) "
           "touched) to a dense-MASKED apply (full-table elementwise, "
           "identical lazy semantics) when the table is at most this many "
           "bytes: on TPU the 160k-id sort alone costs ~12 ms while "
           "elementwise passes over a <=1 GB table cost ~1-4 ms. Set 0 to "
           "force the row path regardless of size (EP-scale tables).")
define_int("_reserved_num_iteration_per_drop_scope", 1,
           "Iterations between temporary-scope cleanups "
           "(≙ ExecutionStrategy::num_iteration_per_drop_scope_).")
