"""Places and device discovery.

Capability equivalent of the reference's Place variant + DeviceContextPool
(reference: paddle/fluid/platform/place.h:25-78, device_context.h:131-173).
On TPU the "device context" is owned by the XLA runtime (PJRT); the framework's
job is discovery, selection, and mesh construction — not stream management.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax

from .enforce import InvalidArgumentError, OutOfRangeError


@dataclass(frozen=True)
class Place:
    """A logical device slot: backend kind + index (≙ platform::Place)."""
    kind: str  # "cpu" | "tpu" | "gpu"
    device_id: int = 0

    def __repr__(self):
        return f"{self.kind.upper()}Place({self.device_id})"


def CPUPlace(device_id: int = 0) -> Place:  # noqa: N802  (API parity with reference)
    return Place("cpu", device_id)


def TPUPlace(device_id: int = 0) -> Place:  # noqa: N802
    return Place("tpu", device_id)


_KIND_ALIASES = {
    "tpu": ("tpu", "axon"),  # axon = tunneled single-chip TPU platform
    "cpu": ("cpu",),
    "gpu": ("gpu", "cuda", "rocm"),
}


def devices(kind: Optional[str] = None) -> List[jax.Device]:
    """All visible jax devices, optionally filtered by kind (≙ InitDevices,
    reference platform/init.cc:76)."""
    devs = jax.devices()
    if kind is None:
        return devs
    aliases = _KIND_ALIASES.get(kind, (kind,))
    out = [d for d in devs if d.platform in aliases]
    return out


def device_count(kind: Optional[str] = None) -> int:
    return len(devices(kind))


def kind_of(platform: str) -> str:
    """Resolve a jax platform name to its place kind (axon -> tpu etc.)."""
    for kind, aliases in _KIND_ALIASES.items():
        if platform in aliases:
            return kind
    return platform


def default_place() -> Place:
    """Best available backend: TPU > GPU > CPU."""
    return Place(kind_of(jax.devices()[0].platform), 0)


def place_to_device(place: Place) -> jax.Device:
    devs = devices(place.kind)
    if not devs:
        raise InvalidArgumentError(f"no devices of kind {place.kind!r} visible")
    if place.device_id >= len(devs):
        raise OutOfRangeError(
            f"device_id {place.device_id} out of range for {len(devs)} "
            f"{place.kind} devices")
    return devs[place.device_id]


def is_compiled_with_tpu() -> bool:
    return device_count("tpu") > 0
