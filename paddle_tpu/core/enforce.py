"""Typed error enforcement.

TPU-native capability equivalent of the reference's PADDLE_ENFORCE macro family
(reference: paddle/fluid/platform/enforce.h:253) — structured error types with
contextual messages instead of C++ exception + demangled stack traces.
"""

from __future__ import annotations


class EnforceError(RuntimeError):
    """Base error for framework invariant violations (≙ platform::EnforceNotMet)."""


class InvalidArgumentError(EnforceError):
    pass


class NotFoundError(EnforceError):
    pass


class OutOfRangeError(EnforceError):
    pass


class AlreadyExistsError(EnforceError):
    pass


class PermissionDeniedError(EnforceError):
    pass


class UnimplementedError(EnforceError):
    pass


class UnavailableError(EnforceError):
    pass


def enforce(cond, msg="enforce failed", *args, exc=EnforceError):
    """Assert `cond` and raise a typed framework error otherwise.

    ≙ PADDLE_ENFORCE(cond, fmt, ...) (reference platform/enforce.h:253).
    """
    if not cond:
        raise exc(msg % args if args else msg)
    return cond


def enforce_eq(a, b, msg=None, exc=InvalidArgumentError):
    if a != b:
        raise exc(f"enforce_eq failed: {a!r} != {b!r}" + (f": {msg}" if msg else ""))


def enforce_ne(a, b, msg=None, exc=InvalidArgumentError):
    if a == b:
        raise exc(f"enforce_ne failed: {a!r} == {b!r}" + (f": {msg}" if msg else ""))


def enforce_gt(a, b, msg=None, exc=InvalidArgumentError):
    if not a > b:
        raise exc(f"enforce_gt failed: {a!r} <= {b!r}" + (f": {msg}" if msg else ""))


def enforce_ge(a, b, msg=None, exc=InvalidArgumentError):
    if not a >= b:
        raise exc(f"enforce_ge failed: {a!r} < {b!r}" + (f": {msg}" if msg else ""))


def enforce_lt(a, b, msg=None, exc=InvalidArgumentError):
    if not a < b:
        raise exc(f"enforce_lt failed: {a!r} >= {b!r}" + (f": {msg}" if msg else ""))


def enforce_le(a, b, msg=None, exc=InvalidArgumentError):
    if not a <= b:
        raise exc(f"enforce_le failed: {a!r} > {b!r}" + (f": {msg}" if msg else ""))


def not_none(value, name="value", exc=NotFoundError):
    if value is None:
        raise exc(f"{name} must not be None")
    return value
