"""Unique name generation for variables/ops.

≙ reference python/paddle/fluid/unique_name.py (UniqueNameGenerator + guard).
"""

from __future__ import annotations

import contextlib
from collections import defaultdict


class UniqueNameGenerator:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self.ids = defaultdict(int)

    def __call__(self, key: str) -> str:
        tmp = self.ids[key]
        self.ids[key] += 1
        return f"{self.prefix}{key}_{tmp}"


_generator = UniqueNameGenerator()


def generate(key: str) -> str:
    return _generator(key)


@contextlib.contextmanager
def guard(new_prefix: str = ""):
    """Fresh name namespace, e.g. for building independent programs in tests."""
    global _generator
    old = _generator
    _generator = UniqueNameGenerator(new_prefix)
    try:
        yield
    finally:
        _generator = old
